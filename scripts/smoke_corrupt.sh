#!/usr/bin/env bash
# Data-integrity smoke: flip one bit in a live KV page mid-trace and
# prove the serving stack detects, contains and heals it, end to end
# through the real CLIs.
#
#   scripts/smoke_corrupt.sh
#
# What it proves (exit 0 = all of it):
#   1. `benchmark.py --mode serve-load --topology 1x2 --chaos-corrupt`
#      replays the seeded trace with one bit flipped in a tracked KV
#      page of r0 at a fixed virtual tick: the router's per-tick scrub
#      detects the flip BEFORE any poisoned token is delivered, the
#      dirty page quarantines, the victim stream heals on the clean
#      replica, and EVERY delivered token stream is bit-identical to
#      the crash-free single-process twin.
#   2. The same flip against a checksums-off twin (same topology, same
#      trace) delivers at least one SILENTLY WRONG stream — the
#      integrity layer is what stands between the flip and the client.
#   3. The router log schema-validates and carries the corruption arc
#      (kv.corrupt / fault.inject / request.recovered).
#   4. The corruption auto-dumped a flight bundle, and `obs doctor`
#      classifies it `kv_corruption` NAMING the dirty replica — from
#      the bundle alone.
set -u -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

dir="$(mktemp -d /tmp/ddp_corrupt_smoke.XXXXXX)"
row="$dir/row.json"
trap 'rm -rf "$dir"' EXIT

echo "== smoke_corrupt: serve-load --topology 1x2 --chaos-corrupt (logs in $dir) =="
# Page index 2 at tick 8 lands the flip on a registered prefix with a
# queued rider (seed-7 trace) — a victim exists to expel and heal.
# Generous SLO: the healed stream keeps its ORIGINAL submit anchor.
python benchmark.py --mode serve-load --topology 1x2 \
    --chaos-victim r0 --chaos-corrupt 2:8 \
    --slo-ttft 2.0 --slo-token 1.0 \
    --event-log "$dir" --file "$row" || exit 1

echo '== smoke_corrupt: router log carries the corruption arc =='
python -m distributed_dot_product_tpu.obs validate "$dir/router.jsonl" \
    --require kv.corrupt,fault.inject,request.recovered || exit 1

echo '== smoke_corrupt: every flip detected, victims healed, twin delivers wrong tokens =='
python - "$row" <<'PY' || exit 1
import json
import sys

rec = json.load(open(sys.argv[1]))[-1]
assert rec['chaos_corrupt'] == {'victim': 'r0', 'page': 2, 'tick': 8}, \
    rec['chaos_corrupt']
assert rec['corruptions_injected'] >= 1, 'the bit flip never landed'
assert rec['corruptions_detected'] >= rec['corruptions_injected'], (
    f"{rec['corruptions_injected']} flip(s) injected but only "
    f"{rec['corruptions_detected']} kv.corrupt verdict(s) — silent "
    f"corruption")
assert rec['corrupt_healed'] or rec['corrupt_rejects'], (
    'the corruption had no victim stream — the flip tick missed the '
    'busy part of the trace')
assert rec['corrupt_compared'] >= 1 and rec['corrupt_bitident'], (
    f"delivered streams not proven bit-identical to the crash-free "
    f"twin: compared={rec['corrupt_compared']}")
assert sum(rec['counts'].values()) == rec['requests'], (
    f"classification classes {rec['counts']} do not partition the "
    f"{rec['requests']} submitted requests")
assert rec['nointeg_wrong_streams'], (
    'the checksums-off twin delivered no wrong stream — the flip was '
    'semantically invisible and the comparison proves nothing')
assert rec['verify_seconds'] >= 0, rec['verify_seconds']
print(f"corruption integrity OK: {rec['corruptions_injected']} flip(s) "
      f"-> {rec['corruptions_detected']} verdict(s) at "
      f"{rec['corrupt_sites']}, {len(rec['corrupt_healed'])} healed + "
      f"{len(rec['corrupt_rejects'])} typed kv_corrupt, twin delivered "
      f"{len(rec['nointeg_wrong_streams'])} silently wrong stream(s)")
PY

echo '== smoke_corrupt: doctor classifies the auto-dumped flight bundle =='
bundle="$(python - "$row" <<'PY'
import json, sys
print(json.load(open(sys.argv[1]))[-1]['flight_bundle'])
PY
)"
test -d "$bundle" || { echo "flight bundle $bundle missing"; exit 1; }
python -m distributed_dot_product_tpu.obs doctor "$bundle" --json \
    > "$dir/incident.json" || exit 1
python - "$dir/incident.json" <<'PY' || exit 1
import json
import sys

inc = json.load(open(sys.argv[1]))
assert inc['primary'] == 'kv_corruption', inc['primary']
assert inc['replica'] == 'r0', (
    f"doctor named {inc['replica']!r}, not the dirty replica r0")
print(f"doctor OK: primary={inc['primary']} replica={inc['replica']}")
PY

echo 'smoke_corrupt OK'
