#!/usr/bin/env bash
# Serving-layer smoke test: run examples/serve_lm.py under an injected
# request burst PLUS one stuck compiled step and one NaN-poisoned slot,
# and assert the process exits 0 with the serving audit green — every
# request in a typed terminal state, the watchdog stall recorded and
# recovered, the poisoned slot quarantined and retried, completed token
# streams bit-identical to a fault-free rerun, and readiness restored
# to READY before shutdown.
#
#   scripts/smoke_serve.sh [requests] [queue_limit]
#
# Companion to scripts/smoke_resume.sh (the training-side smoke): both
# drive a REAL process through the fault env knobs a shell would use.
# Everything here is backend-portable and runs on the CPU mesh (no
# hardware-only pieces — the `tpu`-marked kernel tests cover those and
# are skipped on CPU as usual); tier-1 CI runs this in well under a
# minute.
set -euo pipefail

REQUESTS=${1:-24}
QUEUE_LIMIT=${2:-6}
REPO="$(cd "$(dirname "$0")/.." && pwd)"

export JAX_PLATFORMS=cpu
export PYTHONUNBUFFERED=1
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

# Run the engine on the FUSED Pallas decode path (interpreted on the
# CPU mesh — the same program the TPU compiles): the fault cocktail
# below must hold on the kernel path too — NaN-slot quarantine and
# eviction churn over the in-place aliased cache, not just the XLA
# step. DDP_TPU_DECODE_KERNEL=0 re-runs the same soak on the XLA path.
export DDP_TPU_DECODE_KERNEL="${DDP_TPU_DECODE_KERNEL:-1}"

# The fault cocktail from the soak acceptance bar: a burst that
# overflows the queue (requests >> slots+queue), one stuck decode step
# long enough to trip the 0.25 s watchdog, one NaN slot.
export DDP_TPU_FAULT_BURST="$REQUESTS"
export DDP_TPU_FAULT_STUCK_STEP=4
export DDP_TPU_FAULT_STUCK_SECONDS=0.6
export DDP_TPU_FAULT_NAN_DECODE_STEP=7
export DDP_TPU_FAULT_NAN_DECODE_SLOT=1

OUT="$(mktemp /tmp/ddp_tpu_smoke_serve.XXXXXX)"
DOCTOR_OUT="$(mktemp /tmp/ddp_tpu_smoke_doctor.XXXXXX)"
# Observability event log: the run writes its full serve/health/fault
# lifecycle here, and the audit below must be able to reconstruct the
# whole fault cocktail from this file ALONE.
EVENT_LOG="$(mktemp /tmp/ddp_tpu_smoke_events.XXXXXX.jsonl)"
export DDP_TPU_EVENT_LOG="$EVENT_LOG"
# Incident flight recorder: armed for the faulted run — the injected
# stuck step must make the stall watchdog AUTO-dump a post-mortem
# bundle, and `obs doctor` must classify the incident from that
# bundle alone.
FLIGHT_DIR="$(mktemp -d /tmp/ddp_tpu_smoke_flight.XXXXXX)"
export DDP_TPU_FLIGHT_DIR="$FLIGHT_DIR"
trap 'rm -rf "$OUT" "$DOCTOR_OUT" "$EVENT_LOG" "$EVENT_LOG".[0-9]* "$FLIGHT_DIR"' EXIT

echo "== serving soak: burst=$REQUESTS queue_limit=$QUEUE_LIMIT" \
     "+ stuck step + NaN slot"
if ! (cd "$REPO" && python examples/serve_lm.py \
        --queue-limit "$QUEUE_LIMIT" --check-identical) | tee "$OUT"; then
    echo "== smoke_serve FAILED: serving audit exited nonzero" >&2
    exit 1
fi

# Belt and braces over the exit code: the specific recovery lines the
# audit is supposed to have verified must actually be in the output.
grep -q 'serve.watchdog_stalls' "$OUT" || {
    echo "== smoke_serve FAILED: no watchdog stall recorded" >&2; exit 1; }
grep -q 'serve.nan_quarantined' "$OUT" || {
    echo "== smoke_serve FAILED: no NaN quarantine recorded" >&2; exit 1; }
grep -q 'bit-identity check against clean rerun: ok' "$OUT" || {
    echo "== smoke_serve FAILED: fault isolation not verified" >&2; exit 1; }
grep -q 'readiness restored' "$OUT" || {
    echo "== smoke_serve FAILED: readiness not restored" >&2; exit 1; }
grep -q 'event-log timeline audit: ok' "$OUT" || {
    echo "== smoke_serve FAILED: request timelines not reconstructable" \
         "from the event log" >&2; exit 1; }

# The fault cocktail must be FULLY reconstructable from the JSONL event
# log alone: schema-valid records, complete per-request timelines, and
# every injected fault class + the watchdog's health transitions
# actually present in the durable stream.
if ! python -m distributed_dot_product_tpu.obs validate "$EVENT_LOG" \
        --timelines \
        --require fault.inject,serve.admit,serve.reject,serve.decode,serve.retire,serve.quarantine,health.liveness,health.readiness
then
    echo "== smoke_serve FAILED: event log does not reconstruct the" \
         "fault cocktail" >&2
    exit 1
fi
# Incident response: the stall watchdog must have auto-dumped a
# flight bundle, and `obs doctor` — reading NOTHING but the bundle —
# must classify the incident as the injected fault kind and name
# affected requests.
grep -q 'flight bundle \[stall\]' "$OUT" || {
    echo "== smoke_serve FAILED: stall did not auto-dump a flight" \
         "bundle" >&2; exit 1; }
BUNDLE="$(ls -d "$FLIGHT_DIR"/bundle-*-stall 2>/dev/null | head -n 1)"
if [ -z "$BUNDLE" ]; then
    echo "== smoke_serve FAILED: no stall bundle under $FLIGHT_DIR" >&2
    exit 1
fi
if ! python -m distributed_dot_product_tpu.obs doctor "$BUNDLE" \
        | tee "$DOCTOR_OUT"; then
    echo "== smoke_serve FAILED: obs doctor could not read the" \
         "bundle" >&2
    exit 1
fi
grep -q 'INCIDENT: stuck_step' "$DOCTOR_OUT" || {
    echo "== smoke_serve FAILED: doctor did not classify the injected" \
         "stuck step (wanted INCIDENT: stuck_step)" >&2; exit 1; }
grep -q 'injected fault: stuck_step' "$DOCTOR_OUT" || {
    echo "== smoke_serve FAILED: doctor evidence misses the injected" \
         "fault kind" >&2; exit 1; }
grep -q 'affected requests' "$DOCTOR_OUT" || {
    echo "== smoke_serve FAILED: doctor named no affected requests" >&2
    exit 1; }
echo "== smoke_serve OK: faults injected, recovered, streams intact," \
     "event log reconstructs the cocktail, doctor diagnosed the" \
     "stall bundle"
