#!/usr/bin/env bash
# Disaggregated-serving smoke: the 1-router / 2-decode-pool cocktail on
# the CPU mesh, end to end through the real CLIs.
#
#   scripts/smoke_router.sh
#
# What it proves (exit 0 = all of it):
#   1. `benchmark.py --mode serve-load --topology 1x2` runs the seeded
#      CI trace through the router (sequence-sharded prefill pool +
#      2 paged decode replicas, KV handoff as pool pages) AND through
#      its single-process twin on the byte-identical serialized trace.
#   2. The router/prefill logs schema-validate and actually carry the
#      disaggregation events (router.route placements, prefill.handoff
#      page transfers).
#   3. Goodput computed over the MERGED per-member logs passes the
#      committed SLO_BASELINE.json gate (`obs slo check` with labeled
#      replica=path sources) — the same gate the single-process smoke
#      answers to.
#   4. Every submitted request is accounted exactly once across the
#      merged logs, and the routed topology's goodput is at least the
#      twin's on the same trace (2x the capacity never does worse).
set -u -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

dir="$(mktemp -d /tmp/ddp_router_smoke.XXXXXX)"
row="$dir/row.json"
trap 'rm -rf "$dir"' EXIT

echo "== smoke_router: serve-load --topology 1x2 (logs in $dir) =="
python benchmark.py --mode serve-load --topology 1x2 \
    --event-log "$dir" --file "$row" || exit 1

echo '== smoke_router: member logs schema-validate + carry the routing events =='
python -m distributed_dot_product_tpu.obs validate "$dir/router.jsonl" \
    --require router.route || exit 1
python -m distributed_dot_product_tpu.obs validate "$dir/prefill.jsonl" \
    --require prefill.handoff || exit 1

echo '== smoke_router: goodput gate over the MERGED replica logs =='
python -m distributed_dot_product_tpu.obs slo check \
    router="$dir/router.jsonl" prefill="$dir/prefill.jsonl" \
    r0="$dir/r0.jsonl" r1="$dir/r1.jsonl" \
    --against SLO_BASELINE.json || exit 1

echo '== smoke_router: exactly-once accounting + twin comparison =='
python - "$row" <<'PY' || exit 1
import json
import sys

rec = json.load(open(sys.argv[1]))[-1]
assert rec['topology'] == '1x2', rec
assert sum(rec['counts'].values()) == rec['requests'], (
    f"classification classes {rec['counts']} do not partition the "
    f"{rec['requests']} submitted requests")
assert rec['goodput_pct'] >= rec['twin_goodput_pct'], (
    f"routed topology goodput {rec['goodput_pct']:.1f}% fell below its "
    f"single-process twin's {rec['twin_goodput_pct']:.1f}% on the same "
    f"trace")
assert set(rec['routed']) == {'r0', 'r1'}, rec['routed']
assert rec['handoffs'] >= 1, 'no prefill->decode KV handoff happened'
print(f"router smoke OK: goodput {rec['goodput_pct']:.1f}% "
      f"(twin {rec['twin_goodput_pct']:.1f}%), routed {rec['routed']}, "
      f"{rec['handoffs']} handoffs / {rec['handoff_pages']} pages, "
      f"{rec['prefix_hits']} prefix hits")
PY

echo 'smoke_router OK'
