#!/usr/bin/env bash
# Disaggregated-serving smoke: the 1-router / 2-decode-pool cocktail on
# the CPU mesh, end to end through the real CLIs.
#
#   scripts/smoke_router.sh
#
# What it proves (exit 0 = all of it):
#   1. `benchmark.py --mode serve-load --topology 1x2` runs the seeded
#      CI trace through the router (sequence-sharded prefill pool +
#      2 paged decode replicas, KV handoff as pool pages) AND through
#      its single-process twin on the byte-identical serialized trace.
#   2. The router/prefill logs schema-validate and actually carry the
#      disaggregation events (router.route placements, prefill.handoff
#      page transfers).
#   3. Goodput computed over the MERGED per-member logs passes the
#      committed SLO_BASELINE.json gate (`obs slo check` with labeled
#      replica=path sources) — the same gate the single-process smoke
#      answers to.
#   4. Every submitted request is accounted exactly once across the
#      merged logs, and the routed topology's goodput is at least the
#      twin's on the same trace (2x the capacity never does worse).
#   5. `obs critpath` reconstructs every request's causal phase chain
#      from the merged logs with the phases PARTITIONING its e2e
#      latency (sum == total_seconds to 1e-6 in virtual time — the
#      command exits non-zero on any partition failure).
#   6. `obs trace export` emits Chrome-trace/Perfetto JSON that
#      revalidates (required keys on every event, per-track ts
#      monotone) and actually carries the phase slices.
set -u -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

dir="$(mktemp -d /tmp/ddp_router_smoke.XXXXXX)"
row="$dir/row.json"
trap 'rm -rf "$dir"' EXIT

echo "== smoke_router: serve-load --topology 1x2 (logs in $dir) =="
python benchmark.py --mode serve-load --topology 1x2 \
    --event-log "$dir" --file "$row" || exit 1

echo '== smoke_router: member logs schema-validate + carry the routing events =='
python -m distributed_dot_product_tpu.obs validate "$dir/router.jsonl" \
    --require router.route || exit 1
python -m distributed_dot_product_tpu.obs validate "$dir/prefill.jsonl" \
    --require prefill.handoff || exit 1

echo '== smoke_router: goodput gate over the MERGED replica logs =='
python -m distributed_dot_product_tpu.obs slo check \
    router="$dir/router.jsonl" prefill="$dir/prefill.jsonl" \
    r0="$dir/r0.jsonl" r1="$dir/r1.jsonl" \
    --against SLO_BASELINE.json || exit 1

echo '== smoke_router: exactly-once accounting + twin comparison =='
python - "$row" <<'PY' || exit 1
import json
import sys

rec = json.load(open(sys.argv[1]))[-1]
assert rec['topology'] == '1x2', rec
assert sum(rec['counts'].values()) == rec['requests'], (
    f"classification classes {rec['counts']} do not partition the "
    f"{rec['requests']} submitted requests")
assert rec['goodput_pct'] >= rec['twin_goodput_pct'], (
    f"routed topology goodput {rec['goodput_pct']:.1f}% fell below its "
    f"single-process twin's {rec['twin_goodput_pct']:.1f}% on the same "
    f"trace")
assert set(rec['routed']) == {'r0', 'r1'}, rec['routed']
assert rec['handoffs'] >= 1, 'no prefill->decode KV handoff happened'
print(f"router smoke OK: goodput {rec['goodput_pct']:.1f}% "
      f"(twin {rec['twin_goodput_pct']:.1f}%), routed {rec['routed']}, "
      f"{rec['handoffs']} handoffs / {rec['handoff_pages']} pages, "
      f"{rec['prefix_hits']} prefix hits")
PY

echo '== smoke_router: critpath phase partition over the merged logs =='
# Exits non-zero when any completed request's phases fail to sum to
# its e2e — the partition-by-construction gate.
python -m distributed_dot_product_tpu.obs critpath \
    router="$dir/router.jsonl" prefill="$dir/prefill.jsonl" \
    r0="$dir/r0.jsonl" r1="$dir/r1.jsonl" || exit 1
python -m distributed_dot_product_tpu.obs critpath \
    router="$dir/router.jsonl" prefill="$dir/prefill.jsonl" \
    r0="$dir/r0.jsonl" r1="$dir/r1.jsonl" --json \
    > "$dir/critpath.json" || exit 1
python - "$dir/critpath.json" <<'PY' || exit 1
import json
import sys

prof = json.load(open(sys.argv[1]))
assert prof['requests'] > 0, 'critpath reconstructed zero requests'
assert prof['complete'] > 0, 'no request carried an e2e anchor'
assert not prof['partition_failures'], prof['partition_failures']
assert prof['phases'].get('decode', 0) > 0, (
    'no decode time attributed on a run that committed tokens')
assert prof.get('dispatch', {}).get('total', {}).get('ticks', 0) > 0, (
    'no serve.dispatch records — the dispatch-floor accounting is off')
print(f"critpath OK: {prof['requests']} requests, phases partition "
      f"e2e exactly, {prof['dispatch']['total']['ticks']} dispatch "
      f"ticks accounted")
PY

echo '== smoke_router: Perfetto/Chrome-trace export + schema check =='
python -m distributed_dot_product_tpu.obs trace export \
    router="$dir/router.jsonl" prefill="$dir/prefill.jsonl" \
    r0="$dir/r0.jsonl" r1="$dir/r1.jsonl" \
    -o "$dir/trace.json" || exit 1
python - "$dir/trace.json" <<'PY' || exit 1
import json
import sys

trace = json.load(open(sys.argv[1]))
events = trace['traceEvents']
assert events, 'empty trace'
last = {}
for ev in events:
    for key in ('name', 'ph', 'ts', 'pid', 'tid'):
        assert key in ev, f'missing {key!r}: {ev}'
    if ev['ph'] == 'M':
        continue
    track = (ev['pid'], ev['tid'])
    assert ev['ts'] >= last.get(track, 0), (
        f"non-monotone ts on track {track}: {ev}")
    last[track] = ev['ts']
slices = [e for e in events if e['ph'] == 'X']
assert slices, 'no phase slices in the exported trace'
assert any(e['ph'] == 'i' for e in events), (
    'no instant markers (handoffs at minimum) in the exported trace')
print(f"trace OK: {len(events)} events, {len(slices)} phase slices, "
      f"{len(last)} tracks, ts monotone per track")
PY

echo 'smoke_router OK'
