# -*- coding: utf-8 -*-
"""Diagnose the T=524288 train-step throughput cliff (VERDICT r2 item 2).

Isolates the step's components at T=262144 vs T=524288 on the real chip:
full step, forward-only loss, flash attention alone (fwd, fwd+bwd), and
projections alone. Prints per-component times so the superlinear term is
visible.
"""
import os
import sys

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_dot_product_tpu import DistributedDotProductAttn
from distributed_dot_product_tpu.parallel.mesh import globalize, seq_mesh
from distributed_dot_product_tpu.utils.comm import SEQ_AXIS

DIM = 768
HEADS = 8


from distributed_dot_product_tpu.utils.tracing import time_fn


def timeit(fn, *args, iters=2):
    best, _ = time_fn(fn, *args, iters=iters, warmup=1)
    return best


def run(t, only=None):
    mesh = seq_mesh(None)
    model = DistributedDotProductAttn(
        key_dim=DIM, num_heads=HEADS, softmax_impl='flash',
        dtype=jnp.bfloat16)
    k1, k2 = jax.random.split(jax.random.key(111))
    x = globalize(jax.random.normal(k1, (1, t, DIM), jnp.bfloat16),
                  NamedSharding(mesh, P(None, SEQ_AXIS, None)))
    target = globalize(jax.random.normal(k2, (1, t, DIM), jnp.bfloat16),
                       NamedSharding(mesh, P(None, SEQ_AXIS, None)))
    t0 = 16
    x0 = jnp.zeros((1, t0, DIM), jnp.bfloat16)
    params = model.init(jax.random.key(0), x0, x0, x0, None)

    if only in (None, 'step'):
        import optax
        from distributed_dot_product_tpu.train import make_train_step
        optimizer = optax.adam(1e-3)
        opt_state = optimizer.init(params)
        step = make_train_step(model, optimizer, mesh, donate=False)
        batch = (x, x, x, None, target, None)
        c_step = step.lower(params, opt_state, batch).compile()
        tm = timeit(c_step, params, opt_state, batch)
        ma = c_step.memory_analysis()
        print(f'T={t} full step: {tm:.3f}s  temp={ma.temp_size_in_bytes/2**30:.2f}GiB '
              f'arg={ma.argument_size_in_bytes/2**30:.2f}GiB '
              f'out={ma.output_size_in_bytes/2**30:.2f}GiB')
    if only == 'step':
        return

    if only == 'flash':
        flash_only(t)
        return
    # forward-only loss
    def fwd_local(p, x, target):
        out = model.apply(p, x, x, x, None)
        return jnp.mean((out - target) ** 2)
    a3 = P(None, SEQ_AXIS, None)
    fwd = jax.shard_map(fwd_local, mesh=mesh, in_specs=(P(), a3, a3),
                        out_specs=P(), check_vma=False)
    c_fwd = jax.jit(fwd).lower(params, x, target).compile()
    tm = timeit(c_fwd, params, x, target)
    print(f'T={t} forward-only: {tm:.3f}s')

    # grad of loss (no optimizer)
    g = jax.shard_map(jax.grad(fwd_local), mesh=mesh,
                      in_specs=(P(), a3, a3), out_specs=P(),
                      check_vma=False)
    c_g = jax.jit(g).lower(params, x, target).compile()
    tm = timeit(c_g, params, x, target)
    ma = c_g.memory_analysis()
    print(f'T={t} fwd+bwd (no adam): {tm:.3f}s  temp={ma.temp_size_in_bytes/2**30:.2f}GiB')

    flash_only(t)


def flash_only(t):
    # flash attention alone on pre-projected q/k/v
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention)
    q = jax.random.normal(jax.random.key(1), (HEADS, t, DIM // HEADS),
                          jnp.bfloat16)
    def attn_fwd(q):
        return flash_attention(q, q, q)
    c_a = jax.jit(attn_fwd).lower(q).compile()
    tm = timeit(c_a, q)
    print(f'T={t} flash fwd alone: {tm:.3f}s')

    def attn_loss(q):
        return flash_attention(q, q, q).astype(jnp.float32).sum()
    c_ab = jax.jit(jax.grad(attn_loss)).lower(q).compile()
    tm = timeit(c_ab, q)
    print(f'T={t} flash fwd+bwd alone: {tm:.3f}s')
    sys.stdout.flush()


if __name__ == '__main__':
    only = None
    args = []
    for a in sys.argv[1:]:
        if a.startswith('--only='):
            only = a.split('=', 1)[1]
        else:
            args.append(a)
    for t in (int(a) for a in args or ['262144', '524288']):
        run(t, only)
