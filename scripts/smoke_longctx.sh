#!/usr/bin/env bash
# Cluster-scale long-context smoke: one 128k-token stream decoded
# against a mesh-sharded paged KV pool on the 8-dev CPU mesh, audited
# bit-identical to the single-pool reference.
#
#   scripts/smoke_longctx.sh
#
# What it proves (exit 0 = all of it):
#   1. A 129024-token prompt prefills into a kv_shards=8 paged engine
#      (each mesh member owns a contiguous page range; per-shard flash
#      partials psum/pmax-merge) and every decoded token equals the
#      single-pool reference's — the XLA path at full 128k length.
#   2. The fused kernel path holds the same identity on a sharded
#      8k-token stream (the kernel runs in interpreter mode on CPU, so
#      the full 128k length is reserved for the XLA audit).
#   3. capacity_tokens scales linearly in kv_shards on a FIXED
#      per-shard pool: the 8-shard engine holds the whole 128k stream
#      while its 1-shard twin caps at one shard's pool (≥3.5x line).
set -u -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo '== smoke_longctx: 128k-token stream, kv_shards=8 vs single pool (xla) =='
python - <<'PY' || exit 1
from distributed_dot_product_tpu._compat import ensure_cpu_devices
ensure_cpu_devices(8)

import numpy as np

from distributed_dot_product_tpu.serve import KernelEngine

T_MAX, PS, SHARDS = 131072, 1024, 8
PAGES_PER_SHARD = 17
PROMPT_ROWS = 126 * PS          # 129024 tokens > the 128k bar
STEPS = 24


def engine(**kw):
    return KernelEngine(slots=1, t_max=T_MAX, vocab=64, heads=2,
                        head_dim=8, prefill_chunk=PS, seed=0,
                        decode_impl='xla', cache_mode='paged',
                        page_size=PS, **kw)


sh = engine(pages=PAGES_PER_SHARD, kv_shards=SHARDS)
ref = engine(pages=SHARDS * PAGES_PER_SHARD)

# The linear-capacity line, on the same fixed per-shard pool.
solo = engine(pages=PAGES_PER_SHARD)
ratio = sh.capacity_tokens / solo.capacity_tokens
assert sh.capacity_tokens >= PROMPT_ROWS + STEPS + 1, sh.capacity_tokens
assert ratio >= 3.5, (
    f'capacity_tokens {solo.capacity_tokens} -> {sh.capacity_tokens} '
    f'({ratio:.2f}x at {SHARDS} shards) — the linear scaling line broke')
print(f'capacity: {solo.capacity_tokens} tokens at 1 shard -> '
      f'{sh.capacity_tokens} at {SHARDS} ({ratio:.1f}x)')

rng = np.random.default_rng(0)
prompt = rng.integers(0, 64, size=PROMPT_ROWS).astype(np.int32)
for eng in (ref, sh):
    for i in range(0, PROMPT_ROWS, PS):
        eng.prefill(0, prompt[i:i + PS])
assert int(sh.pool.lengths[0]) == int(ref.pool.lengths[0]) == PROMPT_ROWS

active = np.ones(1, bool)
tr = ts = np.asarray([int(prompt[-1])], np.int32)
out_ref, out_sh = [], []
for _ in range(STEPS):
    tr, _ = ref.step(tr, active)
    ts, _ = sh.step(ts, active)
    out_ref.append(int(tr[0]))
    out_sh.append(int(ts[0]))
assert out_sh == out_ref, (
    f'sharded 128k stream diverged from the single-pool reference:\n'
    f'  ref {out_ref}\n  sh  {out_sh}')
print(f'xla 128k audit OK: {STEPS} decoded tokens bit-identical at '
      f'fill={PROMPT_ROWS} ({out_sh[:6]}...)')
PY

echo '== smoke_longctx: sharded fused-kernel identity (8k stream, interpreted) =='
python - <<'PY' || exit 1
from distributed_dot_product_tpu._compat import ensure_cpu_devices
ensure_cpu_devices(8)

import numpy as np

from distributed_dot_product_tpu.serve import KernelEngine

T_MAX, PS, SHARDS = 8192, 256, 8
PROMPT_ROWS = 28 * PS
STEPS = 12


def engine(impl, **kw):
    return KernelEngine(slots=1, t_max=T_MAX, vocab=64, heads=2,
                        head_dim=8, prefill_chunk=PS, seed=0,
                        decode_impl=impl, cache_mode='paged',
                        page_size=PS, **kw)


sh = engine('kernel', pages=5, kv_shards=SHARDS)
ref = engine('kernel', pages=40)
rng = np.random.default_rng(1)
prompt = rng.integers(0, 64, size=PROMPT_ROWS).astype(np.int32)
for eng in (ref, sh):
    for i in range(0, PROMPT_ROWS, PS):
        eng.prefill(0, prompt[i:i + PS])
active = np.ones(1, bool)
tr = ts = np.asarray([int(prompt[-1])], np.int32)
out_ref, out_sh = [], []
for _ in range(STEPS):
    tr, _ = ref.step(tr, active)
    ts, _ = sh.step(ts, active)
    out_ref.append(int(tr[0]))
    out_sh.append(int(ts[0]))
assert out_sh == out_ref, (
    f'sharded kernel stream diverged:\n  ref {out_ref}\n  sh  {out_sh}')
print(f'kernel audit OK: {STEPS} decoded tokens bit-identical at '
      f'fill={PROMPT_ROWS}')
PY

echo 'smoke_longctx OK'
