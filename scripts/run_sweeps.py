# -*- coding: utf-8 -*-
"""
Run the full benchmark corpus on the current backend and write one JSON
result file per configuration under ``benchmark_results/``.

Reproduces the reference's committed evidence
(``/root/reference/benchmark_results/``, 27 files: nt/all offset sweeps,
nt/all/tn scale sweeps) with the same file-naming convention, plus the
TPU-only modes (ring impls, fused attention paths, bf16). Each
configuration is a separate ``benchmark.py`` subprocess so one OOM/compile
failure cannot take down the sweep, and partial progress is preserved.

    python scripts/run_sweeps.py [--out benchmark_results] [--only nt]

Budget: ~30 configurations; first-compile dominates (~20-40 s each on the
tunneled TPU), ~30-40 min total.
"""

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (file stem, benchmark.py args). bf16 is the TPU-native dtype and the only
# one that fits T=75000 on one 16 GiB chip (the fp32 (T,T) buffer alone is
# 22.5 GiB — the reference needed 3×24 GiB GPUs for the same reason);
# fp32 runs cover the scales that fit.
SWEEPS = [
    # --- nt offset sweep (reference nt_benchmark_{offset}.json) ---
    # Offsets are divisors of T=75000, like every offset the reference's
    # own nt sweep used: a non-divisor pads the scan chunks, and at
    # T=75000 bf16 the resulting extra (T,T)-sized temp copy exceeds the
    # 16 GiB chip ((T,T) alone is 11.25 GiB). 30/750 are the small-offset
    # probes; the kernel itself supports non-divisors (tested at small T).
    *[(f'nt_benchmark_{o}', ['--mode', 'nt', '--offset', str(o),
                             '--dtype', 'bf16'])
      for o in (30, 750, 1000, 6250, 25000)],
    ('nt_benchmark_full', ['--mode', 'nt', '--offset', 'none',
                           '--dtype', 'bf16']),
    # --- nt scale sweep (reference nt_benchmark_size_{scale}.json) ---
    *[(f'nt_benchmark_size_{s}', ['--mode', 'nt', '--offset', '1000',
                                  '--scale', str(s), '--dtype', 'bf16'])
      for s in (1, 2, 4, 8)],
    *[(f'nt_benchmark_f32_size_{s}', ['--mode', 'nt', '--offset', '1000',
                                      '--scale', str(s)])
      for s in (2, 4, 8)],
    ('nt_benchmark_ring', ['--mode', 'nt', '--impl', 'ring',
                           '--dtype', 'bf16']),
    # --- all offset sweep (reference all_benchmark_{offset}.json) ---
    *[(f'all_benchmark_{o}', ['--mode', 'all', '--offset', str(o),
                              '--dtype', 'bf16'])
      for o in (24, 48, 96, 192, 384, 768)],
    ('all_benchmark_full', ['--mode', 'all', '--offset', 'none',
                            '--dtype', 'bf16']),
    # --- all scale sweep ---
    *[(f'all_benchmark_size_{s}', ['--mode', 'all', '--offset', '768',
                                   '--scale', str(s), '--dtype', 'bf16'])
      for s in (1, 2, 4, 8)],
    ('all_benchmark_f32_size_2', ['--mode', 'all', '--offset', '768',
                                  '--scale', '2']),
    ('all_benchmark_ring', ['--mode', 'all', '--impl', 'ring',
                            '--dtype', 'bf16']),
    # --- tn scale sweep (reference tn_benchmark_{scale}.json) ---
    *[(f'tn_benchmark_{s}', ['--mode', 'tn', '--scale', str(s),
                             '--dtype', 'bf16'])
      for s in (1, 2, 4, 8)],
    ('tn_benchmark_f32_2', ['--mode', 'tn', '--scale', '2']),
    # --- attention op: full vs online(ring) vs flash vs flash_bounded ---
    # (no reference analog; T = 75000/scale, H=8, d=64.) 'full'
    # materializes (H, T/N, T) scores, so it only fits at larger scales.
    # 'online' (ring) runs at scale=1 since the flash-backed block fold:
    # the old einsum fold materialized the whole (H, T, T) score block
    # (180 GB at T=75000); the fused fold holds O(block²) and matches
    # flash's rate. Its O((T/N)²) memory story still needs N>1; see
    # RESULTS.md and tests/test_ring_attention.py for CPU-mesh coverage.
    *[(f'attn_benchmark_{impl}', ['--mode', 'attn', '--attn-impl', impl,
                                  '--dtype', 'bf16', '--skip-local'])
      for impl in ('online', 'flash', 'flash_bounded', 'ulysses')],
    *[(f'attn_benchmark_{impl}_size_4',
       ['--mode', 'attn', '--attn-impl', impl, '--scale', '4',
        '--dtype', 'bf16', '--skip-local'])
      for impl in ('full', 'online', 'flash', 'flash_bounded', 'ulysses')],
    # --- flash head-dim sweep: d in {64, 128, 256} x T in {16K, 75K} ---
    # Grounds the "d=64 bounds MFU" analysis in data: per-head arithmetic
    # intensity grows with d, so the rate climbs toward the MXU peak.
    # Grouped-query attention: same compute rate as MHA (the kernel is
    # compute-bound), 4x smaller K/V residency.
    ('attn_benchmark_flash_gqa_kv2',
     ['--mode', 'attn', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '16384', '--kv-heads', '2', '--skip-local']),
    ('attn_benchmark_flash_gqa_kv2_75k',
     ['--mode', 'attn', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--kv-heads', '2', '--skip-local']),
    # int8-quantized QK^T at the head dim where it wins (MXU-bound).
    ('attn_benchmark_flash_d256_16k_int8',
     ['--mode', 'attn', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '16384', '--head-dim', '256', '--qk-quant', 'int8',
      '--skip-local']),
    # (d=64, T=75000 is exactly attn_benchmark_flash above — the RESULTS
    # head-dim table reads that record instead of re-measuring it.)
    *[(f'attn_benchmark_flash_d{d}_{tag}',
       ['--mode', 'attn', '--attn-impl', 'flash', '--dtype', 'bf16',
        '--head-dim', str(d), '--skip-local'] + extra)
      for d in (64, 128, 256)
      for tag, extra in (('16k', ['--seq-len', '16384']), ('75k', []))
      if (d, tag) != (64, '75k')],
    # --- full train step (fwd+bwd+adam as one SPMD program) ---
    # 'full'/'online' materialize (H, T, T) scores FORWARD AND BACKWARD —
    # they fit at T=8192 on 16 GiB; flash scales on (T=32768 included as
    # the memory-scaling point).
    *[(f'train_benchmark_{impl}',
       ['--mode', 'train', '--attn-impl', impl, '--dtype', 'bf16',
        '--seq-len', '16384'])
      for impl in ('flash', 'flash_bounded')],
    *[(f'train_benchmark_{impl}_8k',
       ['--mode', 'train', '--attn-impl', impl, '--dtype', 'bf16',
        '--seq-len', '8192'])
      for impl in ('full', 'online', 'flash')],
    ('train_benchmark_flash_32k',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '32768']),
    # --no-mask (attn_mask=None): the long-context configuration — the
    # dense mask is the only O(T^2) input on the flash path.
    ('train_benchmark_flash_nomask',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '16384', '--no-mask']),
    ('train_benchmark_flash_128k_nomask',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '131072', '--no-mask']),
    ('train_benchmark_flash_256k_nomask',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '262144', '--no-mask', '--iters', '2']),
    ('train_benchmark_flash_512k_nomask',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '524288', '--no-mask', '--iters', '2']),
    ('train_benchmark_flash_128k_causal',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '131072', '--no-mask', '--causal', '--iters', '2']),
    # Sliding-window attention: O(T·window) compute — the linear-in-T
    # long-context configuration (window=4096 ≈ a Mistral-style cap).
    *[(f'train_benchmark_flash_{tag}_win4k',
       ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
        '--seq-len', tlen, '--no-mask', '--causal', '--window', '4096',
        '--iters', '2'])
      for tag, tlen in (('128k', '131072'), ('512k', '524288'))],
    # Segment-id (packed-sequence) mask: O(T) kernel inputs, cross-
    # segment block skipping — the compact-mask capability record.
    ('train_benchmark_flash_segments',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '16384', '--mask-kind', 'segments', '--segments', '8']),
    # --- round-4 module-surface records: GQA projections, RoPE, and the
    # ring path carrying dropout + packed segments (the long-context
    # training combo that used to raise) ---
    ('train_benchmark_flash_gqa_kv2',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '16384', '--no-mask', '--kv-heads', '2']),
    ('train_benchmark_flash_rope',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '16384', '--no-mask', '--causal', '--use-rope']),
    # --- KV-cache decode latency (inference; module decode surface) ---
    *[(f'decode_benchmark_{tag}{suff}',
       ['--mode', 'decode', '--dtype', 'bf16', '--seq-len', tlen,
        '--heads', '8', '--head-dim', '96'] + extra)
      for tag, tlen in (('16k', '16384'), ('128k', '131072'))
      for suff, extra in (('', []), ('_kv2', ['--kv-heads', '2']))],
    # --- train-step head-dim sweep (dim=768 fixed, so d = 768/heads) ---
    *[(f'train_benchmark_flash_h{h}_{tag}_nomask',
       ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
        '--heads', str(h), '--no-mask', '--seq-len', tlen])
      for h in (12, 6, 3)
      for tag, tlen in (('16k', '16384'), ('75k', '75000'))],
    # --- round-5: chained decode (tokens per dispatch amortize the
    # per-dispatch floor) + batched serving — the GQA-wins records.
    # Pinned to the XLA step now that --decode-impl exists, so these
    # rows keep measuring what round 5 measured (the baseline the
    # kernel rows below are judged against). ---
    *[(f'decode_benchmark_128k{suff}_chain{kv}',
       ['--mode', 'decode', '--dtype', 'bf16', '--seq-len', '131072',
        '--heads', '8', '--head-dim', '96', '--decode-chain', '32',
        '--decode-impl', 'xla']
       + extra + kvx)
      for suff, extra in (('', []), ('_b8', ['--batch', '8']))
      for kv, kvx in (('', []), ('_kv2', ['--kv-heads', '2']))],
    ('decode_benchmark_128k_chain_kv2_int8',
     ['--mode', 'decode', '--dtype', 'bf16', '--seq-len', '131072',
      '--heads', '8', '--head-dim', '96', '--decode-chain', '32',
      '--kv-heads', '2', '--qk-quant', 'int8', '--decode-impl', 'xla']),
    # --- round-6: the fused Pallas decode kernel vs those baselines —
    # same shapes, same chained methodology, only the decode path
    # differs. The B=8 full-head pair is the acceptance benchmark
    # (kernel must land ≥1.5× under the 10.34 ms/step XLA row, near
    # the 4.25+0.9 ms component floor); the int8 pair is the mirror
    # regression (kernel int8 must be ≤ bf16, where XLA's s8 lowering
    # lost). TTFT rows ride every decode record now. ---
    *[(f'decode_benchmark_128k{suff}_chain{kv}_kernel',
       ['--mode', 'decode', '--dtype', 'bf16', '--seq-len', '131072',
        '--heads', '8', '--head-dim', '96', '--decode-chain', '32',
        '--decode-impl', 'kernel']
       + extra + kvx)
      for suff, extra in (('', []), ('_b8', ['--batch', '8']))
      for kv, kvx in (('', []), ('_kv2', ['--kv-heads', '2']))],
    ('decode_benchmark_128k_chain_kv2_int8_kernel',
     ['--mode', 'decode', '--dtype', 'bf16', '--seq-len', '131072',
      '--heads', '8', '--head-dim', '96', '--decode-chain', '32',
      '--kv-heads', '2', '--qk-quant', 'int8',
      '--decode-impl', 'kernel']),
    # --- round-9 (ISSUE 14): end-to-end low precision — int8 WEIGHTS
    # (+ the int8 K mirror) vs the bf16 twin rows above, both decode
    # paths. Acceptance: the wq8 row beats its bf16 twin on kv+weight
    # bytes AND time (rows record weight_bytes/step_bytes next to
    # ms_per_step, so the comparison reads straight off the pairs);
    # every row also records paged_int8_kernel_eligible. ---
    *[(f'decode_benchmark_128k_chain_kv2_wq8_{impl}',
       ['--mode', 'decode', '--dtype', 'bf16', '--seq-len', '131072',
        '--heads', '8', '--head-dim', '96', '--decode-chain', '32',
        '--kv-heads', '2', '--qk-quant', 'int8',
        '--weight-quant', 'int8', '--decode-impl', impl])
      for impl in ('xla', 'kernel')],
    # --- round-6: scheduler-vs-bare on both decode paths ---
    *[(f'decode_serve_{impl}',
       ['--mode', 'decode-serve', '--seq-len', '4096', '--batch', '8',
        '--serve-requests', '32', '--decode-impl', impl])
      for impl in ('xla', 'kernel')],
    # --- round-7: paged-cache twins of the rows above — SAME KV byte
    # budget (8 slots × 4096 rows) as a page pool, 4× the slots; the
    # rows record pool utilization + peak concurrency, so the
    # slots-per-chip win reads straight off slab-vs-paged pairs. ---
    *[(f'decode_serve_paged_{impl}',
       ['--mode', 'decode-serve', '--seq-len', '4096', '--batch', '8',
        '--serve-requests', '64', '--decode-impl', impl,
        '--cache-mode', 'paged', '--page-size', '256'])
      for impl in ('xla', 'kernel')],
    # --- round-9 (ISSUE 14): quantized-WEIGHT serving twins of the
    # slab/paged decode-serve rows — same shapes, engine weights int8
    # (DDP_TPU_WEIGHT_QUANT's programmatic twin); rows record
    # weight_bytes so the served-bytes win reads off the pairs. ---
    *[(f'decode_serve{suffix}_wq8_{impl}',
       ['--mode', 'decode-serve', '--seq-len', '4096', '--batch', '8',
        '--serve-requests', str(req), '--decode-impl', impl,
        '--weight-quant', 'int8'] + extra)
      for impl in ('xla', 'kernel')
      for suffix, req, extra in (
          ('', 32, []),
          ('_paged', 64, ['--cache-mode', 'paged',
                          '--page-size', '256']))],
    # --- ISSUE-18: cluster-scale long context — mesh-sharded paged KV.
    # The capacity sweep: a FIXED per-shard pool (a quarter of t_max's
    # pages) at 1/2/4 shards, so capacity_tokens reads ~N/4 × t_max
    # straight off the rows (the ≥3.5×-at-4-shards acceptance line),
    # plus the ms/token cost of the psum/pmax ring merge, both decode
    # paths. And the decode-serve twin at 4 shards: the sharded pool
    # behind the full scheduler. ---
    *[(f'decode_kv_shards_{n}_{impl}',
       ['--mode', 'decode', '--kv-shards', str(n), '--seq-len', '131072',
        '--heads', '8', '--head-dim', '96', '--page-size', '256',
        '--decode-impl', impl])
      for n in (1, 2, 4)
      for impl in ('xla', 'kernel')],
    ('decode_serve_kv_shards_4',
     ['--mode', 'decode-serve', '--seq-len', '4096', '--batch', '8',
      '--serve-requests', '64', '--decode-impl', 'xla',
      '--cache-mode', 'paged', '--page-size', '256',
      '--kv-shards', '4']),
    # --- round-8: speculative decoding B=1 twins — each row times a
    # non-spec scheduler burst AND the proposer-driven verify-k burst
    # on the same engine/prompts (baseline_tokens_per_s rides the
    # record), so the ISSUE-8 hardware acceptance (>2× tokens/s over
    # the measured non-spec rate on the repetitive stream) reads
    # straight off the spec_speedup column; accepted-tokens/step is
    # the amortization telemetry. The draft row is the self-draft
    # twin (machinery cost ceiling) until a distilled checkpoint
    # lands. ---
    *[(f'decode_spec_{name}_{impl}',
       ['--mode', 'decode', '--spec', name, '--seq-len', '4096',
        '--serve-requests', '4', '--spec-k', '4',
        '--heads', '2', '--head-dim', '8', '--decode-impl', impl])
      for name in ('ngram', 'draft')
      for impl in ('xla', 'kernel')],
    # --- round-5: LM capstone training (embed → scanned+remat stack →
    # tied head → chunked cross-entropy, one SPMD program) ---
    ('lm_32k',
     ['--mode', 'lm', '--dtype', 'bf16', '--seq-len', '32768',
      '--layers', '8', '--remat']),
    ('lm_128k_16l',
     ['--mode', 'lm', '--dtype', 'bf16', '--seq-len', '131072',
      '--layers', '16', '--remat', '--iters', '2']),
    ('lm_256k',
     ['--mode', 'lm', '--dtype', 'bf16', '--seq-len', '262144',
      '--layers', '8', '--remat', '--iters', '2']),
    # --- round-5: the dense-mask cost pairs (masked vs no-mask at three
    # lengths, measured back-to-back — the mask-share analysis data) ---
    ('train_benchmark_flash_32k_nomask',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '32768', '--no-mask']),
    ('train_benchmark_flash_65k',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '65536']),
    ('train_benchmark_flash_65k_nomask',
     ['--mode', 'train', '--attn-impl', 'flash', '--dtype', 'bf16',
      '--seq-len', '65536', '--no-mask']),
]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default=os.path.join(REPO, 'benchmark_results'))
    ap.add_argument('--only', default=None,
                    help='substring filter on the file stem')
    ap.add_argument('--iters', type=int, default=5)
    ap.add_argument('--rerun', action='store_true',
                    help='re-measure configs whose result file exists')
    ap.add_argument('--retries', type=int, default=1,
                    help='re-run a failed config this many times (transient '
                         'TPU-runtime/tunnel failures; backoff doubles from '
                         '--retry-backoff seconds)')
    ap.add_argument('--retry-backoff', type=float, default=10.0)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    failures = []
    for stem, bench_args in SWEEPS:
        if args.only and args.only not in stem:
            continue
        path = os.path.join(args.out, f'{stem}.json')
        if os.path.exists(path) and not args.rerun:
            print(f'== {stem}: exists, skipping (--rerun to redo)')
            continue
        # Default iters first so a per-config '--iters' in bench_args wins
        # (argparse keeps the last occurrence).
        cmd = [sys.executable, os.path.join(REPO, 'benchmark.py'),
               '--iters', str(args.iters), *bench_args, '--file', path]
        print(f'== {stem}: {" ".join(bench_args)}', flush=True)
        delay = args.retry_backoff
        for attempt in range(args.retries + 1):
            t0 = time.time()
            proc = subprocess.run(cmd, cwd=REPO, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
            sys.stdout.write(proc.stdout)
            print(f'== {stem}: rc={proc.returncode} '
                  f'({time.time() - t0:.0f}s)', flush=True)
            if proc.returncode == 0:
                break
            # One OOM/compile failure must not take down the sweep; a
            # TRANSIENT failure (tunneled-TPU RPC resets, preempted
            # runtime) should not even cost the config — retry with
            # backoff before recording it as failed.
            if attempt < args.retries:
                print(f'== {stem}: retry {attempt + 1}/{args.retries} '
                      f'in {delay:.0f}s', flush=True)
                time.sleep(delay)
                delay *= 2
        if proc.returncode != 0:
            failures.append(stem)
    if failures:
        print('FAILED configs:', ', '.join(failures))
        return 1
    print('all configs done')
    return 0


if __name__ == '__main__':
    sys.exit(main())
