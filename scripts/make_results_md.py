# -*- coding: utf-8 -*-
"""
Generate RESULTS.md from the benchmark_results/*.json corpus, side by side
with the reference baseline (BASELINE.md).

    python scripts/make_results_md.py > RESULTS.md
"""

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Reference numbers transcribed from BASELINE.md (means over the committed
# runs of /root/reference/benchmark_results/): Dist GFLOP/s/chip and peak
# GiB/rank on 3x Quadro RTX 6000 fp32 over Horovod/NCCL.
BASE_NT_OFFSET = {1000: (1660, 14.26), 1250: (1695, 14.33), 2500: (1763, 14.69),
                  5000: (1794, 15.41), 6250: (1854, 15.77),
                  12500: (1876, 17.57), 25000: (2287, 21.17)}
BASE_NT_SIZE = {1: (1656, 14.26), 2: (986, 3.57), 4: (317, 0.89), 8: (88, 0.23)}
BASE_ALL_OFFSET = {24: (1300, 7.29), 48: (1954, 7.30), 96: (2553, 7.34),
                   192: (2835, 7.40), 384: (3179, 7.56), 768: (4404, 7.70)}
BASE_ALL_SIZE = {1: (3852, 7.70), 2: (1534, 2.10), 4: (492, 0.62),
                 8: (139, 0.20)}
BASE_TN_SIZE = {1: (3188, 3.20), 2: (1133, 0.75), 4: (304, 0.23),
                8: (79, 0.08)}


def load(stem):
    path = os.path.join(REPO, 'benchmark_results', f'{stem}.json')
    if not os.path.exists(path):
        return None
    with open(path) as f:
        recs = json.load(f)
    return recs[-1] if recs else None


def gib(rec):
    ma = rec.get('dist_memory_analysis') or {}
    total = ma.get('total_bytes')
    return f'{total / 2**30:.2f}' if total else 'n/a'


# bf16 matmul peak of the v5e chip: a measured rate above this is the
# readback-fenced timer's resolution floor, not physics — such rows keep
# their raw cells but are EXCLUDED from ours/ref ratio claims.
PEAK_GFLOPS = 197_000


def row(rec, base=None, pad=True):
    if rec is None:
        return None
    ours = rec['dist_gflops_per_chip']
    cells = [f"{rec['dist_time']:.4f}", f'{ours:,.0f}', gib(rec)]
    if base:
        b_gf, b_mem = base
        ratio = ('(timer floor)' if ours > PEAK_GFLOPS
                 else f'{ours / b_gf:.1f}×')
        cells += [f'{b_gf:,}', f'{b_mem:.2f}', ratio]
    elif pad:
        cells += ['—', '—', '—']
    return cells


def table(title, header, rows):
    print(f'\n### {title}\n')
    print('| ' + ' | '.join(header) + ' |')
    print('|' + '|'.join(['---'] * len(header)) + '|')
    for label, cells in rows:
        if cells is not None:
            print('| ' + ' | '.join([label] + cells) + ' |')


def main():
    dev = None
    for p in glob.glob(os.path.join(REPO, 'benchmark_results', '*.json')):
        with open(p) as f:
            recs = json.load(f)
        if recs:
            dev = recs[-1].get('device_kind')
            break

    print('# RESULTS — measured TPU benchmark corpus')
    print(f"""
All numbers measured on **one {dev or 'TPU'} chip** (the driver exposes a
single chip; multi-chip correctness is exercised on the virtual 8-device
CPU mesh and by `dryrun_multichip`). Method: `benchmark.py` per config via
`scripts/run_sweeps.py`; timings block on device completion
(`utils.tracing.time_fn` host-readback fence — the reference's timings
never synchronized, BASELINE.md); memory is XLA's compiled buffer
assignment (argument+output+temp bytes — the tunneled backend exposes no
runtime stats). Reference baseline: 3× Quadro RTX 6000 (24 GB) fp32 over
Horovod/NCCL, per-chip GFLOP/s from BASELINE.md. Our dtype is bf16 (the
MXU-native choice — fp32 rows included where the (T,T) buffer fits one
16 GiB chip). "ours/ref" compares per-chip throughput.

Caveats: (a) sub-millisecond configs (scale=8 rows) sit at the resolution
limit of the readback-fenced timer — rates above the 197 TF/s bf16 device
peak are timer floor, not physics, and their `ours/ref` cells say so
instead of printing a ratio; (b) the `mem GiB` column is the
compiled footprint of the *timed* program, which reduces the op's output
to a scalar — where XLA can fuse the whole pipeline into that reduction
(nt with a single full gather / ring) the (T,T) product is never
materialized and the footprint drops to the operands, which is a real
property of compiled XLA programs, not an accounting trick.
""")

    hdr = ['config', 'time (s)', 'GFLOP/s/chip', 'mem GiB',
           'ref GFLOP/s/chip', 'ref peak GiB', 'ours/ref']
    table('nt (A·Bᵀ) — offset sweep, T=75000, d=768', hdr, [
        *[(f'offset={o} bf16', row(load(f'nt_benchmark_{o}'),
                                   BASE_NT_OFFSET.get(o)))
          for o in (30, 750, 1000, 6250, 25000)],
        ('offset=None (full gather) bf16', row(load('nt_benchmark_full'))),
        ('impl=ring bf16', row(load('nt_benchmark_ring'))),
    ])
    table('nt — scale sweep (offset=1000)', hdr, [
        *[(f'scale={s} (T={75000 // s}) bf16',
           row(load(f'nt_benchmark_size_{s}'), BASE_NT_SIZE.get(s)))
          for s in (1, 2, 4, 8)],
        *[(f'scale={s} f32', row(load(f'nt_benchmark_f32_size_{s}'),
                                 BASE_NT_SIZE.get(s)))
          for s in (2, 4, 8)],
    ])
    table('all (A·B) — offset sweep, T=75000, d=768', hdr, [
        *[(f'offset={o} bf16', row(load(f'all_benchmark_{o}'),
                                   BASE_ALL_OFFSET.get(o)))
          for o in (24, 48, 96, 192, 384, 768)],
        ('offset=None (full gather) bf16', row(load('all_benchmark_full'))),
        ('impl=ring bf16', row(load('all_benchmark_ring'))),
    ])
    table('all — scale sweep (offset=768)', hdr, [
        *[(f'scale={s} bf16', row(load(f'all_benchmark_size_{s}'),
                                  BASE_ALL_SIZE.get(s)))
          for s in (1, 2, 4, 8)],
        ('scale=2 f32', row(load('all_benchmark_f32_size_2'),
                            BASE_ALL_SIZE.get(2))),
    ])
    table('tn (Aᵀ·B) — scale sweep', hdr, [
        *[(f'scale={s} bf16', row(load(f'tn_benchmark_{s}'),
                                  BASE_TN_SIZE.get(s)))
          for s in (1, 2, 4, 8)],
        ('scale=2 f32', row(load('tn_benchmark_f32_2'),
                            BASE_TN_SIZE.get(2))),
    ])

    hdr_a = ['config', 'time (s)', 'GFLOP/s/chip', 'mem GiB']
    table('attention op (H=8, d=64, softmax(q·kᵀ/√d)·v; no reference '
          'analog — its module materializes full score rows)', hdr_a, [
        *[(f'{impl} T=75000', row(load(f'attn_benchmark_{impl}'),
                                  pad=False))
          for impl in ('online', 'flash', 'flash_bounded', 'ulysses')],
        *[(f'{impl} T=18750', row(load(f'attn_benchmark_{impl}_size_4'),
                                  pad=False))
          for impl in ('full', 'online', 'flash', 'flash_bounded',
                       'ulysses')],
    ])

    # Head-dim sweep: the "d=64 bounds MFU" ceiling argument as data.
    # (d=64, T=75000) IS the main attention table's flash config — read
    # that record rather than keeping a duplicate measurement.
    hd_rows = [
        (f'flash d={d} T={tlen}',
         row(load('attn_benchmark_flash' if (d, tag) == (64, '75k')
                  else f'attn_benchmark_flash_d{d}_{tag}'), pad=False))
        for d in (64, 128, 256)
        for tag, tlen in (('16k', 16384), ('75k', 75000))]
    if any(cells for _, cells in hd_rows):
        table('flash forward head-dim sweep (H=8, bf16; arithmetic '
              'intensity per score element grows with d, so the MXU rate '
              'climbs toward peak)', hdr_a, hd_rows)

    gqa_rows = [
        (f'flash H=8 kv=2 T={tlen}',
         row(load(f'attn_benchmark_flash_gqa_kv2{suf}'), pad=False))
        for suf, tlen in (('', 16384), ('_75k', 75000))]
    int8_row = row(load('attn_benchmark_flash_d256_16k_int8'), pad=False)
    if int8_row:
        gqa_rows.append(('flash d=256 T=16384 qk_quant=int8', int8_row))
    if any(cells for _, cells in gqa_rows):
        table('grouped-query attention (GQA, 4 q heads per K/V head: '
              'same rate as multi-head — the kernel is compute-bound — '
              'with 4× smaller K/V residency) and int8-quantized QK^T '
              '(MXU int8 path: +11% at d=256 where the kernel is '
              'MXU-bound; no win at d≤128 — dequant multiplies cost VPU '
              'time)', hdr_a, gqa_rows)

    def trow(rec):
        if rec is None:
            return None
        ma = rec.get('memory_analysis') or {}
        temp = ma.get('temp_bytes')
        return [f"{rec['step_time']:.4f}",
                f"{rec['step_gflops_per_chip']:,.0f}",
                f'{temp / 2**30:.2f}' if temp is not None else 'n/a']
    print("""
### Full train step (fwd + bwd + adam, one SPMD program; dim=768, H=8, bf16)

The reference has no train-step analog (its example stops at
`loss.backward()`, reference example.py:31-33). `temp GiB` is XLA's
compiled temporary-buffer total — the training-memory story: the
full/online softmax paths materialize (H, T/N, T) scores forward AND
backward, flash recomputes blockwise from the saved row logsumexp.
""")
    print('| config | s/step | GFLOP/s/chip | temp GiB |')
    print('|---|---|---|---|')
    for label, stem in [
            ('full T=8192', 'train_benchmark_full_8k'),
            ('online T=8192', 'train_benchmark_online_8k'),
            ('flash T=8192', 'train_benchmark_flash_8k'),
            ('flash T=16384', 'train_benchmark_flash'),
            ('flash_bounded T=16384', 'train_benchmark_flash_bounded'),
            ('flash T=32768', 'train_benchmark_flash_32k'),
            ('flash T=32768 (no mask)', 'train_benchmark_flash_32k_nomask'),
            ('flash T=65536', 'train_benchmark_flash_65k'),
            ('flash T=65536 (no mask)', 'train_benchmark_flash_65k_nomask'),
            ('flash T=16384 (no mask)', 'train_benchmark_flash_nomask'),
            ('flash T=16384 (segment ids, 8 spans)',
             'train_benchmark_flash_segments'),
            ('flash T=131072 (no mask)', 'train_benchmark_flash_128k_nomask'),
            ('flash T=131072 (causal, no mask)',
             'train_benchmark_flash_128k_causal'),
            ('flash T=262144 (no mask)', 'train_benchmark_flash_256k_nomask'),
            ('flash T=524288 (no mask)', 'train_benchmark_flash_512k_nomask'),
            ('flash T=131072 (causal, window=4096)',
             'train_benchmark_flash_128k_win4k'),
            ('flash T=524288 (causal, window=4096)',
             'train_benchmark_flash_512k_win4k'),
            ('flash T=524288 (causal, no mask)',
             'train_benchmark_flash_512k_causal'),
            ('flash T=16384 (no mask, GQA kv_heads=2)',
             'train_benchmark_flash_gqa_kv2'),
            ('flash T=16384 (causal, RoPE)',
             'train_benchmark_flash_rope'),
    ]:
        cells = trow(load(stem))
        if cells:
            print('| ' + ' | '.join([label] + cells) + ' |')

    # Train-step head-dim sweep (dim=768 fixed, so d = 768/heads).
    thd = [(f'flash H={h} (d={768 // h}) T={tlen} (no mask)',
            trow(load(f'train_benchmark_flash_h{h}_{tag}_nomask')))
           for h in (12, 6, 3)
           for tag, tlen in (('16k', 16384), ('75k', 75000))]
    if any(cells for _, cells in thd):
        print('\nTrain-step head-dim sweep (dim=768 held fixed, heads '
              'varied so d = 768/H; no-mask flash path):\n')
        print('| config | s/step | GFLOP/s/chip | temp GiB |')
        print('|---|---|---|---|')
        for label, cells in thd:
            if cells:
                print('| ' + ' | '.join([label] + cells) + ' |')
    # The no-mask prose cites specific rows — print it only when both
    # records exist (partial regeneration must not fabricate claims, and
    # must not drop the analysis section below either).
    if all(load(s) is not None for s in (
            'train_benchmark_flash_nomask',
            'train_benchmark_flash_128k_nomask',
            'train_benchmark_flash_256k_nomask',
            'train_benchmark_flash_512k_nomask')):
        print("""
No-mask rows use `--no-mask` (`attn_mask=None`, an extension over the
reference API): the dense mask is the only O(T²) input on the flash path.

**Dense-mask cost: a flat ~10% share, and the round-4 "32K cliff" is
dead.** Round 4 recorded masked T=32K at 58.2 TF/s vs 82.6 at 16K and
flagged a scaling cliff. Round-5 re-measurement — all six configs
back-to-back in ONE session — gives masked/no-mask pairs of
0.0323/0.0295 s (16K, 9.5% mask cost), 0.1279/0.1153 s (32K, 10.9%),
0.4967/0.4517 s (65K, 10.0%): the share is FLAT in T and the 58.2
record was the same transient-session class as the diagnosed 512K
cliff (the corpus rows above now carry the fresh records). Component
isolation (same session) shows where the ~10% lives: NOT in kernel
mask streaming — the 3-state tile summary + scalar-prefetch redirect
means an all-False mask streams no blocks at all — but in the
wrapper's O(T²) mask preprocessing (bool→int8 conversion + per-tile
min/max summary), pure HBM bandwidth on the T² bytes: 2.3 ms at 16K,
12.8 ms at 32K per pass, computed once per step (XLA CSEs the
identical fwd/bwd subexpressions). Both that tax and the attention
FLOPs are O(T²), which is why the share is flat — a dense T² mask
cannot cost less than touching T² bytes once. Steering: the segment-id
form is O(T) and *faster* than no-mask (cross-segment tiles never
execute) — any mask expressible as packed segments should use it;
dense masks are for genuinely irregular patterns and cost ~10%
flat. The natural single-chip boundary is the mask's own footprint:
at T=131K a dense mask is 16 GiB of bool input before the int8 copy —
it does not fit 16 GiB of HBM regardless of kernel strategy, so past
~65K the dense form is not merely slower, it is infeasible on one
chip; segments / causal / no-mask are the long-context forms (sharded,
the per-device mask slab is T²/N and the same analysis applies per
chip).

Dropping the mask still matters at long context — it
leaves training memory linear in T — ONE 16 GiB chip trains
dim-768 8-head attention at **T=524,288 at ~89 TFLOP/s/step** (the
reference's full-score materialization would need ~2 TiB per device at
that length). Scaling is exactly quadratic from 131K through 512K — each
doubling of T costs 4× the step time at a flat ~89 TFLOP/s, with
temporaries linear in T (2.5 → 5 → 10 GiB).

A round-2 record showed 195.7 s/step (13 TF/s) at T=512K — a 7× cliff.
Round-3 diagnosis (`scripts/diag_cliff.py`): it does not reproduce. In a
fresh process every component scales perfectly — flash fwd alone 1.82 s →
7.28 s, fwd+bwd 7.14 s → 28.5 s, and the full step 7.15 s → 28.6 s going
262K → 512K — and re-running the UNCHANGED round-2 code from a worktree at
its commit also gives 28.6 s, with the compiled executable reporting
identical buffer totals (temp 10.00 GiB) then and now. So the cliff was
transient device/tunnel state during the original one-shot `--iters 1`
sweep measurement, not the compiled program; the corpus now carries the
reproducible record (`train_benchmark_flash_512k_nomask.json`, last
entry) and the sweep runs this config at `--iters 2`.""")
    if load('train_benchmark_flash_bounded') is not None:
        print("""
**`flash_softmax_mode='bounded'` train-step inversion: resolved as a
measurement artifact.** Round 3 recorded the bounded train step at
0.0454 s vs exact's 0.0314 s at T=16K — alarming, because the backward
kernels are mode-independent (the saved logsumexp is shift-invariant),
so bounded could only ever differ in the forward, where it *wins* the
forward-only sweep. Round-4 re-measurement (within one process,
alternating configs, 5 iters): exact 0.0327/0.0327 s vs bounded
0.0296/0.0315 s — and re-running the UNCHANGED round-3 code from a
worktree at its commit gives the same ordering (exact 0.0325, bounded
0.0315/0.0313). The recorded inversion was transient device/tunnel state
in a one-shot sweep (the same failure class as the diagnosed T=512K
cliff); the corpus rows above now carry the reproducible records, and
the bounded mode's contract is unchanged: a forward-only optimization,
identical backward.""")
    if load('train_benchmark_flash_128k_causal') is not None:
        print("""
The causal row runs the kernels' in-kernel triangle with the round-4
**trapezoid pair grid**: with a static shard offset the (Q block,
K block) triangle flattens into one grid axis of exactly the valid
pairs, driven by scalar-prefetched SMEM block-index tables — the
out-of-triangle half of the grid costs no DMA and no sequencing at all
(the same overhead RESULTS measured at 19× on the window path before its
banded grid). T=131,072 causal went 68.8 → **81.8 TF/s/chip**
(1.20 → 0.99 s/step) with bitwise-identical results; the GFLOP/s figure
counts only the lower-triangle work. The pair tables are gated at 64K
pairs (~0.5 MiB SMEM); beyond the cap the rows CHUNK — the forward and
dq pass split over Q rows, the dk/dv pass over K blocks (disjoint output
slices, so nothing is partial-summed; an earlier Q-only chunking that
summed fp32 dk/dv partials OOMed the 16 GiB chip at T=512K and was
replaced) — and every chunk takes the trapezoid. T=524,288 causal:
full-grid 18.83 s/step (67.7 TF/s) → chunked trapezoid **17.20 s/step
(74.1 TF/s)**, both records in
`train_benchmark_flash_512k_causal.json`. Traced (multi-shard SPMD)
offsets keep the full grid — each shard's triangle differs, and a grid
size cannot be data-dependent.

A DMA-aliasing variant for those full-grid cases (clamp out-of-triangle
K/V block indices to the row's last valid block via dynamic index maps,
so skipped programs re-use the resident copy) was built and measured —
and REJECTED: traced-offset causal forward at T=16K ran 7.45 ms aliased
vs 4.80 ms plain (the scalar-prefetch dynamic maps cost ~2-3 µs of
scalar-core work per program, more than the skipped blocks' DMA), while
the trapezoid's 4.55 ms wins by halving the program count outright, not
by saving DMA per skipped program. Negative result recorded so the next
round doesn't re-derive it.""")

    def dec_row(label, stem):
        rec = load(stem)
        if rec is None:
            return None
        tps = rec.get('tokens_per_s')
        ms_step = rec.get('ms_per_step', rec['ms_per_token'])
        return (f"| {label} | {rec.get('batch', 1)} | "
                f"{rec.get('chain', 1)} | {ms_step:.3f} | "
                + (f'{tps:,.0f}' if tps else '—')
                + f" | {rec['cache_gb_per_s']:.0f} |")
    dec_rows = [r for r in [
        dec_row('t_max=16384', 'decode_benchmark_16k'),
        dec_row('t_max=16384, GQA kv_heads=2', 'decode_benchmark_16k_kv2'),
        dec_row('t_max=131072', 'decode_benchmark_128k'),
        dec_row('t_max=131072, GQA kv_heads=2',
                'decode_benchmark_128k_kv2'),
        dec_row('t_max=131072, chained', 'decode_benchmark_128k_chain'),
        dec_row('t_max=131072, chained, GQA kv_heads=2',
                'decode_benchmark_128k_chain_kv2'),
        dec_row('t_max=131072, chained, batched',
                'decode_benchmark_128k_b8_chain'),
        dec_row('t_max=131072, chained, batched, GQA kv_heads=2',
                'decode_benchmark_128k_b8_chain_kv2'),
        dec_row('t_max=131072, chained, GQA kv2, int8-trained (K mirror)',
                'decode_benchmark_128k_chain_kv2_int8'),
    ] if r is not None]
    if dec_rows:
        print("""
### KV-cache decode (inference; dim=768, H=8, bf16, one chip)

Steady-state latency through the module surface
(`DistributedDotProductAttn.decode`) against a ~full cache, with the
cache DONATED to the jitted step (`donate_argnums`) so the append's
`dynamic_update_slice` writes in place — without donation each token
paid a full K/V buffer copy (~1 ms at T=131K: a first measurement read
1.81 ms/token before a probe isolated the copy).

`chain` = tokens decoded per dispatch (`--decode-chain`: a `lax.scan`
of decode steps inside ONE jit). Round 4's single-dispatch rows sat on
a ~0.14 ms per-DISPATCH floor that masked every small-cache effect —
chained, the floor divides by the chain length and the table finally
shows the structural story: at t_max=131K the full-head and
`kv_heads=2` configurations stream at the SAME ~450-475 GB/s, so GQA
wins by exactly its bytes ratio H/H_kv — 0.21 vs 0.89 ms/step, the
4× the feature exists for (round 4 could only assert this; the
chained within-process pair demonstrates it). Batched serving rows
(`--batch 8`) decode 8 sequences per step — the GQA row clears ~5,000
tok/s against 131K-token contexts on one chip. `ms/step` is the time
per decode step (a step emits `batch` tokens); single-step rows
(chain=1) are kept for the dispatch-path story but read them as
PIPELINED THROUGHPUT, not latency — independent dispatches overlap on
the tunneled chip, so a single-step row can report cache GB/s above
the ~820 GB/s HBM peak (the re-measured full-head row does), which no
real per-step latency can. The chained rows serialize on the cache
carry and are the honest steady-state numbers. No reference analog
(it has no inference path).

Measured negative result (int8 K mirror): an int8-TRAINED model's
decode streams the append-time int8 mirror and scores with an
s8×s8→s32 dot — exact, and strictly better than re-quantizing the
bf16 buffer on the fly — yet measures 0.32 ms/step vs the bf16
model's 0.21 at the same kv2/131K shape, despite reading HALF the K
bytes (a first formulation that dequantized the mirror to fp32 before
the dot was worse still, 0.49: the conversion doubled the traffic the
mirror saves). XLA's s8 dot lowering at 4-row operands doesn't cash
the bandwidth saving in; a Pallas decode kernel consuming the mirror
natively is the known next step if int8 serving latency ever matters.
The mirror's real job is exactness: int8-trained models decode to
their training-time logits.

A second measured negative closes the formulation question: routing
the decode step through the FLASH kernel (one fused pass, the prefill
path's kernel with `causal_offset=length`) measures ~0.9 ms/step at
full heads and ~0.7 ms at kv2 on the 131K cache — parity with the
serialized einsum step at full heads (0.89) and 3× WORSE at kv2
(0.21). The asymmetry is structural: the kernel's cost floor is its
grid (128+ K-block programs of sequencing + DMA setup for ≤8 query
rows of work each), which does not shrink with `kv_heads`, while the
einsum's cost is the streamed bytes, which do. (Methodology note: a
naive unrolled microbench of the einsum side reports impossible rates
— XLA batches the independent repeats into one K-streaming matmul;
the serialized chained rows above are the honest einsum numbers.)
Decode on TPU wants the einsum; the kernels earn their keep from
prefill upward, which is exactly how the module routes.

Where the chained numbers sit vs physics: component isolation puts the
ATTENTION of the B=8 full-head step at 4.25 ms (759 GB/s — near the
~820 peak) and the appends at ~0.9 ms, yet the full chained step
measures 10.3 — the in-scan body (append, then read the whole buffer)
makes XLA copy the cache through the loop carry (~4 ms at B=8's
3.2 GB; the kv2 step carries the same proportional tax). So the
chained rows are CONSERVATIVE upper bounds on per-step latency: true
steady-state sits between the attention-only floor and the chained
figure, single-dispatch donated steps avoid the copy but measure
pipelined, and the GQA ratio — the structural claim — holds in every
formulation because both configurations pay proportionally. Two fixes
were tried and rejected with data: reordering the body to
attend-then-append (write-after-read) makes XLA hold MORE buffer
versions live and OOMs the compile at B=8, with the copy visible in
the failed allocation ("output of copy", a full cache-shaped temp) —
the loop-carry aliasing limit lives in XLA's scan machinery, below
anything an operand-level restructure can reach.

| config | batch | chain | ms/step | tok/s | cache GB/s |
|---|---|---|---|---|---|""")
        for r in dec_rows:
            print(r)

    lm_rows = []
    for label, stem in [
            ('8L, T=32768', 'lm_32k'),
            ('16L, T=131072', 'lm_128k_16l'),
            ('8L, T=262144', 'lm_256k'),
    ]:
        rec = load(stem)
        if rec:
            ma = rec.get('memory_analysis') or {}
            temp = ma.get('temp_bytes')
            lm_rows.append(
                f"| {label} ({rec['n_params'] / 1e6:.0f}M params"
                f"{', remat' if rec.get('remat') else ''}) | "
                f"{rec['step_time']:.3f} | {rec['tokens_per_s']:,.0f} | "
                f"{rec['step_gflops_per_chip']:,.0f} | "
                + (f'{temp / 2**30:.2f} |' if temp is not None
                   else 'n/a |'))
    if lm_rows:
        print("""
### Language-model training (capstone; dim=768, H=8, vocab=32768, bf16, one chip)

A REAL model end-to-end — token embedding → scanned (`nn.scan`) pre-LN
transformer stack over the sequence-parallel attention module → tied LM
head → packed-segment cross-entropy → cross-shard grad psum → adam — as
ONE compiled step (`benchmark.py --mode lm`). `remat` wraps each scanned
layer in `jax.checkpoint`, so backward activation memory is one layer's,
and the loss is CHUNKED cross-entropy (`TransformerLM.nll_sum`): the
(T, vocab) logits are never materialized (fp32 logits at T=131K are
17 GiB — the measured OOM without chunking; scanned chunks of 4096 rows
with per-chunk remat bound live score memory at ~0.5 GiB). The
end-to-end proof of the same pipeline (train → checkpoint mid-run →
resume → greedy generation through per-layer KV caches, on the 8-device
mesh) is `examples/train_lm.py` / `tests/test_lm.py`: the long-context
copy task trains to <0.5 copy-loss and >90% generation accuracy. No
reference analog — the reference stops at one attention layer (its
example.py:16-33).

| config | s/step | tokens/s | GFLOP/s/chip | temp GiB |
|---|---|---|---|---|""")
        for lm_row in lm_rows:
            print(lm_row)
        print("""
The counted rate is the full-remat ceiling, not overhead: with every
layer rematerialized the step executes ~4 attention passes (fwd,
recompute, bwd≈2×) while the GFLOP column counts 3, so the expected
counted rate is ~75% of the causal kernel's ~82 TF/s ≈ 61 TF/s — the
measured 60-62, FLAT from 32K through 262K — the whole-model analog of
the attention-layer quadratic-scaling rows above: 8× the context costs
49× the step time (between linear and the T² attention term's 64×,
because the projections/MLP/head grow only linearly), at constant rate
and with temporaries linear in T. Saving all layers' attention residuals
instead would need ~810 MB/layer at T=131K (13 GiB at depth 16, on
top of the 9.8 GiB step) — full remat is the right trade at this
memory, and the knob (`remat_policy`) exists for chips where it
isn't.""")

    print("""
### Communication model (multi-chip, analytic + HLO-validated)

One real chip means multi-chip ICI traffic cannot be measured here; this
is the checkable substitute (`scripts/comm_model.py`, validated by
`tests/test_comm_model.py`): closed-form per-device bytes per train step
for each attention path, with the collective *schedule* (op kinds,
counts, per-op shapes) asserted equal to what XLA actually compiles on
the virtual 8-device mesh. Numbers below: N=8, B=1, H=8, d=96 (dim 768),
T=131,072, bf16 activations (ring dk/dv partials fp32 by design).
""")
    try:
        import sys
        sys.path.insert(0, os.path.join(REPO, 'scripts'))
        import comm_model
        print(comm_model.table_markdown(n=8, h=8, t=131072, d=96))
    except Exception as e:  # pragma: no cover
        print(f'(comm_model table unavailable: {e})')
    print("""
How to read it: the ring moves the same K/V volume forward as one
all-gather ((N−1)/N of the global array per device) but as N−1
neighbour hops that overlap the folds; its fwd+bwd total lands at ~2.1×
the allgather path because the backward rotates fp32 dk/dv partials
along with the k/v buffers. Ulysses is the bytes-per-step winner at N/2×
below allgather but caps the mesh at H_kv | N; GQA (`num_kv_heads`)
multiplies the allgather/ulysses paths' bytes by H_kv/H directly — the
module's headline ICI lever. Pick allgather+GQA for small N, ulysses
while heads divide, ring when N > H or when score memory (not bytes)
binds.""")

    print("""
### Reading the numbers

- **North star: beaten.** The driver baseline (BASELINE.json) asks ≥2× the
  reference's best per-chip rate (2,287 GFLOP/s, nt offset=25000). The bf16
  nt kernel at the same workload runs ~60× that on one v5e chip; even the
  strict-fp32 runs at the scales that fit clear ~9×.
- **The offset↔time trade survives the port, memory-side inverted by
  design.** Larger offsets are faster here too (fewer, larger collectives →
  fewer scan steps). The reference's memory grew with offset because each
  `hvd.allgather` materialized a (W, *, offset, d) buffer per rank; our
  compiled memory is dominated by the (T, T) operand/output, with the
  gathered chunk a rounding error — the XLA totals are flat across offsets
  (see nt rows). The knob still exists and still bounds gathered-operand
  memory; it just no longer dominates at these shapes.
- **Ring vs allgather (1 chip):** on a W=1 mesh the ring (and the
  offset=None full gather) compile to ONE fused local matmul (~192 TF/s,
  97% of bf16 peak), while the chunked-offset path pays for its `lax.scan`
  structure (~142 TF/s) — the knob exists for multi-chip memory control,
  and a W=1 chip shows its pure overhead. The variants only diverge on
  real multi-chip ICI, which this driver cannot measure;
  multi-device correctness of both paths is pinned by the 8-device
  CPU-mesh tests (`tests/test_ops_grad.py`, parametrized over impl).
- **Ring/online now runs at flash-class rates — and at T=75000 on one
  chip.** The round-2 einsum block fold ran at 13.6 TF/s (T=18750) and
  could not run at T=75000 at all (it materialized the (H, T, T) score
  block — 180 GB). With the flash-kernel block fold, online = 64.2 TF/s at
  T=18750 (93% of plain flash's 69.3) and 73.6 TF/s at T=75000 — the
  scale-out path no longer trades throughput for its O((T/N)²) memory
  story. Remaining gap vs flash: the LSE merge between blocks (fp32 VPU
  work per fold).
- **Head-dim sweep (forward + train): d=64 is the VPU-bound floor, not
  the kernel's ceiling.** The score matmul's MXU contraction depth is d,
  so the rate ~doubles from d=64 to d=128 (76 → 161 TF/s fwd at T=16K;
  71 → 127 at T=75K) and holds at d=256 (161/152). The train-step sweep
  (dim=768 fixed, heads varied) shows the same: H=12 (d=64) 60.9 →
  H=6 (d=128) 121.4 → H=3 (d=256) 114.9 TF/s. The "~95% of practical
  ceiling" claim below is a d=64 statement; at d≥128 the kernels run at
  ~80-84% of the chip's 192 TF/s matmul peak.
- **Sliding-window attention is linear in T — and the banded grid is
  what makes it real.** `window=4096` causal training: 0.110 s/step at
  T=131K, 0.401 s at T=524K (3.6× time for 4× T ≈ linear; the full
  triangle at 524K costs ~14.3 s — ~36×). The first implementation kept
  the full (Tq/bq × Tk/bk) Pallas grid and only `pl.when`-skipped
  out-of-window blocks — it measured 7.6 s at T=524K because skipped
  programs still pay their K/V block DMA and grid sequencing. The banded
  grid (K axis = only each Q block's ~window/bk band, selected by
  scalar-prefetch index maps) removes those cells entirely: 19× on the
  same config, and the skipped work never touches HBM.
- **Masked flash after round 3: dense masks cost ~5%, segments are
  FASTER than no-mask.** Block-skip + mask-DMA redirect take the dense-
  masked train step from 59.3 (round 2) to 86.3 TF/s = 95% of the no-mask
  90.7; the segment-id form (8 packed spans, O(T) input) measures 238
  TF/s *apparent* because cross-segment tiles never execute (the FLOP
  count deliberately ignores the skip — see the table note).
- **Flash kernel at d=64**: exact-softmax ~76 TF/s at T=16K (the measured
  matmul-only ceiling of the same grid is ~90; Google's splash-attention
  kernel measures ~75 on this chip/shape). `softmax_mode='bounded'` trades
  the running-max reduce for a norm bound (auto-falls back when unsafe) and
  reaches ~85-90 TF/s. The VERDICT round-1 target of 100 TF/s at d=64
  assumed nt-style full-MXU rates; at d=64 the score matmul runs the MXU at
  half contraction depth, capping the theoretical mix at ~131 TF/s — the
  kernel sits at ~95% of the chip's practical (0.72-efficiency) ceiling.
""")


if __name__ == '__main__':
    sys.exit(main())
