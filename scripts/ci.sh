#!/usr/bin/env bash
# CI gate: generic hygiene (ruff) → domain static analysis (graphlint)
# → tier-1 tests. Each stage fails the build on its own; later stages
# still run so one CI pass reports everything (exit is the OR).
#
#   scripts/ci.sh            # full gate
#   SKIP_TESTS=1 scripts/ci.sh   # lint-only (fast pre-push check)
#
# Two-tier lint story (README "Static analysis"): ruff owns generic
# python hygiene; graphlint owns the jaxpr/domain contracts (fp32
# accumulation, KV-cache aliasing/donation, collective mesh axes,
# retrace budgets, AST hazard patterns). The TPU container image does
# not ship ruff — that stage is skipped with a notice there (the
# pyproject [tool.ruff] config makes any box that HAS ruff enforce the
# same rules).
set -u -o pipefail
cd "$(dirname "$0")/.."

rc=0

echo '=== [1/5] ruff (generic hygiene) ==='
if command -v ruff >/dev/null 2>&1; then
    ruff check . || rc=1
elif python -c 'import ruff' >/dev/null 2>&1; then
    python -m ruff check . || rc=1
else
    echo 'ruff not installed in this image — skipping (graphlint still runs)'
fi

echo '=== [2/5] graphlint (jaxpr/domain contracts) ==='
JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.analysis || rc=1

echo '=== [3/5] tier-1 tests ==='
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping pytest stage'
else
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider || rc=1
fi

echo '=== [4/5] smoke serve + event-log schema validation ==='
# Drives the real serving process through the fault cocktail and then
# schema-validates + timeline-reconstructs its JSONL event log (the
# obs validate CLI runs inside smoke_serve.sh over the run's log).
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping smoke-serve stage'
else
    scripts/smoke_serve.sh 12 4 || rc=1
fi

echo '=== [5/5] perf gate (compiled-program cost vs committed baseline) ==='
# Compiles every registered entrypoint hermetically (8-dev CPU mesh),
# snapshots XLA cost/memory/compile-time/retrace accounting, and gates
# it against the committed PERF_BASELINE.json (tolerances sized for
# CPU-mesh determinism — see obs/perf.py Tolerances). On an
# INTENTIONAL program change, refresh the baseline in the same diff:
#   python -m distributed_dot_product_tpu.obs.perf snapshot -o PERF_BASELINE.json
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping perf-gate stage'
else
    perf_now="$(mktemp /tmp/ddp_perf_now.XXXXXX.json)"
    { JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.obs.perf \
          snapshot -o "$perf_now" \
      && JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.obs.perf \
          check --against PERF_BASELINE.json --current "$perf_now"; } || rc=1
    rm -f "$perf_now"
fi

exit $rc
