#!/usr/bin/env bash
# CI gate: generic hygiene (ruff) → domain static analysis (graphlint)
# → tier-1 tests. Each stage fails the build on its own; later stages
# still run so one CI pass reports everything (exit is the OR).
#
#   scripts/ci.sh            # full gate
#   SKIP_TESTS=1 scripts/ci.sh   # lint-only (fast pre-push check)
#
# Two-tier lint story (README "Static analysis"): ruff owns generic
# python hygiene; graphlint owns the jaxpr/domain contracts (fp32
# accumulation, KV-cache aliasing/donation, collective mesh axes,
# retrace budgets, AST hazard patterns). The TPU container image does
# not ship ruff — that stage is skipped with a notice there (the
# pyproject [tool.ruff] config makes any box that HAS ruff enforce the
# same rules).
set -u -o pipefail
cd "$(dirname "$0")/.."

rc=0

echo '=== [1/13] ruff (generic hygiene) ==='
if command -v ruff >/dev/null 2>&1; then
    ruff check . || rc=1
elif python -c 'import ruff' >/dev/null 2>&1; then
    python -m ruff check . || rc=1
else
    echo 'ruff not installed in this image — skipping (graphlint still runs)'
fi

echo '=== [2/13] graphlint + servelint + flowlint (jaxpr/domain/serving contracts) ==='
# Full pass: jaxpr rules over every registered entrypoint (incl. the
# bf16 serving-dtype and int8-weight twins — the owned dense retired
# the flax-Dense f32-accum waivers, so zero allowed records remain)
# + the AST families (host-pull/traced-bool/clock/
# silent-except) + servelint (protolint event-schema call sites,
# conclint guarded-by/thread discipline, determlint tick-path
# determinism) + flowlint (interprocedural typed-failure flow: typed
# escapes at the serving roots with propagation chains, handler
# totality, RejectReason liveness, shard-stride ownership; pragma
# waivers stay visible and the gate keeps them at zero). Fast
# pre-commit twin:
#   python -m distributed_dot_product_tpu.analysis --changed-only origin/main
JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.analysis || rc=1

echo '=== [3/13] tier-1 tests ==='
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping pytest stage'
else
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider || rc=1
fi

echo '=== [4/13] smoke serve + event-log schema validation ==='
# Drives the real serving process through the fault cocktail and then
# schema-validates + timeline-reconstructs its JSONL event log (the
# obs validate CLI runs inside smoke_serve.sh over the run's log).
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping smoke-serve stage'
else
    scripts/smoke_serve.sh 12 4 || rc=1
fi

echo '=== [5/13] spec-decode bit-identity smoke (DDP_TPU_SPEC=ngram) ==='
# Speculative decoding's exactness guarantee, proven on a real burst
# through the ENV knob a deployment would flip: the same traffic served
# with the n-gram proposer (verify-k steps) and without (plain n=1
# steps) must produce token-for-token identical streams and statuses.
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping spec-smoke stage'
else
    JAX_PLATFORMS=cpu DDP_TPU_SPEC=ngram python - <<'PY' || rc=1
import numpy as np

from distributed_dot_product_tpu.serve import (
    KernelEngine, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry


def burst(spec):
    """spec=None resolves the DDP_TPU_SPEC env knob; 'off' overrides
    it — so the spec run exercises the deployment path and the
    baseline run the explicit opt-out."""
    eng = KernelEngine(slots=2, t_max=128, vocab=32, seed=4,
                       decode_impl='xla')
    sched = Scheduler(
        eng, ServeConfig(queue_limit=16, max_new_tokens=24,
                         watchdog=False, spec=spec, spec_k=4),
        registry=MetricsRegistry())
    rng = np.random.RandomState(11)
    # Mixed traffic: cyclic prompts (speculation's win case) and
    # random ones (its miss case) in one batch.
    for i in range(6):
        if i % 2:
            p = [(j % 3) + 1 for j in range(8)]
        else:
            p = [int(x) for x in rng.randint(1, 32, size=6)]
        sched.submit(p, request_id=f'r{i}')
    results = sched.run_until_idle()
    sched.close()
    steps = sched.registry.snapshot()['counters']['serve.decode_steps']
    return results, steps


spec, spec_steps = burst(None)        # DDP_TPU_SPEC=ngram applies
base, base_steps = burst('off')
assert set(spec) == set(base)
for rid in base:
    assert spec[rid].status == base[rid].status, rid
    assert spec[rid].tokens == base[rid].tokens, (
        f'{rid}: spec stream diverged from non-spec — the greedy '
        f'verify exactness guarantee is broken')
assert spec_steps < base_steps, (
    f'spec burst took {spec_steps} dispatches vs {base_steps} non-spec'
    ' — the verify-k path never amortized a step')
print(f'spec smoke OK: {len(base)} streams bit-identical, '
      f'{spec_steps} vs {base_steps} decode dispatches')
PY
fi

echo '=== [6/13] serve-load smoke + SLO goodput gate ==='
# A seeded open-loop trace (virtual clock — minutes of simulated
# traffic in seconds of wall time, CPU-deterministic) drives the
# scheduler, then the goodput report computed FROM THE EVENT LOG ALONE
# is gated against the committed SLO_BASELINE.json (generous
# tolerances; every violation names the metric and tenant). The
# benchmark's serve-load flag DEFAULTS are the smoke config — on an
# intentional serving/load change refresh the baseline in the same
# diff:
#   python benchmark.py --mode serve-load --event-log /tmp/slo.jsonl
#   python -m distributed_dot_product_tpu.obs slo report /tmp/slo.jsonl \
#       --spec SLO_BASELINE.json --baseline-out SLO_BASELINE.json
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping serve-load stage'
else
    slo_log="$(mktemp -u /tmp/ddp_slo_smoke.XXXXXX).jsonl"
    slo_row="$(mktemp /tmp/ddp_slo_row.XXXXXX.json)"
    rm -f "$slo_row"    # benchmark.py appends into a fresh JSON file
    { JAX_PLATFORMS=cpu python benchmark.py --mode serve-load \
          --event-log "$slo_log" --file "$slo_row" \
      && JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.obs \
          slo check "$slo_log" --against SLO_BASELINE.json; } || rc=1
    rm -f "$slo_log" "$slo_row"
fi

echo '=== [7/13] disaggregated-serving smoke (router + 2 decode pools) ==='
# The 1-router/2-pool cocktail on the CPU mesh: the seeded trace through
# the disaggregated topology AND its single-process twin, member logs
# schema-validated (--require router.route / prefill.handoff), goodput
# over the MERGED replica logs gated against SLO_BASELINE.json, and the
# exactly-once / topology-beats-twin invariants asserted from the row.
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping router-smoke stage'
else
    scripts/smoke_router.sh || rc=1
fi

echo '=== [8/13] perf gate (compiled-program cost vs committed baseline) ==='
# Compiles every registered entrypoint hermetically (8-dev CPU mesh),
# snapshots XLA cost/memory/compile-time/retrace accounting, and gates
# it against the committed PERF_BASELINE.json (tolerances sized for
# CPU-mesh determinism — see obs/perf.py Tolerances). On an
# INTENTIONAL program change, refresh the baseline in the same diff:
#   python -m distributed_dot_product_tpu.obs.perf snapshot -o PERF_BASELINE.json
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping perf-gate stage'
else
    perf_now="$(mktemp /tmp/ddp_perf_now.XXXXXX.json)"
    { JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.obs.perf \
          snapshot -o "$perf_now" \
      && JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.obs.perf \
          check --against PERF_BASELINE.json --current "$perf_now"; } || rc=1
    rm -f "$perf_now"
fi

echo '=== [9/13] weight-quant decode smoke (kv+weight bytes below the bf16 twin) ==='
# The low-precision acceptance row: the SAME decode shape at bf16 and
# at int8 weights + int8 K mirror — the quantized row must move fewer
# kv+weight bytes per step AND be kernel-eligible on the paged pool
# (decode_kernel_eligible(paged, qk_quant='int8') == True, i.e. the
# mirror pools ride the fused kernel at paged concurrency).
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping weight-quant smoke stage'
else
    wq_rows="$(mktemp /tmp/ddp_wq_rows.XXXXXX.json)"
    rm -f "$wq_rows"    # benchmark.py appends into a fresh JSON file
    { JAX_PLATFORMS=cpu python benchmark.py --mode decode \
          --seq-len 512 --heads 2 --head-dim 8 --iters 2 --no-ttft \
          --dtype bf16 --file "$wq_rows" \
      && JAX_PLATFORMS=cpu python benchmark.py --mode decode \
          --seq-len 512 --heads 2 --head-dim 8 --iters 2 --no-ttft \
          --dtype bf16 --weight-quant int8 --qk-quant int8 \
          --file "$wq_rows" \
      && python - "$wq_rows" <<'PY'; } || rc=1
import json
import sys

rows = json.load(open(sys.argv[1]))
bf16, wq8 = rows[-2], rows[-1]
assert wq8['weight_quant'] == 'int8' and bf16['weight_quant'] is None
assert wq8['step_bytes'] < bf16['step_bytes'], (
    f"quantized row moves {wq8['step_bytes']} kv+weight bytes/step vs "
    f"the bf16 twin's {bf16['step_bytes']} — the byte win is gone")
assert wq8['paged_int8_kernel_eligible'] is True, (
    'paged+int8 lost fused-kernel eligibility — quantized serving and '
    '4x concurrency no longer compose')
print(f"weight-quant smoke OK: {wq8['step_bytes']} vs "
      f"{bf16['step_bytes']} bytes/step, paged int8 kernel-eligible")
PY
    rm -f "$wq_rows"
fi

echo '=== [10/13] closed-loop control smoke (static vs controlled under a ramp) ==='
# The control-plane acceptance row: the SAME seeded ramp trace (rate
# climbing to 10x across the trace — deterministic overload) through a
# 1-decode-replica topology twice. STATIC must breach the committed
# per-tenant SLO floors (the trace is sized to break one replica);
# CONTROLLED (the closed-loop controller autoscaling decode replicas
# and actuating admission watermarks) must hold every tenant within
# SLO_BASELINE.json tolerance. The controlled run's control history is
# then validated as closed-vocabulary events from the log alone
# (obs validate --require control.scale).
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping control-smoke stage'
else
    ctl_rows="$(mktemp /tmp/ddp_ctl_rows.XXXXXX.json)"
    ctl_static="$(mktemp -d /tmp/ddp_ctl_static.XXXXXX)"
    ctl_logs="$(mktemp -d /tmp/ddp_ctl_logs.XXXXXX)"
    rm -f "$ctl_rows"    # benchmark.py appends into a fresh JSON file
    { JAX_PLATFORMS=cpu python benchmark.py --mode serve-load \
          --topology 0x1 --arrival ramp --load-rate 300 \
          --ramp-factor 10 --load-requests 64 \
          --event-log "$ctl_static" --file "$ctl_rows" \
      && JAX_PLATFORMS=cpu python benchmark.py --mode serve-load \
          --topology 0x1 --arrival ramp --load-rate 300 \
          --ramp-factor 10 --load-requests 64 --control \
          --event-log "$ctl_logs" --file "$ctl_rows" \
      && JAX_PLATFORMS=cpu python -m distributed_dot_product_tpu.obs \
          validate "$ctl_logs/router.jsonl" \
          --require control.adjust,control.scale \
      && python - "$ctl_rows" <<'PY'; } || rc=1
import json
import sys

rows = json.load(open(sys.argv[1]))
static, controlled = rows[-2], rows[-1]
assert not static['control'] and controlled['control']
base = json.load(open('SLO_BASELINE.json'))
tol = base['tolerances']['tenant_goodput_abs']
floors = {t: gp - tol for t, gp in base['per_tenant'].items()}
breached = [t for t, gp in static['per_tenant'].items()
            if gp < floors[t]]
assert breached, (
    f"the ramp trace no longer breaks the static config "
    f"({static['per_tenant']} vs floors {floors}) — re-size the ramp "
    f"so the control win stays measurable")
held = {t: gp for t, gp in controlled['per_tenant'].items()}
bad = [t for t, gp in held.items() if gp < floors[t]]
assert not bad, (
    f'controlled run breaches the per-tenant SLO floors for {bad}: '
    f'{held} vs floors {floors} — the closed loop stopped holding '
    f'goodput under the ramp')
ups = [a for a in controlled['control_actions']
       if a['action'] == 'scale' and a['direction'] == 'up']
assert ups, 'controlled run never scaled up — the ramp was not acted on'
print(f"control smoke OK: static {static['per_tenant']} (breached "
      f"{breached}) vs controlled {held} within floors {floors}; "
      f"{len(ups)} scale-up(s), {controlled['replicas_final']} "
      f"replicas final")
PY
    rm -rf "$ctl_rows" "$ctl_static" "$ctl_logs"
fi

echo '=== [11/13] replica-failure-domain smoke (seeded crash + recovery) ==='
# The robustness acceptance row: the seeded CI trace with decode
# replica r1 killed at a fixed virtual tick. Probes declare the loss,
# every in-flight stream re-dispatches to the survivor bit-identical
# to the crash-free twin, goodput with recovery strictly beats the
# max_recoveries=0 twin of the same crash, the victim's torn log still
# validates, and `obs doctor` classifies the auto-dumped flight bundle
# as replica_loss NAMING the dead replica.
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping chaos-smoke stage'
else
    scripts/smoke_chaos.sh || rc=1
fi

echo '=== [12/13] data-integrity smoke (seeded bit flip + detect/heal) ==='
# The KV-page-integrity acceptance row: the seeded CI trace with one
# exponent bit flipped in a live KV page of r0 at a fixed virtual
# tick. The scrub detects the flip before any poisoned token is
# delivered, the victim heals bit-identical to the crash-free twin, a
# checksums-off twin of the same flip delivers a SILENTLY wrong
# stream, and `obs doctor` classifies the auto-dumped flight bundle
# as kv_corruption NAMING the dirty replica.
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping corrupt-smoke stage'
else
    scripts/smoke_corrupt.sh || rc=1
fi

echo '=== [13/13] long-context smoke (128k stream on the sharded KV mesh) ==='
# The cluster-scale long-context acceptance row: a 128k-token stream
# prefilled into a kv_shards=8 paged engine (each mesh member owns a
# contiguous page range, per-shard flash partials psum/pmax-merged)
# decodes token-for-token identical to the single-pool reference on
# the 8-dev CPU mesh — XLA path at full length, fused kernel path on a
# shorter sharded stream — and capacity_tokens scales linearly in
# kv_shards on a fixed per-shard pool (≥3.5x line).
if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo 'SKIP_TESTS=1 — skipping longctx-smoke stage'
else
    scripts/smoke_longctx.sh || rc=1
fi

exit $rc
