#!/usr/bin/env bash
# Replica-failure-domain smoke: kill one decode replica mid-trace and
# prove the serving stack recovers, end to end through the real CLIs.
#
#   scripts/smoke_chaos.sh
#
# What it proves (exit 0 = all of it):
#   1. `benchmark.py --mode serve-load --topology 1x2 --chaos` replays
#      the seeded trace with replica r1 killed at a fixed virtual tick:
#      the router's probes declare the loss, every in-flight stream on
#      the victim is re-dispatched to the survivor from the recovery
#      ledger, and each recovered stream is BIT-IDENTICAL to the
#      crash-free single-process twin of the same trace.
#   2. The router log schema-validates and carries the full recovery
#      arc (replica.lost / replica.probe / request.recovered), and the
#      victim's TORN log (killed mid-record) still validates — the
#      half-written tail is tolerated, not fatal.
#   3. Goodput WITH recovery strictly beats the no-recovery twin (same
#      topology, same trace, same crash, max_recoveries=0) — recovery
#      pays for itself — and no request is dropped without a typed
#      reason in either run.
#   4. The replica loss auto-dumped a flight bundle router-side, and
#      `obs doctor` classifies it `replica_loss` NAMING the dead
#      replica.
set -u -o pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

dir="$(mktemp -d /tmp/ddp_chaos_smoke.XXXXXX)"
row="$dir/row.json"
trap 'rm -rf "$dir"' EXIT

echo "== smoke_chaos: serve-load --topology 1x2 --chaos (logs in $dir) =="
# Generous SLO: recovered streams keep their ORIGINAL submit anchor, so
# their TTFT includes the crash + detection + replay window by design.
python benchmark.py --mode serve-load --topology 1x2 --chaos \
    --slo-ttft 2.0 --slo-token 1.0 \
    --event-log "$dir" --file "$row" || exit 1

echo '== smoke_chaos: router log carries the recovery arc; torn victim log validates =='
python -m distributed_dot_product_tpu.obs validate "$dir/router.jsonl" \
    --require replica.lost,replica.probe,request.recovered || exit 1
python -m distributed_dot_product_tpu.obs validate "$dir/r1.jsonl" || exit 1

echo '== smoke_chaos: recovery recovered, bit-identically, and paid for itself =='
python - "$row" <<'PY' || exit 1
import json
import sys

rec = json.load(open(sys.argv[1]))[-1]
assert rec['chaos'] == {'victim': 'r1', 'tick': 40}, rec['chaos']
assert rec['replica_lost'] == ['r1'], rec['replica_lost']
assert rec['recovered'], 'the crash caught no in-flight stream'
assert rec['recovered_compared'] >= 1 and rec['recovered_bitident'], (
    f"recovered streams not proven bit-identical to the crash-free "
    f"twin: compared={rec['recovered_compared']}")
assert sum(rec['counts'].values()) == rec['requests'], (
    f"classification classes {rec['counts']} do not partition the "
    f"{rec['requests']} submitted requests")
assert sum(rec['norec_counts'].values()) == rec['requests'], (
    f"no-recovery twin classes {rec['norec_counts']} do not partition "
    f"the {rec['requests']} submitted requests")
assert rec['norec_replica_lost_rejects'], (
    'the no-recovery twin lost the same replica yet produced no typed '
    'replica_lost terminal')
assert rec['goodput_pct'] > rec['norec_goodput_pct'], (
    f"goodput with recovery {rec['goodput_pct']:.1f}% does not beat "
    f"the no-recovery twin's {rec['norec_goodput_pct']:.1f}% — "
    f"recovery did not pay for itself")
print(f"chaos recovery OK: {len(rec['recovered'])} stream(s) recovered "
      f"({rec['recovered_compared']} bit-identical), goodput "
      f"{rec['goodput_pct']:.1f}% vs no-recovery "
      f"{rec['norec_goodput_pct']:.1f}%")
PY

echo '== smoke_chaos: doctor classifies the auto-dumped flight bundle =='
bundle="$(python - "$row" <<'PY'
import json, sys
print(json.load(open(sys.argv[1]))[-1]['flight_bundle'])
PY
)"
test -d "$bundle" || { echo "flight bundle $bundle missing"; exit 1; }
python -m distributed_dot_product_tpu.obs doctor "$bundle" --json \
    > "$dir/incident.json" || exit 1
python - "$dir/incident.json" <<'PY' || exit 1
import json
import sys

inc = json.load(open(sys.argv[1]))
assert inc['primary'] == 'replica_loss', inc['primary']
assert inc['replica'] == 'r1', (
    f"doctor named {inc['replica']!r}, not the dead replica r1")
print(f"doctor OK: primary={inc['primary']} replica={inc['replica']}")
PY

echo 'smoke_chaos OK'
