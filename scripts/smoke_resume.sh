#!/usr/bin/env bash
# Kill/resume smoke test: run a tiny CPU training job through the
# resilient driver (train_loop.run_training), SIGKILL it mid-run — the
# one signal no handler can catch, i.e. a true crash — restart it, and
# assert the final loss matches an uninterrupted run bit-for-bit.
#
#   scripts/smoke_resume.sh [steps] [kill_after_seconds]
#
# Exercises, end to end and against a REAL process death (the tier-1
# tests cover the same invariant in-process via the fault harness):
# auto-resume from the latest finalized checkpoint, recover_interrupted
# cleanup of whatever the SIGKILL left behind, and the determinism of
# the batch_fn(step) data stream.
set -euo pipefail

STEPS=${1:-40}
KILL_AFTER=${2:-18}   # past the ~13s import+compile, well before the end
REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="$(mktemp -d /tmp/ddp_tpu_smoke_resume.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT

export JAX_PLATFORMS=cpu
export PYTHONUNBUFFERED=1
# job.py lives outside the repo; make the package importable anyway.
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

# The job lives in a real file so the interrupted run can background
# `python` DIRECTLY: backgrounding a shell function would make $! a
# subshell pid and the SIGKILL would miss the python process.
cat > "$WORK/job.py" <<'PY'
import sys

from distributed_dot_product_tpu._compat import ensure_cpu_devices
ensure_cpu_devices(8)

import time

import jax
import jax.numpy as jnp
import optax

from distributed_dot_product_tpu import (
    DistributedDotProductAttn, TrainLoopConfig, TrainState, run_training,
    seq_mesh,
)
from distributed_dot_product_tpu.train import make_train_step

ckpt_dir, loss_out, steps = sys.argv[1] or None, sys.argv[2], int(sys.argv[3])

mesh = seq_mesh(8)
dim, heads, t, b = 16, 2, 16, 2
model = DistributedDotProductAttn(key_dim=dim, num_heads=heads, offset=2)
x0 = jax.random.normal(jax.random.key(0), (b, t, dim), jnp.float32)
mask = jnp.zeros((b, t, t), dtype=bool)
params = model.init(jax.random.key(1), x0, x0, x0, mask)
optimizer = optax.adam(1e-2)
step = make_train_step(model, optimizer, mesh, donate=False, guard=True)


def batch_fn(i):
    key = jax.random.fold_in(jax.random.key(2), i)
    x = jax.random.normal(key, (b, t, dim), jnp.float32)
    return (x, x, x, mask, jnp.zeros_like(x))


# Slow the loop so the SIGKILL reliably lands mid-run.
def slow_batch_fn(i):
    time.sleep(0.5)
    return batch_fn(i)

cfg = TrainLoopConfig(num_steps=steps, ckpt_dir=ckpt_dir, ckpt_every=2,
                      keep_last=3)
result = run_training(step, TrainState(0, params, optimizer.init(params)),
                      slow_batch_fn if ckpt_dir else batch_fn, cfg)
final = result.losses.get(result.state.step - 1)
if final is None:
    # Resumed at/after num_steps: the "interrupted" run already finished
    # before the kill landed — no final step executed here to compare.
    print(f'nothing to do: resumed at step {result.state.step}',
          file=sys.stderr)
    sys.exit(2)
with open(loss_out, 'w') as f:
    f.write(repr(final))
print(f'done: step={result.state.step} final_loss={final!r} '
      f'resumed_from={result.resumed_from}')
if ckpt_dir and '--expect-resume' in sys.argv and result.resumed_from is None:
    print('no checkpoint found at start: the kill landed before the first '
          'save (try a larger kill_after)', file=sys.stderr)
    sys.exit(3)
PY

run_job() {  # run_job <ckpt_dir_or_empty> <loss_out> [--expect-resume]
    (cd "$REPO" && python "$WORK/job.py" "$1" "$2" "$STEPS" "${3:-}")
}

echo "== uninterrupted reference run ($STEPS steps)"
run_job "" "$WORK/loss_ref"

echo "== interrupted run: SIGKILL after ${KILL_AFTER}s"
(cd "$REPO" && exec python "$WORK/job.py" "$WORK/ckpt" \
    "$WORK/loss_killed" "$STEPS") &
PID=$!
sleep "$KILL_AFTER"
if kill -9 "$PID" 2>/dev/null; then
    wait "$PID" 2>/dev/null || true
    echo "== killed pid $PID; restarting"
else
    echo "!! job finished before the kill landed — raise steps or lower" \
         "kill_after for a real mid-run kill" >&2
fi

echo "== resumed run"
if ! run_job "$WORK/ckpt" "$WORK/loss_resumed" --expect-resume; then
    echo "!! no genuine mid-run kill/resume was exercised — tune" \
         "kill_after (killed too late: run finished; too early: no" \
         "checkpoint yet)" >&2
    exit 1
fi

REF="$(cat "$WORK/loss_ref")"
RES="$(cat "$WORK/loss_resumed")"
echo "== reference final loss: $REF"
echo "== resumed   final loss: $RES"
if [ "$REF" = "$RES" ]; then
    echo "== smoke_resume OK: kill/resume run matches uninterrupted run"
else
    echo "== smoke_resume FAILED: losses differ" >&2
    exit 1
fi
