# -*- coding: utf-8 -*-
"""
Train a real language model end-to-end on the framework — the capstone
demo (no reference analog: the reference's example stops at one
attention forward + backward, reference example.py:16-33).

The task is long-context copying: each packed segment is

    [BOS, a_1 .. a_L, SEP, a_1 .. a_L]

with the a_i uniform over the data vocabulary. The first half is
incompressible (loss → log V); the second half is exactly predictable —
but ONLY through attention back to the prefix (an induction task, the
canonical long-context probe). Success is therefore crisp: the
copy-region loss falls to ~0 and greedy generation reproduces the
prefix token-for-token through the KV caches.

Pipeline proved here, all sharded over the (data, seq) mesh:

  tokens → TransformerLM (embed → scanned+remat'd TransformerStack with
  flash/ring attention, RoPE, GQA → tied head) → packed-segment
  cross-entropy (make_lm_train_step) → orbax checkpoint mid-run →
  resume → greedy_generate through per-layer KV caches.

Run (CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python examples/train_lm.py --steps 300
Run (one TPU chip, bigger):
  python examples/train_lm.py --seq-len 32768 --dim 512 --layers 8 \\
      --steps 50 --batch 1 --softmax-impl flash
"""

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_dot_product_tpu import (  # noqa: E402
    TrainLoopConfig, TrainState, TransformerLM, greedy_generate,
    lm_targets, run_training,
)
from distributed_dot_product_tpu.parallel.mesh import (  # noqa: E402
    data_seq_mesh, seq_mesh,
)
from distributed_dot_product_tpu.train import make_lm_train_step  # noqa: E402

BOS_OFF, SEP_OFF = 1, 2   # vocab layout: [0..V-3]=data, V-2=SEP, V-1=BOS


def make_copy_batch(key, batch, t, vocab, seg_len):
    """Packed copy-task batch: tokens, targets (copy region only — the
    incompressible prefix is ignore (−1), giving a loss whose floor is
    ~0 instead of ~log V/2), and segment ids. ``seg_len`` must be even:
    each segment is [BOS, prefix(L), SEP, copy(L)] with L = seg_len/2−1.
    """
    if seg_len % 2 or seg_len < 4:
        raise ValueError(f'seg_len must be even and >= 4, got {seg_len}')
    if t % seg_len:
        raise ValueError(f'seq len {t} must pack whole segments of '
                         f'{seg_len}')
    half = seg_len // 2
    n_seg = t // seg_len
    bos, sep = vocab - BOS_OFF, vocab - SEP_OFF
    prefix = jax.random.randint(key, (batch, n_seg, half - 1), 0,
                                vocab - 2)
    seg = jnp.concatenate([
        jnp.full((batch, n_seg, 1), bos), prefix,
        jnp.full((batch, n_seg, 1), sep), prefix], axis=-1)
    tokens = seg.reshape(batch, t).astype(jnp.int32)
    seg_ids = jnp.broadcast_to(
        jnp.repeat(jnp.arange(n_seg, dtype=jnp.int32), seg_len)[None],
        (batch, t))
    targets = lm_targets(tokens, seg_ids)
    # Score the copy region only: positions SEP..end-1 predict the copy.
    pos = jnp.tile(jnp.arange(seg_len), n_seg)
    in_copy = jnp.logical_and(pos >= half, pos < seg_len - 1)
    targets = jnp.where(in_copy[None], targets, -1)
    return tokens, targets, seg_ids


def build_model(args):
    return TransformerLM(
        vocab_size=args.vocab, dim=args.dim, num_heads=args.heads,
        n_layers=args.layers, scan_layers=not args.no_scan,
        remat=args.remat, dtype=jnp.bfloat16 if args.bf16 else None,
        attn_kwargs=dict(softmax_impl=args.softmax_impl,
                         num_kv_heads=args.kv_heads,
                         dropout_rate=args.dropout))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--steps', type=int, default=300)
    p.add_argument('--batch', type=int, default=2)
    p.add_argument('--seq-len', type=int, default=256)
    p.add_argument('--seg-len', type=int, default=64,
                   help='packed segment length (copy span = half - 1)')
    p.add_argument('--vocab', type=int, default=64)
    p.add_argument('--dim', type=int, default=64)
    p.add_argument('--heads', type=int, default=4)
    p.add_argument('--kv-heads', type=int, default=None)
    p.add_argument('--layers', type=int, default=2)
    p.add_argument('--lr', type=float, default=3e-3)
    p.add_argument('--dropout', type=float, default=0.0)
    p.add_argument('--softmax-impl', default='flash',
                   choices=['full', 'online', 'flash', 'ulysses'])
    p.add_argument('--no-scan', action='store_true',
                   help='unrolled layers instead of nn.scan')
    p.add_argument('--remat', action='store_true')
    p.add_argument('--bf16', action='store_true')
    p.add_argument('--ckpt-dir', default=None)
    p.add_argument('--ckpt-every', type=int, default=100)
    p.add_argument('--keep-last', type=int, default=3,
                   help='checkpoint retention (old step dirs GCed)')
    p.add_argument('--generate', action='store_true',
                   help='after training, greedy-generate a copy and '
                        'report token accuracy')
    p.add_argument('--log-every', type=int, default=25)
    args = p.parse_args(argv)

    import optax

    n_dev = jax.device_count()
    if n_dev >= 4 and n_dev % 2 == 0 and args.batch % 2 == 0:
        mesh, data_axis = data_seq_mesh(2, n_dev // 2), 'data'
    else:
        mesh, data_axis = seq_mesh(n_dev), None
    print(f'devices={n_dev} mesh={dict(mesh.shape)} '
          f'backend={jax.default_backend()}')

    model = build_model(args)
    tokens, targets, seg_ids = make_copy_batch(
        jax.random.key(0), args.batch, args.seq_len, args.vocab,
        args.seg_len)
    params = model.init(jax.random.key(1), tokens[:, :args.seg_len])
    n_params = sum(x.size for x in jax.tree.leaves(params))
    print(f'model: {args.layers}L dim={args.dim} heads={args.heads} '
          f'vocab={args.vocab} — {n_params:,} params')

    optimizer = optax.adam(args.lr)
    opt_state = optimizer.init(params)
    # guard=True: NaN/Inf steps skip the update inside the compiled
    # program and surface as bad_step records to the driver.
    step_fn = make_lm_train_step(model, optimizer, mesh,
                                 data_axis=data_axis, donate=False,
                                 guard=True)

    base_key = jax.random.key(2)

    def batch_fn(i):
        # fold_in(step): the data stream is a function of the step
        # index, so a resumed run consumes exactly the batches an
        # uninterrupted run would (a split-chain restarted from the
        # base key would replay the pre-checkpoint batches).
        return make_copy_batch(jax.random.fold_in(base_key, i),
                               args.batch, args.seq_len,
                               args.vocab, args.seg_len)

    # The resilient driver: auto-resume, periodic async saves with
    # retry/backoff, SIGTERM/SIGINT -> final save + clean exit,
    # NaN-guarded stepping with rollback, keep_last retention.
    cfg = TrainLoopConfig(
        num_steps=args.steps, ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every if args.ckpt_dir else 0,
        keep_last=args.keep_last, max_bad_steps=3,
        log_every=args.log_every)
    t0 = time.time()
    result = run_training(step_fn, TrainState(0, params, opt_state),
                          batch_fn, cfg)
    params, opt_state = result.state.params, result.state.opt_state
    start = result.resumed_from or 0
    loss = jnp.asarray(result.losses.get(result.state.step - 1, jnp.nan))
    dt = time.time() - t0
    executed = result.state.step - start   # != args.steps when preempted
    tok = executed * args.batch * args.seq_len
    print(f'trained {executed} steps in {dt:.1f}s '
          f'({tok / max(dt, 1e-9):,.0f} tok/s incl. data+compile)')
    if result.preempted:
        print(f'preempted (exit code {result.exit_code}); state saved '
              f'at step {result.state.step}')
        sys.exit(result.exit_code)

    if args.generate:
        # One fresh segment: prompt = [BOS, prefix, SEP]; the model must
        # reproduce the prefix through its KV caches.
        half = args.seg_len // 2
        tokens, _, _ = make_copy_batch(jax.random.key(99), 1,
                                       args.seg_len, args.vocab,
                                       args.seg_len)
        prompt, answer = tokens[:, :half + 1], tokens[:, half + 1:]
        steps = answer.shape[1]
        out = greedy_generate(model, params, prompt, steps,
                              t_max=args.seg_len)
        acc = float(jnp.mean((out == answer).astype(jnp.float32)))
        print(f'generation: copy accuracy {acc:.1%} over {steps} tokens')
        return {'loss': float(loss), 'acc': acc}
    return {'loss': float(loss), 'acc': None}


if __name__ == '__main__':
    main()
