# -*- coding: utf-8 -*-
"""
Drive the resilient decode serving layer end to end — the serving
counterpart of ``examples/train_lm.py``'s training demo, and the soak
harness ``scripts/smoke_serve.sh`` runs under injected faults.

A seeded request burst (mixed prompt lengths, optional deadlines) is
submitted through the continuous-batching scheduler; the run then
drains to idle and the driver audits the serving layer's contract:

- every submitted request reached a TERMINAL state — completed,
  evicted, deadline_expired, abandoned, failed_nan, or a typed
  rejection (at submit or in queue). Zero dropped-without-reason.
- with faults injected (``DDP_TPU_FAULT_STUCK_STEP``,
  ``DDP_TPU_FAULT_NAN_DECODE_STEP``, ``DDP_TPU_FAULT_ABANDON_REQUEST``
  env knobs), the faulted paths fire (watchdog stall recorded, NaN slot
  quarantined+retried, abandoned slot reclaimed) and readiness still
  ends READY.
- completed requests' token streams are BIT-IDENTICAL to a fault-free
  run of the same seed (``--check-identical`` reruns clean and
  compares) — a quarantine or stall must not perturb surviving
  streams.

Exit code 0 iff every audit passes.

Run (CPU):
  JAX_PLATFORMS=cpu python examples/serve_lm.py --requests 24
Faulted soak (what smoke_serve.sh does):
  DDP_TPU_FAULT_STUCK_STEP=4 DDP_TPU_FAULT_NAN_DECODE_STEP=7 \\
  JAX_PLATFORMS=cpu python examples/serve_lm.py --requests 24 \\
      --queue-limit 6 --check-identical
"""

import argparse
import contextlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from distributed_dot_product_tpu import obs  # noqa: E402
from distributed_dot_product_tpu.serve import (  # noqa: E402
    KernelEngine, Readiness, RejectedError, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils import faults as faults_lib  # noqa: E402
from distributed_dot_product_tpu.utils.tracing import (  # noqa: E402
    MetricsRegistry,
)


def build_requests(args):
    """Seeded mixed burst: prompt lengths cycle short/medium/long, every
    4th request carries a deadline. Deterministic — the fault-free and
    faulted runs submit byte-identical traffic."""
    rng = np.random.default_rng(args.seed)
    reqs = []
    for i in range(args.requests):
        plen = int(rng.integers(1, args.prompt_len + 1))
        prompt = rng.integers(0, args.vocab, size=plen).astype(np.int32)
        reqs.append((f'req-{i:03d}', prompt))
    return reqs


def run_burst(args, *, fault_injector, deadline_every=0,
              flight_dir=None):
    """``fault_injector=False`` means EXPLICITLY unfaulted (the clean
    reference run) — plain None would let the scheduler re-arm the same
    env knobs and make the bit-identity audit compare a faulted run
    against itself. ``flight_dir`` (faulted run only) installs the
    incident flight recorder there: a watchdog stall auto-dumps a
    post-mortem bundle ``obs doctor`` can diagnose."""
    registry = MetricsRegistry()
    recorder = None
    if flight_dir:
        recorder = obs.flight.FlightRecorder(flight_dir,
                                             registry=registry,
                                             sample_interval=0.1)
        obs.flight.install(recorder)
    engine = KernelEngine(slots=args.slots, t_max=args.t_max,
                          vocab=args.vocab,
                          prefill_chunk=args.prefill_chunk,
                          seed=args.seed)
    # Warm all three compiled programs before the watchdog arms: first
    # compile (~0.3-0.5 s on CPU) would otherwise register as a stall
    # and let the "watchdog fired" audit pass without the injected
    # stuck step ever being detected.
    engine.step(np.zeros(args.slots, np.int32),
                np.ones(args.slots, bool))
    engine.prefill(0, np.asarray([0], np.int32))
    for i in range(args.slots):
        engine.reset(i)
    cfg = ServeConfig(queue_limit=args.queue_limit,
                      max_new_tokens=args.max_new,
                      stall_timeout=args.stall_timeout,
                      # The burst intentionally overflows the queue; the
                      # audit wants typed QUEUE_FULL rejections, not
                      # partial 'evicted' streams, so the ladder stops
                      # before eviction here (eviction has its own
                      # tests).
                      evict_before_reject=False,
                      profile_warmup=args.profile_warmup)
    profiler = None
    if args.profile_warmup:
        # Opt-in: pay the profiler's ~14 s one-time native init HERE,
        # so a later adaptive/anomaly capture spends its bounded
        # window on the regression instead of on init.
        import tempfile
        profiler = obs.ProfileCapture(
            tempfile.mkdtemp(prefix='ddp_serve_profiles_'),
            registry=registry)
    sched = Scheduler(engine, cfg, fault_injector=fault_injector,
                      registry=registry, profiler=profiler)
    # Live device telemetry for the duration of the run: the gauges
    # (device.memory.*{device=...}, devices_reporting) land in the
    # same registry the summary below snapshots — real numbers on
    # TPU/GPU, an honest devices_reporting=0 on this CPU mesh.
    devmon = obs.DeviceMonitor(registry=registry, interval=0.2).start()
    rejected = {}
    submitted = build_requests(args)
    t0 = time.perf_counter()
    try:
        for i, (rid, prompt) in enumerate(submitted):
            deadline = None
            if deadline_every and i % deadline_every == 3:
                deadline = sched.clock() + args.deadline_s
            try:
                sched.submit(prompt, request_id=rid, deadline=deadline)
            except RejectedError as e:
                rejected[rid] = e.reason
            # Drain a tick every few submissions: a real frontend
            # interleaves arrivals with serving — and it lets the burst
            # actually overflow a small queue while slots are busy.
            if i % 4 == 3:
                sched.step()
        results = sched.run_until_idle()
        wall = time.perf_counter() - t0
    finally:
        # close() in the cleanup path: step() now re-raises unhandled
        # exceptions (after its flight dump), and an error exit must
        # not leak the watchdog thread or the scheduler's global
        # flight introspection provider.
        sched.close()
        devmon.stop()
        if recorder is not None:
            obs.flight.install(None)
    return sched, registry, submitted, rejected, results, wall, recorder


def run_load_demo(args):
    """``--load SEED``: a seeded open-loop trace (serve/loadgen.py)
    through the scheduler on a virtual clock, goodput report printed
    at exit. Exit 0 iff every submitted request is classified exactly
    once from the event log alone."""
    import tempfile

    from distributed_dot_product_tpu.obs import slo as obs_slo
    from distributed_dot_product_tpu.serve import (
        KernelEngine, LoadGenConfig, ServeConfig, VirtualClock,
        run_load,
    )

    clock = VirtualClock()
    log_path = args.event_log or os.path.join(
        tempfile.gettempdir(), f'serve_lm_load_{os.getpid()}.jsonl')
    obs.remove_log(log_path)    # EventLog appends; a demo wants fresh
    event_log = obs.EventLog(log_path, clock=clock)
    cfg = LoadGenConfig(seed=args.load, rate=args.load_rate,
                        requests=args.requests, vocab=args.vocab)
    engine = KernelEngine(slots=args.slots, t_max=args.t_max,
                          vocab=args.vocab,
                          prefill_chunk=args.prefill_chunk,
                          seed=args.seed)
    registry = MetricsRegistry()
    devmon = obs.DeviceMonitor(registry=registry, interval=0.2).start()
    try:
        res = run_load(cfg, engine=engine,
                       serve_config=ServeConfig(
                           queue_limit=args.queue_limit,
                           max_new_tokens=max(t.new_hi
                                              for t in cfg.tenants),
                           watchdog=False),
                       registry=registry, event_log=event_log,
                       clock=clock)
    finally:
        devmon.stop()
    event_log.close()
    spec = obs_slo.SloSpec(ttft=0.25, per_token=0.05)
    report = obs_slo.goodput(log_path, spec)
    print(f'loadgen seed={args.load}: {len(res.submitted)} requests '
          f'over {res.virtual_seconds:.2f} virtual seconds '
          f'({res.wall_seconds:.2f}s wall, {res.ticks} ticks)')
    print(obs_slo.render_report(report))
    print(f'event log: {log_path}')
    ok = (res.accounted
          and report.requests == len(res.submitted)
          and sum(report.counts.values()) == report.requests)
    print(f'serve_lm --load {"OK" if ok else "AUDIT FAILED"}: '
          f'{report.requests}/{len(res.submitted)} requests '
          f'classified from the event log alone')
    return 0 if ok else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--slots', type=int, default=4)
    p.add_argument('--t-max', type=int, default=64)
    p.add_argument('--vocab', type=int, default=48)
    p.add_argument('--requests', type=int, default=24)
    p.add_argument('--prompt-len', type=int, default=12,
                   help='max prompt length (burst mixes 1..this)')
    p.add_argument('--prefill-chunk', type=int, default=4)
    p.add_argument('--max-new', type=int, default=8)
    p.add_argument('--queue-limit', type=int, default=8)
    p.add_argument('--deadline-every', type=int, default=0,
                   help='every Nth request gets a deadline (0: none)')
    p.add_argument('--deadline-s', type=float, default=0.5)
    p.add_argument('--stall-timeout', type=float, default=0.25)
    p.add_argument('--seed', type=int, default=0)
    p.add_argument('--check-identical', action='store_true',
                   help='rerun fault-free and require completed '
                        'streams to match bit for bit')
    p.add_argument('--event-log',
                   default=os.environ.get(obs.events.ENV_VAR),
                   help='write the JSONL observability event log here '
                        '(default: $DDP_TPU_EVENT_LOG); the audit then '
                        'additionally requires every request timeline '
                        'to be reconstructable from the log alone')
    p.add_argument('--flight-dir',
                   default=os.environ.get('DDP_TPU_FLIGHT_DIR'),
                   help='arm the incident flight recorder rooted here '
                        '(default: $DDP_TPU_FLIGHT_DIR); a watchdog '
                        'stall / NaN storm auto-dumps a post-mortem '
                        'bundle for `obs doctor` (faulted run only)')
    p.add_argument('--profile-warmup', action='store_true',
                   help='pay the jax profiler\'s one-time native init '
                        '(~14 s) at startup so a later triggered '
                        'capture records the regression, not the init')
    p.add_argument('--load', type=int, default=None, metavar='SEED',
                   help='instead of the fixed burst, run a small '
                        'seeded open-loop loadgen trace (virtual '
                        'clock, two tenants) through the scheduler '
                        'and print the goodput-under-SLO report at '
                        'exit — the runnable demo of the load/SLO '
                        'observatory (README "Load testing & SLO '
                        'accounting")')
    p.add_argument('--load-rate', type=float, default=600.0,
                   help='--load: offered rate, requests per VIRTUAL '
                        'second')
    args = p.parse_args(argv)

    if args.load is not None:
        return run_load_demo(args)

    plan = faults_lib.serve_plan_from_env()
    if plan.burst:
        args.requests = plan.burst
    injector = (faults_lib.ServeFaultInjector(plan) if plan.any()
                else None)
    if injector is not None:
        print(f'faults armed: {plan}')

    # The event log captures the FAULTED run only: the --check-identical
    # clean rerun resubmits the same request ids, and logging both would
    # double every timeline.
    event_log = obs.EventLog(args.event_log) if args.event_log else None
    log_ctx = (obs.activate(event_log) if event_log is not None
               else contextlib.nullcontext())
    with log_ctx:
        (sched, registry, submitted, rejected, results, wall,
         recorder) = run_burst(
            args, fault_injector=injector,
            deadline_every=args.deadline_every,
            # The flight recorder rides the FAULTED run only, like the
            # event log: the clean rerun would overwrite the incident
            # window with healthy traffic.
            flight_dir=args.flight_dir if injector is not None
            else None)
    if event_log is not None:
        event_log.close()

    snap = registry.snapshot()
    counters = {k: v for k, v in snap['counters'].items() if v}
    lat = snap['histograms']['serve.step_seconds']
    n_tokens = snap['counters'].get('serve.tokens_generated', 0)
    by_status = {}
    for r in results.values():
        by_status[r.status] = by_status.get(r.status, 0) + 1
    print(f'submitted={len(submitted)} rejected_at_submit={len(rejected)} '
          f'terminal={by_status}')
    print(f'counters: {counters}')
    print(f'step latency: p50={lat["p50"] * 1e3:.2f}ms '
          f'p99={lat["p99"] * 1e3:.2f}ms over {lat["count"]} steps')
    ttft = snap['histograms']['serve.ttft_seconds']
    queue_wait = snap['histograms']['serve.queue_wait_seconds']
    if ttft['count']:
        print(f'request latency: ttft p50={ttft["p50"] * 1e3:.2f}ms '
              f'p99={ttft["p99"] * 1e3:.2f}ms, queue wait '
              f'p50={queue_wait["p50"] * 1e3:.2f}ms')
    print(f'throughput: {n_tokens} tokens in {wall:.2f}s '
          f'({n_tokens / max(wall, 1e-9):,.0f} tok/s)')

    failures = []
    # 1. Full accounting: terminal state or typed rejection for everyone.
    for rid, _ in submitted:
        if rid in rejected:
            if rejected[rid] is None:
                failures.append(f'{rid}: rejection without a reason')
        elif rid not in results:
            failures.append(f'{rid}: dropped without any terminal state')
        elif results[rid].status == 'rejected' \
                and results[rid].reason is None:
            failures.append(f'{rid}: queue rejection without a reason')
    # 2. Faults fired where armed, and the surface recovered.
    if injector is not None:
        if plan.stuck_at_step is not None \
                and sched.health.stall_events < 1:
            failures.append('stuck step armed but watchdog never fired')
        if plan.nan_at_step is not None \
                and snap['counters'].get('serve.nan_quarantined', 0) < 1:
            failures.append('NaN armed but no slot was quarantined')
        if plan.abandon_request is not None \
                and by_status.get('abandoned', 0) < 1:
            failures.append('abandon armed but no stream abandoned')
    if sched.health.readiness is not Readiness.STOPPED:
        failures.append(f'close() left readiness '
                        f'{sched.health.readiness.value}')
    ready_line = [v for _, kind, v, _ in sched.health.transitions
                  if kind == 'readiness']
    if not ready_line or ready_line[-1] != Readiness.STOPPED.value \
            or (len(ready_line) > 1 and ready_line[-2]
                != Readiness.READY.value):
        failures.append(f'readiness not restored to ready before stop: '
                        f'{ready_line}')
    # 3. Event-log reconstruction: every submitted request's complete
    #    lifecycle (admit→…→retire, or reject/evict with reason) must
    #    be rebuildable from the JSONL alone — the observability
    #    layer's acceptance contract.
    if args.event_log:
        _, schema_errors = obs.validate_file(args.event_log)
        for err in schema_errors:
            failures.append(f'event-log schema: {err}')
        timelines = obs.reconstruct(args.event_log)
        unreconstructed = 0
        for rid, _ in submitted:
            tl = timelines.get(rid)
            if tl is None:
                failures.append(f'{rid}: absent from the event log')
                unreconstructed += 1
            elif not tl.complete:
                failures.append(f'{rid}: incomplete timeline: '
                                + '; '.join(tl.errors))
                unreconstructed += 1
        ok = not unreconstructed and not schema_errors
        print(f'event-log timeline audit: {"ok" if ok else "FAILED"} '
              f'({len(submitted) - unreconstructed}/{len(submitted)} '
              f'requests reconstructed from {args.event_log})')
    # 3b. Incident flight recorder: with the recorder armed and a
    #     stuck step injected, the watchdog stall must have auto-
    #     dumped a post-mortem bundle (what `obs doctor` diagnoses —
    #     scripts/smoke_serve.sh runs it over this very bundle).
    if recorder is not None:
        for d in recorder.dumps:
            print(f'flight bundle [{d["trigger"]}]: {d["path"]}')
        if injector is not None and plan.stuck_at_step is not None \
                and not any(d['trigger'] == 'stall'
                            for d in recorder.dumps):
            failures.append('stuck step armed and flight recorder '
                            'installed, but no stall bundle was '
                            'auto-dumped')
    # 4. Fault isolation: completed streams identical to a clean run.
    if args.check_identical:
        _, _, _, rej0, clean, _, _ = run_burst(args,
                                               fault_injector=False,
                                               deadline_every=0)
        for rid, r in results.items():
            if r.status != 'completed' or r.degraded:
                continue
            ref = clean.get(rid)
            if ref is not None and ref.status == 'completed' \
                    and not ref.degraded and ref.tokens != r.tokens:
                failures.append(f'{rid}: tokens diverged from the '
                                f'fault-free run')
        print(f'bit-identity check against clean rerun: '
              f'{"FAILED" if any("diverged" in f for f in failures) else "ok"}')

    if failures:
        print('AUDIT FAILED:')
        for f in failures:
            print(f'  - {f}')
        return 1
    print(f'serve_lm OK: all {len(submitted)} requests accounted for, '
          f'readiness restored')
    return 0


if __name__ == '__main__':
    sys.exit(main())
