# -*- coding: utf-8 -*-
"""
End-to-end example: sequence-parallel multi-head attention, forward +
backward + optimizer step on a device mesh.

TPU-native rebuild of the reference example (reference example.py:1-33),
which needed ``horovodrun -np N --mpi python example.py`` to spawn N
processes, each pinning one GPU and feeding its own ``(1, T/N, 768)`` shard.
Here it is ONE program: a 1-D ``'seq'`` mesh over every visible device, the
global ``(1, T, 768)`` batch sharded across it, and a single jitted SPMD
train step. Run it anywhere:

    python example.py                      # real devices (e.g. 1 TPU chip)
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python example.py                  # simulate an 8-device mesh

Matches the reference's workload: T=4096 global, model dim 768, 2 heads,
offset=64, zero boolean mask, MSE loss against a random target, seed 111
(reference example.py:12,20,25-29).
"""

import time

import jax
import jax.numpy as jnp
import optax

from distributed_dot_product_tpu import DistributedDotProductAttn, seq_mesh
from distributed_dot_product_tpu.train import make_train_step


def main():
    mesh = seq_mesh()
    n = mesh.devices.size
    print(f'mesh: {n} x {jax.devices()[0].platform} '
          f'(axis {tuple(mesh.axis_names)})')

    dim, heads, t_global, offset = 768, 2, 4096, 64
    model = DistributedDotProductAttn(key_dim=dim, num_heads=heads,
                                      offset=offset)

    key = jax.random.key(111)  # reference example.py:12
    k_in, k_tgt, k_init = jax.random.split(key, 3)
    x = jax.random.normal(k_in, (1, t_global, dim), jnp.float32)
    target = jax.random.normal(k_tgt, (1, t_global, dim), jnp.float32)
    mask = jnp.zeros((1, t_global, t_global), dtype=bool)  # example.py:29

    params = model.init(k_init, x, x, x, mask)
    optimizer = optax.adam(1e-4)
    opt_state = optimizer.init(params)

    step = make_train_step(model, optimizer, mesh)
    batch = (x, x, x, mask, target)

    print('compiling + first step...')
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, batch)
    jax.block_until_ready(loss)
    print(f'step 0: loss={float(loss):.6f} '
          f'({time.perf_counter() - t0:.1f}s incl. compile)')

    for i in range(1, 4):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, batch)
        jax.block_until_ready(loss)
        print(f'step {i}: loss={float(loss):.6f} '
              f'({(time.perf_counter() - t0) * 1000:.1f} ms)')


if __name__ == '__main__':
    main()
