# -*- coding: utf-8 -*-
"""
Chrome-trace / Perfetto export (obs/trace.py): a real scheduler run
(and a faulted one) exports to schema-valid Chrome Trace Event JSON —
phase slices partitioning each request's lane, instant markers for the
discrete incidents (faults, preempts, quarantines, handoffs), one
process track per replica label, per-track monotone timestamps — and
the validator actually rejects the malformed shapes CI gates on.
"""

import json

import numpy as np
import pytest

from distributed_dot_product_tpu.obs.events import EventLog
from distributed_dot_product_tpu.obs.trace import (
    INSTANT_EVENTS, export_trace, validate_trace, write_trace,
)
from distributed_dot_product_tpu.serve import (
    KernelEngine, Scheduler, ServeConfig, VirtualClock,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

VOCAB = 16


def _run(tmp_path, *, injector=None, engine_kw=None, **cfg_kw):
    clock = VirtualClock()
    log = EventLog(tmp_path / 'serve.jsonl', clock=clock)
    cfg_kw.setdefault('queue_limit', 8)
    cfg_kw.setdefault('max_new_tokens', 5)
    engine_kw = dict(engine_kw or {})
    engine_kw.setdefault('t_max', 32)
    sched = Scheduler(
        KernelEngine(slots=2, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl='xla', **engine_kw),
        ServeConfig(watchdog=False, **cfg_kw), clock=clock,
        registry=MetricsRegistry(),
        fault_injector=injector if injector is not None else False,
        event_log=log, on_tick=lambda s: clock.advance(0.01))
    for i in range(4):
        sched.submit(np.asarray([i + 1], np.int32),
                     request_id=f'r{i}')
    results = sched.run_until_idle()
    sched.close()
    log.close()
    return log.path, results


def test_export_is_valid_and_carries_phase_slices(tmp_path, devices):
    path, results = _run(tmp_path)
    trace = export_trace(path)
    assert validate_trace(trace) == []
    evs = trace['traceEvents']
    slices = [e for e in evs if e['ph'] == 'X']
    assert slices, 'no phase slices'
    # Every completed request owns decode slices; args name it.
    rids = {e['args']['request_id'] for e in slices}
    assert rids == set(results)
    assert all(e['dur'] >= 0 for e in slices)
    # Rebased microsecond timestamps: earliest record at ts 0.
    assert min(e['ts'] for e in evs if e['ph'] != 'M') == 0.0
    # One metadata record names each process track.
    metas = [e for e in evs if e['ph'] == 'M']
    assert metas and metas[0]['name'] == 'process_name'


def test_faulted_run_gets_instant_markers(tmp_path, devices):
    """NaN quarantine + fault injection render as 'i' markers on the
    victim's track — the incidents an operator scrubs for."""
    plan = ServeFaultPlan(nan_at_step=2, nan_slot=0)
    path, _ = _run(tmp_path,
                   injector=ServeFaultInjector(plan))
    trace = export_trace(path)
    assert validate_trace(trace) == []
    marks = [e for e in trace['traceEvents'] if e['ph'] == 'i']
    names = {e['name'] for e in marks}
    assert 'fault' in names, names
    assert 'quarantine' in names, names
    for e in marks:
        assert e['s'] in ('t', 'p')
        assert e['args']['event'] in INSTANT_EVENTS


def test_preempt_marker_on_paged_exhaustion(tmp_path, devices):
    path, _ = _run(tmp_path, max_new_tokens=8, max_requeues=6,
                   spec='ngram', spec_k=3, evict_before_reject=False,
                   engine_kw=dict(cache_mode='paged', page_size=2,
                                  pages=5, t_max=16))
    trace = export_trace(path)
    assert validate_trace(trace) == []
    marks = {e['name'] for e in trace['traceEvents']
             if e['ph'] == 'i'}
    assert 'preempt' in marks, marks
    # The requeue arc also renders its stall slice.
    assert any(e['ph'] == 'X' and e['name'] == 'stall'
               for e in trace['traceEvents'])


def test_multi_source_tracks_one_pid_per_replica(tmp_path):
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    a = EventLog(tmp_path / 'a.jsonl', clock=clock)
    b = EventLog(tmp_path / 'b.jsonl', clock=clock)
    a.emit('serve.admit', request_id='x', slot=0, tenant='t')
    a.emit('serve.decode', request_id='x', slot=0, token_index=0)
    a.emit('serve.retire', request_id='x', status='completed',
           total_seconds=2.5)
    b.emit('serve.admit', request_id='y', slot=1, tenant='t')
    b.emit('serve.retire', request_id='y', status='completed',
           total_seconds=1.0)
    a.close(), b.close()

    trace = export_trace([('r0', a.path), ('r1', b.path)])
    assert validate_trace(trace) == []
    evs = trace['traceEvents']
    names = {e['args']['name'] for e in evs if e['ph'] == 'M'}
    assert names == {'r0', 'r1'}
    pids = {e['args']['name']: e['pid'] for e in evs
            if e['ph'] == 'M'}
    xs = [e for e in evs if e['ph'] == 'X']
    assert {e['pid'] for e in xs if e['args']['request_id'] == 'x'} \
        == {pids['r0']}
    assert {e['pid'] for e in xs if e['args']['request_id'] == 'y'} \
        == {pids['r1']}


def test_write_trace_round_trips(tmp_path, devices):
    path, _ = _run(tmp_path)
    out = tmp_path / 'trace.json'
    trace = write_trace(path, out)
    on_disk = json.loads(out.read_text())
    assert on_disk == json.loads(json.dumps(trace))
    assert validate_trace(on_disk) == []
    assert on_disk['displayTimeUnit'] == 'ms'


def test_validator_rejects_malformed_traces():
    ok = {'traceEvents': [
        {'name': 'a', 'ph': 'X', 'ts': 0.0, 'dur': 1.0,
         'pid': 1, 'tid': 0},
        {'name': 'b', 'ph': 'i', 'ts': 2.0, 'pid': 1, 'tid': 0},
    ]}
    assert validate_trace(ok) == []
    [err] = validate_trace('{nope')
    assert err.startswith('not JSON')
    assert validate_trace({}) == ["missing top-level 'traceEvents'"]
    # Missing required key.
    bad = {'traceEvents': [{'ph': 'X', 'ts': 0.0, 'dur': 1.0,
                            'pid': 1, 'tid': 0}]}
    assert any('missing' in e for e in validate_trace(bad))
    # Negative duration.
    bad = {'traceEvents': [{'name': 'a', 'ph': 'X', 'ts': 0.0,
                            'dur': -1.0, 'pid': 1, 'tid': 0}]}
    assert any('dur' in e for e in validate_trace(bad))
    # Non-monotone ts on one track regresses; separate tracks don't.
    bad = {'traceEvents': [
        {'name': 'a', 'ph': 'i', 'ts': 5.0, 'pid': 1, 'tid': 0},
        {'name': 'b', 'ph': 'i', 'ts': 1.0, 'pid': 1, 'tid': 0},
    ]}
    assert any('regresses' in e for e in validate_trace(bad))
    fine = {'traceEvents': [
        {'name': 'a', 'ph': 'i', 'ts': 5.0, 'pid': 1, 'tid': 0},
        {'name': 'b', 'ph': 'i', 'ts': 1.0, 'pid': 2, 'tid': 0},
    ]}
    assert validate_trace(fine) == []


def _cli(argv, capsys):
    from distributed_dot_product_tpu.obs.__main__ import main
    rc = main(argv)
    return rc, capsys.readouterr().out


def test_cli_trace_export(tmp_path, capsys, devices):
    path, _ = _run(tmp_path)
    out = tmp_path / 'trace.json'
    rc, text = _cli(['trace', 'export', str(path), '-o', str(out)],
                    capsys)
    assert rc == 0
    assert 'OK' in text
    assert validate_trace(json.loads(out.read_text())) == []
