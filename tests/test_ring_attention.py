# -*- coding: utf-8 -*-
"""
Ring-attention (online softmax) tests.

No reference analog (SURVEY §2.2: "Ring attention: No" — the reference's
communication is chunked allgather with full-row softmax). Oracle strategy
follows the reference pattern anyway: an unsharded local computation
(``local_attention_reference``) is ground truth; the ring result over a
shard_map mesh must match to fp32 tolerance, including gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.models.ring_attention import (
    local_attention_reference, ring_attention,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

pytestmark = pytest.mark.slow  # Pallas-interpreter / lax.scan-heavy cases


def test_causal_union_empty_row_zero_across_impls():
    """A row emptied only by the UNION of user mask and causality must be 0
    with zero gradients in ring, local-reference AND flash paths — the
    softmax impls must agree on inputs like this."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t, row, dh = 16, 5, 8
    ks = jax.random.split(jax.random.key(3), 3)
    q, k, v = (jax.random.normal(kk, (2, t, dh), jnp.float32) for kk in ks)
    m = jnp.zeros((2, t, t), dtype=bool).at[:, row, :row + 1].set(True)

    local = local_attention_reference(q, k, v, m, causal=True)
    flash = flash_attention(q, k, v, m, causal=True)
    mesh4 = seq_mesh(4)
    ring = jax.shard_map(
        lambda q, k, v, m: ring_attention(q, k, v, m, causal=True),
        mesh=mesh4,
        in_specs=(P(None, 'seq', None),) * 3 + (P(None, 'seq', None),),
        out_specs=P(None, 'seq', None), check_vma=False,
    )(q, k, v, m)

    for name, out in [('local', local), ('flash', flash), ('ring', ring)]:
        assert (np.asarray(out)[:, row] == 0).all(), name
    np.testing.assert_allclose(np.asarray(ring), np.asarray(local),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(local),
                               atol=1e-5, rtol=1e-5)
    g = jax.grad(lambda v: jnp.sum(local_attention_reference(
        q, k, v, m, causal=True) ** 2))(v)
    assert np.isfinite(np.asarray(g)).all()


WORLD = 4
TN = 6
T = WORLD * TN
HEADS = 3
DH = 8
BATCH = 2


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _qkv(dv=DH):
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (BATCH, HEADS, T, DH), jnp.float32)
    k = jax.random.normal(ks[1], (BATCH, HEADS, T, DH), jnp.float32)
    v = jax.random.normal(ks[2], (BATCH, HEADS, T, dv), jnp.float32)
    return q, k, v


def _mask(p=0.3):
    m = jax.random.bernoulli(jax.random.key(9), p, (BATCH, 1, T, T))
    return m.at[..., 0].set(False)  # keep every row attendable


def _ring_global(mesh, **kw):
    spec = P(None, None, 'seq', None)

    def fn(q, k, v, m):
        return ring_attention(q, k, v, m, **kw)

    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, P(None, None, 'seq', None)),
        out_specs=spec, check_vma=False)


@pytest.mark.parametrize('block_impl', ['flash', 'xla'])
@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('masked', [False, True])
def test_forward_matches_oracle(mesh, causal, masked, block_impl):
    q, k, v = _qkv(dv=10)
    m = _mask() if masked else None
    ring = _ring_global(mesh, causal=causal, block_impl=block_impl)
    if m is None:
        spec = P(None, None, 'seq', None)
        ring = jax.shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, causal=causal,
                                              block_impl=block_impl),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)
        out = ring(q, k, v)
    else:
        out = ring(q, k, v, m)
    want = local_attention_reference(q, k, v, m, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('block_impl', ['flash', 'xla'])
def test_gradients_match_oracle(mesh, block_impl):
    q, k, v = _qkv()
    m = _mask()
    ring = _ring_global(mesh, block_impl=block_impl)
    cot = jax.random.normal(jax.random.key(5), v.shape, jnp.float32)

    g_ring = jax.grad(
        lambda q_, k_, v_: jnp.sum(ring(q_, k_, v_, m) * cot),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q_, k_, v_: jnp.sum(
            local_attention_reference(q_, k_, v_, m) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_causal_grads_flash_vs_xla_blocks(mesh, causal):
    """The kernel-backed fold and the einsum fold are the same math —
    gradients must agree on masked + causal inputs (the flash backend's
    VJP is a hand-built second ring pass; this pins it to the autodiff of
    the XLA fold, independently of the local oracle)."""
    q, k, v = _qkv()
    m = _mask()
    cot = jax.random.normal(jax.random.key(7), v.shape, jnp.float32)

    def grads(block_impl):
        ring = _ring_global(mesh, causal=causal, block_impl=block_impl)
        return jax.grad(
            lambda q_, k_, v_: jnp.sum(ring(q_, k_, v_, m) * cot),
            argnums=(0, 1, 2))(q, k, v)

    for got, want in zip(grads('flash'), grads('xla')):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_fully_masked_row_is_zero_not_nan(mesh):
    """Improvement over the reference, which NaNs on fully-masked rows
    (softmax over all -inf, SURVEY §4 'What is NOT tested')."""
    q, k, v = _qkv()
    m = jnp.zeros((BATCH, 1, T, T), bool).at[0, 0, 3, :].set(True)
    out = _ring_global(mesh)(q, k, v, m)
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_array_equal(np.asarray(out[0, :, 3]), 0.0)
    # Gradients through the masked row are finite too.
    g = jax.grad(lambda v_: jnp.sum(_ring_global(mesh)(q, k, v_, m)))(v)
    assert bool(jnp.isfinite(g).all())


def test_module_online_softmax_matches_full(mesh):
    """DistributedDotProductAttn(softmax_impl='online') must reproduce the
    reference-parity 'full' path (same math, different memory profile)."""
    kwargs = dict(key_dim=16, num_heads=4, offset=2)
    full = DistributedDotProductAttn(**kwargs)
    online = DistributedDotProductAttn(softmax_impl='online', **kwargs)
    oracle = DistributedDotProductAttn(distributed=False, **kwargs)

    x = jax.random.normal(jax.random.key(1), (BATCH, T, 16), jnp.float32)
    m = jax.random.bernoulli(jax.random.key(2), 0.25, (BATCH, T, T))
    m = m.at[..., 0].set(False)
    params = oracle.init(jax.random.key(3), x, x, x, m)

    out_full = apply_seq_parallel(full, params, mesh, x, x, x, m)
    out_online = apply_seq_parallel(online, params, mesh, x, x, x, m)
    out_oracle = oracle.apply(params, x, x, x, m)
    np.testing.assert_allclose(np.asarray(out_online), np.asarray(out_full),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_online),
                               np.asarray(out_oracle), rtol=1e-5, atol=1e-5)

    # Gradient parity between the two distributed softmax paths.
    def loss(mod):
        return lambda p: jnp.sum(
            apply_seq_parallel(mod, p, mesh, x, x, x, m) ** 2)
    g_full = jax.grad(loss(full))(params)
    g_online = jax.grad(loss(online))(params)
    for got, want in zip(jax.tree.leaves(g_online), jax.tree.leaves(g_full)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_local_oracle_online_equals_plain_softmax():
    """local_attention_reference (big-neg masking) == plain -inf softmax on
    rows that have at least one valid position."""
    q, k, v = _qkv()
    m = _mask()
    got = local_attention_reference(q, k, v, m)
    scores = jnp.einsum('...td,...od->...to', q / jnp.sqrt(1.0 * DH), k)
    scores = jnp.where(m, -jnp.inf, scores)
    want = jnp.einsum('...to,...od->...td', jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize('world', [4, 8])
def test_zigzag_causal_matches_oracle(world):
    """layout='zigzag': shard i holds half-stripes {i, 2W-1-i}. Permuting
    global arrays in (zigzag_indices) and out (argsort) must reproduce the
    contiguous causal oracle exactly — forward and gradients."""
    from distributed_dot_product_tpu.models.ring_attention import (
        zigzag_indices,
    )
    t = world * 8
    mesh = seq_mesh(world)
    ks = jax.random.split(jax.random.key(11), 4)
    q, k, v = (jax.random.normal(kk, (BATCH, HEADS, t, DH), jnp.float32)
               for kk in ks[:3])
    idx = zigzag_indices(t, world)
    inv = jnp.argsort(idx)
    spec = P(None, None, 'seq', None)

    ring = jax.shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, causal=True,
                                          layout='zigzag'),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)

    def zig(fn):
        def run(q_, k_, v_):
            out = fn(q_[..., idx, :], k_[..., idx, :], v_[..., idx, :])
            return out[..., inv, :]
        return run

    got = zig(ring)(q, k, v)
    want = local_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    cot = jax.random.normal(ks[3], v.shape, jnp.float32)
    g_zig = jax.grad(lambda q_, k_, v_: jnp.sum(zig(ring)(q_, k_, v_) * cot),
                     argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q_, k_, v_: jnp.sum(
        local_attention_reference(q_, k_, v_, causal=True) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for got_g, want_g in zip(g_zig, g_ref):
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                                   rtol=1e-4, atol=1e-5)


def test_zigzag_layout_validation():
    q = jnp.zeros((2, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match='zigzag'):
        ring_attention(q, q, q, causal=False, layout='zigzag')
    with pytest.raises(ValueError, match='zigzag'):
        ring_attention(q, q, q, causal=True, layout='zigzag',
                       block_impl='xla')
    with pytest.raises(ValueError, match='even'):
        ring_attention(q[:, :7], q[:, :7], q[:, :7], causal=True,
                       layout='zigzag')


def test_zigzag_dense_mask_matches_oracle():
    """Round-5: zigzag + dense mask. The mask's ROW axis is permuted like
    the inputs (rows follow the shard's layout); columns stay global and
    each fold gathers the owner's column block — the result must equal
    the contiguous causal+mask oracle, forward and gradients."""
    from distributed_dot_product_tpu.models.ring_attention import (
        zigzag_indices,
    )
    world = 4
    t = world * 8
    mesh = seq_mesh(world)
    ks = jax.random.split(jax.random.key(21), 4)
    q, k, v = (jax.random.normal(kk, (BATCH, HEADS, t, DH), jnp.float32)
               for kk in ks[:3])
    m = jax.random.bernoulli(jax.random.key(22), 0.3, (BATCH, 1, t, t))
    m = m.at[..., 0].set(False)          # keep every row attendable
    idx = zigzag_indices(t, world)
    inv = jnp.argsort(idx)
    spec = P(None, None, 'seq', None)

    ring = jax.shard_map(
        lambda q_, k_, v_, m_: ring_attention(q_, k_, v_, m_, causal=True,
                                              layout='zigzag'),
        mesh=mesh, in_specs=(spec,) * 4, out_specs=spec, check_vma=False)

    def zig(q_, k_, v_):
        # Rows permute with the inputs; columns stay global.
        out = ring(q_[..., idx, :], k_[..., idx, :], v_[..., idx, :],
                   m[..., idx, :])
        return out[..., inv, :]

    got = zig(q, k, v)
    want = local_attention_reference(q, k, v, m, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    cot = jax.random.normal(ks[3], v.shape, jnp.float32)
    g_zig = jax.grad(lambda *a: jnp.sum(zig(*a) * cot),
                     argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: jnp.sum(local_attention_reference(
        *a, m, causal=True) * cot), argnums=(0, 1, 2))(q, k, v)
    for got_g, want_g in zip(g_zig, g_ref):
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                                   rtol=1e-4, atol=1e-5)


def test_zigzag_fully_masked_row_zero():
    """Zigzag + mask inherits the fully-masked-row → 0 contract."""
    from distributed_dot_product_tpu.models.ring_attention import (
        zigzag_indices,
    )
    world = 4
    t = world * 8
    mesh = seq_mesh(world)
    ks = jax.random.split(jax.random.key(23), 3)
    q, k, v = (jax.random.normal(kk, (BATCH, HEADS, t, DH), jnp.float32)
               for kk in ks)
    row = 5
    m = jnp.zeros((BATCH, 1, t, t), bool).at[:, :, row, :].set(True)
    idx = zigzag_indices(t, world)
    inv = jnp.argsort(idx)
    spec = P(None, None, 'seq', None)
    ring = jax.shard_map(
        lambda q_, k_, v_, m_: ring_attention(q_, k_, v_, m_, causal=True,
                                              layout='zigzag'),
        mesh=mesh, in_specs=(spec,) * 4, out_specs=spec, check_vma=False)
    out = ring(q[..., idx, :], k[..., idx, :], v[..., idx, :],
               m[..., idx, :])[..., inv, :]
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_array_equal(np.asarray(out[:, :, row]), 0.0)
