# -*- coding: utf-8 -*-
"""
Sequence-sharded decode (round 5): the KV cache slab-sharded on its
t_max axis over the mesh, appends landing on the owning shard, softmax
merged by the flash-decoding pmax/psum rule. Contract: bit-for-tolerance
parity with the LOCAL decode path for every knob, through both the op
layer and the module surface, including prefill chunks that straddle
shard boundaries.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu import DistributedDotProductAttn
from distributed_dot_product_tpu.models.attention import (
    decode_seq_parallel,
)
from distributed_dot_product_tpu.models.decode import (
    append_kv, append_kv_sharded, decode_attention, init_cache,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD, B, H, D = 4, 2, 4, 16
T_MAX = 32                       # global capacity; 8 per shard


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _cache_spec(cache):
    return jax.tree.map(
        lambda x: P(None, None, 'seq', None) if x.ndim == 4 else P(),
        cache)


def _sharded_append_then_attend(mesh, cache, ks, vs, q, **kw):
    """Append each (k, v) chunk through append_kv_sharded, then one
    merged decode_attention — all inside a single shard_map."""
    spec = _cache_spec(cache)

    def fn(c, q, *chunks):
        for k_new, v_new in zip(chunks[::2], chunks[1::2]):
            c = append_kv_sharded(c, k_new, v_new, axis_name='seq')
        return c, decode_attention(q, c, axis_name='seq', **kw)

    flat = [x for pair in zip(ks, vs) for x in pair]
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(spec,) + (P(),) * (1 + len(flat)),
        out_specs=(spec, P()), check_vma=False)(cache, q, *flat)


def _local_append_then_attend(cache, ks, vs, q, **kw):
    for k_new, v_new in zip(ks, vs):
        cache = append_kv(cache, k_new, v_new)
    return cache, decode_attention(q, cache, **kw)


@pytest.mark.parametrize('hkv', [H, 1])
def test_sharded_decode_matches_local(mesh, hkv):
    keys = jax.random.split(jax.random.key(0), 4)
    # Prefill chunk of 13 (straddles the 8-wide shard slabs), then two
    # single-token appends; q attends the 15-deep prefix.
    k1 = jax.random.normal(keys[0], (B, hkv, 13, D), jnp.float32)
    v1 = jax.random.normal(keys[1], (B, hkv, 13, D), jnp.float32)
    k2, v2 = k1[:, :, :1] + 1.0, v1[:, :, :1] - 1.0
    k3, v3 = k1[:, :, 1:2] * 2.0, v1[:, :, 1:2] * 0.5
    q = jax.random.normal(keys[2], (B, H, 1, D), jnp.float32)

    local = init_cache(B, hkv, T_MAX, D, dtype=jnp.float32)
    # The sharded cache is built at GLOBAL capacity; shard_map splits it
    # into per-shard t_local slabs through the cache spec.
    shard_global = init_cache(B, hkv, T_MAX, D, dtype=jnp.float32)

    lc, lout = _local_append_then_attend(
        local, [k1, k2, k3], [v1, v2, v3], q)
    sc, sout = _sharded_append_then_attend(
        mesh, shard_global, [k1, k2, k3], [v1, v2, v3], q)
    assert int(lc.length) == int(sc.length) == 15
    np.testing.assert_allclose(np.asarray(sout), np.asarray(lout),
                               atol=2e-5, rtol=1e-5)
    # The sharded buffers, concatenated, hold exactly the local buffers.
    np.testing.assert_allclose(np.asarray(sc.k), np.asarray(lc.k),
                               atol=0)
    np.testing.assert_allclose(np.asarray(sc.v), np.asarray(lc.v),
                               atol=0)


def test_sharded_decode_knobs_match_local(mesh):
    """window + ALiBi + int8 through the merged softmax."""
    keys = jax.random.split(jax.random.key(1), 3)
    fill = 14
    k1 = jax.random.normal(keys[0], (B, H, fill, D), jnp.float32)
    v1 = jax.random.normal(keys[1], (B, H, fill, D), jnp.float32)
    q = jax.random.normal(keys[2], (B, H, 1, D), jnp.float32)
    slopes = jnp.asarray([2.0 ** -(i + 1) for i in range(H)])
    for kw in (dict(window=6), dict(alibi_slopes=slopes),
               dict(qk_quant='int8')):
        local = init_cache(B, H, T_MAX, D, dtype=jnp.float32,
                           qk_quant=kw.get('qk_quant'))
        shard_global = init_cache(B, H, T_MAX, D, dtype=jnp.float32,
                                  qk_quant=kw.get('qk_quant'))
        lc, lout = _local_append_then_attend(local, [k1], [v1], q, **kw)
        sc, sout = _sharded_append_then_attend(mesh, shard_global,
                                               [k1], [v1], q, **kw)
        np.testing.assert_allclose(np.asarray(sout), np.asarray(lout),
                                   atol=2e-5, rtol=1e-5, err_msg=str(kw))


def test_module_decode_sharded_matches_local(mesh):
    """Module surface: decode_seq_parallel (projections, GQA, RoPE,
    append, merged attention) == the local module decode, token by
    token, with the cache staying sharded between steps."""
    dim = 32
    model = DistributedDotProductAttn(
        key_dim=dim, num_heads=4, num_kv_heads=2, causal=True,
        use_rope=True)
    x = jax.random.normal(jax.random.key(0), (B, 10, dim), jnp.float32)
    params = model.init(jax.random.key(1), x, x, x, None)

    local_cache = model.make_decode_cache(B, T_MAX)
    shard_cache = model.make_decode_cache(B, T_MAX)
    for t in range(6):
        xt = x[:, t:t + 1]
        local_cache, lout = model.apply(params, xt, xt, xt, local_cache,
                                        method='decode')
        shard_cache, sout = decode_seq_parallel(
            model, params, mesh, xt, xt, xt, shard_cache)
        np.testing.assert_allclose(np.asarray(sout), np.asarray(lout),
                                   atol=2e-5, rtol=1e-5, err_msg=f't={t}')
    assert int(shard_cache.length) == 6


def test_sharded_straddling_overflow_drops_whole_append(mesh):
    """A prefill chunk that would CROSS the global capacity writes
    nothing — not even its in-capacity prefix — exactly like
    append_kv (the parity the sharded path is pinned to)."""
    cap = WORLD * 2                      # 8 global slots
    local = init_cache(1, 1, cap, D, dtype=jnp.float32)
    shard_global = init_cache(1, 1, cap, D, dtype=jnp.float32)
    k1 = jnp.ones((1, 1, 6, D), jnp.float32)
    k2 = jnp.full((1, 1, 4, D), 7.0, jnp.float32)   # 6 + 4 > 8
    q = jnp.ones((1, 1, 1, D), jnp.float32)

    with pytest.raises(ValueError, match='overflow'):
        _local_append_then_attend(local, [k1, k2], [k1, k2], q)
    local2 = init_cache(1, 1, cap, D, dtype=jnp.float32)
    local2 = append_kv(local2, k1, k1)

    sc, _ = _sharded_append_then_attend(mesh, shard_global,
                                        [k1, k2], [k1, k2], q)
    assert int(sc.length) == 10          # length still flags it
    # Buffers hold ONLY the first append — the straddling chunk wrote
    # neither its in-capacity rows (6, 7) nor anything else.
    np.testing.assert_array_equal(np.asarray(sc.k),
                                  np.asarray(local2.k))
    np.testing.assert_array_equal(np.asarray(sc.v),
                                  np.asarray(local2.v))


def test_decode_seq_parallel_caches_compiled_step(mesh):
    """A per-token serving loop must trace ONCE: repeated
    decode_seq_parallel calls for the same (module, mesh) reuse one
    jitted step (the round-5 review found the original wrapper
    re-traced every token)."""
    from distributed_dot_product_tpu.models import attention as attn_mod
    model = DistributedDotProductAttn(key_dim=16, num_heads=2,
                                      causal=True)
    x = jnp.ones((1, 4, 16), jnp.float32)
    params = model.init(jax.random.key(0), x, x, x, None)
    cache = model.make_decode_cache(1, 8)
    key = (model, mesh, None)
    attn_mod._DECODE_STEPS.pop(key, None)
    for t in range(3):
        xt = x[:, t:t + 1]
        cache, _ = decode_seq_parallel(model, params, mesh, xt, xt, xt,
                                       cache)
    step = attn_mod._DECODE_STEPS.get(key)
    assert step is not None, 'compiled step was not cached'
    if hasattr(step, '_cache_size'):
        # At most two traces: the first call sees the host-built
        # (unsharded) cache, every later call the steady-state sharded
        # layout — not one trace per token.
        assert step._cache_size() <= 2, step._cache_size()
    assert int(cache.length) == 3


def test_module_decode_sharded_kernel_matches_local(mesh):
    """decode_sharded on the fused Pallas kernel path (decode_impl):
    each shard runs the kernel over its slab, the owner appends in
    place, and the pmax/psum merge reproduces the local XLA decode."""
    dim = 32
    kw = dict(key_dim=dim, num_heads=4, num_kv_heads=2, causal=True,
              use_rope=True)
    local_model = DistributedDotProductAttn(decode_impl='xla', **kw)
    kernel_model = DistributedDotProductAttn(decode_impl='kernel', **kw)
    x = jax.random.normal(jax.random.key(3), (B, 8, dim), jnp.float32)
    params = local_model.init(jax.random.key(1), x, x, x, None)
    local_cache = local_model.make_decode_cache(B, T_MAX)
    shard_cache = kernel_model.make_decode_cache(B, T_MAX)
    for t in range(5):
        xt = x[:, t:t + 1]
        local_cache, lout = local_model.apply(
            params, xt, xt, xt, local_cache, method='decode')
        shard_cache, sout = decode_seq_parallel(
            kernel_model, params, mesh, xt, xt, xt, shard_cache)
        np.testing.assert_allclose(np.asarray(sout), np.asarray(lout),
                                   atol=2e-5, rtol=1e-5, err_msg=f't={t}')
    assert int(shard_cache.length) == 5
    # The sharded slabs, concatenated, hold the local buffers.
    np.testing.assert_allclose(np.asarray(shard_cache.k),
                               np.asarray(local_cache.k), atol=2e-6)


def test_decode_steps_cache_is_bounded(mesh, monkeypatch):
    """The compiled-step cache evicts LRU past its cap instead of
    growing for every (module, mesh, axis) a long-lived host cycles
    through."""
    from distributed_dot_product_tpu.models import attention as attn_mod
    monkeypatch.setattr(attn_mod, '_DECODE_STEPS_CAP', 2)
    attn_mod._DECODE_STEPS.clear()
    x = jnp.ones((1, 4, 16), jnp.float32)
    for offset in (4, 8, 16):        # three distinct hashable modules
        model = DistributedDotProductAttn(key_dim=16, num_heads=2,
                                          causal=True, offset=offset)
        params = model.init(jax.random.key(0), x, x, x, None)
        cache = model.make_decode_cache(1, 8)
        xt = x[:, :1]
        decode_seq_parallel(model, params, mesh, xt, xt, xt, cache)
    assert len(attn_mod._DECODE_STEPS) <= 2


def test_decode_seq_parallel_warns_once_on_unhashable(mesh):
    """An unhashable module (array-valued field) silently re-traced the
    whole step EVERY token; now it warns — once."""
    import warnings as _warnings

    from distributed_dot_product_tpu.models import attention as attn_mod
    model = DistributedDotProductAttn(
        key_dim=16, num_heads=2, causal=True, softmax_impl='flash',
        alibi_slopes=jnp.asarray([0.5, 0.25]))     # unhashable field
    x = jnp.ones((1, 4, 16), jnp.float32)
    params = model.init(jax.random.key(0), x, x, x, None)
    cache = model.make_decode_cache(1, 8)
    xt = x[:, :1]
    attn_mod._WARNED_UNHASHABLE = False
    with pytest.warns(UserWarning, match='unhashable'):
        cache, _ = decode_seq_parallel(model, params, mesh, xt, xt, xt,
                                       cache)
    with _warnings.catch_warnings():
        _warnings.simplefilter('error')            # a repeat would raise
        decode_seq_parallel(model, params, mesh, xt, xt, xt, cache)


def test_sharded_overflow_advances_length_without_write(mesh):
    """Appending past the GLOBAL capacity writes nowhere; length still
    flags it (the append_kv overflow contract, sharded)."""
    cap = WORLD * 2
    cache = init_cache(1, 1, cap, D, dtype=jnp.float32)
    spec = _cache_spec(cache)

    def fn(c, chunk):
        for i in range(cap + 2):
            c = append_kv_sharded(c, chunk + i, chunk + i,
                                  axis_name='seq')
        return c

    chunk = jnp.ones((1, 1, 1, D), jnp.float32)
    out = jax.shard_map(fn, mesh=mesh, in_specs=(spec, P()),
                        out_specs=spec, check_vma=False)(cache, chunk)
    assert int(out.length) == cap + 2
    # Slots hold appends 0..cap-1 (values 1..cap); the two overflowing
    # appends wrote nowhere.
    np.testing.assert_array_equal(
        np.asarray(out.k[0, 0, :, 0]),
        np.arange(1.0, cap + 1, dtype=np.float32))