# -*- coding: utf-8 -*-
"""
Module-level surface for the round-3 kernel features: dropout (flax rngs
AND explicit-seed forms), ALiBi, qk_quant — threaded through
`DistributedDotProductAttn` and `apply_seq_parallel` on the sharded mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD, LEN, DIM = 4, 16, 32
T = WORLD * LEN

pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _inputs(key=0):
    kk, kq, kv = jax.random.split(jax.random.key(key), 3)
    return (jax.random.normal(kk, (2, T, DIM)),
            jax.random.normal(kq, (2, T, DIM)),
            jax.random.normal(kv, (2, T, DIM)))


def _model(**kw):
    return DistributedDotProductAttn(key_dim=DIM, num_heads=4,
                                     softmax_impl='flash', **kw)


def test_module_dropout_seed_and_determinism(mesh):
    m = _model(dropout_rate=0.3)
    k, q, v = _inputs()
    params = m.init(jax.random.key(0), k, q, v, None)
    a = apply_seq_parallel(m, params, mesh, k, q, v, dropout_seed=7)
    b = apply_seq_parallel(m, params, mesh, k, q, v, dropout_seed=7)
    c = apply_seq_parallel(m, params, mesh, k, q, v, dropout_seed=8)
    d = apply_seq_parallel(m, params, mesh, k, q, v, deterministic=True)
    no_drop = _model()
    e = apply_seq_parallel(no_drop, params, mesh, k, q, v)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    np.testing.assert_allclose(np.asarray(d), np.asarray(e), atol=1e-6)


def test_module_dropout_flax_rngs(mesh):
    m = _model(dropout_rate=0.3)
    k, q, v = _inputs(key=1)
    params = m.init(jax.random.key(0), k, q, v, None)
    rngs = {'dropout': jax.random.key(42)}
    a = apply_seq_parallel(m, params, mesh, k, q, v, rngs=rngs)
    b = apply_seq_parallel(m, params, mesh, k, q, v, rngs=rngs)
    c = apply_seq_parallel(m, params, mesh, k, q, v,
                           rngs={'dropout': jax.random.key(43)})
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_module_dropout_missing_rng_raises(mesh):
    m = _model(dropout_rate=0.3)
    k, q, v = _inputs(key=2)
    params = m.init(jax.random.key(0), k, q, v, None)
    with pytest.raises(Exception, match='dropout'):
        apply_seq_parallel(m, params, mesh, k, q, v)


def test_module_alibi_matches_local_oracle(mesh):
    slopes = tuple(float(2.0 ** (-i - 1)) for i in range(4))
    kw = dict(causal=True, alibi_slopes=slopes)
    dist = _model(**kw)
    local = DistributedDotProductAttn(key_dim=DIM, num_heads=4,
                                      softmax_impl='flash',
                                      distributed=False, **kw)
    k, q, v = _inputs(key=3)
    params = local.init(jax.random.key(1), k, q, v, None)
    out = apply_seq_parallel(dist, params, mesh, k, q, v)
    ref = local.apply(params, k, q, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # and it actually biases: differs from the no-alibi module
    plain = _model(causal=True)
    base = apply_seq_parallel(plain, params, mesh, k, q, v)
    assert not np.allclose(np.asarray(out), np.asarray(base), atol=1e-3)


def test_module_qk_quant_close_to_exact(mesh):
    m = _model(qk_quant='int8')
    k, q, v = _inputs(key=4)
    params = m.init(jax.random.key(0), k, q, v, None)
    out = apply_seq_parallel(m, params, mesh, k, q, v)
    exact = apply_seq_parallel(_model(), params, mesh, k, q, v)
    err = float(jnp.abs(out - exact).max())
    assert 1e-7 < err < 5e-2, err   # engaged, and within int8 noise


def test_module_feature_validation():
    with pytest.raises(ValueError, match='flash'):
        DistributedDotProductAttn(key_dim=DIM, dropout_rate=0.1).init(
            jax.random.key(0), *([jnp.zeros((1, 8, DIM))] * 3), None)
    with pytest.raises(ValueError, match='causal'):
        DistributedDotProductAttn(
            key_dim=DIM, softmax_impl='flash',
            alibi_slopes=(0.5,), num_heads=1).init(
                jax.random.key(0), *([jnp.zeros((1, 8, DIM))] * 3), None)
    # Round 5: int8 QK^T runs on the ring path too — only the 'full'
    # parity path still rejects it.
    with pytest.raises(ValueError, match='online'):
        DistributedDotProductAttn(
            key_dim=DIM, softmax_impl='full', qk_quant='int8').init(
                jax.random.key(0), *([jnp.zeros((1, 8, DIM))] * 3), None)
    DistributedDotProductAttn(
        key_dim=DIM, softmax_impl='online', qk_quant='int8').init(
            jax.random.key(0), *([jnp.zeros((1, 8, DIM))] * 3), None)


def test_module_ulysses_dropout_and_alibi(mesh):
    slopes = tuple(float(2.0 ** (-i - 1)) for i in range(4))
    m = DistributedDotProductAttn(
        key_dim=DIM, num_heads=4, softmax_impl='ulysses', causal=True,
        alibi_slopes=slopes, dropout_rate=0.2)
    k, q, v = _inputs(key=5)
    params = m.init(jax.random.key(0), k, q, v, None)
    a = apply_seq_parallel(m, params, mesh, k, q, v, dropout_seed=3)
    b = apply_seq_parallel(m, params, mesh, k, q, v, dropout_seed=3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # deterministic ulysses+alibi == flash local oracle with same knobs
    local = DistributedDotProductAttn(
        key_dim=DIM, num_heads=4, softmax_impl='flash', causal=True,
        alibi_slopes=slopes, distributed=False)
    out = apply_seq_parallel(m, params, mesh, k, q, v,
                             deterministic=True)
    ref = local.apply(params, k, q, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
