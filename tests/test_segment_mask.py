# -*- coding: utf-8 -*-
"""
Segment-id (packed-sequence) masks and fully-masked-block skipping.

No reference analog: the reference supports only dense boolean masks
(reference README.md:67) and its benchmark masks are all-False. The
segment form is the TPU-native compact mask — O(T) kernel traffic instead
of an O(T²) streamed operand — and the oracle for every test here is the
SAME math with the densified mask ``seg_q[i] != seg_kv[j]``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.ops.pallas_attention import (
    _reference_math, flash_attention,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

B, H, T, D = 2, 3, 96, 16


def _qkv(key=0):
    ks = jax.random.split(jax.random.key(key), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32)
                 for k in ks)


def _packed_segments():
    """Sorted ids, 3 uneven packed sequences: the representative case."""
    return jnp.concatenate([
        jnp.zeros(40, jnp.int32), jnp.ones(26, jnp.int32),
        jnp.full(30, 2, jnp.int32)])[None]                  # (1, T)


def _densify(seg_q, seg_k):
    return seg_q[..., :, None] != seg_k[..., None, :]


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('mode', ['exact', 'bounded'])
def test_segments_match_dense_oracle(causal, mode):
    q, k, v = _qkv()
    seg = _packed_segments()
    dense = _densify(seg, seg)[:, None]                     # (1, 1, T, T)
    want = _reference_math(q, k, v, jnp.broadcast_to(dense, (B, 1, T, T)),
                           1.0 / np.sqrt(D), causal)
    got = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                          softmax_mode=mode)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_segment_grads_match_dense_mask(causal):
    q, k, v = _qkv()
    seg = _packed_segments()
    dense = _densify(seg, seg)[:, None]
    cot = jax.random.normal(jax.random.key(5), v.shape, jnp.float32)

    g_seg = jax.grad(lambda q_, k_, v_: jnp.sum(flash_attention(
        q_, k_, v_, causal=causal, segment_ids=seg) * cot),
        argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(lambda q_, k_, v_: jnp.sum(flash_attention(
        q_, k_, v_, dense, causal=causal) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_seg, g_dense):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)


def test_segments_compose_with_dense_mask():
    """segment_ids AND a dense mask apply as a union of maskings."""
    q, k, v = _qkv()
    seg = _packed_segments()
    extra = jax.random.bernoulli(jax.random.key(7), 0.2, (B, 1, T, T))
    union = jnp.logical_or(_densify(seg, seg)[:, None], extra)
    want = _reference_math(q, k, v, union, 1.0 / np.sqrt(D), False)
    got = flash_attention(q, k, v, extra, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_segment_pair_cross_length():
    """(seg_q, seg_kv) pair with Tq != Tk; single-array form rejected."""
    q, k, v = _qkv()
    tq = 24
    qs = q[..., :tq, :]
    seg_q = _packed_segments()[:, :tq]
    seg_k = _packed_segments()
    want = _reference_math(
        qs, k, v,
        jnp.broadcast_to(_densify(seg_q, seg_k)[:, None], (B, 1, tq, T)),
        1.0 / np.sqrt(D), False)
    got = flash_attention(qs, k, v, segment_ids=(seg_q, seg_k))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match='Tq == Tk'):
        flash_attention(qs, k, v, segment_ids=seg_k)


def test_unsorted_segments_still_exact():
    """The block-skip uses [min, max] interval disjointness — conservative
    but EXACT for any id layout, not just sorted/packed ones."""
    q, k, v = _qkv()
    seg = jax.random.randint(jax.random.key(3), (1, T), 0, 4)
    dense = _densify(seg, seg)[:, None]
    want = _reference_math(q, k, v, jnp.broadcast_to(dense, (B, 1, T, T)),
                           1.0 / np.sqrt(D), False)
    got = flash_attention(q, k, v, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_fully_masked_blocks_skipped_exactly():
    """A dense mask with entire (Q block, K block) tiles masked: the
    summary-driven skip must be invisible in the numbers (fwd + grads).
    Block-diagonal mask at T=96 guarantees fully-masked off-diagonal
    tiles at every block size the kernel can pick."""
    q, k, v = _qkv(key=1)
    blk = jnp.arange(T) // 32
    mask = (blk[:, None] != blk[None, :])[None, None]        # (1,1,T,T)
    want = _reference_math(q, k, v, jnp.broadcast_to(mask, (B, 1, T, T)),
                           1.0 / np.sqrt(D), False)
    got = flash_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    cot = jax.random.normal(jax.random.key(9), v.shape, jnp.float32)
    g = jax.grad(lambda q_, k_, v_: jnp.sum(
        flash_attention(q_, k_, v_, mask) * cot), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q_, k_, v_: jnp.sum(_reference_math(
        q_, k_, v_, jnp.broadcast_to(mask, (B, 1, T, T)),
        1.0 / np.sqrt(D), False) * cot), argnums=(0, 1, 2))(q, k, v)
    for got_g, want_g in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                                   atol=1e-5, rtol=1e-4)


def test_segment_empty_row_zero_with_zero_grads():
    """A q position whose segment id matches NO kv position outputs 0 with
    zero (finite) gradients — in-kernel, with no densified any-valid."""
    q, k, v = _qkv()
    seg_q = _packed_segments().at[0, 5].set(7)              # id 7 nowhere in kv
    seg_k = _packed_segments()
    out = flash_attention(q, k, v, segment_ids=(seg_q, seg_k))
    assert bool(jnp.isfinite(out).all())
    np.testing.assert_array_equal(np.asarray(out[:, :, 5]), 0.0)
    g = jax.grad(lambda v_: jnp.sum(flash_attention(
        q, k, v_, segment_ids=(seg_q, seg_k))))(v)
    assert bool(jnp.isfinite(g).all())


@pytest.mark.slow
def test_mask_dma_redirect_path_exact(monkeypatch):
    """The TPU-only scalar-prefetch mask redirect (non-mixed tiles alias
    block (0,0) so their DMA disappears) must be numerically invisible.
    Off-TPU it is disabled (the HLO interpreter cannot run prefetch
    grids); force it on tiny shapes under the Mosaic interpreter and
    compare fwd + grads against the plain streaming path."""
    import distributed_dot_product_tpu.ops.pallas_attention as pa
    q, k, v = _qkv(key=2)
    blk = jnp.arange(T) // 32
    # fully-masked tiles (skipped), fully-unmasked tiles (redirected,
    # computed mask-free) and mixed tiles (streamed) all present
    mask = (blk[:, None] != blk[None, :])[None, None]
    mask = mask.at[:, :, :40, :].set(False)
    cot = jax.random.normal(jax.random.key(4), v.shape, jnp.float32)

    def run():
        out = flash_attention(q, k, v, mask, causal=True)
        g = jax.grad(lambda q_, k_, v_: jnp.sum(flash_attention(
            q_, k_, v_, mask, causal=True) * cot),
            argnums=(0, 1, 2))(q, k, v)
        return out, g

    want_out, want_g = run()
    monkeypatch.setattr(pa, '_REDIRECT_ON_INTERPRET', True)
    got_out, got_g = run()
    np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                               atol=1e-6, rtol=1e-6)
    for got, want in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6, rtol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize('impl', ['full', 'online', 'flash', 'ulysses'])
def test_module_segment_ids_all_paths(impl):
    """Every softmax path accepts segment_ids and matches the local oracle
    with the densified mask (flash/ulysses in-kernel, full/online via
    densification)."""
    world = 4
    mesh = seq_mesh(world)
    dim, heads, t = 16, 4, 32
    model = DistributedDotProductAttn(key_dim=dim, num_heads=heads,
                                      offset=2, softmax_impl=impl)
    oracle = DistributedDotProductAttn(key_dim=dim, num_heads=heads,
                                       offset=2, distributed=False)
    x = jax.random.normal(jax.random.key(1), (B, t, dim), jnp.float32)
    seg = jnp.concatenate([jnp.zeros(t // 2, jnp.int32),
                           jnp.ones(t - t // 2, jnp.int32)])[None]
    seg = jnp.broadcast_to(seg, (B, t))
    params = oracle.init(jax.random.key(3), x, x, x, None)

    got = apply_seq_parallel(model, params, mesh, x, x, x, None,
                             segment_ids=seg)
    want = oracle.apply(params, x, x, x, _densify(seg, seg))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-4)
