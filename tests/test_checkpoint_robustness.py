# -*- coding: utf-8 -*-
"""
Checkpoint-subsystem robustness: per-root async-save state scoping,
structure-mismatch diagnostics, crash-mid-save recovery
(``recover_interrupted``), and ``keep_last`` retention GC — the
filesystem-level half of the fault-tolerance contract (the driver-level
half lives in test_train_loop.py).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.utils import checkpoint as ckpt
from distributed_dot_product_tpu.utils.checkpoint import (
    CheckpointMismatchError, TrainState, gc_old_steps, latest_step,
    recover_interrupted, restore, save, wait,
)


def _state(step, scale=1.0):
    return TrainState(step, {'w': jnp.full((4,), scale)},
                      {'m': jnp.zeros((4,))})


def test_async_pending_state_scoped_per_root(tmp_path):
    """Two runs (roots) in one process must not interleave each other's
    deferred-backup cleanup: wait(A) finalizes and cleans A's overwrite
    backup but leaves B's pending bookkeeping for B's own wait."""
    root_a, root_b = str(tmp_path / 'a'), str(tmp_path / 'b')
    save(root_a, _state(1, 1.0))
    save(root_b, _state(1, 10.0))
    # Async overwrites on BOTH roots: each defers its backup cleanup.
    save(root_a, _state(1, 2.0), blocking=False)
    save(root_b, _state(1, 20.0), blocking=False)
    pend_a = ckpt._pending(root_a)
    pend_b = ckpt._pending(root_b)
    assert pend_a.async_pending and pend_b.async_pending
    assert len(pend_a.backups) == 1 and len(pend_b.backups) == 1

    wait(root_a)
    assert not pend_a.async_pending and not pend_a.backups
    # B untouched: still pending, backup still tracked (and on disk).
    assert pend_b.async_pending and len(pend_b.backups) == 1
    assert not any(n.endswith('.replaced') for n in os.listdir(root_a))

    wait(root_b)
    assert not pend_b.async_pending and not pend_b.backups
    assert not any(n.endswith('.replaced') for n in os.listdir(root_b))
    # Both roots restore their own (new) contents.
    got_a = restore(root_a, _state(0))
    got_b = restore(root_b, _state(0))
    np.testing.assert_array_equal(np.asarray(got_a.params['w']),
                                  np.full((4,), 2.0))
    np.testing.assert_array_equal(np.asarray(got_b.params['w']),
                                  np.full((4,), 20.0))


def test_bare_wait_finalizes_all_roots(tmp_path):
    root_a, root_b = str(tmp_path / 'a'), str(tmp_path / 'b')
    save(root_a, _state(1))
    save(root_b, _state(1))
    save(root_a, _state(1, 2.0), blocking=False)
    save(root_b, _state(1, 2.0), blocking=False)
    wait()
    for root in (root_a, root_b):
        st = ckpt._pending(root)
        assert not st.async_pending and not st.backups
        assert not any(n.endswith('.replaced') for n in os.listdir(root))


def test_restore_mismatch_raises_diagnostic_error(tmp_path):
    """A template that doesn't match the on-disk tree must produce a
    CheckpointMismatchError naming the step dir, both structures, and
    the TrainState-change hint — not an opaque orbax traceback."""
    save(tmp_path, _state(3))
    bad_template = TrainState(0, {'completely': {'different': jnp.zeros(2)}},
                              {'m': jnp.zeros((4,))})
    with pytest.raises(CheckpointMismatchError) as ei:
        restore(tmp_path, bad_template)
    msg = str(ei.value)
    assert 'step_000000003' in msg
    assert 'expected (template)' in msg and 'found (on disk)' in msg
    assert 'hint' in msg and 'TrainState' in msg
    # The original orbax error is chained for debugging.
    assert ei.value.__cause__ is not None
    # A matching template still restores fine afterwards.
    assert restore(tmp_path, _state(0)).step == 3


def test_restore_missing_still_filenotfound(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore(tmp_path / 'nope', _state(0))


def test_restore_io_errors_keep_their_type(tmp_path, monkeypatch):
    """Transient I/O failures during restore must NOT be rebranded as
    structure mismatches — callers need the OSError type to classify
    and retry them."""
    save(tmp_path, _state(1))

    class _FlakyCkptr:
        def restore(self, *a, **k):
            raise PermissionError('storage said no')

    monkeypatch.setattr(ckpt, '_checkpointer', lambda: _FlakyCkptr())
    with pytest.raises(PermissionError):
        restore(tmp_path, _state(0))


def test_latest_step_skips_partial_write_and_recovers(tmp_path):
    """Crash-mid-save recovery: an unfinalized .orbax-checkpoint-tmp dir
    and a step_N.replaced backup on disk — latest_step skips the partial
    write; recover_interrupted removes it and restores the backup, after
    which the newest finalized step is the recovered one."""
    save(tmp_path, _state(1, 1.0))
    save(tmp_path, _state(2, 2.0))
    # Simulate a crash mid-OVERWRITE of step 2: the old step 2 was
    # renamed to .replaced and the replacement write never finalized.
    d2 = tmp_path / 'step_000000002'
    d2.rename(tmp_path / 'step_000000002.replaced')
    partial = tmp_path / 'step_000000002.orbax-checkpoint-tmp-42'
    partial.mkdir()
    (partial / 'partial').write_text('dead write')

    assert latest_step(tmp_path) == 1   # partial + backup both skipped

    actions = recover_interrupted(tmp_path)
    kinds = {a for a, _ in actions}
    assert 'removed-partial' in kinds and 'restored-backup' in kinds
    assert latest_step(tmp_path) == 2   # the backup IS step 2 again
    got = restore(tmp_path, _state(0))
    assert got.step == 2
    np.testing.assert_array_equal(np.asarray(got.params['w']),
                                  np.full((4,), 2.0))
    assert not any('.orbax-checkpoint-tmp' in n
                   for n in os.listdir(tmp_path))


def test_recover_removes_stale_backup_of_finalized_step(tmp_path):
    save(tmp_path, _state(1, 1.0))
    # A stale backup whose original finalized fine: cleanup only.
    stale = tmp_path / 'step_000000001.replaced'
    stale.mkdir()
    (stale / 'junk').write_text('old')
    actions = recover_interrupted(tmp_path)
    assert ('removed-stale-backup', 'step_000000001.replaced') in actions
    assert latest_step(tmp_path) == 1
    assert not (tmp_path / 'step_000000001.replaced').exists()


def test_gc_old_steps_keeps_newest_finalized(tmp_path):
    for s in (1, 2, 3, 4, 5):
        save(tmp_path, _state(s, float(s)))
    # An unfinalized partial must neither count toward keep_last nor be
    # deleted (it may be an in-flight async save).
    partial = tmp_path / 'step_000000006.orbax-checkpoint-tmp-1'
    partial.mkdir()
    deleted = gc_old_steps(tmp_path, keep_last=2)
    assert deleted == [1, 2, 3]
    names = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith('step_'))
    assert names == ['step_000000004', 'step_000000005',
                     'step_000000006.orbax-checkpoint-tmp-1']
    assert latest_step(tmp_path) == 5
    got = restore(tmp_path, _state(0))
    np.testing.assert_array_equal(np.asarray(got.params['w']),
                                  np.full((4,), 5.0))
    # keep_last larger than what exists: no-op.
    assert gc_old_steps(tmp_path, keep_last=10) == []
    # Disabled retention: no-op.
    assert gc_old_steps(tmp_path, keep_last=0) == []


def test_gc_removes_stale_backups_of_deleted_steps(tmp_path):
    for s in (1, 2, 3):
        save(tmp_path, _state(s))
    stale = tmp_path / 'step_000000001.replaced'
    stale.mkdir()
    (stale / 'junk').write_text('x')
    assert gc_old_steps(tmp_path, keep_last=1) == [1, 2]
    names = set(os.listdir(tmp_path))
    assert 'step_000000001.replaced' not in names
    assert 'step_000000003' in names
