# -*- coding: utf-8 -*-
"""
Bundle diagnosis (obs/doctor.py): each incident class classified from
a synthetic bundle carrying its signature evidence, tie-break order,
affected-party naming, and the human rendering.
"""

import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import doctor as obs_doctor
from distributed_dot_product_tpu.obs import flight
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs


def _bundle_from(tmp_path, emit_fn, *, trigger='manual', registry=None):
    """One bundle whose ring holds exactly the events ``emit_fn``
    writes."""
    reg = registry or MetricsRegistry()
    with flight.recording(base_dir=tmp_path / 'flight',
                          registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        emit_fn(log)
        log.close()
        path = rec.dump_bundle(trigger=trigger)
    return flight.load_bundle(path)


def test_cache_exhaustion_classified(tmp_path):
    def emit(log):
        log.emit('serve.admit', request_id='a', slot=0, tenant='t0',
                 queue_wait=0.0)
        log.emit('serve.preempt', request_id='a', slot=0,
                 requeued=True)
        log.emit('serve.admit', request_id='a', slot=1, tenant='t0',
                 queue_wait=0.1)
        log.emit('serve.preempt', request_id='a', slot=1,
                 requeued=False)
        log.emit('serve.evict', request_id='a', slot=1)
        log.emit('serve.retire', request_id='a', status='evicted',
                 reason='cache_exhausted', tenant='t0')
        log.emit('serve.reject', request_id='b',
                 reason='cache_exhausted', tenant='t0')

    incident = obs_doctor.diagnose(_bundle_from(tmp_path, emit))
    assert incident.primary == 'cache_exhaustion'
    assert incident.affected['preempted'] == ['a']
    assert incident.affected['rejected'] == ['b']
    out = obs_doctor.render_incident(incident)
    assert 'cache_exhausted' in out and 'preemption' in out


def test_cache_exhaustion_pages_free_sample_counts(tmp_path):
    """The metric-sample channel is evidence too: a sample showing
    pages_free == 0 with pages in use votes even without events."""
    reg = MetricsRegistry()
    reg.gauge('serve.cache.pages_free').set(0)
    reg.gauge('serve.cache.pages_used').set(16)
    incident = obs_doctor.diagnose(
        _bundle_from(tmp_path, lambda log: None, registry=reg))
    assert incident.classes['cache_exhaustion']['score'] > 0
    assert incident.primary == 'cache_exhaustion'


def test_deadline_storm_classified(tmp_path):
    def emit(log):
        for i in range(4):
            log.emit('serve.reject', request_id=f'd{i}',
                     reason='deadline_exceeded', tenant='t0')
        log.emit('serve.admit', request_id='e', slot=0, tenant='t0',
                 queue_wait=0.0)
        log.emit('serve.retire', request_id='e',
                 status='deadline_expired', tenant='t0')

    incident = obs_doctor.diagnose(_bundle_from(tmp_path, emit))
    assert incident.primary == 'deadline_storm'
    assert incident.affected['rejected'] == [f'd{i}' for i in range(4)]
    assert incident.affected['failed'] == ['e']


def test_overload_classified_and_tenants_named(tmp_path):
    def emit(log):
        for i in range(6):
            log.emit('serve.reject', request_id=f'q{i}',
                     reason='queue_full',
                     tenant='free' if i % 2 else 'paid')
        log.emit('health.readiness', state='not_ready',
                 reason='queue full')
        log.emit('serve.admit', request_id='ok', slot=0, tenant='paid',
                 queue_wait=0.0)
        log.emit('serve.decode', request_id='ok', slot=0,
                 token_index=0, ttft=0.01)
        log.emit('serve.retire', request_id='ok', status='completed',
                 total_seconds=0.05, tenant='paid')

    incident = obs_doctor.diagnose(_bundle_from(tmp_path, emit))
    assert incident.primary == 'overload'
    assert set(incident.tenants) == {'free', 'paid'}
    assert incident.tenants['paid']['met'] == 1
    assert incident.tenants['free']['rejected'] == 3
    out = obs_doctor.render_incident(incident)
    assert 'queue_full' in out
    assert 'free' in out and 'paid' in out


def test_empty_bundle_is_inconclusive_with_note(tmp_path):
    incident = obs_doctor.diagnose(
        _bundle_from(tmp_path, lambda log: None))
    assert incident.primary is None
    assert any('no events' in n for n in incident.notes)
    out = obs_doctor.render_incident(incident)
    assert 'inconclusive' in out


def test_ring_truncation_is_noted(tmp_path):
    reg = MetricsRegistry()
    with flight.recording(base_dir=tmp_path / 'flight', registry=reg,
                          max_records=4) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        for i in range(10):
            log.emit('serve.reject', request_id=f'r{i}',
                     reason='queue_full', tenant='t0')
        log.close()
        path = rec.dump_bundle(trigger='manual')
    incident = obs_doctor.diagnose(path)
    # 6 events evicted by the record cap, plus the dump-time forced
    # metric/device sample pair that shares the same bound.
    assert incident.window['ring_dropped'] >= 6
    assert any('truncated' in n for n in incident.notes)


def test_anomaly_verdicts_ride_along(tmp_path):
    def emit(log):
        log.emit('anomaly.detected', metric='serve.cache.pages_free',
                 detector='StaticThreshold', value=0.0,
                 watch='pages_free')

    incident = obs_doctor.diagnose(_bundle_from(tmp_path, emit))
    assert len(incident.anomalies) == 1
    assert incident.classes['cache_exhaustion']['score'] > 0
    out = obs_doctor.render_incident(incident)
    assert 'anomaly' in out and 'pages_free' in out


def test_multi_bundle_diagnosis_names_the_replica(tmp_path):
    """Per-replica bundles (a disaggregated topology dumps one black
    box per decode pool) merge into ONE diagnosis: scores sum, the
    verdict names the replica whose bundle carries the primary
    evidence, and affected request ids are prefixed with their
    replica."""
    def emit_quiet(log):
        log.emit('serve.admit', request_id='ok-1', slot=0, tenant='t0',
                 queue_wait=0.0)
        log.emit('serve.retire', request_id='ok-1', status='completed',
                 tenant='t0')

    def emit_nan_storm(log):
        for i in range(3):
            log.emit('serve.admit', request_id=f'n{i}', slot=i,
                     tenant='t1', queue_wait=0.0)
            log.emit('serve.quarantine', request_id=f'n{i}', slot=i,
                     requeued=False)
            log.emit('serve.retire', request_id=f'n{i}',
                     status='failed_nan', tenant='t1')

    quiet = _bundle_from(tmp_path / 'q', emit_quiet)
    stormy = _bundle_from(tmp_path / 's', emit_nan_storm,
                          trigger='nan_storm')
    incident = obs_doctor.diagnose_bundles(
        [('r0', quiet), ('r1', stormy)])
    assert incident.primary == 'nan_storm'
    assert incident.replica == 'r1'
    # Affected ids say where their lifecycle ran.
    assert incident.affected['quarantined'] == ['r1:n0', 'r1:n1',
                                                'r1:n2']
    # Evidence lines carry the bundle label.
    assert any(ev.startswith('[r1]') for ev in
               incident.classes['nan_storm']['evidence'])
    # Tenants sum across replicas.
    assert incident.tenants['t0']['requests'] == 1
    assert incident.tenants['t1']['requests'] == 3
    out = obs_doctor.render_incident(incident)
    assert 'replica r1' in out and 'r1:n0' in out
    # One bundle degenerates to the single-bundle contract (no labels).
    solo = obs_doctor.diagnose_bundles([('r1', stormy)])
    assert solo.replica is None
    assert solo.affected['quarantined'] == ['n0', 'n1', 'n2']


def test_multi_bundle_doctor_cli(tmp_path):
    """`obs doctor r0=B0 r1=B1` merges labeled bundles and prints the
    replica in the verdict; exit 0."""
    import json as _json
    import subprocess
    import sys

    def emit(log):
        log.emit('serve.admit', request_id='a', slot=0, tenant='t0',
                 queue_wait=0.0)
        log.emit('serve.quarantine', request_id='a', slot=0,
                 requeued=False)
        log.emit('serve.retire', request_id='a', status='failed_nan',
                 tenant='t0')

    reg = MetricsRegistry()
    with flight.recording(base_dir=tmp_path / 'f0',
                          registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'e0.jsonl')
        log.emit('health.liveness', state='alive')
        log.close()
        b0 = rec.dump_bundle(trigger='manual')
    with flight.recording(base_dir=tmp_path / 'f1',
                          registry=MetricsRegistry()) as rec:
        log = obs.EventLog(tmp_path / 'e1.jsonl')
        emit(log)
        log.close()
        b1 = rec.dump_bundle(trigger='nan_storm')
    proc = subprocess.run(
        [sys.executable, '-m', 'distributed_dot_product_tpu.obs',
         'doctor', f'r0={b0}', f'r1={b1}', '--json'],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    payload = _json.loads(proc.stdout)
    assert payload['primary'] == 'nan_storm'
    assert payload['replica'] == 'r1'
    assert 'r1:a' in payload['affected']['failed']
