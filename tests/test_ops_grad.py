# -*- coding: utf-8 -*-
"""
Gradient tests for the differentiable distributed matmul operators.

The reference only tests gradients end-to-end through the attention module
(reference tests/test_gradient.py) and leaves ``LeftTransposeMultiplication``
completely untested (SURVEY §4) — which is how its transposed left-gradient
bug (reference ops.py:69) survived. Here every operator's custom VJP is
checked directly against full-array autodiff.

Oracle: for random cotangent-weight ``S``, compare
``∇ sum(dist_op(L, R) * S)`` (JAX autodiff through shard_map + custom_vjp)
with ``∇ sum(local_op(L, R) * S)`` (plain autodiff on the unsharded arrays).
Tolerance 1e-5, matching the reference's input-grad comparison
(reference test_gradient.py:107-113).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.ops.ops import (
    matmul_all, matmul_nt, matmul_tn,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD = 4
LENGTH = 5   # deliberately not a multiple of typical offsets
DIM = 7
T = WORLD * LENGTH


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _rand(key, *shape):
    return jax.random.normal(jax.random.key(key), shape, dtype=jnp.float32)


def _global_op(op, mesh, ndim, offset, impl):
    spec = P(*([None] * (ndim - 2) + ['seq', None]))
    fn = partial(op, offset=offset, impl=impl)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec),
                        out_specs=spec, check_vma=False)


LOCAL = {
    'nt': lambda l, r: jnp.matmul(l, jnp.swapaxes(r, -1, -2)),
    'all': lambda l, r: jnp.matmul(l, r),
    'tn': lambda l, r: jnp.matmul(jnp.swapaxes(l, -1, -2), r),
}
DIST = {'nt': matmul_nt, 'all': matmul_all, 'tn': matmul_tn}

SHAPES = {
    # op -> (left shape, right shape) ; 3-D batch variant exercised for nt.
    'nt': ((T, DIM), (T, DIM)),
    'all': ((T, T), (T, DIM)),
    'tn': ((T, T), (T, DIM)),
}


@pytest.mark.parametrize('op', ['nt', 'all', 'tn'])
@pytest.mark.parametrize('offset', [2, 3, None])
@pytest.mark.parametrize('impl', ['allgather', 'ring'])
def test_vjp_matches_full_autodiff(mesh, op, offset, impl):
    lshape, rshape = SHAPES[op]
    left, right = _rand(0, *lshape), _rand(1, *rshape)

    dist = _global_op(DIST[op], mesh, len(lshape), offset, impl)
    local = LOCAL[op]
    cot = _rand(2, *jax.eval_shape(local, left, right).shape)

    def dist_loss(l, r):
        return jnp.sum(dist(l, r) * cot)

    def local_loss(l, r):
        return jnp.sum(local(l, r) * cot)

    # Forward parity first.
    np.testing.assert_allclose(np.asarray(dist(left, right)),
                               np.asarray(local(left, right)),
                               rtol=1e-5, atol=1e-5)

    gl_d, gr_d = jax.grad(dist_loss, argnums=(0, 1))(left, right)
    gl_l, gr_l = jax.grad(local_loss, argnums=(0, 1))(left, right)
    np.testing.assert_allclose(np.asarray(gl_d), np.asarray(gl_l),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gr_d), np.asarray(gr_l),
                               rtol=1e-5, atol=1e-5)


def test_left_transpose_grad_is_fixed(mesh):
    """Regression pin for the reference defect: for out = AᵀB the left
    cotangent is B·dOutᵀ = nt(B, dOut); the reference computed nt(dOut, B)
    (reference ops.py:69), i.e. the transpose. With a batched 4-D operand
    the wrong version does not even have the right shape semantics — here we
    assert the exact analytic value on a tiny case."""
    left = _rand(3, T, T)
    right = _rand(4, T, DIM)
    dist = _global_op(matmul_tn, mesh, 2, 2, 'allgather')
    cot = _rand(5, T, DIM)
    gl = jax.grad(lambda l: jnp.sum(dist(l, right) * cot))(left)
    # Correct: dL = R·dOutᵀ, i.e. dL[k, i] = Σ_j R[k, j]·cot[i, j].
    expected = np.asarray(right) @ np.asarray(cot).T
    np.testing.assert_allclose(np.asarray(gl), expected, rtol=1e-5,
                               atol=1e-5)


def test_4d_grads(mesh):
    """Multi-head-shaped (B, H, T/N, ·) operands through nt (the attention
    backward path, reference ops.py:29-37)."""
    left, right = _rand(6, 2, 3, T, DIM), _rand(7, 2, 3, T, DIM)
    dist = _global_op(matmul_nt, mesh, 4, 2, 'allgather')
    cot = _rand(8, 2, 3, T, T)
    gl_d, gr_d = jax.grad(
        lambda l, r: jnp.sum(dist(l, r) * cot), argnums=(0, 1))(left, right)
    gl_l, gr_l = jax.grad(
        lambda l, r: jnp.sum(LOCAL['nt'](l, r) * cot),
        argnums=(0, 1))(left, right)
    np.testing.assert_allclose(np.asarray(gl_d), np.asarray(gl_l),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gr_d), np.asarray(gr_l),
                               rtol=1e-5, atol=1e-5)
