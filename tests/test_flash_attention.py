# -*- coding: utf-8 -*-
"""
Tests for the fused flash-attention Pallas kernel.

Oracle pattern per SURVEY §4: the unfused jnp math
(``_reference_math``, identical semantics to
``local_attention_reference``) on the same arrays. On the CPU test mesh the
kernel runs in Pallas interpreter mode — the same code path that compiles
on TPU. Covers what the reference never tests (SURVEY §4): non-trivial
masks, fully-masked rows, batch > 1, and sizes that don't divide the block
shape.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn,
)
from distributed_dot_product_tpu.ops.pallas_attention import (
    _reference_math, flash_attention,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

B, H, D = 2, 3, 16


pytestmark = pytest.mark.slow  # Pallas-interpreter / lax.scan-heavy cases


def _qkv(t, key=0, d_v=D):
    k1, k2, k3 = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(k1, (B, H, t, D), jnp.float32)
    k = jax.random.normal(k2, (B, H, t, D), jnp.float32)
    v = jax.random.normal(k3, (B, H, t, d_v), jnp.float32)
    return q, k, v


def _mask(t, p=0.3):
    m = jax.random.bernoulli(jax.random.key(7), p, (B, H, t, t))
    return m.at[..., 0].set(False)  # keep every row attendable


@pytest.mark.parametrize('t', [64, 100])   # 100: blocks don't divide T
@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('masked', [False, True])
def test_matches_unfused_math(t, causal, masked):
    q, k, v = _qkv(t)
    m = _mask(t) if masked else None
    out = flash_attention(q, k, v, m, causal=causal)
    ref = _reference_math(q, k, v, m, 1.0 / np.sqrt(D), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_rectangular_and_dv():
    """Tq != Tk and d_v != d (the general shape contract)."""
    q, _, _ = _qkv(48)
    _, k, v = _qkv(80, key=1, d_v=24)
    out = flash_attention(q, k, v)
    ref = _reference_math(q, k, v, None, 1.0 / np.sqrt(D), False)
    assert out.shape == (B, H, 48, 24)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_fully_masked_rows_zero_not_nan():
    q, k, v = _qkv(32)
    m = _mask(32).at[:, :, 5, :].set(True)   # row 5 fully masked
    out = flash_attention(q, k, v, m)
    assert np.isfinite(np.asarray(out)).all()
    assert (np.asarray(out)[:, :, 5] == 0).all()
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, m) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize('t', [64, 100])   # 100: blocks don't divide T
@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('masked', [False, True])
def test_gradients_match_unfused(t, causal, masked):
    q, k, v = _qkv(t)
    m = _mask(t) if masked else None

    def f_fused(q, k, v):
        return jnp.sum(flash_attention(q, k, v, m, causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_reference_math(q, k, v, m, 1.0 / np.sqrt(D),
                                       causal) ** 2)

    g1 = jax.grad(f_fused, (0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_gradients_rectangular_and_dv():
    """Backward with Tq != Tk and d_v != d (exercises both bwd kernels on
    non-square grids)."""
    q, _, _ = _qkv(48)
    _, k, v = _qkv(80, key=1, d_v=24)

    def f_fused(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_reference_math(q, k, v, None, 1.0 / np.sqrt(D),
                                       False) ** 2)

    g1 = jax.grad(f_fused, (0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_gradient_dtype_matches_primal():
    """custom_vjp contract: cotangent dtypes equal primal dtypes (bf16)."""
    q, k, v = _qkv(32)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v).astype(jnp.float32) ** 2), (0, 1, 2))(
            q, k, v)
    assert all(x.dtype == jnp.bfloat16 for x in g)


@pytest.mark.parametrize('causal', [False, True])
def test_bounded_softmax_mode_matches_exact(causal):
    """softmax_mode='bounded' (norm-bound shift, no running max) must agree
    with 'exact' to fp32 softmax tolerance, forward and gradients, including
    masks and fully-masked rows."""
    t = 100
    q, k, v = _qkv(t)
    m = _mask(t).at[:, :, 5, :].set(True)   # row 5 fully masked

    out_b = flash_attention(q, k, v, m, causal=causal,
                            softmax_mode='bounded')
    out_e = flash_attention(q, k, v, m, causal=causal)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_e),
                               atol=1e-5, rtol=1e-5)
    gb = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, m, causal=causal, softmax_mode='bounded') ** 2),
        (0, 1, 2))(q, k, v)
    ge = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, m, causal=causal) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(gb, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_bounded_mode_safe_on_adversarial_norms():
    """Huge-norm near-orthogonal q/k make the Cauchy-Schwarz bound exceed
    fp32's exponent range; 'bounded' must auto-fall back to the exact
    kernel instead of silently underflowing every weight to zero."""
    t, d = 32, 64
    q = jnp.zeros((1, t, d)).at[:, :, 0].set(35.0)
    k = jnp.zeros((1, t, d)).at[:, :, 1].set(35.0)   # all scores exactly 0
    v = jax.random.normal(jax.random.key(0), (1, t, d), jnp.float32)
    out_b = flash_attention(q, k, v, softmax_mode='bounded')
    out_e = flash_attention(q, k, v)
    assert not np.allclose(np.asarray(out_b), 0.0)   # the failure mode
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_e),
                               atol=1e-6, rtol=1e-6)
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, softmax_mode='bounded') ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize('mode', ['exact', 'bounded'])
def test_row_masked_only_by_causal_union_is_zero(mode):
    """A row whose attendable keys are emptied only by the UNION of the
    user mask and causality (neither alone) must behave like a
    fully-masked row — 0 output, zero/finite grads — identically in both
    softmax modes and in the oracle."""
    t, row = 16, 5
    q, k, v = _qkv(t)
    m = jnp.zeros((B, H, t, t), dtype=bool)
    m = m.at[:, :, row, :row + 1].set(True)   # user mask kills j<=row only
    out = flash_attention(q, k, v, m, causal=True, softmax_mode=mode)
    ref = _reference_math(q, k, v, m, 1.0 / np.sqrt(D), True)
    assert (np.asarray(out)[:, :, row] == 0).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    g = jax.grad(lambda v: jnp.sum(flash_attention(
        q, k, v, m, causal=True, softmax_mode=mode) ** 2))(v)
    gr = jax.grad(lambda v: jnp.sum(_reference_math(
        q, k, v, m, 1.0 / np.sqrt(D), True) ** 2))(v)
    assert np.isfinite(np.asarray(g)).all()
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               atol=1e-5, rtol=1e-5)


def test_bad_softmax_mode_rejected():
    q, k, v = _qkv(32)
    with pytest.raises(ValueError, match='softmax_mode'):
        flash_attention(q, k, v, softmax_mode='fast')


@pytest.mark.tpu
def test_tpu_hardware_compile_path():
    """Mosaic (real-TPU) compile coverage the interpreter can't give:
    off-block-size T and bf16, forward + gradient, both softmax modes.
    Skipped off-TPU; on TPU f32 matmuls default to bf16 compute, hence the
    loose tolerance vs the fp32 oracle."""
    import jax
    if jax.default_backend() != 'tpu':
        pytest.skip('requires a real TPU backend')
    t = 777   # pads to non-trivial block multiple
    q, k, v = _qkv(t)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    m = _mask(t)
    ref = _reference_math(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), m, 1.0 / np.sqrt(D), False)
    for mode in ('exact', 'bounded'):
        out = flash_attention(q, k, v, m, softmax_mode=mode,
                              interpret=False)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), atol=2e-2, rtol=2e-2)
        g = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, m, softmax_mode=mode,
            interpret=False).astype(jnp.float32) ** 2))(q)
        assert np.isfinite(np.asarray(g, np.float32)).all()


def test_mask_with_extra_leading_dims_rejected():
    """A mask may broadcast over q/k/v leading dims but not ADD dims —
    output batch shape comes solely from q/k/v."""
    q, k, v = (x[0, 0] for x in _qkv(32))   # (T, d)
    m = jnp.zeros((B, 32, 32), dtype=bool)
    with pytest.raises(ValueError, match='may not add batch dims'):
        flash_attention(q, k, v, m)


def test_module_flash_impl_matches_local_oracle(devices):
    """DistributedDotProductAttn(softmax_impl='flash') inside shard_map ==
    the distributed=False local oracle (the reference test_gradient.py
    pattern), through projections, multi-head split and mask broadcast."""
    mesh = seq_mesh(4)
    t, dim, heads = 32, 16, 4
    kw = dict(key_dim=dim, num_heads=heads, offset=2)
    dist = DistributedDotProductAttn(softmax_impl='flash', **kw)
    local = DistributedDotProductAttn(distributed=False, **kw)

    x = jax.random.normal(jax.random.key(0), (B, t, dim))
    m = jax.random.bernoulli(jax.random.key(1), 0.3, (B, t, t))
    m = m.at[..., 0].set(False)
    params = local.init(jax.random.key(2), x, x, x, m)

    expected = local.apply(params, x, x, x, m)

    spec = P(None, 'seq', None)
    got = jax.shard_map(
        lambda p, k, q, v, mm: dist.apply(p, k, q, v, mm),
        mesh=mesh, in_specs=(P(), spec, spec, spec, spec),
        out_specs=spec, check_vma=False,
    )(params, x, x, x, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)
