# -*- coding: utf-8 -*-
"""
Anomaly watchdog (obs/anomaly.py): detector semantics (EWMA z-score
warmup/re-baseline, static thresholds, rate-of-change cliffs), watch
reading (gauge / percentile / counter-rate / fn, absent series
skipped), breach events + cooldowns, the profile/dump action chains,
and the scheduler integration that generalizes the old one-off ttft
trigger.
"""

import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import anomaly as anomaly_mod
from distributed_dot_product_tpu.obs import flight
from distributed_dot_product_tpu.obs.anomaly import (
    AnomalyWatchdog, EwmaZScore, RateOfChange, StaticThreshold, Watch,
    default_watches,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs


# -- detectors -----------------------------------------------------------

def test_static_threshold_above_below():
    above = StaticThreshold(above=10.0)
    assert above.update(9.9) is None
    verdict = above.update(10.1)
    assert verdict['kind'] == 'above' and verdict['threshold'] == 10.0
    below = StaticThreshold(below=1.0)
    assert below.update(1.0) is None
    assert below.update(0.0)['kind'] == 'below'
    with pytest.raises(ValueError):
        StaticThreshold()


def test_ewma_zscore_warms_up_then_flags_spikes():
    det = EwmaZScore(z=4.0, alpha=0.2, min_samples=16)
    # A wild warmup value must NOT breach: the baseline is cold.
    for v in [0.01, 0.5, 0.01] + [0.01] * 13:
        assert det.update(v) is None
    # Steady state: small jitter stays in spec...
    for _ in range(20):
        assert det.update(0.0101) is None
    # ...a spike breaches, with the full forensic fields.
    verdict = det.update(5.0)
    assert verdict is not None
    assert verdict['kind'] == 'zscore'
    assert abs(verdict['z']) > 4.0
    assert verdict['mean'] < 0.1
    assert verdict['threshold'] == 4.0


def test_ewma_zscore_rebaselines_on_sustained_shift():
    """A sustained level shift re-baselines (alerting forever on the
    new normal would be noise, not detection)."""
    det = EwmaZScore(z=4.0, alpha=0.3, min_samples=8)
    for _ in range(20):
        det.update(1.0)
    assert det.update(100.0) is not None      # the shift itself flags
    for _ in range(30):
        det.update(100.0)
    assert det.update(100.5) is None          # the new normal is quiet
    det.reset()
    assert det._n == 0


def test_ewma_constant_stream_does_not_flag_jitter():
    det = EwmaZScore(z=4.0, min_samples=8, min_sigma=1e-3)
    for _ in range(20):
        det.update(1.0)
    # Variance is ~0; the sigma floor keeps harmless jitter in spec.
    assert det.update(1.001) is None


def test_rate_of_change_delta_and_ratio():
    det = RateOfChange(max_delta=5.0)
    assert det.update(10.0) is None           # first sample: no prev
    assert det.update(12.0) is None
    verdict = det.update(30.0)
    assert verdict['kind'] == 'delta' and verdict['previous'] == 12.0
    rel = RateOfChange(max_ratio=0.5)
    rel.update(100.0)
    assert rel.update(120.0) is None
    assert rel.update(10.0)['kind'] == 'ratio'
    with pytest.raises(ValueError):
        RateOfChange()


# -- watch reading -------------------------------------------------------

def test_watch_reads_signals_and_skips_absent_series():
    reg = MetricsRegistry()
    w_gauge = Watch(name='g', metric='serve.queue_depth',
                    detector=StaticThreshold(above=5), signal='gauge')
    # Absent series: skipped, never created (peek, not get-or-create).
    assert w_gauge.read(reg, now=0.0) is None
    assert reg.snapshot()['gauges'] == {}
    reg.gauge('serve.queue_depth').set(7)
    assert w_gauge.read(reg, now=1.0) == 7.0

    w_p99 = Watch(name='p', metric='serve.ttft_seconds',
                  detector=StaticThreshold(above=5), signal='p99')
    assert w_p99.read(reg, now=0.0) is None
    h = reg.histogram('serve.ttft_seconds')
    assert w_p99.read(reg, now=0.0) is None    # empty → NaN → skipped
    h.observe(0.25)
    assert w_p99.read(reg, now=1.0) == 0.25

    w_rate = Watch(name='r', metric='serve.tokens_generated',
                   detector=StaticThreshold(above=1e9),
                   signal='counter', rate=True)
    reg.counter('serve.tokens_generated').inc(10)
    assert w_rate.read(reg, now=10.0) is None  # first sample anchors
    reg.counter('serve.tokens_generated').inc(10)
    assert w_rate.read(reg, now=12.0) == pytest.approx(5.0)

    w_fn = Watch(name='f', metric='x', signal='fn',
                 fn=lambda r: 42.0,
                 detector=StaticThreshold(above=41))
    assert w_fn.read(reg, now=0.0) == 42.0


# -- the watchdog --------------------------------------------------------

class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def start(self, seconds=None, *, trigger='manual', event_log=None,
              **extra):
        self.calls.append(trigger)
        return {'path': '/nowhere', 'seconds': seconds,
                'trigger': trigger}


def test_breach_emits_event_chains_profiler_and_dump(tmp_path):
    reg = MetricsRegistry()
    reg.gauge('serve.queue_depth').set(100)
    prof = _FakeProfiler()
    log = obs.EventLog(tmp_path / 'ev.jsonl')
    dog = AnomalyWatchdog(
        reg,
        [Watch(name='depth', metric='serve.queue_depth',
               detector=StaticThreshold(above=10), signal='gauge',
               actions=('profile', 'dump'))],
        profiler=prof, event_log=log, min_interval=0.0)
    with flight.recording(base_dir=tmp_path / 'flight',
                          registry=reg) as rec:
        fired = dog.tick(force=True)
    log.close()
    assert len(fired) == 1
    # The breach event validates against the closed vocabulary.
    records, errors = obs.validate_file(tmp_path / 'ev.jsonl')
    assert errors == []
    breach = [r for r in records if r['event'] == 'anomaly.detected']
    assert len(breach) == 1
    assert breach[0]['metric'] == 'serve.queue_depth'
    assert breach[0]['detector'] == 'StaticThreshold'
    assert breach[0]['value'] == 100.0
    assert breach[0]['watch'] == 'depth'
    # Both actions chained: a capture and a flight bundle.
    assert prof.calls == ['anomaly.depth']
    assert len(rec.dumps) == 1
    assert rec.dumps[0]['trigger'] == 'anomaly'
    assert 'depth' in rec.dumps[0]['reason']
    # Counters moved.
    counters = reg.snapshot()['counters']
    assert counters['anomaly.breaches'] == 1
    assert counters['anomaly.breaches.depth'] == 1


def test_unchanged_reading_not_refed_no_variance_collapse():
    """A constant histogram p99 re-read every tick must NOT collapse
    an EWMA detector's variance: between real observations the tick
    cadence outruns the stream, and re-feeding the same value would
    make the next tiny jitter an astronomical z — a false breach on a
    healthy service (regression: the detector only sees DISTINCT
    readings)."""
    reg = MetricsRegistry()
    h = reg.histogram('serve.ttft_seconds')
    det = EwmaZScore(z=4.0, min_samples=4)
    dog = AnomalyWatchdog(
        reg,
        [Watch(name='ttft', metric='serve.ttft_seconds',
               detector=det, signal='p99')],
        min_interval=0.0)
    # A handful of real, slightly-varying observations...
    for v in (0.010, 0.011, 0.0105, 0.0102, 0.0108, 0.0101):
        h.observe(v)
        dog.tick(force=True)
    # ...then 200 idle ticks over the unchanged reservoir: the
    # detector must be fed nothing (its sample count freezes).
    n_before = det._n
    for _ in range(200):
        assert dog.tick(force=True) == []
    assert det._n == n_before
    # A fresh observation with ordinary jitter stays in spec.
    h.observe(0.0115)
    assert dog.tick(force=True) == []
    assert dog.breaches == []


def test_breach_cooldown_suppresses_re_alerts():
    reg = MetricsRegistry()
    reg.gauge('serve.queue_depth').set(100)
    dog = AnomalyWatchdog(
        reg,
        [Watch(name='depth', metric='serve.queue_depth',
               detector=StaticThreshold(above=10), signal='gauge',
               cooldown=3600.0)],
        min_interval=0.0)
    assert len(dog.tick(force=True)) == 1
    assert dog.tick(force=True) == []          # inside the cooldown
    assert len(dog.breaches) == 1


def test_tick_throttles_on_real_time():
    reg = MetricsRegistry()
    dog = AnomalyWatchdog(reg, [], min_interval=3600.0)
    dog.tick()
    reg.gauge('serve.queue_depth').set(100)
    dog.watches.append(
        Watch(name='depth', metric='serve.queue_depth',
              detector=StaticThreshold(above=10), signal='gauge'))
    assert dog.tick() == []                    # throttled
    assert len(dog.tick(force=True)) == 1


def test_broken_detector_is_contained():
    reg = MetricsRegistry()
    reg.gauge('g').set(1)
    dog = AnomalyWatchdog(
        reg,
        [Watch(name='bad', metric='g', signal='fn',
               fn=lambda r: (_ for _ in ()).throw(RuntimeError('x')),
               detector=StaticThreshold(above=0)),
         Watch(name='good', metric='g', signal='gauge',
               detector=StaticThreshold(above=0))],
        min_interval=0.0)
    fired = dog.tick(force=True)      # the bad watch must not stop
    assert [w.name for w, _ in fired] == ['good']
    assert reg.snapshot()['counters'][
        'exceptions_swallowed.anomaly.read'] == 1


def test_default_watches_catalog():
    watches = default_watches(queue_limit=8, paged=True)
    names = {w.name for w in watches}
    assert names == {'ttft_p99', 'dispatch_overhead_p99',
                     'tokens_per_s', 'queue_depth', 'reject_rate',
                     'pages_free', 'kv_corrupt'}
    by_name = {w.name: w for w in watches}
    assert by_name['ttft_p99'].actions == ('profile', 'dump')
    # Dispatch-floor watch: a host-loop stall chains a post-mortem
    # dump (no profile — the overhead spike IS host-side already).
    assert by_name['dispatch_overhead_p99'].metric == \
        'serve.dispatch_overhead_seconds'
    assert by_name['dispatch_overhead_p99'].actions == ('dump',)
    assert isinstance(by_name['dispatch_overhead_p99'].detector,
                      EwmaZScore)
    assert isinstance(by_name['queue_depth'].detector, StaticThreshold)
    assert by_name['queue_depth'].detector.above == pytest.approx(7.2)
    assert isinstance(by_name['pages_free'].detector, StaticThreshold)
    assert by_name['pages_free'].detector.below == 1
    # Slab catalog: no pages watch; no queue_limit → EWMA depth.
    slab = {w.name: w for w in default_watches()}
    assert 'pages_free' not in slab
    assert isinstance(slab['queue_depth'].detector, EwmaZScore)


def test_reject_total_sums_typed_counters():
    reg = MetricsRegistry()
    reg.counter('serve.rejected.queue_full').inc(3)
    reg.counter('serve.rejected.deadline_exceeded').inc(2)
    assert anomaly_mod._reject_total(reg) == 5.0


# -- scheduler integration ----------------------------------------------

def test_scheduler_anomaly_tick_fires_and_logs(tmp_path):
    """A scheduler armed with a custom watchdog breaches
    deterministically (static threshold on queue depth under an
    overflowing burst), the breach lands in the run's event log, and
    the chained flight dump is written — the PR-6 one-off ttft
    trigger, generalized."""
    import numpy as np

    from distributed_dot_product_tpu.serve import (
        KernelEngine, RejectedError, Scheduler, ServeConfig,
    )
    reg = MetricsRegistry()
    log = obs.EventLog(tmp_path / 'ev.jsonl')
    dog = AnomalyWatchdog(
        reg,
        [Watch(name='queue_depth', metric='serve.queue_depth',
               detector=StaticThreshold(above=2.5), signal='gauge',
               actions=('dump',))],
        event_log=log, min_interval=0.0)
    eng = KernelEngine(slots=2, t_max=32, vocab=16, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       decode_impl='xla')
    with flight.recording(base_dir=tmp_path / 'flight',
                          registry=reg) as rec:
        sched = Scheduler(
            eng, ServeConfig(queue_limit=6, max_new_tokens=3,
                             watchdog=False,
                             evict_before_reject=False),
            fault_injector=False, registry=reg,
            event_log=log, anomaly=dog)
        rng = np.random.default_rng(3)
        for i in range(10):
            try:
                sched.submit(rng.integers(0, 16, size=3).astype(
                    np.int32), request_id=f'r{i}')
            except RejectedError:
                pass
        sched.run_until_idle()
        sched.close()
    log.close()
    assert len(dog.breaches) >= 1
    records, errors = obs.validate_file(tmp_path / 'ev.jsonl')
    assert errors == []
    assert any(r['event'] == 'anomaly.detected'
               and r['watch'] == 'queue_depth' for r in records)
    assert any(d['trigger'] == 'anomaly' for d in rec.dumps)


def test_serveconfig_anomaly_true_builds_stock_catalog():
    from distributed_dot_product_tpu.serve import (
        KernelEngine, Scheduler, ServeConfig,
    )
    eng = KernelEngine(slots=2, t_max=16, vocab=16, heads=2,
                       head_dim=4, seed=0, decode_impl='xla')
    sched = Scheduler(eng, ServeConfig(watchdog=False, anomaly=True),
                      registry=MetricsRegistry())
    try:
        assert sched._anomaly is not None
        assert {w.name for w in sched._anomaly.watches} >= {
            'ttft_p99', 'tokens_per_s', 'queue_depth', 'reject_rate'}
    finally:
        sched.close()
