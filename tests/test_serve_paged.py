# -*- coding: utf-8 -*-
"""
Paged serving layer — the ISSUE 7 acceptance scenarios on the CPU
backend:

- **4× concurrency on the same memory budget**: a paged engine whose
  pool holds exactly the bytes of the slab engine's cache admits ≥4×
  the slab's concurrent sequence count (actual fill vs worst-case
  reservation — the whole point of paging).
- **Bit-identical streams vs the slab path under the fault cocktail**:
  same seeded traffic, same faults, layouts differ — every completed
  stream matches the slab run's token for token.
- **Prefix sharing counted once**: two sequences riding one registered
  prefix occupy its full pages exactly once (refcount gauge = the
  acceptance check), and copy-on-write keeps divergent appends private.
- **Page exhaustion is typed**: statically impossible requests reject
  CACHE_EXHAUSTED at submit; mid-stream exhaustion walks the
  evict→preempt ladder and terminates with the typed reason, with the
  whole arc reconstructable from the event log alone.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs.timeline import reconstruct
from distributed_dot_product_tpu.obs.exporter import render_prometheus
from distributed_dot_product_tpu.serve import (
    KernelEngine, RejectedError, RejectReason, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

VOCAB = 16
T_MAX = 64
PS = 4
SLAB_SLOTS = 4
# Equal KV bytes: slab = SLAB_SLOTS × T_MAX rows; paged pool = the same
# row count as pages — concurrency comes from raising `slots` 4×.
BUDGET_ROWS = SLAB_SLOTS * T_MAX
PAGED_SLOTS = 4 * SLAB_SLOTS
PAGES = BUDGET_ROWS // PS

TERMINAL = {'completed', 'deadline_expired', 'evicted', 'abandoned',
            'failed_nan', 'rejected'}


def _engine(mode, slots, **kw):
    paged = dict(cache_mode='paged', page_size=PS, pages=PAGES) \
        if mode == 'paged' else {}
    return KernelEngine(slots=slots, t_max=T_MAX, vocab=VOCAB, heads=2,
                        head_dim=4, prefill_chunk=4, seed=5,
                        decode_impl=kw.pop('decode_impl', 'xla'),
                        **paged, **kw)


def _burst(n, seed):
    rng = np.random.default_rng(seed)
    return [(f'r{i:03d}',
             rng.integers(0, VOCAB,
                          size=int(rng.integers(1, 7))).astype(np.int32))
            for i in range(n)]


def _run(mode, slots, n_requests, injector=None, *, seed=11,
         queue_limit=48, max_new=3, decode_impl='xla', on_tick=None):
    sched = Scheduler(
        _engine(mode, slots, decode_impl=decode_impl),
        ServeConfig(queue_limit=queue_limit, max_new_tokens=max_new,
                    watchdog=False, evict_before_reject=False),
        fault_injector=injector if injector is not None else False,
        registry=MetricsRegistry(), on_tick=on_tick)
    rejected = {}
    for i, (rid, prompt) in enumerate(_burst(n_requests, seed)):
        try:
            sched.submit(prompt, request_id=rid)
        except RejectedError as e:
            rejected[rid] = e.reason
    results = sched.run_until_idle()
    sched.close()
    return sched, rejected, results


# -- acceptance: 4x concurrency on the same memory budget ---------------

def test_soak_4x_concurrency_same_memory_budget():
    """The paged pool holds EXACTLY the slab's bytes (BUDGET_ROWS of
    KV) yet serves 4× the concurrent sequences: short requests reserve
    pages for their actual fill, not a worst-case t_max strip."""
    peak = {'busy': 0}

    def on_tick(s):
        peak['busy'] = max(peak['busy'],
                           sum(sl.request is not None
                               for sl in s._slots))

    n = 3 * PAGED_SLOTS
    sched, rejected, results = _run('paged', PAGED_SLOTS, n,
                                    on_tick=on_tick)
    assert peak['busy'] >= 4 * SLAB_SLOTS, peak
    assert not rejected
    assert len(results) == n
    assert all(r.status == 'completed' for r in results.values())
    # The budget really is the slab's: a slab of PAGED_SLOTS slots
    # would need 4× these bytes.
    eng = sched.engine
    assert eng.pool.pages * eng.page_size == BUDGET_ROWS


@pytest.mark.parametrize('decode_impl', ['xla', 'kernel'])
def test_soak_bit_identical_to_slab_under_fault_cocktail(decode_impl):
    """Same seeded traffic + stuck/NaN faults through a slab scheduler
    and a paged one (4× slots, same bytes): every request completed by
    BOTH runs produced bit-identical tokens — the paged layout changes
    memory, never streams. Quarantine/preempt/evict churn included."""
    n = 20
    plan = dict(stuck_at_step=3, stuck_seconds=0.02, nan_at_step=5,
                nan_slot=1)
    _, rej_s, res_s = _run('slab', SLAB_SLOTS, n,
                           ServeFaultInjector(ServeFaultPlan(**plan)),
                           decode_impl=decode_impl)
    sched_p, rej_p, res_p = _run(
        'paged', PAGED_SLOTS, n,
        ServeFaultInjector(ServeFaultPlan(**plan)),
        decode_impl=decode_impl)
    counters = sched_p.registry.snapshot()['counters']
    assert counters['serve.nan_quarantined'] >= 1
    compared = 0
    for rid, rp in res_p.items():
        rs = res_s.get(rid)
        if rs is None or rp.status != 'completed' \
                or rs.status != 'completed':
            continue
        short, long_ = sorted((rp.tokens, rs.tokens), key=len)
        assert long_[:len(short)] == short, f'{rid}: stream diverged'
        if len(short) == len(long_):
            compared += 1
    assert compared >= 5, 'soak too small to witness identity'
    # Zero dropped-without-reason on the paged side too.
    for rid, _ in _burst(n, 11):
        assert rid in res_p or rej_p.get(rid) is not None
        if rid in res_p:
            assert res_p[rid].status in TERMINAL


# -- acceptance: prefix sharing counted once ----------------------------

def test_prefix_pages_occupied_exactly_once():
    eng = _engine('paged', 4)
    sched = Scheduler(eng, ServeConfig(queue_limit=8, max_new_tokens=4,
                                       watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    prefix = np.arange(2 * PS, dtype=np.int32) % VOCAB  # page-aligned
    pid = eng.register_prefix(prefix)
    used_before = eng.pool.used_pages
    sched.submit([1, 2], prefix_id=pid, request_id='a')
    sched.submit([3, 4], prefix_id=pid, request_id='b')
    sched.step()                        # both admitted, prefix attached
    pages = eng._prefix_registry[pid][0]
    # THE acceptance check: both sequences attached, the prefix's pages
    # exist once in the pool (refcount 3 = registry + 2 riders; the
    # shared-pages gauge sees them, pool usage only grew by the two
    # private continuation pages).
    assert all(eng.pool.refcount[p] == 3 for p in pages)
    stats = eng.cache_stats()
    assert stats['shared_pages'] == len(pages) == 2
    assert stats['pages_used'] == used_before + 2
    g = sched.registry.snapshot()['gauges']
    assert g['serve.cache.shared_pages'] == 2
    results = sched.run_until_idle()
    assert {r.status for r in results.values()} == {'completed'}
    # Riders retired: the registry alone holds the prefix.
    assert all(eng.pool.refcount[p] == 1 for p in pages)
    # Both riders saw the SAME context: identical continuations decode
    # identical streams only if prompts matched; here prompts differ,
    # so just check both streams exist and the pool drained.
    eng.unregister_prefix(pid)
    assert eng.pool.used_pages == 0
    sched.close()


def test_prefix_streams_match_unshared_equivalent():
    """A prefix-shared request decodes EXACTLY like the same tokens
    submitted as one flat prompt on a fresh engine — sharing is a
    memory optimization, not a semantics change."""
    prefix = np.arange(2 * PS + 1, dtype=np.int32) % VOCAB  # partial!
    tail = np.array([5, 9], np.int32)
    eng1 = _engine('paged', 2)
    s1 = Scheduler(eng1, ServeConfig(queue_limit=4, max_new_tokens=4,
                                     watchdog=False),
                   registry=MetricsRegistry(), fault_injector=False)
    pid = eng1.register_prefix(prefix)
    s1.submit(tail, prefix_id=pid, request_id='shared')
    r1 = s1.run_until_idle()['shared']
    s1.close()
    eng2 = _engine('paged', 2)
    s2 = Scheduler(eng2, ServeConfig(queue_limit=4, max_new_tokens=4,
                                     watchdog=False),
                   registry=MetricsRegistry(), fault_injector=False)
    s2.submit(np.concatenate([prefix, tail]), request_id='flat')
    r2 = s2.run_until_idle()['flat']
    s2.close()
    assert r1.status == r2.status == 'completed'
    assert r1.tokens == r2.tokens


def test_fork_branches_share_pages_and_streams():
    eng = _engine('paged', 4)
    sched = Scheduler(eng, ServeConfig(queue_limit=8, max_new_tokens=6,
                                       watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    sched.submit([1, 2, 3, 4, 5, 6], request_id='a')
    sched.step()
    sched.step()                             # prefill + first decode
    used = eng.pool.used_pages
    sched.fork('a', request_id_new='a2')
    # Fork cost: at most ONE page (the partial tail copy).
    assert eng.pool.used_pages <= used + 1
    assert eng.cache_stats()['shared_pages'] >= 1
    results = sched.run_until_idle()
    assert results['a'].status == results['a2'].status == 'completed'
    assert results['a'].tokens == results['a2'].tokens
    sched.close()


# -- exhaustion ladder --------------------------------------------------

def test_statically_impossible_prompt_rejects_cache_exhausted():
    eng = KernelEngine(slots=2, t_max=T_MAX, vocab=VOCAB,
                       cache_mode='paged', page_size=PS, pages=4,
                       decode_impl='xla')
    sched = Scheduler(eng, ServeConfig(queue_limit=4, watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    with pytest.raises(RejectedError) as ei:
        sched.submit(np.arange(4 * PS + 1, dtype=np.int32) % VOCAB,
                     request_id='too-big')
    assert ei.value.reason is RejectReason.CACHE_EXHAUSTED
    counters = sched.registry.snapshot()['counters']
    assert counters['serve.rejected.cache_exhausted'] == 1
    sched.close()


def test_unknown_or_unregistered_prefix_is_typed():
    """prefix_id failures are typed, never raw KeyErrors: unknown at
    submit raises PREFIX_UNREGISTERED; a prefix unregistered while its
    rider sat queued finalizes the rider with the same reason instead
    of crashing the tick."""
    eng = _engine('paged', 2)
    sched = Scheduler(eng, ServeConfig(queue_limit=4, max_new_tokens=3,
                                       watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    with pytest.raises(RejectedError) as ei:
        sched.submit([1, 2], prefix_id=999, request_id='ghost')
    assert ei.value.reason is RejectReason.PREFIX_UNREGISTERED
    pid = eng.register_prefix(np.arange(PS, dtype=np.int32))
    sched.submit([1, 2], prefix_id=pid, request_id='rider')
    eng.unregister_prefix(pid)           # vanishes while queued
    results = sched.run_until_idle()
    r = results['rider']
    assert r.status == 'rejected'
    assert r.reason is RejectReason.PREFIX_UNREGISTERED
    sched.close()


def test_midstream_exhaustion_walks_preempt_ladder():
    """Two growing sequences over a pool only one can finish in:
    the deficit slot is preempted with the typed event, retries are
    bounded, and the loser terminates 'evicted' with CACHE_EXHAUSTED —
    never a hang, never a silent drop."""
    eng = KernelEngine(slots=2, t_max=16, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=2, pages=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng,
        ServeConfig(queue_limit=4, max_new_tokens=10, watchdog=False,
                    evict_before_reject=False, max_requeues=1),
        registry=MetricsRegistry(), fault_injector=False)
    sched.submit([1], request_id='a')
    sched.submit([2], request_id='b')
    results = sched.run_until_idle()
    counters = sched.registry.snapshot()['counters']
    assert counters['serve.cache_preempted'] >= 1
    statuses = sorted(r.status for r in results.values())
    assert 'completed' in statuses
    loser = [r for r in results.values() if r.status != 'completed']
    assert loser and loser[0].status == 'evicted'
    assert loser[0].reason is RejectReason.CACHE_EXHAUSTED
    assert eng.pool.used_pages == 0       # everything drained
    sched.close()


def test_exhaustion_evicts_longest_idle_first_when_allowed():
    eng = KernelEngine(slots=2, t_max=16, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=2, pages=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng,
        ServeConfig(queue_limit=4, max_new_tokens=10, watchdog=False,
                    evict_before_reject=True),
        registry=MetricsRegistry(), fault_injector=False)
    sched.submit([1], request_id='a')
    sched.submit([2], request_id='b')
    results = sched.run_until_idle()
    statuses = sorted(r.status for r in results.values())
    assert statuses == ['completed', 'evicted']
    evicted = [r for r in results.values() if r.status == 'evicted'][0]
    assert evicted.tokens, 'eviction keeps partial tokens'
    sched.close()


def test_preempt_arc_reconstructs_from_event_log(tmp_path):
    log = obs_events.EventLog(tmp_path / 'serve.jsonl')
    eng = KernelEngine(slots=2, t_max=16, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=2, pages=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng,
        ServeConfig(queue_limit=4, max_new_tokens=10, watchdog=False,
                    evict_before_reject=False, max_requeues=1),
        registry=MetricsRegistry(), fault_injector=False,
        event_log=log)
    sched.submit([1], request_id='a')
    sched.submit([2], request_id='b')
    sched.run_until_idle()
    sched.close()
    log.close()
    _records, errors = obs_events.validate_file(log.path)
    assert errors == []
    tls = reconstruct(log.path)
    assert set(tls) == {'a', 'b'}
    preempted = [t for t in tls.values() if t.preempts]
    assert preempted, 'no preempt recorded'
    for tl in tls.values():
        assert tl.complete, tl.errors


# -- observability surface ----------------------------------------------

def test_cache_gauges_render_through_metrics_exporter():
    sched, _, _ = _run('paged', PAGED_SLOTS, 8)
    text = render_prometheus(sched.registry)
    for gauge in ('ddp_serve_cache_pages_used',
                  'ddp_serve_cache_pages_free',
                  'ddp_serve_cache_shared_pages'):
        assert gauge in text, f'{gauge} missing from /metrics'
    assert 'ddp_serve_cache_request_pages' in text


# -- slab-surface parity at the capacity boundary -----------------------

def test_slot_at_t_max_steps_frozen_like_slab():
    """A paged slot reaching t_max keeps stepping under the slab
    engine's frozen-write contract (the device append drops while the
    length advances) — step() must NOT raise 'page pool exhausted':
    the pool has plenty of free pages and no allocation could ever
    cover a past-capacity position. Direct callers get the same
    surface on both layouts, token for token."""
    t_max = 16
    kw = dict(slots=1, t_max=t_max, vocab=VOCAB, heads=2, head_dim=4,
              prefill_chunk=4, seed=5, decode_impl='xla')
    slab = KernelEngine(**kw)
    paged = KernelEngine(cache_mode='paged', page_size=PS, pages=100,
                         **kw)
    prompt = [1, 2, 3]
    streams = []
    for eng in (slab, paged):
        eng.prefill(0, prompt)
        tok = np.array([prompt[-1]], np.int32)
        active = np.array([True])
        toks = []
        for _ in range(t_max + 8):          # well past capacity
            tok, finite = eng.step(tok, active)
            assert finite.all()
            toks.append(int(tok[0]))
        streams.append(toks)
    assert streams[0] == streams[1]
    assert paged.pool.free_pages > 0        # it never was exhaustion


def test_fork_budget_clamped_to_config_cap():
    """fork() applies the same budget clamp admission.validate gives
    every submitted request: an explicit max_new_tokens cannot exceed
    the config cap (or the cache/pool capacity), so a branch can't
    hold a slot and pool pages past what submit() would allow."""
    eng = _engine('paged', 4)
    sched = Scheduler(eng, ServeConfig(queue_limit=8, max_new_tokens=4,
                                       watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    sched.submit([1, 2, 3, 4], request_id='a')
    sched.step()
    sched.step()                             # prefill + first decode
    br = sched.fork('a', request_id_new='b', max_new_tokens=1000)
    assert br.max_new_tokens <= 4
    results = sched.run_until_idle()
    assert results['a'].status == results['b'].status == 'completed'
    assert len(results['b'].tokens) <= 4
    sched.close()


def test_cache_stats_on_slab_engine_reports_zeros():
    """Generic dashboard code may probe any engine the way the
    scheduler probes paged ones — a slab engine answers with zeros,
    not an AttributeError."""
    eng = _engine('slab', 2)
    assert eng.cache_stats() == {'pages': 0, 'pages_used': 0,
                                 'pages_free': 0, 'shared_pages': 0,
                                 'pages_quarantined': 0, 'page_size': 0}


def test_never_placeable_prefix_rider_rejects_instead_of_stalling():
    """A rider whose pool can NEVER supply its placement (registry-
    pinned prefix pages + CoW tail copy + fresh prompt pages exceed
    the whole pool) must be typed-rejected at its admission tick —
    admission.validate can't see the registry pin, and an eternal
    head-of-line 'wait' would stall every later request behind it."""
    eng = KernelEngine(slots=2, t_max=32, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=8, pages=4,
                       decode_impl='xla')
    pid = eng.register_prefix(np.arange(20, dtype=np.int32) % VOCAB)
    assert eng.pinned_pages == 3             # 20 rows pin 3 of 4 pages
    sched = Scheduler(eng, ServeConfig(queue_limit=8, max_new_tokens=2,
                                       watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    # Needs the 1-page tail copy + 1 fresh prompt page = 2, but only
    # 1 page can ever be free while the prefix stays registered.
    sched.submit(np.arange(8, dtype=np.int32) % VOCAB,
                 request_id='rider', prefix_id=pid)
    sched.submit([1, 2, 3], request_id='later')
    results = sched.run_until_idle()
    assert results['rider'].status == 'rejected'
    assert results['rider'].reason is RejectReason.CACHE_EXHAUSTED
    assert results['later'].status == 'completed'   # no stall behind it
    counters = sched.registry.snapshot()['counters']
    assert counters['serve.rejected.cache_exhausted'] == 1
    sched.close()


def test_pool_pressure_downgrades_readiness():
    """Pool fill joins queue depth in the readiness signal, not just
    the budget degrade: a load balancer must see DEGRADED on a chip
    whose pool is nearly full even while its queue sits empty."""
    from distributed_dot_product_tpu.serve import Readiness
    eng = KernelEngine(slots=2, t_max=64, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=8, pages=8,
                       decode_impl='xla')
    eng.register_prefix(np.arange(49, dtype=np.int32) % VOCAB)
    assert eng.pinned_pages == 7             # 7/8 pages > 0.75 default
    sched = Scheduler(eng, ServeConfig(queue_limit=8, watchdog=False),
                      registry=MetricsRegistry(), fault_injector=False)
    sched.step()                             # tick refreshes readiness
    assert sched.health.readiness is Readiness.DEGRADED
    sched.close()
