# -*- coding: utf-8 -*-
"""
Paged int8 K mirror — quantized decode on the page pool (ISSUE 14c).

The slab cache has carried an append-time int8 K mirror since the
s8-decode fix; this file pins the mirror ON THE POOL:

- **Mirror parity with the slab**: after identical appends, the
  gathered mirror pools are bit-identical to the slab cache's
  ``k_q``/``k_scale`` (same per-row rule, same append-once contract).
- **Kernel-vs-XLA parity**: the fused kernel's paged int8 step matches
  the gathered-slab XLA formulation to kernel rounding (exp2 vs exp),
  and matches the SLAB int8 kernel bit for bit — the page-table
  redirect changes addressing, never math.
- **Eligibility is explained**: ``decode_kernel_eligible`` accepts
  paged+int8 with the mirror and names the exact gap otherwise
  (the former silent ``impl='auto'`` XLA fallback).
- **Lifecycle ops keep the mirror exact**: rollback and reset zero
  mirror rows/pages alongside the bf16 pools; a non-int8 kernel step
  on a mirror-carrying pool still maintains it; cross-cache page
  transfer rebuilds mirror rows bit-identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.decode import (
    append_kv_slots, decode_kernel_eligible, decode_step,
    init_paged_cache, init_slot_cache, paged_append_kv_slots,
    paged_gather_mirror, paged_reset_slot, paged_rollback_slots,
    paged_transfer_pages,
)

B, H, D, PS, T = 2, 2, 8, 8, 32


def _paged(qk_quant='int8', pages=8):
    c = init_paged_cache(B, H, T, D, pages=pages, page_size=PS,
                         dtype=jnp.bfloat16, qk_quant=qk_quant)
    return c._replace(
        page_table=jnp.array([[0, 1, 4, -1], [2, 3, -1, -1]], jnp.int32))


def _slab_with_mirror():
    base = init_slot_cache(B, H, T, D, dtype=jnp.bfloat16)
    return base._replace(
        k_q=jnp.zeros((B, H, T, D), jnp.int8),
        k_scale=jnp.zeros((B, H, T, 1), jnp.float32))


def _rows(key, n):
    return jax.random.normal(jax.random.key(key), (B, H, n, D),
                             jnp.bfloat16)


def test_init_allocates_mirror_pools():
    c = _paged()
    assert c.k_q_pool.dtype == jnp.int8
    assert c.k_q_pool.shape == c.k_pool.shape
    assert c.k_scale_pool.shape == c.k_pool.shape[:-1] + (1,)
    assert init_paged_cache(B, H, T, D, pages=4,
                            page_size=PS).k_q_pool is None
    with pytest.raises(ValueError, match='qk_quant'):
        init_paged_cache(B, H, T, D, pages=4, page_size=PS,
                         qk_quant='int4')


def test_append_mirror_bit_identical_to_slab():
    slab, paged = _slab_with_mirror(), _paged()
    k, v = _rows(1, 5), _rows(2, 5)
    counts = jnp.array([5, 3], jnp.int32)
    slab = append_kv_slots(slab, k, v, counts=counts)
    paged = paged_append_kv_slots(paged, k, v, counts=counts)
    gq, gs = paged_gather_mirror(paged)
    np.testing.assert_array_equal(np.asarray(gq), np.asarray(slab.k_q))
    np.testing.assert_array_equal(np.asarray(gs),
                                  np.asarray(slab.k_scale))


def test_gather_mirror_requires_mirror():
    with pytest.raises(ValueError, match='mirror'):
        paged_gather_mirror(_paged(qk_quant=None))


# -- eligibility --------------------------------------------------------

def test_paged_int8_kernel_eligible_with_mirror():
    assert decode_kernel_eligible(_paged(), qk_quant='int8') is True


def test_eligibility_reasons_name_the_gap():
    ok, reason = decode_kernel_eligible(_paged(qk_quant=None),
                                        qk_quant='int8', explain=True)
    assert not ok and 'mirror' in reason and 'init_paged_cache' in reason
    ok, reason = decode_kernel_eligible(_paged(), n=2, qk_quant='int8',
                                        explain=True)
    assert not ok and 'verify-k' in reason
    ok, reason = decode_kernel_eligible(_paged(), segment_ids=object(),
                                        explain=True)
    assert not ok and 'segment' in reason
    ok, reason = decode_kernel_eligible(_paged(), explain=True)
    assert ok and reason is None


def test_forced_kernel_raises_with_reason():
    c = _paged(qk_quant=None)
    q = _rows(3, 1)
    with pytest.raises(ValueError, match='mirror'):
        decode_step(q, c, q, q, qk_quant='int8', impl='kernel',
                    interpret=True)


# -- decode parity ------------------------------------------------------

def _filled(which):
    k, v = _rows(1, 5), _rows(2, 5)
    counts = jnp.array([5, 3], jnp.int32)
    if which == 'slab':
        return append_kv_slots(_slab_with_mirror(), k, v, counts=counts)
    return paged_append_kv_slots(_paged(), k, v, counts=counts)


def test_paged_int8_kernel_matches_slab_kernel():
    """The page-table redirect changes ADDRESSING only: the paged int8
    kernel step scores the same quantized rows as the slab int8 kernel
    — outputs agree to K-split rounding (the slab splits at
    ``decode_block_k(t_max)``, the pool at the page size, so the
    online-softmax accumulation ORDER differs; the quantized scores
    themselves are integer-exact)."""
    q, kn, vn = _rows(3, 1), _rows(4, 1), _rows(5, 1)
    _, out_s = decode_step(q, _filled('slab'), kn, vn, qk_quant='int8',
                           impl='kernel', interpret=True)
    _, out_p = decode_step(q, _filled('paged'), kn, vn, qk_quant='int8',
                           impl='kernel', interpret=True)
    np.testing.assert_allclose(np.asarray(out_s, np.float32),
                               np.asarray(out_p, np.float32),
                               atol=1e-2, rtol=1e-2)


def test_paged_int8_kernel_vs_xla_parity():
    """Kernel vs the gathered-slab XLA formulation: same quantized
    scoring, exp2-vs-exp softmax rounding only — and the mirror the
    kernel maintains in place equals the one the XLA append writes."""
    q, kn, vn = _rows(3, 1), _rows(4, 1), _rows(5, 1)
    ck, out_k = decode_step(q, _filled('paged'), kn, vn,
                            qk_quant='int8', impl='kernel',
                            interpret=True)
    cx, out_x = decode_step(q, _filled('paged'), kn, vn,
                            qk_quant='int8', impl='xla')
    np.testing.assert_allclose(
        np.asarray(out_k, np.float32), np.asarray(out_x, np.float32),
        atol=2e-2, rtol=2e-2)
    for a, b in zip(paged_gather_mirror(ck), paged_gather_mirror(cx)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(ck.length),
                                  np.asarray(cx.length))


def test_chained_paged_int8_kernel_tracks_slab():
    """A chained quantized decode (the serving loop shape): every step
    of the paged kernel run matches the slab kernel run to K-split
    rounding, and the MIRRORS stay in bit-exact lockstep (append-time
    quantization is split-order independent)."""
    slab, paged = _filled('slab'), _filled('paged')
    for i in range(4):
        q, kn, vn = _rows(10 + i, 1), _rows(20 + i, 1), _rows(30 + i, 1)
        slab, out_s = decode_step(q, slab, kn, vn, qk_quant='int8',
                                  impl='kernel', interpret=True)
        paged, out_p = decode_step(q, paged, kn, vn, qk_quant='int8',
                                   impl='kernel', interpret=True)
        np.testing.assert_allclose(np.asarray(out_s, np.float32),
                                   np.asarray(out_p, np.float32),
                                   atol=1e-2, rtol=1e-2)
    gq, gs = paged_gather_mirror(paged)
    np.testing.assert_array_equal(np.asarray(gq), np.asarray(slab.k_q))
    np.testing.assert_array_equal(np.asarray(gs),
                                  np.asarray(slab.k_scale))


def test_non_int8_kernel_step_maintains_mirror():
    """A bf16 decode step on a mirror-carrying pool (kernel path) must
    leave the mirror exactly as the append ops would — the post-hoc
    fixup contract."""
    q, kn, vn = _rows(3, 1), _rows(4, 1), _rows(5, 1)
    ck, _ = decode_step(q, _filled('paged'), kn, vn, impl='kernel',
                        interpret=True)
    cx, _ = decode_step(q, _filled('paged'), kn, vn, impl='xla')
    for a, b in zip(paged_gather_mirror(ck), paged_gather_mirror(cx)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- lifecycle ----------------------------------------------------------

def test_rollback_zeroes_mirror_rows():
    paged = _filled('paged')
    rolled = paged_rollback_slots(paged, jnp.array([3, 1], jnp.int32),
                                  span=4)
    gq, gs = paged_gather_mirror(rolled)
    assert not np.asarray(gq)[0, :, 3:, :].any()
    assert not np.asarray(gs)[0, :, 3:, :].any()
    assert not np.asarray(gq)[1, :, 1:, :].any()
    # Kept prefix rows untouched.
    oq, os_ = paged_gather_mirror(paged)
    np.testing.assert_array_equal(np.asarray(gq)[0, :, :3],
                                  np.asarray(oq)[0, :, :3])


def test_reset_zeroes_freed_mirror_pages():
    paged = _filled('paged')
    freed = jnp.array([0, 1, 4, -1], jnp.int32)   # slot 0's pages
    cleared = paged_reset_slot(paged, 0, freed)
    assert not np.asarray(cleared.k_q_pool)[np.asarray(freed[:3])].any()
    assert not np.asarray(
        cleared.k_scale_pool)[np.asarray(freed[:3])].any()
    # Slot 1's pages keep their mirror bits.
    np.testing.assert_array_equal(np.asarray(cleared.k_q_pool)[2],
                                  np.asarray(paged.k_q_pool)[2])


def test_transfer_rebuilds_mirror_rows():
    """Adopting pages from an UNQUANTIZED source pool rebuilds the
    destination mirror from the copied K bits — bit-identical to the
    append-time rule on every filled row."""
    src = init_paged_cache(B, H, T, D, pages=8, page_size=PS,
                           dtype=jnp.bfloat16)
    src = src._replace(
        page_table=jnp.array([[0, 1, -1, -1], [2, -1, -1, -1]],
                             jnp.int32))
    k, v = _rows(1, PS), _rows(2, PS)
    src = paged_append_kv_slots(src, k, v)
    dst = _paged()
    dst = paged_transfer_pages(dst, src.k_pool, src.v_pool,
                               jnp.array([0, 2], jnp.int32),
                               jnp.array([5, 6], jnp.int32))
    # The reference mirror: append the same rows into a quantized pool.
    ref = _paged()
    ref = paged_append_kv_slots(ref, k, v)
    np.testing.assert_array_equal(
        np.asarray(dst.k_q_pool)[5], np.asarray(ref.k_q_pool)[0])
    np.testing.assert_array_equal(
        np.asarray(dst.k_q_pool)[6], np.asarray(ref.k_q_pool)[2])
    np.testing.assert_array_equal(
        np.asarray(dst.k_scale_pool)[5],
        np.asarray(ref.k_scale_pool)[0])
