# -*- coding: utf-8 -*-
"""
graphlint (distributed_dot_product_tpu/analysis/) — the static-analysis
subsystem's own gate and rule tests.

Three layers:

- **Clean-tree gate** (tier-1): the full analyzer over the repo and the
  central registry reports ZERO violations — the mechanism that turns
  every rule into a standing CI contract.
- **Negative fixtures, one per rule**: deliberately violating code
  (tests/graphlint_fixtures/) must produce exactly the expected rule id
  with a usable file:line — so a rule can't bit-rot into always-pass.
  The fp32-accumulation, aliasing/donation and retrace-budget rules
  each catch a seeded regression here (the acceptance contract).
- **Retrace sentinel budgets**: decode_seq_parallel's LRU-cached step
  traces ONCE across a token loop (the round-5 advisor finding, now
  pinned mechanically); the rebuild-storm variant is visible in the
  name-total; the engine's fixed programs trace once; exceeding a
  budget raises.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.analysis import retrace, run_analysis
from distributed_dot_product_tpu.analysis.astlint import lint_file
from distributed_dot_product_tpu.analysis.jaxpr_rules import lint_spec
from distributed_dot_product_tpu.analysis.registry import (
    default_entrypoints,
)

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, 'tests', 'graphlint_fixtures')


def _negatives_module():
    """tests/ is not a package: `tests.graphlint_fixtures` resolves as
    a PEP-420 namespace package when the repo root is on sys.path
    (python -m pytest from the root) — fall back to inserting it."""
    try:
        from tests.graphlint_fixtures import jaxpr_negatives
    except ImportError:
        sys.path.insert(0, REPO)
        from tests.graphlint_fixtures import jaxpr_negatives
    return jaxpr_negatives


# -- clean-tree gate ----------------------------------------------------

def test_clean_tree_gate(devices):
    """THE gate: zero ACTIVE violations across the package AST scan
    (astlint + the servelint families + flowlint's typed-failure-flow
    rules) and every registered entrypoint's jaxpr — AND zero WAIVED
    records of any kind. The owned dense (models/dense.py) retired the
    flax ``linen.Dense`` bf16-accumulation debt the bf16 serving-dtype
    twins used to waive (14 allowed records across three entries), and
    flowlint reports pragma-waived sites as visible ``allowed``
    records, so this assertion also pins the tree at ZERO
    ``# flowlint: allow[...]`` waivers; new waived debt of either kind
    fails here and must be argued in review, not slipped in as an
    "allowed" record."""
    from distributed_dot_product_tpu.analysis import active_violations
    violations = run_analysis()
    active = active_violations(violations)
    assert active == [], '\n'.join(v.render() for v in active)
    waived = [v for v in violations if v.allowed]
    assert waived == [], (
        'the zero-waiver contract broke — the owned-dense refactor '
        'retired every f32-accum waiver, and new waived debt needs a '
        'reviewed decision, not an allow= entry:\n'
        + '\n'.join(v.render() for v in waived))


def test_registry_covers_every_layer(devices):
    """The registry spans the whole stack — a layer hook silently
    returning {} would shrink the gate's coverage without failing it."""
    names = set(default_entrypoints())
    expected = {
        'ops.matmul_grad_allgather', 'ops.matmul_grad_ring',
        'ops.flash_fwd_bf16', 'ops.flash_bwd_bf16', 'ops.flash_fwd_int8',
        'attention.fwd_flash', 'attention.bwd_full', 'attention.fwd_ring',
        'attention.fwd_ulysses', 'decode.seq_parallel_step',
        'decode.step_xla_slots', 'decode.step_kernel_int8',
        'decode.step_sharded', 'decode.step_paged_xla',
        'decode.step_paged_kernel', 'decode.step_verify_slab',
        'decode.step_verify_paged', 'lm.head_bf16', 'lm.loss_f32',
        'serve.engine_decode', 'serve.engine_decode_paged',
        'train.lm_step', 'obs.spanned_decode',
        # serving-dtype twins (PR 13): module-level surfaces traced at
        # bf16 so the cache/donation contracts gate the deployed dtype.
        'attention.fwd_flash_bf16', 'decode.seq_parallel_step_bf16',
        'lm.loss_bf16',
        # low-precision end-to-end (PR 14): the int8-WEIGHT serving
        # programs and the quantized decode step on the page pool.
        'attention.fwd_flash_wq8', 'serve.engine_decode_wq8',
        'decode.step_paged_kernel_int8',
    }
    assert expected <= names, f'missing: {expected - names}'


# -- AST rules: negative fixtures ---------------------------------------

def _expected_lines(path):
    """Lines carrying a '# VIOLATION' marker — the fixture annotates its
    own seeded regressions, so the assertion can't drift from the
    file."""
    with open(path, encoding='utf-8') as f:
        return [i for i, line in enumerate(f, 1) if '# VIOLATION' in line]


@pytest.mark.parametrize('fixture, rule', [
    (os.path.join('ops', 'fx_host_pull.py'), 'host-pull'),
    (os.path.join('ops', 'fx_traced_bool.py'), 'traced-bool-branch'),
    ('fx_clock_in_jit.py', 'clock-in-jit'),
    ('fx_span_in_jit.py', 'clock-in-jit'),
    ('fx_silent_except.py', 'silent-except'),
])
def test_ast_rule_catches_fixture(fixture, rule):
    path = os.path.join(FIXTURES, fixture)
    violations = lint_file(path, repo_root=REPO)
    got = {(v.rule, v.line) for v in violations}
    want = {(rule, line) for line in _expected_lines(path)}
    assert want == got, (f'{fixture}: expected exactly {sorted(want)}, '
                         f'got {sorted(got)}')
    # file:line anchoring — every report names the fixture file.
    assert all(v.file and v.file.endswith(fixture) for v in violations)


# -- jaxpr rules: negative fixtures -------------------------------------

_NEGATIVE_NAMES = ('neg.f32_accum', 'neg.cache_rematerialize',
                   'neg.paged_pool_rematerialize', 'neg.full_shape_dus',
                   'neg.cache_upcast', 'neg.missing_donation',
                   'neg.collective_axis', 'neg.trace_error')


@pytest.mark.parametrize('name', _NEGATIVE_NAMES)
def test_jaxpr_rule_catches_fixture(name, devices):
    ALL = _negatives_module().ALL
    assert set(ALL) == set(_NEGATIVE_NAMES)
    builder, rule = ALL[name]
    violations = lint_spec(builder(), rules=[rule, 'trace-error'])
    fired = {v.rule for v in violations}
    assert rule in fired, (f'{name}: expected rule {rule!r}, got '
                           + '\n'.join(v.render() for v in violations)
                           if violations else f'{name}: no violations')
    for v in violations:
        assert v.entrypoint == name


def test_f32_accum_violation_names_fixture_line(devices):
    """The jaxpr rules anchor to source: the bf16-accumulation seeded
    regression is reported at its line in the fixture module."""
    builder, rule = _negatives_module().ALL['neg.f32_accum']
    (v,) = lint_spec(builder(), rules=[rule])
    assert v.file and v.file.endswith('jaxpr_negatives.py')
    assert v.line and v.line > 0


def test_clean_spec_restricted_rules_run_subset(devices):
    """--rule style filtering: a spec linted under a single rule only
    reports that rule (the CLI contract)."""
    builder, _ = _negatives_module().ALL['neg.cache_upcast']
    assert lint_spec(builder(), rules=['collective-axis']) == []


# -- CLI ----------------------------------------------------------------

def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.run(
        [sys.executable, '-m', 'distributed_dot_product_tpu.analysis',
         *args], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=540)


def test_cli_nonzero_on_ast_fixture():
    res = _cli('--no-jaxpr',
               os.path.join('tests', 'graphlint_fixtures', 'ops',
                            'fx_host_pull.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    assert 'fx_host_pull.py:' in res.stdout      # file:line rendering
    assert 'host-pull' in res.stdout             # rule id named


@pytest.mark.slow
def test_cli_nonzero_on_jaxpr_fixtures():
    """CLI end-to-end over the seeded jaxpr regressions (subprocess
    with full registry import — slow tier)."""
    res = _cli('--no-ast', '--registry',
               'tests.graphlint_fixtures.jaxpr_negatives:REGISTRY')
    assert res.returncode == 1, res.stdout + res.stderr
    for rule in ('f32-accum', 'cache-alias', 'cache-upcast', 'donation',
                 'collective-axis', 'trace-error'):
        assert rule in res.stdout, f'{rule} missing from CLI output'


def test_cli_list_rules():
    res = _cli('--list-rules')
    assert res.returncode == 0
    for rule in ('f32-accum', 'cache-alias', 'retrace-budget',
                 'silent-except'):
        assert rule in res.stdout


# -- retrace sentinel ---------------------------------------------------

def test_retrace_budget_raises_on_seeded_storm():
    """Seeded regression: a watched step traced past its budget (here:
    shape-polymorphic calls against budget 1) raises loudly instead of
    silently recompiling per call."""
    watched = retrace.watch_traces(lambda x: x * 2, 'unit.storm',
                                   budget=1)
    step = jax.jit(watched)
    step(jnp.ones((2,)))
    step(jnp.ones((2,)))          # cache hit: no new trace
    assert watched._graphlint_counter.count == 1
    with pytest.raises(retrace.RetraceBudgetExceeded,
                       match='unit.storm'):
        step(jnp.ones((3,)))      # new shape → second trace > budget


def test_retrace_disabled_counts_but_never_raises(monkeypatch):
    monkeypatch.setenv(retrace.ENV_VAR, '0')
    watched = retrace.watch_traces(lambda x: x + 1, 'unit.disabled',
                                   budget=1)
    step = jax.jit(watched)
    step(jnp.ones((2,)))
    step(jnp.ones((3,)))          # over budget, but sentinel is off
    assert watched._graphlint_counter.count == 2


def _decode_module(**kw):
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn,
    )
    return DistributedDotProductAttn(
        key_dim=8, num_heads=2, causal=True, softmax_impl='flash',
        dtype=jnp.float32, **kw)


def _decode_setup(module, devices):
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    mesh = seq_mesh(2, devices=devices)
    x = jnp.zeros((1, 8, 8), jnp.float32)
    params = module.init(jax.random.key(0), x, x, x, None)
    cache = module.make_decode_cache(1, 16)
    tok = jnp.zeros((1, 1, 8), jnp.float32)
    return mesh, params, cache, tok


def test_decode_seq_parallel_traces_once_across_tokens(devices):
    """The round-5 advisor finding, enforced mechanically: N tokens
    through decode_seq_parallel's LRU-cached step cost exactly ONE
    trace of the compiled decode step."""
    from distributed_dot_product_tpu.models import attention as A
    module = _decode_module()
    mesh, params, cache, tok = _decode_setup(module, devices)
    A._DECODE_STEPS.clear()
    retrace.reset()
    for _ in range(3):
        cache, _out = A.decode_seq_parallel(module, params, mesh, tok,
                                            tok, tok, cache)
    assert retrace.total('attention.make_decode_step') == 1


def test_decode_seq_parallel_rebuild_storm_is_visible(devices):
    """The storm variant (unhashable module → step rebuilt per token)
    can't trip a per-instance budget — each rebuild gets a fresh
    counter — but the name-total exposes it: N tokens, N traces."""
    from distributed_dot_product_tpu.models import attention as A
    module = _decode_module(
        alibi_slopes=np.array([0.25, 0.5], np.float32))  # unhashable
    mesh, params, cache, tok = _decode_setup(module, devices)
    A._DECODE_STEPS.clear()
    retrace.reset()
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter('ignore')      # warn-once may have fired already
        for _ in range(3):
            cache, _out = A.decode_seq_parallel(module, params, mesh,
                                                tok, tok, tok, cache)
    assert retrace.total('attention.make_decode_step') == 3


def test_engine_programs_trace_once(devices):
    """The serving engine's fixed-shape decode program traces exactly
    once across a multi-step serve loop."""
    from distributed_dot_product_tpu.serve.engine import KernelEngine
    retrace.reset()
    eng = KernelEngine(slots=2, t_max=8, decode_impl='xla')
    tokens = np.zeros(2, np.int32)
    active = np.ones(2, bool)
    for _ in range(4):
        tokens, _finite = eng.step(tokens, active)
    assert retrace.total('engine.decode') == 1


# -- satellite: log_exception -------------------------------------------

def test_log_exception_counts_into_registry():
    from distributed_dot_product_tpu.utils.tracing import (
        MetricsRegistry, log_exception,
    )
    reg = MetricsRegistry()
    log_exception('unit.site', ValueError('boom'), registry=reg)
    log_exception('unit.site', ValueError('boom'), registry=reg)
    snap = reg.snapshot()['counters']
    assert snap['exceptions_swallowed'] == 2
    assert snap['exceptions_swallowed.unit.site'] == 2
