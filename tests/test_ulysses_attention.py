# -*- coding: utf-8 -*-
"""
Ulysses (head all-to-all) sequence-parallelism tests.

No reference analog (SURVEY §2.2: "Ulysses: No. Heads stay local; no
all-to-all anywhere"). Oracle strategy as everywhere in this suite: the
unsharded local computation on full arrays is ground truth; the all-to-all
re-sharded path over a shard_map mesh must match to fp32 tolerance,
including gradients, masks and causality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.models.ring_attention import (
    local_attention_reference,
)
from distributed_dot_product_tpu.models.ulysses_attention import (
    ulysses_attention,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD = 4
TN = 8
T = WORLD * TN
HEADS = 8           # divisible by WORLD
DH = 16
BATCH = 2


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _qkv(dv=DH):
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (BATCH, HEADS, T, DH), jnp.float32)
    k = jax.random.normal(ks[1], (BATCH, HEADS, T, DH), jnp.float32)
    v = jax.random.normal(ks[2], (BATCH, HEADS, T, dv), jnp.float32)
    return q, k, v


def _sharded_ulysses(mesh, causal=False, with_mask=False):
    spec = P(None, None, 'seq', None)
    mspec = P(None, None, 'seq', None)

    def fn(q, k, v, m):
        return ulysses_attention(q, k, v, m, causal=causal)

    def call(q, k, v, m):
        in_specs = (spec, spec, spec, mspec)
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=spec, check_vma=False)(q, k, v, m)
    return call


def _mask():
    m = jax.random.bernoulli(jax.random.key(7), 0.3, (BATCH, 1, T, T))
    return m.at[..., 0].set(False)


@pytest.mark.parametrize('causal', [False, True])
def test_forward_matches_oracle(mesh, causal):
    q, k, v = _qkv(dv=12)   # d_v != d
    m = _mask()
    want = local_attention_reference(q, k, v, m, causal=causal)
    got = _sharded_ulysses(mesh, causal=causal)(q, k, v, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_gradients_match_oracle(mesh):
    q, k, v = _qkv()
    m = _mask()

    def loss_dist(q, k, v):
        return jnp.sum(_sharded_ulysses(mesh)(q, k, v, m) ** 2)

    def loss_local(q, k, v):
        return jnp.sum(local_attention_reference(q, k, v, m) ** 2)

    g1 = jax.grad(loss_dist, (0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_local, (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_rank_mismatched_mask_rejected(mesh):
    """A mask without the explicit size-1 head axis would silently
    broadcast its batch dim against the head axis after the all_to_all —
    it must be rejected, not mis-broadcast."""
    q, k, v = _qkv()
    m3 = jnp.zeros((BATCH, T, T), dtype=bool)   # no head axis
    spec = P(None, None, 'seq', None)
    with pytest.raises(ValueError, match='same rank'):
        jax.shard_map(
            lambda q, k, v, m: ulysses_attention(q, k, v, m),
            mesh=mesh, in_specs=(spec, spec, spec, P(None, 'seq', None)),
            out_specs=spec, check_vma=False)(q, k, v, m3)
    m_perhead = jnp.zeros((BATCH, HEADS, T, T), dtype=bool)
    with pytest.raises(ValueError, match='head-broadcast'):
        jax.shard_map(
            lambda q, k, v, m: ulysses_attention(q, k, v, m),
            mesh=mesh, in_specs=(spec, spec, spec, spec),
            out_specs=spec, check_vma=False)(q, k, v, m_perhead)


def test_heads_not_divisible_rejected(mesh):
    q, k, v = _qkv()
    q = q[:, :WORLD + 1]    # 5 heads on a 4-wide mesh
    k, v = k[:, :WORLD + 1], v[:, :WORLD + 1]
    with pytest.raises(ValueError, match='divisible'):
        _sharded_ulysses(mesh)(q, k, v, None)


def test_module_ulysses_impl_matches_local_oracle(mesh):
    """DistributedDotProductAttn(softmax_impl='ulysses') inside shard_map ==
    the distributed=False oracle, through projections, the K-first scoring
    convention, multi-head split and mask broadcast."""
    t, dim, heads = T, 32, HEADS
    kw = dict(key_dim=dim, num_heads=heads, offset=2)
    dist = DistributedDotProductAttn(softmax_impl='ulysses', **kw)
    local = DistributedDotProductAttn(distributed=False, **kw)

    x = jax.random.normal(jax.random.key(0), (BATCH, t, dim))
    m = jax.random.bernoulli(jax.random.key(1), 0.3, (BATCH, t, t))
    m = m.at[..., 0].set(False)
    params = local.init(jax.random.key(2), x, x, x, m)

    expected = local.apply(params, x, x, x, m)
    got = apply_seq_parallel(dist, params, mesh, x, x, x, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)

    # gradients through the module path too
    def loss(mod):
        if mod is local:
            return lambda p: jnp.sum(local.apply(p, x, x, x, m) ** 2)
        return lambda p: jnp.sum(
            apply_seq_parallel(mod, p, mesh, x, x, x, m) ** 2)
    g_d = jax.grad(loss(dist))(params)
    g_l = jax.grad(loss(local))(params)
    for a, b in zip(jax.tree.leaves(g_d), jax.tree.leaves(g_l)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=1e-3)


def test_module_ulysses_single_head_falls_back(mesh):
    """num_heads=1 has no head axis to scatter — the module must route
    through the gathered flash path and still match the oracle."""
    t, dim = T, 16
    kw = dict(key_dim=dim, num_heads=1, offset=2)
    dist = DistributedDotProductAttn(softmax_impl='ulysses', **kw)
    local = DistributedDotProductAttn(distributed=False, **kw)
    x = jax.random.normal(jax.random.key(0), (BATCH, t, dim))
    m = jnp.zeros((BATCH, t, t), dtype=bool)
    params = local.init(jax.random.key(2), x, x, x, m)
    expected = local.apply(params, x, x, x, m)
    got = apply_seq_parallel(dist, params, mesh, x, x, x, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)
