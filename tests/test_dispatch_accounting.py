# -*- coding: utf-8 -*-
"""
Dispatch-floor accounting (serve/engine.py program_seconds odometer +
serve/scheduler.py per-tick split): every decode tick stamps REAL tick
wall time vs device-program time into a `serve.dispatch` event and the
`serve.dispatch_overhead_seconds` / `serve.device_seconds` histograms,
each committed token carries its tick's `device_seconds`, the split
surfaces in /metrics exposition and the benchmark row helper — and
none of it touches the virtual timeline (the phase partition stays
exact with the accounting on, which is always).
"""

import importlib.util
import os

import numpy as np
import pytest

from distributed_dot_product_tpu.obs.critpath import (
    attribute, dispatch_floor,
)
from distributed_dot_product_tpu.obs.events import (
    EventLog, read_events, validate_file,
)
from distributed_dot_product_tpu.obs.exporter import render_prometheus
from distributed_dot_product_tpu.serve import (
    KernelEngine, Scheduler, ServeConfig, VirtualClock,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

VOCAB = 16


def _run(tmp_path, *, spec=None, max_new=5, n=3):
    clock = VirtualClock()
    log = EventLog(tmp_path / 'serve.jsonl', clock=clock)
    registry = MetricsRegistry()
    cfg_kw = dict(queue_limit=8, max_new_tokens=max_new,
                  watchdog=False)
    if spec:
        cfg_kw.update(spec=spec, spec_k=3)
    sched = Scheduler(
        KernelEngine(slots=2, t_max=32, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl='xla'),
        ServeConfig(**cfg_kw), clock=clock, registry=registry,
        fault_injector=False, event_log=log,
        on_tick=lambda s: clock.advance(0.01))
    for i in range(n):
        sched.submit(np.asarray([i + 1], np.int32),
                     request_id=f'r{i}')
    results = sched.run_until_idle()
    sched.close()
    log.close()
    return log.path, registry, results


def test_every_decode_tick_stamps_the_split(tmp_path, devices):
    path, registry, results = _run(tmp_path)
    records, errors = validate_file(path)
    assert errors == [], errors

    disp = [r for r in records if r['event'] == 'serve.dispatch']
    assert disp, 'no serve.dispatch records on a decode run'
    for r in disp:
        # REAL seconds: the program slice is timed inside the tick
        # window, so tick wall time bounds it.
        assert 0.0 <= r['device_seconds'] <= r['tick_seconds'] + 1e-9
        assert r['overhead'] == pytest.approx(
            max(0.0, r['tick_seconds'] - r['device_seconds']))
        assert r['tokens'] >= 0
        assert 'request_id' not in r     # per-tick, not per-stream
    # Tick token counts fold to the run's committed total.
    total_tokens = sum(len(res.tokens) for res in results.values())
    assert sum(r['tokens'] for r in disp) == total_tokens


def test_tokens_carry_their_ticks_device_seconds(tmp_path, devices):
    path, _, _ = _run(tmp_path)
    records = read_events(path)
    decodes = [r for r in records if r['event'] == 'serve.decode']
    assert decodes
    stamped = [r for r in decodes if r.get('device_seconds')
               is not None]
    assert stamped, 'no serve.decode carries the device stamp'
    for r in stamped:
        assert r['device_seconds'] >= 0.0
    # All tokens committed by one tick share that tick's stamp.
    disp = {r['step']: r for r in records
            if r['event'] == 'serve.dispatch'}
    assert disp


def test_histograms_and_metrics_exposition(tmp_path, devices):
    path, registry, _ = _run(tmp_path)
    h_over = registry.peek('histogram',
                           'serve.dispatch_overhead_seconds')
    h_dev = registry.peek('histogram', 'serve.device_seconds')
    assert h_over is not None and h_over.total_count > 0
    assert h_dev is not None and h_dev.total_count == \
        h_over.total_count
    n_disp = sum(1 for r in read_events(path)
                 if r['event'] == 'serve.dispatch')
    assert h_over.total_count == n_disp

    text = render_prometheus(registry)
    assert 'dispatch_overhead_seconds' in text
    assert 'device_seconds' in text


def test_spec_ticks_account_too(tmp_path, devices):
    """Speculative decoding runs its device work through verify_step —
    the odometer must cover that path as well."""
    path, registry, results = _run(tmp_path, spec='ngram', max_new=8)
    assert any(len(r.tokens) for r in results.values())
    records = read_events(path)
    assert any(r['event'] == 'spec.verify' for r in records)
    disp = [r for r in records if r['event'] == 'serve.dispatch']
    assert disp
    assert any(r['device_seconds'] > 0 for r in disp), (
        'spec verify steps never moved the program odometer')


def test_accounting_never_touches_the_virtual_partition(tmp_path,
                                                        devices):
    """The REAL-seconds stamps are payload only: with the accounting
    on (it cannot be turned off), every request's virtual-time phase
    partition still closes exactly."""
    path, _, results = _run(tmp_path)
    chains = attribute(path)
    assert set(chains) == set(results)
    for c in chains.values():
        assert not c.partial and c.ok, (c.request_id, c.errors)
    floor = dispatch_floor(path)
    assert floor['total']['ticks'] > 0
    assert floor['total']['overhead_per_token'] is not None


def test_benchmark_row_helper_reads_the_registry(tmp_path, devices):
    """benchmark.py's `_dispatch_split` turns the two histograms into
    the decode-serve/serve-load row columns."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        'bench_for_test', os.path.join(repo, 'benchmark.py'))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    _, registry, results = _run(tmp_path)
    n_tok = sum(len(r.tokens) for r in results.values())
    row = bench._dispatch_split(registry, n_tok)
    assert row['dispatch_ticks'] > 0
    assert row['dispatch_overhead_s'] >= 0.0
    assert row['dispatch_overhead_ms_per_token'] == pytest.approx(
        row['dispatch_overhead_s'] / n_tok * 1e3)
    assert 0.0 <= row['dispatch_overhead_pct'] <= 100.0
    assert row['dispatch_overhead_p99_ms'] >= 0.0
    # An idle registry yields no columns rather than zeros.
    assert bench._dispatch_split(MetricsRegistry(), 0) == {}
