# -*- coding: utf-8 -*-
"""
End-to-end low precision — owned dense + int8 weight quantization.

The ISSUE-14 acceptance scenarios on the CPU backend:

- **Owned dense parity**: `models/dense.OwnedDense` is a drop-in for
  `nn.Dense` — identical param tree, bit-identical f32 outputs — while
  owning the fp32-accumulation contract graphlint enforces (the
  zero-waiver gate lives in test_graphlint.py).
- **Logit-exactness contract** (the K-mirror treatment applied to
  weights): the int8-weight forward lands within the documented int8
  rounding class of the float reference — per-element error bounded by
  one rounding step of each side's per-row/per-channel scale, i.e.
  ~1% of the output scale — at the dense, attention-module and full-LM
  levels.
- **Bit-identical greedy streams under the stuck+NaN fault cocktail on
  both cache layouts**: quantized engines are deterministic, and slab
  vs paged int8 engines emit token-identical streams (weights are
  layout-oblivious).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from distributed_dot_product_tpu.models.dense import (
    OwnedDense, dense_param_bytes, quantize_dense_params,
    quantize_kernel,
)

# Documented tolerance of the logit-exactness contract: both operands
# quantize symmetrically to int8 (rounding error <= scale/2 per element,
# ~0.4% of the row/column max each), so outputs land within ~1-2% of
# the output scale. Same class as the K-mirror contract
# (test_qk_quant.test_quant_close_to_exact).
WQ8_RTOL = 0.05


def _rel_err(got, want):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    return float(np.abs(got - want).max() / max(np.abs(want).max(),
                                                1e-9))


# -- owned dense --------------------------------------------------------

def test_owned_dense_matches_nn_dense_at_f32():
    x = jax.random.normal(jax.random.key(0), (2, 5, 8))
    own = OwnedDense(16, name='d')
    ref = nn.Dense(16, name='d')
    params = ref.init(jax.random.key(1), x)
    # Same param tree (kernel/bias names, shapes, init): checkpoints
    # carry over.
    assert (jax.tree.structure(params)
            == jax.tree.structure(own.init(jax.random.key(1), x)))
    np.testing.assert_array_equal(np.asarray(ref.apply(params, x)),
                                  np.asarray(own.apply(params, x)))


def test_owned_dense_bf16_casts_back():
    x = jax.random.normal(jax.random.key(0), (2, 8)).astype(jnp.bfloat16)
    own = OwnedDense(4, dtype=jnp.bfloat16)
    p = own.init(jax.random.key(1), x)
    y = own.apply(p, x)
    assert y.dtype == jnp.bfloat16    # f32 ACCUMULATION, not f32 output


def test_owned_dense_rejects_unknown_quant():
    x = jnp.zeros((1, 4))
    with pytest.raises(ValueError, match='weight_quant'):
        OwnedDense(4, weight_quant='int4').init(jax.random.key(0), x)


# -- conversion ---------------------------------------------------------

def test_quantize_dense_params_structure():
    x = jax.random.normal(jax.random.key(0), (2, 8))
    own = OwnedDense(16, name='d')
    p = own.init(jax.random.key(1), x)
    q = quantize_dense_params(p)
    leaf = q['params']
    assert set(leaf) == {'kernel_q', 'kernel_scale', 'bias'}
    assert leaf['kernel_q'].dtype == jnp.int8
    assert leaf['kernel_q'].shape == (8, 16)
    assert leaf['kernel_scale'].shape == (16,)
    # int8 weights + f32 scales undercut the f32 kernel's bytes.
    assert dense_param_bytes(q) < dense_param_bytes(p)


def test_quantize_kernel_handles_layer_stacked():
    """nn.scan stacks kernels as (L, in, out): channels quantize per
    layer — slicing a layer off the stacked quantization must equal
    quantizing that layer alone."""
    w = jax.random.normal(jax.random.key(0), (3, 8, 16))
    wq, ws = quantize_kernel(w)
    assert wq.shape == (3, 8, 16) and ws.shape == (3, 16)
    wq0, ws0 = quantize_kernel(w[1])
    np.testing.assert_array_equal(np.asarray(wq[1]), np.asarray(wq0))
    np.testing.assert_array_equal(np.asarray(ws[1]), np.asarray(ws0))


# -- logit-exactness contract ------------------------------------------

def test_dense_wq8_within_documented_tolerance():
    x = jax.random.normal(jax.random.key(0), (4, 32))
    own = OwnedDense(16, name='d')
    p = own.init(jax.random.key(1), x)
    want = own.apply(p, x)
    got = OwnedDense(16, name='d', weight_quant='int8').apply(
        quantize_dense_params(p), x)
    assert _rel_err(got, want) < WQ8_RTOL


def test_attention_module_wq8_within_tolerance():
    kw = dict(key_dim=8, num_heads=2, causal=True, softmax_impl='flash',
              distributed=False)
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn,
    )
    m = DistributedDotProductAttn(**kw)
    mq = DistributedDotProductAttn(weight_quant='int8', **kw)
    x = jax.random.normal(jax.random.key(2), (1, 16, 8))
    p = m.init(jax.random.key(3), x, x, x, None)
    want = m.apply(p, x, x, x, None)
    got = mq.apply(quantize_dense_params(p), x, x, x, None)
    assert _rel_err(got, want) < WQ8_RTOL


def test_lm_wq8_logits_and_generation():
    """The full capstone at int8 weights: logits within tolerance of
    the float twin, generation deterministic, caches untouched by the
    weight precision (the scanned stack threads weight_quant through
    every block)."""
    from distributed_dot_product_tpu.models.lm import (
        TransformerLM, greedy_generate,
    )
    kw = dict(vocab_size=32, dim=16, num_heads=2, n_layers=2,
              attn_kwargs={'distributed': False})
    lm = TransformerLM(**kw)
    lmq = TransformerLM(weight_quant='int8', **kw)
    tok = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, size=(1, 8)), jnp.int32)
    p = lm.init(jax.random.key(0), tok)
    pq = quantize_dense_params(p)
    assert _rel_err(lmq.apply(pq, tok), lm.apply(p, tok)) < WQ8_RTOL
    out1 = greedy_generate(lmq, pq, tok, 4, 32)
    out2 = greedy_generate(lmq, pq, tok, 4, 32)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


# -- engine: knob, bytes, cocktail bit-identity -------------------------

VOCAB, T_MAX, PS = 16, 64, 4
SLAB_SLOTS = 4
PAGED_SLOTS = 16
PAGES = SLAB_SLOTS * T_MAX // PS


def _engine(mode, slots, **kw):
    from distributed_dot_product_tpu.serve import KernelEngine
    paged = dict(cache_mode='paged', page_size=PS, pages=PAGES) \
        if mode == 'paged' else {}
    return KernelEngine(slots=slots, t_max=T_MAX, vocab=VOCAB, heads=2,
                        head_dim=4, prefill_chunk=4, seed=5,
                        decode_impl=kw.pop('decode_impl', 'xla'),
                        weight_quant=kw.pop('weight_quant', 'int8'),
                        **paged, **kw)


def _burst(n, seed):
    rng = np.random.default_rng(seed)
    return [(f'r{i:03d}',
             rng.integers(0, VOCAB,
                          size=int(rng.integers(1, 7))).astype(np.int32))
            for i in range(n)]


def _run(mode, slots, n_requests, injector=None, *, seed=11,
         max_new=3, decode_impl='xla'):
    from distributed_dot_product_tpu.serve import (
        RejectedError, Scheduler, ServeConfig,
    )
    from distributed_dot_product_tpu.utils.tracing import MetricsRegistry
    sched = Scheduler(
        _engine(mode, slots, decode_impl=decode_impl),
        ServeConfig(queue_limit=48, max_new_tokens=max_new,
                    watchdog=False, evict_before_reject=False),
        fault_injector=injector if injector is not None else False,
        registry=MetricsRegistry())
    rejected = {}
    for rid, prompt in _burst(n_requests, seed):
        try:
            sched.submit(prompt, request_id=rid)
        except RejectedError as e:
            rejected[rid] = e.reason
    results = sched.run_until_idle()
    sched.close()
    return rejected, results


def test_engine_weight_quant_env_knob(monkeypatch):
    from distributed_dot_product_tpu.serve import KernelEngine
    monkeypatch.setenv('DDP_TPU_WEIGHT_QUANT', 'int8')
    eng = KernelEngine(slots=2, t_max=8, decode_impl='xla')
    assert eng.weight_quant == 'int8'
    # Explicit 'off' overrides the env — the deployment opt-out.
    eng2 = KernelEngine(slots=2, t_max=8, decode_impl='xla',
                        weight_quant='off')
    assert eng2.weight_quant is None
    with pytest.raises(ValueError, match='weight_quant'):
        KernelEngine(slots=2, t_max=8, weight_quant='fp4')


def test_engine_wq8_weight_bytes_below_float():
    eq = _engine('slab', SLAB_SLOTS)
    ef = _engine('slab', SLAB_SLOTS, weight_quant='off')
    assert eq.weight_bytes < ef.weight_bytes


def test_wq8_streams_bit_identical_slab_vs_paged_under_cocktail():
    """The cocktail test at int8 weights: same seeded traffic +
    stuck/NaN faults through a quantized slab scheduler and a
    quantized paged one — every request completed by BOTH runs
    produced bit-identical tokens. Weight precision changes the
    logits, never the layout-independence of the math."""
    from distributed_dot_product_tpu.utils.faults import (
        ServeFaultInjector, ServeFaultPlan,
    )
    n = 16
    plan = dict(stuck_at_step=3, stuck_seconds=0.02, nan_at_step=5,
                nan_slot=1)
    _, res_s = _run('slab', SLAB_SLOTS, n,
                    ServeFaultInjector(ServeFaultPlan(**plan)))
    _, res_p = _run('paged', PAGED_SLOTS, n,
                    ServeFaultInjector(ServeFaultPlan(**plan)))
    compared = 0
    for rid, rp in res_p.items():
        rs = res_s.get(rid)
        if rs is None or rp.status != 'completed' \
                or rs.status != 'completed':
            continue
        short, long_ = sorted((rp.tokens, rs.tokens), key=len)
        assert long_[:len(short)] == short, f'{rid}: stream diverged'
        compared += 1
    assert compared >= 5, 'burst too small to witness identity'


def test_wq8_streams_deterministic_across_runs():
    """Same engine config + traffic twice → identical streams (the
    repo's standing determinism contract holds at int8 weights)."""
    _, a = _run('slab', SLAB_SLOTS, 8)
    _, b = _run('slab', SLAB_SLOTS, 8)
    assert set(a) == set(b)
    for rid in a:
        assert a[rid].tokens == b[rid].tokens
        assert a[rid].status == b[rid].status
