# -*- coding: utf-8 -*-
"""
Real-TPU hardware parity suite (``DDP_TPU_TESTS_ON_TPU=1 pytest -m tpu``).

The reference runs its whole test suite on the accelerator when present
(cpu/cuda device fixture, reference test_gradient.py:64-70). The CPU-mesh
suite here covers the same *code* (shard_map plumbing, Pallas interpreter),
but the real backend differs materially — Mosaic kernel compilation, bf16
MXU matmul defaults, ICI collectives — so this module re-runs the core
parity assertions on the actual chip: the three L2 kernels (bitwise, under
``default_matmul_precision('highest')`` — TPU's default bf16 3-pass would
round the integer oracle), their VJPs, flash fwd+bwd with every mask form
(dense + block-skip redirect, segments, positions), ring attention (both
layouts), the 'full' module path and one full train step.

Single-chip W=1 meshes: the shard_map/collective plumbing compiles and
executes for real, degenerate but on-device (multi-chip execution is
covered by the CPU mesh + the driver dryrun; this suite is about the
hardware backend).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu

_ON_TPU = (os.environ.get('DDP_TPU_TESTS_ON_TPU')
           and jax.default_backend() == 'tpu')


@pytest.fixture(autouse=True)
def _require_tpu():
    if not _ON_TPU:
        pytest.skip('requires DDP_TPU_TESTS_ON_TPU=1 and a real TPU backend')


def _ints(*shape, lo=-3, hi=4, seed=0):
    """Integer-valued f32: bitwise-comparable when matmul precision is
    forced to 'highest' (partial sums stay far below 2^24)."""
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(lo, hi, size=shape).astype(np.float32))


T, D = 64, 32


# --- L2 kernels: bitwise parity + VJPs -----------------------------------

@pytest.mark.parametrize('offset', [8, None])
def test_matmul_nt_bitwise(offset):
    from distributed_dot_product_tpu.ops.functions import (
        distributed_matmul_nt_global,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    left, right = _ints(2, T, D), _ints(2, T, D, seed=1)
    with jax.default_matmul_precision('highest'):
        got = distributed_matmul_nt_global(left, right, offset=offset,
                                           mesh=seq_mesh(1))
        want = jnp.matmul(left, jnp.swapaxes(right, -1, -2))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_matmul_tn_bitwise():
    from distributed_dot_product_tpu.ops.functions import (
        distributed_matmul_tn_global,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    left, right = _ints(2, T, T), _ints(2, T, D, seed=1)
    with jax.default_matmul_precision('highest'):
        got = distributed_matmul_tn_global(left, right, mesh=seq_mesh(1))
        want = jnp.matmul(jnp.swapaxes(left, -1, -2), right)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_matmul_all_bitwise():
    from distributed_dot_product_tpu.ops.functions import (
        distributed_matmul_all_global,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    left, right = _ints(2, T, T), _ints(2, T, D, seed=1)
    with jax.default_matmul_precision('highest'):
        got = distributed_matmul_all_global(left, right, offset=8,
                                            mesh=seq_mesh(1))
        want = jnp.matmul(left, right)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_op_grads_match_full_autodiff():
    """The custom VJPs (reference ops.py pairings, fixed) on the chip."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.ops.ops import matmul_nt
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    left, right = _ints(1, T, D), _ints(1, T, D, seed=1)
    cot = _ints(1, T, T, seed=2)
    mesh = seq_mesh(1)

    def dist(left, right):
        return jax.shard_map(
            lambda l, r: matmul_nt(l, r, 8), mesh=mesh,
            in_specs=(P(None, 'seq', None),) * 2,
            out_specs=P(None, 'seq', None), check_vma=False)(left, right)

    with jax.default_matmul_precision('highest'):
        g_dist = jax.grad(lambda l, r: jnp.sum(dist(l, r) * cot),
                          argnums=(0, 1))(left, right)
        g_full = jax.grad(
            lambda l, r: jnp.sum(
                jnp.matmul(l, jnp.swapaxes(r, -1, -2)) * cot),
            argnums=(0, 1))(left, right)
    for got, want in zip(g_dist, g_full):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --- flash kernels: every mask form on Mosaic ----------------------------

def _qkv(t=512, d=64, dtype=jnp.bfloat16, heads=4):
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(kk, (1, heads, t, d), dtype) for kk in ks)


def _oracle(q, k, v, mask, causal=False):
    from distributed_dot_product_tpu.ops.pallas_attention import (
        _reference_math,
    )
    return _reference_math(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32), mask,
                           1.0 / np.sqrt(q.shape[-1]), causal)


def _close(got, want, atol=2.5e-2):
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), atol=atol, rtol=atol)


def test_flash_dense_mask_redirect_fwd_bwd():
    """Dense mask through the scalar-prefetch DMA redirect (TPU-only
    path): block-diagonal mask = skipped, redirected AND mixed tiles."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t = 1024
    q, k, v = _qkv(t)
    blk = jnp.arange(t) // 256
    mask = (blk[:, None] != blk[None, :])[None, None]
    mask = mask.at[:, :, :300, :].set(False)
    _close(flash_attention(q, k, v, mask), _oracle(q, k, v, mask))
    g = jax.grad(lambda v_: jnp.sum(flash_attention(q, k, v_, mask)
                                    .astype(jnp.float32) ** 2))(v)
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def test_flash_segments_fwd_bwd():
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t = 768
    q, k, v = _qkv(t)
    seg = (jnp.arange(t, dtype=jnp.int32) * 3 // t)[None]
    dense = (seg[0][:, None] != seg[0][None, :])[None, None]
    _close(flash_attention(q, k, v, segment_ids=seg),
           _oracle(q, k, v, jnp.broadcast_to(dense, (1, 1, t, t))))
    g = jax.grad(lambda v_: jnp.sum(flash_attention(
        q, k, v_, segment_ids=seg).astype(jnp.float32) ** 2))(v)
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def test_flash_positions_fwd_bwd():
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t = 512
    q, k, v = _qkv(t)
    pos = jax.random.permutation(jax.random.key(3), t)[None].astype(
        jnp.int32)
    dense = (pos[0][:, None] < pos[0][None, :])[None, None]
    _close(flash_attention(q, k, v, positions=pos),
           _oracle(q, k, v, jnp.broadcast_to(dense, (1, 1, t, t))))
    g = jax.grad(lambda q_: jnp.sum(flash_attention(
        q_, k, v, positions=pos).astype(jnp.float32) ** 2))(q)
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def test_flash_causal_offset_traced():
    """Sequence-sharded causal: the traced scalar offset input."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t = 512
    q, k, v = _qkv(t)
    half = q[:, :, t // 2:]
    rows = t // 2 + jnp.arange(t // 2)
    dense = (rows[:, None] < jnp.arange(t)[None, :])[None, None]
    got = jax.jit(lambda off: flash_attention(
        half, k, v, causal=True, causal_offset=off))(t // 2)
    _close(got, _oracle(half, k, v,
                        jnp.broadcast_to(dense, (1, 1, t // 2, t))))


# --- ring attention on the chip ------------------------------------------

def test_ring_attention_w1_fwd_grad():
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    q, k, v = _qkv(512)
    mesh = seq_mesh(1)
    spec = P(None, None, 'seq', None)
    ring = jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)
    _close(ring(q, k, v), _oracle(q, k, v, None, causal=True))
    g = jax.grad(lambda v_: jnp.sum(ring(q, k, v_)
                                    .astype(jnp.float32) ** 2))(v)
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def test_ring_zigzag_w1():
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention, zigzag_indices,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    t = 512
    q, k, v = _qkv(t)
    idx = zigzag_indices(t, 1)
    inv = jnp.argsort(idx)
    mesh = seq_mesh(1)
    spec = P(None, None, 'seq', None)
    ring = jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=True,
                                       layout='zigzag'),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)
    got = ring(q[..., idx, :], k[..., idx, :], v[..., idx, :])[..., inv, :]
    _close(got, _oracle(q, k, v, None, causal=True))


# --- module + train step -------------------------------------------------

def test_module_full_path_matches_oracle():
    """The reference-parity 'full' softmax path (chunked allgather nt/all
    kernels through the module) on the chip."""
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn, apply_seq_parallel,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    dim, t = 64, 256
    model = DistributedDotProductAttn(key_dim=dim, num_heads=4, offset=32)
    oracle = DistributedDotProductAttn(key_dim=dim, num_heads=4,
                                       distributed=False)
    x = jax.random.normal(jax.random.key(1), (2, t, dim), jnp.float32)
    m = jnp.zeros((2, t, t), dtype=bool)
    params = oracle.init(jax.random.key(2), x, x, x, m)
    got = apply_seq_parallel(model, params, seq_mesh(1), x, x, x, m)
    want = oracle.apply(params, x, x, x, m)
    _close(got, want)


def test_train_step_updates_params():
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_dot_product_tpu import DistributedDotProductAttn
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    from distributed_dot_product_tpu.train import make_train_step
    dim, t = 64, 512
    mesh = seq_mesh(1)
    model = DistributedDotProductAttn(key_dim=dim, num_heads=4,
                                      softmax_impl='flash', causal=True,
                                      dtype=jnp.bfloat16)
    x = jax.device_put(
        jax.random.normal(jax.random.key(1), (1, t, dim), jnp.bfloat16),
        NamedSharding(mesh, P(None, 'seq', None)))
    x0 = jnp.zeros((1, 16, dim), jnp.bfloat16)
    params = model.init(jax.random.key(0), x0, x0, x0, None)
    opt = optax.adam(1e-3)
    step = make_train_step(model, opt, mesh, donate=False)
    new_params, _, loss = step(params, opt.init(params),
                               (x, x, x, None, x))
    assert np.isfinite(float(loss))
    changed = any(
        not np.allclose(np.asarray(a, np.float32), np.asarray(b, np.float32))
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(new_params)))
    assert changed, 'adam update did not change the parameters'


def test_ulysses_w1_matches_flash():
    from distributed_dot_product_tpu.models.ulysses_attention import (
        ulysses_attention,
    )
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    from jax.sharding import PartitionSpec as P
    q, k, v = _qkv(256)
    mesh = seq_mesh(1)
    spec = P(None, None, 'seq', None)
    uly = jax.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)
    _close(uly(q, k, v), flash_attention(q, k, v, causal=True),
           atol=1e-2)


def test_flash_window_banded_fwd_bwd():
    """Sliding-window attention on the real chip: the banded grid (active
    on TPU by default — scalar-prefetch index maps, Mosaic-compiled) must
    match the densified-mask oracle, forward and gradients."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        _reference_math, flash_attention,
    )
    t, window = 192, 40
    k1, k2, k3 = jax.random.split(jax.random.key(17), 3)
    q = jax.random.normal(k1, (2, t, D), jnp.float32)
    k = jax.random.normal(k2, (2, t, D), jnp.float32)
    v = jax.random.normal(k3, (2, t, D), jnp.float32)
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    dense = rows - cols >= window

    def f_win(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                window=window) ** 2).sum()

    def f_ref(q, k, v):
        return (_reference_math(q, k, v, dense, 1.0 / np.sqrt(D),
                                True).astype(jnp.float32) ** 2).sum()

    l_w, g_w = jax.value_and_grad(f_win, argnums=(0, 1, 2))(q, k, v)
    l_r, g_r = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(l_w), float(l_r), rtol=2e-2)
    for gw, gr in zip(g_w, g_r):
        # 5e-2: TPU f32 matmul defaults to 3-pass bf16 and the oracle's
        # op order differs; CPU parity for the same path is 1e-5
        # (tests/test_window_attention.py).
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gr),
                                   atol=5e-2, rtol=2e-2)


def test_flash_gqa_fwd_bwd():
    """Grouped-query attention on the real chip: Mosaic-compiled grouped
    K/V index maps + group-summed dk/dv match the repeated-kv oracle."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t, hq, hkv = 128, 4, 2
    k1, k2, k3 = jax.random.split(jax.random.key(23), 3)
    q = jax.random.normal(k1, (hq, t, D), jnp.float32)
    k = jax.random.normal(k2, (hkv, t, D), jnp.float32)
    v = jax.random.normal(k3, (hkv, t, D), jnp.float32)
    rep = lambda x: jnp.repeat(x, hq // hkv, axis=0)  # noqa: E731

    def f(q, k, v):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    def f_rep(q, kr, vr):
        return (flash_attention(q, kr, vr, causal=True) ** 2).sum()

    l, (dq, dk, dv) = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
    lr, (dqr, dkr, dvr) = jax.value_and_grad(
        f_rep, argnums=(0, 1, 2))(q, rep(k), rep(v))
    np.testing.assert_allclose(float(l), float(lr), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dqr), atol=2e-2,
                               rtol=2e-2)
    for got, r in ((dk, dkr), (dv, dvr)):
        want = r.reshape(hkv, hq // hkv, t, D).sum(1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-2, rtol=2e-2)


def test_flash_alibi_and_rope_fwd_bwd():
    """ALiBi slopes (in-kernel SMEM table, Mosaic-compiled) + RoPE'd
    inputs on the real chip vs the dense jnp oracle."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    from distributed_dot_product_tpu.ops.rope import rope
    t, h = 128, 4
    ks = jax.random.split(jax.random.key(29), 3)
    q, k, v = (jax.random.normal(kk, (h, t, D), jnp.float32) for kk in ks)
    q, k = rope(q), rope(k)
    sl = 2.0 ** (-2.0 * (jnp.arange(h) + 1))

    def f(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                alibi_slopes=sl) ** 2).sum()

    def f_ref(q, k, v):
        scale = 1.0 / np.sqrt(D)
        s = jnp.einsum('htd,hod->hto', q * scale, k)
        rows = jnp.arange(t)[:, None]
        cols = jnp.arange(t)[None, :]
        s = s + sl[:, None, None] * (cols - rows)
        s = jnp.where(rows < cols, -jnp.inf, s)
        a = jax.nn.softmax(s, axis=-1)
        return (jnp.einsum('hto,hod->htd', a, v) ** 2).sum()

    l, g = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
    lr, gr = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(l), float(lr), rtol=1e-2)
    for got, want in zip(g, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-2, rtol=2e-2)


def test_flash_qk_quant_int8_fwd_bwd():
    """int8-quantized QK^T on the real chip: the Mosaic int8 MXU dot +
    in-kernel dequant must match the dense quantized-math oracle."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t, h = 128, 4
    ks = jax.random.split(jax.random.key(31), 3)
    q, k, v = (jax.random.normal(kk, (h, t, D), jnp.float32) for kk in ks)

    def dense(q, k, v):
        scale = 1.0 / np.sqrt(D)
        sq = jnp.maximum(jnp.abs(q).max(-1, keepdims=True) / 127.0, 1e-20)
        sk = jnp.maximum(jnp.abs(k).max(-1, keepdims=True) / 127.0, 1e-20)
        s = jnp.einsum('htd,hod->hto', jnp.round(q / sq) * sq,
                       jnp.round(k / sk) * sk) * scale
        rows = jnp.arange(t)[:, None]
        s = jnp.where(rows < jnp.arange(t)[None, :], -jnp.inf, s)
        return jnp.einsum('hto,hod->htd', jax.nn.softmax(s, -1), v)

    out = flash_attention(q, k, v, causal=True, qk_quant='int8')
    with jax.default_matmul_precision('highest'):
        ref = dense(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)
    g = jax.grad(lambda v_: (flash_attention(
        q, k, v_, causal=True, qk_quant='int8') ** 2).sum())(v)
    assert bool(jnp.isfinite(g).all())


def test_flash_dropout_prng_path():
    """In-kernel PRNG dropout on the real chip: deterministic per seed,
    seed-sensitive, keep-rate within statistical bounds, expectation
    close to the exact output, and finite grads."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    t, h, rate = 256, 4, 0.3
    ks = jax.random.split(jax.random.key(37), 3)
    q, k, v = (jax.random.normal(kk, (h, t, D), jnp.float32) for kk in ks)
    kw = dict(dropout_rate=rate)
    a = flash_attention(q, k, v, dropout_seed=1, **kw)
    b = flash_attention(q, k, v, dropout_seed=1, **kw)
    c = flash_attention(q, k, v, dropout_seed=2, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))

    # Keep-rate: recover the dropped-weight matrix by feeding v = I.
    eye = jnp.broadcast_to(jnp.eye(t, dtype=jnp.float32), (h, t, t))
    w = flash_attention(q, k, eye, dropout_seed=3, **kw)
    kept = float((np.asarray(w) != 0).mean())
    assert abs(kept - (1 - rate)) < 0.02, kept

    # The mask is a pure element-coordinate hash — replicate it in
    # numpy and demand EXACT agreement with the Mosaic-compiled kernel
    # (softmax weights are strictly positive non-causal, so w != 0
    # recovers the complete mask).
    u = np.uint32
    rows = np.arange(t, dtype=np.uint32)[None, :, None]
    cols = np.arange(t, dtype=np.uint32)[None, None, :]
    bidx = np.arange(h, dtype=np.uint32)[:, None, None]
    with np.errstate(over='ignore'):
        x = (rows * u(2654435761) ^ cols * u(2246822519)
             ^ (u(3) + bidx * u(668265263)))
        x ^= x >> u(16)
        x = (x * u(2246822507)).astype(np.uint32)
        x ^= x >> u(13)
        x = (x * u(3266489909)).astype(np.uint32)
        x ^= x >> u(16)
    want_keep = x >= u(int(rate * 2.0 ** 32))
    np.testing.assert_array_equal(np.asarray(w) != 0, want_keep)

    exact = flash_attention(q, k, v)
    mean = jnp.stack([flash_attention(q, k, v, dropout_seed=s, **kw)
                      for s in range(48)]).mean(0)
    # Loose: the max-deviation TAIL over h·t·D elements shrinks only as
    # 1/√seeds; the keep-rate assertion above pins the distribution.
    np.testing.assert_allclose(np.asarray(mean), np.asarray(exact),
                               atol=0.2)
    g = jax.grad(lambda q_: (flash_attention(
        q_, k, v, dropout_seed=1, **kw) ** 2).sum())(q)
    assert bool(jnp.isfinite(g).all())


# --- round-4 surface: trapezoid grid, module GQA/RoPE, ring features ----

def test_trapezoid_causal_matches_full_grid_on_chip():
    """Static-offset causal takes the trapezoid pair grid on the real
    Mosaic backend; a traced offset keeps the full grid. Same math, so
    fwd AND both gradients must agree bitwise (identical kernels, only
    the grid walk differs)."""
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    ks = jax.random.split(jax.random.key(5), 4)
    q, k, v, g = (jax.random.normal(kk, (1, 4, 1024, 64), jnp.bfloat16)
                  for kk in ks)

    def run(off):
        f = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, causal_offset=off,
            segment_ids=(jnp.arange(1024) // 300, jnp.arange(1024) // 300))
        out, vjp = jax.vjp(f, q, k, v)
        return (out, *vjp(g))

    trap = run(0)                      # static -> trapezoid
    full = jax.jit(run)(jnp.int32(0))  # traced -> full grid
    for a, b in zip(trap, full):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_module_gqa_rope_fwd_bwd_on_chip():
    """The round-4 module surface on real hardware: num_kv_heads + RoPE
    through apply_seq_parallel (W=1 mesh) vs the distributed=False
    oracle, forward and parameter gradients."""
    from distributed_dot_product_tpu import DistributedDotProductAttn
    from distributed_dot_product_tpu.models.attention import (
        apply_seq_parallel,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    mesh = seq_mesh(1)
    dim, t = 64, 512
    x = jax.random.normal(jax.random.key(2), (1, t, dim), jnp.float32)

    def mk(dist):
        return DistributedDotProductAttn(
            key_dim=dim, num_heads=8, num_kv_heads=2, causal=True,
            use_rope=True, softmax_impl='flash', distributed=dist)

    m = mk(True)
    params = m.init(jax.random.key(0), x[:, :16], x[:, :16], x[:, :16],
                    None)

    def loss_d(p):
        return jnp.sum(apply_seq_parallel(m, p, mesh, x, x, x, None) ** 2)

    def loss_l(p):
        return jnp.sum(mk(False).apply(p, x, x, x, None) ** 2)

    ld, gd = jax.value_and_grad(loss_d)(params)
    ll, gl = jax.value_and_grad(loss_l)(params)
    np.testing.assert_allclose(float(ld), float(ll), rtol=1e-3)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gl)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0.1,
                                   atol=2e-2)


def test_ring_dropout_segments_matches_flash_on_chip():
    """Ring path carrying dropout + packed segments on the real chip:
    with one seed the global-coordinate hash must reproduce the flash
    path's mask exactly (W=1: one fold, but the Mosaic-compiled kernels
    and the kv_offset plumbing are the real thing)."""
    from distributed_dot_product_tpu import DistributedDotProductAttn
    from distributed_dot_product_tpu.models.attention import (
        apply_seq_parallel,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    mesh = seq_mesh(1)
    dim, t = 64, 512
    x = jax.random.normal(jax.random.key(3), (1, t, dim), jnp.float32)
    seg = (jnp.arange(t)[None] // 150).astype(jnp.int32)

    def mk(impl):
        return DistributedDotProductAttn(
            key_dim=dim, num_heads=4, causal=True, softmax_impl=impl,
            dropout_rate=0.3)

    mo, mf = mk('online'), mk('flash')
    params = mo.init(jax.random.key(0), x[:, :16], x[:, :16], x[:, :16],
                     None)
    oo = apply_seq_parallel(mo, params, mesh, x, x, x, None,
                            segment_ids=seg, dropout_seed=7)
    of = apply_seq_parallel(mf, params, mesh, x, x, x, None,
                            segment_ids=seg, dropout_seed=7)
    np.testing.assert_allclose(np.asarray(oo), np.asarray(of), atol=1e-5)
    od = apply_seq_parallel(mo, params, mesh, x, x, x, None,
                            segment_ids=seg, deterministic=True)
    assert not np.allclose(np.asarray(oo), np.asarray(od))


# --- round-5 surfaces on the chip ----------------------------------------

def test_ring_int8_matches_flash_int8_on_chip():
    """Per-fold int8 quantization through the Mosaic int8 MXU path must
    equal the single-device int8 flash kernel (W=1 ring)."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )
    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    ks = jax.random.split(jax.random.key(11), 3)
    q, k, v = (jax.random.normal(kk, (1, 4, 512, 64), jnp.float32)
               for kk in ks)
    spec = P(None, None, 'seq', None)
    ring = jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=True,
                                       qk_quant='int8'),
        mesh=seq_mesh(1), in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False)
    want = flash_attention(q, k, v, causal=True, qk_quant='int8')
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(want), atol=2e-2)


def test_zigzag_dense_mask_on_chip():
    """Zigzag + dense mask: per-fold column gather composed with the
    positions kernels, Mosaic-compiled."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        local_attention_reference, ring_attention, zigzag_indices,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    t = 512
    ks = jax.random.split(jax.random.key(12), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, t, 32), jnp.float32)
               for kk in ks)
    m = jax.random.bernoulli(jax.random.key(13), 0.3, (1, 1, t, t))
    m = m.at[..., 0].set(False)
    idx = zigzag_indices(t, 1)
    inv = jnp.argsort(idx)
    spec = P(None, None, 'seq', None)
    ring = jax.shard_map(
        lambda a, b, c, d: ring_attention(a, b, c, d, causal=True,
                                          layout='zigzag'),
        mesh=seq_mesh(1), in_specs=(spec,) * 4, out_specs=spec,
        check_vma=False)
    got = ring(q[..., idx, :], k[..., idx, :], v[..., idx, :],
               m[..., idx, :])[..., inv, :]
    want = local_attention_reference(q, k, v, m, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-2)


def test_scanned_lm_trains_and_generates_on_chip():
    """The capstone on hardware: a scanned+remat'd TransformerLM's
    sharded train step improves the loss, and greedy generation through
    the layer-stacked KV caches runs."""
    import optax

    from distributed_dot_product_tpu import TransformerLM, greedy_generate
    from distributed_dot_product_tpu.models.lm import lm_targets
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    from distributed_dot_product_tpu.train import make_lm_train_step
    vocab, t = 64, 256
    lm = TransformerLM(vocab_size=vocab, dim=64, num_heads=4, n_layers=3,
                       scan_layers=True, remat=True)
    toks = jax.random.randint(jax.random.key(0), (1, t), 0, vocab,
                              dtype=jnp.int32)
    tgts = lm_targets(toks)
    params = lm.init(jax.random.key(1), toks[:, :16])
    opt = optax.adam(1e-2)
    step = make_lm_train_step(lm, opt, seq_mesh(1), donate=False,
                              loss_chunk=64)
    ost = opt.init(params)
    losses = []
    for _ in range(3):
        params, ost, loss = step(params, ost, (toks, tgts))
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses
    out = greedy_generate(lm, params, toks[:, :16], steps=4, t_max=64)
    assert out.shape == (1, 4)


def test_sharded_decode_matches_local_on_chip():
    from distributed_dot_product_tpu import DistributedDotProductAttn
    from distributed_dot_product_tpu.models.attention import (
        decode_seq_parallel,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    mesh = seq_mesh(1)
    m = DistributedDotProductAttn(key_dim=64, num_heads=4,
                                  num_kv_heads=2, causal=True,
                                  use_rope=True)
    x = jax.random.normal(jax.random.key(2), (2, 6, 64), jnp.float32)
    p = m.init(jax.random.key(3), x, x, x, None)
    sc = m.make_decode_cache(2, 16)
    lc = m.make_decode_cache(2, 16)
    for t in range(4):
        xt = x[:, t:t + 1]
        sc, so = decode_seq_parallel(m, p, mesh, xt, xt, xt, sc)
        lc, lo = m.apply(p, xt, xt, xt, lc, method='decode')
        np.testing.assert_allclose(np.asarray(so), np.asarray(lo),
                                   atol=2e-2)
    assert int(sc.length) == 4


def test_fused_decode_kernel_compiles_on_chip():
    """The fused Pallas decode step (ops/pallas_decode.py) through the
    Mosaic compiler: parity with the XLA step across GQA/window/int8,
    and the aliased in-place append under a donated jit — the config
    the serving engine runs."""
    from distributed_dot_product_tpu.models.decode import (
        append_kv_slots, decode_step, init_cache, init_slot_cache,
    )
    from distributed_dot_product_tpu.models.decode import append_kv
    b, h, hkv, d, t_max = 4, 8, 2, 64, 512
    ks = jax.random.split(jax.random.key(0), 5)
    q = jax.random.normal(ks[0], (b, h, 1, d), jnp.bfloat16)
    kn = jax.random.normal(ks[1], (b, hkv, 1, d), jnp.bfloat16)
    vn = jax.random.normal(ks[2], (b, hkv, 1, d), jnp.bfloat16)
    kf = jax.random.normal(ks[3], (b, hkv, t_max, d), jnp.bfloat16)
    vf = jax.random.normal(ks[4], (b, hkv, t_max, d), jnp.bfloat16)
    lens = [300, 511, 0, 17]

    def filled():
        c = init_slot_cache(b, hkv, t_max, d, dtype=jnp.bfloat16)
        return append_kv_slots(c, kf, vf,
                               counts=jnp.asarray(lens, jnp.int32))

    for kw in ({}, {'window': 64}):
        cx, ox = decode_step(q, filled(), kn, vn, impl='xla', **kw)
        ck, ok = decode_step(q, filled(), kn, vn, impl='kernel', **kw)
        np.testing.assert_allclose(np.asarray(ok, dtype=np.float32),
                                   np.asarray(ox, dtype=np.float32),
                                   atol=3e-2, rtol=3e-2,
                                   err_msg=str(kw))
        np.testing.assert_array_equal(np.asarray(ck.length),
                                      np.asarray(cx.length))

    # int8 mirror: dequantize-in-kernel vs the XLA s8 einsum.
    ci = init_cache(b, hkv, t_max, d, dtype=jnp.bfloat16,
                    qk_quant='int8')
    ci = append_kv(ci, kf[:, :, :300], vf[:, :, :300])
    cx8, ox8 = decode_step(q, ci, kn, vn, qk_quant='int8', impl='xla')
    ck8, ok8 = decode_step(q, ci, kn, vn, qk_quant='int8',
                           impl='kernel')
    np.testing.assert_array_equal(np.asarray(ck8.k_q),
                                  np.asarray(cx8.k_q))
    np.testing.assert_allclose(np.asarray(ok8, dtype=np.float32),
                               np.asarray(ox8, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)

    # Donated + aliased = the cache buffer must not move between steps
    # (the whole point: no scan-carry or donated-copy round trip).
    step = jax.jit(
        lambda c, q, k, v: decode_step(q, c, k, v, impl='kernel'),
        donate_argnums=(0,))
    c0 = filled()
    c1, _ = step(c0, q, kn, vn)
    ptr0 = c1.k.unsafe_buffer_pointer()
    c2, _ = step(c1, q, kn, vn)
    assert c2.k.unsafe_buffer_pointer() == ptr0, \
        'aliased decode cache was copied between donated steps'
