# -*- coding: utf-8 -*-
"""
Disaggregated multi-chip serving (serve/replica.py + serve/router.py):
the sequence-sharded prefill pool, the prefill→decode KV handoff
through the page pool, router placement (prefix affinity / session
affinity / least-loaded / typed NO_REPLICA), and the ISSUE-12
acceptance — a seeded trace against a 1-router/2-decode-pool topology
on the CPU mesh where every submitted request reconstructs exactly
once across the merged replica logs, goodput is at least the
single-process twin's at 2x offered rate, and a re-submitted
registered prefix lands on the replica already holding its pages.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import slo as obs_slo
from distributed_dot_product_tpu.obs.events import EventLog
from distributed_dot_product_tpu.obs.timeline import reconstruct
from distributed_dot_product_tpu.serve import (
    KernelEngine, LoadGenConfig, PrefillPool, RejectReason,
    RejectedError, RouterConfig, Scheduler, ServeConfig,
    TopologyConfig, VirtualClock, build_serving, default_tenants,
    generate_trace, load_trace, maybe_init_distributed, parse_topology,
    run_trace, save_trace,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry


def _topo(replicas=2, slots=2, t_max=64, page_size=16, vocab=32,
          **kw):
    return TopologyConfig(decode_replicas=replicas, slots=slots,
                          t_max=t_max, page_size=page_size,
                          vocab=vocab, seed=3, **kw)


def _serving(tmp_path, clock, *, replicas=2, threshold=4,
             queue_limit=4, max_new=8, cap=32, **topo_kw):
    return build_serving(
        _topo(replicas=replicas, **topo_kw),
        serve_config=ServeConfig(watchdog=False, queue_limit=queue_limit,
                                 max_new_tokens=max_new),
        router_config=RouterConfig(prefill_threshold=threshold,
                                   prefix_cache_cap=cap),
        clock=clock, log_dir=tmp_path / 'logs')


# -- topology plumbing --------------------------------------------------

def test_parse_topology():
    assert parse_topology('1x2') == (1, 2)
    assert parse_topology('0x1') == (0, 1)
    with pytest.raises(ValueError, match='look like'):
        parse_topology('2-3')
    with pytest.raises(ValueError, match='prefill pools'):
        parse_topology('2x2')
    with pytest.raises(ValueError, match='decode replica'):
        parse_topology('1x0')


def test_maybe_init_distributed_is_a_noop_unconfigured():
    """Without a coordinator the single-process multi-replica mode
    needs no process group — the call must be a no-op, not a hang."""
    assert maybe_init_distributed(environ={}) is False


# -- prefill pool: sequence-sharded KV, bit-identical to local ----------

def test_prefill_pool_kv_bitwise_matches_local_prefill(devices):
    """The sharded projection (rows split over the 'seq' mesh axis)
    writes page contents BITWISE equal to register_prefix's local
    chunked prefill — the row-parallel matmul preserves each row's
    accumulation order, so a handed-off prefix is indistinguishable
    from a locally prefilled one."""
    tokens = (np.arange(1, 25, dtype=np.int32) * 5) % 32
    pf = PrefillPool(t_max=64, page_size=16, vocab=32, seed=3)
    assert pf.n_shards == 8
    handle = pf.build(tokens)
    ref = KernelEngine(slots=2, t_max=64, vocab=32, seed=3,
                       cache_mode='paged', page_size=16,
                       decode_impl='xla')
    ref_pages, ref_n = ref._prefix_registry[
        ref.register_prefix(tokens)]
    assert handle.length == ref_n == len(tokens)
    assert len(handle.pages) == len(ref_pages) == 2
    for sp, rp in zip(handle.pages, ref_pages):
        for pool_name in ('k_pool', 'v_pool'):
            a = np.asarray(getattr(pf.engine.cache, pool_name)[sp])
            b = np.asarray(getattr(ref.cache, pool_name)[rp])
            assert (a == b).all(), (pool_name, sp, rp)
    # Release returns the pages; a second build reuses the pool.
    pf.release(handle)
    assert pf.engine.pool.free_pages == pf.engine.pool.pages
    pf.build(tokens)


def test_adopt_prefix_stream_identity_and_validation(devices):
    """A stream started on a handed-off prefix is BIT-IDENTICAL to the
    same prompt served flat on an identical engine; geometry
    mismatches are typed errors, never silent corruption."""
    tokens = np.arange(1, 20, dtype=np.int32) % 32
    prompt = list(tokens) + [5]
    pf = PrefillPool(t_max=64, page_size=16, vocab=32, seed=3)
    handle = pf.build(tokens)
    dec = KernelEngine(slots=2, t_max=64, vocab=32, seed=3,
                       cache_mode='paged', page_size=16,
                       decode_impl='xla')
    pid = dec.adopt_prefix(pf.engine.cache, handle.pages,
                           handle.length)
    pf.release(handle)
    clock = VirtualClock()
    s1 = Scheduler(dec, ServeConfig(watchdog=False, max_new_tokens=8),
                   clock=clock, registry=MetricsRegistry(),
                   fault_injector=False)
    r1 = s1.submit([prompt[-1]], prefix_id=pid, max_new_tokens=8)
    s1.run_until_idle()
    s1.close()
    flat = KernelEngine(slots=2, t_max=64, vocab=32, seed=3,
                        cache_mode='paged', page_size=16,
                        decode_impl='xla')
    s2 = Scheduler(flat, ServeConfig(watchdog=False, max_new_tokens=8),
                   clock=clock, registry=MetricsRegistry(),
                   fault_injector=False)
    r2 = s2.submit(prompt, max_new_tokens=8)
    s2.run_until_idle()
    s2.close()
    assert s1.results[r1.id].tokens == s2.results[r2.id].tokens
    # Page-size mismatch is typed.
    other = KernelEngine(slots=2, t_max=64, vocab=32, seed=3,
                         cache_mode='paged', page_size=8,
                         decode_impl='xla')
    h2 = pf.build(tokens)
    with pytest.raises(ValueError, match='page-size mismatch'):
        other.adopt_prefix(pf.engine.cache, h2.pages, h2.length)
    with pytest.raises(ValueError, match='source pages'):
        dec.adopt_prefix(pf.engine.cache, h2.pages[:1], h2.length)
    pf.release(h2)


# -- router placement ---------------------------------------------------

def test_router_spreads_load_and_sticks_sessions(tmp_path, devices):
    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=100, queue_limit=8,
                      slots=1)
    try:
        for i in range(4):
            router.submit([1 + i, 2, 3], request_id=f'a{i}')
        loads = router.loads()
        # Least-loaded placement alternates across the two replicas.
        assert all(lo['queued'] + lo['busy'] == 2
                   for lo in loads.values()), loads
        router.run_until_idle()
        # Session affinity: every submit under one session lands on
        # the SAME replica even when the other is emptier.
        for i in range(3):
            router.submit([7, 8, 9 + i], request_id=f's{i}',
                          session='sess-1')
            router.run_until_idle()
        tls = reconstruct(router.pool.logs())
        homes = {tls[f's{i}'].replicas[-1] for i in range(3)}
        assert len(homes) == 1, homes
    finally:
        router.close()


def test_router_no_replica_typed_reject(tmp_path, devices):
    """Every replica queue at its bound => the router sheds with the
    typed NO_REPLICA reason BEFORE any replica's ladder runs — no
    replica log carries a reject, the router's own log does."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, queue_limit=1, slots=1,
                      threshold=100)
    try:
        # Fill: queue_limit=1 per replica and no ticks run, so two
        # submits saturate the topology's admission capacity.
        for i in range(2):
            router.submit([1, 2], request_id=f'f{i}',
                          max_new_tokens=4)
        with pytest.raises(RejectedError) as exc:
            router.submit([1, 2], request_id='shed',
                          max_new_tokens=4)
        assert exc.value.reason is RejectReason.NO_REPLICA
        counters = router.registry.snapshot()['counters']
        assert counters[
            'router.rejected.no_replica{tenant=default}'] == 1
        router.run_until_idle()
    finally:
        router.close()
    tls = reconstruct(router.pool.logs())
    shed = tls['shed']
    assert shed.status == 'rejected'
    assert shed.reason == 'no_replica'
    assert shed.complete, shed.errors
    assert shed.replicas == ['router']   # only the router's log saw it
    # No lifecycle leaked into any replica log.
    for name, path in router.pool.logs():
        if name not in ('router', 'prefill'):
            assert not any(r.get('request_id') == 'shed'
                           for r in obs.read_events(path))


def test_prefix_affinity_routes_to_the_page_holder(tmp_path, devices):
    """ISSUE-12 acceptance (prefix affinity): a re-submitted
    registered prefix lands on the replica already holding its pages
    — shared_pages > 0 there while it decodes, 0 on every other
    replica — and the stream equals the first run's."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=4, max_new=6)
    prompt = list((np.arange(18) * 3 + 1) % 32) + [9]
    try:
        router.submit(prompt, request_id='first')
        router.run_until_idle()
        counters = router.registry.snapshot()['counters']
        assert counters['router.handoffs'] == 1
        tls = reconstruct(router.pool.logs())
        home = tls['first'].replicas[-1]
        assert tls['first'].handoffs == 1

        router.submit(prompt, request_id='again')
        router.step()          # admission attaches the shared prefix
        stats = {r.name: r.engine.cache_stats()
                 for r in router.pool.replicas}
        assert stats[home]['shared_pages'] > 0, stats
        for name, st in stats.items():
            if name != home:
                assert st['shared_pages'] == 0, stats
        router.run_until_idle()
        counters = router.registry.snapshot()['counters']
        assert counters['router.prefix_hits'] == 1
        assert counters['router.handoffs'] == 1   # no second transfer
        tls = reconstruct(router.pool.logs())
        assert tls['again'].replicas[-1] == home
        assert tls['again'].handoffs == 0
        results = router.results
        assert results['again'].tokens == results['first'].tokens
    finally:
        router.close()


def test_prefix_cache_lru_cap_unregisters(tmp_path, devices):
    """Past prefix_cache_cap per replica the least-recently-hit prefix
    is unregistered: its pages free (no rider left) and a later
    identical prompt misses the cluster cache."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, replicas=1, threshold=4,
                      cap=2, max_new=4, t_max=96, slots=2)
    try:
        prompts = [list((np.arange(8) + 7 * j) % 32) + [j + 1]
                   for j in range(3)]
        for j, p in enumerate(prompts):
            router.submit(p, request_id=f'p{j}')
            router.run_until_idle()
        counters = router.registry.snapshot()['counters']
        assert counters['router.handoffs'] == 3
        assert counters['router.prefix_unregistered'] == 1
        assert len(router._prefix_map) == 2
        # The evicted (oldest) prefix misses; a cached one hits.
        router.submit(prompts[0], request_id='again0')
        router.run_until_idle()
        counters = router.registry.snapshot()['counters']
        assert counters['router.handoffs'] == 4      # re-built
        router.submit(prompts[2], request_id='again2')
        router.run_until_idle()
        counters = router.registry.snapshot()['counters']
        assert counters['router.prefix_hits'] == 1
        assert counters['router.handoffs'] == 4      # served by pages
    finally:
        router.close()


def test_router_too_long_prompt_sheds_typed_not_crash(tmp_path,
                                                      devices):
    """A prompt past t_max that also crosses the prefill threshold
    must come out as the replica's typed PROMPT_TOO_LONG reject — the
    prefill pool's own impossibility (ValueError in build) falls
    through to the flat submit path, exactly what the non-routed
    scheduler records for the same prompt."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=4)
    prompt = [(i % 31) + 1 for i in range(70)]     # 70 > t_max=64
    try:
        with pytest.raises(RejectedError) as exc:
            router.submit(prompt, request_id='long')
        assert exc.value.reason is RejectReason.PROMPT_TOO_LONG
        router.run_until_idle()
    finally:
        router.close()
    tl = reconstruct(router.pool.logs())['long']
    assert tl.complete, tl.errors
    assert tl.status == 'rejected' and tl.reason == 'prompt_too_long'


def test_prefix_pin_budget_bounds_the_registry(tmp_path, devices):
    """Distinct long prompts must never pin a replica's whole pool:
    past prefix_pin_fraction of the pages the LRU prefixes unregister
    even under the entry cap, leaving decode headroom."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, replicas=1, threshold=4,
                      cap=100, max_new=4, slots=2)
    try:
        for j in range(4):      # 2 pinned pages per distinct prefix
            p = [int(t) for t in (np.arange(18) + 11 * j) % 32] \
                + [j + 1]
            router.submit(p, request_id=f'p{j}')
            router.run_until_idle()
        eng = router.pool.replicas[0].engine
        budget = eng.pool.pages // 2          # default fraction 0.5
        assert eng.pinned_pages <= budget, (
            f'{eng.pinned_pages} pinned of {eng.pool.pages} pages')
        counters = router.registry.snapshot()['counters']
        assert counters['router.prefix_unregistered'] >= 1
        # Idle, so everything not pinned is free again.
        assert eng.free_pages >= eng.pool.pages - budget
    finally:
        router.close()


# -- ISSUE-12 acceptance: trace through the topology vs the twin --------

def test_trace_topology_acceptance(tmp_path, devices):
    """Tier-1 acceptance: a seeded serve-load trace at 2x the CI
    offered rate through a 1-router/2-decode-pool topology on the CPU
    mesh. Every submitted request reconstructs EXACTLY ONCE across the
    merged replica logs (complete lifecycle or typed reject), routed
    goodput >= the single-process twin's on the byte-identical
    serialized trace, and offloaded requests' timelines span the
    prefill and decode logs."""
    cfg = LoadGenConfig(seed=7, rate=1200.0, requests=48,
                        tenants=default_tenants(2), vocab=64,
                        tick_seconds=0.002)
    trace_path = tmp_path / 'trace.json'
    save_trace(trace_path, generate_trace(cfg))
    serve_cfg = ServeConfig(watchdog=False, queue_limit=12,
                            max_new_tokens=24)

    clock = VirtualClock()
    router = build_serving(
        TopologyConfig(decode_replicas=2, slots=4, t_max=96,
                       page_size=16, vocab=64, seed=0),
        serve_config=serve_cfg,
        router_config=RouterConfig(prefill_threshold=8),
        clock=clock, log_dir=tmp_path / 'topo')
    try:
        res = run_trace(router, load_trace(trace_path), clock,
                        tick_seconds=cfg.tick_seconds)
    finally:
        router.close()
    assert res.accounted
    sources = router.pool.logs()
    assert [n for n, _ in sources][:2] == ['router', 'prefill']

    # Exactly once across the merged logs: one complete timeline per
    # submitted request, classes partition the set.
    tls = reconstruct(sources)
    assert len(tls) == len(res.submitted) == 48
    for rid, tl in tls.items():
        assert tl.complete, (rid, tl.errors)
        assert tl.routes <= 1
    spec = obs_slo.SloSpec(ttft=0.25, per_token=0.05)
    report = obs_slo.goodput(sources, spec)
    assert report.requests == 48
    assert sum(report.counts.values()) == 48

    # A handed-off request's lifecycle spans router + prefill + its
    # decode replica's logs.
    offloaded = [tl for tl in tls.values() if tl.handoffs]
    assert offloaded, 'no prompt crossed the prefill threshold'
    for tl in offloaded:
        assert 'prefill' in tl.replicas and 'router' in tl.replicas
        assert any(r.startswith('r') and r not in ('router',)
                   for r in tl.replicas), tl.replicas

    # The single-process twin (ONE replica's engine) on the identical
    # serialized trace, at the same 2x offered rate.
    clock2 = VirtualClock()
    twin_log = EventLog(tmp_path / 'twin.jsonl', clock=clock2)
    twin = Scheduler(
        KernelEngine(slots=4, t_max=96, vocab=64, seed=0,
                     cache_mode='paged', page_size=16,
                     decode_impl='xla'),
        serve_cfg, clock=clock2, event_log=twin_log,
        registry=MetricsRegistry(), fault_injector=False)
    try:
        res_twin = run_trace(twin, load_trace(trace_path), clock2,
                             tick_seconds=cfg.tick_seconds)
    finally:
        twin.close()
        twin_log.close()
    assert res_twin.accounted
    twin_report = obs_slo.goodput(twin_log.path, spec)
    assert report.goodput_pct >= twin_report.goodput_pct, (
        f'routed {report.goodput_pct:.1f}% < twin '
        f'{twin_report.goodput_pct:.1f}% at 2x offered rate')
    # And the replication actually helps under this overload.
    assert report.counts['met'] > twin_report.counts['met']
