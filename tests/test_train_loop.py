# -*- coding: utf-8 -*-
"""
Resilient-driver invariants, exercised through the deterministic
fault-injection harness (utils/faults.py) — no real preemption or flaky
disk needed:

- kill/resume: a run interrupted by SIGTERM (and separately by a
  simulated crash mid-save) resumes and produces BIT-IDENTICAL per-step
  losses to an uninterrupted run;
- NaN guard: an injected NaN step leaves params/opt_state exactly at
  their step-(S-1) values (update skipped in-program), is counted, and K
  consecutive bad steps trigger rollback to the last checkpoint;
- retention: keep_last=N leaves exactly the N newest finalized step dirs;
- transient checkpoint I/O errors are retried with backoff.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_dot_product_tpu import DistributedDotProductAttn
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.train import make_train_step
from distributed_dot_product_tpu.train_loop import (
    TrainLoopConfig, run_training,
)
from distributed_dot_product_tpu.utils.checkpoint import (
    TrainState, latest_step,
)
from distributed_dot_product_tpu.utils.faults import (
    FaultInjector, FaultPlan, SimulatedCrash,
)

DIM, HEADS, T, B = 16, 2, 16, 2


@pytest.fixture(scope='module')
def rig():
    """One compiled guarded step + deterministic data stream shared by
    every test (initial params are never mutated: donate=False)."""
    mesh = seq_mesh(8)
    model = DistributedDotProductAttn(key_dim=DIM, num_heads=HEADS,
                                      offset=2)
    x0 = jax.random.normal(jax.random.key(0), (B, T, DIM), jnp.float32)
    mask = jnp.zeros((B, T, T), dtype=bool)
    params = model.init(jax.random.key(1), x0, x0, x0, mask)
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(model, optimizer, mesh, donate=False,
                           guard=True)

    def batch_fn(i):
        # Pure function of the step index: the property that makes
        # kill/resume bit-identical (and it is what we assert).
        key = jax.random.fold_in(jax.random.key(2), i)
        x = jax.random.normal(key, (B, T, DIM), jnp.float32)
        target = jnp.zeros_like(x)
        return (x, x, x, mask, target)

    return step, TrainState(0, params, opt_state), batch_fn


def _clean_losses(rig_tuple, num_steps, tmp=None):
    step, state0, batch_fn = rig_tuple
    cfg = TrainLoopConfig(num_steps=num_steps,
                          ckpt_dir=str(tmp) if tmp else None)
    return run_training(step, state0, batch_fn, cfg)


def test_uninterrupted_run_counts_and_saves(rig, tmp_path):
    res = _clean_losses(rig, 4, tmp_path / 'base')
    assert sorted(res.losses) == [0, 1, 2, 3]
    assert res.bad_steps == 0 and res.rollbacks == 0
    assert not res.preempted and res.exit_code == 0
    assert res.state.step == 4
    assert latest_step(tmp_path / 'base') == 4   # final save landed


def test_sigterm_resume_bit_identical(rig, tmp_path):
    """Kill/resume invariant, SIGTERM flavor: preempted at step 3, final
    blocking save, clean 128+15 exit code; the restarted driver resumes
    and every per-step loss equals the uninterrupted run's, bitwise."""
    step, state0, batch_fn = rig
    want = _clean_losses(rig, 6).losses

    ck = str(tmp_path / 'sig')
    inj = FaultInjector(FaultPlan(sigterm_at_step=3))
    cfg = TrainLoopConfig(num_steps=6, ckpt_dir=ck, ckpt_every=2)
    res1 = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res1.preempted and res1.exit_code == 128 + 15
    assert res1.state.step == 3          # steps 0..2 ran, 3 never did
    assert latest_step(ck) == 3          # the final preemption save

    res2 = run_training(step, state0, batch_fn, cfg)   # "restart"
    assert res2.resumed_from == 3 and not res2.preempted
    merged = dict(res1.losses)
    merged.update(res2.losses)
    assert set(merged) == set(want)
    np.testing.assert_array_equal(
        [merged[i] for i in sorted(merged)],
        [want[i] for i in sorted(want)])


def test_crash_mid_save_resume_bit_identical(rig, tmp_path):
    """Kill/resume invariant, crash flavor: the save of step 4 dies
    mid-write (unfinalized orbax dir left behind); the restarted driver
    skips the partial write, resumes from the newest finalized step, and
    reproduces the uninterrupted losses bitwise."""
    step, state0, batch_fn = rig
    want = _clean_losses(rig, 6).losses

    ck = str(tmp_path / 'crash')
    inj = FaultInjector(FaultPlan(crash_in_save_at_step=4))
    cfg = TrainLoopConfig(num_steps=6, ckpt_dir=ck, ckpt_every=2,
                          async_saves=False)
    with pytest.raises(SimulatedCrash):
        run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    # The partial write is on disk but must never be selected.
    import os
    assert any('.orbax-checkpoint-tmp' in n for n in os.listdir(ck))
    assert latest_step(ck) == 2

    res2 = run_training(step, state0, batch_fn, cfg)
    assert res2.resumed_from == 2
    assert set(res2.losses) == {2, 3, 4, 5}   # replayed from step 2
    np.testing.assert_array_equal(
        [res2.losses[i] for i in sorted(res2.losses)],
        [want[i] for i in (2, 3, 4, 5)])


def test_nan_guard_skips_update_and_counts(rig, tmp_path):
    """NaN-guard invariant: with a NaN gradient injected at step S, the
    params/opt_state after step S are EXACTLY those after step S-1, and
    the step is counted as bad (but the run continues)."""
    step, state0, batch_fn = rig
    s_bad = 2
    snapshots = {}
    # Drive manually around the injector to snapshot params per step.
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({s_bad})))
    wrapped = inj.wrap_batch_fn(batch_fn)
    params, opt_state = state0.params, state0.opt_state
    with inj:
        for i in range(4):
            params, opt_state, rec = step(params, opt_state, wrapped(i),
                                          dropout_seed=i)
            rec = jax.device_get(rec)
            snapshots[i] = (params, opt_state, rec)
    assert bool(snapshots[s_bad][2]['bad_step'])
    assert not np.isfinite(snapshots[s_bad][2]['loss'])
    assert all(not bool(snapshots[i][2]['bad_step'])
               for i in (0, 1, 3))
    # params/opt_state after the bad step == after the previous step.
    for tree_bad, tree_prev in zip(snapshots[s_bad][:2],
                                   snapshots[s_bad - 1][:2]):
        for a, b in zip(jax.tree.leaves(tree_bad),
                        jax.tree.leaves(tree_prev)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ...and the guarded step recovered on the next clean batch.
    assert np.isfinite(snapshots[3][2]['loss'])

    # Same invariant through the driver, which must also count it.
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({s_bad})))
    cfg = TrainLoopConfig(num_steps=4, ckpt_dir=str(tmp_path / 'nan'),
                          max_bad_steps=3)
    res = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res.bad_steps == 1 and res.rollbacks == 0
    assert not np.isfinite(res.losses[s_bad])
    assert np.isfinite(res.losses[3])


def test_consecutive_bad_steps_roll_back_to_checkpoint(rig, tmp_path):
    """K consecutive bad steps trigger rollback to the last checkpoint;
    the replayed (clean, fire_once injection) trajectory then matches the
    uninterrupted run exactly."""
    step, state0, batch_fn = rig
    want = _clean_losses(rig, 6).losses

    ck = str(tmp_path / 'roll')
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({2, 3})))
    cfg = TrainLoopConfig(num_steps=6, ckpt_dir=ck, ckpt_every=2,
                          max_bad_steps=2)
    res = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res.bad_steps == 2 and res.rollbacks == 1
    # Replay overwrote the bad records: the surviving per-step losses are
    # the uninterrupted run's, bitwise.
    assert set(res.losses) == set(want)
    np.testing.assert_array_equal(
        [res.losses[i] for i in sorted(res.losses)],
        [want[i] for i in sorted(want)])


def test_rollback_without_checkpoint_restores_initial_state(rig):
    step, state0, batch_fn = rig
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({0, 1})))
    cfg = TrainLoopConfig(num_steps=3, ckpt_dir=None, max_bad_steps=2)
    res = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res.rollbacks == 1 and res.bad_steps == 2
    assert np.isfinite(res.losses[2])
    clean = _clean_losses(rig, 3).losses
    np.testing.assert_array_equal(
        [res.losses[i] for i in sorted(res.losses)],
        [clean[i] for i in sorted(clean)])


def test_tail_rollback_reenters_training(rig):
    """A rollback triggered on the FINAL inflight record (processed
    after the dispatch loop exits) must re-enter training, not return
    'success' short of num_steps."""
    step, state0, batch_fn = rig
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({1, 2})))
    cfg = TrainLoopConfig(num_steps=3, ckpt_dir=None, max_bad_steps=2)
    res = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res.state.step == 3
    assert res.rollbacks == 1 and res.bad_steps == 2
    clean = _clean_losses(rig, 3).losses
    np.testing.assert_array_equal([res.losses[i] for i in range(3)],
                                  [clean[i] for i in range(3)])


def test_bad_step_boundary_not_checkpointed(rig, tmp_path):
    """A boundary save scheduled right after a detected-bad step is
    skipped: for bare-loss steps it would checkpoint poisoned params
    (and keep_last GC would then destroy the good checkpoints)."""
    import os
    step, state0, batch_fn = rig
    ck = str(tmp_path / 'badsave')
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({1})))
    cfg = TrainLoopConfig(num_steps=4, ckpt_dir=ck, ckpt_every=2)
    res = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res.bad_steps == 1 and res.state.step == 4
    assert 'step_000000002' not in os.listdir(ck)   # bad boundary skipped
    assert latest_step(ck) == 4


def test_persistent_divergence_raises(rig):
    step, state0, batch_fn = rig
    # fire_once=False: the NaN comes back after every rollback.
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({0, 1}),
                                  fire_once=False))
    cfg = TrainLoopConfig(num_steps=3, ckpt_dir=None, max_bad_steps=2,
                          max_rollbacks=1)
    with pytest.raises(RuntimeError, match='diverged'):
        run_training(step, state0, batch_fn, cfg, fault_injector=inj)


def test_checkpoint_retention_keep_last(rig, tmp_path):
    """After a run with keep_last=3 and a save every step, exactly the 3
    newest finalized step dirs remain and latest_step still resolves."""
    import os
    step, state0, batch_fn = rig
    ck = str(tmp_path / 'keep')
    cfg = TrainLoopConfig(num_steps=6, ckpt_dir=ck, ckpt_every=1,
                          keep_last=3)
    res = run_training(step, state0, batch_fn, cfg)
    assert res.state.step == 6
    step_dirs = sorted(n for n in os.listdir(ck) if n.startswith('step_'))
    assert step_dirs == ['step_000000004', 'step_000000005',
                         'step_000000006']
    assert latest_step(ck) == 6


def test_guard_refuses_donation():
    """guard=True with explicit donate=True is a contract violation (the
    driver's rollback path reuses earlier buffers); the default resolves
    to the compatible value instead."""
    mesh = seq_mesh(8)
    model = DistributedDotProductAttn(key_dim=DIM, num_heads=HEADS,
                                      offset=2)
    with pytest.raises(ValueError, match='donate=False'):
        make_train_step(model, optax.adam(1e-2), mesh, guard=True,
                        donate=True)
    # Defaulted donate with guard=True builds fine (donate=False picked).
    make_train_step(model, optax.adam(1e-2), mesh, guard=True)


def test_run_training_rejects_donating_step(rig):
    """The default unguarded step donates its params/opt_state buffers —
    incompatible with the driver's save/rollback paths; run_training
    must refuse it up front instead of crashing mid-run on a deleted
    array."""
    _, state0, batch_fn = rig
    mesh = seq_mesh(8)
    model = DistributedDotProductAttn(key_dim=DIM, num_heads=HEADS,
                                      offset=2)
    donating = make_train_step(model, optax.adam(1e-2), mesh)
    with pytest.raises(ValueError, match='non-donating'):
        run_training(donating, state0, batch_fn,
                     TrainLoopConfig(num_steps=1))


def test_preempt_flag_escalates_on_second_signal():
    """The first signal sets the flag AND restores the previous handlers
    so a second signal terminates (e.g. a final save hung on unreachable
    storage) instead of being swallowed."""
    from distributed_dot_product_tpu.train_loop import _PreemptFlag
    flag = _PreemptFlag()
    restored = []
    flag.restore = lambda: restored.append(True)
    flag(15, None)
    assert flag.set and flag.signum == 15 and restored == [True]
    flag(15, None)          # second signal: restore NOT re-run
    assert restored == [True]


def test_failed_async_flush_falls_back_to_blocking_save(
        rig, tmp_path, monkeypatch):
    """A transient error surfacing from the BACKGROUND flush (raised by
    wait, not by save) must not kill the run: the driver abandons the
    pending bookkeeping and lands a blocking final save."""
    from distributed_dot_product_tpu.utils import checkpoint as ckpt_mod

    step, state0, batch_fn = rig
    ck = str(tmp_path / 'flush')
    real_wait = ckpt_mod.wait
    calls = {'n': 0}

    def flaky_wait(path=None):
        calls['n'] += 1
        if calls['n'] == 1:
            raise OSError('injected background-flush failure')
        return real_wait(path)

    monkeypatch.setattr(ckpt_mod, 'wait', flaky_wait)
    cfg = TrainLoopConfig(num_steps=4, ckpt_dir=ck, ckpt_every=2)
    res = run_training(step, state0, batch_fn, cfg)
    assert calls['n'] >= 1          # the failing drain was exercised
    assert res.state.step == 4
    assert latest_step(ck) == 4     # blocking fallback save landed


def test_transient_save_errors_are_retried(rig, tmp_path):
    step, state0, batch_fn = rig
    ck = str(tmp_path / 'retry')
    inj = FaultInjector(FaultPlan(io_error_saves=2))
    cfg = TrainLoopConfig(num_steps=2, ckpt_dir=ck, save_retries=3,
                          save_backoff=0.01)
    res = run_training(step, state0, batch_fn, cfg, fault_injector=inj)
    assert res.state.step == 2 and latest_step(ck) == 2

    # More failures than retries: the error propagates.
    inj = FaultInjector(FaultPlan(io_error_saves=10))
    cfg = TrainLoopConfig(num_steps=2, ckpt_dir=str(tmp_path / 'retry2'),
                          save_retries=1, save_backoff=0.01)
    with pytest.raises(OSError):
        run_training(step, state0, batch_fn, cfg, fault_injector=inj)
