# -*- coding: utf-8 -*-
"""
Load/SLO observatory acceptance (tier-1) + loadgen unit tests.

The acceptance scenario (ISSUE 9): a seeded open-loop loadgen run over
the scheduler WITH FAULTS INJECTED yields a goodput report computed
from the event log ALONE in which

- every submitted request is classified exactly once
  (met + missed_* + rejected + incomplete == submitted),
- per-tenant counts sum to the total,
- the same seed reproduces the identical report,
- and /metrics exposes nonzero tenant-labeled TTFT histograms for at
  least two tenants.

Everything runs in virtual time: the scheduler, the event log and the
trace share one injectable clock, so minutes of simulated traffic cost
milliseconds and the report is bit-reproducible.
"""

import urllib.request

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import slo as obs_slo
from distributed_dot_product_tpu.obs.exporter import (
    MetricsServer, render_prometheus,
)
from distributed_dot_product_tpu.serve import (
    KernelEngine, LoadGenConfig, ServeConfig, TenantSpec, VirtualClock,
    default_tenants, generate_trace, run_load,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

SPEC = obs_slo.SloSpec(ttft=0.25, per_token=0.05)


# -- trace generation ---------------------------------------------------

def test_trace_is_seeded_and_replayable():
    cfg = LoadGenConfig(seed=11, rate=300.0, requests=40)
    a, b = generate_trace(cfg), generate_trace(cfg)
    assert [x.at for x in a] == [x.at for x in b]
    assert [x.request_id for x in a] == [x.request_id for x in b]
    assert [x.tenant for x in a] == [x.tenant for x in b]
    assert [x.max_new_tokens for x in a] == [x.max_new_tokens for x in b]
    assert all((x.prompt == y.prompt).all() for x, y in zip(a, b))
    # A different seed is a different trace.
    c = generate_trace(LoadGenConfig(seed=12, rate=300.0, requests=40))
    assert [x.at for x in a] != [x.at for x in c]


def test_trace_respects_tenant_shapes_and_shares():
    tenants = [TenantSpec('small', share=3.0, prompt_lo=1, prompt_hi=4,
                          new_lo=2, new_hi=4),
               TenantSpec('big', share=1.0, prompt_lo=8, prompt_hi=16,
                          new_lo=8, new_hi=16)]
    cfg = LoadGenConfig(seed=0, rate=100.0, requests=200,
                        tenants=tenants)
    trace = generate_trace(cfg)
    by_tenant = {'small': [], 'big': []}
    for a in trace:
        by_tenant[a.tenant].append(a)
        spec = tenants[0] if a.tenant == 'small' else tenants[1]
        assert spec.prompt_lo <= len(a.prompt) <= spec.prompt_hi
        assert spec.new_lo <= a.max_new_tokens <= spec.new_hi
    # 3:1 shares: the split lands near 150/50 (seeded, not flaky).
    assert len(by_tenant['small']) > 2 * len(by_tenant['big'])
    # Heavy tail: the bulk of draws sits in the lower half of the range.
    lens = sorted(len(a.prompt) for a in by_tenant['big'])
    assert lens[len(lens) // 2] <= (8 + 16) // 2


def test_bursty_arrivals_cluster_but_keep_the_mean_rate():
    rate = 200.0
    po = generate_trace(LoadGenConfig(seed=5, rate=rate, requests=400))
    # burst_dwell small enough that 400 arrivals cross MANY ON/OFF
    # cycles — the long-run rate only converges over whole cycles.
    bu = generate_trace(LoadGenConfig(seed=5, rate=rate, requests=400,
                                      arrival='bursty',
                                      burst_factor=8.0,
                                      burst_dwell_s=0.02))
    span_po = po[-1].at - po[0].at
    span_bu = bu[-1].at - bu[0].at
    # Long-run offered rate stays ~rate for both processes...
    assert 400 / span_bu == pytest.approx(rate, rel=0.5)
    assert 400 / span_po == pytest.approx(rate, rel=0.3)
    # ...but the bursty one clusters: its median inter-arrival gap is
    # far below Poisson's (arrivals ride ON windows at rate*factor).
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    gaps = lambda tr: [b.at - a.at  # noqa: E731
                       for a, b in zip(tr, tr[1:])]
    assert med(gaps(bu)) < 0.5 * med(gaps(po))


def test_config_validation():
    with pytest.raises(ValueError, match='rate'):
        generate_trace(LoadGenConfig(rate=0.0))
    with pytest.raises(ValueError, match='arrival'):
        generate_trace(LoadGenConfig(arrival='fractal'))
    with pytest.raises(ValueError, match='burst_factor'):
        generate_trace(LoadGenConfig(arrival='bursty',
                                     burst_factor=0.5))
    with pytest.raises(ValueError, match='TenantSpec'):
        generate_trace(LoadGenConfig(tenants=[]))


# -- the acceptance scenario -------------------------------------------

def _engine():
    return KernelEngine(slots=3, t_max=64, vocab=32, heads=2,
                        head_dim=4, prefill_chunk=4, seed=5,
                        decode_impl='xla')


def _cfg(seed=9):
    return LoadGenConfig(seed=seed, rate=500.0, requests=30,
                         tenants=default_tenants(2), vocab=32,
                         tick_seconds=0.002)


def _run_faulted(tmp_path, tag):
    """One seeded loadgen run with the NaN fault armed, fully virtual
    (scheduler + event log share the clock)."""
    clock = VirtualClock()
    log = obs.EventLog(tmp_path / f'{tag}.jsonl', clock=clock)
    registry = MetricsRegistry()
    injector = ServeFaultInjector(
        ServeFaultPlan(nan_at_step=4, nan_slot=1))
    res = run_load(
        _cfg(), engine=_engine(),
        serve_config=ServeConfig(queue_limit=6, max_new_tokens=24,
                                 watchdog=False,
                                 evict_before_reject=False),
        registry=registry, event_log=log, clock=clock,
        fault_injector=injector)
    log.close()
    return res, log.path, registry


def test_goodput_acceptance_under_faults(tmp_path, devices):
    res, log_path, registry = _run_faulted(tmp_path, 'a')

    # The log itself is schema-clean.
    _, errors = obs.validate_file(log_path)
    assert errors == [], errors

    report = obs_slo.goodput(log_path, SPEC)

    # Every submitted request classified EXACTLY once, from the log
    # alone: the classes partition the submitted set.
    assert res.accounted
    assert report.requests == len(res.submitted)
    assert sum(report.counts.values()) == report.requests
    assert set(report.by_request) == {rid for rid, _ in res.submitted}

    # Per-tenant counts sum back to the aggregate, class by class.
    assert len(report.per_tenant) >= 2
    for cls in obs_slo.CLASSES:
        assert sum(tb['counts'][cls]
                   for tb in report.per_tenant.values()) \
            == report.counts[cls], cls
    assert sum(tb['requests'] for tb in report.per_tenant.values()) \
        == report.requests

    # The armed fault actually fired and is visible in the SAME log.
    records = obs.read_events(log_path)
    assert any(r['event'] == 'serve.quarantine' for r in records)

    # Same seed -> byte-identical report (fresh engine, fresh log,
    # fresh injector).
    res2, log2, _ = _run_faulted(tmp_path, 'b')
    report2 = obs_slo.goodput(log2, SPEC)
    assert report.to_dict() == report2.to_dict()

    # /metrics exposes nonzero tenant-labeled TTFT histograms for both
    # tenants (live per-tenant goodput for an external Prometheus).
    with MetricsServer(registry) as srv:
        with urllib.request.urlopen(srv.url + '/metrics',
                                    timeout=5) as resp:
            text = resp.read().decode()
    assert render_prometheus(registry) == text
    for tenant in ('t0', 't1'):
        line = next((ln for ln in text.splitlines()
                     if ln.startswith('ddp_serve_ttft_seconds_sum'
                                      f'{{tenant="{tenant}"}}')), None)
        assert line is not None, f'no tenant-labeled TTFT for {tenant}'
        assert float(line.split()[-1]) > 0, line
    # Tenant-labeled queue-wait and admit counters ride along.
    assert 'ddp_serve_queue_wait_seconds_sum{tenant="t0"}' in text
    assert 'ddp_serve_admitted_total{tenant="t0"}' in text


def test_open_loop_overload_sheds_typed_and_accounts(tmp_path, devices):
    """Overload (rate far past service capacity, tiny queue): the
    ladder sheds with typed rejects; the report still partitions the
    submitted set and the rejected class is tenant-attributed."""
    clock = VirtualClock()
    log = obs.EventLog(tmp_path / 'overload.jsonl', clock=clock)
    cfg = LoadGenConfig(seed=3, rate=5000.0, requests=40,
                        tenants=default_tenants(2), vocab=32)
    res = run_load(
        cfg, engine=_engine(),
        serve_config=ServeConfig(queue_limit=4, max_new_tokens=24,
                                 watchdog=False,
                                 evict_before_reject=False),
        registry=MetricsRegistry(), event_log=log, clock=clock)
    log.close()
    assert res.rejected_at_submit, 'overload never shed anything'
    report = obs_slo.goodput(log.path, SPEC)
    assert report.requests == len(res.submitted)
    assert sum(report.counts.values()) == report.requests
    assert report.counts['rejected'] >= len(res.rejected_at_submit)
    rej_by_tenant = sum(tb['counts']['rejected']
                       for tb in report.per_tenant.values())
    assert rej_by_tenant == report.counts['rejected']


def test_virtual_time_latencies_are_exact(tmp_path, devices):
    """The whole point of the virtual clock: latency observations are
    tick arithmetic, not wall noise. A lone request admitted into an
    idle scheduler sees queue_wait == 0 and ttft == one tick per
    prefill chunk + one decode tick."""
    clock = VirtualClock()
    log = obs.EventLog(tmp_path / 'exact.jsonl', clock=clock)
    cfg = LoadGenConfig(seed=0, rate=10.0, requests=1,
                        tenants=[TenantSpec('only', prompt_lo=5,
                                            prompt_hi=5, new_lo=4,
                                            new_hi=4)],
                        vocab=32, tick_seconds=0.01)
    run_load(cfg, engine=_engine(),
             serve_config=ServeConfig(queue_limit=4,
                                      max_new_tokens=8,
                                      watchdog=False),
             registry=MetricsRegistry(), event_log=log, clock=clock)
    log.close()
    (tl,) = obs.reconstruct(log.path).values()
    assert tl.complete and tl.status == 'completed'
    assert tl.queue_wait == 0.0
    # One scheduler tick runs admit -> prefill chunk -> decode with
    # `now` read at tick start and the clock advancing AFTER the tick:
    # an idle scheduler admits, prefills the 4-wide chunk and emits
    # the first token inside the arrival tick, so virtual TTFT is
    # exactly 0 — waiting costs ticks, in-tick work does not.
    assert tl.ttft == 0.0
    assert all(g == pytest.approx(0.01) for g in tl.token_gaps)
    assert len(tl.token_gaps) == 3          # 4 tokens, 3 gaps


# -- trace serialization (save_trace / load_trace) ----------------------

def test_trace_save_load_round_trip_exact(tmp_path):
    """A serialized trace reloads to the last bit — every float, id,
    prompt token and budget — so the identical request stream can
    drive a router topology and its single-process twin byte for
    byte."""
    from distributed_dot_product_tpu.serve import load_trace, save_trace

    cfg = LoadGenConfig(
        seed=11, rate=700.0, requests=32, arrival='bursty',
        tenants=[TenantSpec('t0', share=1.0, deadline_s=0.4),
                 TenantSpec('t1', share=2.0)])
    trace = generate_trace(cfg)
    path = tmp_path / 'trace.json'
    save_trace(path, trace, note='round-trip test')
    loaded = load_trace(path)
    assert len(loaded) == len(trace)
    for a, b in zip(trace, loaded):
        assert b.at == a.at                      # exact, not approx
        assert b.request_id == a.request_id
        assert b.tenant == a.tenant
        assert b.prompt.dtype == np.int32
        assert (b.prompt == a.prompt).all()
        assert b.max_new_tokens == a.max_new_tokens
        assert b.deadline_s == a.deadline_s
    # Serialization is deterministic: same trace, same bytes.
    path2 = tmp_path / 'trace2.json'
    save_trace(path2, loaded, note='round-trip test')
    assert path.read_bytes() == path2.read_bytes()


def test_trace_load_rejects_bad_schema_and_malformed(tmp_path):
    import json

    from distributed_dot_product_tpu.serve import load_trace, save_trace

    p = tmp_path / 'bad_schema.json'
    p.write_text('{"schema": 999, "arrivals": []}')
    with pytest.raises(ValueError, match='schema'):
        load_trace(p)
    trace = generate_trace(LoadGenConfig(seed=1, requests=2))
    good = tmp_path / 'good.json'
    save_trace(good, trace)
    payload = json.loads(good.read_text())
    del payload['arrivals'][1]['prompt']
    mangled = tmp_path / 'mangled.json'
    mangled.write_text(json.dumps(payload))
    with pytest.raises(ValueError, match='arrival 1'):
        load_trace(mangled)


def test_saved_trace_drives_identical_run(tmp_path, devices):
    """Generated and reloaded traces produce the SAME results dict
    through a scheduler — the twin-comparison precondition."""
    from distributed_dot_product_tpu.serve import (
        Scheduler, load_trace, run_trace, save_trace,
    )

    cfg = LoadGenConfig(seed=5, rate=400.0, requests=16,
                        tick_seconds=0.002)
    trace = generate_trace(cfg)
    path = tmp_path / 'trace.json'
    save_trace(path, trace)

    def run(tr):
        clock = VirtualClock()
        sched = Scheduler(
            KernelEngine(slots=2, t_max=64, decode_impl='xla'),
            ServeConfig(watchdog=False, queue_limit=8,
                        max_new_tokens=24),
            clock=clock, registry=MetricsRegistry(),
            fault_injector=False)
        try:
            res = run_trace(sched, tr, clock,
                            tick_seconds=cfg.tick_seconds)
        finally:
            sched.close()
        return {rid: (r.status, tuple(r.tokens))
                for rid, r in res.results.items()}

    assert run(trace) == run(load_trace(path))
