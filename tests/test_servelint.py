# -*- coding: utf-8 -*-
"""
servelint (analysis/protolint.py, conclint.py, determlint.py) — the
serving-layer static-analysis families' own gate and rule tests.

Mirrors tests/test_graphlint.py's structure:

- **Clean-tree gate**: the three families report ZERO active violations
  over the repo — every convention (closed event vocabulary, guarded-by
  lock discipline, virtual-clock tick purity) is a standing CI contract.
- **Negative fixtures, one per family** (tests/graphlint_fixtures/
  serve/): each seeded regression line carries a ``# VIOLATION: <rule>``
  marker, so the assertions cannot drift from the files.
- **CLI**: exit 1 over the fixture set with every family represented;
  ``--changed-only`` mechanics; the f32-accum rule over the full
  registry reporting ZERO records (the former flax-Dense waived debt
  is paid — the owned dense accumulates in f32 at every dtype).
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_dot_product_tpu.analysis import (
    active_violations, run_analysis,
)
from distributed_dot_product_tpu.analysis import (
    conclint, determlint, protolint,
)

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, 'tests', 'graphlint_fixtures', 'serve')

SERVELINT_RULES = (list(protolint.PROTO_RULES) + list(conclint.CONC_RULES)
                   + list(determlint.DETERM_RULES))


def _expected(path):
    """``{(rule, line)}`` from the fixture's own ``# VIOLATION: rule``
    markers — the file annotates its seeded regressions."""
    out = set()
    with open(path, encoding='utf-8') as f:
        for i, line in enumerate(f, 1):
            if '# VIOLATION:' in line:
                rule = line.split('# VIOLATION:')[1].strip().split()[0]
                out.add((rule, i))
    return out


# -- clean-tree gate ----------------------------------------------------

def test_servelint_clean_tree_gate():
    """Zero ACTIVE servelint violations repo-wide: emit sites match the
    schema, annotated fields stay behind their locks, threads are
    daemon+named, tick paths stay on the injected clock."""
    violations = run_analysis(rules=SERVELINT_RULES, jaxpr=False)
    active = active_violations(violations)
    assert active == [], '\n'.join(v.render() for v in active)


def test_real_time_contract_covers_the_waived_sites():
    """The determlint allowlist is load-bearing: with the scheduler /
    loadgen entries removed, the closure DOES flag their deliberate
    real-time reads — the contract table is what keeps the tree green,
    not a dead rule."""
    import unittest.mock as mock
    table = dict(determlint.REAL_TIME_CONTRACT)
    table['serve/scheduler.py'] = {}
    table['serve/loadgen.py'] = {}
    with mock.patch.object(determlint, 'REAL_TIME_CONTRACT', table):
        pkg = os.path.join(REPO, 'distributed_dot_product_tpu')
        vs = determlint.lint_paths([os.path.join(pkg, 'serve')],
                                   repo_root=REPO)
    assert {v.rule for v in vs} == {'tick-determinism'}
    hit_files = {os.path.basename(v.file) for v in vs}
    assert hit_files == {'scheduler.py', 'loadgen.py'}, hit_files


# -- negative fixtures --------------------------------------------------

@pytest.mark.parametrize('fixture, linter', [
    ('fx_proto_events.py', protolint),
    ('fx_conc_guarded.py', conclint),
    ('fx_tick_clock.py', determlint),
])
def test_rule_catches_fixture(fixture, linter):
    path = os.path.join(FIXTURES, fixture)
    violations = linter.lint_file(path, repo_root=REPO)
    got = {(v.rule, v.line) for v in violations}
    want = _expected(path)
    assert want == got, (f'{fixture}: expected exactly {sorted(want)}, '
                         f'got {sorted(got)}')
    assert all(v.file and v.file.endswith(fixture) for v in violations)
    assert not any(v.allowed for v in violations)


def test_determlint_transitive_closure_reaches_helper():
    """The sleep lives in a helper the tick root calls — the closure,
    not the root body, is the enforcement surface."""
    path = os.path.join(FIXTURES, 'fx_tick_clock.py')
    vs = determlint.lint_file(path, repo_root=REPO)
    assert any('time.sleep' in v.message and '_throttle' in v.message
               for v in vs), '\n'.join(v.render() for v in vs)


def test_conclint_locked_suffix_and_pragma_are_exempt():
    path = os.path.join(FIXTURES, 'fx_conc_guarded.py')
    vs = conclint.lint_file(path, repo_root=REPO)
    lines = {v.line for v in vs}
    with open(path, encoding='utf-8') as f:
        src = f.readlines()
    locked_line = next(i for i, l in enumerate(src, 1)
                       if '_compact_locked' in l and 'def' in l)
    assert not any(locked_line <= ln <= locked_line + 2 for ln in lines)


# -- CLI ----------------------------------------------------------------

def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.run(
        [sys.executable, '-m', 'distributed_dot_product_tpu.analysis',
         *args], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=540)


def test_cli_nonzero_on_servelint_fixtures():
    """Exit 1 over the fixture set with each family represented —
    including the planted unknown event kind, the off-lock write and
    the time.time() in a tick path (the acceptance criteria trio)."""
    res = _cli('--no-jaxpr',
               os.path.join('tests', 'graphlint_fixtures', 'serve',
                            'fx_proto_events.py'),
               os.path.join('tests', 'graphlint_fixtures', 'serve',
                            'fx_conc_guarded.py'),
               os.path.join('tests', 'graphlint_fixtures', 'serve',
                            'fx_tick_clock.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    for rule in ('event-vocab', 'event-fields', 'reject-reason',
                 'guarded-by', 'thread-discipline', 'tick-determinism'):
        assert rule in res.stdout, f'{rule} missing from CLI output'


def test_cli_rule_filter_runs_single_family():
    res = _cli('--no-jaxpr', '--rule', 'guarded-by',
               os.path.join('tests', 'graphlint_fixtures', 'serve',
                            'fx_conc_guarded.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    assert 'guarded-by' in res.stdout
    assert 'thread-discipline' not in res.stdout


def test_cli_list_rules_names_servelint():
    res = _cli('--list-rules')
    assert res.returncode == 0
    for rule in SERVELINT_RULES:
        assert rule in res.stdout


def test_cli_changed_only_bad_ref_is_usage_error():
    res = _cli('--changed-only', 'definitely-not-a-ref')
    assert res.returncode == 2, res.stdout + res.stderr
    assert 'changed-only' in res.stderr


def test_cli_changed_only_rejects_explicit_paths():
    res = _cli('--changed-only', 'HEAD', 'distributed_dot_product_tpu')
    assert res.returncode == 2


def test_changed_files_mechanics():
    from distributed_dot_product_tpu.analysis.__main__ import (
        changed_files,
    )
    files = changed_files('HEAD')
    assert all(os.path.isfile(f) and f.endswith('.py') for f in files)
    with pytest.raises(RuntimeError):
        changed_files('definitely-not-a-ref')


# -- f32-accum: zero debt, waived or active -----------------------------

@pytest.mark.slow
def test_f32_accum_json_reports_zero_records(devices):
    """The flax Dense bf16-accum debt (ROADMAP item 3a) is PAID — the
    owned dense accumulates in f32 at every dtype, so the f32-accum
    rule over the full registry (bf16 and int8-weight twins included)
    reports NOTHING, allowed or active, and the CLI exits 0."""
    res = _cli('--no-ast', '--format', 'json', '--rule', 'f32-accum')
    assert res.returncode == 0, res.stdout + res.stderr
    records = json.loads(res.stdout)
    assert records == [], records


def test_bf16_variants_trace_clean_inline(devices):
    """In-process twin of the slow CLI check: the serving-dtype
    entries trace CLEAN — the owned dense (models/dense.py) retired
    the flax-Dense f32-accum debt these entries used to waive, so
    they report zero violations (waived or otherwise); the int8-weight
    twin rides along to pin the s8×s8→s32 path."""
    from distributed_dot_product_tpu.analysis.jaxpr_rules import (
        lint_entrypoints,
    )
    from distributed_dot_product_tpu.analysis.registry import (
        default_entrypoints,
    )
    entries = default_entrypoints()
    subset = {name: entries[name] for name in
              ('attention.fwd_flash_bf16', 'lm.loss_bf16',
               'attention.fwd_flash_wq8')}
    vs = lint_entrypoints(subset)
    assert vs == [], '\n'.join(v.render() for v in vs)
