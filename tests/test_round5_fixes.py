# -*- coding: utf-8 -*-
"""
Round-5 advisor-finding regressions (ADVICE.md round 4):

1. ``make_train_step`` must REFUSE to run a dropout-enabled module
   without an explicit ``dropout_seed`` (a silent constant seed would
   reuse one dropout mask every step).
2. ``flash_softmax_mode='bounded'`` combined with dropout/ALiBi/int8
   canonicalizes to the exact kernel BEFORE the beyond-cap chunk
   eligibility check, so long causal sequences still take the chunked
   trapezoid grid.
3. ``prefill`` supports packed segments (parity with ``decode``).
4. ``append_kv`` under jit: an overflowing append leaves the buffers
   unchanged (no silent last-slot corruption) while ``length`` advances
   past ``t_max`` as a detectable flag.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributed_dot_product_tpu.ops.pallas_attention as pa
from distributed_dot_product_tpu import DistributedDotProductAttn
from distributed_dot_product_tpu.models.decode import append_kv, init_cache
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.train import make_train_step


# ---------------------------------------------------------------------------
# 1. dropout-enabled modules require an explicit seed
# ---------------------------------------------------------------------------

def _dropout_step():
    mesh = seq_mesh(8)
    dim, heads, t, b = 32, 4, 16, 2
    model = DistributedDotProductAttn(
        key_dim=dim, num_heads=heads, softmax_impl='flash',
        dropout_rate=0.1)
    x = jax.random.normal(jax.random.key(0), (b, t, dim), jnp.float32)
    target = jax.random.normal(jax.random.key(1), (b, t, dim), jnp.float32)
    params = model.init(jax.random.key(2), x, x, x, None)
    optimizer = optax.adam(1e-2)
    step = make_train_step(model, optimizer, mesh, donate=False)
    return step, params, optimizer.init(params), (x, x, x, None, target)


def test_train_step_requires_seed_with_dropout():
    step, params, opt_state, batch = _dropout_step()
    with pytest.raises(ValueError, match='dropout_seed'):
        step(params, opt_state, batch)
    # With the seed, the same step runs.
    _, _, loss = step(params, opt_state, batch, dropout_seed=0)
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# 2. bounded + dropout canonicalizes before beyond-cap chunking
# ---------------------------------------------------------------------------

def test_bounded_with_dropout_still_chunks_beyond_cap(monkeypatch):
    """'bounded' with dropout always resolves to the exact kernel — the
    resolution must happen before the chunk-eligibility check, or long
    causal sequences silently run the slow full grid (ADVICE round 4)."""
    monkeypatch.setattr(pa, '_TRAP_ON_INTERPRET', True)
    monkeypatch.setattr(pa, '_TRAP_MAX_PAIRS', 8)
    # Tiny blocks so T=96 spans several Q blocks (at natural block sizes
    # one block covers it and no chunking can trigger at test scale).
    monkeypatch.setattr(pa, '_block_sizes', lambda *a, **k: (16, 16))
    seen = []
    orig = pa._trap_chunk_bounds

    def spy(*args, **kw):
        bounds = orig(*args, **kw)
        seen.append(bounds)
        return bounds

    monkeypatch.setattr(pa, '_trap_chunk_bounds', spy)
    ks = jax.random.split(jax.random.key(5), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 96, 16)) for kk in ks)
    out_b = pa.flash_attention(q, k, v, causal=True,
                               softmax_mode='bounded',
                               dropout_rate=0.25, dropout_seed=3)
    assert any(len(b) > 1 for b in seen), (
        'bounded+dropout forward never took the beyond-cap chunking path')
    out_e = pa.flash_attention(q, k, v, causal=True, softmax_mode='exact',
                               dropout_rate=0.25, dropout_seed=3)
    np.testing.assert_array_equal(np.asarray(out_b), np.asarray(out_e))


# ---------------------------------------------------------------------------
# 3. prefill packed segments == causal forward with segment_ids
# ---------------------------------------------------------------------------

def test_prefill_segments_matches_causal_forward():
    b, t, dim = 2, 48, 32
    model = DistributedDotProductAttn(
        key_dim=dim, num_heads=2, causal=True, distributed=False,
        softmax_impl='flash')
    x = jax.random.normal(jax.random.key(0), (b, t, dim), jnp.float32)
    seg = jnp.broadcast_to((jnp.arange(t) // 20)[None], (b, t)
                           ).astype(jnp.int32)
    params = model.init(jax.random.key(1), x, x, x, None)
    want = model.apply(params, x, x, x, None, segment_ids=seg)

    cache = model.make_decode_cache(b, t)
    cache, got = model.apply(params, x, x, x, cache, seg, seg,
                             method='prefill')
    assert int(cache.length) == t
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_prefill_segments_requires_seg_cache():
    b, t, dim = 1, 8, 16
    model = DistributedDotProductAttn(key_dim=dim, causal=True,
                                      distributed=False)
    x = jnp.ones((b, t, dim), jnp.float32)
    params = model.init(jax.random.key(0), x, x, x, None)
    cache = model.make_decode_cache(b, t)
    with pytest.raises(ValueError, match='seg_cache'):
        model.apply(params, x, x, x, cache,
                    jnp.zeros((b, t), jnp.int32), method='prefill')


# ---------------------------------------------------------------------------
# 4. jitted append_kv overflow: buffers intact, length flags it
# ---------------------------------------------------------------------------

def test_append_kv_jit_overflow_no_corruption():
    b, hkv, t_max, d = 1, 1, 4, 8
    cache = init_cache(b, hkv, t_max, d, dtype=jnp.float32,
                       qk_quant='int8')
    step = jax.jit(append_kv)
    for i in range(6):   # two past the cap
        kv = jnp.full((b, hkv, 1, d), float(i + 1), jnp.float32)
        cache = step(cache, kv, kv)
    # length advanced past t_max: the detectable overflow flag.
    assert int(cache.length) == 6 > t_max
    # Buffers hold exactly the first t_max appends — the overflowing
    # writes were dropped, nothing clamped onto the last slot.
    want = np.arange(1, t_max + 1, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(cache.k[0, 0, :, 0]), want)
    np.testing.assert_array_equal(np.asarray(cache.v[0, 0, :, 0]), want)
    # The int8 mirror followed the same guard.
    np.testing.assert_array_equal(
        np.asarray(cache.k_q[0, 0, :, 0]), np.full(t_max, 127, np.int8))
    np.testing.assert_allclose(
        np.asarray(cache.k_scale[0, 0, :, 0]), want / 127.0, rtol=1e-6)
