# -*- coding: utf-8 -*-
"""
Multi-host launch-path test.

The reference's multi-node story is ``horovodrun -np N --mpi python ...``
(reference README.md:77,173-176): N OS processes, one GPU each, joined by
MPI. The TPU-native equivalent is one process per host joined by
``jax.distributed.initialize`` (wrapped by ``comm.init``), after which the
same SPMD programs run unchanged over the global mesh.

This test actually exercises that path: it spawns 2 localhost processes
("hosts") of 4 virtual CPU devices each, has them form one 8-device mesh,
runs ONE full training step, and checks the loss equals the identical
single-process 8-device run — proving the multi-host wiring changes
nothing about the math.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


@pytest.mark.slow
def test_two_process_mesh_matches_single_process(tmp_path):
    # Ephemeral port: bind-and-release so concurrent runs don't collide on
    # a fixed coordinator address.
    import socket
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env['PYTHONPATH'] = _REPO + os.pathsep + env.get('PYTHONPATH', '')
    worker = os.path.join(_HERE, 'multihost_worker.py')
    ckpt_dir = str(tmp_path / 'ckpt')
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), '2', str(port), ckpt_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=_REPO)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f'worker failed:\n{out}'

    joined = '\n'.join(outs)
    line = [ln for ln in joined.splitlines()
            if ln.startswith('MULTIHOST_LOSS=')]
    assert line, joined
    multi_loss = float(line[0].split('=', 1)[1])

    # Single-process oracle on the conftest-provided 8-device CPU mesh.
    sys.path.insert(0, _HERE)   # plain `pytest` doesn't put tests/ on path
    from multihost_worker import run_step
    single_loss = run_step(8)
    np.testing.assert_allclose(multi_loss, single_loss, rtol=1e-6)


@pytest.mark.slow
def test_multihost_benchmark_aggregation(tmp_path):
    """``benchmark.py --multihost``: 2 localhost processes × 4 virtual CPU
    devices form one 8-device mesh; per-process measurements are
    allgathered and process 0 writes ONE averaged record — the reference's
    MPI.gather-to-rank-0 measurement surface (reference
    benchmark.py:104-117)."""
    import json
    import socket
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env['PYTHONPATH'] = _REPO + os.pathsep + env.get('PYTHONPATH', '')
    out_file = str(tmp_path / 'bench.json')

    def code(pid):
        argv = ['benchmark.py', '--multihost', '--mode', 'train',
                '--seq-len', '64', '--iters', '1', '--attn-impl', 'flash',
                '--heads', '4', '--num-processes', '2',
                '--process-id', str(pid),
                '--coordinator', f'127.0.0.1:{port}', '--file', out_file]
        return ('import sys; '
                'from distributed_dot_product_tpu._compat import '
                'ensure_cpu_devices; ensure_cpu_devices(4); '
                f'sys.argv = {argv!r}; '
                'import benchmark; benchmark.main()')

    procs = [subprocess.Popen([sys.executable, '-c', code(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              env=env, cwd=_REPO)
             for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f'benchmark process failed:\n{out}'

    with open(out_file) as f:
        records = json.load(f)
    assert len(records) == 1, records       # process 0 is the only writer
    rec = records[0]
    assert rec['n_processes'] == 2
    assert rec['world'] == 8                # one global mesh, both hosts
    assert rec['step_time'] > 0 and np.isfinite(rec['step_gflops_per_chip'])
