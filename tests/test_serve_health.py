# -*- coding: utf-8 -*-
"""
Watchdog + health surface (serve/health.py) and the NaN-slot
quarantine: a stuck compiled step is detected from OUTSIDE the loop and
recovery is an explicit readiness transition; a poisoned slot is
quarantined with every other slot's stream bit-identical.

The watchdog measures real wall time, so these tests use real (small)
sleeps with generous margins rather than the virtual clock.
"""

import time

import numpy as np

from distributed_dot_product_tpu.serve import (
    HealthMonitor, KernelEngine, Readiness, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.serve.health import Liveness
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

SLOTS, T_MAX, VOCAB = 3, 32, 16


def _warm_engine(**kw):
    """Engine with all three programs compiled and slots re-zeroed, so
    compile time can't masquerade as a stall in watchdog tests."""
    eng = KernelEngine(slots=SLOTS, t_max=T_MAX, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=7, **kw)
    eng.step(np.zeros(SLOTS, np.int32), np.ones(SLOTS, bool))
    eng.prefill(0, np.asarray([1, 2], np.int32))
    for i in range(SLOTS):
        eng.reset(i)
    return eng


def _wait_for(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def test_monitor_detects_stall_and_recovers():
    reg = MetricsRegistry()
    with HealthMonitor(stall_timeout=0.1, poll_interval=0.02,
                       registry=reg) as mon:
        mon.beat()
        mon.set_readiness(Readiness.READY)
        assert _wait_for(lambda: mon.liveness is Liveness.STALLED)
        assert mon.readiness is Readiness.NOT_READY
        assert mon.stall_events == 1
        mon.beat()                       # loop resumed
        assert mon.liveness is Liveness.ALIVE
        mon.set_readiness(Readiness.READY)
    assert mon.readiness is Readiness.STOPPED
    kinds = [(k, v) for _, k, v, _ in mon.transitions]
    assert ('liveness', 'stalled') in kinds
    assert ('liveness', 'alive') in kinds
    assert kinds[-1] == ('readiness', 'stopped')
    assert reg.snapshot()['counters']['serve.watchdog_stalls'] == 1
    assert reg.snapshot()['counters']['serve.watchdog_recoveries'] == 1


def test_monitor_quiet_while_beating():
    with HealthMonitor(stall_timeout=0.25, poll_interval=0.02) as mon:
        for _ in range(10):
            mon.beat()
            time.sleep(0.02)
        assert mon.liveness is Liveness.ALIVE
        assert mon.stall_events == 0
        assert mon.last_beat_age() < 0.25


def test_watchdog_fires_on_injected_stuck_step():
    """The acceptance path: a stuck compiled decode step (injected
    host-side stall, exactly what a hung device call looks like) trips
    the watchdog mid-run, and readiness returns to READY once the step
    unsticks — asserted from the transition log, not just the end
    state."""
    plan = ServeFaultPlan(stuck_at_step=2, stuck_seconds=0.6)
    cfg = ServeConfig(queue_limit=8, max_new_tokens=5,
                      stall_timeout=0.15, watchdog_poll=0.02)
    sched = Scheduler(_warm_engine(), cfg,
                      fault_injector=ServeFaultInjector(plan),
                      registry=MetricsRegistry())
    rng = np.random.default_rng(0)
    for i in range(4):
        sched.submit(rng.integers(0, VOCAB, size=3), request_id=f'r{i}')
    res = sched.run_until_idle()
    assert sched.health.stall_events >= 1
    assert sched.health.readiness is Readiness.READY
    assert all(r.status == 'completed' for r in res.values())
    line = [(k, v) for _, k, v, _ in sched.health.transitions]
    stall_at = line.index(('liveness', 'stalled'))
    assert ('readiness', 'ready') in line[:stall_at], 'was ready first'
    assert ('readiness', 'not_ready') in line[stall_at:], 'drained'
    assert ('readiness', 'ready') in line[
        line.index(('readiness', 'not_ready'), stall_at):], 'restored'
    sched.close()
    assert sched.health.readiness is Readiness.STOPPED


def test_nan_quarantine_leaves_other_slots_bit_identical():
    """One poisoned slot must cost exactly one retry: the quarantined
    request requeues and completes with the SAME tokens, and every
    other request's stream is bit-identical to the fault-free run."""
    prompts = [np.asarray(p, np.int32)
               for p in ([2, 9], [5], [11, 3, 7], [1, 1], [8, 4])]

    def run(injector):
        cfg = ServeConfig(queue_limit=16, max_new_tokens=6,
                          watchdog=False)
        sched = Scheduler(
            KernelEngine(slots=SLOTS, t_max=T_MAX, vocab=VOCAB, heads=2,
                         head_dim=4, prefill_chunk=4, seed=7),
            cfg, fault_injector=injector, registry=MetricsRegistry())
        for i, p in enumerate(prompts):
            sched.submit(p, request_id=f'r{i}')
        res = sched.run_until_idle()
        snap = sched.registry.snapshot()['counters']
        sched.close()
        return res, snap

    clean, _ = run(None)
    plan = ServeFaultPlan(nan_at_step=2, nan_slot=1)
    faulted, counters = run(ServeFaultInjector(plan))
    assert counters['serve.nan_quarantined'] == 1
    assert counters['serve.requeued'] == 1
    hit = [r for r in faulted.values() if r.requeues == 1]
    assert len(hit) == 1, 'exactly one request took the poison'
    for rid in clean:
        assert faulted[rid].status == 'completed'
        assert faulted[rid].tokens == clean[rid].tokens, \
            f'{rid}: fault leaked across slots'


def test_nan_exhausted_requeues_fails_typed():
    """A slot that NaNs on every retry must end in a TYPED failure, not
    an infinite requeue loop."""
    plan = ServeFaultPlan(nan_at_step=1, nan_slot=0, fire_once=False)
    cfg = ServeConfig(queue_limit=8, max_new_tokens=5, max_requeues=1,
                      watchdog=False)
    sched = Scheduler(
        KernelEngine(slots=1, t_max=T_MAX, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=7),
        cfg, fault_injector=ServeFaultInjector(plan),
        registry=MetricsRegistry())
    sched.submit(np.asarray([3], np.int32), request_id='r')
    res = sched.run_until_idle()
    assert res['r'].status == 'failed_nan'
    assert res['r'].requeues == 1
    snap = sched.registry.snapshot()['counters']
    assert snap['serve.nan_quarantined'] == 2
    assert snap['serve.failed'] == 1
    sched.close()


def test_health_snapshot_shape():
    with HealthMonitor(stall_timeout=1.0) as mon:
        mon.beat()
        mon.set_readiness(Readiness.READY)
        snap = mon.snapshot()
    assert snap['liveness'] == 'alive'
    assert snap['last_beat_age_s'] >= 0
    assert 'serve.watchdog_stalls' in snap['metrics']['counters']
    assert isinstance(snap['metrics'], dict)
