# -*- coding: utf-8 -*-
"""
flowlint (analysis/flowlint.py) — the interprocedural typed-failure-
flow engine's own gate and rule tests, plus the pinning tests for the
real violations the first repo-wide sweep found and fixed in-diff.

Mirrors tests/test_servelint.py's structure:

- **Clean-tree gate**: zero flowlint records repo-wide — ACTIVE and
  WAIVED both: the typed-failure contract carries no pragma debt.
- **Negative fixtures, one per rule** (tests/graphlint_fixtures/
  serve/fx_flow_*.py): each seeded line carries a ``# VIOLATION:
  <rule>`` marker; each fixture trips exactly its own rule. The
  typed-escape fixture reproduces PR 17's ``deque.remove`` untyped
  ValueError and renders a two-hop propagation chain.
- **CLI**: exit 1 over the fixture set; ``--rule`` filtering;
  ``--format json``'s stable rule/file/line/chain shape; ``--format
  sarif``'s minimal SARIF 2.1.0 log with waived records at level
  ``note``.
- **Sweep pins**: the typed narrowings (ServeContractError /
  UnknownReplicaError), pop-by-index container deletes, the attach
  pool-state RuntimeError, and the ``decode_kernel_eligible`` sharded
  explain threading stay fixed.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_dot_product_tpu.analysis import (
    active_violations, run_analysis,
)
from distributed_dot_product_tpu.analysis import flowlint

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, 'tests', 'graphlint_fixtures', 'serve')

ESCAPE = os.path.join(FIXTURES, 'fx_flow_escape.py')
ESCAPE_REL = 'tests/graphlint_fixtures/serve/fx_flow_escape.py'


def _expected(path):
    """``{(rule, line)}`` from the fixture's own ``# VIOLATION: rule``
    markers — the file annotates its seeded regressions."""
    out = set()
    with open(path, encoding='utf-8') as f:
        for i, line in enumerate(f, 1):
            if '# VIOLATION:' in line:
                rule = line.split('# VIOLATION:')[1].strip().split()[0]
                out.add((rule, i))
    return out


# -- clean-tree gate ----------------------------------------------------

def test_flowlint_clean_tree_gate_zero_debt():
    """Zero flowlint records repo-wide — including WAIVED ones: every
    exception escaping a serving root is in the typed contract, every
    typed handler routes its failure, the RejectReason taxonomy is
    live, the ownership stride has one home, and none of that rests on
    a pragma."""
    violations = run_analysis(rules=list(flowlint.FLOW_RULES),
                              jaxpr=False)
    assert violations == [], '\n'.join(v.render() for v in violations)


# -- negative fixtures --------------------------------------------------

@pytest.mark.parametrize('fixture', [
    'fx_flow_escape.py', 'fx_flow_totality.py', 'fx_flow_reason.py',
    'fx_flow_shard.py',
])
def test_rule_catches_fixture(fixture):
    path = os.path.join(FIXTURES, fixture)
    violations = flowlint.lint_file(path, repo_root=REPO)
    active = active_violations(violations)
    got = {(v.rule, v.line) for v in active}
    want = _expected(path)
    assert want == got, (f'{fixture}: expected exactly {sorted(want)}, '
                         f'got {sorted(got)}')
    assert all(v.file and v.file.endswith(fixture) for v in violations)


def test_each_fixture_trips_exactly_its_rule():
    """The fixtures are rule-pure: no cross-contamination between the
    four checkers on any of them."""
    rule_of = {
        'fx_flow_escape.py': 'typed-escape',
        'fx_flow_totality.py': 'handler-totality',
        'fx_flow_reason.py': 'reason-coverage',
        'fx_flow_shard.py': 'shard-ownership',
    }
    for fixture, rule in rule_of.items():
        path = os.path.join(FIXTURES, fixture)
        vs = flowlint.lint_file(path, repo_root=REPO)
        assert {v.rule for v in vs} == {rule}, (
            f'{fixture}: {sorted({v.rule for v in vs})}')


def test_typed_escape_renders_transitive_chain():
    """The KeyError escapes Server.step through TWO intermediate
    frames (step → _drain → _pop_head): the violation anchors at the
    origin raise and carries the whole chain, rendered in the message
    as file:line → file:line."""
    vs = active_violations(flowlint.lint_file(ESCAPE, repo_root=REPO))
    key = [v for v in vs if 'KeyError' in v.message]
    assert len(key) == 1, '\n'.join(v.render() for v in vs)
    v = key[0]
    assert 'Server.step' in v.message
    assert v.chain is not None and len(v.chain) == 3, v.chain
    assert all(h.startswith(f'{ESCAPE_REL}:') for h in v.chain)
    assert v.chain[-1] == f'{v.file}:{v.line}'   # anchored at origin
    assert ' → '.join(v.chain) in v.message


def test_pr17_deque_remove_shape_is_caught():
    """The regression fixture reproduces PR 17's drive-found bug —
    ``deque.remove`` walking ``__eq__`` out of a serving root — and
    flowlint names both the root and the implicit-ValueError cause."""
    vs = active_violations(flowlint.lint_file(ESCAPE, repo_root=REPO))
    hits = [v for v in vs if '.remove()' in v.message]
    assert len(hits) == 1, '\n'.join(v.render() for v in vs)
    v = hits[0]
    assert 'Server.submit' in v.message
    assert 'ValueError' in v.message
    assert 'delete by index' in v.message


def test_pragma_waiver_stays_visible_as_allowed_record():
    """``# flowlint: allow[typed-escape]`` waives the site but the
    record STAYS in the output with ``allowed=True`` — waived
    failure-flow debt is enumerable, not invisible (and the clean-tree
    gate above asserts the real tree carries none)."""
    vs = flowlint.lint_file(ESCAPE, repo_root=REPO)
    waived = [v for v in vs if v.allowed]
    assert len(waived) == 1, '\n'.join(v.render() for v in vs)
    v = waived[0]
    assert v.rule == 'typed-escape'
    assert 'IndexError' in v.message and 'run_ok' in v.message
    assert '(allowed)' in v.render()


# -- CLI ----------------------------------------------------------------

def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.run(
        [sys.executable, '-m', 'distributed_dot_product_tpu.analysis',
         *args], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=540)


def _fx(name):
    return os.path.join('tests', 'graphlint_fixtures', 'serve', name)


def test_cli_nonzero_on_flow_fixtures():
    res = _cli('--no-jaxpr',
               _fx('fx_flow_escape.py'), _fx('fx_flow_totality.py'),
               _fx('fx_flow_reason.py'), _fx('fx_flow_shard.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    for rule in flowlint.FLOW_RULES:
        assert rule in res.stdout, f'{rule} missing from CLI output'


def test_cli_rule_filter_isolates_one_rule():
    res = _cli('--no-jaxpr', '--rule', 'typed-escape',
               _fx('fx_flow_escape.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    assert 'typed-escape' in res.stdout
    # The same fixture under a non-matching flow rule is clean.
    res = _cli('--no-jaxpr', '--rule', 'shard-ownership',
               _fx('fx_flow_escape.py'))
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_list_rules_names_flowlint():
    res = _cli('--list-rules')
    assert res.returncode == 0
    for rule in flowlint.FLOW_RULES:
        assert rule in res.stdout


def test_cli_json_shape_carries_chain():
    """The documented stable JSON shape: every record has rule/file/
    line/chain keys; typed-escape chains are file:line hop lists
    ordered root call site → origin raise."""
    res = _cli('--no-jaxpr', '--format', 'json', '--rule',
               'typed-escape', _fx('fx_flow_escape.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    records = json.loads(res.stdout)
    assert records, 'expected typed-escape records'
    for r in records:
        assert {'rule', 'file', 'line', 'chain', 'allowed',
                'message'} <= set(r)
        assert r['rule'] == 'typed-escape'
        if r['chain'] is not None:
            for hop in r['chain']:
                f, ln = hop.rsplit(':', 1)
                assert f.endswith('.py') and ln.isdigit(), hop
    assert any(r['chain'] and len(r['chain']) == 3 for r in records)
    # The waived site rides along, flagged: debt is enumerable.
    assert any(r['allowed'] for r in records)


def test_cli_sarif_shape():
    res = _cli('--no-jaxpr', '--format', 'sarif',
               _fx('fx_flow_escape.py'), _fx('fx_flow_totality.py'),
               _fx('fx_flow_reason.py'), _fx('fx_flow_shard.py'))
    assert res.returncode == 1, res.stdout + res.stderr
    log = json.loads(res.stdout)
    assert log['version'] == '2.1.0'
    assert 'sarif-2.1.0' in log['$schema']
    run = log['runs'][0]
    driver = run['tool']['driver']
    assert driver['name'] == 'graphlint'
    assert set(flowlint.FLOW_RULES) <= {r['id'] for r in
                                        driver['rules']}
    results = run['results']
    assert {r['ruleId'] for r in results} >= set(flowlint.FLOW_RULES)
    for r in results:
        assert r['level'] in ('error', 'note')
        loc = r['locations'][0]['physicalLocation']
        assert loc['artifactLocation']['uri'].endswith('.py')
        assert loc['region']['startLine'] >= 1
    # The pragma-waived escape site downgrades to 'note', not gone.
    assert any(r['level'] == 'note' and r['ruleId'] == 'typed-escape'
               for r in results)


def test_cli_sarif_empty_run_is_valid():
    res = _cli('--no-jaxpr', '--format', 'sarif', '--rule',
               'shard-ownership', _fx('fx_flow_reason.py'))
    assert res.returncode == 0, res.stdout + res.stderr
    log = json.loads(res.stdout)
    assert log['runs'][0]['results'] == []


# -- sweep pins: the in-diff fixes stay fixed ---------------------------

def test_typed_narrowings_subclass_the_builtins():
    """ServeContractError/UnknownReplicaError narrow the caller-
    contract ValueError/KeyError raises flowlint forced out of the
    serving surfaces — as SUBCLASSES, so pre-existing catches keep
    working, and UnknownReplicaError renders without KeyError's
    repr-quoting."""
    from distributed_dot_product_tpu.serve import (
        ServeContractError, UnknownReplicaError,
    )
    assert issubclass(ServeContractError, ValueError)
    assert issubclass(UnknownReplicaError, KeyError)
    assert str(UnknownReplicaError('no replica named r9')) == \
        'no replica named r9'


def test_run_trace_tick_contract_is_typed():
    from distributed_dot_product_tpu.serve import (
        ServeContractError, run_trace,
    )
    with pytest.raises(ServeContractError):
        run_trace(None, [], lambda: 0.0, tick_seconds=0)
    with pytest.raises(ValueError):    # the pre-narrowing catch shape
        run_trace(None, [], lambda: 0.0, tick_seconds=-1)


def test_scheduler_prefix_contract_is_typed():
    from distributed_dot_product_tpu.serve import (
        Scheduler, ServeConfig, ServeContractError,
    )
    from distributed_dot_product_tpu.serve.engine import KernelEngine
    eng = KernelEngine(slots=1, t_max=32, vocab=16, heads=1,
                       head_dim=8, seed=0)
    sched = Scheduler(eng, ServeConfig(watchdog=False))
    with pytest.raises(ServeContractError, match='paged engine'):
        sched.submit(np.array([1, 2, 3]), prefix_id='p0')


def test_replica_pool_unknown_name_is_typed():
    from distributed_dot_product_tpu.serve import TopologyConfig
    from distributed_dot_product_tpu.serve.replica import ReplicaPool
    from distributed_dot_product_tpu.serve import UnknownReplicaError
    pool = ReplicaPool(TopologyConfig(
        decode_replicas=2, slots=2, t_max=64, page_size=16, vocab=32,
        seed=3))
    try:
        with pytest.raises(UnknownReplicaError):
            pool.mark_lost('ghost')
        with pytest.raises(KeyError):   # subclass: old catches hold
            pool.remove_replica('ghost')
        # Pop-by-index delete still works end to end: the member moves
        # to `lost` and the roster shrinks — no untyped ValueError from
        # a container .remove walking replica equality.
        lost = pool.mark_lost('r0')
        assert lost.name == 'r0'
        assert [r.name for r in pool.replicas] == ['r1']
        assert pool.lost == [lost]
        with pytest.raises(ValueError):
            pool.remove_replica('r1')   # last member stays refusable
    finally:
        pool.close()


def test_pagepool_attach_pool_state_is_runtime_error():
    """attach on a non-empty slot is a pool-state invariant break
    (reachable from Scheduler.submit via start_with_prefix), typed as
    RuntimeError — the shard/pool internal-state shape in
    TYPED_CONTRACT — not a bare ValueError."""
    from distributed_dot_product_tpu.models.decode import PagePool
    pool = PagePool(4, 16, 1, 2)
    pool.counts[0] = 1      # simulate an occupied slot
    with pytest.raises(RuntimeError, match='empty slot'):
        pool.attach(0, [0, 1], 16)


def test_pagepool_quarantine_free_list_delete_by_index():
    from distributed_dot_product_tpu.models.decode import PagePool
    pool = PagePool(4, 16, 1, 2)
    free_before = set(pool._free)
    fresh = pool.quarantine([2])
    assert fresh == [2]
    assert set(pool._free) == free_before - {2}
    # Idempotent, and a still-referenced page (left on the free list
    # for _unref to withhold) cannot raise: there is no .remove to
    # miss.
    pool.refcount[1] = 1
    assert pool.quarantine([2, 1]) == [1]
    assert 1 in pool.quarantined


def test_kernel_eligible_sharded_verify_k_names_the_gate():
    """The sharded single-token gate shows up in explain() WITH the
    mesh geometry — the error-text drift fix: the explain string names
    every gate the code actually tests."""
    from distributed_dot_product_tpu.models.decode import (
        decode_kernel_eligible, init_cache,
    )
    cache = init_cache(1, 1, 128, 8)
    ok, reason = decode_kernel_eligible(cache, n=4, explain=True,
                                        n_shards=2)
    assert not ok
    assert 'single-token' in reason and 'n=4' in reason
    assert 'sequence-sharded' in reason     # geometry prefix
    # Unsharded verify-k within the K split stays eligible.
    ok, reason = decode_kernel_eligible(cache, n=4, explain=True)
    assert ok and reason is None


def test_resolve_decode_impl_threads_axis_size_into_probe():
    """Forced-kernel sharded verify-k fails AT RESOLUTION with the
    single-token gate named (geometry included) — previously it passed
    the unsharded probe here and only blew up at the late kernel-path
    check with no geometry in the error."""
    from distributed_dot_product_tpu.models.decode import (
        _axis_env_size, _resolve_decode_impl, init_cache,
    )
    assert _axis_env_size(None) == 1
    # Outside any axis env the count is unknowable: 2 = "sharded" —
    # every gate keys on n_shards > 1, not the count.
    assert _axis_env_size('not-a-live-axis') == 2
    cache = init_cache(1, 1, 128, 8)
    with pytest.raises(ValueError, match='single-token'):
        _resolve_decode_impl('kernel', cache, 4, None, None,
                             axis_name='not-a-live-axis')
    # The same call unsharded resolves: the gate is the axis, not n.
    assert _resolve_decode_impl('kernel', cache, 4, None, None) == \
        'kernel'
