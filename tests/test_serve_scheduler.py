# -*- coding: utf-8 -*-
"""
Continuous-batching scheduler (serve/scheduler.py) over the kernel
engine: request lifecycle, chunked prefill, deadline expiry mid-stream
and in queue, the evict-before-reject ladder, and mid-stream abandon —
all under a virtual clock (the watchdog thread stays off; health.py has
its own real-time tests).
"""

import numpy as np
import pytest

from distributed_dot_product_tpu.serve import (
    KernelEngine, RejectReason, RejectedError, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

SLOTS, T_MAX, VOCAB = 3, 32, 16


class VClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _engine(prefill_chunk=4, slots=SLOTS):
    return KernelEngine(slots=slots, t_max=T_MAX, vocab=VOCAB, heads=2,
                        head_dim=4, prefill_chunk=prefill_chunk, seed=7)


def _sched(engine=None, clock=None, tick_dt=0.0, injector=None, **cfg_kw):
    clock = clock or VClock()
    cfg_kw.setdefault('queue_limit', 8)
    cfg_kw.setdefault('max_new_tokens', 5)
    cfg = ServeConfig(watchdog=False, **cfg_kw)
    on_tick = (lambda s: clock.advance(tick_dt)) if tick_dt else None
    return Scheduler(engine or _engine(), cfg, clock=clock,
                     registry=MetricsRegistry(), fault_injector=injector,
                     on_tick=on_tick), clock


def _prompts(n, seed=0, max_len=8):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB,
                         size=int(rng.integers(1, max_len + 1))
                         ).astype(np.int32) for _ in range(n)]


def test_batched_tokens_match_solo_runs():
    """A request's stream must not depend on its slot or neighbors:
    the batched run reproduces each isolated single-request run bit for
    bit — the foundation of every fault-isolation guarantee."""
    prompts = _prompts(6)
    sched, _ = _sched()
    for i, p in enumerate(prompts):
        sched.submit(p, request_id=f'r{i}')
    batched = sched.run_until_idle()
    assert all(batched[f'r{i}'].status == 'completed' for i in range(6))
    for i, p in enumerate(prompts):
        solo, _ = _sched()
        solo.submit(p, request_id='solo')
        ref = solo.run_until_idle()['solo']
        assert batched[f'r{i}'].tokens == ref.tokens, f'r{i} diverged'


def test_prefill_chunking_is_invisible():
    """Chunk width is a scheduling knob, not a numerics knob: the same
    prompt through chunk=2 and chunk=16 engines yields identical
    tokens."""
    prompt = np.arange(11, dtype=np.int32) % VOCAB
    outs = []
    for chunk in (2, 16):
        sched, _ = _sched(engine=_engine(prefill_chunk=chunk))
        sched.submit(prompt, request_id='r')
        outs.append(sched.run_until_idle()['r'].tokens)
        assert len(outs[-1]) == 5
    assert outs[0] == outs[1]


def test_deadline_expiry_mid_stream():
    """A slot whose deadline passes mid-generation frees with its
    partial tokens and a typed terminal status."""
    sched, clock = _sched(tick_dt=1.0, max_new_tokens=50)
    sched.submit(np.asarray([1], np.int32), request_id='r',
                 deadline=3.5)
    res = sched.run_until_idle()['r']
    assert res.status == 'deadline_expired'
    assert 1 <= len(res.tokens) < 50


def test_deadline_expiry_in_queue():
    """With every slot busy, a queued request whose deadline lapses is
    finalized as a typed DEADLINE_EXCEEDED rejection when it reaches
    the head — queue death is never silent."""
    sched, clock = _sched(engine=_engine(slots=1), tick_dt=1.0,
                          max_new_tokens=30)
    sched.submit(np.asarray([1], np.int32), request_id='long')
    sched.submit(np.asarray([2], np.int32), request_id='doomed',
                 deadline=4.0)
    res = sched.run_until_idle()
    assert res['long'].status == 'completed'
    assert res['doomed'].status == 'rejected'
    assert res['doomed'].reason is RejectReason.DEADLINE_EXCEEDED


def test_evict_before_reject_ladder():
    """Queue full: the longest-idle running sequence is evicted (typed,
    partial tokens kept) to admit new work; only when eviction is off
    does the submit shed with QUEUE_FULL."""
    sched, clock = _sched(engine=_engine(slots=1), queue_limit=1,
                          max_new_tokens=30)
    sched.submit(np.asarray([1], np.int32), request_id='victim')
    for _ in range(3):      # victim decodes a few tokens
        sched.step()
    sched.submit(np.asarray([2], np.int32), request_id='queued')
    sched.submit(np.asarray([3], np.int32), request_id='late')
    res = sched.run_until_idle()
    assert res['victim'].status == 'evicted'
    assert 1 <= len(res['victim'].tokens) < 30
    assert res['queued'].status == 'completed'
    assert res['late'].status == 'completed'
    assert sched.registry.snapshot()['counters']['serve.evicted'] == 1


def test_queue_full_sheds_typed_when_eviction_off():
    sched, _ = _sched(engine=_engine(slots=1), queue_limit=1,
                      evict_before_reject=False, max_new_tokens=30)
    sched.submit(np.asarray([1], np.int32), request_id='a')
    sched.step()
    sched.submit(np.asarray([2], np.int32), request_id='b')
    with pytest.raises(RejectedError, match='queue_full') as ei:
        sched.submit(np.asarray([3], np.int32), request_id='c')
    assert ei.value.reason is RejectReason.QUEUE_FULL
    res = sched.run_until_idle()
    assert res['a'].status == res['b'].status == 'completed'


def test_midstream_abandon_frees_slot():
    """A client abandoning its stream (injector-driven, exactly how the
    DDP_TPU_FAULT_ABANDON_* knobs land) frees the slot for queued work;
    the abandoned request keeps a typed status + partial tokens."""
    plan = ServeFaultPlan(abandon_request=0, abandon_after_tokens=2)
    sched, _ = _sched(engine=_engine(slots=1),
                      injector=ServeFaultInjector(plan),
                      max_new_tokens=30)
    sched.submit(np.asarray([1], np.int32), request_id='gone')
    sched.submit(np.asarray([2], np.int32), request_id='next')
    res = sched.run_until_idle()
    assert res['gone'].status == 'abandoned'
    assert len(res['gone'].tokens) == 2
    assert res['next'].status == 'completed'


def test_cancel_api():
    sched, _ = _sched(max_new_tokens=30)
    sched.submit(np.asarray([1], np.int32), request_id='r')
    sched.step()
    assert sched.cancel('r')
    assert not sched.cancel('nope')
    assert sched.run_until_idle()['r'].status == 'abandoned'


def test_completion_frees_slot_for_reuse():
    """More requests than slots: every slot cycles through several
    sequences; lengths return to zero at idle (nothing leaks)."""
    sched, _ = _sched(queue_limit=12)
    prompts = _prompts(9, seed=3)
    for i, p in enumerate(prompts):
        sched.submit(p, request_id=f'r{i}')
    res = sched.run_until_idle()
    assert sum(r.status == 'completed' for r in res.values()) == 9
    assert list(sched.engine.lengths()) == [0] * SLOTS
    snap = sched.registry.snapshot()
    assert snap['counters']['serve.completed'] == 9
    assert snap['histograms']['serve.step_seconds']['count'] > 0


def test_degraded_admission_is_prefix_of_full_run():
    """Degradation caps the budget, not the content: a degraded stream
    is a PREFIX of the undegraded stream for the same prompt."""
    prompt = np.asarray([3, 1, 4], np.int32)
    full, _ = _sched(max_new_tokens=8)
    full.submit(prompt, request_id='r')
    want = full.run_until_idle()['r'].tokens
    tight, _ = _sched(queue_limit=2, degrade_watermark=0.0,
                      max_new_tokens=8, degraded_max_new_tokens=3)
    tight.submit(prompt, request_id='r')
    got = tight.run_until_idle()['r']
    assert got.degraded and len(got.tokens) == 3
    assert got.tokens == want[:3]
