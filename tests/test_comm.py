# -*- coding: utf-8 -*-
"""Process/topology layer tests (reference has no comm tests; its comm.py is
exercised implicitly by every distributed test, SURVEY §2.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu import (
    SEQ_AXIS, get_rank, get_world_size, is_main_process, seq_mesh,
    synchronize,
)
from distributed_dot_product_tpu.parallel.mesh import (
    data_seq_mesh, seq_spec, shard_seq,
)


def test_host_level_rank_world():
    # Single-process: process-level rank/world (reference comm.py:13-19
    # semantics; rank and world must describe the same unit — processes).
    assert get_rank() == 0
    assert is_main_process()
    assert get_world_size() == jax.process_count() == 1
    synchronize()  # no-op single-host, must not raise


def test_mesh_and_axis_introspection():
    mesh = seq_mesh(4)
    assert mesh.shape == {SEQ_AXIS: 4}

    def body(x):
        # world size is static inside shard_map; rank is per-shard.
        assert get_world_size(SEQ_AXIS) == 4
        return x + get_rank(SEQ_AXIS)

    out = jax.shard_map(body, mesh=mesh, in_specs=P(SEQ_AXIS),
                        out_specs=P(SEQ_AXIS))(jnp.zeros(4))
    np.testing.assert_array_equal(np.asarray(out), [0, 1, 2, 3])


def test_seq_spec_and_shard_seq():
    mesh = seq_mesh(4)
    assert seq_spec(3) == P(None, SEQ_AXIS, None)
    assert seq_spec(4) == P(None, None, SEQ_AXIS, None)
    assert seq_spec(4, batch_axis=0) == P('data', None, SEQ_AXIS, None)
    x = jnp.arange(2 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 3)
    sx = shard_seq(x, mesh)
    assert sx.sharding.spec == P(None, SEQ_AXIS, None)
    np.testing.assert_array_equal(np.asarray(sx), np.asarray(x))


def test_data_seq_mesh():
    mesh = data_seq_mesh(2, 4)
    assert mesh.shape == {'data': 2, SEQ_AXIS: 4}
    with pytest.raises(ValueError):
        data_seq_mesh(4, 4)  # 16 > 8 devices


def test_seq_mesh_too_many_devices():
    with pytest.raises(ValueError):
        seq_mesh(1024)
