# -*- coding: utf-8 -*-
"""
Per-slot KV-cache primitives (models/decode.py): the continuous-batching
substrate. A slot cache packs independent sequences on independent
clocks into one batch — correctness means each slot's attention is
bit-for-bit the attention it would compute alone, eviction touches ONE
slot, and overflow is loud.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.decode import (
    append_kv, append_kv_slots, decode_attention, init_cache,
    init_slot_cache, reset_slot, slots_all_finite,
)

B, H, D, T = 3, 2, 8, 16
LENS = [5, 9, 1]        # staggered slot fills — the serving steady state


def _operands(key=0, t=None):
    ks = jax.random.split(jax.random.key(key), 3)
    t = t or max(LENS)
    k = jax.random.normal(ks[0], (B, H, t, D), jnp.float32)
    v = jax.random.normal(ks[1], (B, H, t, D), jnp.float32)
    q = jax.random.normal(ks[2], (B, H, 1, D), jnp.float32)
    return q, k, v


def _filled_slot_cache(k, v, lens=LENS, chunk=4):
    """Fill a slot cache via padded chunked appends with per-slot
    counts — exactly how the scheduler's prefill lands."""
    cache = init_slot_cache(B, H, T, D, dtype=jnp.float32)
    for c0 in range(0, max(lens), chunk):
        n = k[:, :, c0:c0 + chunk].shape[2]
        counts = jnp.asarray([max(0, min(ln - c0, n)) for ln in lens],
                             jnp.int32)
        cache = append_kv_slots(cache, k[:, :, c0:c0 + chunk],
                                v[:, :, c0:c0 + chunk], counts=counts)
    return cache


def test_per_slot_decode_matches_isolated_caches():
    """Each slot of a staggered batch must attend exactly as it would
    alone in a scalar-length cache of its own fill."""
    q, k, v = _operands()
    cache = _filled_slot_cache(k, v)
    assert [int(x) for x in cache.length] == LENS
    out = decode_attention(q, cache)
    for i, ln in enumerate(LENS):
        solo = init_cache(1, H, T, D, dtype=jnp.float32)
        solo = append_kv(solo, k[i:i + 1, :, :ln], v[i:i + 1, :, :ln])
        want = decode_attention(q[i:i + 1], solo)
        np.testing.assert_allclose(np.asarray(out[i:i + 1]),
                                   np.asarray(want), atol=1e-6)


def test_per_slot_decode_window():
    q, k, v = _operands(key=1)
    cache = _filled_slot_cache(k, v)
    out = decode_attention(q, cache, window=4)
    for i, ln in enumerate(LENS):
        solo = init_cache(1, H, T, D, dtype=jnp.float32)
        solo = append_kv(solo, k[i:i + 1, :, :ln], v[i:i + 1, :, :ln])
        want = decode_attention(q[i:i + 1], solo, window=4)
        np.testing.assert_allclose(np.asarray(out[i:i + 1]),
                                   np.asarray(want), atol=1e-6)


def test_empty_slot_outputs_zero():
    """A FREE slot (length 0) is fully masked: zero output, no NaN from
    the empty softmax."""
    q, _, _ = _operands()
    cache = init_slot_cache(B, H, T, D, dtype=jnp.float32)
    out = decode_attention(q, cache)
    assert float(jnp.abs(out).sum()) == 0.0


def test_reset_slot_is_surgical():
    """Eviction zeroes ONE slot; every other slot's buffers are
    BIT-identical (the quarantine isolation guarantee starts here)."""
    _, k, v = _operands()
    cache = _filled_slot_cache(k, v)
    out = reset_slot(cache, 1)
    assert [int(x) for x in out.length] == [LENS[0], 0, LENS[2]]
    np.testing.assert_array_equal(np.asarray(out.k[0]),
                                  np.asarray(cache.k[0]))
    np.testing.assert_array_equal(np.asarray(out.v[2]),
                                  np.asarray(cache.v[2]))
    assert float(jnp.abs(out.k[1]).sum()) == 0.0
    # The freed slot serves a fresh sequence immediately.
    refill = append_kv_slots(
        out, k[:, :, :1], v[:, :, :1],
        slot_mask=jnp.asarray([False, True, False]))
    assert [int(x) for x in refill.length] == [LENS[0], 1, LENS[2]]


def test_slot_mask_freezes_inactive_slots():
    """A decode append only advances ACTIVE slots — buffers and lengths
    of masked slots must not move."""
    _, k, v = _operands()
    cache = _filled_slot_cache(k, v)
    mask = jnp.asarray([True, False, True])
    out = append_kv_slots(cache, k[:, :, :1], v[:, :, :1],
                          slot_mask=mask)
    assert [int(x) for x in out.length] == [6, 9, 2]
    np.testing.assert_array_equal(np.asarray(out.k[1]),
                                  np.asarray(cache.k[1]))


def test_slot_overflow_raises_concretely():
    """Host-side (concrete-length) overflow must raise naming the slot,
    not wrap around."""
    cache = init_slot_cache(2, H, 4, D, dtype=jnp.float32)
    cache = cache._replace(length=jnp.asarray([3, 0], jnp.int32))
    one = jnp.ones((2, H, 2, D))
    with pytest.raises(ValueError, match='slot 0'):
        append_kv_slots(cache, one, one)


def test_slot_overflow_traced_guard():
    """Under jit the overflowing slot writes NOTHING while its length
    still advances (detectable), and in-bounds slots append normally —
    append_kv's contract, per slot."""
    cache = init_slot_cache(2, H, 4, D, dtype=jnp.float32)
    cache = cache._replace(length=jnp.asarray([3, 0], jnp.int32))
    one = jnp.ones((2, H, 2, D))
    out = jax.jit(append_kv_slots)(cache, one, one)
    assert int(out.length[0]) == 5 and int(out.length[0]) > out.t_max
    assert float(jnp.abs(out.k[0]).sum()) == 0.0
    assert int(out.length[1]) == 2
    assert float(jnp.abs(out.k[1]).sum()) > 0.0


def test_scalar_cache_rejects_slot_ops():
    cache = init_cache(B, H, T, D)
    one = jnp.ones((B, H, 1, D))
    with pytest.raises(ValueError, match='init_slot_cache'):
        append_kv_slots(cache, one, one)
    with pytest.raises(ValueError, match='init_slot_cache'):
        reset_slot(cache, 0)


def test_slots_all_finite():
    x = jnp.asarray([[1.0, 2.0], [jnp.nan, 1.0], [3.0, jnp.inf]])
    assert list(np.asarray(slots_all_finite(x))) == [True, False, False]
    # Works on any per-slot trailing shape (logits, hidden states, ...).
    y = jnp.zeros((2, 3, 4)).at[1, 2, 1].set(jnp.nan)
    assert list(np.asarray(slots_all_finite(y))) == [True, False]


def test_decode_jit_one_program_all_slots():
    """The serving invariant: one compiled (append + attend) program
    serves every slot configuration — staggered lengths and masks are
    data, not shapes."""
    q, k, v = _operands(key=5)
    cache = _filled_slot_cache(k, v)

    @jax.jit
    def step(c, q1, k1, v1, mask):
        c = append_kv_slots(c, k1, v1, slot_mask=mask)
        return c, decode_attention(q1, c)

    m1 = jnp.asarray([True, True, False])
    m2 = jnp.asarray([False, True, True])
    cache, o1 = step(cache, q, k[:, :, :1], v[:, :, :1], m1)
    cache, o2 = step(cache, q, k[:, :, 1:2], v[:, :, 1:2], m2)
    assert o1.shape == o2.shape == (B, H, 1, D)
    assert [int(x) for x in cache.length] == [6, 11, 2]
