# -*- coding: utf-8 -*-
"""
Cluster-scale long context (ISSUE-18) — the ``kv_shards`` engine mode
and its serving integration. One stream's page table shards across the
mesh's ``seq`` axis: each member owns a CONTIGUOUS page range, decodes
over only its own pages, and the per-shard flash partials psum/pmax-
merge into the exact full-attention result. The tests pin the three
acceptance properties on the CPU mesh:

- **Bit identity**: sharded streams (XLA and kernel paths) equal the
  single-pool reference token for token — prefill, decode, rollback
  and the shard-local prefill→decode handoff included.
- **Linear capacity**: with a FIXED per-shard pool, ``capacity_tokens``
  scales ~linearly in ``kv_shards`` (≥3.5× at 4 shards).
- **Typed shard-exhaustion**: one shard's contiguous range running out
  while others have headroom surfaces the typed ``CACHE_EXHAUSTED``
  ladder (scheduler) or a shard-naming RuntimeError (engine) — never a
  silent stall — and corruption verdicts name the owning shard in
  ``kv.corrupt`` + doctor output.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import doctor as obs_doctor
from distributed_dot_product_tpu.obs import flight as obs_flight
from distributed_dot_product_tpu.obs.events import EventLog
from distributed_dot_product_tpu.obs.timeline import reconstruct
from distributed_dot_product_tpu.serve import (
    KernelEngine, PrefillPool, RejectReason, RouterConfig, Scheduler,
    ServeConfig, TopologyConfig, VirtualClock, build_serving,
)
from distributed_dot_product_tpu.serve.engine import PageCorruptionError
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

VOCAB = 32


def _engine(*, kv_shards=1, pages=None, slots=2, t_max=64,
            page_size=16, decode_impl='xla', **kw):
    return KernelEngine(slots=slots, t_max=t_max, vocab=VOCAB, heads=2,
                        head_dim=8, prefill_chunk=8, seed=3,
                        decode_impl=decode_impl, cache_mode='paged',
                        page_size=page_size, pages=pages,
                        kv_shards=kv_shards, **kw)


def _prompt(length, salt=0):
    return (((np.arange(length) * 5 + salt) % (VOCAB - 1)) + 1) \
        .astype(np.int32)


def _stream(eng, prompt, steps, slot=0):
    """Prefill ``prompt`` into ``slot`` and greedy-decode ``steps``
    tokens; returns the token list."""
    for st in range(0, len(prompt), 8):
        eng.prefill(slot, prompt[st:st + 8])
    active = np.zeros(eng.slots, bool)
    active[slot] = True
    tok = np.full(eng.slots, int(prompt[-1]), np.int32)
    out = []
    for _ in range(steps):
        tok, _ = eng.step(tok, active)
        out.append(int(tok[slot]))
    return out


# -- bit identity vs the single-pool reference --------------------------

@pytest.mark.parametrize('impl', ['xla', 'kernel'])
def test_sharded_stream_bit_identical(impl, devices):
    """The tentpole identity: a 4-shard engine (per-shard pool, stacked
    device layout, shard_map programs) decodes the same stream as the
    unsharded reference, across page boundaries, on both decode
    implementations."""
    ref = _engine(decode_impl=impl, pages=8)
    sh = _engine(decode_impl=impl, pages=2, kv_shards=4)
    a = _stream(ref, _prompt(37), 24)
    b = _stream(sh, _prompt(37), 24)
    assert a == b
    assert sh.cache_stats()['kv_shards'] == 4


def test_sharded_rollback_and_reset_bit_identical(devices):
    """Rollback (page-granular truncate across shard boundaries) and
    slot reset keep the sharded stream pinned to the reference."""
    ref = _engine(pages=8)
    sh = _engine(pages=2, kv_shards=4)
    a = _stream(ref, _prompt(21), 20)
    b = _stream(sh, _prompt(21), 20)
    assert a == b
    keep = int(sh.pool.lengths[0]) - 7
    big = np.iinfo(np.int32).max
    ref.rollback(np.array([keep, big]))
    sh.rollback(np.array([keep, big]))
    assert int(ref.pool.lengths[0]) == int(sh.pool.lengths[0]) == keep
    active = np.array([True, False])
    tr = ts = np.array([a[-8]] * 2, np.int32)
    for _ in range(6):
        nr, _ = ref.step(tr, active)
        ns, _ = sh.step(ts, active)
        assert int(nr[0]) == int(ns[0])
        tr, ts = nr, ns
    ref.reset(0)
    sh.reset(0)
    assert ref.pool.used_pages == sh.pool.used_pages == 0
    assert _stream(ref, _prompt(19, salt=2), 8) \
        == _stream(sh, _prompt(19, salt=2), 8)


# -- linear capacity scaling --------------------------------------------

def test_capacity_tokens_scales_linearly(devices):
    """The acceptance bar: a FIXED per-shard pool (4 pages × 16 rows)
    yields ≥3.5× the single-shard ``capacity_tokens`` at 4 shards —
    per-shard PagePool accounting sums across the mesh."""
    caps = {}
    for n in (1, 2, 4):
        eng = _engine(t_max=1024, pages=4, kv_shards=n)
        caps[n] = eng.capacity_tokens
        assert eng.pool.pages == 4 * n
        stats = eng.cache_stats()
        assert stats['pages_free'] == 4 * n
        if n > 1:
            assert stats['pages_free_by_shard'] == [4] * n
    assert caps[4] >= 3.5 * caps[1]
    assert caps[2] >= 1.75 * caps[1]


# -- shard-local prefill→decode handoff ---------------------------------

def test_sharded_handoff_lands_shard_local_and_bit_identical(tmp_path,
                                                             devices):
    """``adopt_prefix`` into a sharded replica: every adopted page
    lands inside the shard that OWNS its ordinal's contiguous range
    (no gather-then-scatter), and the post-handoff stream equals the
    self-prefilled sharded twin's."""
    pool = PrefillPool(t_max=64, page_size=16, vocab=VOCAB, seed=3,
                       event_log=EventLog(tmp_path / 'p.jsonl'))
    prompt = _prompt(37)
    handle = pool.build(prompt)
    dst = _engine(pages=3, kv_shards=4)
    pid = dst.adopt_prefix(pool.engine.cache, handle.pages,
                           handle.length,
                           src_checksums=pool.engine.checksums)
    pool.release(handle)
    gpages, length = dst._prefix_registry[pid]
    assert length == len(prompt)
    for ordinal, g in enumerate(gpages):
        shard, local = dst._gsplit(int(g))
        lo, hi = dst.pool.owned_range(shard)
        assert lo <= ordinal < hi, (ordinal, shard)
        assert 0 <= local < dst.pool.pages_per_shard
    assert dst.start_with_prefix(0, pid)

    twin = _engine(pages=3, kv_shards=4)
    expect = _stream(twin, prompt, 16)
    active = np.array([True, False])
    tok = np.array([int(prompt[-1])] * 2, np.int32)
    got = []
    for _ in range(16):
        tok, _ = dst.step(tok, active)
        got.append(int(tok[0]))
    assert got == expect


# -- typed edges ---------------------------------------------------------

def test_kv_shards_typed_rejections(devices):
    """Config and API edges are typed: slab mode, oversharding, and
    the three single-pool-only surfaces all raise ValueError naming
    kv_shards — never a shape error from inside a compiled program."""
    with pytest.raises(ValueError, match='kv_shards'):
        KernelEngine(slots=2, t_max=32, vocab=VOCAB, kv_shards=2)
    with pytest.raises(ValueError, match='kv_shards'):
        _engine(kv_shards=8, t_max=64, page_size=16)   # pps=4 < 8
    with pytest.raises(ValueError, match='kv_shards'):
        _engine(kv_shards=0)
    eng = _engine(kv_shards=2, pages=4)
    with pytest.raises(ValueError, match='kv_shards'):
        eng.register_prefix(_prompt(20))
    with pytest.raises(ValueError, match='kv_shards'):
        eng.fork_slot(0, 1)
    with pytest.raises(ValueError, match='kv_shards'):
        eng.verify_step(np.zeros((2, 2), np.int32), np.ones(2, int),
                        np.ones(2, bool))
    with pytest.raises(ValueError, match='kv_shards'):
        TopologyConfig(kv_shards=0).validate()


def test_shard_exhaustion_is_typed_at_the_engine(devices):
    """One shard's contiguous range out of pages while others have
    headroom: ``prepare_step`` masks exactly the starved slot and a
    forced step raises a RuntimeError naming the per-shard frees —
    the silent-stall failure mode is structurally impossible."""
    # pps=4, 4 shards → each shard owns ONE ordinal; 1 page per shard
    # means two slots' ordinal-0 pages both contend for shard 0.
    eng = _engine(kv_shards=4, pages=1, t_max=64, page_size=16)
    ok, _ = eng.pool.reserve_rows(0, 16)
    assert ok
    assert eng.pool.free_pages_by_shard == [0, 1, 1, 1]
    assert eng.pool.free_pages == 3           # headroom elsewhere
    ok2, _ = eng.pool.reserve_rows(1, 16)
    assert not ok2                            # shard 0 is the wall
    mask = eng.prepare_step(np.array([True, True]))
    assert list(mask) == [True, False]
    with pytest.raises(RuntimeError, match='free by shard'):
        eng.step(np.zeros(2, np.int32), np.array([True, True]))


def test_shard_exhaustion_walks_ladder_under_faults(devices):
    """The serving-level twin, under the existing fault cocktail: two
    growing streams contend for ONE shard's range (the others stay
    free), the scheduler walks the preempt ladder, the winner
    completes and the loser terminates as the typed CACHE_EXHAUSTED
    eviction — reconstructable, drained, never stalled."""
    eng = KernelEngine(slots=2, t_max=16, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=2, pages=2,
                       kv_shards=4, decode_impl='xla')
    samples = []

    def on_tick(s):
        samples.append((s.engine.pool.free_pages_by_shard[0],
                        s.engine.pool.free_pages))

    sched = Scheduler(
        eng,
        ServeConfig(queue_limit=4, max_new_tokens=10, watchdog=False,
                    evict_before_reject=False, max_requeues=0),
        registry=MetricsRegistry(),
        fault_injector=ServeFaultInjector(ServeFaultPlan(
            stuck_at_step=3, stuck_seconds=0.01)),
        on_tick=on_tick)
    sched.submit([1], request_id='a')
    sched.submit([2], request_id='b')
    results = sched.run_until_idle()
    counters = sched.registry.snapshot()['counters']
    assert counters['serve.cache_preempted'] >= 1
    statuses = sorted(r.status for r in results.values())
    assert 'completed' in statuses
    loser = [r for r in results.values() if r.status != 'completed']
    assert loser and loser[0].status == 'evicted'
    assert loser[0].reason is RejectReason.CACHE_EXHAUSTED
    # The edge this pins: shard 0's range was the wall (0 free) while
    # the pool as a whole still had headroom — and the ladder turned
    # that into the typed eviction above, not a stall.
    assert any(s0 == 0 and total > 0 for s0, total in samples), samples
    assert eng.pool.used_pages == 0
    sched.close()


# -- checksums, chaos and the shard-naming corruption arc ---------------

def test_flip_detected_named_and_quarantined_per_shard(devices):
    """The chaos seam under sharding: ``tracked_pages`` enumerates
    GLOBAL ids, ``flip_page_bit`` lands inside the owning shard's
    slice, verification names the page, ``check_pages`` names the
    shard, and quarantine pins the (shard, local) pair."""
    src = PrefillPool(t_max=64, page_size=16, vocab=VOCAB, seed=3)
    handle = src.build(_prompt(37))
    eng = _engine(pages=3, kv_shards=4)
    eng.adopt_prefix(src.engine.cache, handle.pages, handle.length,
                     src_checksums=src.engine.checksums)
    src.release(handle)
    tracked = eng.tracked_pages()
    assert len(tracked) == 3
    victim = tracked[-1]                 # ordinal 2 → shard 2's range
    shard = eng.page_shard(victim)
    assert shard == 2
    eng.flip_page_bit(victim)
    assert eng.verify_pages() == [victim]
    with pytest.raises(PageCorruptionError) as ei:
        eng.check_pages(tracked, 'attach')
    assert ei.value.pages == [victim]
    assert ei.value.shards == [shard]
    assert f'kv shard(s) [{shard}]' in str(ei.value)
    assert eng.quarantine_pages([victim]) == [victim]
    _, local = eng._gsplit(victim)
    assert (shard, local) in eng.pool.quarantined
    assert eng.verify_pages() == []      # digest dropped with the page


def test_serving_corruption_names_shard_and_heals(tmp_path, devices):
    """End to end on a sharded topology: a flip in a live handed-off
    page is scrubbed, the ``kv.corrupt`` event carries the owning
    ``shards``, the flight dump narrates it, the victim heals
    bit-identically on the clean replica, and the doctor's
    kv_corruption evidence names the dirty shard."""
    prompt = list(_prompt(18))
    topo_kw = dict(kv_shards=2, pages=4)

    clock_twin = VirtualClock()
    twin = build_serving(
        TopologyConfig(decode_replicas=1, slots=2, t_max=64,
                       page_size=16, vocab=VOCAB, seed=3, **topo_kw),
        serve_config=ServeConfig(watchdog=False, queue_limit=8,
                                 max_new_tokens=8),
        router_config=RouterConfig(prefill_threshold=4,
                                   probe_interval=0.02,
                                   probe_backoff_max=0.04,
                                   integrity_interval=0.0),
        clock=clock_twin, log_dir=tmp_path / 'twin')
    try:
        twin.submit(prompt, request_id='v')
        ticks = 0
        while twin.step():
            clock_twin.advance(0.01)
            ticks += 1
            assert ticks < 5000
        base = twin.results
    finally:
        twin.close()
    assert base['v'].status == 'completed'

    with obs_flight.recording(base_dir=tmp_path / 'flight',
                              registry=MetricsRegistry()) as rec:
        clock = VirtualClock()
        router = build_serving(
            TopologyConfig(decode_replicas=2, slots=2, t_max=64,
                           page_size=16, vocab=VOCAB, seed=3,
                           **topo_kw),
            serve_config=ServeConfig(watchdog=False, queue_limit=8,
                                     max_new_tokens=8),
            router_config=RouterConfig(prefill_threshold=4,
                                       probe_interval=0.02,
                                       probe_backoff_max=0.04,
                                       integrity_interval=0.0),
            clock=clock, log_dir=tmp_path / 'logs')
        try:
            router.submit(prompt, request_id='v')
            router.step()
            clock.advance(0.01)
            target = router._ledger['v']['replica']
            eng = next(r for r in router.pool.replicas
                       if r.name == target).engine
            tracked = eng.tracked_pages()
            assert tracked, 'handoff registered no pages'
            victim = tracked[0]
            eng.flip_page_bit(victim)
            ticks = 0
            while router.step():
                clock.advance(0.01)
                ticks += 1
                assert ticks < 5000
            results = router.results
        finally:
            router.close()
        dumps = [d for d in rec.dumps if d['trigger'] == 'kv_corrupt']

    assert results['v'].status == 'completed'
    assert results['v'].tokens == base['v'].tokens

    revs = list(obs.read_events(dict(router.pool.logs())['router']))
    corrupt = [r for r in revs if r['event'] == 'kv.corrupt']
    assert len(corrupt) == 1
    assert corrupt[0]['target'] == target
    assert victim in corrupt[0]['pages']
    assert corrupt[0]['shards'] == [eng.page_shard(victim)]
    handoffs = [r for r in
                obs.read_events(dict(router.pool.logs())['prefill'])
                if r['event'] == 'prefill.handoff']
    assert handoffs and all(r['kv_shards'] == 2 for r in handoffs)
    tls = reconstruct(router.pool.logs())
    assert tls['v'].complete, tls['v'].errors
    assert tls['v'].corruptions == 1 and tls['v'].recoveries == 1

    assert len(dumps) == 1
    incident = obs_doctor.diagnose(obs_flight.load_bundle(
        dumps[0]['path']))
    assert incident.primary == 'kv_corruption'
    joined = ' '.join(incident.classes['kv_corruption']['evidence'])
    assert 'kv shard(s)' in joined
    assert str(eng.page_shard(victim)) in joined
