# -*- coding: utf-8 -*-
"""
Verify-k decode + acceptance-prefix rollback (models/decode.py,
ops/pallas_decode.py) — the kernel half of speculative decoding.

The contracts that make draft-verify decoding EXACT, each pinned here:

- a verify-k step (``decode_step`` with ``q (B, H, k, d)`` + per-slot
  ``counts``) is BIT-IDENTICAL per query row to running k sequential
  single-token steps *on the same impl* — that per-impl identity is
  what makes a speculative stream token-for-token the non-speculative
  stream, whatever the proposer guessed;
- the kernel and XLA verify-k formulations agree to the suite's float
  tolerance (exp2- vs exp-softmax rounding, same as the n=1 parity
  tests) while each stays bitwise-consistent with itself;
- acceptance-prefix rollback (``rollback_slots`` /
  ``paged_rollback_slots`` + ``PagePool.truncate``) leaves the cache
  bit-identical to having appended ONLY the accepted tokens — no
  residue from rejected proposals for any later read.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.decode import (
    PagePool, decode_step, init_cache, init_paged_cache,
    init_slot_cache, paged_rollback_slots, rollback_slots,
)

B, D, T = 2, 8, 32
K = 3                     # verify width (proposals per step)
PRE = [5, 9]              # staggered pre-fill per slot


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype)


def _prefill(cache, impl, steps=max(PRE), key=100, **kw):
    """Advance each slot to its PRE fill through n=1 steps of ``impl``
    (the per-impl oracle must build its prefix on the same impl)."""
    interp = True if impl == 'kernel' else None
    for i in range(steps):
        mask = jnp.asarray([i < p for p in PRE])
        h_kv = cache.k.shape[1]
        h = 2 * h_kv
        cache, _ = decode_step(
            _rand(key + 3 * i, (B, h, 1, D)), cache,
            _rand(key + 3 * i + 1, (B, h_kv, 1, D)),
            _rand(key + 3 * i + 2, (B, h_kv, 1, D)),
            slot_mask=mask, impl=impl, interpret=interp, **kw)
    return cache


def _sequential(cache, impl, q, kn, vn, counts, **kw):
    """The oracle: per slot, ``counts[i]`` single-token steps on the
    same impl. Returns (cache, outs (B, H, K, D) with don't-care rows
    left zero)."""
    interp = True if impl == 'kernel' else None
    outs = np.zeros(q.shape, np.float32)
    for j in range(K):
        mask = jnp.asarray([j < int(counts[i]) for i in range(B)])
        cache, o = decode_step(
            q[:, :, j:j + 1], cache, kn[:, :, j:j + 1],
            vn[:, :, j:j + 1], slot_mask=mask, impl=impl,
            interpret=interp, **kw)
        outs[:, :, j] = np.asarray(o, np.float32)[:, :, 0]
    return cache, outs


@pytest.mark.parametrize('impl', ['xla', 'kernel'])
@pytest.mark.parametrize('h,h_kv,kw', [
    (2, 2, {}),                                            # MHA
    (4, 2, {}),                                            # GQA
    (4, 2, {'window': 8}),                                 # sliding
    (4, 2, {'alibi_slopes': tuple(2.0 ** -(i + 1)         # ALiBi
                                  for i in range(4))}),
])
def test_verify_k_matches_sequential_bitwise(impl, h, h_kv, kw):
    """One verify-k step == counts[i] sequential n=1 steps, BITWISE on
    the same impl (outputs and cache), mixed counts across the batch."""
    kw = dict(kw)
    if 'alibi_slopes' in kw:
        kw['alibi_slopes'] = jnp.asarray(kw['alibi_slopes'])
    cache0 = _prefill(init_slot_cache(B, h_kv, T, D, dtype=jnp.float32),
                      impl, **kw)
    q = _rand(0, (B, h, K, D))
    kn = _rand(1, (B, h_kv, K, D))
    vn = _rand(2, (B, h_kv, K, D))
    counts = jnp.asarray([K, K - 1], jnp.int32)
    ref_cache, ref_out = _sequential(cache0, impl, q, kn, vn, counts,
                                     **kw)
    interp = True if impl == 'kernel' else None
    cv, ov = decode_step(q, cache0, kn, vn, counts=counts, impl=impl,
                         interpret=interp, **kw)
    ov = np.asarray(ov, np.float32)
    for i in range(B):
        c = int(counts[i])
        if impl == 'xla' and h == h_kv:
            # CPU XLA lowers the M=1 score/context dots as gemv and
            # the M=k ones as gemm — different accumulation order at
            # group 1 (GQA folds group·n rows into M, so both shapes
            # take the gemm path and stay bitwise). The kernel impl is
            # bitwise in every configuration: its block math is
            # identical for n = 1 and n > 1.
            np.testing.assert_allclose(ov[i, :, :c], ref_out[i, :, :c],
                                       atol=1e-6, rtol=1e-6)
        else:
            np.testing.assert_array_equal(ov[i, :, :c],
                                          ref_out[i, :, :c])
    np.testing.assert_array_equal(np.asarray(cv.k),
                                  np.asarray(ref_cache.k))
    np.testing.assert_array_equal(np.asarray(cv.v),
                                  np.asarray(ref_cache.v))
    np.testing.assert_array_equal(np.asarray(cv.length),
                                  np.asarray(ref_cache.length))


def test_verify_k_kernel_vs_xla_tolerance():
    """Across impls the two verify-k formulations agree to the n=1
    parity tolerance (exp2 vs exp rounding — bit-identity is a
    per-impl guarantee, same as the engine's)."""
    h, h_kv = 4, 2
    cache0 = _prefill(init_slot_cache(B, h_kv, T, D,
                                      dtype=jnp.float32), 'xla')
    q = _rand(0, (B, h, K, D))
    kn = _rand(1, (B, h_kv, K, D))
    vn = _rand(2, (B, h_kv, K, D))
    counts = jnp.asarray([K, 1], jnp.int32)
    cx, ox = decode_step(q, cache0, kn, vn, counts=counts, impl='xla')
    ck, ok = decode_step(q, cache0, kn, vn, counts=counts,
                         impl='kernel')
    for i in range(B):
        c = int(counts[i])
        np.testing.assert_allclose(
            np.asarray(ok)[i, :, :c], np.asarray(ox)[i, :, :c],
            atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ck.k), np.asarray(cx.k),
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ck.length),
                                  np.asarray(cx.length))


def test_verify_k_zero_count_slot_frozen():
    """counts[i] = 0 freezes the slot exactly like slot_mask=False: no
    append, length unchanged, buffers bit-identical."""
    h_kv = 2
    cache0 = _prefill(init_slot_cache(B, h_kv, T, D,
                                      dtype=jnp.float32), 'xla')
    q = _rand(0, (B, 4, K, D))
    kn = _rand(1, (B, h_kv, K, D))
    vn = _rand(2, (B, h_kv, K, D))
    counts = jnp.asarray([2, 0], jnp.int32)
    cv, _ = decode_step(q, cache0, kn, vn, counts=counts, impl='xla')
    assert [int(x) for x in cv.length] == [PRE[0] + 2, PRE[1]]
    np.testing.assert_array_equal(np.asarray(cv.k)[1],
                                  np.asarray(cache0.k)[1])


def test_verify_k_overflow_contract():
    """Concrete per-slot overflow raises eagerly naming the slot and
    the row count; traced overflow writes nothing while the length
    still advances (the append contract, verify-k width)."""
    cache = init_slot_cache(2, 2, 8, D, dtype=jnp.float32)
    cache = cache._replace(length=jnp.asarray([7, 1], jnp.int32))
    q = jnp.ones((2, 2, K, D))
    one = jnp.ones((2, 2, K, D))
    with pytest.raises(ValueError, match=r'slot 0.*3 new'):
        decode_step(q, cache, one, one, impl='xla')
    out_c, _ = jax.jit(
        lambda c, q, k, v: decode_step(q, c, k, v, impl='kernel',
                                       interpret=True)
    )(cache, q, one, one)
    assert [int(x) for x in out_c.length] == [10, 4]
    assert float(jnp.abs(out_c.k[0]).sum()) == 0.0       # wrote nothing
    assert float(jnp.abs(out_c.k[1]).sum()) > 0.0        # in-bounds did


# -- acceptance-prefix rollback ----------------------------------------

def test_rollback_bit_identical_to_accepted_only():
    """Append K proposals per slot, roll back to the accepted prefix:
    the cache must be BIT-IDENTICAL to having appended only the
    accepted rows (buffers, lengths — no rejected-row residue)."""
    h_kv = 2
    cache0 = _prefill(init_slot_cache(B, h_kv, T, D,
                                      dtype=jnp.float32), 'xla')
    q = _rand(0, (B, 4, K, D))
    kn = _rand(1, (B, h_kv, K, D))
    vn = _rand(2, (B, h_kv, K, D))
    accepted = [1, 2]
    ca, _ = decode_step(q, cache0, kn, vn, impl='xla')
    target = jnp.asarray(np.asarray(cache0.length) + accepted,
                         jnp.int32)
    cr = rollback_slots(ca, target)
    ref, _ = _sequential(cache0, 'xla', q, kn, vn,
                         jnp.asarray(accepted, jnp.int32))
    np.testing.assert_array_equal(np.asarray(cr.k), np.asarray(ref.k))
    np.testing.assert_array_equal(np.asarray(cr.v), np.asarray(ref.v))
    np.testing.assert_array_equal(np.asarray(cr.length),
                                  np.asarray(ref.length))
    # The surgical span path (the serving hot path: O(B·span·d)
    # scatter, not a full-cache rewrite) is bit-identical to the
    # full-mask path.
    cs = rollback_slots(ca, target, span=K)
    np.testing.assert_array_equal(np.asarray(cs.k), np.asarray(ref.k))
    np.testing.assert_array_equal(np.asarray(cs.v), np.asarray(ref.v))
    np.testing.assert_array_equal(np.asarray(cs.length),
                                  np.asarray(ref.length))


def test_rollback_sentinel_leaves_slots_untouched():
    """min(current, target): a past-fill sentinel rolls nothing back,
    so ONE batched call serves a few slots without disturbing the
    rest."""
    h_kv = 2
    cache = _prefill(init_slot_cache(B, h_kv, T, D,
                                     dtype=jnp.float32), 'xla')
    big = np.iinfo(np.int32).max
    cr = rollback_slots(cache, jnp.asarray([3, big], jnp.int32))
    assert [int(x) for x in cr.length] == [3, PRE[1]]
    np.testing.assert_array_equal(np.asarray(cr.k)[1],
                                  np.asarray(cache.k)[1])
    assert float(jnp.abs(np.asarray(cr.k)[0, :, 3:]).sum()) == 0.0


def test_rollback_int8_mirror():
    """Mirror-carrying caches roll the k_q/k_scale rows back with the
    K rows — a later int8 step must not dequantize rejected residue."""
    cache0 = init_cache(B, 2, T, D, dtype=jnp.float32, qk_quant='int8')
    kn = _rand(1, (B, 2, K, D))
    vn = _rand(2, (B, 2, K, D))
    q = _rand(0, (B, 4, K, D))
    ca, _ = decode_step(q, cache0, kn, vn, impl='xla',
                        qk_quant='int8')
    cr = rollback_slots(ca, jnp.asarray(1, jnp.int32))
    ref, _ = decode_step(q[:, :, :1], cache0, kn[:, :, :1],
                         vn[:, :, :1], impl='xla', qk_quant='int8')
    np.testing.assert_array_equal(np.asarray(cr.k_q),
                                  np.asarray(ref.k_q))
    np.testing.assert_array_equal(np.asarray(cr.k_scale),
                                  np.asarray(ref.k_scale))
    assert int(cr.length) == 1


def test_rollback_paged_raises():
    cache = init_paged_cache(B, 2, T, D, pages=4, page_size=8)
    with pytest.raises(ValueError, match='paged_rollback_slots'):
        rollback_slots(cache, jnp.asarray([0, 0], jnp.int32))


# -- paged verify + rollback -------------------------------------------

def _paged_setup(ps=8, pages=10):
    cache = init_paged_cache(B, 2, T, D, pages=pages, page_size=ps,
                             dtype=jnp.float32)
    pool = PagePool(pages, ps, B, T // ps)
    for i in range(max(PRE)):
        mask = np.array([i < p for p in PRE])
        for s in np.nonzero(mask)[0]:
            st, src, dst = pool.prepare_append(int(s))
            assert st in ('ok', 'alloc')
        cache = cache._replace(page_table=jnp.asarray(pool.table))
        cache, _ = decode_step(
            _rand(200 + 3 * i, (B, 4, 1, D)), cache,
            _rand(201 + 3 * i, (B, 2, 1, D)),
            _rand(202 + 3 * i, (B, 2, 1, D)),
            slot_mask=jnp.asarray(mask), impl='xla')
        pool.lengths[mask] += 1
    return cache, pool


@pytest.mark.parametrize('impl', ['xla', 'kernel'])
def test_paged_verify_k_matches_sequential(impl):
    """Paged verify-k == sequential paged steps, bitwise per impl —
    the page-table BlockSpec redirect changes DMA, not math."""
    cache, pool = _paged_setup()
    for s in range(B):
        ok, copies = pool.reserve_rows(s, K)
        assert ok and not copies
    cache = cache._replace(page_table=jnp.asarray(pool.table))
    q = _rand(0, (B, 4, K, D))
    kn = _rand(1, (B, 2, K, D))
    vn = _rand(2, (B, 2, K, D))
    counts = jnp.asarray([K, 2], jnp.int32)
    ref_cache, ref_out = _sequential(cache, impl, q, kn, vn, counts)
    interp = True if impl == 'kernel' else None
    cv, ov = decode_step(q, cache, kn, vn, counts=counts, impl=impl,
                         interpret=interp)
    ov = np.asarray(ov, np.float32)
    for i in range(B):
        c = int(counts[i])
        np.testing.assert_array_equal(ov[i, :, :c], ref_out[i, :, :c])
    # Live pages only: the reserved SINK row (last pool page) parks
    # idle grid rows' mandatory block flushes — its bits are don't-care
    # garbage by contract and legitimately differ between schedules.
    pages = cv.pages
    np.testing.assert_array_equal(np.asarray(cv.k_pool)[:pages],
                                  np.asarray(ref_cache.k_pool)[:pages])
    np.testing.assert_array_equal(np.asarray(cv.v_pool)[:pages],
                                  np.asarray(ref_cache.v_pool)[:pages])


def test_paged_rollback_bit_identical_and_returns_pages():
    """Paged rollback: the pool is bit-identical to having appended
    only the accepted rows, and PagePool.truncate releases exactly the
    now-empty tail pages (refcounts back on the free list)."""
    cache, pool = _paged_setup(ps=4)
    for s in range(B):
        ok, _ = pool.reserve_rows(s, K)
        assert ok
    cache = cache._replace(page_table=jnp.asarray(pool.table))
    q = _rand(0, (B, 4, K, D))
    kn = _rand(1, (B, 2, K, D))
    vn = _rand(2, (B, 2, K, D))
    accepted = [0, 2]
    ca, _ = decode_step(q, cache, kn, vn, impl='xla')
    pool.lengths[:] += K
    pre = np.array(PRE)
    target = jnp.asarray(pre + accepted, jnp.int32)
    cr = paged_rollback_slots(ca, target, span=K)
    # Reference: only the accepted rows ever appended (fresh pool walk
    # over the same page tables — reserve_rows already mapped them).
    ref, _ = _sequential(cache, 'xla', q, kn, vn,
                         jnp.asarray(accepted, jnp.int32))
    np.testing.assert_array_equal(np.asarray(cr.k_pool),
                                  np.asarray(ref.k_pool))
    np.testing.assert_array_equal(np.asarray(cr.v_pool),
                                  np.asarray(ref.v_pool))
    np.testing.assert_array_equal(np.asarray(cr.length),
                                  np.asarray(ref.length))
    # Host side: truncate returns exactly the now-empty tail pages.
    free_before = pool.free_pages
    used_before = [pool.slot_pages(s) for s in range(B)]
    for s, tgt in enumerate(np.asarray(pre) + accepted):
        freed = pool.truncate(s, int(tgt))
        want = used_before[s] - pool.pages_for_rows(int(tgt))
        assert len(freed) == want
        assert pool.lengths[s] == tgt
    assert pool.free_pages >= free_before
    # A no-op truncate (target >= fill) frees nothing.
    assert pool.truncate(0, T) == []


def test_paged_truncate_returns_boundary_pages():
    """A rollback that retreats across a page boundary RETURNS the
    opened tail page: refcount to zero, back on the free list, the
    slot's table entry cleared."""
    ps = 4
    pool = PagePool(6, ps, 1, T // ps)
    ok, _ = pool.reserve_rows(0, 2 * ps)      # two full pages
    assert ok
    pool.lengths[0] = 2 * ps
    ok, _ = pool.reserve_rows(0, 3)           # verify-k opens page 3
    assert ok and pool.slot_pages(0) == 3
    pool.lengths[0] = 2 * ps + 3              # the verify appended
    free_before = pool.free_pages
    opened = int(pool.table[0, 2])
    freed = pool.truncate(0, 2 * ps)          # reject every proposal
    assert freed == [opened]
    assert pool.free_pages == free_before + 1
    assert pool.slot_pages(0) == 2
    assert int(pool.table[0, 2]) == -1
    assert pool.lengths[0] == 2 * ps
