# -*- coding: utf-8 -*-
"""
Fused Pallas decode kernel (ops/pallas_decode.py) — parity and alias
safety. The oracle is the existing XLA formulation (``append_kv*`` +
``decode_attention``), pinned bit-for-tolerance across batch, heads,
GQA, int8, per-slot lengths, window and ALiBi; the alias tests pin the
in-place contract — ONE cache block written per step, every other bit
untouched, and nothing stale after an eviction. On the CPU mesh the
kernel runs under the Pallas interpreter (the same code path the TPU
compiles), exactly like the training-kernel suites.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.decode import (
    append_kv, append_kv_slots, decode_kernel_eligible, decode_step,
    init_cache, init_slot_cache, reset_slot,
)
from distributed_dot_product_tpu.ops.pallas_decode import decode_block_k

B, D, T = 3, 8, 16
LENS = [5, 9, 0]        # staggered slot fills, incl. an empty slot


def _operands(h, h_kv, key=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 5)
    q = jax.random.normal(ks[0], (B, h, 1, D), dtype)
    kn = jax.random.normal(ks[1], (B, h_kv, 1, D), dtype)
    vn = jax.random.normal(ks[2], (B, h_kv, 1, D), dtype)
    kf = jax.random.normal(ks[3], (B, h_kv, T, D), dtype)
    vf = jax.random.normal(ks[4], (B, h_kv, T, D), dtype)
    return q, kn, vn, kf, vf


def _filled(h_kv, kf, vf, lens=LENS, dtype=jnp.float32):
    cache = init_slot_cache(B, h_kv, T, D, dtype=dtype)
    return append_kv_slots(cache, kf, vf,
                           counts=jnp.asarray(lens, jnp.int32))


def _both(q, cache_fn, kn, vn, **kw):
    cx, ox = decode_step(q, cache_fn(), kn, vn, impl='xla', **kw)
    ck, ok = decode_step(q, cache_fn(), kn, vn, impl='kernel', **kw)
    return (cx, ox), (ck, ok)


def _assert_cache_match(ck, cx):
    np.testing.assert_array_equal(np.asarray(ck.length),
                                  np.asarray(cx.length))
    for name in ('k', 'v', 'k_q', 'k_scale'):
        a, b = getattr(ck, name), getattr(cx, name)
        assert (a is None) == (b is None), name
        if a is not None:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, err_msg=name)


@pytest.mark.parametrize('h,h_kv', [(2, 2), (4, 2), (4, 1)])
@pytest.mark.parametrize('kw', [{}, {'window': 4}])
def test_kernel_matches_xla_per_slot(h, h_kv, kw):
    """Per-slot staggered lengths (incl. an empty slot), MHA/GQA/MQA,
    with and without a sliding window."""
    q, kn, vn, kf, vf = _operands(h, h_kv)
    (cx, ox), (ck, ok) = _both(q, lambda: _filled(h_kv, kf, vf),
                               kn, vn, **kw)
    _assert_cache_match(ck, cx)
    np.testing.assert_allclose(np.asarray(ok), np.asarray(ox),
                               atol=1e-5, rtol=1e-5)


def test_kernel_matches_xla_alibi():
    h = 4
    q, kn, vn, kf, vf = _operands(h, 2, key=1)
    slopes = jnp.asarray([2.0 ** -(i + 1) for i in range(h)])
    (cx, ox), (ck, ok) = _both(q, lambda: _filled(2, kf, vf), kn, vn,
                               alibi_slopes=slopes)
    _assert_cache_match(ck, cx)
    np.testing.assert_allclose(np.asarray(ok), np.asarray(ox),
                               atol=1e-5, rtol=1e-5)


def test_kernel_matches_xla_slot_mask():
    """Frozen slots append nothing and attend their un-advanced prefix;
    the kernel and XLA steps agree on buffers, lengths AND outputs."""
    q, kn, vn, kf, vf = _operands(2, 2, key=2)
    mask = jnp.asarray([True, False, True])
    (cx, ox), (ck, ok) = _both(q, lambda: _filled(2, kf, vf), kn, vn,
                               slot_mask=mask)
    _assert_cache_match(ck, cx)
    np.testing.assert_allclose(np.asarray(ok), np.asarray(ox),
                               atol=1e-5, rtol=1e-5)


def test_kernel_matches_xla_scalar_cache_bf16():
    """Scalar-length cache (one clock for the whole batch), bf16
    buffers — the greedy-generation configuration."""
    q, kn, vn, kf, vf = _operands(2, 2, key=3, dtype=jnp.bfloat16)

    def cache_fn():
        c = init_cache(B, 2, T, D, dtype=jnp.bfloat16)
        return append_kv(c, kf[:, :, :6], vf[:, :, :6])

    (cx, ox), (ck, ok) = _both(q, cache_fn, kn, vn)
    _assert_cache_match(ck, cx)
    np.testing.assert_allclose(np.asarray(ok, dtype=np.float32),
                               np.asarray(ox, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)
    assert int(ck.length) == 7


def test_kernel_matches_xla_int8_mirror():
    """int8-trained decode through the append-time K mirror: the kernel
    dequantizes in-place-streamed int8 blocks and must reproduce the
    XLA mirror path's logits — and maintain the mirror bit-identically
    (rows quantize once, at append)."""
    q, kn, vn, kf, vf = _operands(4, 2, key=4)

    def cache_fn():
        c = init_cache(B, 2, T, D, dtype=jnp.float32, qk_quant='int8')
        return append_kv(c, kf[:, :, :9], vf[:, :, :9])

    (cx, ox), (ck, ok) = _both(q, cache_fn, kn, vn, qk_quant='int8')
    np.testing.assert_array_equal(np.asarray(ck.k_q),
                                  np.asarray(cx.k_q))
    np.testing.assert_allclose(np.asarray(ck.k_scale),
                               np.asarray(cx.k_scale), atol=1e-7)
    np.testing.assert_allclose(np.asarray(ok), np.asarray(ox),
                               atol=1e-5, rtol=1e-5)


def test_kernel_first_token_empty_cache():
    """Length-0 slots appending their first row attend exactly that row
    — out = v_new per head group, no NaN from the empty prefix."""
    q, kn, vn, _, _ = _operands(4, 2, key=5)
    cache = init_slot_cache(B, 2, T, D, dtype=jnp.float32)
    ck, ok = decode_step(q, cache, kn, vn, impl='kernel')
    want = jnp.repeat(vn, 2, axis=1)        # softmax over one column
    np.testing.assert_allclose(np.asarray(ok), np.asarray(want),
                               atol=1e-6)
    assert [int(x) for x in ck.length] == [1, 1, 1]


def test_kernel_alias_in_place_and_surgical():
    """The in-place append contract: exactly one row changes per slot —
    every other bit of every buffer is IDENTICAL before/after."""
    q, kn, vn, kf, vf = _operands(2, 2, key=6)
    before = _filled(2, kf, vf)
    ck, _ = decode_step(q, before, kn, vn, impl='kernel')
    bk, bv = np.asarray(before.k), np.asarray(before.v)
    ak, av = np.asarray(ck.k), np.asarray(ck.v)
    for i, ln in enumerate(LENS):
        np.testing.assert_array_equal(ak[i, :, :ln], bk[i, :, :ln])
        np.testing.assert_array_equal(ak[i, :, ln + 1:],
                                      bk[i, :, ln + 1:])
        np.testing.assert_array_equal(ak[i, :, ln],
                                      np.asarray(kn)[i, :, 0])
        np.testing.assert_array_equal(av[i, :, ln],
                                      np.asarray(vn)[i, :, 0])


def test_kernel_not_stale_after_eviction():
    """Evict a filled slot (reset_slot), serve a fresh sequence through
    fused steps: the attention must see ONLY the new rows (a stale
    block would poison the new stream bit-visibly)."""
    q, kn, vn, kf, vf = _operands(2, 2, key=7)
    cache = _filled(2, kf, vf, lens=[12, 3, 7])
    cache = reset_slot(cache, 0)
    only0 = jnp.asarray([True, False, False])
    # Two fused steps land rows 0 and 1 of the fresh sequence.
    cache, _ = decode_step(q, cache, kn, vn, slot_mask=only0,
                           impl='kernel')
    cache, out = decode_step(q, cache, kn + 1.0, vn + 1.0,
                             slot_mask=only0, impl='kernel')
    # Oracle: the same two rows alone in a fresh single-slot cache.
    solo = init_slot_cache(1, 2, T, D, dtype=jnp.float32)
    solo, _ = decode_step(q[:1], solo, kn[:1], vn[:1], impl='xla')
    solo, want = decode_step(q[:1], solo, kn[:1] + 1.0, vn[:1] + 1.0,
                             impl='xla')
    np.testing.assert_allclose(np.asarray(out[:1]), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert [int(x) for x in cache.length] == [2, 3, 7]
    # The evicted slot's tail is still zero — nothing stale survived.
    assert float(jnp.abs(cache.k[0, :, 2:]).sum()) == 0.0


def test_kernel_overflow_contract():
    """Traced overflow: the fused step writes NOTHING for a full slot
    while its length still advances (append_kv_slots' contract);
    concrete overflow raises eagerly naming the slot."""
    cache = init_slot_cache(2, 2, 4, D, dtype=jnp.float32)
    cache = cache._replace(length=jnp.asarray([4, 1], jnp.int32))
    q = jnp.ones((2, 2, 1, D))
    one = jnp.ones((2, 2, 1, D))
    with pytest.raises(ValueError, match='slot 0'):
        decode_step(q, cache, one, one, impl='kernel')
    out_c, _ = jax.jit(
        lambda c, q, k, v: decode_step(q, c, k, v, impl='kernel')
    )(cache, q, one, one)
    assert [int(x) for x in out_c.length] == [5, 2]
    assert float(jnp.abs(out_c.k[0]).sum()) == 0.0       # wrote nothing
    assert float(jnp.abs(out_c.k[1]).sum()) > 0.0        # in-bounds did


def test_kernel_eligibility_and_fallback():
    """The kernel covers the serving hot path; everything else resolves
    to the XLA step under 'auto' and refuses under 'kernel'."""
    assert decode_block_k(16) == 16
    assert decode_block_k(131072) == 1024
    assert decode_block_k(3 * 1024) == 1024
    assert decode_block_k(1027) is None              # prime > cap
    cache = init_slot_cache(B, 2, T, D, dtype=jnp.float32)
    assert decode_kernel_eligible(cache)
    # Verify-k: n up to the K split is kernel-native; wider calls and
    # quantized verify-k fall back to the XLA formulation.
    assert decode_kernel_eligible(cache, n=2)
    assert decode_kernel_eligible(cache, n=decode_block_k(T))
    assert not decode_kernel_eligible(cache, n=decode_block_k(T) + 1)
    assert not decode_kernel_eligible(cache, n=0)
    assert not decode_kernel_eligible(cache, segment_ids=jnp.zeros(
        (B, T), jnp.int32))
    assert not decode_kernel_eligible(cache, qk_quant='int8')  # no mirror
    mirror = init_cache(B, 2, T, D, dtype=jnp.float32, qk_quant='int8')
    assert decode_kernel_eligible(mirror, qk_quant='int8')
    assert not decode_kernel_eligible(mirror, n=2, qk_quant='int8')
    q, kn, vn, kf, vf = _operands(2, 2, key=8)
    seg = jnp.zeros((B, T), jnp.int32)
    seg_q = jnp.zeros((B, 1), jnp.int32)
    with pytest.raises(ValueError, match='fused kernel'):
        decode_step(q, _filled(2, kf, vf), kn, vn, impl='kernel',
                    segment_ids=seg, seg_q=seg_q)
    # auto + segments: falls back, matches the explicit XLA step.
    ca, oa = decode_step(q, _filled(2, kf, vf), kn, vn, impl='auto',
                         segment_ids=seg, seg_q=seg_q)
    cx, ox = decode_step(q, _filled(2, kf, vf), kn, vn, impl='xla',
                         segment_ids=seg, seg_q=seg_q)
    np.testing.assert_array_equal(np.asarray(oa), np.asarray(ox))


def test_module_decode_kernel_matches_xla():
    """Module surface: projections + GQA + RoPE + fused kernel step ==
    the XLA step, token by token (decode_impl is the only delta)."""
    from distributed_dot_product_tpu import DistributedDotProductAttn
    dim = 32
    kw = dict(key_dim=dim, num_heads=4, num_kv_heads=2, causal=True,
              use_rope=True, distributed=False)
    mx = DistributedDotProductAttn(decode_impl='xla', **kw)
    mk = DistributedDotProductAttn(decode_impl='kernel', **kw)
    x = jax.random.normal(jax.random.key(0), (2, 8, dim), jnp.float32)
    params = mx.init(jax.random.key(1), x, x, x, None)
    cx = mx.make_decode_cache(2, 8)
    ck = mk.make_decode_cache(2, 8)
    for t in range(4):
        xt = x[:, t:t + 1]
        cx, ox = mx.apply(params, xt, xt, xt, cx, method='decode')
        ck, ok = mk.apply(params, xt, xt, xt, ck, method='decode')
        np.testing.assert_allclose(np.asarray(ok), np.asarray(ox),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f't={t}')
    np.testing.assert_allclose(np.asarray(ck.k), np.asarray(cx.k),
                               atol=1e-6)


def test_engine_kernel_path_streams():
    """KernelEngine on the fused kernel path: same slot lifecycle and
    (to greedy-argmax stability at these magnitudes) the same token
    streams as the XLA path."""
    from distributed_dot_product_tpu.serve import KernelEngine

    def drive(impl):
        eng = KernelEngine(slots=3, t_max=32, vocab=16, heads=2,
                           head_dim=4, prefill_chunk=4, seed=5,
                           decode_impl=impl)
        eng.prefill(0, [1, 2, 3])
        eng.prefill(1, [4, 5])
        toks = np.array([3, 5, 0], np.int32)
        act = np.array([True, True, False])
        stream = []
        for _ in range(6):
            toks, fin = eng.step(toks, act)
            assert fin.all()
            stream.append(toks.copy())
        return eng.lengths(), stream

    lens_x, stream_x = drive('xla')
    lens_k, stream_k = drive('kernel')
    np.testing.assert_array_equal(lens_k, lens_x)
    for a, b in zip(stream_x, stream_k):
        np.testing.assert_array_equal(a, b)
