# -*- coding: utf-8 -*-
"""
Span layer (obs/spans.py): nesting, thread isolation, the zero-overhead
disabled path, decorator semantics, and the metrics-registry mirror.
"""

import threading

import pytest

from distributed_dot_product_tpu.obs import spans
from distributed_dot_product_tpu.obs.spans import (
    SpanCollector, collecting, span, spanned,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_collector():
    """Each test starts disabled with an empty buffer and leaves no
    global enablement behind."""
    col = spans.get_collector()
    prev = (col.enabled, col.registry)
    col.enabled = False
    col.registry = None
    col.clear()
    yield col
    col.enabled, col.registry = prev
    col.clear()


def test_disabled_span_is_shared_null_object():
    """The disabled path allocates nothing: every span() call returns
    the SAME null context manager (no clock read, no record)."""
    a, b = span('x'), span('y', attr=1)
    assert a is b
    with a:
        pass
    assert spans.get_collector().records() == []


def test_nesting_builds_paths_and_depths():
    with collecting() as col:
        with span('outer'):
            with span('inner'):
                pass
            with span('inner2'):
                pass
    recs = {r.name: r for r in col.records()}
    assert recs['inner'].path == 'outer/inner'
    assert recs['inner'].depth == 1
    assert recs['inner2'].path == 'outer/inner2'
    assert recs['outer'].path == 'outer'
    assert recs['outer'].depth == 0
    # Children finish before the parent; durations nest.
    assert recs['outer'].seconds >= recs['inner'].seconds
    assert all(r.ok for r in col.records())


def test_span_records_exception_and_propagates():
    with collecting() as col:
        with pytest.raises(ValueError):
            with span('boom'):
                raise ValueError('x')
    (rec,) = col.records()
    assert rec.name == 'boom' and not rec.ok
    # The stack unwound: a following span is top-level again.
    with collecting() as col2:
        with span('after'):
            pass
    assert col2.records()[-1].depth == 0


def test_attrs_recorded():
    with collecting() as col:
        with span('s', step=3, kind='decode'):
            pass
    (rec,) = col.records()
    assert dict(rec.attrs) == {'step': 3, 'kind': 'decode'}


def test_decorator_rechecks_enablement_per_call():
    calls = []

    @spanned('unit.work')
    def work(x):
        calls.append(x)
        return x * 2

    assert work(2) == 4                       # disabled: plain call
    assert spans.get_collector().records() == []
    with collecting() as col:
        assert work(3) == 6                   # enabled later: recorded
    assert [r.name for r in col.records()] == ['unit.work']
    assert calls == [2, 3]


def test_decorator_default_name_is_qualname():
    @spanned()
    def some_phase():
        return 1

    with collecting() as col:
        some_phase()
    (rec,) = col.records()
    assert 'some_phase' in rec.name


def test_thread_isolated_nesting():
    """Two threads nesting concurrently never see each other's stack:
    every recorded path is one of the two legal per-thread shapes."""
    errors = []

    def worker(tag):
        try:
            for _ in range(50):
                with span(f'{tag}.outer'):
                    with span(f'{tag}.inner'):
                        pass
        # Collected and re-asserted on the main thread — not swallowed.
        except Exception as e:   # graphlint: allow[silent-except]
            errors.append(e)

    with collecting() as col:
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in ('a', 'b')]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    for rec in col.records():
        tag = rec.name.split('.')[0]
        assert rec.path in (f'{tag}.outer', f'{tag}.outer/{tag}.inner')


def test_registry_mirror_histograms():
    reg = MetricsRegistry()
    with collecting(registry=reg):
        for _ in range(3):
            with span('phase.compile'):
                pass
    snap = reg.snapshot()['histograms']
    assert snap['span.phase.compile.seconds']['total_count'] == 3


def test_collector_summary_and_render():
    col = SpanCollector()
    col.enabled = True
    # Use a private collector via the record API (not the global).
    from distributed_dot_product_tpu.obs.spans import _LiveSpan
    with _LiveSpan('a', {}, col):
        with _LiveSpan('b', {}, col):
            pass
    summary = col.summary()
    assert summary['a']['count'] == 1 and summary['b']['count'] == 1
    text = col.render()
    assert 'b:' in text and text.splitlines()[0].startswith('  ')


def test_engine_step_spans_carry_request_ids(devices):
    """The request-id threading contract: engine.step's span names the
    requests it served (observability only — never reaches the compiled
    program)."""
    import numpy as np

    from distributed_dot_product_tpu.serve.engine import KernelEngine

    eng = KernelEngine(slots=2, t_max=8, decode_impl='xla')
    with collecting() as col:
        eng.step(np.zeros(2, np.int32), np.ones(2, bool),
                 request_ids=['r1', None])
        eng.prefill(0, np.asarray([1], np.int32), request_id='r1')
    by_name = {r.name: r for r in col.records()}
    assert dict(by_name['engine.decode_step'].attrs)['requests'] == ('r1',)
    assert dict(by_name['engine.prefill'].attrs)['request'] == 'r1'
