# -*- coding: utf-8 -*-
"""
SLO accounting unit + gate tests (obs/slo.py):

- the classifier's six-way partition (met / missed_ttft / missed_token
  / missed_e2e / rejected / incomplete) with per-tenant overrides;
- check_baseline tolerances, violations naming metric AND tenant,
  slo.violation events landing in the active log;
- the committed SLO_BASELINE.json gate end to end through the CLI —
  the seeded CI smoke passes clean (rc 0) and a seeded regression
  fixture (the same trace on 50x slower virtual ticks) fails (rc 1)
  naming the metric and tenant, mirroring test_obs_perf's
  PERF_BASELINE gate.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import slo as obs_slo
from distributed_dot_product_tpu.obs.slo import (
    CLASSES, SloSpec, check_baseline, classify, goodput, make_baseline,
)
from distributed_dot_product_tpu.obs.timeline import Timeline

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tl(status='completed', ttft=0.01, gaps=(), total=0.1,
        tenant='t0', complete=True):
    return Timeline(request_id='r', events=[], status=status,
                    complete=complete, ttft=ttft,
                    token_gaps=list(gaps), total_seconds=total,
                    tenant=tenant)


def test_classifier_partition():
    spec = SloSpec(ttft=0.1, per_token=0.05, e2e=1.0)
    assert classify(_tl(), spec) == 'met'
    assert classify(_tl(ttft=0.2), spec) == 'missed_ttft'
    assert classify(_tl(ttft=None), spec) == 'missed_ttft'
    assert classify(_tl(gaps=[0.01, 0.2]), spec) == 'missed_token'
    assert classify(_tl(total=2.0), spec) == 'missed_e2e'
    assert classify(_tl(status='rejected'), spec) == 'rejected'
    # Any non-completed terminal — and a truncated lifecycle — is
    # 'incomplete': the stream was not delivered.
    assert classify(_tl(status='evicted'), spec) == 'incomplete'
    assert classify(_tl(status='failed_nan'), spec) == 'incomplete'
    assert classify(_tl(complete=False), spec) == 'incomplete'
    # Classification order: a rejected/incomplete request never counts
    # as a latency miss, a TTFT miss wins over a token miss.
    assert classify(_tl(status='rejected', ttft=9.0), spec) \
        == 'rejected'
    assert classify(_tl(ttft=0.2, gaps=[0.2]), spec) == 'missed_ttft'
    # Disabled checks never miss.
    assert classify(_tl(ttft=9.9, gaps=[9.9], total=9.9),
                    SloSpec()) == 'met'


def test_per_tenant_overrides():
    spec = SloSpec(ttft=0.1, tenants={'batch': {'ttft': 10.0}})
    assert classify(_tl(ttft=0.5, tenant='batch'), spec) == 'met'
    assert classify(_tl(ttft=0.5, tenant='t0'), spec) == 'missed_ttft'
    # Unset override keys inherit the global contract.
    spec = SloSpec(ttft=0.1, per_token=0.05,
                   tenants={'batch': {'ttft': 10.0}})
    assert classify(_tl(ttft=0.5, gaps=[0.2], tenant='batch'),
                    spec) == 'missed_token'


def _records(recs):
    for i, r in enumerate(recs):
        r.setdefault('seq', i)
        r.setdefault('ts', float(i))
        r.setdefault('schema', obs.SCHEMA_VERSION)
    return recs


def test_goodput_over_records_partitions_and_groups_by_tenant():
    recs = _records([
        # a: met (tenant t0)
        {'event': 'serve.admit', 'request_id': 'a', 'slot': 0,
         'tenant': 't0', 'queue_wait': 0.01},
        {'event': 'serve.decode', 'request_id': 'a', 'slot': 0,
         'token_index': 0, 'ttft': 0.02},
        {'event': 'serve.retire', 'request_id': 'a',
         'status': 'completed', 'total_seconds': 0.05, 'tenant': 't0'},
        # b: missed_ttft (tenant t1)
        {'event': 'serve.admit', 'request_id': 'b', 'slot': 1,
         'tenant': 't1', 'queue_wait': 0.2},
        {'event': 'serve.decode', 'request_id': 'b', 'slot': 1,
         'token_index': 0, 'ttft': 0.9},
        {'event': 'serve.retire', 'request_id': 'b',
         'status': 'completed', 'total_seconds': 1.0, 'tenant': 't1'},
        # c: rejected at submit (tenant t1)
        {'event': 'serve.reject', 'request_id': 'c',
         'reason': 'queue_full', 'tenant': 't1'},
    ])
    report = goodput(recs, SloSpec(ttft=0.1))
    assert report.requests == 3
    assert report.counts['met'] == 1
    assert report.counts['missed_ttft'] == 1
    assert report.counts['rejected'] == 1
    assert sum(report.counts.values()) == 3
    assert report.by_request == {'a': 'met', 'b': 'missed_ttft',
                                 'c': 'rejected'}
    assert report.per_tenant['t0']['goodput_pct'] == 100.0
    assert report.per_tenant['t1']['goodput_pct'] == 0.0
    assert sum(tb['requests'] for tb in report.per_tenant.values()) == 3
    assert report.percentiles['ttft']['count'] == 2
    assert report.goodput_pct == pytest.approx(100.0 / 3)


def _report(goodput_pct=90.0, per_tenant=None, requests=10):
    per_tenant = per_tenant or {'t0': 95.0, 't1': 80.0}
    return obs_slo.SloReport(
        spec=SloSpec(ttft=0.1).to_dict(), requests=requests,
        counts={c: 0 for c in CLASSES}, goodput_pct=goodput_pct,
        per_tenant={t: {'requests': 5, 'goodput_pct': g,
                        'counts': {c: 0 for c in CLASSES}}
                    for t, g in per_tenant.items()},
        percentiles={}, statuses={}, by_request={})


def test_check_baseline_gate_names_metric_and_tenant():
    base = make_baseline(_report())
    assert base['schema'] == obs_slo.SLO_BASELINE_SCHEMA
    # Clean: identical report passes.
    assert check_baseline(_report(), base, emit_events=False) == []
    # Within tolerance passes; past it fails naming the metric.
    ok = _report(goodput_pct=82.0)          # -8 pts, tol 10
    assert check_baseline(ok, base, emit_events=False) == []
    bad = _report(goodput_pct=60.0,
                  per_tenant={'t0': 95.0, 't1': 30.0})
    v = check_baseline(bad, base, emit_events=False)
    assert any('goodput_pct' in s and 'tenant' not in s for s in v)
    assert any('tenant t1' in s and 'goodput_pct' in s for s in v)
    assert not any('tenant t0' in s for s in v)
    # Request-count drift is a config error, named as such.
    v = check_baseline(_report(requests=7), base, emit_events=False)
    assert any('requests' in s for s in v)
    # Tenant coverage both directions.
    v = check_baseline(_report(per_tenant={'t0': 95.0}), base,
                       emit_events=False)
    assert any('tenant t1' in s and 'coverage' in s for s in v)
    v = check_baseline(
        _report(per_tenant={'t0': 95.0, 't1': 80.0, 'tX': 1.0}),
        base, emit_events=False)
    assert any('tenant tX' in s and 'coverage' in s for s in v)
    # Unknown baseline schema demands a refresh.
    v = check_baseline(_report(), {'schema': 99}, emit_events=False)
    assert v and 'schema' in v[0]


def test_check_baseline_emits_slo_violation_events(tmp_path):
    log = obs.EventLog(tmp_path / 'gate.jsonl')
    base = make_baseline(_report())
    with obs.activate(log):
        check_baseline(_report(goodput_pct=10.0,
                               per_tenant={'t0': 10.0, 't1': 10.0}),
                       base)
    log.close()
    recs = [r for r in obs.read_events(log.path)
            if r['event'] == 'slo.violation']
    assert recs, 'no slo.violation events landed in the active log'
    metrics = {(r['metric'], r.get('tenant')) for r in recs}
    assert ('goodput_pct', None) in metrics
    assert ('goodput_pct', 't0') in metrics
    _, errors = obs.validate_file(log.path)
    assert errors == []


def test_goodput_merges_multi_replica_logs(tmp_path):
    """A disaggregated request — admit+prefill in the prefill pool's
    log, decode+retire in the decode pool's — classifies from the
    merged pair."""
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    pre = obs.EventLog(tmp_path / 'prefill.jsonl', clock=clock)
    pre.emit('serve.admit', request_id='x', slot=0, tenant='t0',
             queue_wait=0.01)
    pre.emit('serve.prefill', request_id='x', slot=0, pos=4)
    pre.close()
    dec = obs.EventLog(tmp_path / 'decode.jsonl', clock=clock)
    dec.emit('serve.decode', request_id='x', slot=2, token_index=0,
             ttft=0.03)
    dec.emit('serve.retire', request_id='x', status='completed',
             total_seconds=0.05, tenant='t0')
    dec.close()
    report = goodput([('prefill', pre.path), ('decode', dec.path)],
                     SloSpec(ttft=0.1))
    assert report.requests == 1
    assert report.by_request['x'] == 'met'
    assert report.per_tenant['t0']['requests'] == 1


def test_committed_slo_baseline_gate_cli(tmp_path):
    """Tier-1 acceptance: the CI stage end to end, subprocess for
    subprocess — the seeded serve-load smoke (benchmark.py flag
    DEFAULTS) must pass `slo check` against the COMMITTED
    SLO_BASELINE.json; the regression fixture — the same seeded trace
    on 50x slower ticks — must exit 1 naming the metric and at least
    one tenant."""
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu'}

    def smoke(tag, *extra):
        log = tmp_path / f'{tag}.jsonl'
        rows = tmp_path / f'{tag}_rows.json'
        r = subprocess.run(
            [sys.executable, 'benchmark.py', '--mode', 'serve-load',
             '--event-log', str(log), '--file', str(rows), *extra],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == 0, r.stderr + r.stdout
        return log

    def check(log):
        return subprocess.run(
            [sys.executable, '-m', 'distributed_dot_product_tpu.obs',
             'slo', 'check', str(log), '--against',
             'SLO_BASELINE.json', '--json'],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)

    clean = check(smoke('clean'))
    assert clean.returncode == 0, clean.stdout + clean.stderr

    regress = check(smoke('regress', '--load-tick', '0.1'))
    assert regress.returncode == 1, (
        'the 50x-slower-tick regression fixture passed the SLO gate')
    payload = json.loads(regress.stdout)
    assert any('goodput_pct' in v for v in payload['violations'])
    assert any('tenant t' in v for v in payload['violations'])


def test_committed_baseline_shape():
    """The committed baseline's own contract: schema, a parseable
    embedded spec, the two smoke tenants, a sane goodput."""
    with open(os.path.join(REPO, 'SLO_BASELINE.json'),
              encoding='utf-8') as f:
        base = json.load(f)
    assert base['schema'] == obs_slo.SLO_BASELINE_SCHEMA
    spec = SloSpec.from_dict(base['spec'])
    assert spec.ttft is not None and spec.per_token is not None
    assert set(base['per_tenant']) == {'t0', 't1'}
    assert 0.0 < base['goodput_pct'] <= 100.0
