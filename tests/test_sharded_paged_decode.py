# -*- coding: utf-8 -*-
"""
Cluster-scale long context: the sequence-sharded page table
(models/decode.py ShardedPageTable + init_sharded_paged_cache) and the
paged ring-decode step it feeds.

The contract under test: sharding a stream's page table across the
mesh's seq axis is a MEMORY-placement change, not a numerics change.
Each shard owns a contiguous page-ordinal range, appends drop through
the local table's −1 on non-owners (pool rows land bit-identically to
the single-pool reference), and the per-shard flash partials
pmax/psum-merge into the single-pool attention result to float
tolerance — on the XLA formulation and the fused kernel alike. On the
host side: cross-shard allocation with rollback, per-shard exhaustion
that names the full shard, and capacity that SUMS over shards.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.models.decode import (
    PagedDecodeCache, PagePool, ShardedPageTable, append_kv_slots,
    decode_kernel_eligible, decode_step, init_paged_cache,
    init_sharded_paged_cache, paged_gather,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD, B, H, D, PS = 4, 2, 2, 8, 8
T = 64                       # pps = 8 ordinals; 2 owned per shard
PAGES_SHARD = 3              # per-shard pool: 3 pages + its sink
PAGES_REF = WORLD * PAGES_SHARD


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _rng(seed=0):
    return np.random.default_rng(seed)


def _spt():
    return ShardedPageTable(WORLD, PAGES_SHARD, PS, B, T // PS)


def _spec():
    return PagedDecodeCache(k_pool=P('seq'), v_pool=P('seq'),
                            page_table=P('seq'), length=P(),
                            k_q_pool=None, k_scale_pool=None)


def _sh_call(mesh, fn, cache, *args, pair=False):
    """Run ``fn(local_cache, *args)`` under shard_map: the stacked
    cache splits per shard (its (1, slots, pps) table block squeezed
    to the local view), everything else replicated. ``pair=True`` for
    a ``(cache, out)``-returning ``fn`` (decode_step)."""
    spec = _spec()

    def body(c, *rest):
        local = c._replace(page_table=c.page_table[0])
        out = fn(local, *rest)
        if pair:
            c2, extra = out
            return (c2._replace(page_table=c2.page_table[None]), extra)
        return out._replace(page_table=out.page_table[None])

    out_specs = (spec, P()) if pair else spec
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec,) + (P(),) * len(args),
        out_specs=out_specs, check_vma=False)(cache, *args)


def _mk_pair(fills=(14, 3), seed=0):
    """Single-pool reference and sharded twin holding identical rows,
    plus both host allocators."""
    rng = _rng(seed)
    ref = init_paged_cache(B, H, T, D, pages=PAGES_REF, page_size=PS,
                           dtype=jnp.float32)
    rpool = PagePool(PAGES_REF, PS, B, T // PS)
    sh = init_sharded_paged_cache(WORLD, B, H, T, D,
                                  pages_per_shard=PAGES_SHARD,
                                  page_size=PS, dtype=jnp.float32)
    spt = _spt()
    mesh = seq_mesh(WORLD)
    for slot, n in enumerate(fills):
        if not n:
            continue
        k = jnp.asarray(rng.normal(size=(B, H, n, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, n, D)), jnp.float32)
        sel = np.arange(B) == slot
        counts = np.where(sel, n, 0).astype(np.int32)
        ok, copies = rpool.reserve_rows(slot, n)
        assert ok and not copies
        ok, copies = spt.reserve_rows(slot, n)
        assert ok and not copies
        ref = ref._replace(page_table=jnp.asarray(rpool.table))
        sh = sh._replace(page_table=jnp.asarray(spt.local_tables()))
        ref = append_kv_slots(ref, k, v, slot_mask=sel, counts=counts)
        sh = _sh_call(
            mesh, lambda c, kk, vv: append_kv_slots(
                c, kk, vv, slot_mask=sel, counts=counts), sh, k, v)
        rpool.lengths[slot] += n
        spt.lengths[slot] += n
    return ref, rpool, sh, spt


def _sharded_row(sh, spt, slot, pos):
    """K row of logical position ``pos`` out of the stacked pools."""
    o, r = divmod(pos, PS)
    s = spt.owner(o)
    pg = int(spt.shards[s].table[slot, o])
    assert pg >= 0, f'position {pos} of slot {slot} is unmapped'
    return np.asarray(sh.k_pool)[s * (PAGES_SHARD + 1) + pg, :, r]


# -- host allocator -----------------------------------------------------

def test_contiguous_ownership():
    spt = _spt()
    assert [spt.owner(o) for o in range(8)] == [0, 0, 1, 1, 2, 2, 3, 3]
    assert spt.owned_range(0) == (0, 2)
    assert spt.owned_range(3) == (6, 8)
    assert np.array_equal(spt.owner_vector(),
                          [0, 0, 1, 1, 2, 2, 3, 3])
    # Ceil split: 7 ordinals over 4 shards → 2/2/2/1.
    odd = ShardedPageTable(4, 3, PS, B, 7)
    assert odd.owned_range(3) == (6, 7)
    assert odd.owner(6) == 3


def test_capacity_sums_across_shards():
    spt = _spt()
    assert spt.pages == PAGES_REF
    assert spt.free_pages == PAGES_REF
    assert spt.free_pages_by_shard == [PAGES_SHARD] * WORLD


def test_prepare_append_routes_to_owner():
    spt = _spt()
    # Fill slot 0 to one row short of shard 0's range (2 pages = 16).
    ok, _ = spt.reserve_rows(0, 16)
    assert ok
    spt.lengths[0] = 16
    st, s, src, dst = spt.prepare_append(0)
    assert (st, s) == ('alloc', 1)          # ordinal 2 → shard 1
    assert spt.shards[1].table[0, 2] == dst
    assert spt.shards[0].free_pages == PAGES_SHARD - 2
    assert spt.shards[1].free_pages == PAGES_SHARD - 1


def test_reserve_rollback_spans_shards():
    spt = _spt()
    # Drain shard 1 completely with slot 1 (ordinals 2,3 + quarantine
    # the third page so nothing is left).
    ok, _ = spt.reserve_rows(1, 32)          # ordinals 0..3
    assert ok
    spt.lengths[1] = 32
    spt.quarantine(1, [int(p) for p in spt.shards[1]._free])
    assert spt.shards[1].free_pages == 0
    free0 = spt.free_pages_by_shard
    # Slot 0 asks for rows spanning shards 0 AND 1: shard 1 is dry, so
    # the reservation must fail and leave shard 0's pages untouched.
    ok, copies = spt.reserve_rows(0, 24)     # ordinals 0,1 (s0), 2 (s1)
    assert not ok and not copies
    assert spt.free_pages_by_shard == free0
    assert int(spt.shards[0].counts[0]) == 0
    assert (spt.shards[0].table[0] == -1).all()


def test_one_shard_exhausted_while_others_have_headroom():
    spt = _spt()
    # Three sequences park one page each in shard 0's range.
    pool3 = ShardedPageTable(WORLD, PAGES_SHARD, PS, 4, T // PS)
    for slot in range(3):
        ok, _ = pool3.reserve_rows(slot, 1)
        assert ok
        pool3.lengths[slot] = 1
    st, s, _, _ = pool3.prepare_append(3)
    assert (st, s) == ('exhausted', 0)
    assert pool3.free_pages_by_shard[0] == 0
    assert all(f == PAGES_SHARD for f in pool3.free_pages_by_shard[1:])
    assert pool3.free_pages > 0              # aggregate lies; shard 0 full


def test_release_and_truncate_cross_shards():
    spt = _spt()
    ok, _ = spt.reserve_rows(0, 20)          # ordinals 0,1 (s0), 2 (s1)
    assert ok
    spt.lengths[0] = 20
    freed = spt.truncate(0, 10)              # keep 2 pages → drop s1's
    assert list(freed) == [1] and len(freed[1]) == 1
    assert spt.shards[1].free_pages == PAGES_SHARD
    assert int(spt.lengths[0]) == 10
    freed = spt.release(0)
    assert list(freed) == [0] and len(freed[0]) == 2
    assert spt.free_pages == PAGES_REF
    assert int(spt.lengths[0]) == 0


def test_shared_lengths_vector():
    spt = _spt()
    spt.lengths[0] = 5
    assert all(int(p.lengths[0]) == 5 for p in spt.shards)
    spt.shards[2].lengths[0] += 1            # engine-style alias bump
    assert int(spt.lengths[0]) == 6


# -- decode parity ------------------------------------------------------

@pytest.mark.parametrize('impl', ['xla', 'kernel'])
def test_sharded_step_matches_single_pool(mesh, impl):
    ref, rpool, sh, spt = _mk_pair()
    rng = _rng(7)
    for step in range(4):                    # slot 0 crosses 16 → s1
        q, kn, vn = (jnp.asarray(rng.normal(size=(B, H, 1, D)),
                                 jnp.float32) for _ in range(3))
        for slot in range(B):
            st, _, _ = rpool.prepare_append(slot)
            assert st in ('ok', 'alloc')
            st, s, _, _ = spt.prepare_append(slot)
            assert st in ('ok', 'alloc')
        ref = ref._replace(page_table=jnp.asarray(rpool.table))
        sh = sh._replace(page_table=jnp.asarray(spt.local_tables()))
        ref, out_r = decode_step(q, ref, kn, vn, impl='xla')
        sh, out_s = _sh_call(
            mesh, lambda c, qq, kk, vv: decode_step(
                qq, c, kk, vv, impl=impl, axis_name='seq'),
            sh, q, kn, vn, pair=True)
        rpool.lengths += 1
        spt.lengths += 1
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r),
                                   atol=2e-5, rtol=1e-5)
    # Slot 0's fill (18) now spans shard 0 (rows 0..15) and shard 1
    # (16..17); every row is bit-identical to the single-pool pool row.
    gk, _ = paged_gather(ref)
    for slot in range(B):
        ln = int(spt.lengths[slot])
        for pos in range(ln):
            np.testing.assert_array_equal(
                _sharded_row(sh, spt, slot, pos),
                np.asarray(gk)[slot, :, pos])


def test_sharded_append_drops_on_non_owners(mesh):
    """A non-owning shard's pool takes NOTHING from an append."""
    _, _, sh, spt = _mk_pair(fills=(4, 0))
    # Fill 4 lives in ordinal 0 → shard 0; shards 1..3 own no pages.
    pools = np.asarray(sh.k_pool).reshape(WORLD, PAGES_SHARD + 1, H,
                                          PS, D)
    assert np.all(pools[1:] == 0)
    assert (np.asarray(sh.page_table)[1:] == -1).all()


def test_sharded_verify_k_is_xla_only(mesh):
    _, _, sh, spt = _mk_pair(fills=(4, 3))
    q = jnp.zeros((B, H, 2, D), jnp.float32)
    with pytest.raises(ValueError, match='single-token'):
        _sh_call(
            mesh, lambda c, qq: decode_step(
                qq, c, qq, qq, impl='kernel', axis_name='seq'),
            sh, q, pair=True)


# -- mesh-aware eligibility explanations (satellite) --------------------

def test_eligible_explanations_name_shard_geometry():
    cache = init_paged_cache(B, H, T, D, pages=PAGES_SHARD + 1,
                             page_size=PS, dtype=jnp.float32)
    ok, why = decode_kernel_eligible(cache, explain=True, n_shards=4)
    assert ok
    assert 'sequence-sharded page table' in why
    assert '4 shards' in why and 'contiguous run of 2' in why

    ok, why = decode_kernel_eligible(cache, explain=True, n_shards=4,
                                     shard=2)
    assert ok
    assert 'shard 2/4' in why and '[4, 6)' in why

    # Per-shard ineligibility keeps the geometry prefix.
    ok, why = decode_kernel_eligible(cache, n=2, explain=True,
                                     n_shards=4, shard=1)
    assert not ok
    assert 'shard 1/4' in why and 'single-token' in why

    # Slab sharding names column ranges instead.
    from distributed_dot_product_tpu.models.decode import init_cache
    slab = init_cache(B, H, 16, D, dtype=jnp.float32)
    ok, why = decode_kernel_eligible(slab, explain=True, n_shards=2,
                                     shard=1)
    assert ok and 'columns [16, 32)' in why

    # Unsharded probes are unchanged: eligible means reason is None.
    ok, why = decode_kernel_eligible(cache, explain=True)
    assert ok and why is None
