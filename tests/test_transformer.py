# -*- coding: utf-8 -*-
"""
Transformer stack (models/transformer.py): the composition layer. The
contracts tested — sharded == local oracle on every softmax path, the
train step drives a whole stack, stacked-layer dropout decorrelates
under one explicit seed, and cached generation (prefill + decode with
one KV cache per layer) reproduces the stack's causal forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_dot_product_tpu.models.attention import (
    apply_seq_parallel,
)
from distributed_dot_product_tpu.models.transformer import (
    TransformerStack,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.train import make_train_step

WORLD, LEN, DIM, HEADS = 4, 16, 32, 4
T = WORLD * LEN

pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _stack(dist=True, **attn_kw):
    attn_kw.setdefault('causal', True)
    attn_kw.setdefault('softmax_impl', 'flash')
    attn_kw['distributed'] = dist
    return TransformerStack(dim=DIM, num_heads=HEADS, n_layers=2,
                            attn_kwargs=attn_kw)


def _x(key=0):
    return jax.random.normal(jax.random.key(key), (2, T, DIM))


@pytest.mark.parametrize('impl', ['full', 'online', 'flash', 'ulysses'])
def test_stack_sharded_matches_local(mesh, impl):
    x = _x()
    # ulysses GQA needs kv heads divisible by the mesh width (WORLD=4,
    # HEADS=4 kv=2 would precisely raise) — standard heads there.
    kv = 2 if impl != 'ulysses' else None
    m = _stack(softmax_impl=impl, num_kv_heads=kv, use_rope=True)
    params = m.init(jax.random.key(1), x[:, :8], x[:, :8], x[:, :8], None)
    out = apply_seq_parallel(m, params, mesh, x, x, x, None)
    local = _stack(dist=False, softmax_impl=impl, num_kv_heads=kv,
                   use_rope=True)
    ref = local.apply(params, x, x, x, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5)


def test_stack_train_step(mesh):
    x = _x(1)
    m = _stack(use_rope=True, dropout_rate=0.1)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    opt = optax.adam(1e-3)
    step = make_train_step(m, opt, mesh, donate=False)
    ost = opt.init(params)
    target = jnp.roll(x, -1, axis=1)
    losses = []
    p = params
    for i in range(3):
        p, ost, loss = step(p, ost, (x, x, x, None, target),
                            dropout_seed=i)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_stack_layer_dropout_decorrelates():
    """Two identical-weight layers under ONE explicit seed must apply
    different masks (per-layer salt through the stack)."""
    x = _x(2)
    m = _stack(dist=False, dropout_rate=0.5)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    shared = jax.tree.map(lambda v: v, params)
    shared['params']['block_1'] = shared['params']['block_0']
    out = m.apply(shared, x, x, x, None, dropout_seed=3)
    # If both layers applied the SAME mask, block outputs after layer 1
    # and 2 would be related by the same dropped pattern; instead verify
    # against a one-layer double application.
    one = TransformerStack(dim=DIM, num_heads=HEADS, n_layers=1,
                           attn_kwargs=dict(causal=True,
                                            softmax_impl='flash',
                                            distributed=False,
                                            dropout_rate=0.5))
    p1 = {'params': {'block_0': shared['params']['block_0']}}
    y = one.apply(p1, x, x, x, None, dropout_seed=3)
    z = one.apply(p1, y, y, y, None, dropout_seed=3)
    assert not np.allclose(np.asarray(out), np.asarray(z), atol=1e-6), (
        'stacked layers drew identical dropout masks under one seed')


def test_stack_cached_generation_matches_forward():
    """Prefill + token-by-token decode through per-layer caches ==
    the stack's causal forward (GQA + RoPE + window on)."""
    x = _x(3)
    kw = dict(num_kv_heads=2, use_rope=True, window=24)
    m = _stack(dist=False, **kw)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    want = m.apply(params, x, x, x, None)

    caches = m.make_decode_caches(2, T)
    prefill = 40
    caches, out0 = m.apply(params, x[:, :prefill], caches,
                           method='prefill')
    outs = [out0]
    step = jax.jit(lambda p, xt, c: m.apply(p, xt, c, method='decode'))
    for t in range(prefill, T):
        caches, o = step(params, x[:, t:t + 1], caches)
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=5e-5)
