# -*- coding: utf-8 -*-
"""
Transformer stack (models/transformer.py): the composition layer. The
contracts tested — sharded == local oracle on every softmax path, the
train step drives a whole stack, stacked-layer dropout decorrelates
under one explicit seed, and cached generation (prefill + decode with
one KV cache per layer) reproduces the stack's causal forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_dot_product_tpu.models.attention import (
    apply_seq_parallel,
)
from distributed_dot_product_tpu.models.transformer import (
    TransformerStack,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.train import make_train_step

WORLD, LEN, DIM, HEADS = 4, 16, 32, 4
T = WORLD * LEN

pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _stack(dist=True, **attn_kw):
    attn_kw.setdefault('causal', True)
    attn_kw.setdefault('softmax_impl', 'flash')
    attn_kw['distributed'] = dist
    return TransformerStack(dim=DIM, num_heads=HEADS, n_layers=2,
                            attn_kwargs=attn_kw)


def _x(key=0):
    return jax.random.normal(jax.random.key(key), (2, T, DIM))


@pytest.mark.parametrize('impl', ['full', 'online', 'flash', 'ulysses'])
def test_stack_sharded_matches_local(mesh, impl):
    x = _x()
    # ulysses GQA needs kv heads divisible by the mesh width (WORLD=4,
    # HEADS=4 kv=2 would precisely raise) — standard heads there.
    kv = 2 if impl != 'ulysses' else None
    m = _stack(softmax_impl=impl, num_kv_heads=kv, use_rope=True)
    params = m.init(jax.random.key(1), x[:, :8], x[:, :8], x[:, :8], None)
    out = apply_seq_parallel(m, params, mesh, x, x, x, None)
    local = _stack(dist=False, softmax_impl=impl, num_kv_heads=kv,
                   use_rope=True)
    ref = local.apply(params, x, x, x, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-5)


def test_stack_train_step(mesh):
    x = _x(1)
    m = _stack(use_rope=True, dropout_rate=0.1)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    opt = optax.adam(1e-3)
    step = make_train_step(m, opt, mesh, donate=False)
    ost = opt.init(params)
    target = jnp.roll(x, -1, axis=1)
    losses = []
    p = params
    for i in range(3):
        p, ost, loss = step(p, ost, (x, x, x, None, target),
                            dropout_seed=i)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_stack_layer_dropout_decorrelates():
    """Two identical-weight layers under ONE explicit seed must apply
    different masks (per-layer salt through the stack)."""
    x = _x(2)
    m = _stack(dist=False, dropout_rate=0.5)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    shared = jax.tree.map(lambda v: v, params)
    shared['params']['block_1'] = shared['params']['block_0']
    out = m.apply(shared, x, x, x, None, dropout_seed=3)
    # If both layers applied the SAME mask, block outputs after layer 1
    # and 2 would be related by the same dropped pattern; instead verify
    # against a one-layer double application.
    one = TransformerStack(dim=DIM, num_heads=HEADS, n_layers=1,
                           attn_kwargs=dict(causal=True,
                                            softmax_impl='flash',
                                            distributed=False,
                                            dropout_rate=0.5))
    p1 = {'params': {'block_0': shared['params']['block_0']}}
    y = one.apply(p1, x, x, x, None, dropout_seed=3)
    z = one.apply(p1, y, y, y, None, dropout_seed=3)
    assert not np.allclose(np.asarray(out), np.asarray(z), atol=1e-6), (
        'stacked layers drew identical dropout masks under one seed')


# ---------------------------------------------------------------------------
# scan_layers: one nn.scan over layer-stacked params (round-5)
# ---------------------------------------------------------------------------

def _scan_params_from_unrolled(params, n_layers):
    """Stack the unrolled ``block_i`` subtrees into the scanned layout
    (``layers/block`` with a leading layer axis)."""
    blocks = [params['params'][f'block_{i}'] for i in range(n_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    return {'params': {'layers': {'block': stacked}}}


def _scan_stack(dist=True, n_layers=2, scan=True, **kw):
    attn_kw = dict(causal=True, softmax_impl='flash', distributed=dist,
                   use_rope=True)
    return TransformerStack(dim=DIM, num_heads=HEADS, n_layers=n_layers,
                            attn_kwargs=attn_kw, scan_layers=scan, **kw)


def test_scanned_matches_unrolled():
    """Identical weights through the scanned and unrolled stacks must
    produce identical outputs (same math, same order)."""
    x = _x(4)
    unrolled = _scan_stack(dist=False, scan=False)
    params = unrolled.init(jax.random.key(0), x[:, :8], x[:, :8],
                           x[:, :8], None)
    want = unrolled.apply(params, x, x, x, None)
    scanned = _scan_stack(dist=False)
    sp = _scan_params_from_unrolled(params, 2)
    got = scanned.apply(sp, x, x, x, None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6)


@pytest.mark.parametrize('policy', [None, 'dots_saveable'])
def test_scanned_remat_matches_unrolled_grads(policy):
    """remat (full or policy-guided) must not change outputs OR
    gradients — only the backward's memory schedule."""
    x = _x(5)
    unrolled = _scan_stack(dist=False, scan=False)
    params = unrolled.init(jax.random.key(0), x[:, :8], x[:, :8],
                           x[:, :8], None)
    sp = _scan_params_from_unrolled(params, 2)
    rem = _scan_stack(dist=False, remat=True, remat_policy=policy)
    got = rem.apply(sp, x, x, x, None)
    want = unrolled.apply(params, x, x, x, None)
    # fp32 reassociation in the remat recompute: ~1e-6 drift is expected.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=5e-6)

    def loss_scan(p):
        return jnp.sum(rem.apply(p, x, x, x, None) ** 2)

    def loss_unroll(p):
        return jnp.sum(unrolled.apply(p, x, x, x, None) ** 2)

    g_scan = jax.grad(loss_scan)(sp)['params']['layers']['block']
    g_un = jax.grad(loss_unroll)(params)
    for i in range(2):
        for got_l, want_l in zip(
                jax.tree.leaves(jax.tree.map(lambda a, i=i: a[i], g_scan)),
                jax.tree.leaves(g_un['params'][f'block_{i}'])):
            np.testing.assert_allclose(np.asarray(got_l),
                                       np.asarray(want_l),
                                       atol=2e-5, rtol=1e-4)


def test_scanned_train_step_loss_decreases(mesh):
    x = _x(6)
    m = _scan_stack(n_layers=3, remat=True)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    opt = optax.adam(1e-3)
    step = make_train_step(m, opt, mesh, donate=False)
    ost = opt.init(params)
    target = jnp.roll(x, -1, axis=1)
    losses = []
    p = params
    for _ in range(3):
        p, ost, loss = step(p, ost, (x, x, x, None, target))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_scanned_cached_generation_matches_forward():
    """Scanned prefill + decode (KV caches stacked on the layer axis)
    == the scanned causal forward."""
    x = _x(7)
    m = _scan_stack(dist=False)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    want = m.apply(params, x, x, x, None)
    caches = m.make_decode_caches(2, T)
    assert caches.k.shape[0] == 2 and caches.k.ndim == 5  # (L, B, H, T, d)
    prefill = 40
    caches, out0 = m.apply(params, x[:, :prefill], caches,
                           method='prefill')
    outs = [out0]
    step = jax.jit(lambda p, xt, c: m.apply(p, xt, c, method='decode'))
    for t in range(prefill, T):
        caches, o = step(params, x[:, t:t + 1], caches)
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=5e-5)


def test_scanned_dropout_decorrelates_layers():
    """The scanned stack's layer-index seed fold must decorrelate layers
    sharing one explicit seed (they share a module path, so the flax
    path salt cannot)."""
    x = _x(8)
    m = TransformerStack(dim=DIM, num_heads=HEADS, n_layers=2,
                         scan_layers=True,
                         attn_kwargs=dict(causal=True,
                                          softmax_impl='flash',
                                          distributed=False,
                                          dropout_rate=0.5))
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    # Same weights in both layers.
    shared = jax.tree.map(
        lambda a: jnp.stack([a[0], a[0]]),
        params['params']['layers']['block'])
    sp = {'params': {'layers': {'block': shared}}}
    out = m.apply(sp, x, x, x, None, dropout_seed=3)
    one = TransformerStack(dim=DIM, num_heads=HEADS, n_layers=1,
                           scan_layers=True,
                           attn_kwargs=dict(causal=True,
                                            softmax_impl='flash',
                                            distributed=False,
                                            dropout_rate=0.5))
    p1 = {'params': {'layers': {'block': jax.tree.map(
        lambda a: a[:1], shared)}}}
    y = one.apply(p1, x, x, x, None, dropout_seed=3)
    z = one.apply(p1, y, y, y, None, dropout_seed=3)
    assert not np.allclose(np.asarray(out), np.asarray(z), atol=1e-6), (
        'scanned layers drew identical dropout masks under one seed')


def test_scan_remat_validation():
    with pytest.raises(ValueError, match='scan_layers'):
        TransformerStack(dim=DIM, num_heads=HEADS, remat=True).init(
            jax.random.key(0), jnp.ones((1, 8, DIM)), jnp.ones((1, 8, DIM)),
            jnp.ones((1, 8, DIM)), None)
    with pytest.raises(ValueError, match='remat_policy'):
        TransformerStack(dim=DIM, num_heads=HEADS, scan_layers=True,
                         remat=True, remat_policy='nope').init(
            jax.random.key(0), jnp.ones((1, 8, DIM)), jnp.ones((1, 8, DIM)),
            jnp.ones((1, 8, DIM)), None)


def test_stack_cached_generation_matches_forward():
    """Prefill + token-by-token decode through per-layer caches ==
    the stack's causal forward (GQA + RoPE + window on)."""
    x = _x(3)
    kw = dict(num_kv_heads=2, use_rope=True, window=24)
    m = _stack(dist=False, **kw)
    params = m.init(jax.random.key(0), x[:, :8], x[:, :8], x[:, :8], None)
    want = m.apply(params, x, x, x, None)

    caches = m.make_decode_caches(2, T)
    prefill = 40
    caches, out0 = m.apply(params, x[:, :prefill], caches,
                           method='prefill')
    outs = [out0]
    step = jax.jit(lambda p, xt, c: m.apply(p, xt, c, method='decode'))
    for t in range(prefill, T):
        caches, o = step(params, x[:, t:t + 1], caches)
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=5e-5)
