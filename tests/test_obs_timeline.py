# -*- coding: utf-8 -*-
"""
The observability layer's acceptance scenario (tier-1): drive the
scheduler through the existing fault cocktail (stuck step + NaN slot +
queue-overflow burst) with an event log attached, then

- reconstruct EVERY submitted request's complete timeline
  (admit→…→retire, or reject/evict with a reason) from the JSONL event
  log ALONE;
- require the /metrics endpoint (and the rendered exposition text) to
  expose nonzero TTFT, queue-wait and per-token latency histograms;
- require the injected faults and health transitions to be present in
  the same durable stream.

Plus timeline-unit cases for the lifecycle validator itself.
"""

import re
import urllib.request

import numpy as np
import pytest

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs.events import EventLog, validate_file
from distributed_dot_product_tpu.obs.exporter import (
    MetricsServer, render_prometheus,
)
from distributed_dot_product_tpu.obs.timeline import reconstruct, timeline
from distributed_dot_product_tpu.serve import (
    KernelEngine, RejectedError, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

SLOTS, T_MAX, VOCAB = 3, 32, 16


def _burst(n, seed=11):
    rng = np.random.default_rng(seed)
    return [(f'r{i:03d}',
             rng.integers(0, VOCAB,
                          size=int(rng.integers(1, 7))).astype(np.int32))
            for i in range(n)]


def _run_cocktail(log, n=14):
    """The test_serve_soak fault cocktail, instrumented: stuck step
    (watchdog), NaN slot (quarantine), burst > queue (typed shed)."""
    plan = ServeFaultPlan(stuck_at_step=3, stuck_seconds=0.4,
                          nan_at_step=5, nan_slot=1)
    registry = MetricsRegistry()
    sched = Scheduler(
        KernelEngine(slots=SLOTS, t_max=T_MAX, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl='xla'),
        ServeConfig(queue_limit=4, max_new_tokens=4, stall_timeout=0.12,
                    watchdog_poll=0.02, evict_before_reject=False),
        fault_injector=ServeFaultInjector(plan), registry=registry,
        event_log=log)
    rejected = {}
    for i, (rid, prompt) in enumerate(_burst(n)):
        try:
            sched.submit(prompt, request_id=rid)
        except RejectedError as e:
            rejected[rid] = e.reason
        if i % 3 == 2:
            sched.step()
    results = sched.run_until_idle()
    sched.close()
    return sched, registry, rejected, results


def test_fault_cocktail_fully_reconstructable_from_event_log(tmp_path,
                                                             devices):
    n = 14
    log = EventLog(tmp_path / 'serve.jsonl')
    sched, registry, rejected, results = _run_cocktail(log, n)
    log.close()

    # Schema-clean log.
    records, errors = validate_file(log.path)
    assert errors == [], errors
    assert records, 'no events recorded'

    # EVERY submitted request reconstructs, complete, from JSONL alone.
    timelines = reconstruct(log.path)
    for rid, _ in _burst(n):
        tl = timelines.get(rid)
        assert tl is not None, f'{rid}: absent from the event log'
        assert tl.complete, f'{rid}: {tl.errors}'
        if rid in rejected:
            assert tl.status == 'rejected'
            assert tl.reason == rejected[rid].value
        else:
            # Admitted: the log agrees with the in-process result.
            assert tl.status == results[rid].status
            assert tl.tokens >= len(results[rid].tokens)
            assert tl.queue_wait is not None
    # The injected faults are in the same durable stream.
    kinds = {r.get('kind') for r in records
             if r['event'] == 'fault.inject'}
    assert {'stuck_step', 'nan_slot'} <= kinds
    assert any(r['event'] == 'serve.quarantine' for r in records)
    states = [r['state'] for r in records
              if r['event'] == 'health.liveness']
    assert 'stalled' in states and 'alive' in states

    # The quarantined request's timeline shows the full recovery arc.
    (qrid,) = {r['request_id'] for r in records
               if r['event'] == 'serve.quarantine'}
    qtl = timelines[qrid]
    assert qtl.quarantines == 1 and qtl.admits == 2
    assert qtl.status == 'completed'

    # Latency histograms: nonzero ttft / queue-wait / per-token.
    snap = registry.snapshot()['histograms']
    for name in ('serve.ttft_seconds', 'serve.queue_wait_seconds',
                 'serve.token_seconds'):
        h = snap[name]
        assert h['total_count'] > 0, name
        assert h['total_sum'] > 0, name

    # ...and they are exposed over /metrics as valid families.
    with MetricsServer(registry, health=sched.health) as srv:
        with urllib.request.urlopen(srv.url + '/metrics',
                                    timeout=5) as resp:
            text = resp.read().decode()
    for fam in ('ddp_serve_ttft_seconds', 'ddp_serve_queue_wait_seconds',
                'ddp_serve_token_seconds'):
        m = re.search(rf'^{fam}_sum ([0-9.eE+-]+)$', text, re.MULTILINE)
        assert m is not None, f'{fam} missing from /metrics'
        assert float(m.group(1)) > 0, f'{fam} empty'
    assert render_prometheus(registry) == text


def test_timeline_helper_on_missing_request(tmp_path):
    log = EventLog(tmp_path / 'x.jsonl')
    log.emit('serve.admit', request_id='r0', slot=0)
    log.close()
    tl = timeline('never-submitted', log.path)
    assert not tl.complete and tl.errors == ['no events recorded']


def test_timeline_validator_rejects_broken_lifecycles():
    def tl_of(recs):
        for i, r in enumerate(recs):
            r.setdefault('seq', i)
            r.setdefault('ts', float(i))
            r.setdefault('schema', 1)
        return reconstruct(recs)

    # Decode without an admit.
    tls = tl_of([{'event': 'serve.decode', 'request_id': 'a', 'slot': 0,
                  'token_index': 0},
                 {'event': 'serve.retire', 'request_id': 'a',
                  'status': 'completed'}])
    assert not tls['a'].complete
    assert any('without a slot' in e for e in tls['a'].errors)

    # No terminal event.
    tls = tl_of([{'event': 'serve.admit', 'request_id': 'b', 'slot': 0}])
    assert not tls['b'].complete
    assert any('no terminal event' in e for e in tls['b'].errors)

    # Retire(evicted) demands a serve.evict record.
    tls = tl_of([{'event': 'serve.admit', 'request_id': 'c', 'slot': 0},
                 {'event': 'serve.retire', 'request_id': 'c',
                  'status': 'evicted'}])
    assert any('serve.evict' in e for e in tls['c'].errors)

    # The clean arc passes, including quarantine + readmit.
    tls = tl_of([
        {'event': 'serve.admit', 'request_id': 'd', 'slot': 0,
         'queue_wait': 0.1},
        {'event': 'serve.decode', 'request_id': 'd', 'slot': 0,
         'token_index': 0, 'ttft': 0.5},
        {'event': 'serve.quarantine', 'request_id': 'd', 'slot': 0,
         'requeued': True},
        {'event': 'serve.admit', 'request_id': 'd', 'slot': 1,
         'queue_wait': 0.2},
        {'event': 'serve.decode', 'request_id': 'd', 'slot': 1,
         'token_index': 0, 'ttft': 0.9},
        {'event': 'serve.decode', 'request_id': 'd', 'slot': 1,
         'token_index': 1, 'gap': 0.01},
        {'event': 'serve.retire', 'request_id': 'd',
         'status': 'completed', 'total_seconds': 1.0},
    ])
    tl = tls['d']
    assert tl.complete, tl.errors
    assert tl.admits == 2 and tl.quarantines == 1 and tl.tokens == 3
    assert tl.queue_wait == 0.1 and tl.ttft == 0.5
    assert tl.token_gaps == [0.01]
    assert tl.phases()['total'] == 1.0


def test_eviction_timeline_reconstructs(tmp_path, devices):
    """Eviction path: the ladder frees the longest-idle slot; the log
    must show evict + retire(evicted) for the victim."""
    log = EventLog(tmp_path / 'evict.jsonl')
    registry = MetricsRegistry()
    sched = Scheduler(
        KernelEngine(slots=1, t_max=T_MAX, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl='xla'),
        ServeConfig(queue_limit=1, max_new_tokens=6, watchdog=False,
                    evict_before_reject=True, min_evict_idle=0.0),
        fault_injector=False, registry=registry, event_log=log)
    sched.submit(np.array([1, 2], np.int32), request_id='victim')
    sched.step()                     # victim occupies the slot
    sched.submit(np.array([3], np.int32), request_id='queued')
    sched.submit(np.array([4], np.int32), request_id='usurper')
    sched.run_until_idle()
    sched.close()
    log.close()
    tls = reconstruct(log.path)
    assert tls['victim'].status == 'evicted'
    assert tls['victim'].complete, tls['victim'].errors
    for rid in ('queued', 'usurper'):
        assert tls[rid].complete and tls[rid].status == 'completed'


def test_scheduler_uses_active_log_when_none_passed(tmp_path, devices):
    """`with obs.activate(log):` instruments a scheduler constructed
    without an explicit event_log — the integration serve_lm.py and
    smoke_serve.sh rely on."""
    log = EventLog(tmp_path / 'active.jsonl')
    with obs_events.activate(log):
        sched = Scheduler(
            KernelEngine(slots=1, t_max=16, vocab=VOCAB, heads=2,
                         head_dim=4, seed=5, decode_impl='xla'),
            ServeConfig(queue_limit=2, max_new_tokens=2,
                        watchdog=False),
            fault_injector=False, registry=MetricsRegistry())
        sched.submit(np.array([1], np.int32), request_id='r')
        sched.run_until_idle()
        sched.close()
    log.close()
    assert reconstruct(log.path)['r'].complete
