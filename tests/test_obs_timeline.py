# -*- coding: utf-8 -*-
"""
The observability layer's acceptance scenario (tier-1): drive the
scheduler through the existing fault cocktail (stuck step + NaN slot +
queue-overflow burst) with an event log attached, then

- reconstruct EVERY submitted request's complete timeline
  (admit→…→retire, or reject/evict with a reason) from the JSONL event
  log ALONE;
- require the /metrics endpoint (and the rendered exposition text) to
  expose nonzero TTFT, queue-wait and per-token latency histograms;
- require the injected faults and health transitions to be present in
  the same durable stream.

Plus timeline-unit cases for the lifecycle validator itself.
"""

import re
import urllib.request

import numpy as np
import pytest

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs.events import EventLog, validate_file
from distributed_dot_product_tpu.obs.exporter import (
    MetricsServer, render_prometheus,
)
from distributed_dot_product_tpu.obs.timeline import reconstruct, timeline
from distributed_dot_product_tpu.serve import (
    KernelEngine, RejectedError, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

SLOTS, T_MAX, VOCAB = 3, 32, 16


def _burst(n, seed=11):
    rng = np.random.default_rng(seed)
    return [(f'r{i:03d}',
             rng.integers(0, VOCAB,
                          size=int(rng.integers(1, 7))).astype(np.int32))
            for i in range(n)]


def _run_cocktail(log, n=14):
    """The test_serve_soak fault cocktail, instrumented: stuck step
    (watchdog), NaN slot (quarantine), burst > queue (typed shed)."""
    plan = ServeFaultPlan(stuck_at_step=3, stuck_seconds=0.4,
                          nan_at_step=5, nan_slot=1)
    registry = MetricsRegistry()
    sched = Scheduler(
        KernelEngine(slots=SLOTS, t_max=T_MAX, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl='xla'),
        ServeConfig(queue_limit=4, max_new_tokens=4, stall_timeout=0.12,
                    watchdog_poll=0.02, evict_before_reject=False),
        fault_injector=ServeFaultInjector(plan), registry=registry,
        event_log=log)
    rejected = {}
    for i, (rid, prompt) in enumerate(_burst(n)):
        try:
            sched.submit(prompt, request_id=rid)
        except RejectedError as e:
            rejected[rid] = e.reason
        if i % 3 == 2:
            sched.step()
    results = sched.run_until_idle()
    sched.close()
    return sched, registry, rejected, results


def test_fault_cocktail_fully_reconstructable_from_event_log(tmp_path,
                                                             devices):
    n = 14
    log = EventLog(tmp_path / 'serve.jsonl')
    sched, registry, rejected, results = _run_cocktail(log, n)
    log.close()

    # Schema-clean log.
    records, errors = validate_file(log.path)
    assert errors == [], errors
    assert records, 'no events recorded'

    # EVERY submitted request reconstructs, complete, from JSONL alone.
    timelines = reconstruct(log.path)
    for rid, _ in _burst(n):
        tl = timelines.get(rid)
        assert tl is not None, f'{rid}: absent from the event log'
        assert tl.complete, f'{rid}: {tl.errors}'
        if rid in rejected:
            assert tl.status == 'rejected'
            assert tl.reason == rejected[rid].value
        else:
            # Admitted: the log agrees with the in-process result.
            assert tl.status == results[rid].status
            assert tl.tokens >= len(results[rid].tokens)
            assert tl.queue_wait is not None
    # The injected faults are in the same durable stream.
    kinds = {r.get('kind') for r in records
             if r['event'] == 'fault.inject'}
    assert {'stuck_step', 'nan_slot'} <= kinds
    assert any(r['event'] == 'serve.quarantine' for r in records)
    states = [r['state'] for r in records
              if r['event'] == 'health.liveness']
    assert 'stalled' in states and 'alive' in states

    # The quarantined request's timeline shows the full recovery arc.
    (qrid,) = {r['request_id'] for r in records
               if r['event'] == 'serve.quarantine'}
    qtl = timelines[qrid]
    assert qtl.quarantines == 1 and qtl.admits == 2
    assert qtl.status == 'completed'

    # Latency histograms: nonzero ttft / queue-wait / per-token.
    snap = registry.snapshot()['histograms']
    for name in ('serve.ttft_seconds', 'serve.queue_wait_seconds',
                 'serve.token_seconds'):
        h = snap[name]
        assert h['total_count'] > 0, name
        assert h['total_sum'] > 0, name

    # ...and they are exposed over /metrics as valid families.
    with MetricsServer(registry, health=sched.health) as srv:
        with urllib.request.urlopen(srv.url + '/metrics',
                                    timeout=5) as resp:
            text = resp.read().decode()
    for fam in ('ddp_serve_ttft_seconds', 'ddp_serve_queue_wait_seconds',
                'ddp_serve_token_seconds'):
        m = re.search(rf'^{fam}_sum ([0-9.eE+-]+)$', text, re.MULTILINE)
        assert m is not None, f'{fam} missing from /metrics'
        assert float(m.group(1)) > 0, f'{fam} empty'
    assert render_prometheus(registry) == text


def test_timeline_helper_on_missing_request(tmp_path):
    log = EventLog(tmp_path / 'x.jsonl')
    log.emit('serve.admit', request_id='r0', slot=0,
             tenant='default')
    log.close()
    tl = timeline('never-submitted', log.path)
    assert not tl.complete and tl.errors == ['no events recorded']


def test_timeline_validator_rejects_broken_lifecycles():
    def tl_of(recs):
        for i, r in enumerate(recs):
            r.setdefault('seq', i)
            r.setdefault('ts', float(i))
            r.setdefault('schema', 1)
        return reconstruct(recs)

    # Decode without an admit.
    tls = tl_of([{'event': 'serve.decode', 'request_id': 'a', 'slot': 0,
                  'token_index': 0},
                 {'event': 'serve.retire', 'request_id': 'a',
                  'status': 'completed'}])
    assert not tls['a'].complete
    assert any('without a slot' in e for e in tls['a'].errors)

    # No terminal event.
    tls = tl_of([{'event': 'serve.admit', 'request_id': 'b', 'slot': 0}])
    assert not tls['b'].complete
    assert any('no terminal event' in e for e in tls['b'].errors)

    # Retire(evicted) demands a serve.evict record.
    tls = tl_of([{'event': 'serve.admit', 'request_id': 'c', 'slot': 0},
                 {'event': 'serve.retire', 'request_id': 'c',
                  'status': 'evicted'}])
    assert any('serve.evict' in e for e in tls['c'].errors)

    # The clean arc passes, including quarantine + readmit.
    tls = tl_of([
        {'event': 'serve.admit', 'request_id': 'd', 'slot': 0,
         'queue_wait': 0.1},
        {'event': 'serve.decode', 'request_id': 'd', 'slot': 0,
         'token_index': 0, 'ttft': 0.5},
        {'event': 'serve.quarantine', 'request_id': 'd', 'slot': 0,
         'requeued': True},
        {'event': 'serve.admit', 'request_id': 'd', 'slot': 1,
         'queue_wait': 0.2},
        {'event': 'serve.decode', 'request_id': 'd', 'slot': 1,
         'token_index': 0, 'ttft': 0.9},
        {'event': 'serve.decode', 'request_id': 'd', 'slot': 1,
         'token_index': 1, 'gap': 0.01},
        {'event': 'serve.retire', 'request_id': 'd',
         'status': 'completed', 'total_seconds': 1.0},
    ])
    tl = tls['d']
    assert tl.complete, tl.errors
    assert tl.admits == 2 and tl.quarantines == 1 and tl.tokens == 3
    # The quarantine DISCARDED the first attempt's stream, so the
    # timeline reports the DELIVERED stream's TTFT (0.9 — stamped by
    # the retry, still measured from the original submit), not the
    # aborted attempt's 0.5.
    assert tl.queue_wait == 0.1 and tl.ttft == 0.9
    assert tl.token_gaps == [0.01]
    assert tl.phases()['total'] == 1.0


def test_eviction_timeline_reconstructs(tmp_path, devices):
    """Eviction path: the ladder frees the longest-idle slot; the log
    must show evict + retire(evicted) for the victim."""
    log = EventLog(tmp_path / 'evict.jsonl')
    registry = MetricsRegistry()
    sched = Scheduler(
        KernelEngine(slots=1, t_max=T_MAX, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl='xla'),
        ServeConfig(queue_limit=1, max_new_tokens=6, watchdog=False,
                    evict_before_reject=True, min_evict_idle=0.0),
        fault_injector=False, registry=registry, event_log=log)
    sched.submit(np.array([1, 2], np.int32), request_id='victim')
    sched.step()                     # victim occupies the slot
    sched.submit(np.array([3], np.int32), request_id='queued')
    sched.submit(np.array([4], np.int32), request_id='usurper')
    sched.run_until_idle()
    sched.close()
    log.close()
    tls = reconstruct(log.path)
    assert tls['victim'].status == 'evicted'
    assert tls['victim'].complete, tls['victim'].errors
    for rid in ('queued', 'usurper'):
        assert tls[rid].complete and tls[rid].status == 'completed'


def test_scheduler_uses_active_log_when_none_passed(tmp_path, devices):
    """`with obs.activate(log):` instruments a scheduler constructed
    without an explicit event_log — the integration serve_lm.py and
    smoke_serve.sh rely on."""
    log = EventLog(tmp_path / 'active.jsonl')
    with obs_events.activate(log):
        sched = Scheduler(
            KernelEngine(slots=1, t_max=16, vocab=VOCAB, heads=2,
                         head_dim=4, seed=5, decode_impl='xla'),
            ServeConfig(queue_limit=2, max_new_tokens=2,
                        watchdog=False),
            fault_injector=False, registry=MetricsRegistry())
        sched.submit(np.array([1], np.int32), request_id='r')
        sched.run_until_idle()
        sched.close()
    log.close()
    assert reconstruct(log.path)['r'].complete


def test_multi_source_merge_spans_prefill_and_decode_pools(tmp_path):
    """ROADMAP item 2 prereq: one request whose lifecycle spans a
    prefill pool's log and a decode pool's must reconstruct from the
    merged pair — per-source seq order preserved, cross-source order
    by (ts, source), replica labels annotated — with a crash-torn
    tail on one source tolerated."""
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    pre = EventLog(tmp_path / 'prefill.jsonl', clock=clock)
    pre.emit('serve.admit', request_id='x', slot=0, tenant='t0',
             queue_wait=0.01)                                  # ts 1
    pre.emit('serve.prefill', request_id='x', slot=0, pos=4)   # ts 2
    dec = EventLog(tmp_path / 'decode.jsonl', clock=clock)
    dec.emit('serve.decode', request_id='x', slot=2,
             token_index=0, ttft=0.03)                         # ts 3
    # Interleave: another prefill-pool request lands BETWEEN the
    # decode pool's records.
    pre.emit('serve.admit', request_id='y', slot=1, tenant='t1',
             queue_wait=0.0)                                   # ts 4
    dec.emit('serve.decode', request_id='x', slot=2,
             token_index=1, gap=0.002)                         # ts 5
    dec.emit('serve.retire', request_id='x', status='completed',
             total_seconds=0.05, tenant='t0')                  # ts 6
    pre.emit('serve.retire', request_id='y', status='abandoned',
             tenant='t1')                                      # ts 7
    pre.close()
    dec.close()
    # Torn tail on the decode source (crash mid-write): tolerated on
    # read, exactly like the single-log contract.
    with open(dec.path, 'a', encoding='utf-8') as f:
        f.write('{"schema": 2, "seq": 99, "ev')

    tls = reconstruct([('prefill', pre.path), ('decode', dec.path)])
    x = tls['x']
    assert x.complete, x.errors
    assert x.status == 'completed' and x.tenant == 't0'
    assert x.ttft == 0.03 and x.token_gaps == [0.002]
    assert x.replicas == ['prefill', 'decode']
    # Merge order: the automaton saw admit -> prefill -> decode ->
    # decode -> retire (any other order would have errored), and the
    # merged per-request stream is ts-sorted.
    assert [r['event'] for r in x.events] == [
        'serve.admit', 'serve.prefill', 'serve.decode', 'serve.decode',
        'serve.retire']
    assert [r['replica'] for r in x.events] == [
        'prefill', 'prefill', 'decode', 'decode', 'decode']
    y = tls['y']
    assert y.complete and y.status == 'abandoned'
    assert y.replicas == ['prefill']


def test_merge_events_stable_on_ts_ties(tmp_path):
    """Equal timestamps resolve in source order, and records of one
    source never reorder against each other (seq stays authoritative
    within a source even when its clock stands still)."""
    from distributed_dot_product_tpu.obs.events import merge_events

    frozen = lambda: 5.0  # noqa: E731
    a = EventLog(tmp_path / 'a.jsonl', clock=frozen)
    a.emit('health.liveness', state='alive')
    a.emit('health.liveness', state='stalled')
    b = EventLog(tmp_path / 'b.jsonl', clock=frozen)
    b.emit('health.readiness', state='ready')
    a.close()
    b.close()
    recs = merge_events([a.path, b.path])
    assert [(r['replica'], r['seq']) for r in recs] == [
        ('r0', 0), ('r0', 1), ('r1', 0)]


def test_preempt_requeue_spec_completion_arc(tmp_path, devices):
    """Combined-arc satellite: a request preempted by page exhaustion,
    requeued, then completed via speculative ticks must reconstruct
    from the JSONL alone with the preempt + re-admit counted, spec
    acceptance recorded, and a nonzero TTFT measured from the ORIGINAL
    submit (the requeue does not reset the request's clock to its
    first token)."""
    from distributed_dot_product_tpu.serve import VirtualClock

    clock = VirtualClock()
    log = EventLog(tmp_path / 'arc.jsonl', clock=clock)
    eng = KernelEngine(slots=2, t_max=16, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       cache_mode='paged', page_size=2, pages=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng,
        ServeConfig(queue_limit=4, max_new_tokens=8, watchdog=False,
                    evict_before_reject=False, max_requeues=6,
                    spec='ngram', spec_k=3),
        registry=MetricsRegistry(), fault_injector=False,
        event_log=log, clock=clock,
        on_tick=lambda s: clock.advance(0.01))
    sched.submit([1], request_id='a')
    sched.submit([2], request_id='b')
    results = sched.run_until_idle()
    sched.close()
    log.close()

    # Both requests eventually completed (pool frees as the winner
    # retires; max_requeues is generous enough for the loser).
    assert {r.status for r in results.values()} == {'completed'}
    _, errors = validate_file(log.path)
    assert errors == [], errors
    tls = reconstruct(log.path)
    arcs = [t for t in tls.values() if t.preempts]
    assert arcs, 'page exhaustion never preempted anyone'
    tl = arcs[0]
    assert tl.complete, tl.errors
    assert tl.status == 'completed'
    assert tl.admits == 1 + tl.preempts     # re-admitted per preempt
    # The retried stream completed through verify ticks with real
    # acceptance — the spec arcs fold into the same lifecycle.
    assert tl.spec_steps > 0
    assert tl.spec_accepted > 0
    # TTFT anchored at the ORIGINAL submit: the first committed token
    # arrived only AFTER the preempt (whose virtual time is the event
    # ts — same clock), so the stamped TTFT must cover that wait.
    assert tl.ttft is not None and tl.ttft > 0
    preempt_ts = min(r['ts'] for r in tl.events
                     if r['event'] == 'serve.preempt')
    submit_like = [r for r in tl.events if r['event'] == 'serve.admit']
    first_admit_ts = min(r['ts'] for r in submit_like)
    # The DELIVERED stream's first token = the last stamped TTFT (the
    # earlier attempt's was discarded by the requeue).
    ttft_decode = [r for r in tl.events
                   if r['event'] == 'serve.decode'
                   and r.get('ttft') is not None][-1]
    assert tl.ttft == ttft_decode['ttft']
    assert ttft_decode['ts'] >= preempt_ts
    assert tl.ttft >= ttft_decode['ts'] - first_admit_ts > 0


# -- merge_events edge cases (disaggregated log sets) -------------------

def test_merge_events_three_replicas_and_empty_source(tmp_path):
    """>= 3 sources merge with per-source seq order preserved and
    every record labeled; a completely EMPTY source log (a replica
    that saw no traffic) contributes nothing and breaks nothing."""
    from distributed_dot_product_tpu.obs.events import merge_events

    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    logs = []
    for name in ('router', 'r0', 'r1'):
        logs.append((name, EventLog(tmp_path / f'{name}.jsonl',
                                    clock=clock)))
    by = dict(logs)
    by['router'].emit('router.route', request_id='x', target='r0')
    by['r0'].emit('serve.admit', request_id='x', slot=0, tenant='t0',
                  queue_wait=0.0)
    by['r1'].emit('serve.admit', request_id='y', slot=0, tenant='t1',
                  queue_wait=0.0)
    by['r0'].emit('serve.retire', request_id='x', status='completed',
                  tenant='t0')
    by['r1'].emit('serve.retire', request_id='y', status='completed',
                  tenant='t1')
    for _, log in logs:
        log.close()
    empty = tmp_path / 'r2.jsonl'
    empty.write_text('')
    sources = [(n, log.path) for n, log in logs] + [('r2', empty)]
    recs = merge_events(sources)
    assert len(recs) == 5
    assert [r['replica'] for r in recs] == [
        'router', 'r0', 'r1', 'r0', 'r1']
    # Per-source seq never reorders.
    for name in ('router', 'r0', 'r1'):
        seqs = [r['seq'] for r in recs if r['replica'] == name]
        assert seqs == sorted(seqs)
    assert not any(r['replica'] == 'r2' for r in recs)
    # The merged set reconstructs: the route event rides x's timeline.
    tls = reconstruct(sources)
    assert tls['x'].complete and tls['x'].routes == 1
    assert tls['x'].replicas == ['router', 'r0']
    assert tls['y'].complete and tls['y'].replicas == ['r1']


def test_merge_events_duplicate_labels_typed_error(tmp_path):
    """Two sources under one replica label would collapse into one
    indistinguishable stream — a typed ValueError naming the label,
    never a silently corrupted merge."""
    from distributed_dot_product_tpu.obs.events import merge_events

    a = EventLog(tmp_path / 'a.jsonl')
    a.emit('health.liveness', state='alive')
    a.close()
    b = EventLog(tmp_path / 'b.jsonl')
    b.emit('health.liveness', state='alive')
    b.close()
    with pytest.raises(ValueError, match="duplicate replica label 'r0'"):
        merge_events([('r0', a.path), ('r0', b.path)])
    # Auto-labels are positional and unique — the same pair merges.
    assert len(merge_events([a.path, b.path])) == 2


def test_merge_events_ts_tie_stability_three_sources(tmp_path):
    """Equal timestamps across THREE sources resolve in source order,
    deterministically: merging twice yields the identical sequence,
    and reordering the sources reorders ONLY the tied records."""
    from distributed_dot_product_tpu.obs.events import merge_events

    frozen = lambda: 7.0  # noqa: E731
    paths = []
    for i in range(3):
        log = EventLog(tmp_path / f's{i}.jsonl', clock=frozen)
        log.emit('health.liveness', state='alive')
        log.emit('health.readiness', state='ready')
        log.close()
        paths.append((f's{i}', log.path))
    recs = merge_events(paths)
    assert [(r['replica'], r['seq']) for r in recs] == [
        ('s0', 0), ('s0', 1), ('s1', 0), ('s1', 1), ('s2', 0),
        ('s2', 1)]
    assert [(r['replica'], r['seq']) for r in merge_events(paths)] \
        == [(r['replica'], r['seq']) for r in recs]
    flipped = merge_events(list(reversed(paths)))
    assert [(r['replica'], r['seq']) for r in flipped] == [
        ('s2', 0), ('s2', 1), ('s1', 0), ('s1', 1), ('s0', 0),
        ('s0', 1)]
