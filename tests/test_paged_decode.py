# -*- coding: utf-8 -*-
"""
Paged KV cache (models/decode.py PagedDecodeCache + PagePool,
ops/pallas_decode.py page-table mode) — unit and parity tests.

The contract under test: the paged cache is a MEMORY layout change,
not a numerics change. The paged XLA step must match the slab XLA step
bit for bit; the paged kernel step must match the paged XLA step to
kernel tolerance (exp2 online softmax) and keep the pool bit-identical
to the XLA append. On top of the layout: refcounted prefix sharing,
copy-on-write fork, freed-page zeroing, and the exhaustion surface the
scheduler's ladder is built on.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_dot_product_tpu.models.decode import (
    PagePool, append_kv_slots, decode_step, init_paged_cache,
    init_slot_cache, paged_append_rows, paged_copy_attach, paged_gather,
    paged_reset_slot, reset_slot,
)

B, H, T, D, PS, PAGES = 2, 2, 32, 8, 8, 10


def _rng(seed=0):
    return np.random.default_rng(seed)


def _mk_pair(dtype=jnp.float32, fills=(10, 3), seed=0):
    """A slab cache and a paged twin holding identical contents at
    per-slot fills, plus the paged side's host allocator."""
    rng = _rng(seed)
    slab = init_slot_cache(B, H, T, D, dtype=dtype)
    paged = init_paged_cache(B, H, T, D, pages=PAGES, page_size=PS,
                             dtype=dtype)
    pool = PagePool(PAGES, PS, B, T // PS)
    for slot, n in enumerate(fills):
        if not n:
            continue
        k = jnp.asarray(rng.normal(size=(B, H, n, D)), dtype)
        v = jnp.asarray(rng.normal(size=(B, H, n, D)), dtype)
        sel = np.arange(B) == slot
        counts = np.where(sel, n, 0).astype(np.int32)
        ok, copies = pool.reserve_rows(slot, n)
        assert ok and not copies
        paged = paged._replace(page_table=jnp.asarray(pool.table))
        slab = append_kv_slots(slab, k, v, slot_mask=sel, counts=counts)
        paged = append_kv_slots(paged, k, v, slot_mask=sel,
                                counts=counts)
        pool.lengths[slot] += n
    return slab, paged, pool


def _prepare(paged, pool, active=None):
    """Host-side page reservation + device mirror for one decode step."""
    for slot in range(pool.slots):
        if active is not None and not active[slot]:
            continue
        st, src, dst = pool.prepare_append(slot)
        assert st != 'exhausted'
        if st == 'cow':
            paged = paged_copy_attach(paged, jnp.int32(src),
                                      jnp.int32(dst), jnp.int32(-1),
                                      jnp.int32(0))
    return paged._replace(page_table=jnp.asarray(pool.table))


def _qkv(seed=7, dtype=jnp.float32):
    rng = _rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(B, H, 1, D)), dtype)
                 for _ in range(3))


# -- layout parity ------------------------------------------------------

def test_append_and_gather_match_slab_bitwise():
    slab, paged, pool = _mk_pair()
    gk, gv = paged_gather(paged)
    assert np.array_equal(np.asarray(slab.length),
                          np.asarray(paged.length))
    for i, ln in enumerate(np.asarray(slab.length)):
        assert np.array_equal(np.asarray(slab.k)[i, :, :ln],
                              np.asarray(gk)[i, :, :ln])
        assert np.array_equal(np.asarray(slab.v)[i, :, :ln],
                              np.asarray(gv)[i, :, :ln])


def test_append_crosses_page_boundary():
    """A chunk straddling two pages lands split across pool pages."""
    _, paged, pool = _mk_pair(fills=(6, 0), seed=3)
    rng = _rng(9)
    k = jnp.asarray(rng.normal(size=(B, H, 5, D)), jnp.float32)
    sel = np.arange(B) == 0
    ok, _ = pool.reserve_rows(0, 5)          # rows 6..10: pages 0 and 1
    assert ok and pool.counts[0] == 2
    paged = paged._replace(page_table=jnp.asarray(pool.table))
    paged = append_kv_slots(paged, k, k, slot_mask=sel,
                            counts=np.where(sel, 5, 0).astype(np.int32))
    gk, _ = paged_gather(paged)
    assert np.array_equal(np.asarray(gk)[0, :, 6:11],
                          np.asarray(k)[0])


def test_decode_step_xla_bit_identical_to_slab():
    slab, paged, pool = _mk_pair()
    q, kn, vn = _qkv()
    paged = _prepare(paged, pool)
    slab2, out_s = decode_step(q, slab, kn, vn, impl='xla')
    paged2, out_p = decode_step(q, paged, kn, vn, impl='xla')
    assert np.array_equal(np.asarray(out_s), np.asarray(out_p))
    gk, gv = paged_gather(paged2)
    for i, ln in enumerate(np.asarray(slab2.length)):
        assert np.array_equal(np.asarray(slab2.k)[i, :, :ln],
                              np.asarray(gk)[i, :, :ln])


@pytest.mark.parametrize('window,alibi', [(None, False), (6, False),
                                          (None, True)])
def test_decode_step_kernel_matches_xla(window, alibi):
    """The fused paged kernel (page-table BlockSpec redirect, run
    interpreted off-TPU) reproduces the paged XLA step: outputs to
    kernel tolerance, pool contents BIT-identical (the aliased append
    writes exactly the XLA scatter's bytes)."""
    slopes = np.array([0.3, 0.7], np.float32) if alibi else None
    _, paged, pool = _mk_pair()
    q, kn, vn = _qkv()
    paged = _prepare(paged, pool)
    px, out_x = decode_step(q, paged, kn, vn, impl='xla',
                            window=window, alibi_slopes=slopes)
    pk, out_k = decode_step(q, paged, kn, vn, impl='kernel',
                            interpret=True, window=window,
                            alibi_slopes=slopes)
    assert np.allclose(np.asarray(out_x), np.asarray(out_k), atol=1e-5)
    assert np.array_equal(np.asarray(px.k_pool), np.asarray(pk.k_pool))
    assert np.array_equal(np.asarray(px.v_pool), np.asarray(pk.v_pool))
    assert np.array_equal(np.asarray(px.length), np.asarray(pk.length))


def test_kernel_writes_only_the_append_pages():
    """Aliasing discipline: every pool page NOT containing a slot's
    append position keeps its exact bits through the kernel step."""
    _, paged, pool = _mk_pair()
    q, kn, vn = _qkv()
    paged = _prepare(paged, pool)
    before = np.asarray(paged.k_pool).copy()
    append_pages = {int(pool.table[s, int(pool.lengths[s]) // PS])
                    for s in range(B)}
    pk, _ = decode_step(q, paged, kn, vn, impl='kernel', interpret=True)
    after = np.asarray(pk.k_pool)
    for page in range(PAGES):
        if page not in append_pages:
            assert np.array_equal(before[page], after[page]), page


def test_slot_mask_freezes_inactive_slots():
    slab, paged, pool = _mk_pair()
    q, kn, vn = _qkv()
    active = np.array([True, False])
    paged = _prepare(paged, pool, active=active)
    slab2, out_s = decode_step(q, slab, kn, vn, slot_mask=active,
                               impl='xla')
    paged2, out_p = decode_step(q, paged, kn, vn, slot_mask=active,
                                impl='xla')
    assert np.array_equal(np.asarray(out_s), np.asarray(out_p))
    assert np.asarray(paged2.length)[1] == np.asarray(paged.length)[1]


def test_overflow_raises_eagerly_naming_slot():
    _, paged, pool = _mk_pair(fills=(0, 0))
    paged = paged._replace(length=jnp.array([T, 0], jnp.int32))
    rng = _rng(1)
    k = jnp.asarray(rng.normal(size=(B, H, 1, D)), jnp.float32)
    with pytest.raises(ValueError, match='slot 0'):
        append_kv_slots(paged, k, k)


def test_unallocated_page_drops_write():
    """The device-side guard: a row whose table entry is −1 writes
    NOTHING anywhere (host allocator bug ≠ silent cross-slot
    corruption), while the length still advances (detectable)."""
    _, paged, pool = _mk_pair(fills=(10, 3))
    before = np.asarray(paged.k_pool).copy()
    rng = _rng(2)
    k = jnp.asarray(rng.normal(size=(B, H, 1, D)), jnp.float32)
    # No reserve_rows / prepare_append: slot 0's position 10 has a page
    # (page 1 row 2) but make it unallocated to simulate the bug.
    tbl = pool.table.copy()
    tbl[0, 1] = -1
    paged = paged._replace(page_table=jnp.asarray(tbl))
    out = append_kv_slots(paged, k, k,
                          slot_mask=np.array([True, False]))
    after = np.asarray(out.k_pool)
    assert np.array_equal(before, after)
    assert int(np.asarray(out.length)[0]) == 11


def test_reset_zeroes_freed_pages_only():
    _, paged, pool = _mk_pair()
    shared_page = int(pool.table[1, 0])      # slot 1's page survives
    freed = pool.release(0)
    assert freed and shared_page not in freed
    vec = np.full(T // PS, -1, np.int32)
    vec[:len(freed)] = freed
    out = paged_reset_slot(paged, jnp.int32(0), jnp.asarray(vec))
    kp = np.asarray(out.k_pool)
    for page in freed:
        assert not kp[page].any()
    assert kp[shared_page].any()
    assert int(np.asarray(out.length)[0]) == 0
    assert (np.asarray(out.page_table)[0] == -1).all()


def test_reset_slot_on_paged_cache_directs_to_paged_reset():
    _, paged, _ = _mk_pair()
    with pytest.raises(ValueError, match='paged_reset_slot'):
        reset_slot(paged, 0)


# -- sharing: prefix attach, fork, copy-on-write ------------------------

def test_attach_shares_full_pages_and_copies_tail():
    """Two slots attached to one registered prefix occupy the prefix's
    FULL pages exactly once (refcount 3 = registry + 2 slots, pool
    usage unchanged) and each get a private copy of the partial tail
    page."""
    pool = PagePool(PAGES, PS, 2, T // PS)
    plen = PS + 3                            # one full page + 3 rows
    prefix_pages = [pool.alloc(), pool.alloc()]
    used0 = pool.used_pages
    attaches = []
    for slot in range(2):
        ok, src, dst = pool.attach(slot, prefix_pages, plen)
        assert ok
        assert src == prefix_pages[1] and dst not in prefix_pages
        attaches.append(dst)
        pool.lengths[slot] = plen
    # The full page is counted once however many sequences share it.
    assert pool.used_pages == used0 + 2      # the two private tails
    assert pool.refcount[prefix_pages[0]] == 3
    assert pool.shared_pages == 1
    assert attaches[0] != attaches[1]


def test_cow_on_first_divergent_append():
    """Fork then append: the shared tail was already copied at fork, so
    the branches' first appends hit private pages; a SHARED full page
    boundary triggers the copy-on-write pair from prepare_append."""
    pool = PagePool(PAGES, PS, 2, T // PS)
    # Slot 0 with exactly one FULL page, then fork at the page boundary.
    ok, _ = pool.reserve_rows(0, PS)
    assert ok
    pool.lengths[0] = PS
    ok, src, dst = pool.fork(0, 1)
    assert ok and src == -1 and dst == -1    # aligned fork: no copy
    page = int(pool.table[0, 0])
    assert pool.refcount[page] == 2
    # Next append of either branch lands in a FRESH page (position PS
    # opens page ordinal 1) — no CoW needed, the shared page is never
    # written again.
    st, _, _ = pool.prepare_append(0)
    assert st == 'alloc'
    # Now seed a genuinely shared append page: mid-page fork.
    pool2 = PagePool(PAGES, PS, 2, T // PS)
    ok, _ = pool2.reserve_rows(0, 3)
    pool2.lengths[0] = 3
    ok, src, dst = pool2.fork(0, 1)
    assert ok and src == int(pool2.table[0, 0]) and dst >= 0
    assert pool2.table[1, 0] == dst          # branch owns its tail copy
    st, _, _ = pool2.prepare_append(1)
    assert st == 'ok'                        # already private
    st, _, _ = pool2.prepare_append(0)
    assert st == 'ok'


def test_fork_streams_identical(monkeypatch):
    """Device-level fork: branch attends the forked prefix identically
    to the source (shared pages + copied tail), then diverges only
    through its own appends."""
    _, paged, pool = _mk_pair(fills=(10, 0))
    ok, src, dst = pool.fork(0, 1)
    assert ok
    paged = paged_copy_attach(paged, jnp.int32(src), jnp.int32(dst),
                              jnp.int32(1), jnp.int32(10))
    paged = paged._replace(page_table=jnp.asarray(pool.table))
    gk, gv = paged_gather(paged)
    assert np.array_equal(np.asarray(gk)[0, :, :10],
                          np.asarray(gk)[1, :, :10])
    # Shared full page counted once: 10 rows = 2 pages, 1 full shared.
    assert pool.refcount[int(pool.table[0, 0])] == 2
    assert pool.table[0, 1] != pool.table[1, 1]
    # Divergent appends stay private.
    q, kn, vn = _qkv(11)
    paged = _prepare(paged, pool)
    p2, out = decode_step(q, paged, kn, vn, impl='xla')
    g2k, _ = paged_gather(p2)
    assert np.array_equal(np.asarray(g2k)[0, :, :10],
                          np.asarray(g2k)[1, :, :10])
    assert np.array_equal(np.asarray(out)[0], np.asarray(out)[1]) \
        == bool(np.array_equal(np.asarray(q)[0], np.asarray(q)[1]))


def test_prefix_fill_writes_registry_pages():
    paged = init_paged_cache(1, H, T, D, pages=PAGES, page_size=PS,
                             dtype=jnp.float32)
    pool = PagePool(PAGES, PS, 1, T // PS)
    pages = [pool.alloc(), pool.alloc()]
    rng = _rng(5)
    rows = jnp.asarray(rng.normal(size=(H, PS + 2, D)), jnp.float32)
    row_vec = np.full(T // PS, -1, np.int32)
    row_vec[:2] = pages
    paged = paged_append_rows(paged, rows, rows, jnp.asarray(row_vec),
                              jnp.int32(0), jnp.int32(PS + 2))
    kp = np.asarray(paged.k_pool)
    assert np.array_equal(kp[pages[0]], np.asarray(rows)[:, :PS]
                          .transpose(0, 1, 2))
    assert np.array_equal(kp[pages[1], :, :2], np.asarray(rows)[:, PS:])
    assert not kp[pages[1], :, 2:].any()


# -- exhaustion ---------------------------------------------------------

def test_pool_exhaustion_is_typed_and_rolls_back():
    pool = PagePool(2, PS, 2, T // PS)
    ok, _ = pool.reserve_rows(0, 2 * PS)     # takes both pages
    assert ok and pool.free_pages == 0
    ok, copies = pool.reserve_rows(1, 1)
    assert not ok and not copies
    assert pool.counts[1] == 0 and (pool.table[1] == -1).all()
    st, _, _ = pool.prepare_append(1)
    assert st == 'exhausted'
    freed = pool.release(0)
    assert sorted(freed) == sorted(pool._free[-2:])
    assert pool.free_pages == 2


def test_reserve_rollback_keeps_pool_consistent():
    pool = PagePool(3, PS, 2, T // PS)
    ok, _ = pool.reserve_rows(0, PS)         # 1 page used
    assert ok
    ok, _ = pool.reserve_rows(1, 3 * PS)     # needs 3, only 2 free
    assert not ok
    assert pool.free_pages == 2 and pool.counts[1] == 0
    assert (pool.refcount >= 0).all()
    ok, _ = pool.reserve_rows(1, 2 * PS)     # what's left still works
    assert ok


def test_kernel_ineligible_when_page_exceeds_vmem_cap():
    """The paged kernel's K split IS the page size, so a page larger
    than the slab split's VMEM cap must route to the XLA path (auto)
    and raise a typed error when the kernel is forced — not hand
    Mosaic an oversized double-buffered K+V stream."""
    from distributed_dot_product_tpu.models.decode import (
        decode_kernel_eligible,
    )
    from distributed_dot_product_tpu.ops.pallas_decode import (
        _BLOCK_K_CAP,
    )
    big_ps = 2 * _BLOCK_K_CAP
    cache = init_paged_cache(1, H, 2 * big_ps, D, pages=3,
                             page_size=big_ps)
    assert not decode_kernel_eligible(cache)
    small = init_paged_cache(1, H, T, D, pages=PAGES, page_size=PS)
    assert decode_kernel_eligible(small)
    q = jnp.zeros((1, H, 1, D))
    new = jnp.zeros((1, H, 1, D))
    with pytest.raises(ValueError, match='does not cover'):
        decode_step(q, cache, new, new, impl='kernel')


def test_pool_alloc_block_and_release_pages():
    """Block allocation is all-or-nothing (rollback leaves the pool
    untouched) and release_pages reports exactly the pages whose last
    reference dropped."""
    pool = PagePool(4, PS, 1, 4)
    assert pool.alloc_block(5) is None       # too big: nothing changed
    assert pool.free_pages == 4
    assert (pool.refcount == 0).all()
    pages = pool.alloc_block(3)
    assert pages is not None and pool.free_pages == 1
    assert all(pool.refcount[p] == 1 for p in pages)
    assert pool.alloc_block(2) is None       # partial: rolled back
    assert pool.free_pages == 1
    pool.refcount[pages[0]] += 1             # a rider shares page 0
    freed = pool.release_pages(pages)
    assert sorted(freed) == sorted(pages[1:])
    assert pool.refcount[pages[0]] == 1
    assert pool.free_pages == 3
