# -*- coding: utf-8 -*-
"""
Additive-schema regression (obs/events.py v1/v2 + the dispatch-floor
fields): the new accounting fields (`serve.dispatch` records,
`device_seconds` on serve.decode, `build_seconds`/`transfer_seconds`
on prefill.handoff, `total_seconds` on serve.reject) are ADDITIVE —
v1 logs (pre-tenancy) and v2 logs written before this change still
schema-validate, timeline-reconstruct, and critpath-attribute, and
the new records validate against the same closed vocabulary.
"""

import json

import pytest

from distributed_dot_product_tpu.obs.critpath import attribute, profile
from distributed_dot_product_tpu.obs.events import (
    EVENT_SCHEMA, SCHEMA_VERSION, SUPPORTED_SCHEMAS, validate_file,
    validate_record,
)
from distributed_dot_product_tpu.obs.timeline import reconstruct

pytestmark = pytest.mark.obs


def _write(path, recs):
    with open(path, 'w', encoding='utf-8') as f:
        for rec in recs:
            f.write(json.dumps(rec) + '\n')
    return str(path)


def _v1_lifecycle():
    """A pre-tenancy (schema 1) lifecycle, exactly as an old log wrote
    it: no `tenant`, no dispatch records, no device stamps."""
    return [
        {'schema': 1, 'seq': 0, 'ts': 1.0, 'event': 'serve.admit',
         'request_id': 'r', 'slot': 0, 'queue_wait': 0.5},
        {'schema': 1, 'seq': 1, 'ts': 1.5, 'event': 'serve.prefill',
         'request_id': 'r', 'slot': 0, 'pos': 4},
        {'schema': 1, 'seq': 2, 'ts': 2.0, 'event': 'serve.decode',
         'request_id': 'r', 'slot': 0, 'token_index': 0},
        {'schema': 1, 'seq': 3, 'ts': 3.0, 'event': 'serve.retire',
         'request_id': 'r', 'status': 'completed',
         'total_seconds': 2.5},
    ]


def _v2_pre_dispatch():
    """A schema-2 log written BEFORE dispatch-floor accounting: tenant
    present, none of the new additive fields."""
    return [
        {'schema': 2, 'seq': 0, 'ts': 1.0, 'event': 'serve.admit',
         'request_id': 'r', 'slot': 0, 'tenant': 'default'},
        {'schema': 2, 'seq': 1, 'ts': 2.0, 'event': 'serve.decode',
         'request_id': 'r', 'slot': 0, 'token_index': 0},
        {'schema': 2, 'seq': 2, 'ts': 2.25, 'event': 'serve.reject',
         'request_id': 'q', 'reason': 'queue_full',
         'tenant': 'default'},
        {'schema': 2, 'seq': 3, 'ts': 3.0, 'event': 'serve.retire',
         'request_id': 'r', 'status': 'completed',
         'total_seconds': 2.0},
    ]


def test_v1_log_still_validates_and_reconstructs(tmp_path):
    path = _write(tmp_path / 'v1.jsonl', _v1_lifecycle())
    records, errors = validate_file(path)
    assert errors == [], errors
    tls = reconstruct(records)
    assert tls['r'].complete and tls['r'].status == 'completed'
    # And critpath-attributes: the new module asks nothing of old logs
    # beyond what they always carried.
    chains = attribute(path)
    assert chains['r'].ok
    assert sum(chains['r'].phases.values()) == pytest.approx(2.5)


def test_v2_pre_dispatch_log_still_validates(tmp_path):
    path = _write(tmp_path / 'v2.jsonl', _v2_pre_dispatch())
    records, errors = validate_file(path)
    assert errors == [], errors
    chains = attribute(path)
    assert chains['r'].ok
    # The reject without total_seconds is a PARTIAL chain (old logs
    # did not stamp it) — attributed best-effort, never asserted.
    assert chains['q'].partial
    prof = profile(chains)
    assert prof['partition_failures'] == []


def test_v1_tenant_exemption_is_versioned():
    """`tenant` is required at v2, exempt at v1 — the exemption must
    key on the RECORD's version, not the writer's."""
    v1 = {'schema': 1, 'seq': 0, 'ts': 1.0, 'event': 'serve.admit',
          'request_id': 'r', 'slot': 0}
    assert validate_record(v1) == []
    v2 = dict(v1, schema=2)
    assert any('tenant' in e for e in validate_record(v2))


def test_dispatch_event_is_in_the_closed_vocabulary():
    assert 'serve.dispatch' in EVENT_SCHEMA
    rec = {'schema': SCHEMA_VERSION, 'seq': 0, 'ts': 1.0,
           'event': 'serve.dispatch', 'step': 3,
           'tick_seconds': 0.01, 'device_seconds': 0.004,
           'overhead': 0.006, 'tokens': 2}
    assert validate_record(rec) == []
    # Required fields enforced.
    missing = {k: v for k, v in rec.items() if k != 'device_seconds'}
    assert any('device_seconds' in e for e in validate_record(missing))


def test_additive_fields_need_no_schema_bump(tmp_path):
    """The new stamps ride as EXTRA fields on existing events — the
    schema version did not move, and both supported versions accept
    records with or without them."""
    assert SCHEMA_VERSION == 2
    assert SUPPORTED_SCHEMAS == (1, 2)
    recs = _v2_pre_dispatch()
    # The same events as a fresh log writes them, stamps included.
    recs[1] = dict(recs[1], device_seconds=0.004)
    recs[2] = dict(recs[2], total_seconds=0.25, queued=True)
    path = _write(tmp_path / 'new.jsonl', recs)
    records, errors = validate_file(path)
    assert errors == [], errors
    chains = attribute(path)
    assert chains['r'].ok
    # The stamped reject now anchors: its whole e2e is queue time.
    assert not chains['q'].partial
    assert chains['q'].phases == pytest.approx({'queue': 0.25})


def test_handoff_split_fields_are_optional(tmp_path):
    base = {'schema': 2, 'seq': 0, 'ts': 1.0,
            'event': 'prefill.handoff', 'request_id': 'r',
            'target': 'r0', 'pages': 2}
    assert validate_record(base) == []
    assert validate_record(dict(base, build_seconds=0.1,
                                transfer_seconds=0.05)) == []
