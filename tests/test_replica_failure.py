# -*- coding: utf-8 -*-
"""
Replica failure domains (ISSUE-16): crash-tolerant disaggregated
serving with deterministic stream recovery. A decode replica dying
mid-stream is detected by router liveness probes (never by shared
memory), every in-flight stream it held is re-dispatched to a
survivor by replay-prefill from the recovery ledger — bit-identical
to a crash-free run, TTFT still anchored at the ORIGINAL submit — and
the whole arc is auditable: the torn victim log merges, every request
classifies exactly once, and ``obs doctor`` names the dead replica.
Recovery that cannot happen (no survivor, budget spent) terminates
with the typed ``REPLICA_LOST`` reject, never a silent drop.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import doctor as obs_doctor
from distributed_dot_product_tpu.obs import flight as obs_flight
from distributed_dot_product_tpu.obs.events import EventLog
from distributed_dot_product_tpu.obs.timeline import reconstruct
from distributed_dot_product_tpu.serve import (
    ChaosSchedule, LoadGenConfig, RejectReason, RouterConfig,
    ServeConfig, TopologyConfig, VirtualClock, build_serving,
    default_tenants, generate_trace, load_trace, run_trace, save_trace,
)
from distributed_dot_product_tpu.utils.faults import (
    ChaosInjector, ChaosPlan, chaos_plan_from_env,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry


def _topo(replicas=2, slots=2, t_max=64, page_size=16, vocab=32,
          **kw):
    return TopologyConfig(decode_replicas=replicas, slots=slots,
                          t_max=t_max, page_size=page_size,
                          vocab=vocab, seed=3, **kw)


def _serving(tmp_path, clock, *, chaos=None, replicas=2,
             threshold=100, queue_limit=8, max_new=6, slots=2,
             **router_kw):
    """A serving topology with FAST probes on the virtual clock —
    loss detection must land inside a test-sized run."""
    router_kw.setdefault('probe_interval', 0.02)
    router_kw.setdefault('probe_backoff_max', 0.04)
    return build_serving(
        _topo(replicas=replicas, slots=slots),
        serve_config=ServeConfig(watchdog=False,
                                 queue_limit=queue_limit,
                                 max_new_tokens=max_new),
        router_config=RouterConfig(prefill_threshold=threshold,
                                   **router_kw),
        clock=clock, log_dir=tmp_path / 'logs', chaos=chaos)


def _settle(router, clock, dt=0.01, max_ticks=5000):
    """run_until_idle with the clock ADVANCING: probe deadlines are
    virtual-time, so a static clock would never detect a loss."""
    ticks = 0
    while router.step():
        clock.advance(dt)
        ticks += 1
        assert ticks < max_ticks, 'topology never settled'
    return router.results


def _prompts(n, length=6):
    return {f'p{i}': list(((np.arange(length) * 3 + i) % 32) + 1)
            for i in range(n)}


def _member(router, name):
    return next(r for r in router.pool.replicas if r.name == name)


def _events(router, name='router'):
    return list(obs.read_events(dict(router.pool.logs())[name]))


# -- the tentpole arc: kill -> probe -> recover, bit-identical ----------

def test_crash_recovery_bit_identical_and_torn_log_merges(tmp_path,
                                                          devices):
    """ISSUE-16 acceptance in miniature: kill one of two replicas with
    streams in flight. Probes declare the loss, the ledger re-places
    every in-flight stream on the survivor, each recovered stream is
    BIT-IDENTICAL to a crash-free single-replica run of the same
    prompts, and every request reconstructs exactly once across the
    merged logs — the victim's torn tail included."""
    prompts = _prompts(4)

    # Crash-free twin: same engine seed, same prompts, one replica.
    clock_twin = VirtualClock()
    twin = _serving(tmp_path / 'twin', clock_twin, replicas=1)
    try:
        for rid, p in prompts.items():
            twin.submit(p, request_id=rid)
        base = twin.run_until_idle()
    finally:
        twin.close()
    assert all(base[rid].status == 'completed' for rid in prompts)

    clock = VirtualClock()
    router = _serving(tmp_path, clock)
    try:
        for rid, p in prompts.items():
            router.submit(p, request_id=rid)
        for _ in range(2):          # streams decoding on BOTH members
            router.step()
            clock.advance(0.01)
        victims = [rid for rid, e in router._ledger.items()
                   if e['replica'] == 'r1']
        assert victims, 'least-loaded placement left r1 empty'
        _member(router, 'r1').kill()   # the process is gone, router
        results = _settle(router, clock)   # ...finds out by probing
    finally:
        router.close()

    assert [r.name for r in router.pool.replicas] == ['r0']
    assert [r.name for r in router.pool.lost] == ['r1']
    counters = router.registry.snapshot()['counters']
    assert counters['router.replicas_lost'] == 1
    assert counters['router.recovered'] == len(victims)

    # Every stream completed, and recovered ones equal the twin's.
    for rid in prompts:
        assert results[rid].status == 'completed', results[rid]
        assert results[rid].tokens == base[rid].tokens, rid

    revs = _events(router)
    lost = [r for r in revs if r['event'] == 'replica.lost']
    assert len(lost) == 1 and lost[0]['target'] == 'r1'
    assert lost[0]['reason'] == 'probe_timeout'
    assert lost[0]['in_flight'] == len(victims)
    recovered = {r['request_id'] for r in revs
                 if r['event'] == 'request.recovered'
                 and r['requeued']}
    assert recovered == set(victims)
    assert any(r['event'] == 'replica.probe'
               and r['state'] == 'missed' for r in revs)

    # The victim's log is TORN — kill() left a half-written record —
    # yet it still reads, and the merged reconstruction classifies
    # every request exactly once with a complete arc.
    victim_path = dict(router.pool.logs())['r1']
    with open(victim_path, encoding='utf-8') as fh:
        tail = fh.read().rsplit('\n', 1)[-1]
    assert tail == '{"schema":2,"seq":'
    assert list(obs.read_events(victim_path))   # tolerated, not fatal
    tls = reconstruct(router.pool.logs())
    assert set(tls) == set(prompts)
    for rid, tl in tls.items():
        assert tl.complete, (rid, tl.errors)
        assert tl.recoveries == (1 if rid in recovered else 0)


def test_recovered_ttft_anchored_at_original_submit(tmp_path, devices):
    """The recovery ledger preserves ``submitted_at``: a recovered
    stream's TTFT is measured from the ORIGINAL submit, not from the
    re-dispatch — recovery does not launder latency."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, max_new=8)
    try:
        router.submit(list(range(1, 7)), request_id='v')
        router.step()
        clock.advance(1.0)          # a full virtual second passes...
        victim = router._ledger['v']['replica']
        _member(router, victim).kill()
        results = _settle(router, clock)
    finally:
        router.close()
    assert results['v'].status == 'completed'
    tl = reconstruct(router.pool.logs())['v']
    assert tl.complete and tl.recoveries == 1
    # ...so the delivered TTFT must carry it. A re-dispatch anchor
    # would report ~0.1s here.
    assert tl.ttft is not None and tl.ttft >= 1.0, tl.ttft


def test_recovery_budget_spent_is_a_typed_terminal(tmp_path, devices):
    """``max_recoveries=0``: the in-flight stream on the dead replica
    terminates as a typed REPLICA_LOST reject — accounted in
    ``results``, complete in the timeline, never silently dropped."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, max_recoveries=0)
    try:
        for rid, p in _prompts(2).items():
            router.submit(p, request_id=rid)
        router.step()
        victims = [rid for rid, e in router._ledger.items()
                   if e['replica'] == 'r1']
        assert victims
        _member(router, 'r1').kill()
        results = _settle(router, clock)
    finally:
        router.close()
    for rid in victims:
        rr = results[rid]
        assert rr.status == 'rejected'
        assert rr.reason is RejectReason.REPLICA_LOST
    counters = router.registry.snapshot()['counters']
    assert counters[
        'router.rejected.replica_lost{tenant=default}'] == len(victims)
    revs = _events(router)
    assert {r['request_id'] for r in revs
            if r['event'] == 'request.recovered'
            and not r['requeued']} == set(victims)
    tls = reconstruct(router.pool.logs())
    for rid in victims:
        assert tls[rid].complete, tls[rid].errors
        assert tls[rid].status == 'rejected'
        assert tls[rid].reason == 'replica_lost'


def test_no_survivor_is_a_typed_terminal(tmp_path, devices):
    """The LAST replica dying has nowhere to recover to — same typed
    terminal, regardless of the recovery budget."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, replicas=1)
    try:
        router.submit(list(range(1, 7)), request_id='solo')
        router.step()
        _member(router, 'r0').kill()
        results = _settle(router, clock)
    finally:
        router.close()
    assert results['solo'].status == 'rejected'
    assert results['solo'].reason is RejectReason.REPLICA_LOST
    assert router.pool.replicas == []


# -- the other two chaos seams ------------------------------------------

def test_handoff_crash_falls_back_to_a_survivor(tmp_path, devices):
    """A replica dying DURING the prefill->decode handoff (pages
    adopted, stream never admitted): the router declares the loss
    inline and re-places the request on a survivor in the same
    submit — the caller never sees the crash."""
    chaos = ChaosInjector(ChaosPlan(crash_in_handoff='r0'))
    clock = VirtualClock()
    router = _serving(tmp_path, clock, chaos=chaos, threshold=4)
    prompt = list((np.arange(18) * 3 + 1) % 31 + 1)
    try:
        router.submit(prompt, request_id='h')
        results = _settle(router, clock)
    finally:
        router.close()
    assert results['h'].status == 'completed'
    assert [r.name for r in router.pool.replicas] == ['r1']
    revs = _events(router)
    lost = [r for r in revs if r['event'] == 'replica.lost']
    assert len(lost) == 1 and lost[0]['target'] == 'r0'
    assert lost[0]['reason'] == 'handoff_crash'
    assert any(r['event'] == 'fault.inject'
               and r['kind'] == 'handoff_crash' for r in revs)
    assert reconstruct(router.pool.logs())['h'].complete


def test_probe_blackhole_declares_loss(tmp_path, devices):
    """A replica that stops ANSWERING (process alive, network dead)
    is indistinguishable from a dead one at the router — the probe
    timeout path declares it lost and recovery proceeds."""
    chaos = ChaosInjector(ChaosPlan(probe_blackhole='r1'))
    clock = VirtualClock()
    router = _serving(tmp_path, clock, chaos=chaos)
    try:
        for rid, p in _prompts(4).items():
            router.submit(p, request_id=rid)
        results = _settle(router, clock)
    finally:
        router.close()
    assert all(r.status == 'completed' for r in results.values())
    revs = _events(router)
    lost = [r for r in revs if r['event'] == 'replica.lost']
    assert len(lost) == 1 and lost[0]['target'] == 'r1'
    assert lost[0]['reason'] == 'probe_timeout'
    assert any(r['event'] == 'fault.inject'
               and r['kind'] == 'probe_blackhole' for r in revs)


def test_rejoin_after_loss_restores_capacity(tmp_path, devices):
    """``rejoin_replica`` after a loss: a FRESH member (never a name
    reuse) joins, the rejoin is audited, and it serves."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock)
    try:
        router.mark_lost('r1', reason='crash')
        fresh = router.rejoin_replica()
        assert fresh.name not in ('r0', 'r1')
        assert len(router.pool.replicas) == 2
        for rid, p in _prompts(4).items():
            router.submit(p, request_id=rid)
        results = _settle(router, clock)
    finally:
        router.close()
    assert all(r.status == 'completed' for r in results.values())
    rejoins = [r for r in _events(router)
               if r['event'] == 'replica.rejoin']
    assert len(rejoins) == 1
    assert rejoins[0]['target'] == fresh.name
    assert rejoins[0]['replicas'] == 2
    counters = router.registry.snapshot()['counters']
    assert any(k.startswith('router.routed{replica=' + fresh.name)
               for k in counters)


# -- seeded chaos replays bit-identically -------------------------------

def test_chaos_schedule_replays_bit_identically(tmp_path, devices):
    """The same seeded trace + the same ChaosPlan replay the crash at
    the same virtual instant: two independent runs produce identical
    results, tick counts, and recovery sets."""
    cfg = LoadGenConfig(seed=7, rate=400.0, requests=12, vocab=32,
                        tenants=default_tenants(2), tick_seconds=0.01)
    trace_path = tmp_path / 'trace.json'
    save_trace(trace_path, generate_trace(cfg))

    def run(tag):
        chaos = ChaosInjector(ChaosPlan(replica_crash=('r1', 8)))
        clock = VirtualClock()
        router = _serving(tmp_path / tag, clock, chaos=chaos,
                          max_new=24)
        sched = ChaosSchedule(chaos, router)
        try:
            res = run_trace(router, load_trace(trace_path), clock,
                            tick_seconds=cfg.tick_seconds,
                            on_tick=sched)
        finally:
            router.close()
        recovered = sorted(
            r['request_id'] for r in _events(router)
            if r['event'] == 'request.recovered' and r['requeued'])
        return res, sched, recovered

    res_a, sched_a, rec_a = run('a')
    res_b, sched_b, rec_b = run('b')
    assert sched_a.killed == sched_b.killed == ['r1']
    assert res_a.accounted and res_b.accounted
    assert rec_a == rec_b and rec_a, 'crash missed the busy window'
    assert res_a.ticks == res_b.ticks
    assert ({rid: (rr.status, tuple(rr.tokens))
             for rid, rr in res_a.results.items()}
            == {rid: (rr.status, tuple(rr.tokens))
                for rid, rr in res_b.results.items()})


# -- audit surfaces: flight, doctor, events, timeline -------------------

def test_replica_loss_auto_dumps_flight_bundle(tmp_path, devices):
    """A replica loss is a postmortem moment: the ROUTER dumps the
    armed flight recorder with trigger ``replica_lost`` — no operator
    in the loop."""
    with obs_flight.recording(base_dir=tmp_path / 'flight',
                              registry=MetricsRegistry()) as rec:
        clock = VirtualClock()
        router = _serving(tmp_path, clock)
        try:
            for rid, p in _prompts(2).items():
                router.submit(p, request_id=rid)
            router.step()
            _member(router, 'r1').kill()
            _settle(router, clock)
        finally:
            router.close()
        dumps = [d for d in rec.dumps if d['trigger'] == 'replica_lost']
    assert len(dumps) == 1
    bundle = obs_flight.load_bundle(dumps[0]['path'])
    assert any(r.get('event') == 'replica.lost'
               for r in bundle.get('events', []))


def test_doctor_classifies_replica_loss_naming_the_dead(tmp_path):
    """The ``replica_loss`` incident class wins on loss evidence and
    the verdict names the DEAD replica — even when the bundle itself
    came from the router."""
    reg = MetricsRegistry()
    with obs_flight.recording(base_dir=tmp_path / 'flight',
                              registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        log.emit('fault.inject', kind='replica_crash', target='r1',
                 tick=40)
        log.emit('replica.probe', target='r1', state='missed',
                 misses=3)
        log.emit('replica.lost', target='r1', reason='probe_timeout',
                 in_flight=2)
        log.emit('request.recovered', request_id='a',
                 from_replica='r1', requeued=True)
        log.emit('request.recovered', request_id='b',
                 from_replica='r1', requeued=False)
        log.emit('serve.reject', request_id='b',
                 reason='replica_lost', tenant='t0', queued=True)
        log.close()
        path = rec.dump_bundle(trigger='replica_lost')
    incident = obs_doctor.diagnose(obs_flight.load_bundle(path))
    assert incident.primary == 'replica_loss'
    assert incident.replica == 'r1'
    out = obs_doctor.render_incident(incident)
    assert 'replica_loss' in out and 'r1' in out


def test_new_event_schemas_are_enforced(tmp_path):
    """The four failure-domain events validate like every other
    schema-2 event: all required fields or an immediate raise."""
    log = EventLog(tmp_path / 'ev.jsonl')
    log.emit('replica.lost', target='r1', reason='crash', in_flight=0)
    log.emit('replica.probe', target='r1', state='missed', misses=1)
    log.emit('replica.rejoin', target='r2', replicas=2)
    log.emit('request.recovered', request_id='a', from_replica='r1',
             requeued=True)
    for ev, kw in [
        ('replica.lost', {'target': 'r1', 'reason': 'crash'}),
        ('replica.probe', {'target': 'r1'}),
        ('replica.rejoin', {}),
        ('request.recovered', {'request_id': 'a', 'requeued': True}),
    ]:
        with pytest.raises(ValueError):
            log.emit(ev, **kw)
    log.close()
    assert len(list(obs.read_events(log.path))) == 4


def test_timeline_recovery_arcs():
    """The lifecycle automaton's two recovery arcs: recovered →
    re-admit → complete (requeued) and recovered → typed reject
    (terminal). Both CLOSE the arc; the delivered latency restarts."""
    def tl_of(recs):
        for i, r in enumerate(recs):
            r.setdefault('seq', i)
            r.setdefault('ts', float(i))
            r.setdefault('schema', 2)
        return reconstruct(recs)

    tls = tl_of([
        {'event': 'serve.admit', 'request_id': 'a', 'slot': 0,
         'queue_wait': 0.1},
        {'event': 'serve.decode', 'request_id': 'a', 'slot': 0,
         'token_index': 0, 'ttft': 0.5},
        {'event': 'request.recovered', 'request_id': 'a',
         'from_replica': 'r1', 'requeued': True},
        {'event': 'serve.admit', 'request_id': 'a', 'slot': 1,
         'queue_wait': 0.2},
        {'event': 'serve.decode', 'request_id': 'a', 'slot': 1,
         'token_index': 0, 'ttft': 2.1},
        {'event': 'serve.retire', 'request_id': 'a',
         'status': 'completed', 'total_seconds': 2.5},
    ])
    tl = tls['a']
    assert tl.complete, tl.errors
    assert tl.recoveries == 1 and tl.admits == 2
    # The crashed attempt's stream died with the replica: the
    # DELIVERED latency is the survivor's (still original-anchored).
    assert tl.ttft == 2.1

    tls = tl_of([
        {'event': 'serve.admit', 'request_id': 'b', 'slot': 0,
         'queue_wait': 0.0},
        {'event': 'request.recovered', 'request_id': 'b',
         'from_replica': 'r1', 'requeued': False},
        {'event': 'serve.reject', 'request_id': 'b',
         'reason': 'replica_lost', 'tenant': 't0', 'queued': True},
    ])
    tl = tls['b']
    assert tl.complete, tl.errors
    assert tl.status == 'rejected' and tl.reason == 'replica_lost'
    assert tl.recoveries == 1


def test_chaos_plan_from_env():
    plan = chaos_plan_from_env({
        'DDP_TPU_FAULT_REPLICA_CRASH': 'r1:40',
        'DDP_TPU_FAULT_HANDOFF_CRASH': 'r0',
        'DDP_TPU_FAULT_PROBE_BLACKHOLE': 'r2',
    })
    assert plan.replica_crash == ('r1', 40)
    assert plan.crash_in_handoff == 'r0'
    assert plan.probe_blackhole == 'r2'
    assert plan.any()
    assert not chaos_plan_from_env({}).any()
    with pytest.raises(ValueError, match='REPLICA_CRASH'):
        chaos_plan_from_env({'DDP_TPU_FAULT_REPLICA_CRASH': '40'})
