# -*- coding: utf-8 -*-
"""
Policy-layer tests (serve/policy.py) + the satellite surfaces that
ride with it: ramp/step arrival shapes (loadgen), the widened
``Scheduler.load()`` probe, and the ``serve.degrade`` event the
degradation rung now emits (it used to engage silently).
"""

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.serve import (
    KernelEngine, LoadGenConfig, PolicyConfig, Request, Scheduler,
    SchedulingPolicy, ServeConfig, TenantPolicy, TenantSpec,
    VirtualClock, default_tenants, generate_trace, load_trace,
    run_load, save_trace,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

def _req(tenant, rid='r', deadline=None, max_new_tokens=8):
    return Request(prompt=np.array([1, 2], np.int32),
                   max_new_tokens=max_new_tokens, deadline=deadline,
                   id=rid, tenant=tenant)


# -- fair share + priority classes --------------------------------------

def test_select_weighted_fair_share():
    pol = SchedulingPolicy(PolicyConfig(
        tenants={'a': TenantPolicy(weight=1.0),
                 'b': TenantPolicy(weight=1.0)}))
    queue = [_req('a', 'a0'), _req('a', 'a1'), _req('b', 'b0')]
    # a holds 2 slots, b none: b's share (0) wins despite queue order.
    assert pol.select(queue, {'a': 2}) == 2
    # Shares equal -> FIFO.
    assert pol.select(queue, {'a': 1, 'b': 1}) == 0


def test_select_respects_weights_and_priority():
    pol = SchedulingPolicy(PolicyConfig(
        tenants={'heavy': TenantPolicy(weight=4.0),
                 'vip': TenantPolicy(priority=1)}))
    queue = [_req('light', 'l0'), _req('heavy', 'h0')]
    # heavy holds 2 of weight 4 (share 0.5) vs light 1 of weight 1
    # (share 1.0): heavy is still below its entitlement.
    assert pol.select(queue, {'heavy': 2, 'light': 1}) == 1
    # A higher priority class boards first regardless of shares.
    queue = [_req('heavy', 'h0'), _req('vip', 'v0')]
    assert pol.select(queue, {'vip': 3}) == 1


def test_fair_share_admission_in_scheduler():
    """A tenant flooding the queue cannot starve the other: with the
    policy armed, admissions interleave by weighted share instead of
    FIFO order."""
    clock = VirtualClock()
    eng = KernelEngine(slots=2, t_max=64, vocab=32, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng, ServeConfig(queue_limit=16, max_new_tokens=6,
                         watchdog=False, policy=PolicyConfig()),
        clock=clock, registry=MetricsRegistry(),
        fault_injector=False)
    # 6 flooder requests queued ahead of 2 minority ones.
    for i in range(6):
        sched.submit([1, 2, 3], request_id=f'flood-{i}',
                     tenant='flood')
    for i in range(2):
        sched.submit([1, 2, 3], request_id=f'mino-{i}', tenant='mino')
    order = []
    orig = sched._admit_into_free_slots

    def spy():
        before = {s.index: (s.request.id if s.request else None)
                  for s in sched._slots}
        orig()
        for s in sched._slots:
            rid = s.request.id if s.request else None
            if rid is not None and before[s.index] != rid:
                order.append(rid)

    sched._admit_into_free_slots = spy
    sched.run_until_idle()
    sched.close()
    # The FIRST pair admitted must split across tenants (FIFO would
    # admit flood-0, flood-1).
    assert {order[0], order[1]} == {'flood-0', 'mino-0'}, order
    assert len(order) == 8
    assert all(r.status == 'completed'
               for r in sched.results.values())


# -- deadline-aware eviction --------------------------------------------

def test_eviction_victim_picks_the_doomed_stream():
    pol = SchedulingPolicy(PolicyConfig())

    class Slot:
        def __init__(self, index):
            self.index = index

    s0, s1 = Slot(0), Slot(1)
    # s0: 10 tokens to go, deadline in 0.05s, gap 0.01 -> misses by
    # 0.05s. s1: 2 to go, deadline in 0.05s -> finishes in time.
    doomed = pol.eviction_victim(
        [(s0, _req('a', deadline=0.05, max_new_tokens=10), 0),
         (s1, _req('a', deadline=0.05, max_new_tokens=2), 0)],
        now=0.0, gap_estimate=0.01)
    assert doomed is s0
    # Nobody doomed -> None (caller falls back to longest-idle).
    assert pol.eviction_victim(
        [(s1, _req('a', deadline=10.0, max_new_tokens=2), 0)],
        now=0.0, gap_estimate=0.01) is None
    # No pace signal yet -> refuse to guess.
    assert pol.eviction_victim(
        [(s0, _req('a', deadline=0.0, max_new_tokens=10), 0)],
        now=0.0, gap_estimate=float('nan')) is None


def test_scheduler_evicts_doomed_not_longest_idle(devices):
    """Under queue-full pressure the ladder evicts the stream that
    will miss its deadline anyway, not the longest-idle one."""
    clock = VirtualClock()
    eng = KernelEngine(slots=2, t_max=64, vocab=32, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng, ServeConfig(queue_limit=1, max_new_tokens=32,
                         watchdog=False, policy=PolicyConfig()),
        clock=clock, registry=MetricsRegistry(),
        fault_injector=False)
    # Two running streams: `doomed` has a huge remaining budget and a
    # deadline it cannot meet at the measured pace; `fine` has slack.
    # (queue_limit=1: admit each into its slot before the next submit.)
    sched.submit([1, 2], request_id='doomed', max_new_tokens=30,
                 deadline=clock() + 0.05)
    sched.step()
    sched.submit([1, 2], request_id='fine', max_new_tokens=30,
                 deadline=clock() + 100.0)
    sched.step()
    # Ticks to measure inter-token gaps (both streams decoding).
    for _ in range(4):
        sched.step()
        clock.advance(0.01)
    # Fill the queue, then one more submit forces the evict rung.
    sched.submit([1, 2], request_id='q0')
    sched.submit([1, 2], request_id='next')
    assert sched.results['doomed'].status == 'evicted'
    assert 'fine' not in sched.results
    sched.run_until_idle()
    sched.close()
    assert sched.results['fine'].status in ('completed',
                                            'deadline_expired')


# -- prefill/decode interleave tuning -----------------------------------

def test_prefill_chunks_scales_with_ttft_overrun():
    pol = SchedulingPolicy(PolicyConfig(target_ttft=0.1,
                                        max_prefill_boost=4))
    assert pol.prefill_chunks(float('nan')) == 1     # no signal yet
    assert pol.prefill_chunks(0.05) == 1             # in SLO
    assert pol.prefill_chunks(0.16) == 3             # ~60% over
    assert pol.prefill_chunks(0.3) == 4              # saturated
    # Disabled without a target.
    assert SchedulingPolicy(PolicyConfig()).prefill_chunks(9.0) == 1


def test_prefill_boost_shortens_ttft(devices):
    """With the boost armed and TTFT already hot, a long prompt
    prefills several chunks per tick — fewer ticks to first token."""
    def run(policy):
        clock = VirtualClock()
        eng = KernelEngine(slots=1, t_max=64, vocab=32, heads=2,
                           head_dim=4, prefill_chunk=4, seed=5,
                           decode_impl='xla')
        sched = Scheduler(
            eng, ServeConfig(queue_limit=4, max_new_tokens=4,
                             watchdog=False, policy=policy),
            clock=clock, registry=MetricsRegistry(),
            fault_injector=False)
        # Seed the TTFT histogram hot (as a regressing serve would).
        sched._h_ttft.observe(1.0)
        sched.submit(list(range(1, 25)), request_id='long')
        ticks = 0
        while sched.results.get('long') is None:
            sched.step()
            clock.advance(0.01)
            ticks += 1
        sched.close()
        return ticks

    plain = run(None)
    boosted = run(PolicyConfig(target_ttft=0.1, max_prefill_boost=4))
    assert boosted < plain, (boosted, plain)


# -- ramp/step arrival shapes (loadgen satellite) -----------------------

def test_ramp_trace_accelerates_and_round_trips(tmp_path):
    cfg = LoadGenConfig(seed=3, rate=100.0, requests=60,
                        arrival='ramp', ramp_factor=8.0)
    trace = generate_trace(cfg)
    again = generate_trace(cfg)                 # seeded
    assert [a.at for a in trace] == [a.at for a in again]
    gaps = [b.at - a.at for a, b in zip(trace, trace[1:])]
    third = len(gaps) // 3
    early = sum(gaps[:third]) / third
    late = sum(gaps[-third:]) / third
    # The rate climbs ~8x: late inter-arrival gaps are far tighter.
    assert late < early / 3, (early, late)
    # Round-trips byte-exactly through the trace serialization.
    path = tmp_path / 'ramp.json'
    save_trace(path, trace)
    loaded = load_trace(path)
    assert [a.at for a in loaded] == [a.at for a in trace]
    assert all((a.prompt == b.prompt).all()
               for a, b in zip(trace, loaded))


def test_step_trace_jumps_at_the_step(tmp_path):
    cfg = LoadGenConfig(seed=3, rate=100.0, requests=80,
                        arrival='step', ramp_factor=10.0, step_at=0.5)
    trace = generate_trace(cfg)
    gaps = [b.at - a.at for a, b in zip(trace, trace[1:])]
    pre = gaps[:38]
    post = gaps[41:]
    assert sum(post) / len(post) < sum(pre) / len(pre) / 3
    save_trace(tmp_path / 't.json', trace)
    assert ([a.at for a in load_trace(tmp_path / 't.json')]
            == [a.at for a in trace])


def test_ramp_step_validation():
    with pytest.raises(ValueError, match='arrival'):
        generate_trace(LoadGenConfig(arrival='sawtooth'))
    with pytest.raises(ValueError, match='ramp_factor'):
        generate_trace(LoadGenConfig(arrival='ramp', ramp_factor=0.0))
    with pytest.raises(ValueError, match='step_at'):
        generate_trace(LoadGenConfig(arrival='step', step_at=1.5))


# -- widened load() probe (router/controller satellite) -----------------

def test_load_probe_reports_tenant_backlog_and_urgency(devices):
    clock = VirtualClock()
    eng = KernelEngine(slots=1, t_max=64, vocab=32, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng, ServeConfig(queue_limit=8, max_new_tokens=4,
                         watchdog=False),
        clock=clock, registry=MetricsRegistry(), fault_injector=False)
    sched.submit([1, 2], tenant='a')                 # takes the slot
    sched.step()
    sched.submit([1, 2], tenant='a', deadline=clock() + 9.0)
    sched.submit([1, 2], tenant='b', deadline=clock() + 5.0)
    sched.submit([1, 2], tenant='b')
    load = sched.load()
    assert load['queued_by_tenant'] == {'a': 1, 'b': 2}
    assert load['oldest_deadline'] == pytest.approx(clock() + 5.0)
    sched.run_until_idle()
    sched.close()
    assert sched.load()['queued_by_tenant'] == {}
    assert sched.load()['oldest_deadline'] is None


# -- serve.degrade event (bugfix satellite) -----------------------------

def test_degrade_emits_event_and_timeline_stays_complete(tmp_path,
                                                         devices):
    clock = VirtualClock()
    log = obs.EventLog(tmp_path / 'degrade.jsonl', clock=clock)
    cfg = LoadGenConfig(seed=3, rate=5000.0, requests=24,
                        tenants=default_tenants(2), vocab=32)
    res = run_load(
        cfg,
        engine=KernelEngine(slots=2, t_max=64, vocab=32, heads=2,
                            head_dim=4, prefill_chunk=4, seed=5,
                            decode_impl='xla'),
        serve_config=ServeConfig(queue_limit=8, max_new_tokens=24,
                                 degrade_watermark=0.5,
                                 watchdog=False),
        registry=MetricsRegistry(), event_log=log, clock=clock)
    log.close()
    assert res.accounted
    records, errors = obs.validate_file(log.path)
    assert errors == [], errors
    degrades = [r for r in records if r['event'] == 'serve.degrade']
    assert degrades, 'overload never tripped the degrade rung'
    for rec in degrades:
        assert rec['watermark'] == 0.5
        assert rec['reason'] == 'queue'
        assert rec['tenant'] in ('t0', 't1')
    # The automaton treats the rung as state-exempt: every lifecycle
    # still reconstructs, and the degraded ones carry the count.
    tls = obs.reconstruct(records)
    assert all(tl.complete for tl in tls.values()), [
        (rid, tl.errors) for rid, tl in tls.items() if not tl.complete]
    assert sum(tl.degrades for tl in tls.values()) == len(degrades)


def test_policy_config_validation():
    with pytest.raises(ValueError, match='weight'):
        SchedulingPolicy(PolicyConfig(
            tenants={'a': TenantPolicy(weight=0.0)}))
    with pytest.raises(ValueError, match='max_prefill_boost'):
        SchedulingPolicy(PolicyConfig(max_prefill_boost=0))
    with pytest.raises(ValueError, match='gap_percentile'):
        SchedulingPolicy(PolicyConfig(gap_percentile=0))
