# -*- coding: utf-8 -*-
"""
Round-4 module surface: GQA (``num_kv_heads``) end-to-end on every
softmax path, RoPE integration, and ring-path feature parity
(dropout / ALiBi / native segments — the knobs that used to raise for
``softmax_impl='online'``).

Oracle strategy follows the reference's ``distributed=False`` pattern
(reference test_gradient.py:45-47) plus a repeated-kv-head oracle for
GQA: a module with ``num_kv_heads=None`` whose queries/values kernels
are the GQA module's kernels tiled per group must produce bitwise the
same forward (the grouped kernels read each kv head once per group
member — identical math, different layout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.models.ring_attention import zigzag_indices
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.train import make_train_step

WORLD, LEN, DIM, HEADS, KV_HEADS = 4, 16, 32, 4, 2
T = WORLD * LEN
GROUP = HEADS // KV_HEADS

pytestmark = pytest.mark.slow

IMPLS = ['full', 'online', 'flash', 'ulysses']


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _inputs(key=0, t=T):
    kk, kq, kv = jax.random.split(jax.random.key(key), 3)
    return (jax.random.normal(kk, (2, t, DIM)),
            jax.random.normal(kq, (2, t, DIM)),
            jax.random.normal(kv, (2, t, DIM)))


def _model(**kw):
    kw.setdefault('num_heads', HEADS)
    return DistributedDotProductAttn(key_dim=DIM, **kw)


def _segments(t=T):
    # Three ragged segments, same for both batch rows.
    ids = np.zeros((2, t), np.int32)
    ids[:, t // 3:] = 1
    ids[:, 2 * t // 3 + 3:] = 2
    return jnp.asarray(ids)


def _tile_gqa_params(params):
    """Repeated-kv-head oracle params: tile each kv head's queries/values
    kernel columns for every member of its group."""
    def tile(kernel):
        d_in, d_out = kernel.shape
        dh = d_out // KV_HEADS
        k = kernel.reshape(d_in, KV_HEADS, dh)
        return jnp.repeat(k, GROUP, axis=1).reshape(d_in, KV_HEADS * GROUP
                                                    * dh)
    p = jax.tree.map(lambda x: x, params)  # copy structure
    for name in ('queries', 'values'):
        p['params'][name]['kernel'] = tile(params['params'][name]['kernel'])
    return p


@pytest.mark.parametrize('impl', IMPLS)
def test_gqa_module_matches_repeated_kv_oracle(mesh, impl):
    kv = KV_HEADS if impl != 'ulysses' else WORLD  # ulysses: Hkv % N == 0
    heads = HEADS if impl != 'ulysses' else 2 * WORLD
    m = _model(num_heads=heads, num_kv_heads=kv, causal=True,
               softmax_impl=impl)
    k, q, v = _inputs()
    params = m.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    out = apply_seq_parallel(m, params, mesh, k, q, v)

    group = heads // kv

    def tile(kernel):
        d_in, d_out = kernel.shape
        dh = d_out // kv
        kk = kernel.reshape(d_in, kv, dh)
        return jnp.repeat(kk, group, axis=1).reshape(d_in, heads * dh)
    full_params = jax.tree.map(lambda x: x, params)
    for name in ('queries', 'values'):
        full_params['params'][name]['kernel'] = tile(
            params['params'][name]['kernel'])
    oracle = _model(num_heads=heads, causal=True, softmax_impl=impl)
    ref = apply_seq_parallel(oracle, full_params, mesh, k, q, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


def test_gqa_module_gradients_are_group_sums(mesh):
    """The full-head oracle's queries/values kernel grads, summed over
    each kv group, must equal the GQA module's grads — the module-level
    version of the kernel's fp32 group-sum contract."""
    m = _model(num_kv_heads=KV_HEADS, causal=True, softmax_impl='flash')
    k, q, v = _inputs(key=1)
    params = m.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    full_params = _tile_gqa_params(params)
    oracle = _model(causal=True, softmax_impl='flash')

    def loss_gqa(p):
        return jnp.sum(apply_seq_parallel(m, p, mesh, k, q, v) ** 2)

    def loss_full(p):
        return jnp.sum(apply_seq_parallel(oracle, p, mesh, k, q, v) ** 2)

    lg, gg = jax.value_and_grad(loss_gqa)(params)
    lf, gf = jax.value_and_grad(loss_full)(full_params)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)

    for name in ('queries', 'values'):
        got = np.asarray(gg['params'][name]['kernel'])
        full = np.asarray(gf['params'][name]['kernel'])
        d_in, d_out = full.shape
        dh = d_out // HEADS
        want = full.reshape(d_in, KV_HEADS, GROUP, dh).sum(axis=2)
        np.testing.assert_allclose(got.reshape(d_in, KV_HEADS, dh), want,
                                   atol=1e-4)
    # keys/composition grads agree outright (same shapes both modules).
    for name in ('keys', 'composition'):
        np.testing.assert_allclose(
            np.asarray(gg['params'][name]['kernel']),
            np.asarray(gf['params'][name]['kernel']), atol=1e-4)


def test_gqa_train_step(mesh):
    m = _model(num_kv_heads=KV_HEADS, causal=True, softmax_impl='flash',
               dtype=jnp.bfloat16)
    k, q, v = _inputs(key=2)
    params = m.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    opt = optax.adam(1e-3)
    step = make_train_step(m, opt, mesh)
    opt_state = opt.init(params)
    batch = (k, q, v, jnp.zeros((2, T, T), bool), jnp.zeros_like(v))
    l0 = None
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, batch)
        l0 = l0 if l0 is not None else float(loss)
    assert np.isfinite(float(loss)) and float(loss) < l0 * 1.001


@pytest.mark.parametrize('impl', IMPLS)
def test_rope_module_sharded_matches_local(mesh, impl):
    m = _model(use_rope=True, causal=True, softmax_impl=impl)
    k, q, v = _inputs(key=3)
    params = m.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    out = apply_seq_parallel(m, params, mesh, k, q, v)
    local = _model(use_rope=True, causal=True, softmax_impl=impl,
                   distributed=False)
    ref = local.apply(params, k, q, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_rope_changes_output_and_base_matters(mesh):
    k, q, v = _inputs(key=4)
    base = _model(softmax_impl='flash', causal=True)
    m1 = _model(softmax_impl='flash', causal=True, use_rope=True)
    m2 = _model(softmax_impl='flash', causal=True, use_rope=True,
                rope_base=500.0)
    params = base.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8],
                       None)
    o0 = apply_seq_parallel(base, params, mesh, k, q, v)
    o1 = apply_seq_parallel(m1, params, mesh, k, q, v)
    o2 = apply_seq_parallel(m2, params, mesh, k, q, v)
    assert not np.allclose(np.asarray(o0), np.asarray(o1))
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_rope_zigzag_ring_matches_local(mesh):
    """RoPE under the zigzag ring layout: feed zigzag-permuted shards,
    invert the permutation on the output, compare against the local
    (contiguous, unsharded) module — exercises the position-vector
    plumbing end-to-end through the module."""
    idx = zigzag_indices(T, WORLD)
    inv = jnp.argsort(idx)
    k, q, v = _inputs(key=5)
    m = _model(use_rope=True, causal=True, softmax_impl='online',
               ring_layout='zigzag')
    params = m.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    out = apply_seq_parallel(m, params, mesh, k[:, idx], q[:, idx],
                             v[:, idx])[:, inv]
    local = _model(use_rope=True, causal=True, softmax_impl='online',
                   distributed=False)
    ref = local.apply(params, k, q, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_dropout_matches_flash_same_seed(mesh):
    """The dropout hash keys on global coordinates, so the ring path must
    draw EXACTLY the flash path's mask for one replicated seed."""
    k, q, v = _inputs(key=6)
    mo = _model(softmax_impl='online', dropout_rate=0.35)
    mf = _model(softmax_impl='flash', dropout_rate=0.35)
    params = mo.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    oo = apply_seq_parallel(mo, params, mesh, k, q, v, dropout_seed=9)
    of = apply_seq_parallel(mf, params, mesh, k, q, v, dropout_seed=9)
    np.testing.assert_allclose(np.asarray(oo), np.asarray(of), atol=2e-5)
    # And it actually drops: deterministic=True differs.
    od = apply_seq_parallel(mo, params, mesh, k, q, v, deterministic=True)
    assert not np.allclose(np.asarray(oo), np.asarray(od))


def test_ring_dropout_gradients(mesh):
    """Ring backward regenerates the forward's keep mask per fold: grads
    must match the flash path's (same seed, same global mask)."""
    k, q, v = _inputs(key=7)
    mo = _model(softmax_impl='online', dropout_rate=0.25)
    mf = _model(softmax_impl='flash', dropout_rate=0.25)
    params = mo.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)

    def loss(m, p):
        out = apply_seq_parallel(m, p, mesh, k, q, v, dropout_seed=11)
        return jnp.sum(out ** 2)

    go = jax.grad(lambda p: loss(mo, p))(params)
    gf = jax.grad(lambda p: loss(mf, p))(params)
    for name in ('keys', 'queries', 'values', 'composition'):
        np.testing.assert_allclose(
            np.asarray(go['params'][name]['kernel']),
            np.asarray(gf['params'][name]['kernel']), atol=5e-4)


def test_ring_alibi_matches_flash(mesh):
    slopes = jnp.asarray([2.0 ** -(i + 1) for i in range(HEADS)])
    k, q, v = _inputs(key=8)
    mo = _model(softmax_impl='online', causal=True, alibi_slopes=slopes)
    mf = _model(softmax_impl='flash', causal=True, alibi_slopes=slopes)
    params = mo.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    oo = apply_seq_parallel(mo, params, mesh, k, q, v)
    of = apply_seq_parallel(mf, params, mesh, k, q, v)
    np.testing.assert_allclose(np.asarray(oo), np.asarray(of), atol=2e-5)


def test_ring_native_segments_match_flash_and_densified(mesh):
    """Segments ride the ring as O(T/N) vectors — outputs must equal both
    the flash path's in-kernel form and the 'full' path's densified
    mask."""
    seg = _segments()
    k, q, v = _inputs(key=9)
    mo = _model(softmax_impl='online')
    mf = _model(softmax_impl='flash')
    md = _model(softmax_impl='full')
    params = mo.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    oo = apply_seq_parallel(mo, params, mesh, k, q, v, segment_ids=seg)
    of = apply_seq_parallel(mf, params, mesh, k, q, v, segment_ids=seg)
    od = apply_seq_parallel(md, params, mesh, k, q, v, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(oo), np.asarray(of), atol=2e-5)
    np.testing.assert_allclose(np.asarray(oo), np.asarray(od), atol=2e-5)


def test_ring_segments_gradients_match_flash(mesh):
    seg = _segments()
    k, q, v = _inputs(key=10)
    mo = _model(softmax_impl='online', causal=True)
    mf = _model(softmax_impl='flash', causal=True)
    params = mo.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)

    def loss(m, p):
        out = apply_seq_parallel(m, p, mesh, k, q, v, segment_ids=seg)
        return jnp.sum(out ** 2)

    go = jax.grad(lambda p: loss(mo, p))(params)
    gf = jax.grad(lambda p: loss(mf, p))(params)
    for name in ('keys', 'queries', 'values', 'composition'):
        np.testing.assert_allclose(
            np.asarray(go['params'][name]['kernel']),
            np.asarray(gf['params'][name]['kernel']), atol=5e-4)


def test_zigzag_ring_with_segments(mesh):
    """Zigzag + packed sequences: ids follow their rows through the
    permutation, so the permuted-shard result must invert back to the
    contiguous local oracle."""
    idx = zigzag_indices(T, WORLD)
    inv = jnp.argsort(idx)
    seg = _segments()
    k, q, v = _inputs(key=11)
    m = _model(softmax_impl='online', causal=True, ring_layout='zigzag')
    params = m.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    out = apply_seq_parallel(m, params, mesh, k[:, idx], q[:, idx],
                             v[:, idx], segment_ids=seg[:, idx])[:, inv]
    local = _model(softmax_impl='online', causal=True, distributed=False)
    ref = local.apply(params, k, q, v, None, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_ring_dropout_positions_hash(mesh):
    """Zigzag + dropout exercises the explicit-positions branch of the
    in-kernel dropout hash (rows/cols come from the position vectors, not
    the offset arithmetic): the permuted-shard result must invert back to
    the contiguous flash path's output for the SAME seed — in forward and
    backward (a row/col broadcast swap in any of the three kernels would
    desynchronize the backward's mask from the forward's)."""
    idx = zigzag_indices(T, WORLD)
    inv = jnp.argsort(idx)
    k, q, v = _inputs(key=14)
    mz = _model(softmax_impl='online', causal=True, ring_layout='zigzag',
                dropout_rate=0.3)
    mf = _model(softmax_impl='flash', causal=True, dropout_rate=0.3)
    params = mz.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)

    def loss_z(p):
        out = apply_seq_parallel(mz, p, mesh, k[:, idx], q[:, idx],
                                 v[:, idx], dropout_seed=17)[:, inv]
        return jnp.sum(out ** 2), out

    def loss_f(p):
        out = apply_seq_parallel(mf, p, mesh, k, q, v, dropout_seed=17)
        return jnp.sum(out ** 2), out

    (_, oz), gz = jax.value_and_grad(loss_z, has_aux=True)(params)
    (_, of), gf = jax.value_and_grad(loss_f, has_aux=True)(params)
    np.testing.assert_allclose(np.asarray(oz), np.asarray(of), atol=2e-5)
    for name in ('keys', 'queries', 'values', 'composition'):
        np.testing.assert_allclose(
            np.asarray(gz['params'][name]['kernel']),
            np.asarray(gf['params'][name]['kernel']), atol=5e-4)


def test_ring_dropout_with_window_and_segments(mesh):
    """The long-context training combo the verdict called out: ring path
    with causal + window + packed sequences + dropout, at ring memory
    cost — must agree with the flash path under one seed."""
    seg = _segments()
    k, q, v = _inputs(key=12)
    kw = dict(causal=True, window=24, dropout_rate=0.2)
    mo = _model(softmax_impl='online', **kw)
    mf = _model(softmax_impl='flash', **kw)
    params = mo.init(jax.random.key(0), k[:, :8], q[:, :8], v[:, :8], None)
    oo = apply_seq_parallel(mo, params, mesh, k, q, v, segment_ids=seg,
                            dropout_seed=13)
    of = apply_seq_parallel(mf, params, mesh, k, q, v, segment_ids=seg,
                            dropout_seed=13)
    np.testing.assert_allclose(np.asarray(oo), np.asarray(of), atol=2e-5)


def test_per_layer_dropout_salt(mesh):
    """Two sibling attention layers given the SAME explicit seed must
    draw different masks (the per-layer salt, advisor round-3 item 1)."""
    import flax.linen as nn

    class Stack(nn.Module):
        @nn.compact
        def __call__(self, k, q, v):
            a = DistributedDotProductAttn(
                key_dim=DIM, num_heads=HEADS, softmax_impl='flash',
                dropout_rate=0.4, distributed=False,
                name='layer_a')(k, q, v, None, dropout_seed=21)
            b = DistributedDotProductAttn(
                key_dim=DIM, num_heads=HEADS, softmax_impl='flash',
                dropout_rate=0.4, distributed=False,
                name='layer_b')(k, q, v, None, dropout_seed=21)
            return a, b

    k, q, v = _inputs(key=13)
    stack = Stack()
    params = stack.init(jax.random.key(0), k, q, v)
    # Give both layers IDENTICAL weights so any output difference can only
    # come from the dropout masks.
    shared = {'params': {'layer_b': params['params']['layer_a'],
                         'layer_a': params['params']['layer_a']}}
    a, b = stack.apply(shared, k, q, v)
    assert not np.allclose(np.asarray(a), np.asarray(b)), \
        'identical layers + identical explicit seed must still decorrelate'
