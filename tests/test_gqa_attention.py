# -*- coding: utf-8 -*-
"""
Grouped-query / multi-query attention (GQA/MQA) tests.

Oracle pattern: repeat each K/V head over its query group
(``jnp.repeat(k, group, axis=-3)``) and run the standard multi-head
kernel — the GQA kernel must match, and the true ``dk``/``dv`` must equal
the per-repeated-head gradients summed over each group. No reference
analog (the reference module shares one head count across K/Q/V,
reference module.py:29-39).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.pallas_attention import (
    flash_attention,
)

B, HQ, HKV, D = 2, 6, 2, 16
GROUP = HQ // HKV

pytestmark = pytest.mark.slow


def _qkv(t, hkv=HKV, key=0):
    k1, k2, k3 = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(k1, (B, HQ, t, D), jnp.float32)
    k = jax.random.normal(k2, (B, hkv, t, D), jnp.float32)
    v = jax.random.normal(k3, (B, hkv, t, D), jnp.float32)
    return q, k, v


def _rep(x, hkv):
    return jnp.repeat(x, HQ // hkv, axis=-3)


@pytest.mark.parametrize('t', [64, 100])
@pytest.mark.parametrize('hkv', [HKV, 1])   # grouped and multi-query
def test_gqa_forward_matches_repeated_kv(t, hkv):
    q, k, v = _qkv(t, hkv)
    out = flash_attention(q, k, v)
    ref = flash_attention(q, _rep(k, hkv), _rep(v, hkv))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_gqa_gradients_are_group_sums(t=100):
    q, k, v = _qkv(t)

    def f(q, k, v):
        return (flash_attention(q, k, v) ** 2).sum()

    def f_rep(q, kr, vr):
        return (flash_attention(q, kr, vr) ** 2).sum()

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    dq_r, dk_r, dv_r = jax.grad(f_rep, argnums=(0, 1, 2))(
        q, _rep(k, HKV), _rep(v, HKV))
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_r),
                               atol=1e-5, rtol=1e-5)
    for got, rep in ((dk, dk_r), (dv, dv_r)):
        want = rep.reshape(B, HKV, GROUP, t, D).sum(2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_gqa_composes_with_mask_causal_segments():
    t = 64
    q, k, v = _qkv(t, key=1)
    mask = jax.random.bernoulli(jax.random.key(5), 0.2, (B, 1, t, t))
    seg = (jnp.arange(t, dtype=jnp.int32) * 3 // t)
    out = flash_attention(q, k, v, mask, causal=True, segment_ids=seg)
    ref = flash_attention(q, _rep(k, HKV), _rep(v, HKV), mask, causal=True,
                          segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_gqa_with_window_banded(monkeypatch):
    """GQA composes with the banded sliding-window grid: the K/V maps
    stack the group division on the band translation."""
    import distributed_dot_product_tpu.ops.pallas_attention as pa

    t, window = 64, 11
    q, k, v = _qkv(t, key=2)
    ref = flash_attention(q, _rep(k, HKV), _rep(v, HKV), causal=True,
                          window=window)
    out_full = flash_attention(q, k, v, causal=True, window=window)
    monkeypatch.setattr(pa, '_BAND_ON_INTERPRET', True)
    out_band = flash_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out_band), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_gqa_bounded_mode():
    t = 64
    q, k, v = _qkv(t, key=3)
    out = flash_attention(q, k, v, softmax_mode='bounded')
    ref = flash_attention(q, _rep(k, HKV), _rep(v, HKV),
                          softmax_mode='bounded')
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_gqa_ring_attention(mesh8):
    """Ring attention with grouped K/V heads on the CPU mesh: rotating
    buffers carry the kv-head shapes; the per-block flash folds handle
    the group."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )

    t = 64
    q, k, v = _qkv(t, key=4)

    def run(q, k, v):
        return ring_attention(q, k, v, causal=True)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh8,
        in_specs=(P(None, None, 'seq', None),) * 3,
        out_specs=P(None, None, 'seq', None), check_vma=False))(q, k, v)
    ref = flash_attention(q, _rep(k, HKV), _rep(v, HKV), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_gqa_ring_gradients(mesh8):
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )

    t = 32
    q, k, v = _qkv(t, key=6)

    def loss_ring(q, k, v):
        fn = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=True),
            mesh=mesh8, in_specs=(P(None, None, 'seq', None),) * 3,
            out_specs=P(None, None, 'seq', None), check_vma=False)
        return (fn(q, k, v) ** 2).sum()

    def loss_rep(q, kr, vr):
        return (flash_attention(q, kr, vr, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    dq_r, dk_r, dv_r = jax.grad(loss_rep, argnums=(0, 1, 2))(
        q, _rep(k, HKV), _rep(v, HKV))
    want = (dq_r, dk_r.reshape(B, HKV, GROUP, t, D).sum(2),
            dv_r.reshape(B, HKV, GROUP, t, D).sum(2))
    for got, exp in zip(g_ring, want):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=1e-4, rtol=1e-4)


def test_gqa_ulysses(mesh8):
    """Ulysses with GQA: q and kv heads ride separate all_to_alls (both
    must divide the mesh width); HQ=8, HKV=... over an 8-wide mesh."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ulysses_attention import (
        ulysses_attention,
    )

    t = 64
    k1, k2, k3 = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(k1, (B, 16, t, D), jnp.float32)
    k = jax.random.normal(k2, (B, 8, t, D), jnp.float32)
    v = jax.random.normal(k3, (B, 8, t, D), jnp.float32)

    out = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v),
        mesh=mesh8, in_specs=(P(None, None, 'seq', None),) * 3,
        out_specs=P(None, None, 'seq', None), check_vma=False))(q, k, v)
    ref = flash_attention(q, jnp.repeat(k, 2, axis=-3),
                          jnp.repeat(v, 2, axis=-3))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_gqa_validation():
    q, k, v = _qkv(16)
    with pytest.raises(ValueError, match='GQA'):
        flash_attention(q, k[:1], v[:1])              # batch-dim mismatch
    bad_k = jnp.zeros((B, 4, 16, D))                  # 6 % 4 != 0
    with pytest.raises(ValueError, match='divisible|GQA'):
        flash_attention(q, bad_k, bad_k)
    with pytest.raises(ValueError, match='agree'):
        flash_attention(q, k, v[:, :1])               # k/v head mismatch


@pytest.fixture(scope='module')
def mesh8():
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    return seq_mesh(8)


def test_gqa_xla_ring_backend_rejected(mesh8):
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )
    q, k, v = _qkv(32, key=8)
    with pytest.raises(ValueError, match="block_impl='flash'"):
        jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, block_impl='xla'),
            mesh=mesh8, in_specs=(P(None, None, 'seq', None),) * 3,
            out_specs=P(None, None, 'seq', None),
            check_vma=False))(q, k, v)
