# -*- coding: utf-8 -*-
"""
Prometheus exporter (obs/exporter.py): exposition-format validity,
label escaping, concurrent rendering against live writer threads (the
scheduler/watchdog shape), and the /metrics + /healthz endpoint.
"""

import json
import re
import threading
import urllib.error
import urllib.request

import pytest

from distributed_dot_product_tpu.obs.exporter import (
    MetricsServer, escape_label_value, render_prometheus,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

# One exposition line: name, optional {labels}, value. Label values are
# quoted strings with \\ \" \n escapes only.
_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*\})?'
    r' (NaN|[+-]?Inf|[-+0-9.eE]+)$')


def _assert_valid_exposition(text):
    for line in text.rstrip('\n').split('\n'):
        if not line:
            continue      # the empty document (no metrics yet)
        if line.startswith('#'):
            assert re.match(r'^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ',
                            line), line
        else:
            assert _LINE.match(line), f'invalid exposition line: {line!r}'


def test_render_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter('serve.admitted').inc(5)
    reg.gauge('serve.queue_depth').set(3)
    h = reg.histogram('serve.step_seconds')
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert 'ddp_serve_admitted_total 5' in text
    assert 'ddp_serve_queue_depth 3' in text
    assert 'ddp_serve_step_seconds{quantile="0.5"} 0.2' in text
    assert 'ddp_serve_step_seconds_count 3' in text
    assert re.search(r'ddp_serve_step_seconds_sum 0\.6\d*', text)
    assert '# TYPE ddp_serve_step_seconds summary' in text


def test_build_info_gauge_always_rendered():
    """Every render carries the constant ddp_build_info gauge —
    schema/jax/python versions as labels, value 1 — even over an empty
    registry (a merged multi-replica scrape detects version skew from
    the scrape alone)."""
    import platform

    import jax

    from distributed_dot_product_tpu.obs import events as obs_events
    text = render_prometheus(MetricsRegistry())
    _assert_valid_exposition(text)
    assert '# TYPE ddp_build_info gauge' in text
    line = next(ln for ln in text.splitlines()
                if ln.startswith('ddp_build_info{'))
    assert line.endswith(' 1')
    assert f'schema_version="{obs_events.SCHEMA_VERSION}"' in line
    assert f'jax_version="{jax.__version__}"' in line
    assert f'python_version="{platform.python_version()}"' in line
    # Present next to real metrics too, exactly once.
    reg = MetricsRegistry()
    reg.counter('serve.admitted').inc()
    text = render_prometheus(reg)
    assert text.count('ddp_build_info{') == 1


def test_label_escaping_round_trip():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    reg = MetricsRegistry()
    reg.counter('serve.rejected',
                labels={'reason': 'queue "full"\nline'}).inc()
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert ('ddp_serve_rejected_total'
            '{reason="queue \\"full\\"\\nline"} 1') in text


def test_histogram_empty_renders_nan_quantiles():
    reg = MetricsRegistry()
    reg.histogram('empty.h')
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert 'ddp_empty_h{quantile="0.5"} NaN' in text
    assert 'ddp_empty_h_count 0' in text


def test_histogram_bucket_family_rendered():
    """Cumulative `_bucket{le=...}` lines under a real histogram family
    NEXT TO the reservoir summary — lifetime counters an external
    Prometheus can sum across replicas."""
    reg = MetricsRegistry()
    h = reg.histogram('serve.ttft_seconds', buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert '# TYPE ddp_serve_ttft_seconds summary' in text
    assert '# TYPE ddp_serve_ttft_seconds_hist histogram' in text
    assert 'ddp_serve_ttft_seconds_hist_bucket{le="0.01"} 1' in text
    assert 'ddp_serve_ttft_seconds_hist_bucket{le="0.1"} 2' in text
    assert 'ddp_serve_ttft_seconds_hist_bucket{le="1"} 3' in text
    assert 'ddp_serve_ttft_seconds_hist_bucket{le="+Inf"} 4' in text
    assert 'ddp_serve_ttft_seconds_hist_count 4' in text
    assert re.search(r'ddp_serve_ttft_seconds_hist_sum 5\.5\d*', text)


def test_labeled_bucket_families_stay_contiguous():
    """Labeled histograms must not interleave the summary and _hist
    families per label set — strict exposition parsers require all
    lines of one family in a single group."""
    reg = MetricsRegistry()
    for tenant in ('a', 'b'):
        h = reg.histogram('serve.ttft_seconds', buckets=(0.1,),
                          labels={'tenant': tenant})
        h.observe(0.05)
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    # Every _hist line comes after every summary line of the family.
    last_summary = max(i for i, ln in enumerate(text.splitlines())
                       if ln.startswith('ddp_serve_ttft_seconds')
                       and '_hist' not in ln)
    first_hist = min(i for i, ln in enumerate(text.splitlines())
                     if '_hist' in ln)
    assert last_summary < first_hist
    # And each family's own lines form one contiguous block.
    kinds = [('hist' if '_hist' in ln else 'summary')
             for ln in text.splitlines()
             if ln.startswith(('ddp_serve_ttft_seconds', '# '))]
    joined = ''.join('h' if k == 'hist' else 's' for k in kinds)
    assert 'hs' not in joined, joined


def test_bucket_counts_are_lifetime_not_reservoir():
    """Bucket counters never age out: a tiny reservoir drops old
    observations from the quantiles, but the cumulative buckets keep
    counting — the property cross-replica aggregation needs."""
    from distributed_dot_product_tpu.utils.tracing import Histogram
    h = Histogram(maxlen=2, buckets=(1.0,))
    for _ in range(10):
        h.observe(0.5)
    s = h.summary()
    assert s['count'] == 2               # reservoir window
    assert s['total_count'] == 10
    assert s['buckets'] == [[1.0, 10]]   # lifetime cumulative
    assert h.buckets() == [(1.0, 10)]


def test_buckets_disabled_and_default_bounds():
    from distributed_dot_product_tpu.utils.tracing import (
        DEFAULT_BUCKETS, Histogram,
    )
    reg = MetricsRegistry()
    assert reg.histogram('h.default').bucket_bounds \
        == tuple(sorted(DEFAULT_BUCKETS))
    h = Histogram(buckets=())
    h.observe(0.1)
    assert 'buckets' not in h.summary()
    reg.histogram('h.off', buckets=()).observe(0.1)
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert 'ddp_h_off_hist' not in text
    assert 'ddp_h_default_hist_bucket' in text


def test_concurrent_export_no_torn_reads():
    """Writer threads (counters + histograms, the scheduler/watchdog
    write pattern) hammer the registry while a reader renders: every
    render is valid exposition text, counter values are monotonic
    across renders, and the final render shows the exact totals."""
    reg = MetricsRegistry()
    n_writers, n_incs = 4, 300
    stop = threading.Event()
    errors = []

    def writer(i):
        c = reg.counter('unit.work')
        labeled = reg.counter('unit.by_thread', labels={'t': str(i)})
        h = reg.histogram('unit.latency')
        for k in range(n_incs):
            c.inc()
            labeled.inc()
            h.observe(k * 1e-4)

    def reader():
        last = 0
        while not stop.is_set():
            text = render_prometheus(reg)
            try:
                _assert_valid_exposition(text)
            except AssertionError as e:
                errors.append(e)
                return
            m = re.search(r'^ddp_unit_work_total (\d+)$', text,
                          re.MULTILINE)
            if m:
                value = int(m.group(1))
                if value < last:
                    errors.append(
                        AssertionError(f'counter went backwards: '
                                       f'{value} < {last}'))
                    return
                last = value

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_writers)]
    rt = threading.Thread(target=reader)
    rt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join()
    assert not errors, errors[0]
    final = render_prometheus(reg)
    assert f'ddp_unit_work_total {n_writers * n_incs}' in final
    for i in range(n_writers):
        assert f'ddp_unit_by_thread_total{{t="{i}"}} {n_incs}' in final
    assert (f'ddp_unit_latency_count {n_writers * n_incs}'
            in final)


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_metrics_and_healthz_endpoints():
    from distributed_dot_product_tpu.serve.health import (
        HealthMonitor, Readiness,
    )
    reg = MetricsRegistry()
    reg.counter('serve.admitted').inc(2)
    mon = HealthMonitor(stall_timeout=5.0, registry=reg)
    with MetricsServer(reg, health=mon) as srv:
        # STARTING: not yet safe for traffic.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/healthz')
        assert exc.value.code == 503
        mon.beat()
        mon.set_readiness(Readiness.READY, 'serving')
        code, body = _get(srv.url + '/healthz')
        assert code == 200
        snap = json.loads(body)
        assert snap['readiness'] == 'ready'
        assert snap['metrics']['counters']['serve.admitted'] == 2
        code, text = _get(srv.url + '/metrics')
        assert code == 200
        _assert_valid_exposition(text)
        assert 'ddp_serve_admitted_total 2' in text
        # DEGRADED still serves traffic.
        mon.set_readiness(Readiness.DEGRADED, 'pressure')
        code, _ = _get(srv.url + '/healthz')
        assert code == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/nope')
        assert exc.value.code == 404


def test_server_without_health_monitor_is_ok():
    reg = MetricsRegistry()
    with MetricsServer(reg) as srv:
        code, body = _get(srv.url + '/healthz')
        assert code == 200 and json.loads(body)['health'] is None
