# -*- coding: utf-8 -*-
"""
Prometheus exporter (obs/exporter.py): exposition-format validity,
label escaping, concurrent rendering against live writer threads (the
scheduler/watchdog shape), and the /metrics + /healthz endpoint.
"""

import json
import re
import threading
import urllib.error
import urllib.request

import pytest

from distributed_dot_product_tpu.obs.exporter import (
    MetricsServer, escape_label_value, render_prometheus,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

# One exposition line: name, optional {labels}, value. Label values are
# quoted strings with \\ \" \n escapes only.
_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*\})?'
    r' (NaN|[+-]?Inf|[-+0-9.eE]+)$')


def _assert_valid_exposition(text):
    for line in text.rstrip('\n').split('\n'):
        if not line:
            continue      # the empty document (no metrics yet)
        if line.startswith('#'):
            assert re.match(r'^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ',
                            line), line
        else:
            assert _LINE.match(line), f'invalid exposition line: {line!r}'


def test_render_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter('serve.admitted').inc(5)
    reg.gauge('serve.queue_depth').set(3)
    h = reg.histogram('serve.step_seconds')
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert 'ddp_serve_admitted_total 5' in text
    assert 'ddp_serve_queue_depth 3' in text
    assert 'ddp_serve_step_seconds{quantile="0.5"} 0.2' in text
    assert 'ddp_serve_step_seconds_count 3' in text
    assert re.search(r'ddp_serve_step_seconds_sum 0\.6\d*', text)
    assert '# TYPE ddp_serve_step_seconds summary' in text


def test_label_escaping_round_trip():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    reg = MetricsRegistry()
    reg.counter('serve.rejected',
                labels={'reason': 'queue "full"\nline'}).inc()
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert ('ddp_serve_rejected_total'
            '{reason="queue \\"full\\"\\nline"} 1') in text


def test_histogram_empty_renders_nan_quantiles():
    reg = MetricsRegistry()
    reg.histogram('empty.h')
    text = render_prometheus(reg)
    _assert_valid_exposition(text)
    assert 'ddp_empty_h{quantile="0.5"} NaN' in text
    assert 'ddp_empty_h_count 0' in text


def test_concurrent_export_no_torn_reads():
    """Writer threads (counters + histograms, the scheduler/watchdog
    write pattern) hammer the registry while a reader renders: every
    render is valid exposition text, counter values are monotonic
    across renders, and the final render shows the exact totals."""
    reg = MetricsRegistry()
    n_writers, n_incs = 4, 300
    stop = threading.Event()
    errors = []

    def writer(i):
        c = reg.counter('unit.work')
        labeled = reg.counter('unit.by_thread', labels={'t': str(i)})
        h = reg.histogram('unit.latency')
        for k in range(n_incs):
            c.inc()
            labeled.inc()
            h.observe(k * 1e-4)

    def reader():
        last = 0
        while not stop.is_set():
            text = render_prometheus(reg)
            try:
                _assert_valid_exposition(text)
            except AssertionError as e:
                errors.append(e)
                return
            m = re.search(r'^ddp_unit_work_total (\d+)$', text,
                          re.MULTILINE)
            if m:
                value = int(m.group(1))
                if value < last:
                    errors.append(
                        AssertionError(f'counter went backwards: '
                                       f'{value} < {last}'))
                    return
                last = value

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_writers)]
    rt = threading.Thread(target=reader)
    rt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join()
    assert not errors, errors[0]
    final = render_prometheus(reg)
    assert f'ddp_unit_work_total {n_writers * n_incs}' in final
    for i in range(n_writers):
        assert f'ddp_unit_by_thread_total{{t="{i}"}} {n_incs}' in final
    assert (f'ddp_unit_latency_count {n_writers * n_incs}'
            in final)


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_metrics_and_healthz_endpoints():
    from distributed_dot_product_tpu.serve.health import (
        HealthMonitor, Readiness,
    )
    reg = MetricsRegistry()
    reg.counter('serve.admitted').inc(2)
    mon = HealthMonitor(stall_timeout=5.0, registry=reg)
    with MetricsServer(reg, health=mon) as srv:
        # STARTING: not yet safe for traffic.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/healthz')
        assert exc.value.code == 503
        mon.beat()
        mon.set_readiness(Readiness.READY, 'serving')
        code, body = _get(srv.url + '/healthz')
        assert code == 200
        snap = json.loads(body)
        assert snap['readiness'] == 'ready'
        assert snap['metrics']['counters']['serve.admitted'] == 2
        code, text = _get(srv.url + '/metrics')
        assert code == 200
        _assert_valid_exposition(text)
        assert 'ddp_serve_admitted_total 2' in text
        # DEGRADED still serves traffic.
        mon.set_readiness(Readiness.DEGRADED, 'pressure')
        code, _ = _get(srv.url + '/healthz')
        assert code == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/nope')
        assert exc.value.code == 404


def test_server_without_health_monitor_is_ok():
    reg = MetricsRegistry()
    with MetricsServer(reg) as srv:
        code, body = _get(srv.url + '/healthz')
        assert code == 200 and json.loads(body)['health'] is None
