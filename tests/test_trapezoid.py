# -*- coding: utf-8 -*-
"""
Trapezoid causal pair-grid parity (ops/pallas_attention.py
``_trap_tables``/``_wrap_specs_pairs``): the flattened grid must be
bitwise identical to the full grid with in-kernel skipping, in both
directions, across the feature compositions it claims to support.

The pair grid needs the Mosaic interpreter off-TPU (scalar-prefetch index
maps), so these tests force it via the ``_TRAP_ON_INTERPRET`` hook and
keep shapes tiny. The real-chip speed claim lives in RESULTS.md
(T=131,072 causal train: 68.8 → 81.8 TF/s) and the hardware suite.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_dot_product_tpu.ops.pallas_attention as pa

pytestmark = pytest.mark.slow

B, H, T, D = 1, 2, 64, 16


def _qkvg(key=0):
    ks = jax.random.split(jax.random.key(key), 4)
    return [jax.random.normal(k, (B, H, T, D)) for k in ks]


def _run(trap, monkeypatch, *, seg=None, drop=0.0, hkv=None, off=0,
         alibi=None):
    monkeypatch.setattr(pa, '_TRAP_ON_INTERPRET', trap)
    q, k, v, g = _qkvg()
    if hkv is not None:
        k, v = k[:, :hkv], v[:, :hkv]

    def f(q, k, v):
        return pa.flash_attention(
            q, k, v, causal=True, causal_offset=off, segment_ids=seg,
            alibi_slopes=alibi, dropout_rate=drop,
            dropout_seed=3 if drop else None)

    out, vjp = jax.vjp(f, q, k, v)
    return (out, *vjp(g))


CASES = {
    'plain': {},
    'segments': {'seg': (jnp.arange(T) // 20, jnp.arange(T) // 20)},
    'dropout': {'drop': 0.25},
    'gqa': {'hkv': 1},
    'row_offset': {'off': 32},
    'alibi': {'alibi': jnp.asarray([0.5, 0.25])},
}


@pytest.mark.parametrize('case', sorted(CASES))
def test_trapezoid_matches_full_grid(monkeypatch, case):
    a = _run(True, monkeypatch, **CASES[case])
    b = _run(False, monkeypatch, **CASES[case])
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_trap_tables_cover_exactly_the_triangle():
    """Every causally-relevant (Q block, K block) pair appears exactly
    once, in Q-major order with K ascending from 0 — the ordering the
    kernels' init/finalize conditions assume."""
    for rel, nqb, nkb, bq, bk in [(0, 7, 7, 8, 8), (16, 4, 6, 8, 8),
                                  (-8, 5, 5, 8, 8), (0, 3, 9, 16, 8)]:
        qtab, ktab, ext = (np.asarray(t)
                           for t in pa._trap_tables(rel, nqb, nkb, bq, bk))
        assert len(qtab) == len(ktab) == ext.sum()
        for qi in range(nqb):
            ks = ktab[qtab == qi]
            # contiguous run 0..ext-1; ext covers every K block with any
            # visible column (clamped to >= 1 so the output block writes)
            want = min(nkb, max(1, -(-(rel + (qi + 1) * bq) // bk)))
            assert list(ks) == list(range(want)), (rel, qi, ks)


def test_trap_tables_t_cover_exactly_the_triangle():
    for rel, nqb, nkb, bq, bk in [(0, 7, 7, 8, 8), (16, 4, 6, 8, 8),
                                  (0, 3, 9, 16, 8)]:
        qtab, ktab, qlo = (np.asarray(t) for t in
                           pa._trap_tables_t(rel, nqb, nkb, bq, bk))
        for kj in range(nkb):
            qs = qtab[ktab == kj]
            assert list(qs) == list(range(qlo[kj], nqb)), (rel, kj, qs)
            # first visible Q block: its last row reaches this K block
            lo = qlo[kj]
            if lo not in (0, nqb - 1):
                assert rel + (lo + 1) * bq - 1 >= kj * bk
                assert rel + lo * bq - 1 < kj * bk


def test_trap_eligibility_gates():
    """Traced offsets, windows, masks, positions and 'bounded' must all
    fall back to the full grid (the pair count would be dynamic, or the
    config has its own grid)."""
    assert pa._trap_eligible(True, None, None, None, 0, 0, 'exact', False)
    ok = pa._trap_eligible
    assert not ok(False, None, None, None, 0, 0, 'exact', False)
    assert not ok(True, 8, None, None, 0, 0, 'exact', False)   # window
    assert not ok(True, None, 'm', None, 0, 0, 'exact', False)  # mask
    assert not ok(True, None, None, 'p', 0, 0, 'exact', False)  # positions
    assert not ok(True, None, None, None, jnp.int32(0), 0, 'exact', False)
    assert not ok(True, None, None, None, 0, 0, 'bounded', False)
    assert not ok(True, None, None, None, 0, 0, 'exact', True)  # interp


def test_trap_with_kv_offset_static():
    """Static kv_offset (a caller whose K slab is a slice of a longer
    sequence) composes with the trapezoid."""
    q, k, v, g = _qkvg(1)
    half = T // 2

    def run(trap):
        import distributed_dot_product_tpu.ops.pallas_attention as m
        old = m._TRAP_ON_INTERPRET
        m._TRAP_ON_INTERPRET = trap
        try:
            out = pa.flash_attention(q, k[..., :half, :], v[..., :half, :],
                                     causal=True, causal_offset=16,
                                     kv_offset=8)
        finally:
            m._TRAP_ON_INTERPRET = old
        return out

    np.testing.assert_array_equal(np.asarray(run(True)),
                                  np.asarray(run(False)))


def test_chunked_trapezoid_matches_full_grid(monkeypatch):
    """Beyond-cap sequences split into Q-row chunks that each take the
    trapezoid (fwd: rows concat; bwd: dk/dv partials sum in fp32) — a
    tiny forced cap must still be bitwise identical to the full grid,
    with dropout and segments composed."""
    monkeypatch.setattr(pa, '_TRAP_ON_INTERPRET', True)
    ks = jax.random.split(jax.random.key(3), 4)
    q, k, v, g = (jax.random.normal(kk, (B, H, 96, D)) for kk in ks)
    seg = (jnp.arange(96) // 40, jnp.arange(96) // 40)

    def run(cap, trap):
        monkeypatch.setattr(pa, '_TRAP_MAX_PAIRS', cap)
        monkeypatch.setattr(pa, '_TRAP_ON_INTERPRET', trap)
        f = lambda q, k, v: pa.flash_attention(  # noqa: E731
            q, k, v, causal=True, segment_ids=seg, dropout_rate=0.25,
            dropout_seed=3)
        out, vjp = jax.vjp(f, q, k, v)
        return (out, *vjp(g))

    a = run(8, True)            # forced chunking
    b = run(10 ** 9, False)     # plain full grid
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_chunk_bounds_cover_rows_exactly():
    import distributed_dot_product_tpu.ops.pallas_attention as m
    orig = m._TRAP_MAX_PAIRS
    try:
        m._TRAP_MAX_PAIRS = 10
        bounds = m._trap_chunk_bounds(0, 512, 512, 8, 8)
        assert bounds[0][0] == 0 and bounds[-1][1] == 512
        for (a0, a1), (b0, b1) in zip(bounds, bounds[1:]):
            assert a1 == b0 and a0 < a1
    finally:
        m._TRAP_MAX_PAIRS = orig
