# -*- coding: utf-8 -*-
"""
Event log (obs/events.py): schema enforcement, crash-safe flushing,
rotation, the active-log routing from ``log_step``/``log_exception``
and the fault injectors, and the training driver's lifecycle events.
"""

import json
import threading

import pytest

from distributed_dot_product_tpu.obs import events
from distributed_dot_product_tpu.obs.events import (
    EventLog, read_events, validate_file, validate_record,
)
from distributed_dot_product_tpu.utils.tracing import (
    MetricsRegistry, log_exception, log_step,
)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _no_active_log():
    """Tests control the active log explicitly; never leak one."""
    prev = events.set_active(None)
    yield
    events.set_active(prev)


def _log(tmp_path, **kw):
    return EventLog(tmp_path / 'events.jsonl', **kw)


def test_emit_envelope_and_readback(tmp_path):
    with _log(tmp_path) as log:
        rec = log.emit('serve.admit', request_id='r0', slot=1,
                       tenant='default', queue_wait=0.25)
    (got,) = read_events(tmp_path / 'events.jsonl')
    assert got == rec
    assert got['schema'] == events.SCHEMA_VERSION
    assert got['seq'] == 0 and got['event'] == 'serve.admit'
    assert validate_record(got) == []


def test_unknown_event_and_missing_field_raise(tmp_path):
    with _log(tmp_path) as log:
        with pytest.raises(ValueError, match='unknown event'):
            log.emit('serve.frobnicate', request_id='r0')
        with pytest.raises(ValueError, match='required field'):
            log.emit('serve.admit', request_id='r0',
                     tenant='default')   # no slot
        # Failed emits consume no seq and write no line.
        log.emit('serve.admit', request_id='r0', slot=0,
                 tenant='default')
    (got,) = read_events(tmp_path / 'events.jsonl')
    assert got['seq'] == 0


def test_crash_safe_flush_visible_before_close(tmp_path):
    log = _log(tmp_path)
    log.emit('health.readiness', state='ready')
    # No close(): the line must already be durable in the file.
    (got,) = read_events(tmp_path / 'events.jsonl')
    assert got['state'] == 'ready'
    log.close()


def test_torn_tail_line_tolerated_elsewhere_rejected(tmp_path):
    path = tmp_path / 'events.jsonl'
    with EventLog(path) as log:
        log.emit('health.readiness', state='ready')
        log.emit('health.readiness', state='degraded')
    with open(path, 'a') as f:
        f.write('{"schema": 1, "seq": 2, "ev')   # crash mid-write
    recs = read_events(path)
    assert [r['state'] for r in recs] == ['ready', 'degraded']
    # The same torn line mid-file is corruption, not a crash tail.
    lines = open(path).read().splitlines()
    lines.insert(1, '{"torn')
    path.write_text('\n'.join(lines) + '\n')
    with pytest.raises(ValueError, match='corrupt event line'):
        read_events(path)


def test_rotation_keeps_order_and_bounds_files(tmp_path):
    path = tmp_path / 'events.jsonl'
    log = EventLog(path, rotate_bytes=600, keep_rotations=2)
    n = 40
    for i in range(n):
        log.emit('train.step', step=i, loss=0.5)
    log.close()
    assert log.rotations >= 2
    files = log.files()
    assert len(files) <= 3          # live + keep_rotations
    recs = read_events(path)
    seqs = [r['seq'] for r in recs]
    assert seqs == sorted(seqs)
    assert seqs[-1] == n - 1        # newest records always survive
    # Oldest were dropped by the bound — that is the rotation contract.
    assert len(recs) < n


def test_reopen_continues_seq_series(tmp_path):
    """A second run appending to the same log must continue seq, not
    restart at 0 — read_events sorts by seq, so duplicated values would
    interleave the two runs' records (and corrupt reused-request-id
    timelines)."""
    path = tmp_path / 'events.jsonl'
    with EventLog(path) as log:
        log.emit('health.readiness', state='ready')
        log.emit('health.readiness', state='stopped')
    with open(path, 'a') as f:
        f.write('{"torn')                      # crash tail survives too
    with EventLog(path) as log2:
        rec = log2.emit('health.readiness', state='ready')
    assert rec['seq'] == 2
    assert [r['seq'] for r in read_events(path)] == [0, 1, 2]


def test_non_finite_floats_serialize_as_strict_json(tmp_path):
    """NaN losses (the bad-step records a fault log exists for) must
    not produce bare NaN tokens — spec-compliant JSONL consumers
    reject those lines."""
    path = tmp_path / 'events.jsonl'
    with EventLog(path) as log:
        log.emit('train.step', step=1, loss=float('nan'), bad=True)
        log.emit('train.step', step=2, loss=float('inf'),
                 extra=[float('-inf'), {'x': float('nan')}])
    raw = path.read_text()
    assert 'NaN' not in raw and 'Infinity' not in raw
    # Strict parsers accept every line.
    recs = [json.loads(line, parse_constant=lambda c: pytest.fail(
        f'non-strict JSON constant {c}')) for line in raw.splitlines()]
    assert recs[0]['loss'] == 'nan'
    assert recs[1]['loss'] == 'inf'
    assert recs[1]['extra'] == ['-inf', {'x': 'nan'}]


def test_validate_file_reports_schema_violations(tmp_path):
    path = tmp_path / 'events.jsonl'
    with EventLog(path) as log:
        log.emit('serve.retire', request_id='r0', status='completed',
                 tokens=3)
    with open(path, 'a') as f:
        f.write(json.dumps({'schema': 99, 'seq': 1, 'ts': 0,
                            'event': 'serve.admit'}) + '\n')
    _, errors = validate_file(path)
    assert any('unknown schema version' in e for e in errors)
    assert any('missing required field' in e for e in errors)


def test_emit_helper_noop_without_active_log(tmp_path):
    assert events.emit('health.readiness', state='ready') is None
    with events.activate(_log(tmp_path)) as log:
        events.emit('health.readiness', state='ready')
    assert len(read_events(log)) == 1


def test_open_from_env(tmp_path):
    path = tmp_path / 'env.jsonl'
    assert events.open_from_env({}) is None
    log = events.open_from_env({events.ENV_VAR: str(path)})
    log.emit('health.liveness', state='alive')
    log.close()
    assert len(read_events(path)) == 1


def test_log_step_and_log_exception_route_through_active_log(tmp_path):
    """The tracing seams share the JSONL stream: per-step training
    history and swallowed exceptions land as typed events, independent
    of the debug print gate."""
    with events.activate(_log(tmp_path)) as log:
        log_step(3, 0.5, grad_norm=1.25, seconds=0.01)
        log_step(4, float('nan'), bad=True)
        log_exception('unit.site', ValueError('boom'),
                      registry=MetricsRegistry())
    recs = read_events(log)
    by_event = {}
    for r in recs:
        by_event.setdefault(r['event'], []).append(r)
    assert by_event['train.step'][0]['step'] == 3
    assert by_event['train.step'][0]['grad_norm'] == 1.25
    assert by_event['train.step'][1]['bad'] is True
    assert by_event['train.bad_step'][0]['step'] == 4
    (exc,) = by_event['exception']
    assert exc['context'] == 'unit.site' and exc['type'] == 'ValueError'


def test_serve_fault_injector_emits_fault_events(tmp_path):
    from distributed_dot_product_tpu.utils.faults import (
        ServeFaultInjector, ServeFaultPlan,
    )
    plan = ServeFaultPlan(stuck_at_step=1, stuck_seconds=0.0,
                          nan_at_step=2, nan_slot=1,
                          abandon_request=0, abandon_after_tokens=1)
    inj = ServeFaultInjector(plan)
    with events.activate(_log(tmp_path)) as log:
        inj.on_decode_step(0)               # not armed: no event
        inj.on_decode_step(1)               # stall
        assert inj.poison_slots(2, 4) == [False, True, False, False]
        assert inj.should_abandon(0, 1)
    kinds = [r['kind'] for r in read_events(log)
             if r['event'] == 'fault.inject']
    assert kinds == ['stuck_step', 'nan_slot', 'abandon']


def test_train_loop_emits_lifecycle_events_and_metrics(tmp_path):
    """run_training end to end with an event log + registry: per-step
    records, a NaN bad step, checkpoint saves and the restore on a
    second run all land in the stream; the step/checkpoint histograms
    and tokens/s gauge fill."""
    import jax.numpy as jnp

    from distributed_dot_product_tpu.train_loop import (
        TrainLoopConfig, run_training,
    )
    from distributed_dot_product_tpu.utils.checkpoint import TrainState

    def step_fn(params, opt_state, batch, dropout_seed=0):
        loss = jnp.mean(batch) + params['w']
        bad = ~jnp.isfinite(loss)
        return params, opt_state, {'loss': jnp.where(bad, loss, loss),
                                   'bad_step': bad,
                                   'grad_norm': jnp.float32(1.0)}

    def batch_fn(step):
        if step == 1:
            return jnp.full((2,), jnp.nan)
        return jnp.ones((2,)) * step

    def fresh_state():
        return TrainState(0, {'w': jnp.float32(0.0)},
                          {'m': jnp.float32(0.0)})

    reg = MetricsRegistry()
    cfg = TrainLoopConfig(num_steps=3, ckpt_dir=str(tmp_path / 'ckpt'),
                          ckpt_every=2, max_bad_steps=5,
                          async_saves=False, tokens_per_step=128)
    with events.activate(_log(tmp_path)) as log:
        result = run_training(step_fn, fresh_state(), batch_fn, cfg,
                              registry=reg)
        assert result.exit_code == 0
        # Second run resumes from the final checkpoint -> restore event.
        run_training(step_fn, fresh_state(), batch_fn, cfg,
                     registry=reg)
    recs = read_events(log)
    kinds = [r['event'] for r in recs]
    assert kinds.count('train.step') >= 3
    assert 'train.bad_step' in kinds
    assert 'train.checkpoint_save' in kinds
    assert 'train.restore' in kinds
    snap = reg.snapshot()
    assert snap['histograms']['train.step_seconds']['total_count'] >= 3
    assert snap['histograms']['train.checkpoint_save_seconds'][
        'total_count'] >= 1
    assert snap['gauges']['train.tokens_per_s'] > 0


# -- offline CLI: stats + machine-readable timeline ---------------------

def _cli_main(argv, capsys):
    from distributed_dot_product_tpu.obs.__main__ import main
    rc = main(argv)
    return rc, capsys.readouterr().out


def test_cli_stats_counts_rate_and_files(tmp_path, capsys):
    path = tmp_path / 'events.jsonl'
    t = [100.0]

    def clock():
        t[0] += 0.5
        return t[0]

    # rotate_bytes sized so the whole run FITS in the rotated set
    # (keep_rotations + live): schema v2 admit lines carry `tenant`,
    # and a dropped oldest file would shrink the counted events.
    with EventLog(path, clock=clock, rotate_bytes=512,
                  keep_rotations=3) as log:
        for i in range(12):
            log.emit('serve.admit', request_id=f'r{i}', slot=0,
                     tenant='default')
        log.emit('serve.retire', request_id='r0', status='completed')
    rc, out = _cli_main(['stats', str(path)], capsys)
    assert rc == 0
    assert 'serve.admit' in out and '12' in out
    assert 'file ' in out                      # rotation accounting

    rc, out = _cli_main(['stats', '--json', str(path)], capsys)
    assert rc == 0
    [rep] = json.loads(out)           # stable shape: always a list
    assert rep['events'] == 13
    assert rep['by_event']['serve.admit'] == 12
    assert rep['wall_span_seconds'] == pytest.approx(6.0)
    assert rep['events_per_second'] == pytest.approx(13 / 6.0)
    assert len(rep['files']) > 1               # rotated set accounted
    assert sum(f['lines'] for f in rep['files']) == 13


def test_cli_stats_unreadable_log_exits_nonzero(tmp_path, capsys):
    bad = tmp_path / 'bad.jsonl'
    bad.write_text('{"schema": 1}\nnot json mid-file\n{"schema": 1}\n')
    rc, _ = _cli_main(['stats', str(bad)], capsys)
    assert rc == 1


def test_cli_timeline_json_full_records(tmp_path, capsys):
    path = tmp_path / 'events.jsonl'
    with EventLog(path) as log:
        log.emit('serve.admit', request_id='r1', slot=0,
                 tenant='default', queue_wait=0.0)
        log.emit('serve.decode', request_id='r1', slot=0,
                 token_index=0, ttft=0.01)
        log.emit('serve.retire', request_id='r1', status='completed',
                 tokens=1, total_seconds=0.02)
    rc, out = _cli_main(['timeline', str(path), 'r1', '--json'], capsys)
    assert rc == 0
    payload = json.loads(out)
    assert payload['complete'] is True
    # Machine-readable form carries the FULL records, not (seq, event).
    assert payload['events'][0]['event'] == 'serve.admit'
    assert payload['events'][0]['request_id'] == 'r1'


def test_schema_v2_tenant_requirement_and_v1_backcompat(tmp_path):
    # Emit side writes v2: tenant is REQUIRED on admit/reject.
    with _log(tmp_path) as log:
        with pytest.raises(ValueError, match='tenant'):
            log.emit('serve.admit', request_id='r0', slot=0)
        with pytest.raises(ValueError, match='tenant'):
            log.emit('serve.reject', request_id='r0',
                     reason='queue_full')
        log.emit('serve.admit', request_id='r0', slot=0, tenant='t0')
    # A v1 record WITHOUT tenant still validates (old logs don't rot)…
    v1 = {'schema': 1, 'seq': 0, 'ts': 0.0, 'event': 'serve.admit',
          'request_id': 'r0', 'slot': 0}
    assert validate_record(v1) == []
    # …while the same shape stamped v2 does not.
    v2 = dict(v1, schema=2)
    assert any('tenant' in e for e in validate_record(v2))
    # Unsupported versions are named with the supported set.
    errs = validate_record(dict(v1, schema=3))
    assert any('unknown schema version' in e for e in errs)
    # slo.violation joined the closed vocabulary.
    assert events.EVENT_SCHEMA['slo.violation'] == ('metric',)


def test_cli_stats_percentiles(tmp_path, capsys):
    path = tmp_path / 'lat.jsonl'
    with EventLog(path) as log:
        for i, (ttft, gap) in enumerate([(0.01, 0.002), (0.03, 0.004),
                                         (0.05, 0.006)]):
            rid = f'r{i}'
            log.emit('serve.admit', request_id=rid, slot=0,
                     tenant='t0', queue_wait=0.1 * (i + 1))
            log.emit('serve.decode', request_id=rid, slot=0,
                     token_index=0, ttft=ttft)
            log.emit('serve.decode', request_id=rid, slot=0,
                     token_index=1, gap=gap)
            log.emit('serve.retire', request_id=rid,
                     status='completed', total_seconds=1.0)
    rc, out = _cli_main(['stats', str(path), '--percentiles',
                         '--json'], capsys)
    assert rc == 0
    [rep] = json.loads(out)
    lat = rep['latency_percentiles']
    assert lat['ttft']['count'] == 3
    assert lat['ttft']['p50'] == pytest.approx(0.03)
    assert lat['ttft']['p99'] == pytest.approx(0.05)
    assert lat['queue_wait']['p50'] == pytest.approx(0.2)
    assert lat['gap']['count'] == 3
    assert lat['gap']['p95'] == pytest.approx(0.006)
    # Human rendering carries the same numbers in ms.
    rc, out = _cli_main(['stats', str(path), '--percentiles'], capsys)
    assert rc == 0
    assert 'ttft' in out and 'p95=' in out and 'queue_wait' in out


def test_cli_stats_merged_per_replica_breakdown(tmp_path, capsys):
    """`stats` over a labeled multi-replica set appends the merged
    per-replica event-count breakdown — who actually emitted what —
    in both renderings."""
    a = EventLog(tmp_path / 'a.jsonl')
    b = EventLog(tmp_path / 'b.jsonl')
    a.emit('serve.admit', request_id='x', slot=0, tenant='t')
    a.emit('serve.retire', request_id='x', status='completed',
           total_seconds=0.1)
    b.emit('serve.admit', request_id='y', slot=0, tenant='t')
    a.close(), b.close()

    rc, out = _cli_main(['stats', f'r0={a.path}', f'r1={b.path}'],
                        capsys)
    assert rc == 0
    assert 'per-replica breakdown' in out
    assert 'r0' in out and 'r1' in out

    rc, out = _cli_main(['stats', '--json', f'r0={a.path}',
                         f'r1={b.path}'], capsys)
    assert rc == 0
    reps = json.loads(out)
    merged = reps[-1]
    assert merged['log'] == '<merged>'
    assert merged['events'] == 3
    assert merged['by_replica']['r0']['by_event'] == {
        'serve.admit': 1, 'serve.retire': 1}
    assert merged['by_replica']['r1']['by_event'] == {
        'serve.admit': 1}
    # Single unlabeled log: no merged report, shape unchanged.
    rc, out = _cli_main(['stats', '--json', str(a.path)], capsys)
    assert rc == 0
    [only] = json.loads(out)
    assert only['log'] == str(a.path)
