# -*- coding: utf-8 -*-
"""
Fault-injection harness unit tests (utils/faults.py): each seam behaves
deterministically on its own, so the driver tests that compose them
(test_train_loop.py) are debuggable when they fail.
"""

import signal

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.utils import checkpoint as ckpt
from distributed_dot_product_tpu.utils.faults import (
    FaultInjector, FaultPlan, SimulatedCrash, plan_from_env, poison_batch,
)


def test_poison_batch_nans_floats_only():
    batch = (jnp.ones((2, 3)), jnp.arange(4), None,
             jnp.zeros((2,), dtype=bool), {'t': jnp.full((2,), 2.0)})
    poisoned = poison_batch(batch)
    assert np.isnan(np.asarray(poisoned[0])).all()
    np.testing.assert_array_equal(np.asarray(poisoned[1]), np.arange(4))
    assert poisoned[2] is None
    assert poisoned[3].dtype == bool
    assert np.isnan(np.asarray(poisoned[4]['t'])).all()


def test_poison_batch_requires_float_leaves():
    """All-integer batches (LM tokens) cannot carry a NaN: silently not
    injecting would fake guard coverage, so it must raise."""
    with pytest.raises(ValueError, match='no floating'):
        poison_batch((jnp.arange(4), None))


def test_plan_from_env_parses_knobs():
    env = {'DDP_TPU_FAULT_NAN_STEPS': '3, 7',
           'DDP_TPU_FAULT_IO_ERRORS': '2',
           'DDP_TPU_FAULT_CRASH_SAVE_STEP': '10',
           'DDP_TPU_FAULT_SIGTERM_STEP': '20'}
    plan = plan_from_env(env)
    assert plan.nan_at_steps == frozenset({3, 7})
    assert plan.io_error_saves == 2
    assert plan.crash_in_save_at_step == 10
    assert plan.sigterm_at_step == 20
    assert plan.any()
    assert not plan_from_env({}).any()


def test_wrapped_batch_fn_fires_once_per_step():
    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({1})))
    wrapped = inj.wrap_batch_fn(lambda i: (jnp.ones(3),))
    assert not np.isnan(np.asarray(wrapped(0)[0])).any()
    assert np.isnan(np.asarray(wrapped(1)[0])).all()
    # fire_once (the default): the replay after a rollback is clean.
    assert not np.isnan(np.asarray(wrapped(1)[0])).any()

    inj = FaultInjector(FaultPlan(nan_at_steps=frozenset({1}),
                                  fire_once=False))
    wrapped = inj.wrap_batch_fn(lambda i: (jnp.ones(3),))
    assert np.isnan(np.asarray(wrapped(1)[0])).all()
    assert np.isnan(np.asarray(wrapped(1)[0])).all()


def test_io_error_injection_counts_down(tmp_path):
    state = ckpt.TrainState(1, {'w': jnp.zeros(3)}, {'m': jnp.zeros(3)})
    inj = FaultInjector(FaultPlan(io_error_saves=2))
    with inj:
        with pytest.raises(OSError, match='injected'):
            ckpt.save(tmp_path, state)
        with pytest.raises(OSError, match='injected'):
            ckpt.save(tmp_path, state)
        ckpt.save(tmp_path, state)   # countdown exhausted: save lands
    assert ckpt.latest_step(tmp_path) == 1


def test_crash_mid_save_leaves_unfinalized_dir(tmp_path):
    import os
    state = ckpt.TrainState(4, {'w': jnp.zeros(3)}, {'m': jnp.zeros(3)})
    inj = FaultInjector(FaultPlan(crash_in_save_at_step=4))
    with inj:
        with pytest.raises(SimulatedCrash):
            ckpt.save(tmp_path, state)
    names = os.listdir(tmp_path)
    assert any('.orbax-checkpoint-tmp' in n for n in names)
    assert ckpt.latest_step(tmp_path) is None   # partial never selected
    # SimulatedCrash models process death: no except-Exception handler
    # (e.g. a retry loop) may swallow it.
    assert not issubclass(SimulatedCrash, Exception)


def test_injector_install_is_exclusive_and_restores():
    inj1 = FaultInjector(FaultPlan(io_error_saves=1))
    inj2 = FaultInjector(FaultPlan(io_error_saves=1))
    with inj1:
        with pytest.raises(RuntimeError, match='already installed'):
            inj2.install()
    assert ckpt._SAVE_FAULT_HOOK is None
    with inj2:
        assert ckpt._SAVE_FAULT_HOOK is inj2._hook
    assert ckpt._SAVE_FAULT_HOOK is None


def test_synthetic_sigterm_is_a_real_signal():
    got = []
    old = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    try:
        inj = FaultInjector(FaultPlan(sigterm_at_step=5))
        inj.on_step(4)
        assert got == []
        inj.on_step(5)
        assert got == [signal.SIGTERM]
        inj.on_step(5)   # one-shot: a second visit does not re-signal
        assert got == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, old)
