# -*- coding: utf-8 -*-
"""
The analytic ICI communication model (scripts/comm_model.py) must match
what XLA actually compiles: per path, the multiset of collective ops and
their per-op byte sizes in the compiled HLO equals the model's predicted
schedule. This is the checkable substitute for multi-chip measurement
(one real chip in the environment — RESULTS.md 'Communication model').
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                'scripts'))
import comm_model  # noqa: E402

pytestmark = pytest.mark.slow


def test_schedule_matches_compiled_hlo():
    results = comm_model.check_against_hlo(n=8)
    for path, r in results.items():
        assert r['match'], (
            f"{path}: model schedule {r['expected']} != compiled HLO "
            f"{r['got']}")


def test_gqa_cuts_allgather_bytes():
    full = comm_model.comm_model('allgather', n=8, h=8, t=4096, d=64)
    gqa = comm_model.comm_model('allgather', n=8, h=8, h_kv=2, t=4096,
                                d=64)
    assert gqa['total_bytes'] == full['total_bytes'] / 4


def test_ring_equals_allgather_volume_at_bf16():
    """The classic identity: ring rotation moves the same total K/V bytes
    as one all-gather — (N−1)/N of the global array per device — so the
    FORWARD volumes agree exactly; the ring backward additionally carries
    fp32 dk/dv partials."""
    n, h, t, d = 8, 8, 4096, 64
    ag = comm_model.comm_model('allgather', n=n, h=h, t=t, d=d)
    ring = comm_model.comm_model('ring', n=n, h=h, t=t, d=d)
    ag_fwd = ag['collectives'][0]
    ring_fwd = ring['collectives'][0]
    assert ag_fwd[1] * ag_fwd[2] == pytest.approx(
        ring_fwd[1] * ring_fwd[2])


def test_ulysses_is_n_over_2_cheaper():
    """Ulysses moves O(T·d·H/N) per device per tensor vs allgather's
    O(T·d·H): allgather ships 2 tensors each way (q, v — 4 collectives
    total), ulysses 4 each way but at 1/N volume, so the total ratio is
    N/2 (H_kv = H, same dtypes both ways)."""
    n, h, t, d = 8, 8, 4096, 64
    ag = comm_model.comm_model('allgather', n=n, h=h, t=t, d=d)
    ul = comm_model.comm_model('ulysses', n=n, h=h, t=t, d=d)
    assert ag['total_bytes'] / ul['total_bytes'] == pytest.approx(n / 2)
