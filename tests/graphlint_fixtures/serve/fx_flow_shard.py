# -*- coding: utf-8 -*-
"""Seeded flowlint shard-ownership regressions: host code re-deriving
the ``pages_per_shard + 1`` contiguous-ownership stride instead of
going through the ShardedPageTable helpers (analysis/flowlint.py).
The PR 18 layout has exactly one home — models/decode.py."""


def leaky_global_page(cache, shard, page):
    return shard * (cache.pages_per_shard + 1) + page  # VIOLATION: shard-ownership


def leaky_owner(cache, gpage):
    return gpage // (cache.pages_per_shard + 1)  # VIOLATION: shard-ownership


def owned_global_page(cache, shard, page):
    return cache.gpage(shard, page)


def owned_owner(cache, gpage):
    return cache.page_shard(gpage)
