# -*- coding: utf-8 -*-
"""Seeded flowlint typed-escape regressions: untyped builtins escaping
declared serving roots (analysis/flowlint.py). The module literals
``FLOWLINT_ROOTS`` / ``FLOWLINT_CONTRACT`` stand in for the central
SERVING_ROOTS / TYPED_CONTRACT tables — the fixture is a standalone
universe. Each marked line is a production incident shape: PR 17's
drive-found ``deque.remove`` untyped ValueError out of
``Scheduler.step`` is reproduced verbatim by ``Server.submit``."""

from collections import deque

FLOWLINT_ROOTS = ('Server.step', 'Server.submit', 'run_ok')
FLOWLINT_CONTRACT = ('TypedServeError',)


class TypedServeError(Exception):
    """The fixture universe's whole typed-failure contract."""


def _pop_head(table, key):
    if key not in table:
        raise KeyError(key)  # VIOLATION: typed-escape
    return table.pop(key)


def _drain(table):
    # One hop between the root and the raise: the chain must render
    # step -> _drain -> _pop_head (two hops, three frames).
    return _pop_head(table, 'head')


class Server:
    def __init__(self):
        self.pending = deque()
        self.table = {}

    def step(self):
        return _drain(self.table)

    def submit(self, req):
        self.pending.append(req)
        if req is None:
            # The PR 17 regression shape: deque.remove walks __eq__
            # over every queued request (numpy prompt fields make the
            # comparison itself blow up) and raises an untyped
            # ValueError when nothing matches.
            self.pending.remove(req)  # VIOLATION: typed-escape
        return len(self.pending)

    def refuse(self, req):
        # In-contract raise: never flagged.
        raise TypedServeError(req)


def _tail(xs):
    if not xs:
        # Deliberate, enumerable debt: the pragma keeps this VISIBLE
        # as an allowed record instead of silently dropping it.
        raise IndexError('empty')  # flowlint: allow[typed-escape]
    return xs[-1]


def run_ok(xs):
    return _tail(xs)
