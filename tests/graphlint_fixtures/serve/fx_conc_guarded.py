# -*- coding: utf-8 -*-
"""Seeded conclint regressions: reads/writes of a ``# guarded-by:``
annotated field outside its lock, and an undisciplined thread spawn
(no daemon=True, no name)."""
import threading


class LeakyCollector:
    """Follows the EventLog/SpanCollector convention — except where the
    seeded regressions say otherwise."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []            # guarded-by: self._lock

    def add(self, item):
        with self._lock:
            self._items.append(item)

    def drain(self):
        out = list(self._items)     # VIOLATION: guarded-by
        self._items = []            # VIOLATION: guarded-by
        return out

    def _compact_locked(self):
        # *_locked convention: the caller holds the lock — exempt.
        self._items = [i for i in self._items if i is not None]

    def snapshot_documented_torn_read(self):
        # The scheduler-introspection idiom: deliberate, pragma'd.
        return len(self._items)  # graphlint: allow[guarded-by] torn read ok

    def start_worker(self):
        t = threading.Thread(target=self.drain)  # VIOLATION: thread-discipline
        return t

    def start_deferred(self):
        # The classic deferred race: the closure is DEFINED under the
        # lock but RUNS later on the worker thread without it.
        with self._lock:
            def worker():
                self._items.append('late')  # VIOLATION: guarded-by
            return threading.Thread(target=worker, name='fx-late',
                                    daemon=True)

    def start_disciplined(self):
        return threading.Thread(target=self.drain, name='fx-drain',
                                daemon=True)
