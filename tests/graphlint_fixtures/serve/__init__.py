# Deliberately-violating servelint fixtures (protolint / conclint /
# determlint). Excluded from the clean-tree walk like the rest of
# graphlint_fixtures; linted explicitly by tests/test_servelint.py.
