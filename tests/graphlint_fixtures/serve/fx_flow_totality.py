# -*- coding: utf-8 -*-
"""Seeded flowlint handler-totality regressions: ``except`` clauses
that catch a TYPED serving error and then drop it on the floor — no
re-raise, no event/metric routing, no payload consumption
(analysis/flowlint.py). The local ``RejectedError`` shadows the real
one by NAME: handler-totality keys on the TOTALITY_BASES names plus
in-universe subclasses, so the fixture stays standalone."""


class RejectedError(Exception):
    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


class QuotaError(RejectedError):
    """In-universe subclass: the totality closure must reach it."""


def swallow(op):
    try:
        op()
    except RejectedError:  # VIOLATION: handler-totality
        pass


def swallow_subclass(op):
    try:
        op()
    except QuotaError as e:  # VIOLATION: handler-totality
        print(e)


def reraise_is_total(op):
    try:
        op()
    except RejectedError:
        raise


def consume_payload_is_total(op, rejected):
    try:
        op()
    except RejectedError as e:
        rejected['last'] = e.reason


def emit_is_total(op, log):
    try:
        op()
    except RejectedError as e:
        log.emit('serve.reject', **_payload(e))


def _note_reject(log, e):
    log.emit('serve.reject', **_payload(e))


def transitive_route_is_total(op, log):
    # The emit lives one intra-package call away: the may-emit
    # fixpoint, not the handler body, is the enforcement surface.
    try:
        op()
    except RejectedError as e:
        _note_reject(log, e)


def untyped_catch_is_out_of_scope(op):
    # astlint owns generic silent-except hygiene; flowlint only judges
    # the TYPED serving contract.
    try:
        op()
    except ValueError:
        return None


def _payload(e):
    return {'reason': str(e)}
