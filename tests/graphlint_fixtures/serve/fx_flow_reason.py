# -*- coding: utf-8 -*-
"""Seeded flowlint reason-coverage regression: a RejectReason member
no code path can produce (analysis/flowlint.py). The live members show
the full healthy ladder — a reference site, a ``serve.reject`` emit,
and the canonical dynamic per-reason counter loop; ``GHOST_CAUSE`` has
none of the first and flags as dead taxonomy."""

from enum import Enum


class RejectReason(Enum):
    QUEUE_FULL = 'queue_full'
    QUOTA_EXCEEDED = 'quota_exceeded'
    GHOST_CAUSE = 'ghost_cause'  # VIOLATION: reason-coverage


def admit(queue, log):
    if queue.full():
        _reject(log, RejectReason.QUEUE_FULL)
        return False
    return True


def charge(budget, log):
    if budget <= 0:
        _reject(log, RejectReason.QUOTA_EXCEEDED)
        return False
    return True


def _reject(log, reason):
    log.emit('serve.reject', **_payload(reason))


def _payload(reason):
    return {'reason': reason.value}


def install_counters(registry):
    # Dynamic per-member loop: covers the counter leg for EVERY
    # member, so GHOST_CAUSE flags only for its missing raise site.
    for r in RejectReason:
        registry.counter(f'serve.rejected.{r.value}')
