# -*- coding: utf-8 -*-
"""Seeded protolint regressions: emit call sites that violate the
closed EVENT_SCHEMA vocabulary / required-field / typed-reject
contracts (obs/events.py, serve/admission.py). Each marked line is a
runtime ValueError waiting for an incident; protolint fails it at PR
time instead."""


def emit_unknown_kind(log, rid):
    log.emit('serve.launch', request_id=rid)  # VIOLATION: event-vocab


def emit_missing_fields(log, rid):
    log.emit('serve.admit', request_id=rid)  # VIOLATION: event-fields


def emit_untyped_reason(log, rid):
    log.emit('serve.reject', request_id=rid, tenant='t0',
             reason='because')               # VIOLATION: reject-reason


def emit_enum_without_value(log, rid, RejectReason):
    log.emit('serve.reject', request_id=rid, tenant='t0',
             reason=RejectReason.QUEUE_FULL)  # VIOLATION: reject-reason


def fine_complete_payloads(log, rid):
    log.emit('serve.admit', request_id=rid, slot=0, tenant='t0')
    log.emit('serve.reject', request_id=rid, reason='queue_full',
             tenant='t0')


def fine_forwarded_payload(log, rid, **fields):
    # **fields forwarding: statically incomplete, runtime validation
    # owns it — never judged.
    log.emit('serve.decode', request_id=rid, **fields)
