# -*- coding: utf-8 -*-
"""Seeded determlint regressions: real-time / random / environment
reads inside a declared virtual-clock tick path (and transitively
through an intra-module helper), plus a loop-blocking sleep."""
import os
import random
import time

GRAPHLINT_TICK_ROOTS = ('drive',)


def drive(scheduler, clock, trace):
    t0 = time.time()                     # VIOLATION: tick-determinism
    jitter = random.random()             # VIOLATION: tick-determinism
    debug = os.environ.get('FX_DEBUG')   # VIOLATION: tick-determinism
    _throttle(scheduler)
    while trace:
        scheduler.submit(trace.pop(0))
        scheduler.step()
        clock.advance(0.002)
    return t0, jitter, debug


def _throttle(scheduler):
    # Reached through the closure from `drive` — flagged transitively.
    time.sleep(0.01)                     # VIOLATION: tick-determinism


def fine_outside_closure(cfg):
    # Not reachable from a tick root: real time is fine here.
    return time.time()
