# Deliberate-violation fixtures for tests/test_graphlint.py. This tree
# is EXCLUDED from the analyzer's default scan (astlint.iter_python_files
# skips 'graphlint_fixtures'; ruff excludes it in pyproject) — each file
# seeds exactly the regression its rule must catch, and the tests assert
# the analyzer reports it with file:line.
