# -*- coding: utf-8 -*-
"""Seeded jaxpr-rule regressions: TraceSpec builders that each break
exactly ONE contract the jaxpr linter enforces. tests/test_graphlint.py
lints them and asserts the expected rule id fires (and that file:line
points here)."""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_dot_product_tpu.analysis.registry import TraceSpec
from distributed_dot_product_tpu.models.decode import (
    append_kv, decode_attention, init_cache, init_paged_cache,
    paged_append_kv_slots,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.utils.comm import SEQ_AXIS


def _sds(*shape, dtype=jnp.bfloat16):
    return jax.ShapeDtypeStruct(shape, dtype)


def _cache_and_new():
    cache = init_cache(1, 2, 32, 8, dtype=jnp.bfloat16)
    new = jnp.zeros((1, 2, 1, 8), jnp.bfloat16)
    return cache, new


def bad_f32_accum():
    """bf16 dot_general WITHOUT preferred_element_type → bf16 accum."""

    def fn(a, b):
        return lax.dot_general(a, b, (((1,), (0,)), ((), ())))

    return TraceSpec(name='neg.f32_accum', fn=fn,
                     args=(_sds(16, 8), _sds(8, 16)))


def bad_cache_rematerialize():
    """The appended cache K buffer is re-materialized by arithmetic
    (`k * 1`) on the way out — the in-place append contract is broken
    even though the VALUES are identical."""

    def fn(cache, k_new, v_new):
        cache = append_kv(cache, k_new, v_new)
        return cache._replace(k=cache.k * jnp.bfloat16(1))

    cache, new = _cache_and_new()
    return TraceSpec(
        name='neg.cache_rematerialize', fn=fn, args=(cache, new, new),
        cache_in=lambda a: [a[0].k, a[0].v],
        cache_out=lambda o: [o.k, o.v])


def bad_full_shape_dus():
    """A dynamic_update_slice whose update is the FULL buffer shape —
    the degenerate 'append' that rewrites the whole cache per step."""

    def fn(cache, k_new, v_new):
        zeros = (jnp.zeros((), jnp.int32),) * 4
        full = jnp.broadcast_to(k_new, cache.k.shape)
        return cache._replace(
            k=lax.dynamic_update_slice(cache.k, full, zeros))

    cache, new = _cache_and_new()
    return TraceSpec(
        name='neg.full_shape_dus', fn=fn, args=(cache, new, new),
        cache_in=lambda a: [a[0].k],
        cache_out=lambda o: [o.k])


def bad_paged_pool_rematerialize():
    """The paged append done WRONG: the pool buffer is re-materialized
    by arithmetic (`pool * 1`) on the way out, off the page-write
    scatter spine — every decode step would copy the ENTIRE pool, the
    exact failure paging exists to avoid."""

    def fn(cache, k_new, v_new):
        cache = paged_append_kv_slots(cache, k_new, v_new)
        return cache._replace(k_pool=cache.k_pool * jnp.bfloat16(1))

    cache = init_paged_cache(1, 2, 32, 8, pages=4, page_size=8,
                             dtype=jnp.bfloat16)
    cache = cache._replace(
        page_table=jnp.array([[0, -1, -1, -1]], jnp.int32))
    new = jnp.zeros((1, 2, 1, 8), jnp.bfloat16)
    return TraceSpec(
        name='neg.paged_pool_rematerialize', fn=fn,
        args=(cache, new, new),
        cache_in=lambda a: [a[0].k_pool, a[0].v_pool],
        cache_out=lambda o: [o.k_pool, o.v_pool])


def bad_cache_upcast():
    """The pre-fix decode_attention formulation: upcast the whole K/V
    buffers to f32 before the dots (full-size copies per step)."""

    def fn(q, cache):
        s = jnp.einsum('bhqd,bhtd->bhqt', q.astype(jnp.float32),
                       cache.k.astype(jnp.float32))
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum('bhqt,bhtd->bhqd', p,
                          cache.v.astype(jnp.float32))

    cache, new = _cache_and_new()
    return TraceSpec(
        name='neg.cache_upcast', fn=fn, args=(new, cache),
        cache_in=lambda a: [a[1].k, a[1].v],
        cache_out=lambda o: [o, o])      # unused by the upcast rule


def bad_missing_donation():
    """The real decode step — but registered WITHOUT donate_argnums, as
    if someone dropped the donation from the serving jit: the lowered
    module then aliases nothing and every step copies the cache."""
    from distributed_dot_product_tpu.models.decode import decode_step

    cache, new = _cache_and_new()
    return TraceSpec(
        name='neg.missing_donation',
        fn=partial(decode_step, impl='xla'),
        args=(new, cache, new, new),
        expect_donation=True, donate_argnums=(), min_donated=2)


def bad_collective_axis():
    """Program built over mesh axis 'seq' while the registration
    declares the mesh as ('data',) — topology drift."""
    mesh = seq_mesh(2)

    def body(q, cache):
        out = decode_attention(q, cache, axis_name=SEQ_AXIS)
        return out

    cache = init_cache(1, 2, 32, 8, dtype=jnp.bfloat16)
    new = jnp.zeros((1, 2, 1, 8), jnp.bfloat16)
    spec4 = P(None, None, SEQ_AXIS, None)
    cache_spec = type(cache)(k=spec4, v=spec4, length=P(),
                             k_q=None, k_scale=None)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(P(), cache_spec),
                       out_specs=P(), check_vma=False)
    return TraceSpec(name='neg.collective_axis', fn=fn,
                     args=(new, cache), mesh_axes=('data',))


def bad_trace_error():
    """A registration whose entrypoint no longer traces at its declared
    shapes (here: a shape assertion that fires) — reported as
    trace-error, not a crash of the whole run."""

    def fn(x):
        raise ValueError('entrypoint regressed')

    return TraceSpec(name='neg.trace_error', fn=fn, args=(_sds(4, 4),))


ALL = {
    'neg.f32_accum': (bad_f32_accum, 'f32-accum'),
    'neg.cache_rematerialize': (bad_cache_rematerialize, 'cache-alias'),
    'neg.paged_pool_rematerialize': (bad_paged_pool_rematerialize,
                                     'cache-alias'),
    'neg.full_shape_dus': (bad_full_shape_dus, 'cache-alias'),
    'neg.cache_upcast': (bad_cache_upcast, 'cache-upcast'),
    'neg.missing_donation': (bad_missing_donation, 'donation'),
    'neg.collective_axis': (bad_collective_axis, 'collective-axis'),
    'neg.trace_error': (bad_trace_error, 'trace-error'),
}


# CLI-shaped view ({name: builder}) for --registry MODULE:ATTR runs.
REGISTRY = {name: builder for name, (builder, _rule) in ALL.items()}
