"""Seeded span-in-jit regression: an obs span inside a jitted function
(reads the host clock at TRACE time — the recorded span describes
compilation, not execution). Spans wrap host-side dispatch only."""
import jax

from distributed_dot_product_tpu.obs import span, spanned


@jax.jit
def spanned_step(x):
    with span('step'):           # VIOLATION: clock-in-jit
        return x * 2


@jax.jit
def decorated_body(x):
    y = spanned('inner')(lambda v: v + 1)(x)   # VIOLATION
    return y


def fine_host_span(step, x):
    with span('dispatch'):       # outside jit: NOT flagged
        return step(x)
