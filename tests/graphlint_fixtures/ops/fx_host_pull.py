"""Seeded host-pull regressions: host conversions of jnp-derived values
in a hot-path ('ops/') module."""
import jax.numpy as jnp


def pulls_float(x):
    total = jnp.sum(x)
    return float(total)          # VIOLATION: host-pull (line 8)


def pulls_item(x):
    return (x * 2).item()        # VIOLATION: host-pull (line 12)


def fine_static_config(scale):
    # float() on a static kwarg is NOT flagged (no jnp derivation).
    return jnp.float32(float(scale))
