"""Seeded traced-bool-branch regression: python `if` on a traced
predicate in a hot-path module."""
import jax.numpy as jnp


def branches_on_traced(x):
    if jnp.any(x > 0):           # VIOLATION: traced-bool-branch (line 7)
        return x * 2
    return x


def fine_identity_check(mask):
    m = jnp.asarray(mask)
    if m is not None:            # identity check: NOT flagged
        return m
    return None
