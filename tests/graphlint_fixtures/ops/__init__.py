# Lives under an 'ops/' path segment on purpose: the host-pull and
# traced-bool-branch rules only police hot paths (ops/, models/).
