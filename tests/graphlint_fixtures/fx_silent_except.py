"""Seeded silent-except regression: a broad handler that neither
re-raises nor logs."""


def swallows(fn):
    try:
        return fn()
    except Exception:            # VIOLATION: silent-except (line 8)
        pass


def fine_logged(fn, log):
    try:
        return fn()
    except Exception as e:
        log.warning('fn failed: %s', e)
        return None


def fine_pragma(fn):
    try:
        return fn()
    except Exception:  # graphlint: allow[silent-except] fixture demo
        return None
