"""Seeded clock-in-jit regression: a wall-clock read inside a jitted
function (bakes a constant into the compiled program)."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def stamped_step(x):
    t = time.time()              # VIOLATION: clock-in-jit (line 11)
    return x + jnp.float32(t)


def fine_host_timing(fn, x):
    start = time.perf_counter()  # outside jit: NOT flagged
    out = fn(x)
    return out, time.perf_counter() - start
