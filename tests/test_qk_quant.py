# -*- coding: utf-8 -*-
"""
int8-quantized QK^T flash attention tests.

Two oracles: (a) the EXACT bf16/f32 kernel — the quantized forward must
land within int8 rounding noise of it; (b) a dense jnp re-implementation
of the SAME quantized math with straight-through rounding — the kernel's
VJP must match ITS gradients to float precision (the quantized path is a
different, self-consistent function, not a noisy version of the exact
one). No reference analog.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.pallas_attention import (
    flash_attention,
)

B, H, D = 2, 3, 32

pytestmark = pytest.mark.slow


def _qkv(t, key=0, h=H):
    ks = jax.random.split(jax.random.key(key), 3)
    return tuple(jax.random.normal(kk, (B, h, t, D)) for kk in ks)


def _dense_quant(q, k, v, causal=True, window=None):
    """The same quantized computation in jnp, STE rounding."""
    def ste_round(x):
        return x + jax.lax.stop_gradient(jnp.round(x) - x)

    t = q.shape[-2]
    scale = 1.0 / np.sqrt(D)
    sq = jax.lax.stop_gradient(
        jnp.maximum(jnp.abs(q).max(-1, keepdims=True) / 127.0, 1e-20))
    sk = jax.lax.stop_gradient(
        jnp.maximum(jnp.abs(k).max(-1, keepdims=True) / 127.0, 1e-20))
    s = jnp.einsum('...td,...od->...to', ste_round(q / sq) * sq,
                   ste_round(k / sk) * sk) * scale
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    if causal:
        s = jnp.where(rows < cols, -jnp.inf, s)
    if window is not None:
        s = jnp.where(rows - cols >= window, -jnp.inf, s)
    return jnp.einsum('...to,...od->...td', jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize('t', [64, 100])
def test_quant_forward_matches_quant_oracle(t):
    q, k, v = _qkv(t)
    out = flash_attention(q, k, v, causal=True, qk_quant='int8')
    ref = _dense_quant(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_quant_close_to_exact():
    """Quantization noise stays in the int8 class (~1% of output scale)."""
    q, k, v = _qkv(64, key=1)
    out_q = flash_attention(q, k, v, causal=True, qk_quant='int8')
    out_e = flash_attention(q, k, v, causal=True)
    err = float(jnp.abs(out_q - out_e).max())
    assert err < 5e-2, err


def test_quant_gradients_match_quant_oracle():
    t = 100
    q, k, v = _qkv(t, key=2)

    def f(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                qk_quant='int8') ** 2).sum()

    def f_ref(q, k, v):
        return (_dense_quant(q, k, v) ** 2).sum()

    lk, gk = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
    lr, gr = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(lk), float(lr), rtol=1e-6)
    for got, want in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_quant_with_window_banded(monkeypatch):
    import distributed_dot_product_tpu.ops.pallas_attention as pa

    t, window = 64, 11
    q, k, v = _qkv(t, key=3)
    ref = _dense_quant(q, k, v, window=window)
    out = flash_attention(q, k, v, causal=True, window=window,
                          qk_quant='int8')
    monkeypatch.setattr(pa, '_BAND_ON_INTERPRET', True)
    out_band = flash_attention(q, k, v, causal=True, window=window,
                               qk_quant='int8')
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_band), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_quant_with_gqa():
    t = 64
    q, _, _ = _qkv(t, key=4)
    kk, kv = jax.random.split(jax.random.key(5))
    k = jax.random.normal(kk, (B, 1, t, D))      # MQA
    v = jax.random.normal(kv, (B, 1, t, D))
    out = flash_attention(q, k, v, causal=True, qk_quant='int8')
    ref = _dense_quant(q, jnp.broadcast_to(k, q.shape),
                       jnp.broadcast_to(v, q.shape))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_quant_bounded_mode_falls_back():
    q, k, v = _qkv(64, key=6)
    out_b = flash_attention(q, k, v, causal=True, qk_quant='int8',
                            softmax_mode='bounded')
    out_e = flash_attention(q, k, v, causal=True, qk_quant='int8')
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_e),
                               atol=1e-6)


def test_quant_zero_rows_safe():
    """All-zero q/k rows: eps-clamped scales, no NaN."""
    q, k, v = _qkv(64, key=7)
    q = q.at[..., :8, :].set(0.0)
    k = k.at[..., :8, :].set(0.0)
    out = flash_attention(q, k, v, causal=True, qk_quant='int8')
    assert bool(jnp.isfinite(out).all())


def test_quant_validation():
    q, k, v = _qkv(16)
    with pytest.raises(ValueError, match='qk_quant'):
        flash_attention(q, k, v, qk_quant='int4')


# ---------------------------------------------------------------------------
# Ring-path int8: the per-fold quantization is row-local, so the ring
# result must match the single-device int8 flash kernel (fwd AND grads).
# ---------------------------------------------------------------------------

def _ring_int8(mesh, layout='contiguous'):
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )
    spec = P(None, None, 'seq', None)
    return jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True,
                                       qk_quant='int8', layout=layout),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)


def test_ring_int8_matches_flash_int8():
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    q, k, v = _qkv(64, key=8)
    want = flash_attention(q, k, v, causal=True, qk_quant='int8')
    ring = _ring_int8(seq_mesh(4))
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-5)


def test_ring_int8_gradients_match_flash_int8():
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    q, k, v = _qkv(64, key=9)
    cot = jax.random.normal(jax.random.key(10), v.shape, jnp.float32)
    ring = _ring_int8(seq_mesh(4))

    g_ring = jax.grad(lambda *a: jnp.sum(ring(*a) * cot),
                      argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(
        lambda *a: jnp.sum(flash_attention(*a, causal=True,
                                           qk_quant='int8') * cot),
        argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_flash):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_ring_int8_zigzag_round_trip():
    from distributed_dot_product_tpu.models.ring_attention import (
        zigzag_indices,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    world, t = 4, 64
    q, k, v = _qkv(t, key=11)
    idx = zigzag_indices(t, world)
    inv = jnp.argsort(idx)
    ring = _ring_int8(seq_mesh(world), layout='zigzag')
    got = ring(q[..., idx, :], k[..., idx, :], v[..., idx, :])[..., inv, :]
    want = flash_attention(q, k, v, causal=True, qk_quant='int8')
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-5)


def test_ring_int8_xla_fold_rejected():
    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention,
    )
    q, k, v = _qkv(16, key=12)
    with pytest.raises(ValueError, match='qk_quant'):
        ring_attention(q[..., :4, :], k[..., :4, :], v[..., :4, :],
                       block_impl='xla', qk_quant='int8')
