# -*- coding: utf-8 -*-
"""
Worker process for the multi-host launch test (run by test_multihost.py).

Each OS process simulates one host: it owns ``LOCAL_DEVICES`` virtual CPU
devices and joins the others through ``comm.init`` /
``jax.distributed.initialize`` — the TPU-native replacement for the
reference's ``horovodrun -np N --mpi`` process launch (reference
README.md:77,173-176). The joined processes form ONE global mesh and run
ONE SPMD train step on deterministic data; process 0 prints the loss,
which the test compares against the identical single-process run.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
"""

import sys

import jax

LOCAL_DEVICES = 4


def make_batch(batch, t, dim):
    """Deterministic batch — identical in every process and in the
    single-process oracle, with no dependence on device topology."""
    import numpy as np
    base = np.arange(batch * t * dim, dtype=np.float32)
    x = (np.sin(base * 0.01).reshape(batch, t, dim) * 0.5).astype(np.float32)
    target = (np.cos(base * 0.02).reshape(batch, t, dim) * 0.5
              ).astype(np.float32)
    mask = np.zeros((batch, t, t), dtype=bool)
    return x, target, mask


def run_step(world, ckpt_dir=None):
    """Build the model/mesh/step and run one training step on global
    arrays; returns the (fully-replicated) loss as a float. With
    ``ckpt_dir``, also saves the post-step state and restores it — the
    collective multi-host checkpoint path (every process participates)."""
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_dot_product_tpu import DistributedDotProductAttn
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh
    from distributed_dot_product_tpu.train import make_train_step

    mesh = seq_mesh(world)
    batch, t, dim, heads = 2, world * 4, 32, 4
    x_np, target_np, mask_np = make_batch(batch, t, dim)

    act = NamedSharding(mesh, P(None, 'seq', None))
    mask_sh = NamedSharding(mesh, P(None, 'seq', None))

    def globalize(np_arr, sharding):
        return jax.make_array_from_callback(
            np_arr.shape, sharding, lambda idx: np_arr[idx])

    x = globalize(x_np, act)
    target = globalize(target_np, act)
    mask = globalize(mask_np, mask_sh)

    model = DistributedDotProductAttn(key_dim=dim, num_heads=heads, offset=2)
    # Init on host-local (replicated) data — identical in every process —
    # then commit the params to the mesh as fully-replicated global arrays.
    params_local = model.init(jax.random.key(1),
                              jnp_like(x_np), jnp_like(x_np), jnp_like(x_np),
                              jnp_like(mask_np))
    rep = NamedSharding(mesh, P())
    params = jax.tree.map(
        lambda p: globalize(np.asarray(p), rep), params_local)

    optimizer = optax.adam(1e-3)
    opt_state = jax.tree.map(
        lambda p: globalize(np.asarray(p), rep) if hasattr(p, 'shape') else p,
        optimizer.init(params_local))

    step = make_train_step(model, optimizer, mesh, donate=False)
    new_params, new_opt, loss = step(params, opt_state,
                                     (x, x, x, mask, target))

    if ckpt_dir is not None:
        # Collective save + restore across all processes (the checkpoint
        # module's multi-host contract): every process calls with its view
        # of the same global arrays; restored leaves adopt the template's
        # (mesh-committed) shardings and must round-trip bitwise.
        import distributed_dot_product_tpu as ddp
        ddp.save(ckpt_dir, ddp.TrainState(1, new_params, new_opt))
        restored = ddp.restore(
            ckpt_dir, ddp.TrainState(0, new_params, new_opt))
        assert restored.step == 1
        for got_tree, want_tree in ((restored.params, new_params),
                                    (restored.opt_state, new_opt)):
            for a, b in zip(jax.tree.leaves(got_tree),
                            jax.tree.leaves(want_tree)):
                got = np.asarray(jax.device_get(a))
                want = np.asarray(jax.device_get(b))
                assert (got == want).all(), 'checkpoint round-trip mismatch'

    return float(np.asarray(jax.device_get(loss)))


def jnp_like(np_arr):
    import jax.numpy as jnp
    return jnp.asarray(np_arr)


def main():
    process_id, num_processes, port = (int(sys.argv[1]), int(sys.argv[2]),
                                       sys.argv[3])
    ckpt_dir = sys.argv[4] if len(sys.argv) > 4 else None
    # ensure_cpu_devices: version-portable jax_num_cpu_devices /
    # XLA_FLAGS provisioning (must run before backend init).
    from distributed_dot_product_tpu._compat import ensure_cpu_devices
    ensure_cpu_devices(LOCAL_DEVICES)

    from distributed_dot_product_tpu.utils import comm
    comm.init(coordinator_address=f'127.0.0.1:{port}',
              num_processes=num_processes, process_id=process_id)
    assert jax.process_count() == num_processes, jax.process_count()
    world = num_processes * LOCAL_DEVICES
    assert len(jax.devices()) == world, jax.devices()

    loss = run_step(world, ckpt_dir=ckpt_dir)
    comm.synchronize()
    if comm.is_main_process():
        print(f'MULTIHOST_LOSS={loss:.10f}', flush=True)


if __name__ == '__main__':
    main()
