# -*- coding: utf-8 -*-
"""
Test-session setup: force an 8-device CPU JAX platform.

Replaces the reference's distributed test harness — ``horovodrun -np N
--mpi pytest ...`` launching N OS processes that must collect tests in
identical order or deadlock (reference README.md:171-179) — with a single
pytest process over 8 virtual CPU devices (SURVEY §4 "TPU-native test
translation"): no collective-ordering flakiness, plain ``pytest`` runs it.

JAX backend selection is lazy, so even if a sitecustomize already imported
jax pinned to a TPU plugin, flipping the config here (before any
``jax.devices()`` call) is sufficient — equivalent to
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

import os
import tempfile

import jax
import pytest

_N_DEVICES = 8

# DDP_TPU_TESTS_ON_TPU=1 keeps the process on its real backend so the
# `tpu`-marked hardware tests (Mosaic compile path) can run:
#   DDP_TPU_TESTS_ON_TPU=1 pytest tests -m tpu
# Everything else assumes the 8-device CPU mesh and is skipped/fails there.
if not os.environ.get('DDP_TPU_TESTS_ON_TPU'):
    # ensure_cpu_devices handles old jax (no jax_num_cpu_devices option)
    # by falling back to the XLA_FLAGS host-platform knob; importing the
    # package also installs the jax.shard_map shim the tests rely on.
    from distributed_dot_product_tpu._compat import ensure_cpu_devices
    ensure_cpu_devices(_N_DEVICES)

# Suite time is dominated by XLA:CPU compiles (~100 distinct jits), not by
# the math — persist compiled executables across runs so the second and
# later `pytest` invocations skip them. Keyed by jax version via the cache
# itself; shared across workers.
_CACHE = os.path.join(tempfile.gettempdir(),
                      f'ddp_tpu_xla_cache_{os.getuid()}')
jax.config.update('jax_compilation_cache_dir', _CACHE)
jax.config.update('jax_persistent_cache_min_compile_time_secs', 0)
jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)

# Retrace sentinel (analysis/retrace.py): ON for the whole suite —
# every decode/serve test runs under its entrypoint's trace-count
# budget, so a per-token retrace storm (the round-5 decode_seq_parallel
# finding) fails the offending test loudly instead of showing up as
# mysterious slowness. Explicit (not just the pytest auto-default) so
# `pytest -p no:cacheprovider tests/...` behaves identically under any
# runner that strips PYTEST_CURRENT_TEST.
os.environ.setdefault('DDP_TPU_RETRACE_SENTINEL', '1')


@pytest.fixture(scope='session')
def devices():
    devs = jax.devices()
    assert len(devs) >= _N_DEVICES, (
        f'expected >= {_N_DEVICES} CPU devices, got {devs}')
    return devs


@pytest.fixture(autouse=True)
def _retrace_isolation():
    """Zero every live trace counter between tests: budgets bound ONE
    test's behavior (compiled steps and their jit caches persist across
    tests, so carried-over counts would charge later tests for earlier
    tests' legitimate traces)."""
    from distributed_dot_product_tpu.analysis import retrace
    retrace.reset()
    yield
