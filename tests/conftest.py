# -*- coding: utf-8 -*-
"""
Test-session setup: force an 8-device CPU JAX platform.

Replaces the reference's distributed test harness — ``horovodrun -np N
--mpi pytest ...`` launching N OS processes that must collect tests in
identical order or deadlock (reference README.md:171-179) — with a single
pytest process over 8 virtual CPU devices (SURVEY §4 "TPU-native test
translation"): no collective-ordering flakiness, plain ``pytest`` runs it.

JAX backend selection is lazy, so even if a sitecustomize already imported
jax pinned to a TPU plugin, flipping the config here (before any
``jax.devices()`` call) is sufficient — equivalent to
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

import jax
import pytest

_N_DEVICES = 8

jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', _N_DEVICES)


@pytest.fixture(scope='session')
def devices():
    devs = jax.devices()
    assert len(devs) >= _N_DEVICES, (
        f'expected >= {_N_DEVICES} CPU devices, got {devs}')
    return devs
