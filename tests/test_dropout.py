# -*- coding: utf-8 -*-
"""
Attention-weight dropout tests.

The keep mask is a pure hash of (seed, batch, global element coords), so
it can be RECOVERED exactly from the kernel itself: with ``v = I`` the
output IS the dropped weight matrix (entries are exactly 0 where
dropped — ``jnp.where`` semantics). That recovered mask feeds a dense
jnp oracle for exact forward and gradient comparison on any backend —
including the regimes where the forward and backward kernels use
DIFFERENT block sizes (large ``d_total``), which a block-seeded PRNG
would get wrong. No reference analog.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.pallas_attention import (
    flash_attention,
)

B, H, T, D = 2, 3, 64, 32
RATE = 0.3

pytestmark = pytest.mark.slow


def _qkv(key=0, t=T, d=D, b=B):
    ks = jax.random.split(jax.random.key(key), 3)
    return tuple(jax.random.normal(kk, (b, H, t, d)) for kk in ks)


def _recover_keep(q, k, seed, rate=RATE, **kw):
    """Dropped-weights trick: v = I gives (m̃ ⊙ a); nonzero ⇔ kept.
    (Entries where a == 0 — e.g. the causal future — are reported as
    dropped, which is harmless: their weight contributes nothing.)"""
    t = k.shape[-2]
    eye = jnp.broadcast_to(jnp.eye(t, dtype=q.dtype),
                           (*k.shape[:-2], t, t))
    w = flash_attention(q, k, eye, dropout_rate=rate, dropout_seed=seed,
                        **kw)
    return w != 0


def _dense(q, k, v, keep, rate=RATE, causal=True, window=None):
    t, tk = q.shape[-2], k.shape[-2]
    s = jnp.einsum('...td,...od->...to', q / np.sqrt(q.shape[-1]), k)
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(tk)[None, :]
    if causal:
        s = jnp.where(rows < cols, -jnp.inf, s)
    if window is not None:
        s = jnp.where(rows - cols >= window, -jnp.inf, s)
    a = jax.nn.softmax(s, axis=-1)
    m = jax.lax.stop_gradient(keep.astype(a.dtype)) / (1.0 - rate)
    return jnp.einsum('...to,...od->...td', a * m, v)


def test_dropout_forward_matches_dense_oracle():
    q, k, v = _qkv()
    keep = _recover_keep(q, k, seed=11, causal=True)
    out = flash_attention(q, k, v, causal=True, dropout_rate=RATE,
                          dropout_seed=11)
    ref = _dense(q, k, v, keep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_dropout_gradients_match_dense_oracle():
    q, k, v = _qkv(key=1)
    keep = _recover_keep(q, k, seed=5, causal=True)

    def f(q, k, v):
        return (flash_attention(q, k, v, causal=True, dropout_rate=RATE,
                                dropout_seed=5) ** 2).sum()

    def f_ref(q, k, v):
        return (_dense(q, k, v, keep) ** 2).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=1e-4)


def test_dropout_mask_blocksize_invariant_gradients():
    """The regression the element-coordinate hash exists for: at
    d_total > 256 the backward uses SMALLER blocks than the forward
    (``_bwd_block_sizes``); the mask must be identical anyway. b=1,
    d=160 (d_total=320) with T=128 exercises exactly that divergence."""
    t, d = 128, 160
    ks = jax.random.split(jax.random.key(9), 3)
    q, k, v = (jax.random.normal(kk, (1, t, d)) for kk in ks)
    eye = jnp.eye(t, dtype=q.dtype)[None]
    w = flash_attention(q, k, eye, dropout_rate=RATE, dropout_seed=3)
    keep = w != 0

    def f(q, k, v):
        return (flash_attention(q, k, v, dropout_rate=RATE,
                                dropout_seed=3) ** 2).sum()

    def f_ref(q, k, v):
        return (_dense(q, k, v, keep, causal=False) ** 2).sum()

    np.testing.assert_allclose(float(f(q, k, v)), float(f_ref(q, k, v)),
                               rtol=1e-5)
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-4, rtol=2e-4)


def test_dropout_zero_rate_is_exact():
    q, k, v = _qkv(key=2)
    out = flash_attention(q, k, v, causal=True, dropout_rate=0.0)
    ref = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


def test_dropout_deterministic_and_seed_sensitive():
    q, k, v = _qkv(key=3)
    kw = dict(causal=True, dropout_rate=RATE)
    a = flash_attention(q, k, v, dropout_seed=1, **kw)
    b = flash_attention(q, k, v, dropout_seed=1, **kw)
    c = flash_attention(q, k, v, dropout_seed=2, **kw)
    assert bool(jnp.array_equal(a, b))
    assert not bool(jnp.array_equal(a, c))


def test_dropout_keep_rate_and_expectation():
    q, k, v = _qkv(key=4)
    keep = _recover_keep(q, k, seed=21, causal=False)
    kept = float(jnp.mean(keep.astype(jnp.float32)))
    assert abs(kept - (1 - RATE)) < 0.02, kept
    # Inverted dropout: averaging over seeds recovers the exact output
    # (non-causal so every row has T keys; loose LLN tolerance).
    exact = flash_attention(q, k, v)
    mean = jnp.stack([
        flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=s)
        for s in range(48)]).mean(0)
    # Loose: the max over B·H·T·D elements of a 1/√48-scaled deviation.
    np.testing.assert_allclose(np.asarray(mean), np.asarray(exact),
                               atol=0.25)


def test_dropout_composes_with_window():
    q, k, v = _qkv(key=6)
    window = 17
    kw = dict(causal=True, window=window)
    keep = _recover_keep(q, k, seed=9, **kw)
    out = flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=9,
                          **kw)
    ref = _dense(q, k, v, keep, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_dropout_validation():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match='dropout_seed'):
        flash_attention(q, k, v, dropout_rate=0.5)
    with pytest.raises(ValueError, match='dropout_rate'):
        flash_attention(q, k, v, dropout_rate=1.0, dropout_seed=0)
    with pytest.raises(ValueError, match='dropout_rate'):
        flash_attention(q, k, v, dropout_rate=-0.1, dropout_seed=0)


def test_dropout_shards_decorrelated_by_offset():
    """Sequence-parallel shards share a replicated seed but pass their
    global row offset — their masks must differ (the hash tracks global
    rows, not shard-local ones)."""
    q, k, _ = _qkv(key=8)
    eye = jnp.broadcast_to(jnp.eye(T, dtype=q.dtype), (B, H, T, T))
    w0 = flash_attention(q, k, eye, causal=True, causal_offset=0,
                         dropout_rate=RATE, dropout_seed=4)
    w1 = flash_attention(q, k, eye, causal=True, causal_offset=T,
                         dropout_rate=RATE, dropout_seed=4)
    # offset=T: every pair is causally visible; compare keep patterns on
    # the lower triangle (visible in both).
    tri = jnp.tril(jnp.ones((T, T), bool))
    k0 = (w0 != 0) & tri
    k1 = (w1 != 0) & tri
    assert not bool(jnp.array_equal(k0, k1))
