# -*- coding: utf-8 -*-
"""
Training-step + driver-entry tests.

The reference has no optimizer/training-step component (its example stops at
``loss.backward()``, reference example.py:31-33); these cover the
framework's sharded train step (DP×SP) and the driver entry points.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_dot_product_tpu import DistributedDotProductAttn
from distributed_dot_product_tpu.parallel.mesh import data_seq_mesh, seq_mesh
from distributed_dot_product_tpu.train import make_train_step


def _setup(mesh_kind):
    if mesh_kind == 'seq':
        mesh, data_axis = seq_mesh(8), None
    else:
        mesh, data_axis = data_seq_mesh(2, 4), 'data'
    dim, heads, t, b = 32, 4, 16, 4
    model = DistributedDotProductAttn(key_dim=dim, num_heads=heads, offset=2)
    x = jax.random.normal(jax.random.key(0), (b, t, dim), jnp.float32)
    target = jax.random.normal(jax.random.key(1), (b, t, dim), jnp.float32)
    mask = jnp.zeros((b, t, t), dtype=bool)
    params = model.init(jax.random.key(2), x, x, x, mask)
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(model, optimizer, mesh, data_axis=data_axis,
                           donate=False)
    return step, params, opt_state, (x, x, x, mask, target)


@pytest.mark.parametrize('mesh_kind', ['seq', 'data_seq'])
def test_loss_decreases(mesh_kind):
    step, params, opt_state, batch = _setup(mesh_kind)
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_sp_and_dpsp_agree():
    """The same data through a pure-SP mesh and a DP×SP mesh must produce
    the same loss trajectory (the sharding must not change the math)."""
    step_a, params, opt_a, batch = _setup('seq')
    step_b, _, opt_b, _ = _setup('data_seq')
    _, _, loss_a = step_a(params, opt_a, batch)
    _, _, loss_b = step_b(params, opt_b, batch)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_graft_entry_single_chip():
    sys.path.insert(0, _REPO_ROOT)
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = jax.block_until_ready(fn(*args))
    assert out.shape == (1, 1024, 512)
    assert bool(jnp.isfinite(out).all())


def test_graft_dryrun_multichip():
    sys.path.insert(0, _REPO_ROOT)
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)   # asserts internally
    __graft_entry__.dryrun_multichip(5)   # odd -> pure SP path


@pytest.mark.slow
def test_graft_dryrun_self_provisions_from_single_device():
    """Reproduce the driver's environment: a process whose JAX sees ONE
    device calls ``dryrun_multichip(8)``. The dryrun must re-exec itself
    onto an 8-device virtual CPU mesh and succeed — round 1 failed exactly
    this (MULTICHIP_r01.json rc=1). Runs in a subprocess so the conftest's
    8-device pin can't mask the condition."""
    import subprocess
    code = ("from distributed_dot_product_tpu._compat import "
            "ensure_cpu_devices; ensure_cpu_devices(1); "
            "import jax; "
            "assert len(jax.devices()) == 1, jax.devices(); "
            "import __graft_entry__ as g; g.dryrun_multichip(8)")
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS',
                        'DDP_TPU_DRYRUN_SUBPROCESS')}
    proc = subprocess.run(
        [sys.executable, '-c', code], cwd=_REPO_ROOT, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stdout
    assert 'dryrun_multichip(8)' in proc.stdout and 'OK' in proc.stdout, \
        proc.stdout
