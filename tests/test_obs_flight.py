# -*- coding: utf-8 -*-
"""
Incident flight recorder (obs/flight.py): zero-alloc disabled path,
hard ring bounds, bundle validity (obs validate / reconstruct / slo run
on the ring JSONL unchanged — including a rotation-boundary source log
and a torn tail), the /dump endpoint, SIGTERM chaining, and the tier-1
acceptance: under the burst+stuck+NaN fault cocktail the watchdog
stall AUTO-dumps a bundle and `obs doctor` classifies the incident —
naming the injected fault kind and the affected request ids/tenants —
from the bundle alone.
"""

import json
import os
import signal
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import doctor as obs_doctor
from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs import flight
from distributed_dot_product_tpu.obs.__main__ import main as obs_main
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_flight_state():
    """Every test starts with no recorder installed, no stray
    providers, and leaves the module state as it found it."""
    prev_recorder = flight.get_recorder()
    prev_providers = dict(flight._PROVIDERS)
    flight.install(None)
    yield
    flight.install(prev_recorder)
    flight._PROVIDERS.clear()
    flight._PROVIDERS.update(prev_providers)


def _emit_lifecycle(log, rid, tenant='default', tokens=2,
                    status='completed'):
    log.emit('serve.admit', request_id=rid, slot=0, tenant=tenant,
             queue_wait=0.0, prompt_len=2, requeues=0)
    for i in range(tokens):
        fields = dict(request_id=rid, slot=0, token_index=i)
        if i == 0:
            fields['ttft'] = 0.01
        else:
            fields['gap'] = 0.002
        log.emit('serve.decode', **fields)
    log.emit('serve.retire', request_id=rid, status=status,
             tokens=tokens, total_seconds=0.02, tenant=tenant)


# -- disabled path -------------------------------------------------------

def test_disabled_recorder_is_shared_null_object():
    """The spans contract: with nothing installed, recorder() returns
    ONE shared null object (no allocation per call), the events tee is
    a plain None, and emitting events records nothing anywhere."""
    a, b = flight.recorder(), flight.recorder()
    assert a is b is flight._NULL
    assert flight.get_recorder() is None
    assert obs_events._TEE is None
    # The null surface is inert end to end.
    assert a.sample() is False
    assert a.maybe_dump(trigger='stall') is None
    assert a.dump_bundle() is None
    assert a.stats()['records'] == 0


def test_install_wires_and_unwires_the_tee(tmp_path):
    rec = flight.FlightRecorder(tmp_path, registry=MetricsRegistry())
    prev = flight.install(rec)
    assert prev is None
    assert flight.recorder() is rec
    assert obs_events._TEE is not None
    flight.install(None)
    assert obs_events._TEE is None
    assert flight.recorder() is flight._NULL


# -- the ring ------------------------------------------------------------

def test_ring_is_hard_bounded_in_records_and_bytes(tmp_path):
    """Both bounds enforced: the record cap caps the deque, the byte
    cap evicts oldest-first even below the record cap; evictions are
    counted, never silent."""
    reg = MetricsRegistry()
    rec = flight.FlightRecorder(tmp_path, max_records=8,
                                max_bytes=100_000, registry=reg)
    for i in range(50):
        rec._add('event', json.dumps({'i': i, 'pad': 'x' * 20}))
    stats = rec.stats()
    assert stats['records'] <= 8
    assert stats['dropped'] == 50 - stats['records']
    # Byte bound alone (record bound loose): oldest evicted until fit.
    rec2 = flight.FlightRecorder(tmp_path, max_records=10_000,
                                 max_bytes=500, registry=reg)
    line = 'y' * 100
    for _ in range(50):
        rec2._add('event', line)
    stats2 = rec2.stats()
    assert stats2['bytes'] <= 500
    assert stats2['records'] == 5
    assert stats2['dropped'] == 45


def test_tee_captures_event_log_emits(tmp_path):
    reg = MetricsRegistry()
    with flight.recording(base_dir=tmp_path, registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        _emit_lifecycle(log, 'r0')
        log.close()
        assert rec.stats()['teed'] == 4
        path = rec.dump_bundle(trigger='manual')
    bundle = flight.load_bundle(path)
    assert [r['event'] for r in bundle['events']] == [
        'serve.admit', 'serve.decode', 'serve.decode', 'serve.retire']
    # The teed lines are byte-identical to what the log wrote.
    with open(tmp_path / 'ev.jsonl', encoding='utf-8') as f:
        assert len(f.read().splitlines()) == 4


# -- bundle validity -----------------------------------------------------

def test_bundle_ring_jsonl_validates_and_reconstructs(tmp_path,
                                                      capsys):
    """The acceptance contract for the ring window: `obs validate
    --require` exits 0 over the bundle's events.jsonl and
    reconstruct() rebuilds complete timelines — INCLUDING when the
    source log rotated mid-window (events spanning path.1 + the live
    file) and when the bundle's own tail is torn."""
    reg = MetricsRegistry()
    with flight.recording(base_dir=tmp_path, registry=reg) as rec:
        # Tiny rotate_bytes: the lifecycle stream spans rotations.
        log = obs.EventLog(tmp_path / 'rot.jsonl', rotate_bytes=400,
                           keep_rotations=5)
        for i in range(6):
            _emit_lifecycle(log, f'r{i}', tenant=f't{i % 2}')
        log.close()
        assert log.rotations >= 1, 'source log never rotated — the ' \
                                   'boundary case is not exercised'
        path = rec.dump_bundle(trigger='manual')

    bundle = flight.load_bundle(path)
    # 1. CLI validation, with required events, over the ring JSONL.
    rc = obs_main(['validate', bundle['events_path'],
                   '--timelines',
                   '--require', 'serve.admit,serve.decode,serve.retire'])
    out = capsys.readouterr().out
    assert rc == 0, out
    # 2. Library reconstruction: every request complete, tenants kept.
    timelines = obs.reconstruct(bundle['events_path'])
    assert set(timelines) == {f'r{i}' for i in range(6)}
    assert all(tl.complete for tl in timelines.values())
    # 3. Goodput accounting runs on the same records unchanged.
    report = obs.goodput(bundle['events'], obs.SloSpec())
    assert report.requests == 6
    assert set(report.per_tenant) == {'t0', 't1'}

    # 4. Torn tail: truncate the bundle's last line mid-record — the
    # readers must tolerate it (crash-mid-dump semantics).
    with open(bundle['events_path'], 'r+', encoding='utf-8') as f:
        data = f.read()
        f.seek(0)
        f.write(data[:-25])
        f.truncate()
    _, errors = obs.validate_file(bundle['events_path'])
    assert errors == []
    timelines = obs.reconstruct(bundle['events_path'])
    assert len(timelines) == 6      # the torn record was r5's retire
    reloaded = flight.load_bundle(path)
    assert len(reloaded['events']) == len(bundle['events']) - 1


def test_bundle_layout_and_manifest(tmp_path):
    reg = MetricsRegistry()
    reg.counter('serve.completed').inc(3)
    flight.add_provider('custom', lambda: {'hello': 'world'})
    with flight.recording(base_dir=tmp_path, registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        _emit_lifecycle(log, 'r0')
        log.close()
        path = rec.dump_bundle(trigger='manual', reason='layout test',
                               sections={'extra': {'k': 1}})
    for fname in ('MANIFEST.json', 'events.jsonl', 'metrics.json',
                  'metric_samples.jsonl', 'device_samples.jsonl',
                  'stacks.json', 'custom.json', 'extra.json'):
        assert os.path.exists(os.path.join(path, fname)), fname
    bundle = flight.load_bundle(path)
    man = bundle['manifest']
    assert man['schema'] == flight.BUNDLE_SCHEMA
    assert man['trigger'] == 'manual'
    assert man['reason'] == 'layout test'
    assert man['event_schema_version'] == obs_events.SCHEMA_VERSION
    assert man['python_version']
    assert bundle['metrics']['counters']['serve.completed'] == 3
    # The forced dump-time sample landed.
    assert len(bundle['metric_samples']) >= 1
    assert len(bundle['device_samples']) >= 1
    assert bundle['sections']['custom'] == {'hello': 'world'}
    assert bundle['sections']['extra'] == {'k': 1}
    # Every live thread (at least this one) has a stack.
    assert any('MainThread' in name for name in bundle['stacks'])


def test_postmortem_dump_event_emitted_and_valid(tmp_path):
    reg = MetricsRegistry()
    log = obs.EventLog(tmp_path / 'ev.jsonl')
    with flight.recording(base_dir=tmp_path, registry=reg) as rec, \
            obs.activate(log):
        path = rec.dump_bundle(trigger='manual')
    log.close()
    records, errors = obs.validate_file(tmp_path / 'ev.jsonl')
    assert errors == []
    dumps = [r for r in records if r['event'] == 'postmortem.dump']
    assert len(dumps) == 1
    assert dumps[0]['trigger'] == 'manual'
    assert dumps[0]['path'] == path


def test_maybe_dump_cooldown_rate_limits_per_trigger(tmp_path):
    reg = MetricsRegistry()
    rec = flight.FlightRecorder(tmp_path, registry=reg,
                                dump_cooldown=3600.0)
    first = rec.maybe_dump(trigger='stall')
    assert first is not None
    assert rec.maybe_dump(trigger='stall') is None     # suppressed
    # A DIFFERENT trigger has its own budget.
    assert rec.maybe_dump(trigger='nan_storm') is not None
    # dump_bundle stays direct (the operator's explicit request).
    assert rec.dump_bundle(trigger='manual') is not None


def test_failed_dump_does_not_consume_the_cooldown(tmp_path):
    """The cooldown anchors on SUCCESS: a dump that failed (disk
    full, unwritable base_dir) must not suppress the retry the
    still-firing trigger requests (regression)."""
    rec = flight.FlightRecorder(tmp_path, registry=MetricsRegistry(),
                                dump_cooldown=3600.0)
    orig = rec.dump_bundle

    def _boom(*args, **kwargs):
        raise OSError('disk full')

    rec.dump_bundle = _boom
    with pytest.raises(OSError):
        rec.maybe_dump(trigger='stall')
    # The failure propagated (the scheduler's _flight_dump logs it)
    # AND left the trigger's budget intact: the retry dumps.
    rec.dump_bundle = orig
    assert rec.maybe_dump(trigger='stall') is not None
    # A SUCCESSFUL dump does consume the budget.
    assert rec.maybe_dump(trigger='stall') is None


def test_load_bundle_rejects_non_bundles(tmp_path):
    with pytest.raises(FileNotFoundError):
        flight.load_bundle(tmp_path)
    (tmp_path / 'MANIFEST.json').write_text('{"schema": 99}')
    with pytest.raises(ValueError, match='schema'):
        flight.load_bundle(tmp_path)
    assert obs_main(['doctor', str(tmp_path)]) == 1


def test_open_from_env(tmp_path):
    assert flight.open_from_env(environ={}) is None
    rec = flight.open_from_env(
        environ={'DDP_TPU_FLIGHT_DIR': str(tmp_path)},
        registry=MetricsRegistry())
    assert rec is not None
    assert rec.base_dir == str(tmp_path)


def test_sample_throttles_on_real_time(tmp_path):
    reg = MetricsRegistry()
    rec = flight.FlightRecorder(tmp_path, registry=reg,
                                sample_interval=3600.0)
    assert rec.sample() is True
    assert rec.sample() is False        # inside the interval
    assert rec.sample(force=True) is True


# -- HTTP /dump ----------------------------------------------------------

def test_dump_endpoint(tmp_path):
    reg = MetricsRegistry()
    srv = obs.MetricsServer(reg).start()
    try:
        # No recorder installed: 404, like the profiler-less /profile.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f'{srv.url}/dump', timeout=60)
        assert exc.value.code == 404
        with flight.recording(base_dir=tmp_path, registry=reg):
            with urllib.request.urlopen(
                    f'{srv.url}/dump?reason=operator+poke',
                    timeout=60) as resp:
                body = json.loads(resp.read())
            assert resp.status == 200
        assert os.path.exists(os.path.join(body['path'],
                                           'MANIFEST.json'))
        man = json.load(open(os.path.join(body['path'],
                                          'MANIFEST.json')))
        assert man['trigger'] == 'http'
        assert man['reason'] == 'operator poke'
    finally:
        srv.stop()


# -- SIGTERM trigger -----------------------------------------------------

def test_sigterm_trigger_dumps_and_chains(tmp_path):
    """install_sigterm dumps a bundle and then calls the PREVIOUS
    handler — the training driver's final-save handler keeps
    working."""
    chained = threading.Event()
    prev = signal.signal(signal.SIGTERM,
                         lambda signum, frame: chained.set())
    rec = flight.FlightRecorder(tmp_path, registry=MetricsRegistry())
    try:
        rec.install_sigterm()
        os.kill(os.getpid(), signal.SIGTERM)
        assert chained.wait(5.0), 'previous SIGTERM handler not chained'
        assert len(rec.dumps) == 1
        assert rec.dumps[0]['trigger'] == 'sigterm'
    finally:
        rec.uninstall_sigterm()
        signal.signal(signal.SIGTERM, prev)


# -- the tier-1 acceptance: cocktail → stall auto-dump → doctor ----------

def _run_cocktail(tmp_path):
    from distributed_dot_product_tpu.serve import (
        KernelEngine, RejectedError, Scheduler, ServeConfig,
    )
    from distributed_dot_product_tpu.utils.faults import (
        ServeFaultInjector, ServeFaultPlan,
    )
    reg = MetricsRegistry()
    log = obs.EventLog(tmp_path / 'ev.jsonl')
    rec = flight.FlightRecorder(tmp_path / 'flight', registry=reg,
                                sample_interval=0.05)
    flight.install(rec)
    try:
        eng = KernelEngine(slots=3, t_max=32, vocab=16, heads=2,
                           head_dim=4, prefill_chunk=4, seed=5,
                           decode_impl='xla')
        # Warm the compiled programs: the watchdog's first stall must
        # be the INJECTED one, not the first-compile pause
        # (examples/serve_lm.py documents the same dance).
        eng.step(np.zeros(3, np.int32), np.ones(3, bool))
        eng.prefill(0, np.asarray([0], np.int32))
        for i in range(3):
            eng.reset(i)
        plan = ServeFaultPlan(stuck_at_step=3, stuck_seconds=0.5,
                              nan_at_step=5, nan_slot=1)
        sched = Scheduler(
            eng,
            ServeConfig(queue_limit=4, max_new_tokens=4,
                        stall_timeout=0.15, watchdog_poll=0.02,
                        evict_before_reject=False),
            fault_injector=ServeFaultInjector(plan), registry=reg,
            event_log=log)
        rng = np.random.default_rng(11)
        rejected = []
        for i in range(14):
            prompt = rng.integers(
                0, 16, size=int(rng.integers(1, 7))).astype(np.int32)
            try:
                sched.submit(prompt, request_id=f'r{i:03d}',
                             tenant='paid' if i % 2 else 'free')
            except RejectedError:
                rejected.append(f'r{i:03d}')
            if i % 3 == 2:      # interleave serving with the burst
                sched.step()
        results = sched.run_until_idle()
        sched.close()
        assert sched.health.stall_events >= 1
        assert reg.snapshot()['counters']['serve.nan_quarantined'] >= 1
        assert rejected, 'burst never overflowed the queue'
        return rec, log, results
    finally:
        flight.install(None)
        log.close()


def test_cocktail_stall_autodumps_bundle_and_doctor_classifies(
        tmp_path, capsys):
    """ISSUE 10 acceptance: burst + stuck step + NaN slot with faults
    ENABLED — the stall auto-dumps a bundle, and `obs doctor`,
    reading NOTHING but that bundle, classifies the incident naming
    the injected fault kind and the affected request ids and
    tenants."""
    rec, log, results = _run_cocktail(tmp_path)

    # The watchdog stall AUTO-dumped (no manual dump call anywhere).
    stall_dumps = [d for d in rec.dumps if d['trigger'] == 'stall']
    assert len(stall_dumps) == 1, rec.dumps
    bundle_path = stall_dumps[0]['path']

    # Doctor runs from the bundle directory alone (CLI surface).
    rc = obs_main(['doctor', bundle_path])
    out = capsys.readouterr().out
    assert rc == 0, out
    # Classification names the injected fault kind...
    assert 'INCIDENT: stuck_step' in out
    assert 'injected fault: stuck_step' in out
    # ...and the affected request ids and tenants.
    assert 'affected requests' in out
    assert 'r00' in out
    assert 'free' in out and 'paid' in out

    # Library surface agrees, with structured evidence.
    incident = obs_doctor.diagnose(bundle_path)
    assert incident.primary == 'stuck_step'
    assert incident.classes['stuck_step']['score'] \
        > incident.classes['overload']['score']
    assert incident.affected['in_flight'], \
        'the slot table at stall time names nobody'
    assert set(incident.tenants) == {'free', 'paid'}

    # An end-of-run bundle (same ring, later window) carries the NaN
    # evidence too: the quarantined request is named.
    final = rec.dump_bundle(trigger='manual', reason='post-run')
    incident2 = obs_doctor.diagnose(final)
    assert incident2.classes['nan_storm']['score'] > 0
    assert incident2.affected['quarantined'], \
        'quarantined request not named'
    quarantined = incident2.affected['quarantined'][0]
    rc = obs_main(['doctor', final])
    out = capsys.readouterr().out
    assert rc == 0
    assert quarantined in out
    # Ring accounting is honest in the MANIFEST.
    man = flight.load_bundle(final)['manifest']
    assert man['ring']['records'] > 0
    assert man['ring']['max_records'] == 2048


def test_doctor_json_output(tmp_path, capsys):
    reg = MetricsRegistry()
    with flight.recording(base_dir=tmp_path, registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        _emit_lifecycle(log, 'r0', status='failed_nan')
        log.emit('serve.quarantine', request_id='r0', slot=0,
                 requeued=False)
        log.close()
        path = rec.dump_bundle(trigger='nan_storm')
    rc = obs_main(['doctor', path, '--json'])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload['primary'] == 'nan_storm'
    assert payload['trigger'] == 'nan_storm'
