# -*- coding: utf-8 -*-
"""
Closed-loop control-plane tests (serve/control.py): watchdog/probe-
driven watermark actuation, elastic decode autoscaling with
drain-by-preempt+requeue, the exactly-once drain audit, and the
acceptance scenario — a seeded ramp trace that breaks the static
config's SLO is held within SLO_BASELINE tolerance by the controlled
topology, with every control action reconstructable from the JSONL
alone.
"""

import collections
import json

import numpy as np
import pytest

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.obs import doctor as obs_doctor
from distributed_dot_product_tpu.obs import slo as obs_slo
from distributed_dot_product_tpu.serve import (
    ControlConfig, Controller, KernelEngine, LoadGenConfig, Scheduler,
    ServeConfig, TopologyConfig, VirtualClock, build_serving,
    default_tenants, generate_trace, run_trace,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

SPEC = obs_slo.SloSpec(ttft=0.25, per_token=0.05)


# -- watermark actuation (single scheduler) -----------------------------

def test_controller_tightens_on_pressure_and_relaxes_on_headroom(
        tmp_path, devices):
    clock = VirtualClock()
    log = obs.EventLog(tmp_path / 'ctl.jsonl', clock=clock)
    eng = KernelEngine(slots=2, t_max=64, vocab=32, heads=2,
                       head_dim=4, prefill_chunk=4, seed=5,
                       decode_impl='xla')
    sched = Scheduler(
        eng, ServeConfig(queue_limit=4, max_new_tokens=8,
                         degrade_watermark=0.75, watchdog=False),
        clock=clock, registry=MetricsRegistry(), event_log=log,
        fault_injector=False)
    ctrl = Controller(
        scheduler=sched,
        config=ControlConfig(interval=0.01, tighten_step=0.2,
                             relax_step=0.2, relax_after=2,
                             min_watermark=0.3),
        clock=clock, event_log=log)
    # Fill the queue to the bound: pressure 1.0 >= tighten_pressure.
    for i in range(4):
        sched.submit([1, 2], request_id=f'p{i}')
    acted = ctrl.tick()
    assert any(a['action'] == 'adjust'
               and a['knob'] == 'degrade_watermark'
               and a['value'] == pytest.approx(0.55) for a in acted)
    assert sched.cfg.degrade_watermark == pytest.approx(0.55)
    assert sched.admission.degrade_watermark == pytest.approx(0.55)
    # The queue bound tightened too (the router-spill knob).
    assert any(a['knob'] == 'queue_limit' and a['value'] == 2
               for a in acted)
    assert sched.admission.queue_limit == 2
    # Gauge mirrors the knob.
    assert ctrl.registry.gauge('control.watermark').value \
        == pytest.approx(0.55)
    # Drain the backlog, then sustained headroom relaxes stepwise.
    while sched.step():
        clock.advance(0.01)
    relaxes = []
    for _ in range(6):
        clock.advance(0.01)
        relaxes += ctrl.tick()
    assert any(a['knob'] == 'degrade_watermark'
               and a['reason'] == 'sustained_headroom'
               for a in relaxes)
    assert sched.cfg.degrade_watermark == pytest.approx(0.75)
    assert sched.admission.queue_limit == 4
    sched.close()
    log.close()
    # Every control action is a schema-clean closed-vocabulary event.
    records, errors = obs.validate_file(log.path)
    assert errors == [], errors
    kinds = collections.Counter(r['event'] for r in records
                                if r['event'].startswith('control.'))
    assert kinds['control.adjust'] == len(ctrl.actions)


def test_controller_needs_exactly_one_target():
    with pytest.raises(ValueError, match='exactly one'):
        Controller(config=ControlConfig())
    with pytest.raises(ValueError, match='interval'):
        ControlConfig(interval=0.0).validate()
    with pytest.raises(ValueError, match='replicas'):
        ControlConfig(min_replicas=2, max_replicas=1).validate()


# -- drain under removal (satellite: exactly-once audit) ----------------

def _topology(clock, log_dir, replicas=2, queue_limit=8,
              max_new_tokens=12):
    topo = TopologyConfig(prefill_pools=0, decode_replicas=replicas,
                          slots=2, t_max=64, page_size=16, vocab=32,
                          heads=2, head_dim=4, seed=0,
                          decode_impl='xla')
    return build_serving(
        topo, serve_config=ServeConfig(queue_limit=queue_limit,
                                       max_new_tokens=max_new_tokens,
                                       watchdog=False),
        clock=clock, log_dir=str(log_dir))


def test_drain_mid_stream_requeues_exactly_once(tmp_path, devices):
    """A decode replica drained mid-stream: every in-flight request
    preempts with the typed drain arc and requeues EXACTLY once; none
    retire twice across the merged logs; the timelines all
    reconstruct."""
    clock = VirtualClock()
    router = _topology(clock, tmp_path)
    rng = np.random.default_rng(3)
    for i in range(8):
        router.submit([int(x) for x in rng.integers(1, 32, size=5)],
                      request_id=f'q{i}', max_new_tokens=10)
    for _ in range(4):          # streams mid-flight on both replicas
        router.step()
        clock.advance(0.002)
    assert all(ld['busy'] for ld in router.loads().values())
    requeued = router.drain_replica('r1')
    assert requeued == 4        # 2 in-flight + 2 queued
    assert [r.name for r in router.pool.replicas] == ['r0']
    while router.step():
        clock.advance(0.002)
    router.close()
    # Every request has a terminal record, and exactly one.
    assert len(router.results) == 8
    assert all(r.status == 'completed'
               for r in router.results.values())
    sources = router.pool.logs()
    assert dict(sources).keys() == {'router', 'r0', 'r1'}
    records = obs.merge_events(sources)
    retires = collections.Counter(
        r['request_id'] for r in records
        if r['event'] == 'serve.retire')
    assert set(retires) == {f'q{i}' for i in range(8)}
    assert all(n == 1 for n in retires.values()), retires
    # The drained replica's log carries one typed preempt per
    # in-flight request (requeued=true, drain=true), nothing silent.
    drains = [r for r in records if r['event'] == 'serve.preempt'
              and r.get('drain')]
    assert len(drains) == 2
    assert all(r['replica'] == 'r1' and r['requeued']
               for r in drains)
    # Each drained request re-admits exactly once more than its
    # pre-drain admissions, and every lifecycle reconstructs.
    tls = obs.reconstruct(sources)
    assert all(tl.complete for tl in tls.values()), [
        (rid, tl.errors) for rid, tl in tls.items()
        if not tl.complete]
    for rec in drains:
        tl = tls[rec['request_id']]
        assert tl.admits == 2 and tl.preempts == 1
        assert set(tl.replicas) == {'router', 'r0', 'r1'}


def test_drain_finalizes_vanished_prefix_rider_typed(tmp_path,
                                                     devices):
    """A drained request whose registered prefix the router no longer
    tracks (the LRU-evicted-while-queued race) must finalize with the
    typed PREFIX_UNREGISTERED reason on the draining member — never a
    silently stripped-prompt resubmission decoding garbage."""
    from distributed_dot_product_tpu.serve import RejectReason

    clock = VirtualClock()
    router = _topology(clock, tmp_path)
    r1 = router._by_name['r1']
    # A prefix registered on r1's engine but absent from the router's
    # reverse map — exactly what an LRU eviction leaves behind.
    pid = r1.engine.register_prefix([1, 2, 3, 4])
    r1.scheduler.submit([5], prefix_id=pid, request_id='rider',
                        max_new_tokens=4)
    assert router.drain_replica('r1') == 0
    res = router.results['rider']
    assert res.status == 'rejected'
    assert res.reason is RejectReason.PREFIX_UNREGISTERED
    router.close()
    tls = obs.reconstruct(router.pool.logs())
    assert tls['rider'].complete, tls['rider'].errors
    assert tls['rider'].reason == 'prefix_unregistered'


def test_drain_refuses_unknown_and_last_replica(tmp_path, devices):
    clock = VirtualClock()
    router = _topology(clock, tmp_path, replicas=1)
    with pytest.raises(KeyError, match='r9'):
        router.drain_replica('r9')
    with pytest.raises(ValueError, match='last'):
        router.drain_replica('r0')
    router.close()


# -- elastic autoscaling ------------------------------------------------

def test_autoscale_up_then_down_with_drain(tmp_path, devices):
    """A ramp trace scales the pool up; the idle tail after the trace
    scales it back down through a drain — every transition a
    closed-vocabulary event, every lifecycle exactly-once."""
    clock = VirtualClock()
    router = _topology(clock, tmp_path, replicas=1, queue_limit=12,
                       max_new_tokens=24)
    ctrl = Controller(
        router=router,
        config=ControlConfig(interval=0.01, scale_up_after=1,
                             scale_down_after=3, max_replicas=3),
        clock=clock, event_log=router.event_log)
    cfg = LoadGenConfig(seed=7, rate=250.0, requests=48,
                        arrival='ramp', ramp_factor=8.0,
                        tenants=default_tenants(2), vocab=32)
    trace = generate_trace(cfg)
    res = run_trace(router, trace, clock,
                    tick_seconds=cfg.tick_seconds, on_tick=ctrl.tick)
    assert res.accounted
    ups = [a for a in ctrl.actions if a['action'] == 'scale'
           and a['direction'] == 'up']
    assert ups, 'the ramp never scaled the pool up'
    assert len(router.pool.replicas) > 1
    # Idle tail: the controller drains back toward min_replicas.
    for _ in range(40):
        router.step()
        ctrl.tick()
        clock.advance(0.002)
    router.close()
    downs = [a for a in ctrl.actions if a['action'] == 'scale'
             and a['direction'] == 'down']
    assert downs, 'sustained idleness never scaled the pool down'
    assert len(router.pool.replicas) == 1
    # Event-log audit: the control history reconstructs from the
    # router's log alone, schema-clean.
    sources = router.pool.logs()
    for _name, path in sources:
        _, errors = obs.validate_file(path)
        assert errors == [], errors
    records = obs.merge_events(sources)
    kinds = collections.Counter(r['event'] for r in records)
    assert kinds['control.scale'] == len(ups) + len(downs)
    assert kinds['control.drain'] == len(downs)
    scale_events = [r for r in records
                    if r['event'] == 'control.scale']
    assert [e['direction'] for e in scale_events] \
        == ['up'] * len(ups) + ['down'] * len(downs)
    assert [e['replicas'] for e in scale_events[:len(ups)]] \
        == list(range(2, 2 + len(ups)))
    # Exactly-once across the whole elastic run.
    retires = collections.Counter(
        r['request_id'] for r in records
        if r['event'] == 'serve.retire')
    assert set(retires) == {rid for rid, _ in res.submitted}
    assert all(n == 1 for n in retires.values())
    tls = obs.reconstruct(sources)
    assert all(tl.complete for tl in tls.values()), [
        (rid, tl.errors) for rid, tl in tls.items()
        if not tl.complete]


def test_controlled_run_is_seeded_deterministic(tmp_path, devices):
    """Same seed, same trace -> byte-identical control history and
    goodput report (the property the CI gate rests on)."""
    def run(tag):
        clock = VirtualClock()
        d = tmp_path / tag
        router = _topology(clock, d, replicas=1, queue_limit=12,
                           max_new_tokens=24)
        ctrl = Controller(
            router=router,
            config=ControlConfig(interval=0.01, scale_up_after=1,
                                 scale_down_after=20,
                                 max_replicas=3),
            clock=clock, event_log=router.event_log)
        cfg = LoadGenConfig(seed=11, rate=250.0, requests=40,
                            arrival='ramp', ramp_factor=8.0,
                            tenants=default_tenants(2), vocab=32)
        run_trace(router, generate_trace(cfg), clock,
                  tick_seconds=cfg.tick_seconds, on_tick=ctrl.tick)
        router.close()
        report = obs_slo.goodput(router.pool.logs(), SPEC)
        return ctrl.actions, report.to_dict()

    actions_a, report_a = run('a')
    actions_b, report_b = run('b')
    assert actions_a == actions_b
    assert json.dumps(report_a, sort_keys=True) \
        == json.dumps(report_b, sort_keys=True)
    assert actions_a, 'the run never exercised the controller'


# -- the acceptance scenario --------------------------------------------

def test_controlled_topology_holds_slo_where_static_breaks(
        tmp_path, devices):
    """ISSUE 15 acceptance: a seeded ramp trace that breaks the
    static config's per-tenant SLO is held within the committed
    SLO_BASELINE.json tolerance by the controlled topology, and the
    control history validates from the log alone."""
    cfg = LoadGenConfig(seed=7, rate=300.0, requests=64,
                        arrival='ramp', ramp_factor=10.0,
                        tenants=default_tenants(2), vocab=32)
    topo_kw = dict(prefill_pools=0, decode_replicas=1, slots=4,
                   t_max=96, page_size=16, vocab=64, heads=2,
                   head_dim=8, seed=0, decode_impl='xla')

    def run(tag, control):
        clock = VirtualClock()
        router = build_serving(
            TopologyConfig(**topo_kw),
            serve_config=ServeConfig(queue_limit=12,
                                     max_new_tokens=24,
                                     watchdog=False),
            clock=clock, log_dir=str(tmp_path / tag))
        ctrl = Controller(
            router=router,
            config=ControlConfig(interval=0.01, scale_up_after=1,
                                 scale_down_after=20,
                                 max_replicas=3),
            clock=clock,
            event_log=router.event_log) if control else None
        res = run_trace(router, generate_trace(cfg), clock,
                        tick_seconds=cfg.tick_seconds,
                        on_tick=(ctrl.tick if ctrl else None))
        router.close()
        assert res.accounted
        return obs_slo.goodput(router.pool.logs(), SPEC), router

    static, _ = run('static', control=False)
    controlled, router = run('ctl', control=True)
    with open('SLO_BASELINE.json', encoding='utf-8') as f:
        base = json.load(f)
    tol = base['tolerances']['tenant_goodput_abs']
    floors = {t: gp - tol for t, gp in base['per_tenant'].items()}
    breached = [t for t in floors
                if static.per_tenant[t]['goodput_pct'] < floors[t]]
    assert breached, (
        'the ramp no longer breaks the static config — re-size it so '
        'the control win stays measurable')
    for t, floor in floors.items():
        assert controlled.per_tenant[t]['goodput_pct'] >= floor, (
            t, controlled.per_tenant[t]['goodput_pct'], floor)
    # The control history is a closed-vocabulary record in the
    # router's log: schema-clean, with the scale arc present.
    router_log = dict(router.pool.logs())['router']
    records, errors = obs.validate_file(router_log)
    assert errors == [], errors
    assert any(r['event'] == 'control.scale'
               and r['direction'] == 'up' for r in records)
    assert any(r['event'] == 'control.adjust' for r in records)


# -- obs doctor learns the control arcs ---------------------------------

def test_doctor_reports_control_arcs():
    events = [
        {'schema': 2, 'seq': i, 'ts': float(i), **e}
        for i, e in enumerate([
            {'event': 'serve.reject', 'request_id': 'x',
             'reason': 'queue_full', 'tenant': 't0'},
            # Drain preempts are membership changes, NOT pool
            # exhaustion: they must not vote cache_exhaustion.
            {'event': 'serve.preempt', 'request_id': 'x', 'slot': 0,
             'requeued': True, 'drain': True},
            {'event': 'control.adjust', 'knob': 'degrade_watermark',
             'value': 0.6, 'reason': 'breach:queue_depth',
             'previous': 0.75},
            {'event': 'control.scale', 'direction': 'up',
             'replicas': 2, 'reason': 'backlog:1.50'},
            {'event': 'control.drain', 'target': 'r1', 'requeued': 3},
            {'event': 'control.scale', 'direction': 'down',
             'replicas': 1, 'reason': 'sustained_idle'},
        ])]
    incident = obs_doctor.diagnose(
        {'manifest': {'trigger': 'manual', 'reason': 'test'},
         'events': events})
    assert incident.primary == 'overload'
    assert incident.classes['cache_exhaustion']['score'] == 0
    evidence = ' | '.join(incident.classes['overload']['evidence'])
    assert 'controller tightened admission' in evidence
    assert 'scaled decode replicas up' in evidence
    assert any('control plane acted' in n for n in incident.notes)
    rendered = obs_doctor.render_incident(incident)
    assert 'controller' in rendered
