# -*- coding: utf-8 -*-
"""
RoPE tests: the rotation identities that make it a *relative* position
encoding, plus shard-layout equivariance (contiguous offset and zigzag
positions must reproduce the full-array rotation exactly).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.rope import rope, rope_seq_parallel

D = 32


def test_rope_relative_property():
    """q_i · k_j after RoPE depends only on (i − j): shifting BOTH
    positions by a constant leaves every logit unchanged."""
    t = 48
    kq, kk = jax.random.split(jax.random.key(0))
    q = jax.random.normal(kq, (t, D))
    k = jax.random.normal(kk, (t, D))
    s0 = rope(q) @ rope(k).T
    s_shift = rope(q, offset=1000) @ rope(k, offset=1000).T
    # atol: f32 angle rounding at position ~1000 is ~1000·2^-24 rad,
    # which propagates to ~1e-3 on a d=32 dot product.
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s_shift),
                               atol=2e-3)


def test_rope_zero_position_is_identity():
    x = jax.random.normal(jax.random.key(1), (4, D))
    out = rope(x, positions=jnp.zeros(4, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_rope_norm_preserving():
    x = jax.random.normal(jax.random.key(2), (2, 16, D))
    out = rope(x, offset=12345)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1),
                               rtol=1e-5)


def test_rope_odd_dim_rejected():
    with pytest.raises(ValueError, match='even'):
        rope(jnp.zeros((4, 5)))


def test_rope_seq_parallel_matches_full():
    """Sharded application with per-shard global offsets == full-array
    RoPE (the thing naive per-shard arange would get wrong)."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.parallel.mesh import seq_mesh

    mesh = seq_mesh(8)
    t = 8 * 16
    x = jax.random.normal(jax.random.key(3), (2, t, D))
    out = jax.jit(jax.shard_map(
        lambda x: rope_seq_parallel(x), mesh=mesh,
        in_specs=P(None, 'seq', None), out_specs=P(None, 'seq', None),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rope(x)),
                               atol=1e-5)


def test_rope_zigzag_positions_match_full():
    """Zigzag layout: feeding the SAME position vectors used for causal
    masking reproduces the full rotation after inverse permutation."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.models.ring_attention import (
        zigzag_indices,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh

    mesh = seq_mesh(8)
    t = 8 * 16
    x = jax.random.normal(jax.random.key(4), (2, t, D))
    idx = zigzag_indices(t, 8)
    pos = jnp.arange(t, dtype=jnp.int32)[idx]

    out_z = jax.jit(jax.shard_map(
        lambda x, p: rope(x, p), mesh=mesh,
        in_specs=(P(None, 'seq', None), P('seq')),
        out_specs=P(None, 'seq', None), check_vma=False))(x[:, idx], pos)
    np.testing.assert_allclose(np.asarray(out_z[:, jnp.argsort(idx)]),
                               np.asarray(rope(x)), atol=1e-5)


def test_rope_then_window_attention_end_to_end():
    """The composition users actually run: RoPE'd q/k through causal
    sliding-window flash attention, sharded == full."""
    from jax.sharding import PartitionSpec as P

    from distributed_dot_product_tpu.ops.pallas_attention import (
        flash_attention,
    )
    from distributed_dot_product_tpu.parallel.mesh import seq_mesh

    mesh = seq_mesh(8)
    t = 8 * 16
    ks = jax.random.split(jax.random.key(5), 3)
    q, k, v = (jax.random.normal(kk, (2, t, D)) for kk in ks)

    def shard_fn(q, k, v):
        tn = q.shape[-2]
        off = jax.lax.axis_index('seq') * tn
        qr = rope_seq_parallel(q)
        kr_local = rope_seq_parallel(k)
        kf = jax.lax.all_gather(kr_local, 'seq', axis=1, tiled=True)
        vf = jax.lax.all_gather(v, 'seq', axis=1, tiled=True)
        return flash_attention(qr, kf, vf, causal=True, causal_offset=off,
                               window=24)

    out = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(None, 'seq', None),) * 3,
        out_specs=P(None, 'seq', None), check_vma=False))(q, k, v)
    ref = flash_attention(rope(q), rope(k), v, causal=True, window=24)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
