# -*- coding: utf-8 -*-
"""
Smoke tests for the benchmark CLI (the driver's measurement surface).

The reference benchmark harness is part of its capability surface
(reference benchmark.py:29-39); ours additionally feeds the per-round
driver artifacts, so a broken flag or record schema would surface only at
measurement time on real hardware. These run every mode end-to-end at tiny
shapes on the CPU mesh in subprocesses (mirroring how run_sweeps.py
invokes it) and validate the appended JSON records.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

# The attn online/ulysses CLI modes fail on jax 0.4.x: their pjit
# lowering emits a PartitionId op the CPU SPMD pipeline of that line
# cannot compile. PINNED to the jax version rather than blanket-xfailed
# so a jax upgrade AUTO-UN-XFAILS them (condition False → the tests run
# and must pass) instead of the marker rotting over a fixed bug.
_JAX_VERSION = tuple(int(p) for p in jax.__version__.split('.')[:3]
                     if p.isdigit())
_PARTITION_ID_XFAIL = pytest.mark.xfail(
    condition=_JAX_VERSION < (0, 5, 0),
    reason=f'PartitionId SPMD lowering, jax {jax.__version__} '
           f'(auto-un-xfails at jax >= 0.5)',
    strict=False)


def _run(tmp_path, name, *bench_args):
    out = tmp_path / f'{name}.json'
    # Strip backend pins AND the serving knobs (DDP_TPU_DECODE_KERNEL /
    # DDP_TPU_FAULT_*): the decode-impl assertions below test the
    # benchmark's own resolution, and an inherited fault plan would
    # inject faults into the benchmarked scheduler.
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS', 'PALLAS_AXON_POOL_IPS')
           and not k.startswith('DDP_TPU_')}
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
    env['PYTHONPATH'] = _REPO + os.pathsep + env.get('PYTHONPATH', '')
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, 'benchmark.py'),
         *bench_args, '--iters', '1', '--file', str(out)],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout
    with open(out) as f:
        records = json.load(f)
    assert len(records) == 1
    return records[0]


def test_nt_mode(tmp_path):
    # scale 2344 -> T = 32 over 8 devices (4 rows per shard).
    rec = _run(tmp_path, 'nt', '--mode', 'nt', '--scale', '2344',
               '--offset', '2')
    assert rec['mode'] == 'nt' and rec['world'] == 8
    assert rec['dist_gflops_per_chip'] > 0
    assert rec['local_gflops'] > 0


def test_all_and_tn_modes(tmp_path):
    rec = _run(tmp_path, 'all', '--mode', 'all', '--scale', '2344',
               '--offset', '2', '--skip-local')
    assert rec['mode'] == 'all' and 'local_gflops' not in rec
    rec = _run(tmp_path, 'tn', '--mode', 'tn', '--scale', '2344',
               '--skip-local')
    assert rec['offset'] is None and rec['impl'] is None


def test_offset_none_and_ring(tmp_path):
    rec = _run(tmp_path, 'ntf', '--mode', 'nt', '--scale', '2344',
               '--offset', 'none', '--skip-local')
    assert rec['offset'] is None
    rec = _run(tmp_path, 'ntr', '--mode', 'nt', '--scale', '2344',
               '--impl', 'ring', '--skip-local')
    assert rec['impl'] == 'ring'


@_PARTITION_ID_XFAIL
def test_attn_mode(tmp_path):
    rec = _run(tmp_path, 'attn', '--mode', 'attn', '--attn-impl', 'online',
               '--scale', '2344', '--skip-local')
    assert rec['attn_impl'] == 'online'
    assert rec['T'] == 24  # 75000 // 2344 = 31, floored to the 8-mesh
    assert rec['dist_gflops_per_chip'] > 0


@_PARTITION_ID_XFAIL
def test_attn_mode_seq_len_override(tmp_path):
    # --seq-len overrides the reference's T = 75000/scale convention
    # (used by the head-dim sweep to pin T exactly).
    rec = _run(tmp_path, 'attn_sl', '--mode', 'attn', '--attn-impl',
               'online', '--seq-len', '64', '--head-dim', '32',
               '--skip-local')
    assert rec['T'] == 64 and rec['head_dim'] == 32


def test_train_mode(tmp_path):
    rec = _run(tmp_path, 'train', '--mode', 'train', '--attn-impl', 'online',
               '--seq-len', '64')
    assert rec['mode'] == 'train' and rec['mask'] is True
    assert rec['step_gflops_per_chip'] > 0
    rec = _run(tmp_path, 'train_nm', '--mode', 'train', '--attn-impl',
               'online', '--seq-len', '64', '--no-mask')
    assert rec['mask'] is False
    rec = _run(tmp_path, 'train_c', '--mode', 'train', '--attn-impl',
               'online', '--seq-len', '64', '--no-mask', '--causal')
    assert rec['causal'] is True and rec['step_gflops_per_chip'] > 0


def test_decode_serve_mode(tmp_path):
    """The serving microbenchmark: scheduler vs bare decode loop on the
    same engine shape, both rates recorded, plus the decode path and
    the engine-surface TTFT row."""
    rec = _run(tmp_path, 'dserve', '--mode', 'decode-serve',
               '--seq-len', '48', '--serve-requests', '4')
    assert rec['mode'] == 'decode-serve'
    assert rec['completed'] == 4
    assert rec['bare_tokens_per_s'] > 0
    assert rec['sched_tokens_per_s'] > 0
    assert rec['decode_impl'] == 'xla'        # auto resolves off-TPU
    assert rec['ttft_ms'] > 0


def test_decode_serve_mode_paged_twin(tmp_path):
    """--cache-mode paged: the fixed-memory twin row — same KV byte
    budget as the slab row, more slots, pool-utilization and
    peak-concurrency columns recorded."""
    rec_s = _run(tmp_path, 'dserve_s', '--mode', 'decode-serve',
                 '--seq-len', '64', '--batch', '2',
                 '--serve-requests', '8')
    rec_p = _run(tmp_path, 'dserve_p', '--mode', 'decode-serve',
                 '--seq-len', '64', '--batch', '2',
                 '--serve-requests', '8', '--cache-mode', 'paged',
                 '--page-size', '8')
    assert rec_s['cache_mode'] == 'slab'
    assert rec_p['cache_mode'] == 'paged'
    # The twin framing: identical KV budget, strictly more concurrency.
    assert rec_p['kv_budget_bytes'] == rec_s['kv_budget_bytes']
    assert rec_p['slots'] > rec_s['slots']
    assert rec_p['max_concurrent'] > rec_s['max_concurrent']
    assert rec_p['pages'] * rec_p['page_size'] \
        == rec_s['slots'] * rec_s['t_max']
    assert 0 < rec_p['pages_used_peak'] <= rec_p['pages']
    # The burst rounds up to whole rounds of `slots` requests.
    assert rec_p['completed'] == rec_p['requests'] >= 8
    assert rec_p['sched_tokens_per_s'] > 0


def test_decode_serve_mode_kernel_path(tmp_path):
    """--decode-impl kernel routes the engine through the fused Pallas
    step (interpreted on CPU) and records it."""
    rec = _run(tmp_path, 'dserve_k', '--mode', 'decode-serve',
               '--seq-len', '48', '--serve-requests', '4',
               '--decode-impl', 'kernel')
    assert rec['decode_impl'] == 'kernel'
    assert rec['completed'] == 4
    assert rec['sched_tokens_per_s'] > 0


def test_decode_mode_kernel_vs_xla_rows(tmp_path):
    """--mode decode grows kernel-vs-XLA rows: one invocation per path,
    each recording its decode_impl and the TTFT/prefill columns."""
    rec_x = _run(tmp_path, 'dec_x', '--mode', 'decode', '--seq-len',
                 '128', '--heads', '2', '--head-dim', '8',
                 '--decode-impl', 'xla', '--decode-chain', '2')
    rec_k = _run(tmp_path, 'dec_k', '--mode', 'decode', '--seq-len',
                 '128', '--heads', '2', '--head-dim', '8',
                 '--decode-impl', 'kernel')
    for rec in (rec_x, rec_k):
        assert rec['mode'] == 'decode'
        assert rec['ms_per_step'] > 0
        assert rec['ttft_ms'] > rec['prefill_ms'] > 0
    assert rec_x['decode_impl'] == 'xla'
    assert rec_k['decode_impl'] == 'kernel'


def test_decode_spec_row(tmp_path):
    """--mode decode --spec ngram: the draft-verify generation row —
    spec and non-spec tokens/s on the same engine/prompts plus the
    amortization telemetry, and the ISSUE-8 CPU acceptance numbers
    (accepted-tokens/step > 2, fewer dispatches than tokens) on the
    repetitive stream. The run itself asserts stream identity before
    recording, so a passing row IS an exactness check."""
    rec = _run(tmp_path, 'dspec', '--mode', 'decode', '--spec', 'ngram',
               '--seq-len', '128', '--heads', '2', '--head-dim', '8')
    assert rec['mode'] == 'decode' and rec['spec'] == 'ngram'
    assert rec['spec_k'] == 4
    assert rec['tokens_per_s'] > 0
    assert rec['baseline_tokens_per_s'] > 0
    assert rec['accepted_per_step'] > 2.0
    assert rec['proposed_per_step'] >= rec['accepted_per_step']
    assert rec['decode_steps'] < rec['baseline_decode_steps']
    assert rec['completed'] == rec['requests'] == 2


def test_train_mode_window(tmp_path):
    rec = _run(tmp_path, 'train_w', '--mode', 'train', '--attn-impl',
               'flash', '--seq-len', '64', '--no-mask', '--causal',
               '--window', '16')
    assert rec['window'] == 16 and rec['step_gflops_per_chip'] > 0


def test_metrics_out_snapshot(tmp_path):
    """--metrics-out writes the observability artifact: the metrics
    snapshot (serve histograms when the mode drives the scheduler,
    span-mirror histograms always) plus the phase-span summary."""
    mpath = tmp_path / 'metrics.json'
    rec = _run(tmp_path, 'dserve_m', '--mode', 'decode-serve',
               '--seq-len', '48', '--serve-requests', '4',
               '--metrics-out', str(mpath))
    assert rec['completed'] == 4
    with open(mpath) as f:
        payload = json.load(f)
    assert payload['mode'] == 'decode-serve'
    assert payload['record']['completed'] == 4
    # Phase spans were collected and mirrored into histograms.
    assert payload['spans']['benchmark.scheduler_burst']['count'] == 1
    assert payload['metrics']['histograms'][
        'span.benchmark.scheduler_burst.seconds']['total_count'] == 1
    # The scheduler's request-latency decomposition is in the snapshot.
    hists = payload['metrics']['histograms']
    assert hists['serve.ttft_seconds']['total_count'] > 0
    assert hists['serve.queue_wait_seconds']['total_count'] > 0


def test_serve_load_topology_mode(tmp_path):
    """--mode serve-load --topology 1x2: the disaggregated row runs the
    trace through the router AND the single-process twin, merges the
    per-member logs, and records both goodputs plus the routing
    telemetry. The per-member JSONL logs must exist and the placements
    must cover every decode replica."""
    logs = tmp_path / 'topo'
    rec = _run(tmp_path, 'topo', '--mode', 'serve-load',
               '--topology', '1x2', '--load-requests', '24',
               '--event-log', str(logs))
    assert rec['topology'] == '1x2'
    assert rec['requests'] == 24
    assert set(rec['routed']) == {'r0', 'r1'}
    assert sum(rec['routed'].values()) + rec['counts']['rejected'] >= 24
    assert rec['handoffs'] >= 1          # the long-prompt tail offloads
    # 2x the capacity on the same trace: the topology never does worse.
    assert rec['goodput_pct'] >= rec['twin_goodput_pct']
    for name in ('router', 'prefill', 'r0', 'r1'):
        assert (logs / f'{name}.jsonl').exists(), name
    assert (logs / 'twin.jsonl').exists()
    assert (logs / 'trace.json').exists()
