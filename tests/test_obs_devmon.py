# -*- coding: utf-8 -*-
"""
Device telemetry + on-demand profiling (obs/devmon.py): memory-stats
gauges over injectable devices, guarded ProfileCapture (one trace at a
time — the /profile endpoint's 409 contract), the profile.capture
event, and the scheduler's adaptive ttft-p99 trigger.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs.devmon import (
    CaptureInFlight, DeviceMonitor, ProfileCapture,
    device_stats_snapshot,
)
from distributed_dot_product_tpu.obs.events import EventLog, activate
from distributed_dot_product_tpu.obs.exporter import (
    MetricsServer, render_prometheus,
)
from distributed_dot_product_tpu.serve import (
    KernelEngine, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs


class FakeDevice:
    platform = 'tpu'
    device_kind = 'fake v9'

    def __init__(self, dev_id, stats):
        self.id = dev_id
        self._stats = stats

    def memory_stats(self):
        if self._stats is None:
            raise NotImplementedError('no stats on this backend')
        return self._stats


# -- DeviceMonitor ------------------------------------------------------

def test_poll_once_fills_labeled_gauges():
    reg = MetricsRegistry()
    devs = [FakeDevice(0, {'bytes_in_use': 5 * 2**20,
                           'peak_bytes_in_use': 9 * 2**20,
                           'bytes_limit': 16 * 2**30,
                           'ignored_key': 'not-a-number'}),
            FakeDevice(1, None)]          # backend without stats
    mon = DeviceMonitor(reg, devices=devs)
    out = mon.poll_once()
    assert set(out) == {'tpu:0'}
    g = reg.gauge('device.memory.bytes_in_use',
                  labels={'device': 'tpu:0'})
    assert g.value == 5 * 2**20
    assert reg.gauge('device.memory.devices_reporting').value == 1
    assert reg.counter('device.memory.polls').value == 1
    text = render_prometheus(reg)
    assert ('ddp_device_memory_bytes_in_use{device="tpu:0"} '
            f'{5 * 2**20}') in text
    assert f'ddp_device_memory_bytes_limit{{device="tpu:0"}} ' \
           f'{16 * 2**30}' in text


def test_gauges_go_nan_when_device_stops_reporting():
    """A device that stops answering must not keep serving its last
    value as if it were live — the gauge flips to NaN (unknown) and
    recovers when the device reports again."""
    import math
    reg = MetricsRegistry()
    dev = FakeDevice(0, {'bytes_in_use': 5})
    mon = DeviceMonitor(reg, devices=[dev])
    mon.poll_once()
    g = reg.gauge('device.memory.bytes_in_use', labels={'device': 'tpu:0'})
    assert g.value == 5
    dev._stats = None                     # backend starts failing
    mon.poll_once()
    assert math.isnan(g.value)
    assert reg.gauge('device.memory.devices_reporting').value == 0
    dev._stats = {'bytes_in_use': 7}      # and recovers
    mon.poll_once()
    assert g.value == 7


def test_monitor_thread_polls_on_interval():
    reg = MetricsRegistry()
    mon = DeviceMonitor(reg, devices=[FakeDevice(0, {'bytes_in_use': 1})],
                        interval=0.01)
    with mon:
        deadline = time.monotonic() + 5.0
        while (reg.counter('device.memory.polls').value < 3
               and time.monotonic() < deadline):
            time.sleep(0.01)
    assert reg.counter('device.memory.polls').value >= 3
    assert mon._thread is None            # stopped cleanly


def test_device_stats_snapshot_shapes():
    snap = device_stats_snapshot(devices=[
        FakeDevice(0, {'bytes_in_use': 7}), FakeDevice(1, None)])
    assert snap[0]['device'] == 'tpu:0'
    assert snap[0]['memory_stats'] == {'bytes_in_use': 7}
    assert snap[1]['memory_stats'] is None
    # Real backend: never raises, CPU reports stats-less devices.
    real = device_stats_snapshot()
    assert len(real) >= 1 and 'device' in real[0]


# -- ProfileCapture -----------------------------------------------------

def _trace_files(path):
    return [os.path.join(r, f) for r, _, fs in os.walk(path) for f in fs]


def test_capture_writes_loadable_trace_and_event(tmp_path):
    import jax.numpy as jnp
    reg = MetricsRegistry()
    prof = ProfileCapture(tmp_path / 'traces', registry=reg,
                          max_seconds=1.0)
    log_path = tmp_path / 'ev.jsonl'
    with activate(EventLog(log_path)) as log:
        info = prof.start(0.05, trigger='unit-test')
        # Device work inside the capture window, so the trace has a
        # device timeline to show.
        jnp.ones((32, 32)).sum().block_until_ready()
        assert prof.join(60.0)
        log.flush()
    assert info['seconds'] == 0.05
    assert info['trigger'] == 'unit-test'
    files = _trace_files(info['path'])
    assert files, 'capture produced no trace files'
    assert any('plugins' in f or f.endswith('.pb') for f in files)
    assert reg.counter('profile.captures').value == 1
    assert reg.gauge('profile.capture_in_flight').value == 0
    records, errors = obs_events.validate_file(str(log_path))
    assert errors == []
    caps = [r for r in records if r['event'] == 'profile.capture']
    assert caps and caps[0]['trigger'] == 'unit-test'
    assert caps[0]['path'] == info['path']


def test_warmup_pays_init_once_and_is_guarded(tmp_path):
    """warmup() pays the profiler's one-time native init (a real
    throwaway start/stop trace) exactly once: the first call warms,
    later calls are no-ops, a real capture also marks the instance
    warmed, and warming is refused while a capture is in flight."""
    reg = MetricsRegistry()
    prof = ProfileCapture(tmp_path / 'traces', registry=reg)
    assert not prof.warmed
    assert prof.warmup() is True
    assert prof.warmed
    assert (tmp_path / 'traces' / 'warmup').exists()
    assert prof.warmup() is False        # idempotent
    # No phantom accounting: warmup is not a capture.
    assert reg.counter('profile.captures').value == 0
    assert reg.gauge('profile.capture_in_flight').value == 0

    # While a capture is in flight, warmup is refused like a second
    # capture (flag forced directly: a real capture's worker can lose
    # the flag fast under profiler contention, making the race
    # untestable end-to-end).
    prof2 = ProfileCapture(tmp_path / 't2', registry=MetricsRegistry())
    prof2._in_flight = True
    with pytest.raises(CaptureInFlight):
        prof2.warmup()
    prof2._in_flight = False
    # A real capture pays the init too: the instance comes out warmed.
    prof2.start(0.01)
    assert prof2.join(60.0)
    assert prof2.warmed
    assert prof2.warmup() is False


def test_capture_seconds_clamped_and_validated(tmp_path):
    prof = ProfileCapture(tmp_path, registry=MetricsRegistry(),
                          max_seconds=0.05, clock=lambda s: None)
    info = prof.start(3600)
    assert info['seconds'] == 0.05       # clamped to max_seconds
    assert prof.join(60.0)
    with pytest.raises(ValueError):
        prof.start(0)
    with pytest.raises(ValueError):
        prof.start(-1)


def test_second_capture_while_in_flight_raises(tmp_path):
    release = threading.Event()
    prof = ProfileCapture(tmp_path, registry=MetricsRegistry(),
                          clock=lambda s: release.wait(60))
    prof.start(0.2)
    try:
        assert prof.busy
        with pytest.raises(CaptureInFlight):
            prof.start(0.2)
    finally:
        release.set()
    assert prof.join(60.0)
    # After the first lands, a new capture is accepted again.
    prof.start(0.01)
    assert prof.join(60.0)


def test_capture_never_reuses_populated_trace_dir(tmp_path):
    """A restarted process sharing base_dir must not hand out a
    directory holding the previous run's trace."""
    base = tmp_path / 'traces'
    stale = base / 'trace-0001'
    stale.mkdir(parents=True)
    (stale / 'old.pb').write_bytes(b'previous run')
    prof = ProfileCapture(base, registry=MetricsRegistry(),
                          clock=lambda s: None)
    info = prof.start(0.01)
    assert prof.join(60.0)
    assert info['path'] != str(stale)
    assert not os.listdir(info['path']) or 'old.pb' not in \
        os.listdir(info['path'])


# -- /profile endpoint --------------------------------------------------

def _get(url):
    # Generous timeout: under a loaded suite the profiler's native
    # start/stop can hold the GIL for seconds; the contract under test
    # is request ORDERING (409 while busy), not endpoint latency.
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.status, resp.read().decode()


def test_profile_endpoint_guarded_concurrency(tmp_path):
    """The 409 contract: a second /profile hit while a capture is in
    flight is refused — never two traces — and the endpoint recovers
    once the capture lands."""
    release = threading.Event()
    started = threading.Event()

    def gated_sleep(seconds):
        started.set()
        release.wait(60)

    reg = MetricsRegistry()
    prof = ProfileCapture(tmp_path / 'traces', registry=reg,
                          clock=gated_sleep)
    with MetricsServer(reg, profiler=prof) as srv:
        code, body = _get(srv.url + '/profile?seconds=0.2')
        assert code == 200
        first = json.loads(body)
        assert first['status'] == 'capturing'
        assert started.wait(60)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/profile?seconds=0.2')
        assert exc.value.code == 409
        # Malformed durations are a client error, not a crash.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/profile?seconds=nope')
        assert exc.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/profile?seconds=-3')
        assert exc.value.code == 400
        release.set()
        assert prof.join(60.0)
        code, body = _get(srv.url + '/profile?seconds=0.01')
        assert code == 200
        assert json.loads(body)['path'] != first['path']
        assert prof.join(60.0)
    assert _trace_files(first['path'])


def test_profile_endpoint_404_without_profiler():
    with MetricsServer(MetricsRegistry()) as srv:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.url + '/profile?seconds=1')
        assert exc.value.code == 404


# -- scheduler adaptive trigger -----------------------------------------

class StubProfiler:
    def __init__(self, busy=False):
        self.busy = busy
        self.calls = []

    def start(self, seconds, **kw):
        self.calls.append((seconds, kw))
        return {'path': 'stub', 'seconds': seconds}


def _run_burst(profiler, **cfg_kw):
    reg = MetricsRegistry()
    eng = KernelEngine(slots=2, t_max=32, vocab=16, heads=2, head_dim=4,
                       prefill_chunk=4, seed=3)
    cfg = ServeConfig(watchdog=False, queue_limit=16, max_new_tokens=4,
                      **cfg_kw)
    sched = Scheduler(eng, cfg, registry=reg, profiler=profiler)
    for i in range(6):
        sched.submit(np.array([1, 2, 3], np.int32), request_id=f'r{i}')
    sched.run_until_idle()
    sched.close()
    return reg


def test_ttft_p99_trigger_fires_once_under_cooldown():
    stub = StubProfiler()
    reg = _run_burst(stub, profile_ttft_p99=0.0, profile_seconds=1.5,
                     profile_cooldown=3600.0)
    assert len(stub.calls) == 1, stub.calls
    seconds, kw = stub.calls[0]
    assert seconds == 1.5
    assert kw['trigger'] == 'serve.ttft_p99'
    assert kw['ttft_p99'] > 0.0
    assert kw['threshold'] == 0.0
    assert reg.counter('serve.profile_triggers').value == 1


def test_trigger_skips_while_capture_in_flight():
    stub = StubProfiler(busy=True)
    reg = _run_burst(stub, profile_ttft_p99=0.0,
                     profile_cooldown=0.0)
    assert stub.calls == []
    assert reg.counter('serve.profile_triggers').value == 0


def test_trigger_disarmed_by_default():
    stub = StubProfiler()
    _run_burst(stub)                     # profile_ttft_p99 defaults None
    assert stub.calls == []


def test_trigger_respects_threshold():
    stub = StubProfiler()
    _run_burst(stub, profile_ttft_p99=3600.0, profile_cooldown=0.0)
    assert stub.calls == []              # p99 never crosses an hour
