# -*- coding: utf-8 -*-
"""
Seeded perf regressions for the perf-gate negative tests
(tests/test_obs_perf.py) and the CLI
(``python -m distributed_dot_product_tpu.obs.perf check --registry
tests.perf_fixtures:regressed``).

One entry, two variants under the SAME registry name:

- ``clean()``     — a decode-shaped step (surgical append + attention
  scores over the whole cache) that stores and streams its cache at
  bf16 with f32 accumulation on the dot — the contract the
  cache-upcast graphlint rule and the decode kernels keep.
- ``regressed()`` — the identical step with the cache WIDENED to f32
  (the upcast persisted into the stored buffer — the form the
  optimizer cannot fold away, unlike a transient ``astype`` pair,
  which XLA simplifies to identity): argument bytes double and the
  compiler-counted bytes accessed / peak memory blow through the
  check tolerances. ``perf check`` against a clean baseline must exit
  1 naming this entry.
"""

import jax
import jax.numpy as jnp

from distributed_dot_product_tpu.analysis.registry import TraceSpec

# Cache big enough that its bytes dominate the program (the regression
# signal must clear the default 25% relative tolerance decisively).
_B, _H, _T, _D = 1, 4, 2048, 16


def _builder(cache_dtype):
    def build():
        def step(cache, q, k):
            cache = jax.lax.dynamic_update_slice(
                cache, k.astype(cache.dtype), (0, 0, 5, 0))
            scores = jax.lax.dot_general(
                q.astype(cache.dtype), cache,
                (((3,), (3,)), ((0, 1), (0, 1))),
                preferred_element_type=jnp.float32)
            return cache, scores

        cache = jnp.zeros((_B, _H, _T, _D), cache_dtype)
        q = jnp.zeros((_B, _H, 1, _D), jnp.bfloat16)
        k = jnp.zeros((_B, _H, 1, _D), jnp.bfloat16)
        return TraceSpec(name='fx.cache_step', fn=step,
                         args=(cache, q, k))

    return build


def clean():
    return {'fx.cache_step': _builder(jnp.bfloat16)}


def regressed():
    return {'fx.cache_step': _builder(jnp.float32)}
