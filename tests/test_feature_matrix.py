# -*- coding: utf-8 -*-
"""
The feature × path matrix (models/features.py) is the single source of
truth — this file holds it to that: every cell is EXECUTED. A truthy cell
must run a tiny sharded forward; a falsy cell must raise ValueError at
module construction. The README table must be the generated one, verbatim.
"""

import os

import jax
import jax.numpy as jnp
import pytest

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.models import features
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD, LEN, DIM = 4, 8, 32
T = WORLD * LEN

pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _inputs():
    kk, kq, kv = jax.random.split(jax.random.key(0), 3)
    return (jax.random.normal(kk, (1, T, DIM)),
            jax.random.normal(kq, (1, T, DIM)),
            jax.random.normal(kv, (1, T, DIM)))


# knob -> (module kwargs, call kwargs). Each activates exactly the knob
# under test (plus its interaction prerequisites, e.g. causal for window).
KNOB_SETUPS = {
    'attn_mask': ({}, {'mask': True}),
    'causal': ({'causal': True}, {}),
    'window': ({'causal': True, 'window': 8}, {}),
    'segment_ids': ({}, {'segment_ids': True}),
    'num_kv_heads': ({'num_heads': 8, 'num_kv_heads': 4}, {}),
    'dropout_rate': ({'dropout_rate': 0.3}, {'dropout_seed': 1}),
    'alibi_slopes': ({'causal': True,
                      'alibi_slopes': (0.5, 0.25, 0.125, 0.0625)}, {}),
    'qk_quant': ({'qk_quant': 'int8'}, {}),
    'use_rope': ({'use_rope': True}, {}),
    'ring_layout=zigzag': ({'causal': True, 'ring_layout': 'zigzag'}, {}),
    'flash_softmax_mode=bounded': ({'flash_softmax_mode': 'bounded'}, {}),
    'offset': ({'offset': 16}, {}),
}


def test_matrix_covers_every_knob():
    assert set(KNOB_SETUPS) == set(features.FEATURE_MATRIX), (
        'every matrix row must have an executable setup here (and vice '
        'versa) — a row this test cannot run is an unverified claim')


@pytest.mark.parametrize('impl', features.IMPLS)
@pytest.mark.parametrize('knob', sorted(KNOB_SETUPS))
def test_matrix_cell_matches_behavior(mesh, knob, impl):
    mod_kw, call_kw = KNOB_SETUPS[knob]
    mod_kw = dict(mod_kw)
    mod_kw.setdefault('num_heads', 4)
    supported = features.supports(knob, impl)

    def build_and_run():
        m = DistributedDotProductAttn(key_dim=DIM, softmax_impl=impl,
                                      **mod_kw)
        k, q, v = _inputs()
        params = m.init(jax.random.key(0), k[:, :LEN], q[:, :LEN],
                        v[:, :LEN], None)
        kw = dict(call_kw)
        mask = None
        if kw.pop('mask', False):
            mask = jnp.zeros((1, T, T), bool).at[:, :, -3:].set(True)
        if kw.pop('segment_ids', False):
            kw['segment_ids'] = (jnp.arange(T)[None, :] // (T // 2)
                                 ).astype(jnp.int32)
        out = apply_seq_parallel(m, params, mesh, k, q, v, mask, **kw)
        assert bool(jnp.all(jnp.isfinite(out)))
        return out

    if supported:
        build_and_run()
    else:
        with pytest.raises(ValueError):
            build_and_run()


def test_readme_table_is_generated():
    readme = os.path.join(os.path.dirname(__file__), '..', 'README.md')
    with open(readme, encoding='utf-8') as f:
        content = f.read()
    table = features.feature_table_markdown()
    assert table in content, (
        'README feature table is stale — regenerate with '
        '`python -m distributed_dot_product_tpu.models.features` and '
        'paste verbatim')
