# -*- coding: utf-8 -*-
"""
Critical-path latency attribution (obs/critpath.py): every request's
causal phase chain reconstructs from the merged JSONL alone with the
phases PARTITIONING its e2e latency exactly (virtual clock → exact to
float rounding), across the hard arcs — ring-decode (`kv_shards`)
scheduler runs, preempt→requeue stalls, typed rejects — plus merge
determinism when three sources tie on `ts`, and the dispatch-floor
fold over `serve.dispatch` records.
"""

import json
import os

import numpy as np
import pytest

from distributed_dot_product_tpu.obs import critpath
from distributed_dot_product_tpu.obs.critpath import (
    PARTITION_TOL, PHASES, attribute, dispatch_floor, profile,
    render_report, summarize_records,
)
from distributed_dot_product_tpu.obs.events import (
    EventLog, merge_events, validate_file,
)
from distributed_dot_product_tpu.serve import (
    KernelEngine, Scheduler, ServeConfig, VirtualClock,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

pytestmark = pytest.mark.obs

VOCAB = 16


def _sched(tmp_path, name='serve.jsonl', *, tick_dt=0.01, slots=2,
           t_max=32, engine_kw=None, **cfg_kw):
    """A virtual-clock scheduler with an attached event log — the
    clock drives BOTH the log ts and the latency stamps, so the
    partition check is exact, not approximate."""
    clock = VirtualClock()
    log = EventLog(tmp_path / name, clock=clock)
    cfg_kw.setdefault('queue_limit', 8)
    cfg_kw.setdefault('max_new_tokens', 6)
    eng_kw = dict(heads=2, head_dim=4, prefill_chunk=4, seed=5,
                  decode_impl='xla')
    eng_kw.update(engine_kw or {})
    eng = KernelEngine(slots=slots, t_max=t_max, vocab=VOCAB, **eng_kw)
    sched = Scheduler(eng, ServeConfig(watchdog=False, **cfg_kw),
                      clock=clock, registry=MetricsRegistry(),
                      fault_injector=False, event_log=log,
                      on_tick=lambda s: clock.advance(tick_dt))
    return sched, clock, log


def _assert_partitions(chains):
    """The module's headline contract, asserted chain by chain."""
    anchored = [c for c in chains.values() if not c.partial]
    assert anchored, 'no chain carried an e2e anchor'
    for c in anchored:
        assert c.ok, (c.request_id, c.errors, c.partition_error)
        assert c.partition_error <= PARTITION_TOL, (
            f'{c.request_id}: sum(phases)={sum(c.phases.values())} '
            f'!= e2e={c.e2e}')
        # Segments are adjacent and cover [submit_ts, terminal_ts].
        for (_, s0, e0), (_, s1, _) in zip(c.segments, c.segments[1:]):
            assert e0 == s1, f'{c.request_id}: gap {e0} -> {s1}'
        if c.segments:
            assert c.segments[0][1] == pytest.approx(c.submit_ts)
        assert set(c.phases) <= set(PHASES)


# -- synthetic arcs: the attribution state machine in isolation ---------

def _rec(seq, ts, event, **fields):
    rec = {'schema': 2, 'seq': seq, 'ts': ts, 'event': event}
    rec.update(fields)
    return rec


def test_synthetic_chain_partitions_exactly():
    """Hand-built lifecycle with known phase durations: queue 1s
    (submit→admit), prefill 2s (admit→first token), decode 3s (the
    inter-token gap), commit 0.5s — the chain must recover those exact
    numbers and sum to the stamped total_seconds."""
    recs = [
        _rec(0, 11.0, 'serve.admit', request_id='r', slot=0,
             tenant='t0', queue_wait=1.0),
        _rec(1, 12.0, 'serve.prefill', request_id='r', slot=0, pos=4),
        _rec(2, 13.0, 'serve.decode', request_id='r', slot=0,
             token_index=0),
        _rec(3, 16.0, 'serve.decode', request_id='r', slot=0,
             token_index=1),
        _rec(4, 16.5, 'serve.retire', request_id='r',
             status='completed', total_seconds=6.5),
    ]
    chains = attribute(recs)
    c = chains['r']
    assert not c.partial and c.ok
    assert c.submit_ts == pytest.approx(10.0)
    assert c.phases == pytest.approx(
        {'queue': 1.0, 'prefill': 2.0, 'decode': 3.0, 'commit': 0.5})
    assert c.e2e == 6.5
    assert c.partition_error <= PARTITION_TOL
    assert c.tenant == 't0'
    assert c.tokens == 2


def test_synthetic_requeue_stall_attributed():
    """A preempt(requeued)→re-admit window is a `stall` segment, not
    queue and not decode — the partition still closes."""
    recs = [
        _rec(0, 1.0, 'serve.admit', request_id='r', slot=0,
             tenant='t'),
        _rec(1, 2.0, 'serve.decode', request_id='r', slot=0,
             token_index=0),
        _rec(2, 3.0, 'serve.preempt', request_id='r', slot=0,
             requeued=True),
        _rec(3, 5.0, 'serve.admit', request_id='r', slot=1,
             tenant='t'),
        _rec(4, 6.0, 'serve.decode', request_id='r', slot=1,
             token_index=1),
        _rec(5, 6.5, 'serve.retire', request_id='r',
             status='completed', total_seconds=6.0),
    ]
    c = attribute(recs)['r']
    assert c.ok and c.stalls == 1
    assert c.phases['stall'] == pytest.approx(2.0)   # preempt→re-admit
    assert c.phases['decode'] == pytest.approx(1.0)
    # The re-admitted attempt re-prefills before its next token.
    assert c.phases['prefill'] == pytest.approx(2.0)
    assert sum(c.phases.values()) == pytest.approx(6.0)


def test_synthetic_reject_collapses_to_queue():
    """A queue-death reject never left the queue: its whole e2e lands
    in the `queue` phase."""
    recs = [
        _rec(0, 4.0, 'serve.reject', request_id='r',
             reason='deadline_exceeded', tenant='t',
             total_seconds=3.0),
    ]
    c = attribute(recs)['r']
    assert not c.partial and c.ok
    assert c.status == 'rejected' and c.reason == 'deadline_exceeded'
    assert c.phases == pytest.approx({'queue': 3.0})


def test_torn_chain_is_partial_never_asserted():
    """No terminal record → best-effort attribution flagged partial;
    profile() counts it but excludes it from partition failures."""
    recs = [
        _rec(0, 1.0, 'serve.admit', request_id='r', slot=0,
             tenant='t', queue_wait=0.5),
        _rec(1, 2.0, 'serve.decode', request_id='r', slot=0,
             token_index=0),
    ]
    c = attribute(recs)['r']
    assert c.partial and c.e2e is None
    prof = profile({'r': c})
    assert prof['partial'] == 1
    assert prof['partition_failures'] == []


def test_handoff_phase_and_real_split():
    """prefill.handoff cuts its own phase; the REAL build/transfer
    stamps ride alongside without entering the virtual partition."""
    recs = [
        _rec(0, 1.0, 'router.route', request_id='r', target='r0'),
        _rec(1, 3.0, 'prefill.handoff', request_id='r', target='r0',
             pages=2, build_seconds=0.25, transfer_seconds=0.125),
        _rec(2, 4.0, 'serve.admit', request_id='r', slot=0,
             tenant='t'),
        _rec(3, 5.0, 'serve.decode', request_id='r', slot=0,
             token_index=0),
        _rec(4, 5.5, 'serve.retire', request_id='r',
             status='completed', total_seconds=5.0),
    ]
    c = attribute(recs)['r']
    assert c.ok
    # queue = submit→route (0.5) + post-handoff wait for a slot (1.0).
    assert c.phases['queue'] == pytest.approx(1.5)
    assert c.phases['handoff'] == pytest.approx(2.0)
    assert c.phases['prefill'] == pytest.approx(1.0)
    assert c.handoff_build == pytest.approx(0.25)
    assert c.handoff_transfer == pytest.approx(0.125)
    assert sum(c.phases.values()) == pytest.approx(5.0)


# -- merge determinism: three sources tying on ts -----------------------

def test_three_source_ts_tie_merge_is_stable(tmp_path):
    """Records from router/prefill/replica logs sharing identical
    virtual timestamps must merge in SOURCE order, every run — the
    attribution is a function of the log set, not of dict/iteration
    luck."""
    t = [10.0]
    clock = lambda: t[0]            # noqa: E731 — frozen clock: ties
    router = EventLog(tmp_path / 'router.jsonl', clock=clock)
    prefill = EventLog(tmp_path / 'prefill.jsonl', clock=clock)
    rep = EventLog(tmp_path / 'r0.jsonl', clock=clock)
    router.emit('router.route', request_id='x', target='r0')
    prefill.emit('prefill.handoff', request_id='x', target='r0',
                 pages=1)
    rep.emit('serve.admit', request_id='x', slot=0, tenant='t')
    t[0] = 11.0
    rep.emit('serve.decode', request_id='x', slot=0, token_index=0)
    t[0] = 11.5
    rep.emit('serve.retire', request_id='x', status='completed',
             total_seconds=1.5)
    for log in (router, prefill, rep):
        log.close()

    sources = [('router', router.path), ('prefill', prefill.path),
               ('r0', rep.path)]
    merged = merge_events(sources)
    ties = [r['replica'] for r in merged if r['ts'] == 10.0]
    assert ties == ['router', 'prefill', 'r0']   # source order, always

    first = attribute(sources)['x']
    again = attribute(list(sources))['x']
    assert first.segments == again.segments
    assert first.ok
    # The tied records collapse to zero-width segments; the decode and
    # commit spans carry all the time.
    assert sum(first.phases.values()) == pytest.approx(1.5)
    assert first.replicas[-1] == 'r0'


# -- real scheduler arcs ------------------------------------------------

def test_scheduler_run_partitions_every_request(tmp_path, devices):
    sched, clock, log = _sched(tmp_path)
    for i in range(5):
        sched.submit(np.asarray([i + 1], np.int32),
                     request_id=f'r{i}')
    results = sched.run_until_idle()
    sched.close()
    log.close()
    assert all(r.status == 'completed' for r in results.values())
    _, errors = validate_file(log.path)
    assert errors == [], errors

    chains = attribute(log.path)
    assert set(chains) == {f'r{i}' for i in range(5)}
    _assert_partitions(chains)
    prof = profile(chains, dispatch=dispatch_floor(log.path))
    assert prof['partition_failures'] == []
    assert prof['phases'].get('decode', 0) > 0
    assert prof['dispatch']['total']['ticks'] > 0
    assert 'phase totals' in render_report(prof)


def test_preempt_requeue_arc_attributes_stall(tmp_path, devices):
    """Page-pool exhaustion preempts a stream; its requeue window must
    land in `stall` and the partition must still close on the ORIGINAL
    submit anchor (the requeue never resets the clock)."""
    sched, clock, log = _sched(
        tmp_path, max_new_tokens=8, max_requeues=6, spec='ngram',
        spec_k=3, evict_before_reject=False,
        engine_kw=dict(cache_mode='paged', page_size=2, pages=5),
        t_max=16)
    sched.submit([1], request_id='a')
    sched.submit([2], request_id='b')
    results = sched.run_until_idle()
    sched.close()
    log.close()
    assert {r.status for r in results.values()} == {'completed'}

    chains = attribute(log.path)
    _assert_partitions(chains)
    stalled = [c for c in chains.values() if c.stalls]
    assert stalled, 'page exhaustion never preempted anyone'
    for c in stalled:
        assert c.phases.get('stall', 0) > 0, (
            'a requeued request must carry stall time')


def test_ring_decode_kv_shards_partitions(tmp_path, devices):
    """ISSUE acceptance: the `kv_shards` ring-decode engine emits the
    same lifecycle vocabulary — attribution neither knows nor cares
    that attention ran as a ring, and the partition stays exact."""
    sched, clock, log = _sched(
        tmp_path, t_max=64,
        engine_kw=dict(cache_mode='paged', page_size=16, pages=None,
                       head_dim=8, kv_shards=2))
    for i in range(3):
        sched.submit(((np.arange(6) * 3 + i) % (VOCAB - 1) + 1)
                     .astype(np.int32), request_id=f'r{i}')
    results = sched.run_until_idle()
    sched.close()
    log.close()
    assert all(r.status == 'completed' for r in results.values())
    chains = attribute(log.path)
    assert len(chains) == 3
    _assert_partitions(chains)
    for c in chains.values():
        assert c.phases.get('decode', 0) > 0


# -- dispatch floor + record-list summarizer ----------------------------

def test_dispatch_floor_folds_serve_dispatch(tmp_path, devices):
    sched, clock, log = _sched(tmp_path)
    sched.submit(np.asarray([1, 2, 3], np.int32), request_id='r')
    res = sched.run_until_idle()
    sched.close()
    log.close()
    from distributed_dot_product_tpu.obs.events import read_events
    recs = read_events(log.path)
    disp_recs = [r for r in recs if r['event'] == 'serve.dispatch']
    assert disp_recs, 'no dispatch-floor records on a decode run'
    for r in disp_recs:
        # The program slice is timed INSIDE the tick window, so the
        # tick wall time bounds it (1ns slack for clock granularity).
        assert 0 <= r['device_seconds'] <= r['tick_seconds'] + 1e-9
        assert r['overhead'] >= 0
        assert 'request_id' not in r

    floor = dispatch_floor(log.path)
    agg = floor['per_replica']['unlabeled']
    assert agg['ticks'] == len(disp_recs)
    assert agg['tokens'] == len(res['r'].tokens)
    assert floor['total']['overhead_per_token'] is not None

    # The ring-style record-list path (flight recorder) agrees.
    prof = summarize_records(recs)
    assert prof['requests'] == 1
    assert prof['partition_failures'] == []
    assert prof['dispatch']['total']['ticks'] == len(disp_recs)


# -- flight-recorder provider + doctor evidence -------------------------

def test_flight_bundle_carries_critpath_section(tmp_path, devices):
    """Post-mortem bundles must answer 'where was the time going' for
    the ring's in-window requests, and `obs doctor` must cite the
    dominant phase as evidence."""
    from distributed_dot_product_tpu.obs import doctor, flight

    clock = VirtualClock()
    log = EventLog(tmp_path / 'serve.jsonl', clock=clock)
    rec = flight.FlightRecorder(base_dir=str(tmp_path),
                                registry=MetricsRegistry())
    flight.install(rec)
    try:
        sched = Scheduler(
            KernelEngine(slots=2, t_max=32, vocab=VOCAB, heads=2,
                         head_dim=4, prefill_chunk=4, seed=5,
                         decode_impl='xla'),
            ServeConfig(watchdog=False, queue_limit=8,
                        max_new_tokens=5),
            clock=clock, registry=MetricsRegistry(),
            fault_injector=False, event_log=log,
            on_tick=lambda s: clock.advance(0.01))
        for i in range(3):
            sched.submit(np.asarray([i + 1], np.int32),
                         request_id=f'r{i}')
        sched.run_until_idle()
        sched.close()
        log.close()
        path = rec.dump_bundle(trigger='manual', reason='test')
    finally:
        flight.install(None)

    crit = json.load(open(os.path.join(path, 'critpath.json')))
    assert crit['requests'] == 3
    assert crit['partition_failures'] == []
    assert crit['dispatch']['total']['ticks'] > 0

    diag = doctor.diagnose(flight.load_bundle(path))
    notes = [n for n in diag.notes if 'critpath' in n]
    assert any('dominant phase' in n for n in notes), diag.notes
    assert any('dispatch overhead' in n for n in notes), diag.notes


def test_flight_provider_empty_without_recorder():
    """The provider never crashes a dump when no recorder is live —
    it reports an empty summary instead."""
    from distributed_dot_product_tpu.obs import flight

    section = flight._critpath_section()
    assert section['requests'] == 0
    assert section['partition_failures'] == []


# -- CLI ----------------------------------------------------------------

def _cli(argv, capsys):
    from distributed_dot_product_tpu.obs.__main__ import main
    rc = main(argv)
    return rc, capsys.readouterr().out


def test_cli_critpath_gates_on_partition(tmp_path, capsys, devices):
    sched, clock, log = _sched(tmp_path)
    sched.submit(np.asarray([1], np.int32), request_id='r')
    sched.run_until_idle()
    sched.close()
    log.close()

    rc, out = _cli(['critpath', str(log.path)], capsys)
    assert rc == 0
    assert 'partition_failures=0' in out
    rc, out = _cli(['critpath', str(log.path), '--json'], capsys)
    assert rc == 0
    prof = json.loads(out)
    assert prof['requests'] == 1 and not prof['partition_failures']
    assert prof['dispatch']['total']['ticks'] > 0
