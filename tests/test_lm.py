# -*- coding: utf-8 -*-
"""
TransformerLM (models/lm.py) — the capstone composition. Contracts:
target construction respects packed-segment boundaries; the sharded LM
train step computes EXACTLY the unsharded cross-entropy loss and
gradient (SGD(1.0) makes the updated params a direct gradient probe);
the copy task trains below threshold on the 8-device mesh and greedy
generation through the KV caches reproduces the prefix; checkpoint /
resume mid-run continues the same trajectory.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_dot_product_tpu import TransformerLM, lm_targets
from distributed_dot_product_tpu.parallel.mesh import (
    data_seq_mesh, seq_mesh,
)
from distributed_dot_product_tpu.train import make_lm_train_step

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, 'examples'))
from train_lm import make_copy_batch  # noqa: E402

VOCAB, DIM, HEADS, LAYERS = 32, 32, 4, 2


def _model(**kw):
    kw.setdefault('vocab_size', VOCAB)
    kw.setdefault('dim', DIM)
    kw.setdefault('num_heads', HEADS)
    kw.setdefault('n_layers', LAYERS)
    return TransformerLM(**kw)


def test_lm_targets_shift_boundaries_and_padding():
    tokens = jnp.asarray([[5, 6, 7, 8, 9, 10]], jnp.int32)
    seg = jnp.asarray([[0, 0, 0, 1, 1, 1]], jnp.int32)
    got = lm_targets(tokens, seg)
    # position 2 is segment 0's last token: must not predict token 8;
    # the final position has no next token.
    np.testing.assert_array_equal(np.asarray(got),
                                  [[6, 7, -1, 9, 10, -1]])
    got_pad = lm_targets(jnp.asarray([[5, 6, 0, 0]], jnp.int32),
                         pad_id=0)
    np.testing.assert_array_equal(np.asarray(got_pad),
                                  [[6, -1, -1, -1]])


def test_lm_forward_shape_and_finite():
    m = _model(attn_kwargs=dict(distributed=False))
    toks = jnp.arange(16, dtype=jnp.int32).reshape(1, 16) % VOCAB
    params = m.init(jax.random.key(0), toks)
    out = m.apply(params, toks)
    assert out.shape == (1, 16, VOCAB)
    assert bool(jnp.isfinite(out).all())


@pytest.mark.parametrize('mesh_kind', ['seq', 'data_seq'])
def test_lm_step_matches_unsharded_loss_and_grad(mesh_kind):
    """SGD(1.0) probe: sharded step's loss AND updated params must equal
    the unsharded cross-entropy's (params - grad) — the loss psum /
    grad psum wiring is exactly the invariant under test."""
    if mesh_kind == 'seq':
        mesh, data_axis = seq_mesh(8), None
    else:
        mesh, data_axis = data_seq_mesh(2, 4), 'data'
    b, t = 2, 64
    tokens, targets, seg = make_copy_batch(jax.random.key(3), b, t,
                                           VOCAB, 16)
    m = _model()
    m_local = _model(attn_kwargs=dict(distributed=False))
    params = m.init(jax.random.key(1), tokens[:, :16])
    opt = optax.sgd(1.0)
    step = make_lm_train_step(m, opt, mesh, data_axis=data_axis,
                              donate=False)
    new_params, _, loss = step(params, opt.init(params),
                               (tokens, targets, seg))

    def local_loss(p):
        logits = m_local.apply(p, tokens, segment_ids=seg)
        valid = targets >= 0
        tgt = jnp.where(valid, targets, 0)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
        return (jnp.sum(jnp.where(valid, nll, 0.0))
                / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0))

    want_loss, g = jax.value_and_grad(local_loss)(params)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    want = jax.tree.map(lambda p, gg: p - gg, params, g)
    for got_l, want_l in zip(jax.tree.leaves(new_params),
                             jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(got_l), np.asarray(want_l),
                                   atol=2e-5, rtol=1e-4)


def test_lm_chunked_nll_matches_unchunked():
    """Chunked cross-entropy (scan + per-chunk remat) is the same math
    — values and gradients — including a chunk that doesn't divide T."""
    m = _model(attn_kwargs=dict(distributed=False))
    tokens, targets, seg = make_copy_batch(jax.random.key(5), 2, 64,
                                           VOCAB, 16)
    params = m.init(jax.random.key(1), tokens[:, :16])

    def loss(p, chunk):
        s, c = m.apply(p, tokens, targets, segment_ids=seg, chunk=chunk,
                       method='nll_sum')
        return s / c

    for chunk in (16, 24, 64, None):
        np.testing.assert_allclose(float(loss(params, chunk)),
                                   float(loss(params, None)), rtol=1e-6)
    g_c = jax.grad(lambda p: loss(p, 24))(params)
    g_u = jax.grad(lambda p: loss(p, None))(params)
    for a, b in zip(jax.tree.leaves(g_c), jax.tree.leaves(g_u)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_greedy_generate_validates_steps():
    from distributed_dot_product_tpu import greedy_generate
    m = _model(attn_kwargs=dict(distributed=False))
    toks = jnp.zeros((1, 4), jnp.int32)
    params = m.init(jax.random.key(0), toks)
    with pytest.raises(ValueError, match='steps'):
        greedy_generate(m, params, toks, steps=0, t_max=8)
    with pytest.raises(ValueError, match='t_max'):
        greedy_generate(m, params, toks, steps=8, t_max=8)


def test_greedy_generate_exact_capacity_boundary():
    """Prefill writes n rows and the loop writes steps − 1 more (the
    first token comes from the prefill logits), so n + steps − 1 ==
    t_max must GENERATE — the earlier check rejected it off by one —
    while one more step must raise."""
    from distributed_dot_product_tpu import greedy_generate
    m = _model(attn_kwargs=dict(distributed=False))
    toks = jnp.zeros((1, 4), jnp.int32)
    params = m.init(jax.random.key(0), toks)
    out = greedy_generate(m, params, toks, steps=5, t_max=8)  # 4+5-1=8
    assert out.shape == (1, 5)
    with pytest.raises(ValueError, match='t_max'):
        greedy_generate(m, params, toks, steps=6, t_max=8)
    # The boundary run used every cache row and the capacity-checked
    # stream equals a roomier run's prefix (no silent tail corruption).
    roomy = greedy_generate(m, params, toks, steps=5, t_max=16)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(roomy))


def test_greedy_generate_reuses_compiled_programs():
    """Two greedy_generate calls with identical (model, shapes) trace
    the prefill and step programs ONCE each — the round-8 recompile
    finding: the old implementation wrapped both in fresh jax.jit
    closures per invocation, so every call paid a full trace. The
    compiled pair now lives in an LRU cache keyed by every
    shape-determining input, and the watchers are retrace-budgeted so
    a regression raises rather than silently rebuilding."""
    from distributed_dot_product_tpu import greedy_generate
    from distributed_dot_product_tpu.analysis import retrace
    m = _model(attn_kwargs=dict(distributed=False))
    # Shapes unique to this test: the program cache is module-global,
    # so reusing another test's (b, n, t_max) would read its entry and
    # vacuously count zero traces.
    toks = jnp.arange(6, dtype=jnp.int32).reshape(2, 3) % VOCAB
    params = m.init(jax.random.key(0), toks)
    before_p = retrace.total('lm.generate_prefill')
    before_s = retrace.total('lm.generate_step')
    first = greedy_generate(m, params, toks, steps=4, t_max=12)
    second = greedy_generate(m, params, toks, steps=4, t_max=12)
    np.testing.assert_array_equal(np.asarray(first), np.asarray(second))
    assert retrace.total('lm.generate_prefill') - before_p == 1
    assert retrace.total('lm.generate_step') - before_s == 1


def test_lm_dropout_requires_seed():
    mesh = seq_mesh(8)
    m = _model(attn_kwargs=dict(dropout_rate=0.1))
    tokens, targets, seg = make_copy_batch(jax.random.key(3), 2, 64,
                                           VOCAB, 16)
    params = m.init(jax.random.key(1), tokens[:, :16])
    opt = optax.adam(1e-3)
    step = make_lm_train_step(m, opt, mesh, donate=False)
    with pytest.raises(ValueError, match='dropout_seed'):
        step(params, opt.init(params), (tokens, targets, seg))


@pytest.mark.slow
def test_lm_copy_task_trains_and_generates_on_mesh():
    """The capstone criterion: copy-region loss below threshold on the
    8-device mesh AND greedy generation through the stacked KV caches
    reproduces the prefix."""
    from train_lm import main
    res = main(['--steps', '250', '--seq-len', '128', '--seg-len', '32',
                '--dim', '64', '--vocab', '32', '--lr', '3e-3',
                '--log-every', '100', '--remat', '--generate'])
    assert res['loss'] < 0.5, f'copy loss stayed high: {res}'
    assert res['acc'] > 0.9, f'generation failed the copy: {res}'


@pytest.mark.slow
def test_lm_checkpoint_resume_continues(tmp_path):
    """Mid-run save → restore must resume the exact trajectory (same
    step counter, same params, loss keeps improving)."""
    from distributed_dot_product_tpu import TrainState, restore, save
    mesh = seq_mesh(8)
    b, t = 2, 64
    m = _model()
    tokens, targets, seg = make_copy_batch(jax.random.key(7), b, t,
                                           VOCAB, 16)
    params = m.init(jax.random.key(1), tokens[:, :16])
    opt = optax.adam(1e-3)
    step = make_lm_train_step(m, opt, mesh, donate=False)
    ost = opt.init(params)
    for i in range(3):
        params, ost, loss0 = step(params, ost, (tokens, targets, seg))
    save(str(tmp_path), TrainState(3, params, ost))

    restored = restore(str(tmp_path), TrainState(0, params, ost))
    assert restored.step == 3
    for a, b_ in zip(jax.tree.leaves(restored.params),
                     jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    p2, o2 = restored.params, restored.opt_state
    losses = []
    for i in range(3, 6):
        p2, o2, loss = step(p2, o2, (tokens, targets, seg))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < float(loss0)
