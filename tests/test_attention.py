# -*- coding: utf-8 -*-
"""
End-to-end attention-module tests.

Port of the reference gradient-test strategy (reference
tests/test_gradient.py, SURVEY §4): the *same module class* with
``distributed=False`` on the full (unsharded) sequence is the ground truth
(reference test_gradient.py:45-47); the distributed run must match its
forward outputs, input gradients (atol 1e-5, reference
test_gradient.py:107-113) and weight gradients. The reference's
"sum of per-rank weight grads == full-sequence weight grad" identity
(reference test_gradient.py:116-121) is implied here: shard_map transposes
the replicated-params spec into exactly that psum.

Extra coverage the reference lacks (SURVEY §4): a non-trivial mask,
``add_bias=True``, and batch size > 1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.attention import (
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD = 4
LENGTH = 5            # per-shard rows (reference used 18, test_gradient.py:18)
T = WORLD * LENGTH
KEY_DIM = 16
QUERY_DIM = 12
VALUE_DIM = 8
BATCH = 2


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _inputs(masked):
    kk, kq, kv = jax.random.split(jax.random.key(0), 3)
    keys = jax.random.normal(kk, (BATCH, T, KEY_DIM), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, T, QUERY_DIM), jnp.float32)
    values = jax.random.normal(kv, (BATCH, T, VALUE_DIM), jnp.float32)
    if masked:
        mask = jax.random.bernoulli(jax.random.key(3), 0.3, (BATCH, T, T))
        mask = mask.at[..., 0].set(False)  # keep every row attendable
    else:
        mask = jnp.zeros((BATCH, T, T), dtype=bool)  # reference example.py:29
    return keys, queries, values, mask


def _modules(num_heads, add_bias, offset, impl='allgather'):
    kwargs = dict(key_dim=KEY_DIM, value_dim=VALUE_DIM, query_dim=QUERY_DIM,
                  num_heads=num_heads, add_bias=add_bias, offset=offset)
    dist = DistributedDotProductAttn(distributed=True, impl=impl, **kwargs)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    return dist, local


@pytest.mark.parametrize('num_heads', [1, 4])   # reference test_gradient.py:42-45
@pytest.mark.parametrize('add_bias', [False, True])
@pytest.mark.parametrize('masked', [False, True])
def test_forward_parity(mesh, num_heads, add_bias, masked):
    dist, local = _modules(num_heads, add_bias, offset=2)
    k, q, v, m = _inputs(masked)
    params = local.init(jax.random.key(42), k, q, v, m)
    out_local = local.apply(params, k, q, v, m)
    out_dist = apply_seq_parallel(dist, params, mesh, k, q, v, m)
    assert out_dist.shape == (BATCH, T, VALUE_DIM)
    np.testing.assert_allclose(np.asarray(out_dist), np.asarray(out_local),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('num_heads', [1, 4])
def test_gradient_parity(mesh, num_heads):
    """Input + weight grads of an MSE-style loss match the full-sequence
    oracle (reference test_gradient.py:90-121)."""
    dist, local = _modules(num_heads, add_bias=False, offset=2)
    k, q, v, m = _inputs(masked=True)
    params = local.init(jax.random.key(7), k, q, v, m)

    def loss_dist(p, k_, q_, v_):
        return jnp.sum(apply_seq_parallel(dist, p, mesh, k_, q_, v_, m) ** 2)

    def loss_local(p, k_, q_, v_):
        return jnp.sum(local.apply(p, k_, q_, v_, m) ** 2)

    gd = jax.grad(loss_dist, argnums=(0, 1, 2, 3))(params, k, q, v)
    gl = jax.grad(loss_local, argnums=(0, 1, 2, 3))(params, k, q, v)
    for got, want in zip(jax.tree.leaves(gd), jax.tree.leaves(gl)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_ring_impl_forward_parity(mesh):
    dist, local = _modules(4, add_bias=False, offset=2, impl='ring')
    k, q, v, m = _inputs(masked=True)
    params = local.init(jax.random.key(42), k, q, v, m)
    np.testing.assert_allclose(
        np.asarray(apply_seq_parallel(dist, params, mesh, k, q, v, m)),
        np.asarray(local.apply(params, k, q, v, m)),
        rtol=1e-5, atol=1e-5)


def test_bad_head_split_raises():
    with pytest.raises(ValueError, match='divisible'):
        DistributedDotProductAttn(key_dim=10, num_heads=4).init(
            jax.random.key(0), *(jnp.zeros((1, 4, 10)),) * 3,
            jnp.zeros((1, 4, 4), bool))


@pytest.mark.parametrize('softmax_impl', ['full', 'online', 'flash',
                                          'ulysses'])
def test_causal_parity_across_impls(mesh, softmax_impl):
    """causal=True must produce identical outputs in every softmax_impl,
    matching the distributed=False oracle — the causal triangle is over
    GLOBAL positions, so shard offsets must be accounted for."""
    num_heads = 4
    kwargs = dict(key_dim=KEY_DIM, value_dim=VALUE_DIM, query_dim=QUERY_DIM,
                  num_heads=num_heads, causal=True, offset=2)
    dist = DistributedDotProductAttn(softmax_impl=softmax_impl, **kwargs)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    k, q, v, m = _inputs(masked=True)
    params = local.init(jax.random.key(42), k, q, v, m)
    out_local = local.apply(params, k, q, v, m)
    out_dist = apply_seq_parallel(dist, params, mesh, k, q, v, m)
    np.testing.assert_allclose(np.asarray(out_dist), np.asarray(out_local),
                               rtol=1e-5, atol=1e-5)


def test_causal_first_row_ignores_future(mesh):
    """With causal=True and no user mask, output row 0 must equal the
    attention over position 0 alone — i.e. v_0 through the projections."""
    kwargs = dict(key_dim=KEY_DIM, value_dim=VALUE_DIM, query_dim=QUERY_DIM,
                  causal=True)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    k, q, v, m = _inputs(masked=False)
    params = local.init(jax.random.key(42), k, q, v, m)
    out = local.apply(params, k, q, v, m)
    # row 0 attends only to col 0 -> context = values_proj(v)[..., 0, :]
    vproj = local.bind(params).values_proj(v)[:, 0]
    comp = local.bind(params).composition(vproj)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(comp),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('softmax_impl', ['full', 'online', 'flash',
                                          'ulysses'])
def test_no_mask_parity_across_impls(mesh, softmax_impl):
    """attn_mask=None (no masking — the reference's all-False-mask case
    without paying for the O(T^2) mask input) must equal the zeros-mask
    run in every impl."""
    num_heads = 4
    kwargs = dict(key_dim=KEY_DIM, value_dim=VALUE_DIM, query_dim=QUERY_DIM,
                  num_heads=num_heads, offset=2)
    dist = DistributedDotProductAttn(softmax_impl=softmax_impl, **kwargs)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    k, q, v, m = _inputs(masked=False)   # all-False mask
    params = local.init(jax.random.key(42), k, q, v, m)
    want = local.apply(params, k, q, v, m)
    got_none = apply_seq_parallel(dist, params, mesh, k, q, v, None)
    np.testing.assert_allclose(np.asarray(got_none), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # local oracle without a mask agrees too
    np.testing.assert_allclose(
        np.asarray(local.apply(params, k, q, v)), np.asarray(want),
        rtol=1e-5, atol=1e-5)


def test_no_mask_causal_train_step(mesh):
    """causal=True with attn_mask=None trains through make_train_step —
    the long-context configuration (no O(T^2) input anywhere on the
    native-causal paths)."""
    import optax
    from distributed_dot_product_tpu.train import make_train_step
    model = DistributedDotProductAttn(key_dim=KEY_DIM, num_heads=4,
                                      causal=True, softmax_impl='online')
    k, q, v, _ = _inputs(masked=False)
    params = model.init(jax.random.key(0), k, k, k, None)
    opt = optax.adam(1e-2)
    step = make_train_step(model, opt, mesh, donate=False)
    p, o, loss = step(params, opt.init(params),
                      (k, k, k, None, jnp.zeros_like(k)))
    assert np.isfinite(float(loss))


@pytest.mark.parametrize('softmax_impl', ['online', 'flash', 'ulysses'])
def test_causal_no_mask_parity(mesh, softmax_impl):
    """causal=True with attn_mask=None — the long-context configuration.
    The distributed flash path must use its global causal_offset (no
    materialized triangle) and still match the unsharded oracle."""
    kwargs = dict(key_dim=KEY_DIM, value_dim=VALUE_DIM, query_dim=QUERY_DIM,
                  num_heads=4, causal=True, offset=2)
    dist = DistributedDotProductAttn(softmax_impl=softmax_impl, **kwargs)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    k, q, v, _ = _inputs(masked=False)
    params = local.init(jax.random.key(42), k, q, v, None)
    want = local.apply(params, k, q, v, None)
    got = apply_seq_parallel(dist, params, mesh, k, q, v, None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda p: jnp.sum(
        apply_seq_parallel(dist, p, mesh, k, q, v, None) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(local.apply(p, k, q, v, None) ** 2))(
        params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=1e-3)
