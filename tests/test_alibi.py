# -*- coding: utf-8 -*-
"""
ALiBi (additive linear position bias) tests: the in-kernel
``slope·(pos_k − pos_q)`` bias against a dense jnp oracle, composed with
the shard offset, explicit positions, windows and GQA. No reference
analog.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.pallas_attention import (
    flash_attention,
)

B, H, D = 2, 4, 16

pytestmark = pytest.mark.slow


def _qkv(t, key=0, h=H):
    ks = jax.random.split(jax.random.key(key), 3)
    return tuple(jax.random.normal(kk, (B, h, t, D)) for kk in ks)


def _slopes(h=H):
    # The classic geometric ALiBi slopes 2^(-8i/h).
    return 2.0 ** (-8.0 * (jnp.arange(h) + 1) / h)


def _oracle(q, k, v, slopes, t, causal=True, offset=0, window=None):
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum('bhtd,bhod->bhto', q * scale, k)
    rows = offset + jnp.arange(q.shape[-2])[:, None]
    cols = jnp.arange(t)[None, :]
    s = s + slopes[None, :, None, None] * (cols - rows)
    if causal:
        s = jnp.where(rows < cols, -jnp.inf, s)
    if window is not None:
        s = jnp.where(rows - cols >= window, -jnp.inf, s)
    a = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhto,bhod->bhtd', a, v)


@pytest.mark.parametrize('t', [64, 100])
def test_alibi_matches_dense_oracle(t):
    q, k, v = _qkv(t)
    sl = _slopes()
    out = flash_attention(q, k, v, causal=True, alibi_slopes=sl)
    ref = _oracle(q, k, v, sl, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_alibi_with_shard_offset():
    t, off = 64, 128
    q, k, v = _qkv(t, key=1)
    kf = jnp.concatenate([k, k, k], axis=-2)
    vf = jnp.concatenate([v, v, v], axis=-2)
    sl = _slopes()
    out = flash_attention(q, kf, vf, causal=True, causal_offset=off,
                          alibi_slopes=sl)
    ref = _oracle(q, kf, vf, sl, 3 * t, offset=off)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_alibi_gradients():
    t = 64
    q, k, v = _qkv(t, key=2)
    sl = _slopes()

    def f(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                alibi_slopes=sl) ** 2).sum()

    def f_ref(q, k, v):
        return (_oracle(q, k, v, sl, t) ** 2).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=1e-4)


def test_alibi_with_positions_layout():
    """Shuffled rows with explicit positions: bias follows GLOBAL
    positions, not buffer order."""
    t = 64
    q, k, v = _qkv(t, key=3)
    sl = _slopes()
    perm = jax.random.permutation(jax.random.key(9), t)
    pos = jnp.arange(t, dtype=jnp.int32)
    out_p = flash_attention(
        q[..., perm, :], k[..., perm, :], v[..., perm, :],
        positions=(pos[perm], pos[perm]), alibi_slopes=sl)
    ref = _oracle(q, k, v, sl, t)
    np.testing.assert_allclose(np.asarray(out_p[..., jnp.argsort(perm), :]),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_alibi_with_window_and_gqa():
    t, window = 64, 13
    q, k, v = _qkv(t, key=4)
    sl = _slopes()
    kg, vg = k[:, ::2], v[:, ::2]     # 2 kv heads
    out = flash_attention(q, kg, vg, causal=True, window=window,
                          alibi_slopes=sl)
    ref = _oracle(q, jnp.repeat(kg, 2, axis=1), jnp.repeat(vg, 2, axis=1),
                  sl, t, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_alibi_bounded_mode_falls_back_exact():
    t = 64
    q, k, v = _qkv(t, key=5)
    sl = _slopes()
    out_b = flash_attention(q, k, v, causal=True, alibi_slopes=sl,
                            softmax_mode='bounded')
    out_e = flash_attention(q, k, v, causal=True, alibi_slopes=sl)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_e),
                               atol=1e-6)


def test_alibi_requires_positions_or_causal():
    q, k, v = _qkv(16)
    with pytest.raises(ValueError, match='alibi'):
        flash_attention(q, k, v, alibi_slopes=_slopes())
