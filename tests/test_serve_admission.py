# -*- coding: utf-8 -*-
"""
Admission control and backpressure (serve/admission.py) — driven
standalone under a virtual clock: typed rejection taxonomy, deadline
handling at submit and in queue, token-budget clamping, and the
degradation watermark. No device work: admission is pure host policy.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu.serve.admission import (
    AdmissionController, RejectReason, RejectedError, Request,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry


class VClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _ctrl(**kw):
    clock = VClock()
    reg = MetricsRegistry()
    kw.setdefault('queue_limit', 4)
    kw.setdefault('t_max', 32)
    kw.setdefault('max_new_tokens', 8)
    return AdmissionController(clock=clock, registry=reg, **kw), clock, reg


def _req(plen=4, max_new=8, deadline=None):
    return Request(prompt=np.arange(plen), max_new_tokens=max_new,
                   deadline=deadline)


def test_prompt_too_long_is_typed():
    ctrl, _, _ = _ctrl(t_max=8)
    with pytest.raises(RejectedError, match='prompt_too_long') as ei:
        ctrl.validate(_req(plen=8))    # leaves no room for one token
    assert ei.value.reason is RejectReason.PROMPT_TOO_LONG
    assert ctrl.reject_count(RejectReason.PROMPT_TOO_LONG) == 1


def test_expired_deadline_rejected_at_submit():
    ctrl, clock, _ = _ctrl()
    clock.advance(10.0)
    with pytest.raises(RejectedError, match='deadline') as ei:
        ctrl.validate(_req(deadline=5.0))
    assert ei.value.reason is RejectReason.DEADLINE_EXCEEDED


def test_token_budget_clamped_to_cap_and_capacity():
    ctrl, _, _ = _ctrl(t_max=12, max_new_tokens=8)
    r = _req(plen=4, max_new=100)
    ctrl.validate(r)
    assert r.max_new_tokens == 8          # config cap
    r2 = _req(plen=10, max_new=8)
    ctrl.validate(r2)
    assert r2.max_new_tokens == 2         # cache capacity t_max - plen


def test_queue_full_is_typed_and_counted():
    ctrl, _, _ = _ctrl(queue_limit=2)
    ctrl.push(_req())
    ctrl.push(_req())
    assert ctrl.full and ctrl.pressure == 1.0
    with pytest.raises(RejectedError, match='queue_full') as ei:
        ctrl.push(_req())
    assert ei.value.reason is RejectReason.QUEUE_FULL
    assert ctrl.reject_count(RejectReason.QUEUE_FULL) == 1


def test_degradation_watermark_caps_budget():
    """Above the watermark new requests are admitted with a REDUCED
    budget — rung one of the ladder, before any shedding."""
    ctrl, _, reg = _ctrl(queue_limit=4, degrade_watermark=0.5,
                         degraded_max_new_tokens=2)
    below = _req(max_new=8)
    ctrl.validate(below)
    ctrl.maybe_degrade(below)
    assert not below.degraded and below.max_new_tokens == 8
    ctrl.push(_req())
    ctrl.push(_req())                     # pressure now 0.5
    above = _req(max_new=8)
    ctrl.validate(above)
    ctrl.maybe_degrade(above)
    assert above.degraded and above.max_new_tokens == 2
    assert reg.snapshot()['counters']['serve.degraded'] == 1


def test_queue_expiry_is_loud():
    """Requests whose deadline passes while QUEUED come back from
    pop_ready as expired (typed, counted) — never silently skipped."""
    ctrl, clock, _ = _ctrl()
    doomed = _req(deadline=1.0)
    ok = _req(deadline=50.0)
    ctrl.push(doomed)
    ctrl.push(ok)
    clock.advance(2.0)
    req, expired = ctrl.pop_ready()
    assert req is ok
    assert expired == [doomed]
    assert ctrl.reject_count(RejectReason.DEADLINE_EXCEEDED) == 1


def test_cancelled_queued_request_surfaces_on_pop():
    ctrl, _, _ = _ctrl()
    gone = _req()
    gone.cancelled = True
    ctrl.push(gone)
    req, expired = ctrl.pop_ready()
    assert req is None and expired == [gone]


def test_push_front_bypasses_bound():
    """Requeued (already-admitted) work is never dropped by capacity."""
    ctrl, _, _ = _ctrl(queue_limit=1)
    ctrl.push(_req())
    retry = _req()
    ctrl.push_front(retry)                # full, but admitted work
    assert ctrl.depth == 2
    req, _ = ctrl.pop_ready()
    assert req is retry                   # retries go first


def test_queue_depth_gauge_tracks():
    ctrl, _, reg = _ctrl()
    ctrl.push(_req())
    ctrl.push(_req())
    assert reg.snapshot()['gauges']['serve.queue_depth'] == 2
    ctrl.pop_ready()
    assert reg.snapshot()['gauges']['serve.queue_depth'] == 1
