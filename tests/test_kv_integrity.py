# -*- coding: utf-8 -*-
"""
KV page integrity (ISSUE-17): checksummed transfers, corruption chaos
and self-healing replay. Every KV page transfer is end-to-end
verifiable — host-side CRC digests recorded at TRANSFER boundaries
(registry fills, slab handoff, ``adopt_prefix``, recovery replay),
never inside a compiled decode step — and every detected corruption
self-heals: the dirty pages quarantine (never re-enter the free
list), every prefix built on them invalidates cluster-wide, and every
victim stream replays through the PR-16 recovery ledger on a clean
replica, bit-identical to a corruption-free run, or terminates as the
typed ``KV_CORRUPT`` reject. The seeded fuzz sweep at the bottom pins
the acceptance bar: a single flipped bit in any live tracked page is
detected at the next transfer/scrub boundary, BEFORE any token reads
the poisoned page. The prefill pool's own failure domain rides along:
killed mid-trace it is probed like a replica, declared with a typed
``prefill.lost``, and routing falls back to flat prefill — no stream
ever blocks on a dead pool.
"""


import numpy as np
import pytest

import jax.numpy as jnp

from distributed_dot_product_tpu import obs
from distributed_dot_product_tpu.models.decode import PageChecksums
from distributed_dot_product_tpu.obs import anomaly as obs_anomaly
from distributed_dot_product_tpu.obs import doctor as obs_doctor
from distributed_dot_product_tpu.obs import flight as obs_flight
from distributed_dot_product_tpu.obs.events import EventLog
from distributed_dot_product_tpu.obs.timeline import reconstruct
from distributed_dot_product_tpu.serve import (
    KernelEngine, PrefillPool, RejectReason, RouterConfig, ServeConfig,
    TopologyConfig, VirtualClock, build_serving,
)
from distributed_dot_product_tpu.serve.engine import PageCorruptionError
from distributed_dot_product_tpu.utils.faults import (
    ChaosSpecError, chaos_plan_from_env,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry


def _topo(replicas=2, slots=2, t_max=64, page_size=16, vocab=32, **kw):
    return TopologyConfig(decode_replicas=replicas, slots=slots,
                          t_max=t_max, page_size=page_size,
                          vocab=vocab, seed=3, **kw)


def _serving(tmp_path, clock, *, chaos=None, replicas=2,
             threshold=100, queue_limit=8, max_new=6, slots=2,
             topo_kw=None, **router_kw):
    """A serving topology with FAST probes and an every-tick integrity
    scrub on the virtual clock — detection must land inside a
    test-sized run."""
    router_kw.setdefault('probe_interval', 0.02)
    router_kw.setdefault('probe_backoff_max', 0.04)
    router_kw.setdefault('integrity_interval', 0.0)
    return build_serving(
        _topo(replicas=replicas, slots=slots, **(topo_kw or {})),
        serve_config=ServeConfig(watchdog=False,
                                 queue_limit=queue_limit,
                                 max_new_tokens=max_new),
        router_config=RouterConfig(prefill_threshold=threshold,
                                   **router_kw),
        clock=clock, log_dir=tmp_path / 'logs', chaos=chaos)


def _settle(router, clock, dt=0.01, max_ticks=5000):
    ticks = 0
    while router.step():
        clock.advance(dt)
        ticks += 1
        assert ticks < max_ticks, 'topology never settled'
    return router.results


def _member(router, name):
    return next(r for r in router.pool.replicas if r.name == name)


def _events(router, name='router'):
    return list(obs.read_events(dict(router.pool.logs())[name]))


def _long_prompt(length=18, salt=0):
    return list(((np.arange(length) * 5 + salt) % 31) + 1)


def _flip_bit(eng, page, rng):
    """Flip one random bit of ``page``'s K or V buffer host-side — a
    device round-trip outside every compiled program, exactly what the
    chaos knob does."""
    k_pool = np.array(eng.cache.k_pool)
    v_pool = np.array(eng.cache.v_pool)
    buf = k_pool if rng.rand() < 0.5 else v_pool
    flat = buf[int(page)].reshape(-1).view(np.uint8)
    flat[int(rng.randint(len(flat)))] ^= np.uint8(
        1 << int(rng.randint(8)))
    # jnp.array, not asarray: the replaced buffers must own their
    # bytes — the next decode step donates them back to XLA.
    eng.cache = eng.cache._replace(k_pool=jnp.array(k_pool),
                                   v_pool=jnp.array(v_pool))


def _paged_engine(pages=16, slots=2, t_max=64, **kw):
    return KernelEngine(slots=slots, t_max=t_max, vocab=32, seed=3,
                        decode_impl='xla', cache_mode='paged',
                        page_size=16, pages=pages, **kw)


# -- the checksum table and the quarantine set --------------------------

def test_page_checksums_record_verify_drop(devices):
    """The table's full life: record declares content canonical,
    verify names exactly the tampered pages, drop forgets."""
    eng = _paged_engine()
    pid = eng.register_prefix(_long_prompt(20))
    pages, _ = eng._prefix_registry[pid]
    assert sorted(eng.checksums.pages()) == sorted(int(p)
                                                   for p in pages)
    assert eng.verify_pages() == []

    rng = np.random.RandomState(0)
    _flip_bit(eng, pages[0], rng)
    assert eng.verify_pages() == [int(pages[0])]
    assert eng.verify_pages([pages[1]]) == []   # the other page clean
    assert eng.verify_prefix(pid) == [int(pages[0])]
    with pytest.raises(PageCorruptionError) as exc:
        eng.check_pages(pages, 'attach')
    assert exc.value.site == 'attach'
    assert exc.value.pages == [int(pages[0])]

    # Unrecorded pages are out of coverage — skipped, not failures.
    eng.checksums.drop([pages[0]])
    assert eng.verify_pages() == []
    assert eng.verify_seconds > 0.0


def test_checksums_cover_the_int8_mirror(devices):
    """A mirror-carrying cache digests the int8 K mirror too: rot in
    the quantized copy (the tensor the fused kernel actually reads) is
    detected even when the float K/V are pristine."""
    from distributed_dot_product_tpu.models.decode import (
        init_paged_cache,
    )
    cache = init_paged_cache(2, 2, 64, 8, pages=4, page_size=16,
                             dtype=jnp.float32, qk_quant='int8')
    table = PageChecksums()
    table.record(cache, [0, 1])
    assert table.verify(cache) == []
    kq = np.array(cache.k_q_pool)
    kq[0].reshape(-1)[0] ^= 1
    cache = cache._replace(k_q_pool=jnp.asarray(kq))
    assert table.verify(cache) == [0]
    assert table.verify(cache, [1]) == []


def test_quarantined_page_never_reallocated(devices):
    """The quarantine set's one invariant: a page with a corruption
    verdict never re-enters the free list — not while referenced, not
    when its last reference drops, not via a direct alloc sweep."""
    eng = _paged_engine(pages=4)
    pid = eng.register_prefix(_long_prompt(20))     # 2 pages
    pages, _ = eng._prefix_registry[pid]
    victim = int(pages[0])

    assert eng.quarantine_pages([victim]) == [victim]
    assert eng.quarantine_pages([victim]) == []     # idempotent
    assert victim in eng.pool.quarantined
    assert victim not in eng.checksums              # digest dropped

    eng.unregister_prefix(pid)                      # last ref drops
    assert victim not in eng.pool._free
    got = [eng.pool.alloc() for _ in range(eng.pool.free_pages)]
    assert victim not in got
    assert eng.cache_stats()['pages_quarantined'] == 1

    # A FREE page quarantines too — straight off the free list.
    free_victim = next(p for p in got if p is not None)
    for p in got:
        eng.pool.release_pages([p])
    assert eng.quarantine_pages([free_victim]) == [free_victim]
    assert free_victim not in eng.pool._free


# -- transfer boundaries raise before any token reads the page ----------

def test_adopt_prefix_rejects_a_corrupted_source(tmp_path, devices):
    """Slab handoff, source side: the prefill pool's pages are
    verified against ITS table before one byte copies — a poisoned
    source never lands in the destination pool."""
    pool = PrefillPool(t_max=64, page_size=16, vocab=32, seed=3,
                       event_log=EventLog(tmp_path / 'p.jsonl'))
    eng = _paged_engine()
    handle = pool.build(_long_prompt(20))
    _flip_bit(pool.engine, handle.pages[0], np.random.RandomState(1))
    with pytest.raises(PageCorruptionError) as exc:
        eng.adopt_prefix(pool.engine.cache, handle.pages,
                         handle.length,
                         src_checksums=pool.engine.checksums)
    assert exc.value.site == 'handoff_src'
    assert len(eng._prefix_registry) == 0   # nothing half-adopted
    pool.release(handle)


def test_adopt_prefix_rejects_a_corrupted_copy(devices):
    """Slab handoff, destination side: the LANDED copy re-digests
    against the source's kv_crc. A lying source table (digest matches
    nothing the copy produced — the wire-corruption stand-in) is
    caught after the copy, and the half-adopted prefix is rolled back
    out of the registry."""
    class _LyingChecksums:
        def __init__(self, real):
            self._real = real

        def verify(self, cache, pages):
            return []                        # source "looks" clean

        def get(self, page):
            want = self._real.get(page)
            return None if want is None else (want[0] ^ 1, want[1])

    src = _paged_engine()
    pid = src.register_prefix(_long_prompt(20))
    pages, length = src._prefix_registry[pid]
    dst = _paged_engine()
    with pytest.raises(PageCorruptionError) as exc:
        dst.adopt_prefix(src.cache, pages, length,
                         src_checksums=_LyingChecksums(src.checksums))
    assert exc.value.site == 'handoff_copy'
    assert len(dst._prefix_registry) == 0


def test_attach_and_fork_verify_before_sharing(devices):
    """The two sharing boundaries: attaching a sequence to a
    registered prefix and CoW-forking a slot both verify the shared
    pages FIRST — a rider never decodes from rot."""
    eng = _paged_engine(slots=3, pages=16)
    pid = eng.register_prefix(_long_prompt(20))
    assert eng.start_with_prefix(0, pid)
    rng = np.random.RandomState(2)
    _flip_bit(eng, eng._prefix_registry[pid][0][0], rng)

    with pytest.raises(PageCorruptionError) as exc:
        eng.start_with_prefix(1, pid)
    assert exc.value.site == 'attach'
    with pytest.raises(PageCorruptionError) as exc:
        eng.fork_slot(0, 2)                 # slot 0 shares the page
    assert exc.value.site == 'fork'


# -- the router's containment arc ---------------------------------------

def test_scrub_detects_quarantines_and_heals_bit_identical(tmp_path,
                                                           devices):
    """ISSUE-17 acceptance in miniature: a bit flips in a live shared
    prefix page while the stream riding it decodes. The per-tick scrub
    detects it, the page quarantines, the prefix invalidates, the
    victim is expelled WITHOUT a terminal and healed on the clean
    replica through the recovery ledger — bit-identical to a
    corruption-free twin, TTFT still anchored at the original submit.
    The dirty replica STAYS ALIVE (it lost pages, not its process)."""
    prompt = _long_prompt(18)

    clock_twin = VirtualClock()
    twin = _serving(tmp_path / 'twin', clock_twin, replicas=1,
                    threshold=4, max_new=8)
    try:
        twin.submit(prompt, request_id='v')
        base = _settle(twin, clock_twin)
    finally:
        twin.close()
    assert base['v'].status == 'completed'

    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=4, max_new=8)
    try:
        router.submit(prompt, request_id='v')
        router.step()                   # handoff lands, decode starts
        clock.advance(0.01)
        target = router._ledger['v']['replica']
        eng = _member(router, target).engine
        tracked = eng.checksums.pages()
        assert tracked, 'handoff registered no pages'
        _flip_bit(eng, tracked[0], np.random.RandomState(3))
        results = _settle(router, clock)
    finally:
        router.close()

    assert results['v'].status == 'completed'
    assert results['v'].tokens == base['v'].tokens
    # The dirty replica is a full citizen minus its poisoned pages.
    assert {r.name for r in router.pool.replicas} == {'r0', 'r1'}
    assert tracked[0] in eng.pool.quarantined
    assert eng._prefix_registry == {}   # prefix invalidated
    counters = router.registry.snapshot()['counters']
    assert counters['router.kv_corrupt'] == 1

    revs = _events(router)
    corrupt = [r for r in revs if r['event'] == 'kv.corrupt']
    assert len(corrupt) == 1
    assert corrupt[0]['target'] == target
    assert corrupt[0]['site'] == 'scrub'
    assert tracked[0] in corrupt[0]['pages']
    healed = [r for r in revs if r['event'] == 'request.recovered']
    assert len(healed) == 1 and healed[0]['requeued']
    assert healed[0]['reason'] == 'kv_corrupt'
    assert healed[0]['request_id'] == 'v'
    # No replica.lost: corruption containment is not a crash.
    assert not [r for r in revs if r['event'] == 'replica.lost']

    tls = reconstruct(router.pool.logs())
    assert tls['v'].complete, tls['v'].errors
    assert tls['v'].corruptions == 1 and tls['v'].recoveries == 1


def test_corruption_past_budget_is_a_typed_terminal(tmp_path, devices):
    """``max_recoveries=0``: the victim of a corruption that cannot
    heal terminates as the typed KV_CORRUPT reject — accounted,
    complete in the timeline, never a silent drop."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=4, max_new=8,
                      max_recoveries=0)
    try:
        router.submit(_long_prompt(18), request_id='v')
        router.step()
        clock.advance(0.01)
        target = router._ledger['v']['replica']
        eng = _member(router, target).engine
        _flip_bit(eng, eng.checksums.pages()[0],
                  np.random.RandomState(4))
        results = _settle(router, clock)
    finally:
        router.close()
    assert results['v'].status == 'rejected'
    assert results['v'].reason is RejectReason.KV_CORRUPT
    counters = router.registry.snapshot()['counters']
    assert counters['router.rejected.kv_corrupt{tenant=default}'] == 1
    tls = reconstruct(router.pool.logs())
    assert tls['v'].complete, tls['v'].errors
    assert tls['v'].status == 'rejected'
    assert tls['v'].reason == 'kv_corrupt'


def test_corruption_auto_dumps_flight_bundle(tmp_path, devices):
    """A corruption verdict is a postmortem moment: the router dumps
    the armed flight recorder with trigger ``kv_corrupt``."""
    with obs_flight.recording(base_dir=tmp_path / 'flight',
                              registry=MetricsRegistry()) as rec:
        clock = VirtualClock()
        router = _serving(tmp_path, clock, threshold=4, max_new=8)
        try:
            router.submit(_long_prompt(18), request_id='v')
            router.step()
            clock.advance(0.01)
            target = router._ledger['v']['replica']
            eng = _member(router, target).engine
            _flip_bit(eng, eng.checksums.pages()[0],
                      np.random.RandomState(5))
            _settle(router, clock)
        finally:
            router.close()
        dumps = [d for d in rec.dumps if d['trigger'] == 'kv_corrupt']
    assert len(dumps) == 1
    bundle = obs_flight.load_bundle(dumps[0]['path'])
    assert any(r.get('event') == 'kv.corrupt'
               for r in bundle.get('events', []))


# -- the prefill pool is a failure domain too ---------------------------

def test_prefill_crash_falls_back_to_flat_prefill(tmp_path, devices):
    """Kill the pool mid-run: probes declare ``prefill.lost``, every
    LATER long prompt is served by flat prefill on the survivors —
    completed, never blocked — and the torn pool log still reads."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=4, max_new=6)
    try:
        router.submit(_long_prompt(18, salt=1), request_id='before')
        router.step()
        clock.advance(0.01)
        pool = router.pool.prefill
        assert pool is not None and pool.alive
        pool.kill()                     # the router is told nothing
        router.submit(_long_prompt(18, salt=2), request_id='after')
        results = _settle(router, clock)
    finally:
        router.close()

    assert results['before'].status == 'completed'
    assert results['after'].status == 'completed'
    assert router.pool.prefill is None
    assert [p.name for p in router.pool.prefill_lost] == ['prefill']
    counters = router.registry.snapshot()['counters']
    assert counters['router.prefill_lost'] == 1

    revs = _events(router)
    lost = [r for r in revs if r['event'] == 'prefill.lost']
    assert len(lost) == 1 and lost[0]['target'] == 'prefill'
    assert lost[0]['reason'] in ('crash', 'probe_timeout')
    # The dead pool's torn log is readable, and NO decode replica died.
    assert list(obs.read_events(dict(router.pool.logs())['prefill']))
    assert not [r for r in revs if r['event'] == 'replica.lost']
    tls = reconstruct(router.pool.logs())
    assert tls['before'].complete and tls['after'].complete


def test_rebuild_pool_restores_offload(tmp_path, devices):
    """``rebuild_pool`` after a loss: a FRESH pool (never a name
    reuse) joins, the rejoin is audited, and handoffs resume."""
    clock = VirtualClock()
    router = _serving(tmp_path, clock, threshold=4, max_new=6)
    try:
        router.pool.prefill.kill()
        router.submit(_long_prompt(18, salt=3), request_id='flat')
        _settle(router, clock)          # loss declared, stream served
        fresh = router.rebuild_pool()
        assert fresh.name == 'prefill1'
        assert router.pool.prefill is fresh and fresh.alive
        router.submit(_long_prompt(18, salt=4), request_id='offload')
        results = _settle(router, clock)
    finally:
        router.close()
    assert results['offload'].status == 'completed'
    revs = _events(router)
    assert any(r['event'] == 'replica.rejoin'
               and r['target'] == 'prefill1' for r in revs)
    handoffs = [
        r for r in obs.read_events(dict(router.pool.logs())['prefill1'])
        if r['event'] == 'prefill.handoff']
    assert [r['request_id'] for r in handoffs] == ['offload']


# -- chaos knobs, watchdog, doctor, timeline, schemas -------------------

def test_chaos_plan_from_env_new_knobs():
    plan = chaos_plan_from_env({
        'DDP_TPU_FAULT_PAGE_CORRUPT': 'r0:2:8',
        'DDP_TPU_FAULT_PREFILL_CRASH': '10',
    })
    assert plan.page_corrupt == ('r0', 2, 8)
    assert plan.prefill_crash == 10
    assert plan.any()
    for env, knob in [
        ({'DDP_TPU_FAULT_PAGE_CORRUPT': 'r0:2'}, 'PAGE_CORRUPT'),
        ({'DDP_TPU_FAULT_PAGE_CORRUPT': 'r0:x:8'}, 'PAGE_CORRUPT'),
        ({'DDP_TPU_FAULT_PREFILL_CRASH': 'soon'}, 'PREFILL_CRASH'),
    ]:
        with pytest.raises(ChaosSpecError, match=knob):
            chaos_plan_from_env(env)


def test_default_watches_include_kv_corrupt():
    """The stock watchdog catalog watches the corruption counter and
    chains a flight dump — a corruption in production pages a human
    WITH the bundle already on disk."""
    watches = {w.name: w for w in obs_anomaly.default_watches()}
    w = watches['kv_corrupt']
    assert w.metric == 'router.kv_corrupt'
    assert w.signal == 'counter'
    assert 'dump' in w.actions


def test_doctor_classifies_kv_corruption_naming_the_dirty(tmp_path):
    """The ``kv_corruption`` incident class wins on corruption
    evidence — over the replica_loss class the healing events would
    otherwise vote for — and the verdict names the DIRTY replica."""
    reg = MetricsRegistry()
    with obs_flight.recording(base_dir=tmp_path / 'flight',
                              registry=reg) as rec:
        log = obs.EventLog(tmp_path / 'ev.jsonl')
        log.emit('fault.inject', kind='page_corrupt', target='r0',
                 page=3, tick=8)
        log.emit('kv.corrupt', target='r0', pages=[3], site='scrub')
        log.emit('request.recovered', request_id='a',
                 from_replica='r0', requeued=True, reason='kv_corrupt')
        log.emit('request.recovered', request_id='b',
                 from_replica='r0', requeued=False,
                 reason='kv_corrupt')
        log.emit('serve.reject', request_id='b', reason='kv_corrupt',
                 tenant='t0', queued=True)
        log.close()
        path = rec.dump_bundle(trigger='kv_corrupt')
    incident = obs_doctor.diagnose(obs_flight.load_bundle(path))
    assert incident.primary == 'kv_corruption'
    assert incident.replica == 'r0'
    out = obs_doctor.render_incident(incident)
    assert 'kv_corruption' in out and 'r0' in out


def test_timeline_folds_corruption_arcs():
    """A ``reason: kv_corrupt`` recovery counts in ``corruptions`` AND
    ``recoveries``; a plain crash recovery counts in neither's
    corruption tally."""
    recs = [
        {'event': 'serve.admit', 'request_id': 'a', 'slot': 0,
         'queue_wait': 0.0},
        {'event': 'request.recovered', 'request_id': 'a',
         'from_replica': 'r0', 'requeued': True,
         'reason': 'kv_corrupt'},
        {'event': 'serve.admit', 'request_id': 'a', 'slot': 1,
         'queue_wait': 0.1},
        {'event': 'serve.retire', 'request_id': 'a',
         'status': 'completed', 'total_seconds': 1.0},
    ]
    for i, r in enumerate(recs):
        r.update(schema=2, seq=i, ts=float(i))
    tl = reconstruct(recs)['a']
    assert tl.complete, tl.errors
    assert tl.recoveries == 1 and tl.corruptions == 1


def test_new_event_schemas_are_enforced(tmp_path):
    """The two integrity events validate like every other schema-2
    event: all required fields or an immediate raise."""
    log = EventLog(tmp_path / 'ev.jsonl')
    log.emit('kv.corrupt', target='r0', pages=[3], site='scrub')
    log.emit('prefill.lost', target='prefill', reason='probe_timeout')
    for ev, kw in [
        ('kv.corrupt', {'target': 'r0', 'pages': [3]}),
        ('prefill.lost', {'target': 'prefill'}),
    ]:
        with pytest.raises(ValueError):
            log.emit(ev, **kw)
    log.close()
    assert len(list(obs.read_events(log.path))) == 2


# -- the seeded fuzz sweep: one bit, any page, any boundary -------------

def test_fuzz_any_flip_detected_at_every_boundary(tmp_path, devices):
    """The acceptance sweep: a seeded rng flips ONE random bit in a
    random live tracked page, at each of the transfer boundaries in
    turn — slab handoff, prefix attach, CoW fork — and every single
    flip is detected before any sequence reads the page. Detection is
    structural (CRC32 changes for any one-bit flip), so the sweep
    pins the wiring, not luck."""
    rng = np.random.RandomState(42)
    pool = PrefillPool(t_max=64, page_size=16, vocab=32, seed=3,
                       event_log=EventLog(tmp_path / 'p.jsonl'))

    for trial in range(4):              # slab-handoff boundary
        eng = _paged_engine()
        handle = pool.build(_long_prompt(
            int(rng.randint(17, 40)), salt=trial))
        page = handle.pages[int(rng.randint(len(handle.pages)))]
        _flip_bit(pool.engine, page, rng)
        with pytest.raises(PageCorruptionError) as exc:
            eng.adopt_prefix(pool.engine.cache, handle.pages,
                             handle.length,
                             src_checksums=pool.engine.checksums)
        assert exc.value.site == 'handoff_src'
        assert int(page) in exc.value.pages
        pool.release(handle)

    for trial in range(4):              # attach + fork boundaries
        eng = _paged_engine(slots=3, pages=16)
        plen = int(rng.randint(17, 40))
        pid = eng.register_prefix(_long_prompt(plen, salt=10 + trial))
        pages, _ = eng._prefix_registry[pid]
        assert eng.start_with_prefix(0, pid)
        # Flip a FULL page: those are the ones slot 0 actually shares
        # (the partial tail page attaches as a private copy, so the
        # fork boundary rightly never reads the registry's tail —
        # attach still verifies it, as the handoff loop above pins).
        full = pages[:-1] if plen % 16 else pages
        _flip_bit(eng, full[int(rng.randint(len(full)))], rng)
        with pytest.raises(PageCorruptionError):
            eng.start_with_prefix(1, pid)
        with pytest.raises(PageCorruptionError):
            eng.fork_slot(0, 2)


def test_fuzz_healed_streams_bit_identical_to_twin(tmp_path, devices):
    """End-to-end fuzz over the SERVING arc: random live tracked page,
    random bit, mid-decode. Every trial must end with zero silent
    wrong tokens — every stream's tokens equal the corruption-free
    twin's — whether the victim healed or never touched the page."""
    prompt = _long_prompt(18)
    clock_twin = VirtualClock()
    twin = _serving(tmp_path / 'twin', clock_twin, replicas=1,
                    threshold=4, max_new=8)
    try:
        twin.submit(prompt, request_id='v')
        base = _settle(twin, clock_twin)
    finally:
        twin.close()

    rng = np.random.RandomState(7)
    for trial in range(3):
        clock = VirtualClock()
        router = _serving(tmp_path / f't{trial}', clock, threshold=4,
                          max_new=8)
        try:
            router.submit(prompt, request_id='v')
            router.step()
            clock.advance(0.01)
            target = router._ledger['v']['replica']
            eng = _member(router, target).engine
            tracked = eng.checksums.pages()
            page = tracked[int(rng.randint(len(tracked)))]
            _flip_bit(eng, page, rng)
            results = _settle(router, clock)
        finally:
            router.close()
        assert results['v'].status == 'completed', (trial, results)
        assert results['v'].tokens == base['v'].tokens, trial
        revs = _events(router)
        corrupt = [r for r in revs if r['event'] == 'kv.corrupt']
        assert corrupt and corrupt[0]['target'] == target, trial
        assert int(page) in corrupt[0]['pages'], trial
