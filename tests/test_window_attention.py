# -*- coding: utf-8 -*-
"""
Sliding-window (local) attention tests.

Oracle pattern per SURVEY §4: the window is densified into a boolean mask
(``i − j >= window`` masked, on global positions) and fed to the unfused
jnp math / the windowless kernel — the windowed kernel must match both,
forward and gradients, including when the window does not align with the
kernel block sizes and when it composes with user masks, segment ids and
explicit-position layouts. No reference analog (its module materializes
every (T/N, T) score row, reference module.py:66-67).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.pallas_attention import (
    _reference_math, flash_attention,
)

B, H, D = 2, 3, 16

pytestmark = pytest.mark.slow  # Pallas-interpreter-heavy


def _qkv(t, key=0):
    k1, k2, k3 = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(k1, (B, H, t, D), jnp.float32)
    k = jax.random.normal(k2, (B, H, t, D), jnp.float32)
    v = jax.random.normal(k3, (B, H, t, D), jnp.float32)
    return q, k, v


def _window_mask(t, window, offset=0):
    """Dense equivalent: global row i attends cols (i − window, i]."""
    rows = offset + jnp.arange(t)[:, None]
    cols = jnp.arange(t)[None, :]
    return rows - cols >= window


@pytest.mark.parametrize('t,window', [(64, 16), (100, 7), (64, 1),
                                      (64, 200)])
def test_window_matches_densified_mask(t, window):
    q, k, v = _qkv(t)
    out = flash_attention(q, k, v, causal=True, window=window)
    ref = _reference_math(q, k, v, _window_mask(t, window),
                          1.0 / np.sqrt(D), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_window_with_causal_offset():
    """Sequence-sharded case: query rows are global rows offset..offset+t."""
    t, window, off = 64, 10, 128
    q, k, v = _qkv(t, key=3)
    kf = jnp.concatenate([k, k, k], axis=-2)   # gathered keys, Tk = 3t
    vf = jnp.concatenate([v, v, v], axis=-2)
    out = flash_attention(q, kf, vf, causal=True, causal_offset=off,
                          window=window)
    rows = off + jnp.arange(t)[:, None]
    cols = jnp.arange(3 * t)[None, :]
    dense = (rows < cols) | (rows - cols >= window)
    ref = _reference_math(q, kf, vf, dense, 1.0 / np.sqrt(D), False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_window_gradients_match_densified(t=100, window=13):
    q, k, v = _qkv(t, key=1)

    def f_win(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                window=window) ** 2).sum()

    def f_dense(q, k, v):
        return (_reference_math(q, k, v, _window_mask(t, window),
                                1.0 / np.sqrt(D), True) ** 2).sum()

    g_win = jax.grad(f_win, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for gw, gr in zip(g_win, g_ref):
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gr),
                                   atol=2e-4, rtol=1e-4)


def test_window_with_positions_shuffled_layout():
    """window over EXPLICIT positions: a zigzag-style permuted row layout
    must behave as if rows were in natural order."""
    t, window = 64, 9
    q, k, v = _qkv(t, key=2)
    perm = jax.random.permutation(jax.random.key(11), t)
    pos = jnp.arange(t, dtype=jnp.int32)
    qp, kp, vp = q[..., perm, :], k[..., perm, :], v[..., perm, :]
    out_p = flash_attention(qp, kp, vp, positions=(pos[perm], pos[perm]),
                            window=window)
    out_n = flash_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out_p[..., jnp.argsort(perm), :]),
                               np.asarray(out_n), atol=1e-5, rtol=1e-5)


def test_window_composes_with_mask_and_segments():
    t, window = 64, 12
    q, k, v = _qkv(t, key=4)
    user = jax.random.bernoulli(jax.random.key(5), 0.2, (B, H, t, t))
    seg = (jnp.arange(t, dtype=jnp.int32) * 4 // t)
    out = flash_attention(q, k, v, user, causal=True, window=window,
                          segment_ids=seg)
    dense = (user | _window_mask(t, window)
             | (seg[:, None] != seg[None, :]))
    ref = _reference_math(q, k, v, dense, 1.0 / np.sqrt(D), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_window_bounded_mode_matches_exact():
    t, window = 64, 8
    q, k, v = _qkv(t, key=6)
    out_b = flash_attention(q, k, v, causal=True, window=window,
                            softmax_mode='bounded')
    out_e = flash_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_e),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize('t,window,off', [(64, 16, 0), (48, 5, 32),
                                          (64, 200, 0)])
def test_banded_grid_matches_full_grid(monkeypatch, t, window, off):
    """The TPU-only banded grid (scalar-prefetch index maps select each Q
    block's K band; ~window/bk blocks per row instead of Tk/bk) must be
    bit-identical to the full-grid window path, forward and backward —
    forced under the Mosaic interpreter on tiny shapes, like the mask
    redirect."""
    import distributed_dot_product_tpu.ops.pallas_attention as pa

    q, k, v = _qkv(t, key=8)
    kf = jnp.concatenate([k, k], axis=-2)
    vf = jnp.concatenate([v, v], axis=-2)

    def run(q):
        def f(q):
            return (flash_attention(q, kf, vf, causal=True,
                                    causal_offset=off,
                                    window=window) ** 2).sum()
        return jax.value_and_grad(f)(q)

    ref_out, ref_g = run(q)
    monkeypatch.setattr(pa, '_BAND_ON_INTERPRET', True)
    band_out, band_g = run(q)
    np.testing.assert_allclose(np.asarray(band_out), np.asarray(ref_out),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(band_g), np.asarray(ref_g),
                               atol=1e-5, rtol=1e-5)


def test_banded_grid_with_segments(monkeypatch):
    """Banded grid composes with segment ids (their kv-side vector spec is
    the one aux input that needs the band's index translation)."""
    import distributed_dot_product_tpu.ops.pallas_attention as pa

    t, window = 64, 10
    q, k, v = _qkv(t, key=9)
    seg = (jnp.arange(t, dtype=jnp.int32) * 3 // t)
    ref = flash_attention(q, k, v, causal=True, window=window,
                          segment_ids=seg)
    monkeypatch.setattr(pa, '_BAND_ON_INTERPRET', True)
    got = flash_attention(q, k, v, causal=True, window=window,
                          segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)


def test_window_validation():
    q, k, v = _qkv(16)
    with pytest.raises(ValueError, match='causal semantics'):
        flash_attention(q, k, v, window=4)
    with pytest.raises(ValueError, match='positive int'):
        flash_attention(q, k, v, causal=True, window=0)
    with pytest.raises(ValueError, match='positive int'):
        flash_attention(q, k, v, causal=True, window=2.5)


# --- module-level: every softmax path agrees with the local oracle -------

from distributed_dot_product_tpu.models.attention import (  # noqa: E402
    DistributedDotProductAttn, apply_seq_parallel,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh  # noqa: E402

WORLD, LEN = 4, 8
T = WORLD * LEN
DIM = 16


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


def _module_inputs():
    kk, kq, kv = jax.random.split(jax.random.key(20), 3)
    k = jax.random.normal(kk, (2, T, DIM), jnp.float32)
    q = jax.random.normal(kq, (2, T, DIM), jnp.float32)
    v = jax.random.normal(kv, (2, T, DIM), jnp.float32)
    return k, q, v


@pytest.mark.parametrize('impl', ['full', 'flash', 'online', 'ulysses'])
def test_module_window_matches_local_oracle(mesh, impl):
    """Distributed window attention == the distributed=False oracle, for
    every softmax path. The oracle runs the 'full' path (windows densified
    into the mask), so kernels and densification cross-check each other."""
    kwargs = dict(key_dim=DIM, num_heads=4, causal=True, window=11)
    dist = DistributedDotProductAttn(distributed=True, softmax_impl=impl,
                                     **kwargs)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    k, q, v = _module_inputs()
    params = local.init(jax.random.key(1), k, q, v, None)
    out = apply_seq_parallel(dist, params, mesh, k, q, v, None)
    ref = local.apply(params, k, q, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_module_window_gradients(mesh):
    kwargs = dict(key_dim=DIM, num_heads=4, causal=True, window=7)
    dist = DistributedDotProductAttn(distributed=True, softmax_impl='flash',
                                     **kwargs)
    local = DistributedDotProductAttn(distributed=False, **kwargs)
    k, q, v = _module_inputs()
    params = local.init(jax.random.key(2), k, q, v, None)

    def ld(p):
        return jnp.sum(apply_seq_parallel(dist, p, mesh, k, q, v, None) ** 2)

    def ll(p):
        return jnp.sum(local.apply(p, k, q, v, None) ** 2)

    for got, want in zip(jax.tree.leaves(jax.grad(ld)(params)),
                         jax.tree.leaves(jax.grad(ll)(params))):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_ring_window_zigzag_layout(mesh):
    """window composes with the zigzag causal ring layout (positions-based
    masking path)."""
    from distributed_dot_product_tpu.models.ring_attention import (
        ring_attention, local_attention_reference, zigzag_indices,
    )
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    window = 9
    kq, kk, kv = jax.random.split(jax.random.key(30), 3)
    q = jax.random.normal(kq, (2, T, DIM), jnp.float32)
    k = jax.random.normal(kk, (2, T, DIM), jnp.float32)
    v = jax.random.normal(kv, (2, T, DIM), jnp.float32)
    idx = zigzag_indices(T, WORLD)
    inv = jnp.argsort(idx)

    def run(qz, kz, vz):
        return ring_attention(qz, kz, vz, causal=True, layout='zigzag',
                              window=window)

    out_z = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(P(None, 'seq', None),) * 3,
        out_specs=P(None, 'seq', None), check_vma=False,
    ))(q[:, idx], k[:, idx], v[:, idx])[:, inv]
    ref = local_attention_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out_z), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_module_window_requires_causal():
    with pytest.raises(ValueError, match='causal'):
        DistributedDotProductAttn(key_dim=DIM, window=4).init(
            jax.random.key(0), *([jnp.zeros((1, 8, DIM))] * 3), None)
