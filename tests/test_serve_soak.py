# -*- coding: utf-8 -*-
"""
Seeded burst soak for the serving layer — the ISSUE 2 acceptance
scenario, verified on the CPU backend: with 1 stuck step + 1 NaN slot +
a queue-overflow burst injected, the scheduler finishes every
ADMISSIBLE request, every shed request carries a typed reason, streams
untouched by the faults are bit-identical to a fault-free run, and
readiness returns to healthy.

The fast variant runs in tier-1; the `slow`-marked variant scales the
burst and adds the abandon fault.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu.serve import (
    KernelEngine, Readiness, RejectedError, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

SLOTS, T_MAX, VOCAB = 3, 32, 16
TERMINAL = {'completed', 'deadline_expired', 'evicted', 'abandoned',
            'failed_nan', 'rejected'}


def _burst(n, seed):
    rng = np.random.default_rng(seed)
    return [(f'r{i:03d}',
             rng.integers(0, VOCAB,
                          size=int(rng.integers(1, 7))).astype(np.int32))
            for i in range(n)]


def _run_soak(n_requests, injector, *, seed=11, queue_limit=4,
              max_new=4, stall_timeout=0.15, decode_impl='xla'):
    sched = Scheduler(
        KernelEngine(slots=SLOTS, t_max=T_MAX, vocab=VOCAB, heads=2,
                     head_dim=4, prefill_chunk=4, seed=5,
                     decode_impl=decode_impl),
        ServeConfig(queue_limit=queue_limit, max_new_tokens=max_new,
                    stall_timeout=stall_timeout, watchdog_poll=0.02,
                    evict_before_reject=False),
        fault_injector=injector, registry=MetricsRegistry())
    rejected = {}
    for i, (rid, prompt) in enumerate(_burst(n_requests, seed)):
        try:
            sched.submit(prompt, request_id=rid)
        except RejectedError as e:
            rejected[rid] = e.reason
        if i % 3 == 2:      # interleave serving with the arrival burst
            sched.step()
    results = sched.run_until_idle()
    return sched, rejected, results


def _audit(n_requests, sched, rejected, results, seed=11):
    # 1. Zero dropped-without-reason: every request is terminal or a
    #    typed rejection.
    for rid, _ in _burst(n_requests, seed):
        if rid in rejected:
            assert rejected[rid] is not None, f'{rid}: untyped rejection'
        else:
            assert rid in results, f'{rid}: vanished'
            r = results[rid]
            assert r.status in TERMINAL, f'{rid}: {r.status}'
            if r.status == 'rejected':
                assert r.reason is not None, f'{rid}: untyped'
    # 2. Every ADMISSIBLE (admitted) request finished its stream.
    for r in results.values():
        if r.status == 'completed':
            assert len(r.tokens) >= 1
    # 3. Readiness healthy again before shutdown.
    assert sched.health.readiness in (Readiness.READY,
                                      Readiness.STOPPED)


@pytest.mark.parametrize('decode_impl', ['xla', 'kernel'])
def test_burst_soak_with_fault_cocktail(decode_impl):
    """Stuck step + NaN slot + overflow burst, against a clean
    reference run of the same seeded traffic — on BOTH decode paths
    (the fused Pallas kernel runs interpreted on the CPU mesh; its
    in-place aliased cache must survive the quarantine/evict/requeue
    churn exactly like the XLA step's)."""
    n = 14
    _, rej0, clean = _run_soak(n, None, decode_impl=decode_impl)
    plan = ServeFaultPlan(stuck_at_step=3, stuck_seconds=0.5,
                          nan_at_step=5, nan_slot=1)
    sched, rejected, results = _run_soak(n, ServeFaultInjector(plan),
                                         decode_impl=decode_impl)
    _audit(n, sched, rejected, results)
    counters = sched.registry.snapshot()['counters']
    assert sched.health.stall_events >= 1, 'stuck step undetected'
    assert counters['serve.nan_quarantined'] >= 1, 'NaN not quarantined'
    assert counters['serve.rejected.queue_full'] >= 1, \
        'burst never overflowed the queue — not a soak'
    # 4. Fault isolation: any request completed (undegraded) in BOTH
    #    runs produced bit-identical tokens; degradation differences
    #    only ever truncate (greedy streams are prefix-stable).
    compared = 0
    for rid, r in results.items():
        ref = clean.get(rid)
        if ref is None or r.status != 'completed' \
                or ref.status != 'completed':
            continue
        short, long_ = sorted((r.tokens, ref.tokens), key=len)
        assert long_[:len(short)] == short, f'{rid}: stream diverged'
        if len(short) == len(long_):
            compared += 1
    assert compared >= 3, 'soak too small to witness isolation'
    sched.close()
    assert sched.health.readiness is Readiness.STOPPED


@pytest.mark.slow
def test_burst_soak_scaled():
    """Bigger burst + the abandon fault; same invariants."""
    n = 60
    plan = ServeFaultPlan(stuck_at_step=4, stuck_seconds=0.5,
                          nan_at_step=9, nan_slot=2,
                          abandon_request=3, abandon_after_tokens=1)
    sched, rejected, results = _run_soak(n, ServeFaultInjector(plan),
                                         queue_limit=6, max_new=5)
    _audit(n, sched, rejected, results)
    counters = sched.registry.snapshot()['counters']
    assert counters['serve.nan_quarantined'] >= 1
    assert counters['serve.abandoned'] >= 1
    assert counters['serve.rejected.queue_full'] >= 1
    assert sched.health.stall_events >= 1
    # Accounting identity: everything submitted is exactly once in
    # {results} ∪ {rejected-at-submit}.
    assert len(results) + len(rejected) == n
    sched.close()
