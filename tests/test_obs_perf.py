# -*- coding: utf-8 -*-
"""
Perf observatory (obs/perf.py): compiled-program cost/roofline
accounting over the analysis registry, the committed-baseline gate,
the seeded-regression negative path, and the report rendering.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.obs import perf
from distributed_dot_product_tpu.obs.events import EventLog, activate

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, 'PERF_BASELINE.json')


def _fixtures_module():
    """tests/ is not a package: `tests.perf_fixtures` resolves as a
    PEP-420 namespace package when the repo root is on sys.path —
    fall back to inserting it (same dance as test_graphlint)."""
    try:
        from tests import perf_fixtures
    except ImportError:
        sys.path.insert(0, REPO)
        from tests import perf_fixtures
    return perf_fixtures


@pytest.fixture(scope='module')
def full_snapshot(devices):
    """ONE compile pass over the whole registry, shared by the
    acceptance tests below (it is the expensive part — the same cost
    class as the graphlint clean-tree gate)."""
    return perf.snapshot()


@pytest.fixture(scope='module')
def fixture_snapshots(devices):
    fx = _fixtures_module()
    return (perf.snapshot(fx.clean()), perf.snapshot(fx.regressed()))


# -- snapshot coverage (tier-1 acceptance) ------------------------------

def test_every_entrypoint_in_snapshot_with_nonzero_cost(full_snapshot):
    """Every registered entrypoint appears with nonzero compiler-counted
    flops AND bytes and a roofline classification — the registry and
    the cost snapshot cannot drift apart."""
    from distributed_dot_product_tpu.analysis.registry import (
        default_entrypoints,
    )
    entries = full_snapshot['entries']
    assert set(entries) == set(default_entrypoints())
    for name, e in entries.items():
        assert 'error' not in e, f'{name}: {e.get("error")}'
        assert e['flops'] > 0, name
        assert e['bytes_accessed'] > 0, name
        assert e['roofline'] in ('compute-bound', 'bandwidth-bound'), name
        assert e['compile_seconds'] > 0, name
        assert e['peak_bytes'] > 0, name


def test_snapshot_schema_and_retrace_totals(full_snapshot):
    assert full_snapshot['schema'] == perf.PERF_SCHEMA_VERSION
    assert full_snapshot['n_devices'] >= 8
    # The engine/decode builders run under watch_traces — the snapshot
    # must have recorded the traces its own compiles incurred.
    rt = full_snapshot['retrace_totals']
    assert any(v > 0 for v in rt.values()), rt
    peaks = full_snapshot['peaks']
    assert peaks['ridge_flops_per_byte'] == pytest.approx(
        peaks['flops_per_s'] / peaks['bytes_per_s'])


def test_committed_baseline_gate_passes(full_snapshot):
    """THE gate scripts/ci.sh stage [5/5] runs: the current tree against
    the committed PERF_BASELINE.json must be violation-free. On an
    intentional program change, refresh with
    `python -m distributed_dot_product_tpu.obs.perf snapshot -o
    PERF_BASELINE.json`."""
    with open(BASELINE) as f:
        baseline = json.load(f)
    violations = perf.check_snapshots(full_snapshot, baseline)
    assert violations == [], '\n'.join(violations)


# -- the regression gate ------------------------------------------------

def test_seeded_upcast_regression_is_caught(fixture_snapshots):
    """An f32 cache upcast persisted into the stored buffer: argument
    bytes double and the compiler-counted bytes/peak blow through the
    tolerances — check must flag the entry by name."""
    clean_snap, bad_snap = fixture_snapshots
    ce = clean_snap['entries']['fx.cache_step']
    be = bad_snap['entries']['fx.cache_step']
    assert be['argument_bytes'] > 1.9 * ce['argument_bytes']
    assert be['peak_bytes'] > 1.25 * ce['peak_bytes']
    violations = perf.check_snapshots(bad_snap, clean_snap)
    assert violations, 'seeded regression not detected'
    assert any('fx.cache_step' in v
               and ('argument_bytes' in v or 'peak_bytes' in v)
               for v in violations), violations
    # The clean tree against its own baseline stays green.
    assert perf.check_snapshots(clean_snap, clean_snap) == []


def test_check_emits_perf_regression_events(fixture_snapshots, tmp_path):
    clean_snap, bad_snap = fixture_snapshots
    log_path = tmp_path / 'perf_events.jsonl'
    with activate(EventLog(log_path)) as log:
        perf.check_snapshots(bad_snap, clean_snap)
        log.flush()
    records = obs_events.read_events(str(log_path))
    regs = [r for r in records if r['event'] == 'perf.regression']
    assert regs and regs[0]['entry'] == 'fx.cache_step'
    # The extended schema validates offline like every other event.
    _, errors = obs_events.validate_file(str(log_path))
    assert errors == []


def test_check_compile_time_tolerance():
    def snap(compile_s):
        return {'schema': 1, 'entries': {'e': {
            'flops': 100.0, 'bytes_accessed': 100.0,
            'argument_bytes': 100, 'peak_bytes': 100,
            'compile_seconds': compile_s}}, 'retrace_totals': {}}
    base, ok, slow = snap(1.0), snap(9.0), snap(40.0)
    tol = perf.Tolerances(compile_factor=10.0, compile_slack_s=5.0)
    assert perf.check_snapshots(ok, base, tol=tol,
                                emit_events=False) == []
    v = perf.check_snapshots(slow, base, tol=tol, emit_events=False)
    assert v and 'compile_seconds' in v[0]


def test_check_coverage_and_retrace_gates():
    entry = {'flops': 1.0, 'bytes_accessed': 1.0, 'argument_bytes': 1,
             'peak_bytes': 1, 'compile_seconds': 0.1}
    base = {'schema': 1, 'entries': {'a': dict(entry)},
            'retrace_totals': {'engine.decode': 1}}
    # Missing entry.
    cur = {'schema': 1, 'entries': {}, 'retrace_totals': {}}
    v = perf.check_snapshots(cur, base, emit_events=False)
    assert any('a' in s and 'coverage' in s for s in v)
    # New unbaselined entry.
    cur = {'schema': 1, 'entries': {'a': dict(entry), 'b': dict(entry)},
           'retrace_totals': {'engine.decode': 1}}
    v = perf.check_snapshots(cur, base, emit_events=False)
    assert any(s.startswith('b: coverage') for s in v)
    # Retrace storm during snapshot.
    cur = {'schema': 1, 'entries': {'a': dict(entry)},
           'retrace_totals': {'engine.decode': 5}}
    v = perf.check_snapshots(cur, base, emit_events=False)
    assert any('retrace_total' in s for s in v)
    # Storm under a NEW watcher name (not in the baseline) is gated
    # against an implicit baseline of 0, not silently skipped.
    cur = {'schema': 1, 'entries': {'a': dict(entry)},
           'retrace_totals': {'engine.decode': 1, 'models.new_step': 7}}
    v = perf.check_snapshots(cur, base, emit_events=False)
    assert any('models.new_step' in s and 'retrace_total' in s
               for s in v), v
    # ...but a current-only name with zero traces (a counter merely
    # alive during the snapshot) stays green.
    cur = {'schema': 1, 'entries': {'a': dict(entry)},
           'retrace_totals': {'engine.decode': 1, 'models.idle': 0}}
    assert perf.check_snapshots(cur, base, emit_events=False) == []
    # Schema drift refuses to compare.
    v = perf.check_snapshots({'schema': 99}, base, emit_events=False)
    assert v and 'schema' in v[0]


def test_snapshot_retrace_delta_ignores_prior_history(devices):
    """Traces incurred (and counters retired) BEFORE a snapshot must
    not charge its retrace delta — otherwise any in-process use after
    prior engine churn fails the gate with a phantom storm."""
    import gc

    from distributed_dot_product_tpu.analysis import retrace
    w = retrace.watch_traces(lambda x: x, 'unit.prior_history',
                             budget=10)
    w(1)
    w(2)
    del w
    gc.collect()
    assert retrace.total('unit.prior_history') == 2   # folded, retired
    fx = _fixtures_module()
    snap = perf.snapshot(fx.clean())
    assert snap['retrace_totals'].get('unit.prior_history', 0) == 0


# -- report + program model --------------------------------------------

def test_report_renders_roofline_table(fixture_snapshots):
    clean_snap, _ = fixture_snapshots
    text = perf.render_report(clean_snap)
    assert 'fx.cache_step' in text
    assert 'bandwidth' in text          # tiny-q cache read: HBM-bound
    assert 'ridge' in text


def test_program_model_measured_columns(devices):
    import jax
    import jax.numpy as jnp
    compiled = jax.jit(
        lambda a, b: a @ b).lower(jnp.ones((64, 64)),
                                  jnp.ones((64, 64))).compile()
    m = perf.program_model(compiled, measured_seconds=1e-3)
    assert m['flops'] > 0 and m['bytes_accessed'] > 0
    assert m['measured_gflops_per_s'] == pytest.approx(
        m['flops'] / 1e-3 / 1e9)
    assert 0 < m['fraction_of_roofline']
    assert m['roofline'] in ('compute-bound', 'bandwidth-bound')


# -- CLI ----------------------------------------------------------------

def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.run(
        [sys.executable, '-m', 'distributed_dot_product_tpu.obs.perf',
         *args], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=540)


def test_cli_snapshot_check_report_on_fixture(tmp_path):
    """End-to-end through the CLI surface on the one-entry fixture
    registry: snapshot a clean baseline, check the regressed tree
    against it (exit 1, entry named), check clean-vs-clean (exit 0),
    render the report from the file (no devices touched)."""
    base = tmp_path / 'base.json'
    res = _cli('--registry', 'tests.perf_fixtures:clean',
               'snapshot', '-o', str(base))
    assert res.returncode == 0, res.stdout + res.stderr
    snap = json.loads(base.read_text())
    assert snap['entries']['fx.cache_step']['flops'] > 0

    res = _cli('--registry', 'tests.perf_fixtures:regressed',
               'check', '--against', str(base))
    assert res.returncode == 1, res.stdout + res.stderr
    assert 'fx.cache_step' in res.stdout
    assert 'argument_bytes' in res.stdout or 'peak_bytes' in res.stdout

    res = _cli('--registry', 'tests.perf_fixtures:clean',
               'check', '--against', str(base))
    assert res.returncode == 0, res.stdout + res.stderr
    assert 'OK' in res.stdout

    res = _cli('report', str(base))
    assert res.returncode == 0, res.stdout + res.stderr
    assert 'fx.cache_step' in res.stdout
