# -*- coding: utf-8 -*-
"""
Operator parity tests for the distributed matmul kernels.

Port of the reference oracle strategy (reference
tests/test_multiplication.py, SURVEY §4): deterministic integer-valued
tensors, a local full-array matmul as ground truth, the distributed kernel
on sequence shards, and **bitwise equality** (exact for integer-valued
floats — every partial sum stays below 2^24, so summation order cannot
matter).

The reference's 6-mode table (NT, NT-4D, TN, TN-4D, FULL, FULL-4D,
reference test_multiplication.py:50-109) carries over, plus coverage the
reference lacks (SURVEY §4 "What is NOT tested"): non-divisor offsets,
offset larger than the shard, batch > 1 everywhere in the 4-D modes, and
the ring (`ppermute`) implementations.

Where the reference needed ``horovodrun -np N`` + allgather-and-compare
(reference test_multiplication.py:134-144), here the distributed result is
a single global ``jax.Array`` from ``shard_map`` — directly comparable.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.ops.functions import (
    distributed_matmul_all_global, distributed_matmul_nt_global,
    distributed_matmul_tn_global,
)
from distributed_dot_product_tpu.parallel.mesh import seq_mesh

WORLD = 4
LENGTH = 4          # rows per shard (reference test_multiplication.py:23)
DIM = 6             # feature dim (reference test_multiplication.py:24)
T = WORLD * LENGTH  # global sequence length


def create_tensor(*shape):
    """Deterministic integer-valued tensor (reference
    test_multiplication.py:27-31 used torch.arange; values are bounded to
    keep all partial sums exactly representable in fp32)."""
    n = int(np.prod(shape))
    return jnp.asarray((np.arange(n) % 50) - 17, dtype=jnp.float32
                       ).reshape(shape)


def gt_nt(left, right):
    return np.asarray(left) @ np.asarray(right).swapaxes(-1, -2)


def gt_tn(left, right):
    return np.asarray(left).swapaxes(-1, -2) @ np.asarray(right)


def gt_all(left, right):
    return np.asarray(left) @ np.asarray(right)


# Each mode: (left global shape, right global shape, ground truth, kernel).
# 3-D/4-D split mirrors the reference's create_multi_tensor variants
# (reference test_multiplication.py:34-47); 4-D uses B=2, H=3.
MODES = {
    'nt': ((T, DIM), (T, DIM), gt_nt, distributed_matmul_nt_global),
    'nt-3d': ((2, T, DIM), (2, T, DIM), gt_nt, distributed_matmul_nt_global),
    'nt-4d': ((2, 3, T, DIM), (2, 3, T, DIM), gt_nt,
              distributed_matmul_nt_global),
    'tn': ((T, T), (T, DIM), gt_tn, distributed_matmul_tn_global),
    'tn-4d': ((2, 3, T, T), (2, 3, T, DIM), gt_tn,
              distributed_matmul_tn_global),
    'all': ((T, T), (T, DIM), gt_all, distributed_matmul_all_global),
    'all-4d': ((2, 3, T, T), (2, 3, T, DIM), gt_all,
               distributed_matmul_all_global),
}

HAS_OFFSET = {'nt', 'nt-3d', 'nt-4d', 'all', 'all-4d'}
# offset=2 forces multiple chunk-loop iterations (reference
# test_multiplication.py:56,96,108); 3 is a non-divisor of both LENGTH=4
# and DIM=6; 1000 > shard; None = single full gather.
OFFSETS = [2, 3, 1000, None]


@pytest.fixture(scope='module')
def mesh():
    return seq_mesh(WORLD)


@pytest.mark.parametrize('mode', sorted(MODES))
@pytest.mark.parametrize('offset', OFFSETS)
def test_parity_bitwise(mesh, mode, offset):
    lshape, rshape, gt, kernel = MODES[mode]
    if mode not in HAS_OFFSET:
        if offset != OFFSETS[0]:
            pytest.skip('tn has no offset knob (reference functions.py:103)')
        kwargs = {}
    else:
        kwargs = {'offset': offset}
    left, right = create_tensor(*lshape), create_tensor(*rshape)
    out = kernel(left, right, mesh=mesh, **kwargs)
    expected = gt(left, right)
    assert out.shape == expected.shape
    # Bitwise equality, as in the reference (test_multiplication.py:144).
    assert (np.asarray(out) == expected).all()


@pytest.mark.parametrize('mode', ['nt', 'nt-4d', 'all', 'all-4d'])
def test_ring_impl_parity(mesh, mode):
    """ppermute-ring variants (no reference analog) match the same oracle."""
    lshape, rshape, gt, kernel = MODES[mode]
    left, right = create_tensor(*lshape), create_tensor(*rshape)
    out = kernel(left, right, mesh=mesh, impl='ring')
    assert (np.asarray(out) == gt(left, right)).all()


def test_tn_rejects_bad_width(mesh):
    """tn requires left's last dim divisible by the mesh width (the
    reference would produce garbage shapes; we raise)."""
    left = create_tensor(T, T - 1)
    right = create_tensor(T, DIM)
    with pytest.raises(ValueError, match='divisible'):
        distributed_matmul_tn_global(left, right, mesh=mesh)


def test_single_device_mesh_degenerates_to_local():
    """W=1 mesh: kernels must reduce to plain matmuls (the path the real
    single-TPU-chip benchmark exercises)."""
    mesh1 = seq_mesh(1)
    left, right = create_tensor(T, DIM), create_tensor(T, DIM)
    out = distributed_matmul_nt_global(left, right, offset=5, mesh=mesh1)
    assert (np.asarray(out) == gt_nt(left, right)).all()
