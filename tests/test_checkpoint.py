# -*- coding: utf-8 -*-
"""
Checkpoint / resume tests.

No reference analog (SURVEY §5: the reference has no checkpoint subsystem
at all). The contract tested: interrupting a training run, restoring from
disk, and continuing must produce exactly the losses of the uninterrupted
run — including the optimizer state (adam moments), which is where naive
params-only checkpointing silently diverges.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_dot_product_tpu import DistributedDotProductAttn
from distributed_dot_product_tpu.parallel.mesh import seq_mesh
from distributed_dot_product_tpu.train import make_train_step
from distributed_dot_product_tpu.utils.checkpoint import (
    TrainState, latest_step, restore, save,
)


def _setup():
    mesh = seq_mesh(8)
    dim, heads, t, b = 32, 4, 16, 2
    model = DistributedDotProductAttn(key_dim=dim, num_heads=heads, offset=2)
    x = jax.random.normal(jax.random.key(0), (b, t, dim), jnp.float32)
    target = jax.random.normal(jax.random.key(1), (b, t, dim), jnp.float32)
    mask = jnp.zeros((b, t, t), dtype=bool)
    params = model.init(jax.random.key(2), x, x, x, mask)
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(model, optimizer, mesh, donate=False)
    return step, params, opt_state, (x, x, x, mask, target)


def test_resume_reproduces_uninterrupted_run(tmp_path):
    step, params, opt_state, batch = _setup()

    # Uninterrupted: 4 steps.
    p, o = params, opt_state
    losses = []
    for _ in range(4):
        p, o, loss = step(p, o, batch)
        losses.append(float(loss))

    # Interrupted: 2 steps, checkpoint, "crash", restore, 2 more.
    p, o = params, opt_state
    for i in range(2):
        p, o, _ = step(p, o, batch)
    save(tmp_path, TrainState(step=2, params=p, opt_state=o))
    assert latest_step(tmp_path) == 2

    template = TrainState(step=0, params=p, opt_state=o)
    restored = restore(tmp_path, template)
    assert restored.step == 2
    p2, o2 = restored.params, restored.opt_state
    resumed = []
    for _ in range(2):
        p2, o2, loss = step(p2, o2, batch)
        resumed.append(float(loss))
    np.testing.assert_allclose(resumed, losses[2:], rtol=1e-6)


def test_restored_arrays_bitwise_equal(tmp_path):
    step, params, opt_state, batch = _setup()
    p, o, _ = step(params, opt_state, batch)
    save(tmp_path, TrainState(step=1, params=p, opt_state=o))
    restored = restore(tmp_path, TrainState(step=0, params=p, opt_state=o))
    # Params AND optimizer state (adam moments are where naive
    # checkpointing silently diverges — the module's stated contract).
    for got, want in ((restored.params, p), (restored.opt_state, o)):
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            assert (np.asarray(a) == np.asarray(b)).all()


def test_resave_same_step_keeps_backup_until_finalized(tmp_path):
    """Overwriting an existing step must not destroy the old checkpoint
    before the new one is finalized (crash-safety of force=True)."""
    _, params, opt_state, _ = _setup()
    save(tmp_path, TrainState(step=2, params=params, opt_state=opt_state))
    save(tmp_path, TrainState(step=2, params=params, opt_state=opt_state))
    assert latest_step(tmp_path) == 2
    restored = restore(tmp_path, TrainState(0, params, opt_state))
    assert restored.step == 2
    import os
    assert not os.path.isdir(str(tmp_path / 'step_000000002.replaced'))
    with pytest.raises(FileExistsError):
        save(tmp_path, TrainState(step=2, params=params,
                                  opt_state=opt_state), force=False)


def test_restore_without_checkpoint_raises(tmp_path):
    _, params, opt_state, _ = _setup()
    with pytest.raises(FileNotFoundError):
        restore(tmp_path / 'empty', TrainState(0, params, opt_state))


def test_multiple_steps_latest_wins(tmp_path):
    _, params, opt_state, _ = _setup()
    for s in (1, 5, 3):
        save(tmp_path, TrainState(step=s, params=params,
                                  opt_state=opt_state))
    assert latest_step(tmp_path) == 5
    assert restore(tmp_path,
                   TrainState(0, params, opt_state)).step == 5
    assert restore(tmp_path, TrainState(0, params, opt_state),
                   step=3).step == 3


def test_epath_round_trip(tmp_path):
    """Every path operation (step-dir construction, existence, listing,
    the overwrite-backup rename, finalization checks) routes through
    etils.epath — the backend abstraction object stores use. A POSIX
    directory wrapped in epath exercises the identical code path; the
    URL-specific string handling is covered below."""
    from etils import epath

    step, params, opt_state, batch = _setup()
    p1, o1, _ = step(params, opt_state, batch)
    root = epath.Path(tmp_path) / 'ck'
    save(root, TrainState(1, p1, o1))
    assert latest_step(root) == 1
    got = restore(root, TrainState(0, params, opt_state))
    assert got.step == 1
    for a, b in zip(jax.tree.leaves(got.params), jax.tree.leaves(p1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Overwrite keeps the epath backup dance crash-safe (backup removed
    # only after the new write finalizes).
    save(root, TrainState(1, p1, o1))
    names = {c.name for c in root.iterdir()}
    assert 'step_000000001' in names and not any(
        n.endswith('.replaced') for n in names)


def test_object_store_urls_accepted():
    """URL paths are no longer rejected up front (the round-3 verdict's
    POSIX-only gap): path construction keeps the scheme intact and
    ``latest_step`` on a nonexistent bucket path simply reports no
    checkpoint. (No real object store in the test environment — writes
    are exercised via the epath POSIX backend above; the scheme handling
    is what used to raise.)"""
    from distributed_dot_product_tpu.utils import checkpoint as ck

    d = ck._step_dir('gs://bucket/run1', 7)
    assert str(d) == 'gs://bucket/run1/step_000000007'
    assert str(ck._root('gs://bucket/run1')) == 'gs://bucket/run1'
    # Local relative paths still absolutize (orbax requires absolute).
    assert str(ck._root('relative/dir')).startswith('/')


def test_async_save_overlaps_training(tmp_path):
    """blocking=False returns before the write finalizes (training keeps
    stepping); `wait()` finalizes; `latest_step` never selects an
    in-flight save. Overlapping saves serialize safely."""
    from distributed_dot_product_tpu.utils.checkpoint import wait

    step, params, opt_state, batch = _setup()
    ck = str(tmp_path / 'async')
    p, o = params, opt_state
    for i in range(1, 4):
        p, o, loss = step(p, o, batch)
        save(ck, TrainState(i, p, o), blocking=False)
        # the loop continues immediately; a subsequent save waits for the
        # previous flush internally, so this sequence is the real pattern
    wait()
    assert latest_step(ck) == 3
    got = restore(ck, TrainState(0, params, opt_state))
    for a, b in zip(jax.tree.leaves(got.params), jax.tree.leaves(p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # async overwrite of an existing step: backup dance still crash-safe
    save(ck, TrainState(3, p, o), blocking=False)
    wait()
    import os
    names = set(os.listdir(ck))
    assert 'step_000000003' in names
    assert not any(n.endswith('.replaced') for n in names)


def test_async_resave_same_step_without_overwrite(tmp_path):
    """A second async save right after a non-overwrite async one must
    wait for the first flush (no stale filesystem view): same-step
    re-save goes through the backup dance instead of orbax's
    'destination already exists' error (the round-4 review repro)."""
    step, params, opt_state, batch = _setup()
    ck = str(tmp_path / 'resave')
    p, o, _ = step(params, opt_state, batch)
    save(ck, TrainState(1, p, o), blocking=False)
    p2, o2, _ = step(p, o, batch)
    save(ck, TrainState(1, p2, o2), blocking=False)  # must not raise
    from distributed_dot_product_tpu.utils.checkpoint import wait
    wait()
    got = restore(ck, TrainState(0, params, opt_state))
    for a, b in zip(jax.tree.leaves(got.params), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
