# -*- coding: utf-8 -*-
"""
Speculative decoding at the serving layer (serve/spec.py proposers,
engine verify-k/rollback programs, scheduler spec ticks).

The standing contract: greedy verification makes a speculative stream
TOKEN-FOR-TOKEN IDENTICAL to the non-speculative stream on the same
decode impl — the proposer is an untrusted accelerator, so every test
here compares spec runs against their non-spec twins, including under
the stuck+NaN fault cocktail on both cache layouts and both decode
impls. The obs tests pin that a spec-decoded request reconstructs from
the JSONL event log alone with its accepted-token record.
"""

import numpy as np
import pytest

from distributed_dot_product_tpu.obs.events import EventLog, validate_file
from distributed_dot_product_tpu.obs.timeline import reconstruct
from distributed_dot_product_tpu.serve import (
    KernelEngine, Readiness, RejectedError, Scheduler, ServeConfig,
)
from distributed_dot_product_tpu.serve.spec import (
    DraftEngineProposer, NgramProposer, make_draft_engine, ngram_propose,
)
from distributed_dot_product_tpu.utils.faults import (
    ServeFaultInjector, ServeFaultPlan,
)
from distributed_dot_product_tpu.utils.tracing import MetricsRegistry

VOCAB = 16


# -- ngram lookahead ----------------------------------------------------

def test_ngram_propose_basic():
    # Suffix [2, 3] recurred at position 1; full-k continuation wins.
    assert ngram_propose([1, 2, 3, 9, 8, 2, 3], 2) == [9, 8]
    # Nothing recurs -> no proposal (the slot decodes normally).
    assert ngram_propose([1, 2, 3, 4], 3) == []
    assert ngram_propose([5], 3) == []
    assert ngram_propose([1, 2, 1, 2], 0) == []


def test_ngram_propose_prefers_full_continuation():
    """On a cyclic tail the MOST RECENT match truncates at the end of
    history — the proposer must fall back to an occurrence that can
    supply the full k guesses (that's where lookahead pays)."""
    h = [7] * 10
    assert ngram_propose(h, 4) == [7, 7, 7, 7]
    h = [1, 2, 3, 4] * 4
    assert ngram_propose(h, 4) == [1, 2, 3, 4]


def test_ngram_proposer_caps_to_budget():
    p = NgramProposer()
    out = p.propose_batch([(0, [7] * 10, 2), (1, [1, 2, 3, 4], 4)], 4)
    assert out == {0: [7, 7]}        # slot 1: nothing recurs
    with pytest.raises(ValueError):
        NgramProposer(max_ngram=0)


# -- draft proposer -----------------------------------------------------

def test_draft_proposer_cache_tracks_committed_stream():
    """Propose → commit → end_step leaves the draft cache holding
    exactly history[:-1] rows (acceptance-prefix rollback on the
    draft's own slot cache), whatever was accepted."""
    target = KernelEngine(slots=2, t_max=64, vocab=VOCAB,
                          decode_impl='xla')
    prop = DraftEngineProposer(make_draft_engine(target))
    hist = [3, 1, 4, 1, 5]
    prop.start(0, hist)
    assert int(prop.engine.lengths()[0]) == len(hist) - 1
    out = prop.propose_batch([(0, hist, 3)], 3)
    guesses = out.get(0, [])
    assert 1 <= len(guesses) <= 3
    # Pretend verify accepted 1 guess and committed 2 tokens.
    committed = [guesses[0], 9]
    prop.commit(0, committed, 1)
    prop.end_step()
    hist = hist + committed
    assert int(prop.engine.lengths()[0]) == len(hist) - 1
    # A slot it never drafted for must not roll anything back.
    prop.commit(1, [5], 0)
    prop.end_step()
    assert int(prop.engine.lengths()[1]) == 0
    prop.reset(0)
    assert int(prop.engine.lengths()[0]) == 0


def test_make_draft_engine_defaults_mirror_target():
    target = KernelEngine(slots=3, t_max=32, vocab=VOCAB, heads=2,
                          head_dim=4, seed=9, decode_impl='xla',
                          cache_mode='paged', page_size=8)
    draft = make_draft_engine(target)
    assert (draft.slots, draft.t_max, draft.vocab) == (3, 32, VOCAB)
    assert (draft.heads, draft.head_dim, draft.seed) == (2, 4, 9)
    assert draft.cache_mode == 'slab'     # the twin never pages


# -- stream identity ----------------------------------------------------

def _mk_sched(spec, cache_mode, *, decode_impl='xla', slots=3,
              t_max=64, max_new=12, spec_k=4, injector=None,
              event_log=None, seed=0):
    kw = {}
    if cache_mode == 'paged':
        kw.update(cache_mode='paged', page_size=8, pages=24)
    eng = KernelEngine(slots=slots, t_max=t_max, vocab=VOCAB, heads=2,
                       head_dim=4, prefill_chunk=4, seed=seed,
                       decode_impl=decode_impl, **kw)
    cfg = ServeConfig(queue_limit=16, max_new_tokens=max_new,
                      watchdog=False, evict_before_reject=False,
                      spec=spec, spec_k=spec_k)
    return Scheduler(eng, cfg, registry=MetricsRegistry(),
                     fault_injector=injector, event_log=event_log)


def _drive(sched, n_req=6, seed=7, interleave=False):
    rng = np.random.RandomState(seed)
    rejected = {}
    for i in range(n_req):
        p = [int(x) for x in rng.randint(1, VOCAB,
                                         size=rng.randint(2, 12))]
        try:
            sched.submit(p, request_id=f'r{i}')
        except RejectedError as e:
            rejected[f'r{i}'] = e.reason
        if interleave and i % 3 == 2:
            sched.step()
    results = sched.run_until_idle()
    sched.close()
    return results, rejected


@pytest.mark.parametrize('cache_mode', ['slab', 'paged'])
@pytest.mark.parametrize('spec', ['ngram', 'draft'])
def test_spec_streams_token_identical(cache_mode, spec):
    """Every request's status and FULL token stream match the non-spec
    run exactly — on both cache layouts, both proposers."""
    base, _ = _drive(_mk_sched(None, cache_mode))
    got, _ = _drive(_mk_sched(spec, cache_mode))
    assert set(base) == set(got)
    for rid in base:
        assert got[rid].status == base[rid].status, rid
        assert got[rid].tokens == base[rid].tokens, rid


def test_spec_streams_token_identical_kernel():
    """Same identity on the fused Pallas decode path (interpreted on
    CPU): the verify-k kernel's streams == the n=1 kernel's."""
    base, _ = _drive(_mk_sched(None, 'slab', decode_impl='kernel'),
                     n_req=4)
    got, _ = _drive(_mk_sched('ngram', 'slab', decode_impl='kernel'),
                    n_req=4)
    for rid in base:
        assert got[rid].status == base[rid].status, rid
        assert got[rid].tokens == base[rid].tokens, rid


def test_spec_amortizes_steps_and_reports_histograms():
    """A repetitive prompt: the run commits its tokens in FEWER decode
    dispatches than tokens generated, accepted-tokens/step > 2 through
    the serve.spec histograms (the ISSUE acceptance scenario, pinned
    on CPU with the n-gram proposer)."""
    eng = KernelEngine(slots=1, t_max=256, vocab=VOCAB,
                       decode_impl='xla', seed=4)
    cfg = ServeConfig(queue_limit=4, max_new_tokens=64, watchdog=False,
                      spec='ngram', spec_k=4)
    sched = Scheduler(eng, cfg, registry=MetricsRegistry())
    sched.submit([1, 2, 3, 1, 2, 3, 1, 2], request_id='r0')
    results = sched.run_until_idle()
    sched.close()
    assert len(results['r0'].tokens) == 64
    snap = sched.registry.snapshot()
    steps = snap['counters']['serve.decode_steps']
    assert steps < 32, f'{steps} dispatches for 64 tokens: no win'
    acc = sched.registry.histogram('serve.spec.accepted_per_step',
                                   buckets=()).summary()
    prop = sched.registry.histogram('serve.spec.proposed_per_step',
                                    buckets=()).summary()
    assert acc['count'] > 0 and prop['count'] >= acc['count']
    assert acc['mean'] > 2.0, f"accepted/step {acc['mean']:.2f} <= 2"


def test_plain_tick_after_dropped_proposals_rolls_back_draft():
    """A tick where the proposer drafted but EVERY proposal was shed
    (nothing guessed / paged reservation dropped them all) rides the
    plain n=1 program — the stateful draft proposer must still get its
    commit/end_step so the rows it speculatively appended roll back.
    Regression: that path skipped the proposer protocol entirely, so
    the draft cache grew ~k+1 rows per tick against 1 committed token
    and drifted into its overflow guard mid-serve."""
    class DropAll(DraftEngineProposer):
        def propose_batch(self, requests, k):
            super().propose_batch(requests, k)   # draft engine steps
            return {}                            # ...all guesses shed

    target = KernelEngine(slots=2, t_max=24, vocab=VOCAB, heads=2,
                          head_dim=4, prefill_chunk=4, seed=0,
                          decode_impl='xla')
    prop = DropAll(make_draft_engine(target))
    cfg = ServeConfig(queue_limit=8, max_new_tokens=12, watchdog=False,
                      spec_k=3)

    def draft_in_sync(s):
        # Between ticks the draft cache of an active slot holds exactly
        # history[:-1] = prompt + produced − 1 rows (the proposer's
        # documented invariant) — the drift the regression caused.
        lens = np.asarray(prop.engine.lengths())
        for slot in s._slots:
            if slot.state.name == 'ACTIVE' and slot.request is not None:
                expected = len(slot.request.prompt) + slot.produced - 1
                assert lens[slot.index] == expected, (
                    f'slot {slot.index}: draft cache at '
                    f'{lens[slot.index]} rows, committed stream at '
                    f'{expected} — rollback missed')

    sched = Scheduler(target, cfg, registry=MetricsRegistry(),
                      proposer=prop, on_tick=draft_in_sync)
    rng = np.random.RandomState(3)
    for i in range(4):
        sched.submit([int(x) for x in rng.randint(1, VOCAB, size=5)],
                     request_id=f'r{i}')
    got = sched.run_until_idle()     # overflow would raise mid-drain
    sched.close()
    # Same traffic through a non-spec scheduler for the identity check.
    eng2 = KernelEngine(slots=2, t_max=24, vocab=VOCAB, heads=2,
                        head_dim=4, prefill_chunk=4, seed=0,
                        decode_impl='xla')
    sched2 = Scheduler(eng2, ServeConfig(queue_limit=8,
                                         max_new_tokens=12,
                                         watchdog=False),
                       registry=MetricsRegistry())
    rng = np.random.RandomState(3)
    for i in range(4):
        sched2.submit([int(x) for x in rng.randint(1, VOCAB, size=5)],
                      request_id=f'r{i}')
    base = sched2.run_until_idle()
    sched2.close()
    for rid in base:
        assert got[rid].tokens == base[rid].tokens, rid


def test_spec_mixed_batch_with_non_spec_slot():
    """A slot whose history never recurs rides the same verify tick
    with counts=1 (no proposals) — both streams still match their
    non-spec twins."""
    prompts = {'cyc': [1, 2, 3] * 3, 'rnd': [9, 4, 11, 2, 7]}
    base = {}
    sched = _mk_sched(None, 'slab', slots=2, max_new=16)
    for rid, p in prompts.items():
        sched.submit(p, request_id=rid)
    base = sched.run_until_idle()
    sched.close()
    sched = _mk_sched('ngram', 'slab', slots=2, max_new=16)
    for rid, p in prompts.items():
        sched.submit(p, request_id=rid)
    got = sched.run_until_idle()
    sched.close()
    for rid in prompts:
        assert got[rid].tokens == base[rid].tokens, rid


def test_spec_respects_max_new_tokens_and_eos():
    """A verify commit never overshoots the token budget, and an EOS
    inside the accepted prefix truncates the commit exactly where the
    sequential stream would stop."""
    base_s = _mk_sched(None, 'slab', slots=1, max_new=7)
    base_s.submit([1, 2, 3] * 3, request_id='r0')
    base = base_s.run_until_idle()
    base_s.close()
    eos = base['r0'].tokens[3] if len(base['r0'].tokens) > 3 else None
    for eos_id in (None, eos):
        sched = _mk_sched('ngram', 'slab', slots=1, max_new=7)
        sched.cfg.eos_id = eos_id
        sched.submit([1, 2, 3] * 3, request_id='r0')
        got = sched.run_until_idle()
        sched.close()
        ref_s = _mk_sched(None, 'slab', slots=1, max_new=7)
        ref_s.cfg.eos_id = eos_id
        ref_s.submit([1, 2, 3] * 3, request_id='r0')
        ref = ref_s.run_until_idle()
        ref_s.close()
        assert got['r0'].tokens == ref['r0'].tokens
        assert got['r0'].status == ref['r0'].status
        assert len(got['r0'].tokens) <= 7


# -- fault cocktail -----------------------------------------------------

TERMINAL = {'completed', 'deadline_expired', 'evicted', 'abandoned',
            'failed_nan', 'rejected'}


@pytest.mark.parametrize('cache_mode,decode_impl',
                         [('slab', 'xla'), ('slab', 'kernel'),
                          ('paged', 'xla'), ('paged', 'kernel')])
def test_spec_soak_fault_cocktail_identical(cache_mode, decode_impl):
    """Stuck step + NaN slot against the SAME seeded burst, spec vs
    non-spec: every completed request's stream is bit-identical, every
    request terminal or typed, readiness restored — the quarantine/
    requeue churn must not leak a single speculative token."""
    def run(spec):
        plan = ServeFaultPlan(stuck_at_step=2, stuck_seconds=0.2,
                              nan_at_step=4, nan_slot=1)
        sched = _mk_sched(spec, cache_mode, decode_impl=decode_impl,
                          max_new=4, t_max=32,
                          injector=ServeFaultInjector(plan))
        results, rejected = _drive(sched, n_req=10, interleave=True)
        return sched, results, rejected

    sched_a, base, rej_a = run(None)
    sched_b, got, rej_b = run('ngram')
    assert rej_a == rej_b
    assert set(base) == set(got)
    compared = 0
    for rid in base:
        assert base[rid].status in TERMINAL
        assert got[rid].status in TERMINAL
        if base[rid].status == 'completed' \
                and got[rid].status == 'completed':
            assert got[rid].tokens == base[rid].tokens, rid
            compared += 1
    assert compared >= 4, 'soak too small to witness identity'
    for s in (sched_a, sched_b):
        assert s.registry.snapshot()['counters'][
            'serve.nan_quarantined'] >= 1
        assert s.health.readiness in (Readiness.READY,
                                      Readiness.STOPPED)


# -- observability ------------------------------------------------------

def test_spec_request_reconstructs_from_event_log(tmp_path):
    """A spec-decoded request's full lifecycle — including the
    spec.propose/spec.verify arcs and accepted-token counts —
    reconstructs from the JSONL alone, and the log passes offline
    schema validation."""
    log = EventLog(tmp_path / 'spec.jsonl')
    eng = KernelEngine(slots=1, t_max=256, vocab=VOCAB,
                       decode_impl='xla', seed=4)
    cfg = ServeConfig(queue_limit=4, max_new_tokens=32, watchdog=False,
                      spec='ngram', spec_k=4)
    sched = Scheduler(eng, cfg, registry=MetricsRegistry(),
                      event_log=log)
    sched.submit([1, 2, 3, 1, 2, 3, 1, 2], request_id='r0')
    results = sched.run_until_idle()
    sched.close()
    log.close()
    records, errors = validate_file(log.path)
    assert not errors, errors[:3]
    assert any(r['event'] == 'spec.propose' for r in records)
    tls = reconstruct(log.path)
    tl = tls['r0']
    assert tl.complete, tl.errors
    assert tl.status == 'completed'
    assert tl.tokens == len(results['r0'].tokens) == 32
    assert tl.spec_steps > 0
    assert tl.spec_proposed >= tl.spec_accepted > 0
    # The amortization record reconstructs: committed tokens =
    # accepted + one free token per verify step, plus the plain-tick
    # tokens — so accepted tokens are strictly fewer than the stream.
    assert tl.spec_accepted <= tl.tokens
    # Events carry the per-step accepted counts the histogram saw.
    acc = sched.registry.histogram('serve.spec.accepted_per_step',
                                   buckets=()).summary()
    ev_acc = sum(r['accepted'] for r in records
                 if r['event'] == 'spec.verify')
    assert ev_acc == tl.spec_accepted
    assert acc['count'] == tl.spec_steps


def test_spec_retrace_budget_one_program_per_width():
    """One verify program per width and one rollback program per span
    bucket over a whole serving run — the retrace sentinel (enabled
    suite-wide) would raise on a storm; this pins the totals."""
    from distributed_dot_product_tpu.analysis import retrace
    sched = _mk_sched('ngram', 'slab', slots=2, max_new=16)
    for i, p in enumerate(([1, 2, 3] * 3, [4, 5] * 4)):
        sched.submit(list(p), request_id=f'r{i}')
    sched.run_until_idle()
    sched.close()
    w = sched.cfg.spec_k + 1
    assert retrace.total(f'engine.verify_w{w}') == 1
