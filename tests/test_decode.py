# -*- coding: utf-8 -*-
"""
KV-cache decode path (models/decode.py): token-by-token decoding must
reproduce the training kernels' causal attention over the same sequence
— prefill + N decode steps == one flash_attention(causal=True) call, for
every knob the decode path carries (GQA, window, ALiBi, segments).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_dot_product_tpu.models.decode import (
    append_kv, decode_attention, init_cache,
)
from distributed_dot_product_tpu.ops.pallas_attention import flash_attention

B, H, T, D = 2, 4, 48, 16
PREFILL = 32


def _seq(hkv=H, key=0):
    ks = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, hkv, T, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, hkv, T, D), jnp.float32)
    return q, k, v


def _decode_all(q, k, v, t_max=T, **kw):
    """Prefill the first PREFILL positions, then decode the rest one
    token at a time; returns the decode-phase outputs."""
    hkv = k.shape[1]
    cache = init_cache(B, hkv, t_max, D, dtype=jnp.float32)
    cache = append_kv(cache, k[:, :, :PREFILL], v[:, :, :PREFILL])
    step = jax.jit(lambda q1, k1, v1, c: (
        lambda c2: (c2, decode_attention(q1, c2, **kw)))(
            append_kv(c, k1, v1)))
    outs = []
    for t in range(PREFILL, T):
        cache, o = step(q[:, :, t:t + 1], k[:, :, t:t + 1],
                        v[:, :, t:t + 1], cache)
        outs.append(o)
    assert int(cache.length) == T
    return jnp.concatenate(outs, axis=2)


@pytest.mark.parametrize('hkv', [H, 2, 1])
def test_decode_matches_training_kernel(hkv):
    q, k, v = _seq(hkv)
    got = _decode_all(q, k, v)
    want = flash_attention(q, k, v, causal=True)[:, :, PREFILL:]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_decode_window():
    q, k, v = _seq(key=1)
    got = _decode_all(q, k, v, window=8)
    want = flash_attention(q, k, v, causal=True, window=8)[:, :, PREFILL:]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_decode_alibi():
    slopes = jnp.asarray([2.0 ** -(i + 1) for i in range(H)])
    q, k, v = _seq(key=2)
    got = _decode_all(q, k, v, alibi_slopes=slopes)
    want = flash_attention(q, k, v, causal=True,
                           alibi_slopes=slopes)[:, :, PREFILL:]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_decode_segments():
    """Packed multi-turn serving: cached-side ids + query-row ids."""
    q, k, v = _seq(key=3)
    seg_full = jnp.broadcast_to((jnp.arange(T) // 20)[None], (B, T)
                                ).astype(jnp.int32)
    hkv = k.shape[1]
    cache = init_cache(B, hkv, T, D, dtype=jnp.float32)
    cache = append_kv(cache, k[:, :, :PREFILL], v[:, :, :PREFILL])
    outs = []
    for t in range(PREFILL, T):
        cache = append_kv(cache, k[:, :, t:t + 1], v[:, :, t:t + 1])
        # segment ids for positions not yet appended are irrelevant: the
        # causal mask already excludes them — pass the full array.
        outs.append(decode_attention(
            q[:, :, t:t + 1], cache, segment_ids=seg_full,
            seg_q=seg_full[:, t:t + 1]))
    got = jnp.concatenate(outs, axis=2)
    want = flash_attention(
        q, k, v, causal=True,
        segment_ids=(seg_full[:, None], seg_full[:, None]))[:, :, PREFILL:]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_decode_multi_row_and_t_max_headroom():
    """n>1 query rows per step, and a cache larger than the sequence
    (the serving configuration: t_max = context limit)."""
    q, k, v = _seq(key=4)
    cache = init_cache(B, H, T + 64, D, dtype=jnp.float32)
    cache = append_kv(cache, k[:, :, :PREFILL], v[:, :, :PREFILL])
    cache = append_kv(cache, k[:, :, PREFILL:], v[:, :, PREFILL:])
    out = decode_attention(q[:, :, PREFILL:], cache)
    want = flash_attention(q, k, v, causal=True)[:, :, PREFILL:]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5)


def test_decode_validation():
    cache = init_cache(B, 3, T, D)
    with pytest.raises(ValueError, match='multiple'):
        decode_attention(jnp.zeros((B, H, 1, D)), cache)
    with pytest.raises(ValueError, match='t_max'):
        append_kv(cache, jnp.zeros((B, 3, T + 1, D)),
                  jnp.zeros((B, 3, T + 1, D)))
    with pytest.raises(ValueError, match='seg_q'):
        decode_attention(jnp.zeros((B, 3, 1, D)), cache,
                         segment_ids=jnp.zeros((B, T), jnp.int32))


def test_append_overflow_raises_eagerly():
    """Cumulative overflow past t_max must raise when the length is
    concrete (the serving-loop case) instead of silently clamping the
    write onto the newest slot (the round-4 review repro)."""
    cache = init_cache(B, H, 4, D, dtype=jnp.float32)
    one = jnp.ones((B, H, 1, D))
    for _ in range(4):
        cache = append_kv(cache, one, one)
    with pytest.raises(ValueError, match='overflow'):
        append_kv(cache, one, one)


@pytest.mark.parametrize('kwargs', [
    dict(),
    dict(num_kv_heads=2),
    dict(use_rope=True),
    dict(num_kv_heads=2, use_rope=True, window=12),
    dict(alibi_slopes=tuple(2.0 ** -(i + 1) for i in range(4))),
    dict(qk_quant='int8'),
])
def test_module_decode_matches_causal_forward(kwargs):
    """The flagship-module decode surface: prefill + token-by-token
    module.decode must reproduce the module's causal __call__ over the
    same inputs, for every knob combination the decode path carries."""
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn,
    )
    DIM = 32
    m = DistributedDotProductAttn(key_dim=DIM, num_heads=4, causal=True,
                                  softmax_impl='flash', distributed=False,
                                  **kwargs)
    x = jax.random.normal(jax.random.key(0), (B, T, DIM))
    params = m.init(jax.random.key(1), x[:, :8], x[:, :8], x[:, :8], None)
    want = m.apply(params, x, x, x, None)

    cache = m.make_decode_cache(B, T)
    # Prefill the first PREFILL positions via the flash-kernel prefill
    # method, then decode token by token.
    cache, out0 = m.apply(params, x[:, :PREFILL], x[:, :PREFILL],
                          x[:, :PREFILL], cache, method='prefill')
    outs = [out0]
    step = jax.jit(lambda p, xt, c: m.apply(p, xt, xt, xt, c,
                                            method='decode'))
    for t in range(PREFILL, T):
        cache, o = step(params, x[:, t:t + 1], cache)
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-5)


def test_module_decode_requires_causal():
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn,
    )
    m = DistributedDotProductAttn(key_dim=32, num_heads=4,
                                  distributed=False)
    x = jnp.zeros((B, 4, 32))
    params = m.init(jax.random.key(0), x, x, x, None)
    cache = m.make_decode_cache(B, 16)
    with pytest.raises(ValueError, match='causal'):
        m.apply(params, x, x, x, cache, method='decode')


def test_module_decode_segments():
    """Packed multi-turn serving through the module surface: per-step
    segment_ids + the cached positions' ids must match the causal
    forward with the same packing."""
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn,
    )
    DIM = 32
    m = DistributedDotProductAttn(key_dim=DIM, num_heads=4, causal=True,
                                  softmax_impl='flash', distributed=False)
    x = jax.random.normal(jax.random.key(5), (B, T, DIM))
    seg = jnp.broadcast_to((jnp.arange(T) // 20)[None], (B, T)
                           ).astype(jnp.int32)
    params = m.init(jax.random.key(1), x[:, :8], x[:, :8], x[:, :8], None)
    want = m.apply(params, x, x, x, None, segment_ids=seg)

    cache = m.make_decode_cache(B, T)
    cache, out0 = m.apply(params, x[:, :PREFILL], x[:, :PREFILL],
                          x[:, :PREFILL], cache, method='decode',
                          segment_ids=seg[:, :PREFILL], seg_cache=seg)
    outs = [out0]
    for t in range(PREFILL, T):
        cache, o = m.apply(params, x[:, t:t + 1], x[:, t:t + 1],
                           x[:, t:t + 1], cache, method='decode',
                           segment_ids=seg[:, t:t + 1], seg_cache=seg)
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-5)


def test_module_midstream_prefill():
    """prefill from a NON-empty cache (decode a few tokens, prefill a
    chunk, decode the rest): pins the causal_offset=start math — row
    positions start+i vs buffer columns — which the fresh-cache tests
    never reach."""
    from distributed_dot_product_tpu.models.attention import (
        DistributedDotProductAttn,
    )
    DIM = 32
    m = DistributedDotProductAttn(key_dim=DIM, num_heads=4, causal=True,
                                  use_rope=True, window=20,
                                  softmax_impl='flash', distributed=False)
    x = jax.random.normal(jax.random.key(7), (B, T, DIM))
    params = m.init(jax.random.key(1), x[:, :8], x[:, :8], x[:, :8], None)
    want = m.apply(params, x, x, x, None)

    cache = m.make_decode_cache(B, T)
    outs = []
    for t in range(8):                       # decode 8 single tokens
        cache, o = m.apply(params, x[:, t:t + 1], x[:, t:t + 1],
                           x[:, t:t + 1], cache, method='decode')
        outs.append(o)
    cache, o = m.apply(params, x[:, 8:PREFILL], x[:, 8:PREFILL],
                       x[:, 8:PREFILL], cache, method='prefill')
    outs.append(o)                           # mid-stream prefill chunk
    for t in range(PREFILL, T):
        cache, o = m.apply(params, x[:, t:t + 1], x[:, t:t + 1],
                           x[:, t:t + 1], cache, method='decode')
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-5)


def test_int8_cache_mirror_matches_onthefly():
    """The append-time int8 mirror must score exactly like on-the-fly
    re-quantization of the raw cache (per-row rule, append-only rows)."""
    q, k, v = _seq(hkv=2, key=8)
    with_mirror = init_cache(B, 2, T, D, dtype=jnp.float32,
                             qk_quant='int8')
    without = init_cache(B, 2, T, D, dtype=jnp.float32)
    for c0, c1 in ((0, PREFILL), (PREFILL, T)):
        with_mirror = append_kv(with_mirror, k[:, :, c0:c1],
                                v[:, :, c0:c1])
        without = append_kv(without, k[:, :, c0:c1], v[:, :, c0:c1])
    a = decode_attention(q[:, :, -1:], with_mirror, qk_quant='int8')
    b2 = decode_attention(q[:, :, -1:], without, qk_quant='int8')
    np.testing.assert_allclose(np.asarray(a), np.asarray(b2), atol=1e-6)


def test_int8_mirror_exact_with_mixed_dtypes():
    """A bf16 cache fed fp32 k_new must quantize the CACHE-dtype value,
    keeping the mirror bit-identical to on-the-fly re-quantization of
    the stored buffer (the round-4 review repro: quantizing the fp32
    input diverged by ~4e-3)."""
    ks = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(ks[0], (B, 2, 1, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, 2, 16, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, 2, 16, D), jnp.float32)
    with_mirror = init_cache(B, 2, 16, D, dtype=jnp.bfloat16,
                             qk_quant='int8')
    without = init_cache(B, 2, 16, D, dtype=jnp.bfloat16)
    with_mirror = append_kv(with_mirror, k, v)   # fp32 into bf16 cache
    without = append_kv(without, k, v)
    a = decode_attention(q, with_mirror, qk_quant='int8')
    b2 = decode_attention(q, without, qk_quant='int8')
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b2, np.float32), atol=1e-6)
