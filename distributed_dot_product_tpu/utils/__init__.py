# -*- coding: utf-8 -*-
# Note: the reference's utils/ directory has NO __init__.py (implicit
# namespace package — reference SURVEY §2.1); we make it explicit.
