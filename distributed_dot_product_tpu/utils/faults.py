# -*- coding: utf-8 -*-
"""
Deterministic fault injection for exercising every recovery path of the
resilient training driver (:mod:`distributed_dot_product_tpu.train_loop`)
in plain tier-1 CPU tests — no real preemption, flaky disk, or diverging
optimizer required.

Injectable faults (compose freely in one :class:`FaultPlan`):

- **NaN gradients at step S** (``nan_at_steps``): the batch produced by
  the wrapped batch function has every float leaf poisoned with NaN, so
  the compiled step's loss AND gradients come out NaN and the in-step
  all-finite guard must skip the update. One-shot by default: after a
  rollback the replayed step gets the clean batch (recovery provable).
- **Transient checkpoint I/O errors** (``io_error_saves``): the first N
  ``checkpoint.save`` attempts raise ``OSError`` (disk full / flaky
  store), exercising the driver's retry + exponential backoff.
- **Crash mid-save** (``crash_in_save_at_step``): when the save for step
  S starts, an unfinalized ``*.orbax-checkpoint-tmp`` partial write is
  left on disk and :class:`SimulatedCrash` (a ``BaseException``, so no
  retry/except-Exception handler swallows it) propagates — the process
  "died". Recovery: ``latest_step`` must skip the partial write and a
  restarted driver resumes from the newest finalized step.
- **Synthetic SIGTERM** (``sigterm_at_step``): a real ``SIGTERM`` is
  delivered to this process when the batch for step S is requested —
  exactly how a TPU preemption notice lands mid-loop — exercising the
  driver's catch → final blocking save → clean exit path.

Env knobs (picked up by :func:`plan_from_env`; the driver reads them when
no explicit injector is passed, so a shell can fault a real run):

- ``DDP_TPU_FAULT_NAN_STEPS=5,7``      inject NaN at steps 5 and 7
- ``DDP_TPU_FAULT_IO_ERRORS=2``        first 2 save attempts raise OSError
- ``DDP_TPU_FAULT_CRASH_SAVE_STEP=10`` crash mid-save of step 10
- ``DDP_TPU_FAULT_SIGTERM_STEP=20``    deliver SIGTERM at step 20
"""

import dataclasses
import os
import signal
import time
from typing import FrozenSet, Optional, Tuple

import jax
import jax.numpy as jnp

from distributed_dot_product_tpu.obs import events as obs_events
from distributed_dot_product_tpu.utils import checkpoint as _ckpt

__all__ = ['FaultPlan', 'FaultInjector', 'SimulatedCrash', 'plan_from_env',
           'poison_batch', 'ServeFaultPlan', 'ServeFaultInjector',
           'serve_plan_from_env', 'burst_prompts',
           'ChaosPlan', 'ChaosInjector', 'chaos_plan_from_env',
           'ChaosSpecError']


class SimulatedCrash(BaseException):
    """Raised to simulate the process dying mid-save. Derives from
    ``BaseException`` so no retry loop or ``except Exception`` recovery
    path can accidentally swallow a "dead" process."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to inject, and when. Immutable; runtime countdown state lives
    in the :class:`FaultInjector`."""
    nan_at_steps: FrozenSet[int] = frozenset()
    io_error_saves: int = 0
    crash_in_save_at_step: Optional[int] = None
    sigterm_at_step: Optional[int] = None
    fire_once: bool = True

    def any(self):
        return bool(self.nan_at_steps or self.io_error_saves
                    or self.crash_in_save_at_step is not None
                    or self.sigterm_at_step is not None)


def plan_from_env(environ=None) -> FaultPlan:
    """Build a :class:`FaultPlan` from the ``DDP_TPU_FAULT_*`` env knobs
    (an empty plan when none are set)."""
    env = os.environ if environ is None else environ

    def _int(name):
        v = env.get(name)
        return int(v) if v not in (None, '') else None

    nan_steps = frozenset(
        int(s) for s in env.get('DDP_TPU_FAULT_NAN_STEPS', '').split(',')
        if s.strip())
    return FaultPlan(
        nan_at_steps=nan_steps,
        io_error_saves=_int('DDP_TPU_FAULT_IO_ERRORS') or 0,
        crash_in_save_at_step=_int('DDP_TPU_FAULT_CRASH_SAVE_STEP'),
        sigterm_at_step=_int('DDP_TPU_FAULT_SIGTERM_STEP'),
    )


def poison_batch(batch):
    """Every floating leaf of ``batch`` becomes all-NaN (ints/bools/None
    pass through): the step's loss and every gradient leaf come out NaN,
    which is exactly the "diverged step" the guard must catch.

    Raises ``ValueError`` when the batch has NO floating leaf (e.g. an
    integer-token LM batch): NaN cannot be injected through such inputs,
    and silently not injecting would let an operator believe the guard
    path was exercised when it never ran.
    """
    hit = []

    def _poison(x):
        if x is None or not hasattr(x, 'dtype'):
            return x
        if jnp.issubdtype(x.dtype, jnp.floating):
            hit.append(True)
            return jnp.full_like(x, jnp.nan)
        return x

    out = jax.tree.map(_poison, batch, is_leaf=lambda x: x is None)
    if not hit:
        raise ValueError(
            'cannot inject NaN: the batch has no floating-point leaves '
            '(integer-token batches reach the loss through an embedding '
            '— poison a float input or test the guard with a float-batch '
            'model instead)')
    return out


def _step_of(target_dir):
    name = getattr(target_dir, 'name', str(target_dir))
    try:
        return int(str(name).rsplit('step_', 1)[-1])
    except ValueError:
        return None


class FaultInjector:
    """Runtime for a :class:`FaultPlan`.

    Use as a context manager (installs/uninstalls the checkpoint save
    hook) and wrap the driver's batch function::

        plan = FaultPlan(nan_at_steps=frozenset({3}), io_error_saves=1)
        with FaultInjector(plan) as inj:
            run_training(step_fn, state, inj.wrap_batch_fn(batch_fn), cfg)

    The driver also accepts ``fault_injector=inj`` and wires both seams
    itself.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._io_errors_left = plan.io_error_saves
        self._nan_fired = set()
        self._crash_fired = False
        self._sigterm_fired = False
        # ONE bound-method object, captured here: `self._save_hook` would
        # mint a fresh object per attribute access, breaking the identity
        # checks below (install exclusivity / uninstall ownership).
        self._hook = self._save_hook

    # -- install / uninstall the checkpoint-backend seam ---------------
    def install(self):
        if _ckpt._SAVE_FAULT_HOOK is not None \
                and _ckpt._SAVE_FAULT_HOOK is not self._hook:
            raise RuntimeError('another FaultInjector is already installed')
        _ckpt._SAVE_FAULT_HOOK = self._hook
        return self

    def uninstall(self):
        if _ckpt._SAVE_FAULT_HOOK is self._hook:
            _ckpt._SAVE_FAULT_HOOK = None

    __enter__ = install

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- batch-function seam (NaN injection + synthetic SIGTERM) -------
    def wrap_batch_fn(self, batch_fn):
        def wrapped(step):
            self.on_step(step)
            batch = batch_fn(step)
            if self._should_nan(step):
                batch = poison_batch(batch)
            return batch
        return wrapped

    def on_step(self, step):
        """Per-step trigger point (the driver calls this even when it owns
        batch construction): delivers the synthetic SIGTERM."""
        p = self.plan
        if p.sigterm_at_step is not None and step == p.sigterm_at_step \
                and not self._sigterm_fired:
            self._sigterm_fired = True
            obs_events.emit('fault.inject', kind='sigterm', step=step)
            # A REAL signal through the OS, not a direct handler call —
            # the driver's installed handler (and only it) must catch it.
            os.kill(os.getpid(), signal.SIGTERM)

    def _should_nan(self, step):
        if step not in self.plan.nan_at_steps:
            return False
        if self.plan.fire_once:
            if step in self._nan_fired:
                return False
            self._nan_fired.add(step)
        obs_events.emit('fault.inject', kind='nan_batch', step=step)
        return True

    # -- checkpoint save seam ------------------------------------------
    def _save_hook(self, target_dir):
        p = self.plan
        if p.crash_in_save_at_step is not None and not self._crash_fired \
                and _step_of(target_dir) == p.crash_in_save_at_step:
            if p.fire_once:
                self._crash_fired = True
            # Leave the partial write a real crash mid-save leaves: an
            # unfinalized orbax temp directory (plus a marker file so the
            # dir is non-empty on every backend).
            partial = target_dir.parent / (
                target_dir.name + '.orbax-checkpoint-tmp-0')
            partial.mkdir(parents=True, exist_ok=True)
            (partial / 'partial_write').write_text('simulated crash')
            obs_events.emit('fault.inject', kind='crash_in_save',
                            step=_step_of(target_dir))
            raise SimulatedCrash(
                f'simulated crash mid-save of {target_dir}')
        if self._io_errors_left > 0:
            self._io_errors_left -= 1
            obs_events.emit('fault.inject', kind='io_error',
                            step=_step_of(target_dir))
            raise OSError(
                f'injected transient checkpoint I/O failure '
                f'({self._io_errors_left} more to come)')


# ---------------------------------------------------------------------------
# Serving-path fault injection (serve/scheduler.py)
#
# The decode serving layer has its own failure modes, orthogonal to the
# training driver's: a compiled step that hangs (driver bug, pathological
# retrace, wedged runtime), NaN logits poisoning ONE slot of the batch, a
# request burst overflowing admission, and a client abandoning a stream
# mid-generation. Each is injectable deterministically so tier-1 CPU tests
# exercise the watchdog, the per-slot quarantine, load shedding, and slot
# reclamation — and the same knobs fault a real serving run from the shell.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ServeFaultPlan:
    """What to inject into the serving loop, and when. ``fire_once``
    (default) makes every fault one-shot so recovery is provable."""
    stuck_at_step: Optional[int] = None     # decode step index to stall
    stuck_seconds: float = 0.75             # how long the stall lasts
    nan_at_step: Optional[int] = None       # decode step to poison
    nan_slot: int = 0                       # slot whose logits go NaN
    abandon_request: Optional[int] = None   # k-th ADMITTED request (0-based)
    abandon_after_tokens: int = 2           # ...after this many tokens
    burst: int = 0                          # request-burst size (drivers)
    fire_once: bool = True

    def any(self):
        return (self.stuck_at_step is not None
                or self.nan_at_step is not None
                or self.abandon_request is not None
                or self.burst > 0)


def serve_plan_from_env(environ=None) -> ServeFaultPlan:
    """Build a :class:`ServeFaultPlan` from ``DDP_TPU_FAULT_*`` env knobs
    (an empty plan when none are set):

    - ``DDP_TPU_FAULT_STUCK_STEP=5``          stall decode step 5
    - ``DDP_TPU_FAULT_STUCK_SECONDS=1.5``     ...for 1.5 s
    - ``DDP_TPU_FAULT_NAN_DECODE_STEP=8``     NaN logits at decode step 8
    - ``DDP_TPU_FAULT_NAN_DECODE_SLOT=2``     ...in slot 2
    - ``DDP_TPU_FAULT_ABANDON_REQUEST=3``     4th admitted request abandons
    - ``DDP_TPU_FAULT_ABANDON_AFTER=4``       ...after 4 tokens
    - ``DDP_TPU_FAULT_BURST=64``              drivers submit a 64-request
      burst (examples/serve_lm.py, scripts/smoke_serve.sh)
    - ``DDP_TPU_FAULT_NAN_REPEAT=1``          the NaN fault fires on EVERY
      step from ``nan_at_step`` on (``fire_once=False``) — the
      quarantine STORM that exhausts ``max_requeues`` into typed
      failures and trips the flight recorder's nan_storm auto-dump
      (obs/flight.py), instead of the default one-shot glitch
    """
    env = os.environ if environ is None else environ

    def _int(name):
        v = env.get(name)
        return int(v) if v not in (None, '') else None

    def _float(name, default):
        v = env.get(name)
        return float(v) if v not in (None, '') else default

    def _int_default(name, default):
        # Explicit None check: `or default` would rewrite a deliberate
        # 0 (e.g. abandon after 0 tokens) to the default.
        v = _int(name)
        return default if v is None else v

    return ServeFaultPlan(
        stuck_at_step=_int('DDP_TPU_FAULT_STUCK_STEP'),
        stuck_seconds=_float('DDP_TPU_FAULT_STUCK_SECONDS', 0.75),
        nan_at_step=_int('DDP_TPU_FAULT_NAN_DECODE_STEP'),
        nan_slot=_int_default('DDP_TPU_FAULT_NAN_DECODE_SLOT', 0),
        abandon_request=_int('DDP_TPU_FAULT_ABANDON_REQUEST'),
        abandon_after_tokens=_int_default('DDP_TPU_FAULT_ABANDON_AFTER',
                                          2),
        burst=_int_default('DDP_TPU_FAULT_BURST', 0),
        fire_once=not _int_default('DDP_TPU_FAULT_NAN_REPEAT', 0),
    )


def burst_prompts(n, prompt_len=8, vocab=64, seed=0):
    """Deterministic request burst: ``n`` prompts of ``prompt_len``
    tokens drawn from ``[0, vocab)`` — the adversarial admission load
    for soak tests and :mod:`scripts/smoke_serve.sh`. Seeded numpy, no
    device work: generating the burst must not perturb the run being
    faulted."""
    import numpy as np
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=prompt_len).astype(np.int32)
            for _ in range(n)]


class ServeFaultInjector:
    """Runtime for a :class:`ServeFaultPlan`. The scheduler calls the
    three hooks at its seams:

    - :meth:`on_decode_step` right before dispatching decode step ``i``
      — a stuck-step plan sleeps here, exactly what a hung compiled
      step looks like to the watchdog (no heartbeat while the host is
      blocked on the device).
    - :meth:`poison_slots` — the per-step NaN mask the engine applies
      to its logits IN-PROGRAM, so the per-slot finite predicate is
      exercised on real NaNs flowing out of the compiled step.
    - :meth:`should_abandon` after each token — mid-stream client
      abandon, keyed by admission order (stable under rescheduling).
    """

    def __init__(self, plan: ServeFaultPlan):
        self.plan = plan
        self._stuck_fired = False
        self._nan_fired = False
        self._abandon_fired = False
        self.stalls_injected = 0
        # Observability sink: the scheduler points this at its own
        # event log so injections land in the same stream as the
        # lifecycle they disrupt; None falls back to the active log.
        self.event_log = None

    def on_decode_step(self, step):
        p = self.plan
        if p.stuck_at_step is not None and step == p.stuck_at_step \
                and not (p.fire_once and self._stuck_fired):
            self._stuck_fired = True
            self.stalls_injected += 1
            obs_events.emit('fault.inject', _log=self.event_log,
                            kind='stuck_step', step=step,
                            seconds=p.stuck_seconds)
            time.sleep(p.stuck_seconds)

    def poison_slots(self, step, n_slots):
        """Bool list of slots whose logits the engine must NaN at this
        step, or None for a clean step. ``fire_once=True`` (default)
        poisons exactly decode step ``nan_at_step`` — a transient
        glitch the quarantine+retry must fully absorb;
        ``fire_once=False`` poisons EVERY step from ``nan_at_step`` on
        — a persistently bad path that must exhaust ``max_requeues``
        into a typed failure instead of retrying forever."""
        p = self.plan
        if p.nan_at_step is None:
            return None
        if p.fire_once:
            if step != p.nan_at_step or self._nan_fired:
                return None
        elif step < p.nan_at_step:
            return None
        self._nan_fired = True
        if not 0 <= p.nan_slot < n_slots:
            raise ValueError(f'nan_slot {p.nan_slot} out of range for '
                             f'{n_slots} slots')
        obs_events.emit('fault.inject', _log=self.event_log,
                        kind='nan_slot', step=step, slot=p.nan_slot)
        return [i == p.nan_slot for i in range(n_slots)]

    def should_abandon(self, admit_index, tokens_done):
        p = self.plan
        if p.abandon_request is None or admit_index != p.abandon_request \
                or tokens_done < p.abandon_after_tokens \
                or (p.fire_once and self._abandon_fired):
            return False
        self._abandon_fired = True
        obs_events.emit('fault.inject', _log=self.event_log,
                        kind='abandon', admit_index=admit_index,
                        tokens_done=tokens_done)
        return True


# ---------------------------------------------------------------------------
# Replica-scoped chaos (serve/replica.py, serve/router.py)
#
# The disaggregated layer's failure domain is a whole decode REPLICA, not
# a slot: a crashed replica takes its in-flight streams, its paged KV and
# its share of the cluster prefix cache down at once. Every knob here is
# keyed by replica name and virtual-time tick so a crash replays
# bit-identically (serve/loadgen.py ChaosSchedule drives crash_due from
# run_trace's on_tick; the router consults the handoff/probe hooks).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """Replica-scoped faults, keyed by name + virtual tick. Immutable;
    runtime one-shot state lives in the :class:`ChaosInjector`."""
    # Kill this replica when the loadgen reaches this tick (name, tick).
    replica_crash: Optional[Tuple[str, int]] = None
    # Kill this replica DURING its next prefill->decode KV handoff —
    # after adopt_prefix, before the router records the placement (the
    # worst moment: pages adopted, stream never admitted).
    crash_in_handoff: Optional[str] = None
    # This replica stops answering router liveness probes (process
    # alive, network dead): loss must come from the probe timeout path.
    probe_blackhole: Optional[str] = None
    # Flip one bit in a live KV page of this replica at this tick
    # (name, page, tick). `page` indexes the replica's TRACKED
    # (registry) pages — sorted order, modulo the tracked count — so a
    # seeded trace corrupts the same prefix page whatever the pool's
    # allocation history; the flip defers to the first tick at/after
    # `tick` with any tracked page.
    page_corrupt: Optional[Tuple[str, int, int]] = None
    # Kill the shared prefill pool at this tick: routing must fall
    # back to flat prefill on the decode replicas, never block.
    prefill_crash: Optional[int] = None
    fire_once: bool = True

    def any(self):
        return (self.replica_crash is not None
                or self.crash_in_handoff is not None
                or self.probe_blackhole is not None
                or self.page_corrupt is not None
                or self.prefill_crash is not None)


class ChaosSpecError(ValueError):
    """A ``DDP_TPU_FAULT_*`` chaos knob holds a malformed spec. The
    message names the knob and its grammar — a typo'd chaos run must
    die loudly, not silently run fault-free."""


def _spec_name(spec):
    return spec


def _spec_tick(spec):
    return int(spec)


def _spec_name_tick(spec):
    name, _, tick = spec.rpartition(':')
    if not name:
        raise ValueError(spec)
    return (name, int(tick))


def _spec_name_page_tick(spec):
    parts = spec.split(':')
    if len(parts) != 3 or not parts[0]:
        raise ValueError(spec)
    return (parts[0], int(parts[1]), int(parts[2]))


# The one knob table: env key -> (plan field, spec parser, grammar).
# Adding a chaos knob is one row; the parser below gives every row the
# same typed-error discipline.
_CHAOS_KNOBS = (
    ('DDP_TPU_FAULT_REPLICA_CRASH', 'replica_crash',
     _spec_name_tick, '<replica>:<tick>'),
    ('DDP_TPU_FAULT_HANDOFF_CRASH', 'crash_in_handoff',
     _spec_name, '<replica>'),
    ('DDP_TPU_FAULT_PROBE_BLACKHOLE', 'probe_blackhole',
     _spec_name, '<replica>'),
    ('DDP_TPU_FAULT_PAGE_CORRUPT', 'page_corrupt',
     _spec_name_page_tick, '<replica>:<page>:<tick>'),
    ('DDP_TPU_FAULT_PREFILL_CRASH', 'prefill_crash',
     _spec_tick, '<tick>'),
)


def chaos_plan_from_env(environ=None) -> ChaosPlan:
    """Build a :class:`ChaosPlan` from the ``DDP_TPU_FAULT_*`` env
    knobs (an empty plan when none are set), table-driven over
    ``_CHAOS_KNOBS``:

    - ``DDP_TPU_FAULT_REPLICA_CRASH=r1:40``   kill replica r1 at tick 40
    - ``DDP_TPU_FAULT_HANDOFF_CRASH=r1``      kill r1 mid-KV-handoff
    - ``DDP_TPU_FAULT_PROBE_BLACKHOLE=r1``    r1 stops answering probes
    - ``DDP_TPU_FAULT_PAGE_CORRUPT=r1:0:40``  flip a bit in r1's
      tracked page #0 at tick 40
    - ``DDP_TPU_FAULT_PREFILL_CRASH=40``      kill the prefill pool at
      tick 40

    Malformed specs raise :class:`ChaosSpecError` naming the knob and
    its grammar."""
    env = os.environ if environ is None else environ
    fields = {}
    for key, field, parse, grammar in _CHAOS_KNOBS:
        spec = env.get(key, '').strip()
        if not spec:
            continue
        try:
            fields[field] = parse(spec)
        except ValueError as exc:
            raise ChaosSpecError(
                f'{key}={spec!r}: expected {grammar}') from exc
    return ChaosPlan(**fields)


class ChaosInjector:
    """Runtime for a :class:`ChaosPlan`. Three hooks, all pure functions
    of plan + one-shot state (no clock reads — chaos timing arrives as
    tick indices from the loadgen, so a seeded trace replays the same
    crash at the same virtual instant every run):

    - :meth:`crash_due` — the loadgen's per-tick hook; returns the name
      of the replica to kill at this tick (once), else None.
    - :meth:`crash_on_handoff` — the router asks right after a KV
      handoff lands on ``target``; True means kill it there.
    - :meth:`blackholed` — the router's prober asks before counting a
      probe answer; True means the replica never answers.
    """

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._crash_fired = False
        self._handoff_fired = False
        self._blackhole_announced = False
        self._corrupt_fired = False
        self._prefill_fired = False
        # Observability sink: the driver points this at the ROUTER's
        # log — injections land next to the loss/recovery arc they
        # cause; None falls back to the active log.
        self.event_log = None

    def crash_due(self, tick):
        p = self.plan
        if p.replica_crash is None:
            return None
        name, at_tick = p.replica_crash
        if tick != at_tick or (p.fire_once and self._crash_fired):
            return None
        self._crash_fired = True
        obs_events.emit('fault.inject', _log=self.event_log,
                        kind='replica_crash', target=name, tick=tick)
        return name

    def crash_on_handoff(self, target):
        p = self.plan
        if p.crash_in_handoff != target \
                or (p.fire_once and self._handoff_fired):
            return False
        self._handoff_fired = True
        obs_events.emit('fault.inject', _log=self.event_log,
                        kind='handoff_crash', target=target)
        return True

    def corrupt_due(self, tick):
        """The loadgen's per-tick corruption hook: at/after the planned
        tick, return ``(replica, page_index)`` once — the ChaosSchedule
        resolves the index over the replica's tracked pages and flips
        one bit host-side. None otherwise."""
        p = self.plan
        if p.page_corrupt is None:
            return None
        name, page, at_tick = p.page_corrupt
        if tick < at_tick or (p.fire_once and self._corrupt_fired):
            return None
        self._corrupt_fired = True
        obs_events.emit('fault.inject', _log=self.event_log,
                        kind='page_corrupt', target=name, page=page,
                        tick=tick)
        return (name, page)

    def prefill_crash_due(self, tick):
        """True exactly once when the planned prefill-pool crash tick
        arrives — the ChaosSchedule kills the pool there."""
        p = self.plan
        if p.prefill_crash is None or tick != p.prefill_crash \
                or (p.fire_once and self._prefill_fired):
            return False
        self._prefill_fired = True
        obs_events.emit('fault.inject', _log=self.event_log,
                        kind='prefill_crash', tick=tick)
        return True

    def blackholed(self, name):
        if self.plan.probe_blackhole != name:
            return False
        # Announce the blackhole once; the probe-miss stream itself is
        # the router's to narrate (replica.probe state=missed).
        if not self._blackhole_announced:
            self._blackhole_announced = True
            obs_events.emit('fault.inject', _log=self.event_log,
                            kind='probe_blackhole', target=name)
        return True
