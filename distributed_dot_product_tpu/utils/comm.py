# -*- coding: utf-8 -*-
"""
Process / topology layer on JAX.

TPU-native replacement for the reference communication layer
(reference utils/comm.py:1-30), which wraps Horovod (``hvd.init()``,
``hvd.rank()``, ``hvd.size()``) and raw mpi4py (``MPI.COMM_WORLD.Barrier``)
and initializes the distributed runtime *at import time*
(reference comm.py:6-10, module.py:19).

Design differences, deliberate:

- **No import-time side effects.** ``init()`` is an explicit entry point;
  single-host (including single-host × 8 TPU chips) needs no init at all
  because every device is visible to the one process.
- **Two notions of rank.** The reference's "rank" is an OS process == one
  GPU. In SPMD JAX the analog depends on where you ask:
  inside a ``shard_map``'ed kernel the rank along the sequence mesh axis is
  ``lax.axis_index(axis_name)`` (a traced, per-shard value); outside, at the
  host level, it is ``jax.process_index()``. ``get_rank``/``get_world_size``
  take an optional ``axis_name`` to select the former.
- **Barriers are implicit.** A shard_map program is one XLA computation;
  collective ordering is fixed at compile time, so the reference's
  ``synchronize()`` barrier before each kernel (reference functions.py:77)
  and its named-collective matching discipline (reference functions.py:95,
  144, 207; README.md:179 flakiness warning) have no equivalent failure mode
  here. ``synchronize()`` is kept for host-level coordination across
  processes (multi-host) and as a no-op otherwise.
"""

import jax
from jax import lax

# Canonical mesh-axis name for the sequence (time) dimension. The reference
# has no name for this because its "axis" is the MPI world itself.
SEQ_AXIS = 'seq'

_initialized = False


def init(coordinator_address=None, num_processes=None, process_id=None,
         local_device_ids=None):
    """Initialize the multi-host runtime (replaces ``hvd.init()`` +
    MPI-threading asserts, reference comm.py:6-10).

    On a single host this is a no-op: one process already sees all local
    devices. On multi-host (one process per host, e.g. a v5e pod slice),
    wraps :func:`jax.distributed.initialize`; arguments are optional because
    TPU pod environments auto-discover them.
    """
    global _initialized
    if _initialized:
        return
    if num_processes is not None and num_processes > 1:
        kwargs = {}
        if coordinator_address is not None:
            kwargs['coordinator_address'] = coordinator_address
        if process_id is not None:
            kwargs['process_id'] = process_id
        if local_device_ids is not None:
            kwargs['local_device_ids'] = local_device_ids
        jax.distributed.initialize(num_processes=num_processes, **kwargs)
    _initialized = True


def get_world_size(axis_name=None):
    """Total parallel width (replaces ``hvd.size()``, reference comm.py:13-15).

    Inside a ``shard_map`` body pass ``axis_name`` to get the (static) size
    of that mesh axis. Outside, returns the host **process** count, coherent
    with :func:`get_rank` — the reference's process==GPU identity does not
    hold in JAX, where one process drives many devices; the device-level
    world is a mesh property (``mesh.shape[axis]`` or ``jax.device_count()``).
    """
    if axis_name is not None:
        return lax.psum(1, axis_name)
    return jax.process_count()


def get_rank(axis_name=None):
    """This shard's index (replaces ``hvd.rank()``, reference comm.py:17-19).

    Inside a ``shard_map`` body pass ``axis_name`` for the per-shard mesh
    position (traced value); outside, returns the host **process** index
    (coherent with :func:`get_world_size`'s process count).
    """
    if axis_name is not None:
        return lax.axis_index(axis_name)
    return jax.process_index()


def is_main_process(axis_name=None):
    """True on the coordinating shard/process (reference comm.py:21-23)."""
    return get_rank(axis_name) == 0


def synchronize():
    """Host-level barrier across processes (reference comm.py:25-30 used
    ``MPI.COMM_WORLD.Barrier()``).

    Within a compiled SPMD program there is nothing to synchronize — the
    reference called this before every distributed matmul (functions.py:77)
    because its eager collectives could interleave; ours cannot. Multi-host,
    this syncs the hosts (e.g. before timing or checkpoint I/O).
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices('ddp_tpu_synchronize')


def axis_size(axis_name=SEQ_AXIS):
    """Static size of a mesh axis, valid inside ``shard_map`` bodies."""
    return lax.psum(1, axis_name)
